"""r11 sign2 (2-bit) codec + adaptive precision: cross-tier parity and
engine-tier behavior.

Parity discipline (same as the existing codec parity tests): tiers are
bit-identical GIVEN the same scales — scales are sender-chosen and ride
the wire, so each test feeds one tier's scales into the other and demands
byte-equal planes/residuals/applies. Three independent implementations are
pinned against each other: the JAX pod-tier lab step
(parallel/ici_lab.build_sign2_sync_step — the measured-best design this PR
promotes), the pure-numpy reference twins (ops/codec_np.quantize2_table_np
/ apply2_table_np), and the C engine kernels (stc_quantize2_ef_cascade /
stc_apply_frames2).
"""

import ctypes
import os
import socket
import time

import jax.numpy as jnp
import numpy as np
import pytest

from shared_tensor_tpu.ops import codec_np
from shared_tensor_tpu.ops.table import make_spec

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _native():
    lib = codec_np._native()
    if lib is None:
        pytest.skip("native libstcodec.so unavailable")
    return lib


def _dp(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


# ---- kernel-level parity: C vs the numpy reference twins -------------------


def test_sign2_c_kernels_match_numpy_reference():
    """stc_quantize2_ef_cascade(k=1) and stc_apply_frames2 are byte-equal
    to the pure-numpy Sign2 rule on a ragged multi-leaf table (pool path
    included at 1 Mi)."""
    lib = _native()
    for template in (
        np.zeros(1 << 14, np.float32),
        {"a": np.zeros(999, np.float32), "b": np.zeros((1 << 19) + 5, np.float32)},
    ):
        spec = make_spec(template)
        offs, ns, padded = codec_np._layout(spec)
        L, W = spec.num_leaves, spec.total // 32
        rng = np.random.default_rng(11)
        live = codec_np._live_mask_np(spec)
        r = np.zeros(spec.total, np.float32)
        r[live] = rng.normal(0, 1, int(live.sum())).astype(np.float32)
        scales, sw, mw, nr = codec_np.quantize2_table_np(r, spec)
        r2 = np.empty_like(r)
        words = np.empty(2 * W, np.uint32)
        pa = np.zeros(L)
        ps = np.zeros(L)
        pb = np.zeros(L)
        lib.stc_quantize2_ef_cascade(
            r, r2, offs, ns, padded, L, 1, scales, words, 2 * W, W, pa, ps, pb
        )
        assert np.array_equal(words[:W], sw), "sign plane"
        assert np.array_equal(words[W:], mw), "magnitude plane"
        assert np.array_equal(r2, nr), "post-quantize residual"
        # fused partials == a standalone rescan of the result
        a2 = np.zeros(L)
        s2 = np.zeros(L)
        b2 = np.zeros(L)
        lib.stc_scale_partials(r2, offs, ns, L, a2, s2, b2)
        np.testing.assert_allclose(ps, s2, rtol=1e-12)
        np.testing.assert_array_equal(pa, a2)
        # apply parity (values + the rollback path)
        v = np.zeros(spec.total, np.float32)
        v[live] = rng.normal(0, 1, int(live.sum())).astype(np.float32)
        (want,) = codec_np.apply2_table_np(
            (v,), scales.reshape(1, -1), words.reshape(1, -1), spec
        )
        got = np.empty_like(v)
        lib.stc_apply_frames2(
            v, got, offs, ns, padded, L, W, 1, scales, words, None, None, None
        )
        assert np.array_equal(got, want)
        # rollback: re-applying the frame to the residual restores the
        # pre-quantize state (the ledger _unapply discipline; same
        # float-rounding class as the 1-bit codec)
        back = np.empty_like(r2)
        lib.stc_apply_frame2(r2, back, offs, ns, padded, L, W, scales, words)
        np.testing.assert_allclose(back, r, atol=4e-6)


def test_sign2_engine_kernels_match_ici_lab_jax_reference():
    """Engine-tier sign2 pack/unpack vs the JAX pod-tier lab on shared
    random state: run one build_sign2_sync_step step on a 2-peer mesh,
    then reproduce each peer's quantize AND the cross-peer apply with the
    C kernels at the LAB'S scales — planes, residuals and applied values
    must match byte-for-byte (pack_bits and the C packing share the
    LSB-first u32 wire contract)."""
    lib = _native()
    from shared_tensor_tpu.ops.packing import LANES, pack_bits  # noqa: F401
    from shared_tensor_tpu.parallel import add_updates, init_state
    from shared_tensor_tpu.parallel.ici_lab import build_sign2_sync_step
    from tests._mesh import make_mesh

    n_peer, n = 2, 4096
    mesh = make_mesh(n_peer, 2)
    tpl = {"w": jnp.zeros((n,), jnp.float32)}
    spec = make_spec(tpl)
    offs, ns, padded = codec_np._layout(spec)
    L, W = spec.num_leaves, spec.total // 32
    rng = np.random.default_rng(3)
    ups = jnp.asarray(
        np.stack([rng.normal(0, 1, spec.total) for _ in range(n_peer)]),
        jnp.float32,
    )
    state = add_updates(init_state(mesh, spec, tpl), ups)
    r_before = np.asarray(state.residual)  # (n_peer, total)
    v_before = np.asarray(state.values)
    step = build_sign2_sync_step(mesh, spec)
    state2, scales = step(state)
    scales = np.asarray(scales, np.float32)  # (n_peer, L)
    r_after = np.asarray(state2.residual)
    v_after = np.asarray(state2.values)

    c_words = []
    for p in range(n_peer):
        r2 = np.empty(spec.total, np.float32)
        words = np.empty(2 * W, np.uint32)
        pa = np.zeros(L)
        ps = np.zeros(L)
        pb = np.zeros(L)
        lib.stc_quantize2_ef_cascade(
            np.ascontiguousarray(r_before[p]), r2, offs, ns, padded, L, 1,
            np.ascontiguousarray(scales[p]), words, 2 * W, W, pa, ps, pb,
        )
        assert np.array_equal(r2, r_after[p]), f"peer {p} residual"
        c_words.append(words)
    for p in range(n_peer):
        q = 1 - p  # the one other peer (no reduction-order ambiguity)
        got = np.empty(spec.total, np.float32)
        lib.stc_apply_frames2(
            np.ascontiguousarray(v_before[p]), got, offs, ns, padded, L, W,
            1, np.ascontiguousarray(scales[q]), c_words[q], None, None, None,
        )
        assert np.array_equal(got, v_after[p]), f"peer {p} values"


def test_cascade_matches_sequential_quantize_at_same_schedule():
    """The r11 cascade kernel is pure fusion: K frames in one pass are
    byte-equal to K sequential stc_quantize calls at the same scales."""
    lib = _native()
    spec = make_spec(np.zeros(1 << 15, np.float32))
    offs, ns, padded = codec_np._layout(spec)
    W = spec.total // 32
    rng = np.random.default_rng(5)
    r = rng.normal(0, 1, spec.total).astype(np.float32)
    s0 = codec_np.compute_scales_np(r, spec)
    k = 5
    sch = np.ascontiguousarray(
        np.stack([s0 * np.float32(0.5**j) for j in range(k)])
    )
    cw = np.empty(k * W, np.uint32)
    rc = np.empty_like(r)
    pa = np.zeros(1)
    ps = np.zeros(1)
    pb = np.zeros(1)
    lib.stc_quantize_ef_cascade(
        r, rc, offs, ns, padded, 1, k, sch, cw, W, pa, ps, pb
    )
    rr = r.copy()
    for j in range(k):
        row = np.ascontiguousarray(sch[j])
        wj = np.empty(W, np.uint32)
        ro = np.empty_like(rr)
        lib.stc_quantize(rr, ro, offs, ns, padded, 1, row, wj)
        assert np.array_equal(wj, cw[j * W : (j + 1) * W]), f"frame {j}"
        rr = ro
    assert np.array_equal(rc, rr)


# ---- engine-tier behavior ---------------------------------------------------


def _mk_pair(port, n=1 << 14, env_master=None, env_child=None, cfg=None):
    from shared_tensor_tpu.comm.peer import create_or_fetch

    tpl = jnp.zeros((n,), jnp.float32)
    saved = {}

    def _with(env, fn):
        for k, v in (env or {}).items():
            saved[k] = os.environ.get(k)
            os.environ[k] = v
        try:
            return fn()
        finally:
            for k in (env or {}):
                if saved[k] is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = saved[k]

    m = _with(env_master, lambda: create_or_fetch("127.0.0.1", port, tpl, cfg))
    c = _with(env_child, lambda: create_or_fetch("127.0.0.1", port, tpl, cfg))
    return m, c


def _drain(peers, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if all(
            all(p.st.residual_rms(li) == 0 for li in p.st.link_ids)
            and (p._engine is None or p._engine.inflight_total() == 0)
            for p in peers
        ):
            return True
        time.sleep(0.05)
    return False


def test_sign2_pinned_pair_converges_and_counts_frames2():
    """Two engine peers pinned to sign2 (ST_SIGN2=2): the stream runs at
    2 bits, frames2 counters move on both ends, and the tree converges to
    the float envelope."""
    from shared_tensor_tpu.comm.peer import create_or_fetch  # noqa: F401

    env = {"ST_SIGN2": "2"}
    m, c = _mk_pair(_free_port(), env_master=env, env_child=env)
    try:
        if m._engine is None or c._engine is None:
            pytest.skip("native engine unavailable")
        rng = np.random.default_rng(0)
        total = np.zeros(1 << 14, np.float32)
        for _ in range(6):
            u = rng.normal(0, 1, 1 << 14).astype(np.float32)
            total += u
            m.add(jnp.asarray(u))
        assert _drain([m, c]), "did not quiesce"
        a = np.asarray(m.read())
        b = np.asarray(c.read())
        np.testing.assert_allclose(a, b, atol=2e-5)
        np.testing.assert_allclose(a, total, atol=1e-3)
        cm, cc = m._engine._counters(), c._engine._counters()
        assert int(cm[20]) > 0, "master sent no sign2 frames"
        assert int(cc[21]) > 0, "child applied no sign2 frames"
        assert m._engine.link_precision(next(iter(m.st.link_ids))) == 2
    finally:
        m.close()
        c.close()


def test_sign2_mixed_tree_interop_with_disabled_peer():
    """Mixed tree: an adaptive/pinned-sign2 peer paired with an ST_SIGN2=0
    peer. The disabled peer never advertises, so the capable peer must
    stay 1-bit toward it (frames2 == 0 on the wire in BOTH directions) and
    the pair converges — the capability gate in action."""
    m, c = _mk_pair(
        _free_port(),
        env_master={"ST_SIGN2": "2"},
        env_child={"ST_SIGN2": "0"},
    )
    try:
        if m._engine is None or c._engine is None:
            pytest.skip("native engine unavailable")
        rng = np.random.default_rng(1)
        for _ in range(4):
            m.add(jnp.asarray(rng.normal(0, 1, 1 << 14), jnp.float32))
            c.add(jnp.asarray(rng.normal(0, 1, 1 << 14), jnp.float32))
        assert _drain([m, c]), "did not quiesce"
        np.testing.assert_allclose(
            np.asarray(m.read()), np.asarray(c.read()), atol=2e-5
        )
        cm, cc = m._engine._counters(), c._engine._counters()
        assert int(cm[20]) == 0 and int(cc[20]) == 0, "sign2 leaked"
        assert m._engine.link_precision(next(iter(m.st.link_ids))) == 1
    finally:
        m.close()
        c.close()


def test_governor_upshifts_under_sustained_residual_and_emits_event():
    """The closed telemetry loop: a BYTE-BOUND link (token-bucket cap —
    the honest stand-in for a saturated NIC) whose residual RMS refuses
    to decay upshifts to sign2; the flip lands in the upshift counter,
    the st_link_precision gauge and the precision_shift ring event. The
    byte-bound gate is load-bearing: an uncapped loopback link is
    frame-bound, where sign2 would just halve the frame rate, and the
    governor must not engage there (test_governor_stays_quiet below)."""
    from shared_tensor_tpu.config import CodecConfig, Config, TransportConfig

    cfg = Config(
        transport=TransportConfig(
            # ~16 1-bit frames/s at 16 Ki: the add schedule below outruns
            # the wire by construction, so the sendq backpressures and
            # the residual grows — the byte-bound regime
            bandwidth_cap_bytes_per_sec=1 << 15,
            ack_timeout_sec=2.0,
        ),
        codec=CodecConfig(
            precision_interval_sec=0.02,
            # any non-decay counts as a stall: upshift after 2 beats
            precision_up_ratio=0.05,
            precision_down_ratio=0.0001,
        ),
    )
    m, c = _mk_pair(_free_port(), cfg=cfg)
    try:
        if m._engine is None or c._engine is None:
            pytest.skip("native engine unavailable")
        rng = np.random.default_rng(2)
        deadline = time.time() + 20
        upshifted = False
        while time.time() < deadline and not upshifted:
            m.add(jnp.asarray(rng.normal(0, 1, 1 << 14), jnp.float32))
            time.sleep(0.005)
            upshifted = int(m._engine._counters()[18]) > 0
        assert upshifted, "governor never upshifted under sustained load"
        link = next(iter(m.st.link_ids))
        assert m._engine.link_precision(link) == 2
        # the flip is visible in the canonical metrics and as a ring event
        # in the process flight recorder (the peer's recv loop drains the
        # native ring into the hub)
        metrics = m.metrics(canonical=True)
        assert metrics.get("st_precision_upshifts_total", 0) > 0
        from shared_tensor_tpu import obs as _obs

        hub = _obs.hub()
        hub.poll_native()
        deadline2 = time.time() + 5
        while time.time() < deadline2:
            if any(
                e.name == "precision_shift" for e in hub.recorder.timeline()
            ):
                break
            time.sleep(0.1)
            hub.poll_native()
        assert any(
            e.name == "precision_shift" for e in hub.recorder.timeline()
        ), "precision_shift event missing from the flight recorder"
        assert _drain([m, c]), "did not quiesce after the burst"
    finally:
        m.close()
        c.close()


def test_governor_stays_quiet_on_frame_bound_link():
    """The byte-bound gate's other half (the r11 bimodal-bench
    regression): an UNCAPPED loopback link under the same sustained add
    load is frame-bound — sends never backpressure — so the governor
    must never upshift no matter how the startup-transient rms ramps
    (sign2 there would just halve the frame rate for the same applied
    mass). Same aggressive thresholds as the upshift test; the only
    difference is the absent byte pressure."""
    from shared_tensor_tpu.config import CodecConfig, Config

    cfg = Config(
        codec=CodecConfig(
            precision_interval_sec=0.02,
            precision_up_ratio=0.05,
            precision_down_ratio=0.0001,
        )
    )
    m, c = _mk_pair(_free_port(), cfg=cfg)
    try:
        if m._engine is None or c._engine is None:
            pytest.skip("native engine unavailable")
        rng = np.random.default_rng(4)
        t_end = time.time() + 3.0
        while time.time() < t_end:
            m.add(jnp.asarray(rng.normal(0, 1, 1 << 14), jnp.float32))
            time.sleep(0.005)
        cm = m.metrics()
        assert cm.get("st_precision_upshifts_total", 0) == 0, (
            "governor upshifted a frame-bound link"
        )
        assert int(m._engine._counters()[20]) == 0, "sign2 frames leaked"
        assert m._engine.link_precision(next(iter(m.st.link_ids))) == 1
        assert _drain([m, c]), "did not quiesce after the burst"
    finally:
        m.close()
        c.close()
