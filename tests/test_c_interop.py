"""Byte-level interop against a real compiled-C reference-protocol peer
(VERDICT.md round-1 item 5; SURVEY.md §7.4 hard part 5).

`native/stc_harness.c` is a fresh C implementation of the reference wire
protocol + codec spec (reference src/sharedtensor.c:106-189 BEHAVIOR, per
SURVEY.md Appendix B — not a copy). A wire-compat framework node and the C
peer exchange real codec frames over loopback TCP; both sides must converge
to seed + both adds — the reference README.md:24 eventual-consistency
contract, proven across the language boundary.
"""

import os
import socket
import subprocess
import time

import jax.numpy as jnp
import numpy as np
import pytest

from shared_tensor_tpu.comm.peer import create_or_fetch
from shared_tensor_tpu.config import Config, TransportConfig

NATIVE = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
HARNESS = os.path.join(NATIVE, "stc_harness")


from tests._ports import free_port as _free_port


@pytest.fixture(scope="module")
def harness_bin():
    proc = subprocess.run(
        ["make", "-C", NATIVE, "stc_harness"], capture_output=True, text=True
    )
    if proc.returncode != 0 or not os.path.exists(HARNESS):
        pytest.skip(f"no C toolchain to build stc_harness: {proc.stderr[-300:]}")
    return HARNESS


def test_c_peer_mutual_convergence(harness_bin):
    """C peer joins a wire-compat master, both add known deltas, both
    replicas converge to seed + sum of adds within codec tolerance."""
    n = 256
    port = _free_port()
    # Homogeneous-magnitude seed: 1 bit/elem/frame convergence (BASELINE.md
    # curve), exact in ~30 frames at loopback frame rates.
    seed = jnp.asarray(np.linspace(0.5, 1.5, n).astype("f4"))
    cfg = Config(transport=TransportConfig(peer_timeout_sec=10.0, wire_compat=True))

    peer = create_or_fetch("127.0.0.1", port, seed, cfg)
    try:
        c = subprocess.Popen(
            # 12 s runtime: the harness deadline is wall-clock, and under
            # full-suite load on this 1-vCPU box a 6 s window intermittently
            # closed before the master's +2 add finished streaming (one
            # observed suite failure; the interior-node sibling uses 10 s)
            [harness_bin, "127.0.0.1", str(port), str(n), "12.0", "1.0"],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        time.sleep(1.0)  # C peer is joined and streaming; now add our delta
        peer.add(jnp.full((n,), 2.0, jnp.float32))

        out, err = c.communicate(timeout=40)
        assert c.returncode == 0, err[-500:]

        expected = np.asarray(seed) + 1.0 + 2.0
        c_values = np.array([float(x) for x in out.split()], dtype="f4")
        assert c_values.shape == (n,), c_values.shape
        np.testing.assert_allclose(c_values, expected, atol=0.02)

        # our side must have converged to the same state (C's +1 arrived)
        deadline = time.time() + 10
        while time.time() < deadline:
            ours = np.asarray(peer.read())
            if np.allclose(ours, expected, atol=0.02):
                break
            time.sleep(0.25)
        np.testing.assert_allclose(ours, expected, atol=0.02)
    finally:
        peer.close()


def test_c_peer_receives_seed_state(harness_bin):
    """A C joiner with add=0 must end up holding the master's seed — the
    state-transfer-through-codec join (reference src/sharedtensor.c:379-391)
    working for a peer we didn't write."""
    n = 128
    port = _free_port()
    seed = jnp.asarray((np.arange(n) % 7 + 1).astype("f4") * 0.25)
    cfg = Config(transport=TransportConfig(peer_timeout_sec=10.0, wire_compat=True))

    peer = create_or_fetch("127.0.0.1", port, seed, cfg)
    try:
        c = subprocess.Popen(
            [harness_bin, "127.0.0.1", str(port), str(n), "5.0", "0.0"],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        out, err = c.communicate(timeout=30)
        assert c.returncode == 0, err[-500:]
        c_values = np.array([float(x) for x in out.split()], dtype="f4")
        np.testing.assert_allclose(c_values, np.asarray(seed), atol=0.02)
    finally:
        peer.close()


def test_c_peer_as_interior_node(harness_bin):
    """The C peer as an INTERIOR node (round-3 verdict Weak #5): master
    (python, max_children=1) <- C harness (children=1) <- python joiner.
    The master's single child slot is taken by the C peer, so the second
    python peer's join walk gets redirected ('N' + raw sockaddr) to the C
    node, which accepts it. All three replicas must then converge to
    seed + every add — which can only happen if the C node FLOODS frames
    between its links with per-hop re-quantization through its own
    residuals (reference src/sharedtensor.c:124-127)."""
    n = 192
    port = _free_port()
    seed = jnp.asarray(np.linspace(0.25, 1.25, n).astype("f4"))
    cfg = Config(
        transport=TransportConfig(
            peer_timeout_sec=10.0, wire_compat=True, max_children=1
        )
    )
    expected = np.asarray(seed) + 2.0 + 1.0 + 0.5

    master = create_or_fetch("127.0.0.1", port, seed, cfg)
    leaf = None
    try:
        c = subprocess.Popen(
            [harness_bin, "127.0.0.1", str(port), str(n), "10.0", "1.0", "1"],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        time.sleep(1.0)  # C interior is joined + listening
        # this join MUST walk through the master's redirect to the C node
        leaf = create_or_fetch(
            "127.0.0.1", port, jnp.zeros((n,), jnp.float32), cfg
        )
        assert not leaf.is_master
        master.add(jnp.full((n,), 2.0, jnp.float32))
        leaf.add(jnp.full((n,), 0.5, jnp.float32))

        out, err = c.communicate(timeout=40)
        assert c.returncode == 0, err[-500:]
        c_values = np.array([float(x) for x in out.split()], dtype="f4")
        # the C interior saw both directions' mass
        np.testing.assert_allclose(c_values, expected, atol=0.05)

        # both python ends converged THROUGH the C node's flood: the
        # master's +2 reached the leaf only via C, and the leaf's +0.5
        # reached the master only via C
        deadline = time.time() + 15
        while time.time() < deadline:
            m = np.asarray(master.read())
            l = np.asarray(leaf.read())
            if np.allclose(m, expected, atol=0.05) and np.allclose(
                l, expected, atol=0.05
            ):
                break
            time.sleep(0.25)
        np.testing.assert_allclose(np.asarray(master.read()), expected, atol=0.05)
        np.testing.assert_allclose(np.asarray(leaf.read()), expected, atol=0.05)
    finally:
        if leaf is not None:
            leaf.close()
        master.close()
