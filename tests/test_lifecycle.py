"""r12 cluster lifecycle: consistent-cut snapshot/restore, bounded-time
restart, drain-node, rolling upgrade, and the ctl operator surface.

The barrier protocol under test (comm/peer.py): the root pauses its own
production and floods a wire.SNAP marker down the tree; each node pauses,
forwards, waits for every child's SNAP_ACK AND its own in-flight ledgers
to drain empty, captures (or loads) its shard, and acks up; the root
writes MANIFEST.json with per-node sha256 digests and releases the
barrier with wire.RESUME. Per-link FIFO + drained ledgers make the cut
consistent with EMPTY channels, which is what lets a restore rebuild the
cluster with no retransmission storm and no double-apply.
"""

import json
import os
import time

import jax.numpy as jnp
import numpy as np
import pytest

from shared_tensor_tpu.comm import wire
from shared_tensor_tpu.comm.peer import create_or_fetch
from shared_tensor_tpu.config import (
    Config,
    LifecycleConfig,
    ObsConfig,
    TransportConfig,
)
from shared_tensor_tpu.utils import checkpoint as ckpt
from tests._ports import free_port

N = 2048


def _cfg(name: str, restore: str = "", **lc) -> Config:
    return Config(
        lifecycle=LifecycleConfig(
            node_name=name, restore_path=restore, **lc
        ),
        transport=TransportConfig(peer_timeout_sec=20.0),
    )


def _tree(port, names, cfgs=None, timeout=45.0):
    seed = jnp.zeros((N,), jnp.float32)
    peers = []
    for i, name in enumerate(names):
        cfg = cfgs[i] if cfgs else _cfg(name)
        peers.append(
            create_or_fetch("127.0.0.1", port, seed, cfg, timeout=timeout)
        )
    return peers


def _converged(peers, total, deadline_sec=40.0, atol=1e-4) -> bool:
    deadline = time.time() + deadline_sec
    while time.time() < deadline:
        if all(
            np.allclose(np.asarray(p.read()), total, atol=atol)
            for p in peers
        ):
            return True
        time.sleep(0.05)
    return False


def _add(peers, rng, total, rounds=6):
    for i in range(rounds):
        d = rng.uniform(-1, 1, N).astype(np.float32)
        peers[i % len(peers)].add(jnp.asarray(d))
        total += d


def test_snapshot_barrier_manifest_and_telemetry(tmp_path):
    """Root-initiated consistent cut: every node's shard lands with a
    matching sha256 in the manifest, the audit passes, the lifecycle
    telemetry moved, and the tree RESUMES (post-snapshot adds converge —
    a lifecycle op must never leave the cluster paused)."""
    port = free_port()
    peers = _tree(port, ["n0", "n1", "n2"])
    total = np.zeros(N)
    try:
        _add(peers, np.random.default_rng(0), total, rounds=9)
        time.sleep(0.3)
        res = peers[0].snapshot_cluster(str(tmp_path))
        assert res["ok"] and res["nodes"] == 3
        assert res["duration_sec"] < 30.0
        # manifest + shards audit clean, and each shard loads with the
        # right layout + a consistent link table
        assert ckpt.verify_manifest(str(tmp_path)) == []
        doc = ckpt.load_manifest(str(tmp_path))
        assert {e["node"] for e in doc["nodes"]} == {"n0", "n1", "n2"}
        for name in ("n0", "n1", "n2"):
            shard = ckpt.load_cluster_shard(
                os.path.join(str(tmp_path), ckpt.shard_filename(name))
            )
            assert shard["layout"] == peers[0].st.spec.layout_digest()
            assert shard["meta"]["snap_id"] == res["id"]
        # pairwise seq consistency of the cut: child's uplink tx == the
        # parent's rx for that link is unverifiable offline without names
        # per link, but the DRAINED property implies every node's inflight
        # was zero — spot-check the telemetry instead
        for p in peers:
            snap = p.metrics(canonical=True)
            assert snap["st_snapshot_total"] == 1
            assert snap["st_lifecycle_paused"] == 0
            assert snap["st_snapshot_in_progress"] == 0
        assert _converged(peers, total)
        # the tree is actually live again
        _add(peers, np.random.default_rng(1), total, rounds=3)
        assert _converged(peers, total)
    finally:
        for p in peers:
            p.close()


def test_kill_restore_restart_converges_to_same_mass(tmp_path):
    """The whole-cluster kill-and-restore contract: snapshot, kill every
    process state, restart each node from its shard
    (LifecycleConfig.restore_path), keep training — the restored cluster
    converges to exactly the mass an uninterrupted run would have (the
    checkpointed uplink residuals ride the re-graft carry; child residuals
    re-derive through the diff joins — no loss, no double-apply)."""
    port = free_port()
    peers = _tree(port, ["n0", "n1", "n2"])
    total = np.zeros(N)
    rng = np.random.default_rng(2)
    try:
        _add(peers, rng, total, rounds=9)
        time.sleep(0.3)
        res = peers[0].snapshot_cluster(str(tmp_path))
        assert res["ok"]
    finally:
        for p in peers:
            p.close()  # the "kill": all state dies with the processes
    port2 = free_port()
    cfgs = [
        _cfg(
            f"n{i}",
            restore=os.path.join(str(tmp_path), f"shard_n{i}.npz"),
        )
        for i in range(3)
    ]
    peers2 = _tree(port2, ["n0", "n1", "n2"], cfgs)
    try:
        assert all(p._restored_from for p in peers2)
        for p in peers2:
            assert p.metrics(canonical=True)["st_restore_total"] == 1
        # pre-kill mass must reappear without any new adds...
        assert _converged(peers2, total)
        # ...and training continues on top of it
        _add(peers2, rng, total, rounds=6)
        assert _converged(peers2, total)
    finally:
        for p in peers2:
            p.close()


def test_inplace_restore_rolls_back_to_the_cut(tmp_path):
    """restore_cluster on a LIVE tree: state rolls back to the consistent
    cut (post-snapshot adds vanish), no retransmission storm (the
    barrier's drained ledgers mean no seq surgery), and the tree keeps
    working afterwards."""
    port = free_port()
    peers = _tree(port, ["n0", "n1", "n2"])
    A = np.zeros(N)
    rng = np.random.default_rng(3)
    try:
        _add(peers, rng, A, rounds=6)
        time.sleep(0.3)
        assert peers[0].snapshot_cluster(str(tmp_path))["ok"]
        B = np.zeros(N)
        _add(peers, rng, B, rounds=4)
        assert _converged(peers, A + B)
        res = peers[0].restore_cluster(str(tmp_path))
        assert res["ok"] and res["nodes"] == 3
        assert _converged(peers, A)
        # retransmit counters must not have exploded (no storm): the cut
        # restored consistent residuals onto live links
        for p in peers:
            assert (
                p.metrics(canonical=True)["st_retransmit_msgs_total"] <= 2
            )
        C = np.zeros(N)
        _add(peers, rng, C, rounds=3)
        assert _converged(peers, A + C)
    finally:
        for p in peers:
            p.close()


def test_inplace_restore_under_drop_chaos_loses_nothing(monkeypatch, tmp_path):
    """The cut-under-loss discipline (review finding): markers only flood
    once every unacked ledger is EMPTY, so a chaos-dropped frame's
    go-back-N retransmission can never arrive past its receiver's capture
    (mass in neither shard — fatal for the in-place restore, which has no
    diff-join to re-derive it). Snapshot MID-STREAM under 25% drops, keep
    writing, restore in place: the tree must roll back to exactly the
    cut."""
    from shared_tensor_tpu.comm import faults
    from shared_tensor_tpu.config import FaultConfig

    port = free_port()
    seed = jnp.zeros((N,), jnp.float32)

    def cfg(name):
        return Config(
            lifecycle=LifecycleConfig(node_name=name),
            transport=TransportConfig(
                peer_timeout_sec=20.0, ack_timeout_sec=0.4
            ),
        )

    env = faults.to_env(
        FaultConfig(enabled=True, seed=11, drop_pct=0.25, only_link=1)
    )
    root = create_or_fetch("127.0.0.1", port, seed, cfg("n0"), timeout=45.0)
    monkeypatch.setenv("ST_FAULT_PLAN", env["ST_FAULT_PLAN"])
    chaotic = create_or_fetch(
        "127.0.0.1", port, seed, cfg("n1"), timeout=45.0
    )
    monkeypatch.delenv("ST_FAULT_PLAN")
    peers = [root, chaotic]
    A = np.zeros(N)
    rng = np.random.default_rng(12)
    try:
        # paced adds from the CHAOTIC node (each lands in its own wire
        # message on the dropped uplink) with no settle: residual mass and
        # dropped frames are in flight when the barrier starts
        for _ in range(14):
            d = rng.uniform(-1, 1, N).astype(np.float32)
            chaotic.add(jnp.asarray(d))
            A += d
            time.sleep(0.01)
        res = peers[0].snapshot_cluster(str(tmp_path))
        assert res["ok"] and res["nodes"] == 2
        B = np.zeros(N)
        _add(peers, rng, B, rounds=4)
        assert _converged(peers, A + B, deadline_sec=60.0)
        assert peers[0].restore_cluster(str(tmp_path))["ok"]
        # EXACT rollback to the cut — a retransmission that crossed the
        # marker would leave the chaotic node short of that frame's mass
        assert _converged(peers, A, deadline_sec=60.0)
        # chaos was real
        retx = sum(
            p.metrics(canonical=True)["st_retransmit_msgs_total"]
            for p in peers
        )
        assert retx >= 1, "drop chaos never fired"
    finally:
        for p in peers:
            p.close()


def test_drain_node_routed_migration_zero_loss():
    """ctl drain as a planned migration: a CHAIN root→n1→n2
    (max_children=1), drain the INTERIOR node via the root's routed CTL —
    n1 seals, drains, closes; n2 re-grafts through the r06
    quarantine/carry/re-graft path; no mass is lost and the survivors
    keep converging."""
    port = free_port()
    seed = jnp.zeros((N,), jnp.float32)
    cfgs = []
    for name in ("n0", "n1", "n2"):
        cfgs.append(
            Config(
                lifecycle=LifecycleConfig(node_name=name),
                transport=TransportConfig(
                    peer_timeout_sec=20.0, max_children=1
                ),
            )
        )
    peers = [
        create_or_fetch("127.0.0.1", port, seed, c, timeout=45.0)
        for c in cfgs
    ]
    total = np.zeros(N)
    rng = np.random.default_rng(4)
    try:
        _add(peers, rng, total, rounds=6)
        assert _converged(peers, total)
        peers[0].drain_node("n1")
        deadline = time.time() + 30.0
        while time.time() < deadline and not peers[1]._stop.is_set():
            time.sleep(0.1)
        assert peers[1]._stop.is_set(), "drain target never left"
        assert peers[1].metrics(canonical=True)["st_drain_total"] == 1
        # survivors re-form and keep the whole mass + new adds
        d = rng.uniform(-1, 1, N).astype(np.float32)
        peers[2].add(jnp.asarray(d))
        total += d
        assert _converged([peers[0], peers[2]], total, deadline_sec=60.0)
    finally:
        for p in peers:
            p.close()


def test_snapshot_and_restore_with_subscriber_no_false_fresh(tmp_path):
    """Serving-tier arm: with a read-only subscriber attached, (a) a
    snapshot barrier never breaks the read contract — every read during
    and after the cut verifies its bound or raises, and the post-barrier
    subscriber converges; (b) an IN-PLACE restore re-seeds the subscriber
    from the restored replica, so reads reflect the cut — never a FRESH
    mark falsely verifying pre-restore state across it."""
    from shared_tensor_tpu import serve
    from shared_tensor_tpu.serve import StalenessError

    port = free_port()
    peers = _tree(port, ["n0", "n1"])
    seed = jnp.zeros((N,), jnp.float32)
    sub = serve.subscribe(
        "127.0.0.1", port, seed, Config(), timeout=45.0
    )
    A = np.zeros(N)
    rng = np.random.default_rng(5)
    try:
        _add(peers, rng, A, rounds=6)
        time.sleep(0.5)
        ok_reads = refused = 0
        res = peers[0].snapshot_cluster(str(tmp_path))
        assert res["ok"]
        deadline = time.time() + 30.0
        converged = False
        while time.time() < deadline:
            try:
                v = np.asarray(sub.read(max_staleness=1.0))
                ok_reads += 1
                if np.allclose(v, A, atol=1e-3):
                    converged = True
                    break
            except StalenessError:
                refused += 1
            time.sleep(0.05)
        assert converged, (ok_reads, refused)
        assert ok_reads >= 1  # FRESH beats survive the barrier
        # (b) post-snapshot writes, then roll back: the subscriber must
        # follow the restore, not keep (or falsely re-verify) B
        B = np.zeros(N)
        _add(peers, rng, B, rounds=4)
        assert _converged(peers, A + B)
        assert peers[0].restore_cluster(str(tmp_path))["ok"]
        deadline = time.time() + 30.0
        back = False
        while time.time() < deadline:
            try:
                v = np.asarray(sub.read(max_staleness=1.0))
                if np.allclose(v, A, atol=1e-3):
                    back = True
                    break
            except StalenessError:
                pass
            time.sleep(0.05)
        assert back, "subscriber never re-seeded to the restored cut"
    finally:
        sub.close()
        for p in peers:
            p.close()


def test_rolling_upgrade_version_skew_interop(monkeypatch, tmp_path):
    """Rolling-upgrade verification on the r09/r10/r11 compat machinery:
    an 'old' node (v1 emission — trace_wire off, adaptive precision off)
    interops mid-upgrade with v2 peers UNDER DROP CHAOS on its uplink
    (the version-skew chaos arm); the root's digest shows the mixed
    st_wire_version; then the old node drains out and rejoins upgraded,
    and the skew disappears. The upgrade path loses nothing."""
    from shared_tensor_tpu.comm import faults
    from shared_tensor_tpu.config import FaultConfig

    port = free_port()
    seed = jnp.zeros((N,), jnp.float32)
    new_cfg = Config(
        lifecycle=LifecycleConfig(node_name="root"),
        obs=ObsConfig(digest_interval_sec=0.2),
        transport=TransportConfig(ack_timeout_sec=0.4),
    )
    old_cfg = Config(
        lifecycle=LifecycleConfig(node_name="old"),
        obs=ObsConfig(digest_interval_sec=0.2, trace_wire=False),
        transport=TransportConfig(ack_timeout_sec=0.4),
    )
    root = create_or_fetch("127.0.0.1", port, seed, new_cfg, timeout=45.0)
    env = faults.to_env(
        FaultConfig(enabled=True, seed=7, drop_pct=0.25, only_link=1)
    )
    monkeypatch.setenv("ST_FAULT_PLAN", env["ST_FAULT_PLAN"])
    old = create_or_fetch("127.0.0.1", port, seed, old_cfg, timeout=45.0)
    monkeypatch.delenv("ST_FAULT_PLAN")
    total = np.zeros(N)
    rng = np.random.default_rng(6)
    try:
        assert old._wire_version == 1 and root._wire_version == 2
        # mid-upgrade interop under chaos: both directions converge exactly
        for i in range(8):
            d = rng.uniform(-1, 1, N).astype(np.float32)
            (root if i % 2 else old).add(jnp.asarray(d))
            total += d
        assert _converged([root, old], total, deadline_sec=60.0)
        old.push_digest()
        time.sleep(0.3)
        cluster = root.metrics(cluster=True)
        versions = {
            int(e["m"].get("st_wire_version", 0))
            for e in cluster["nodes"].values()
        }
        assert versions == {1, 2}, versions
        # chaos actually fired and was repaired on the skewed link
        retx = sum(
            p.metrics(canonical=True)["st_retransmit_msgs_total"]
            for p in (root, old)
        )
        assert retx >= 1, "drop chaos never exercised the skewed link"
        # the upgrade step: drain out, rejoin with the current build
        assert old.leave(timeout=30.0)
        upgraded = create_or_fetch(
            "127.0.0.1", port, seed,
            Config(
                lifecycle=LifecycleConfig(node_name="old"),
                obs=ObsConfig(digest_interval_sec=0.2),
            ),
            timeout=45.0,
        )
        try:
            d = rng.uniform(-1, 1, N).astype(np.float32)
            upgraded.add(jnp.asarray(d))
            total += d
            assert _converged([root, upgraded], total, deadline_sec=60.0)
            upgraded.push_digest()
            time.sleep(0.3)
            cluster = root.metrics(cluster=True)
            live = {
                int(e["m"].get("st_wire_version", 0))
                for e in cluster["nodes"].values()
                if e.get("name") in ("root", "old")
            }
            assert live == {2}, live
        finally:
            upgraded.close()
    finally:
        old.close()
        root.close()


def test_barrier_auto_resume_when_root_dies(tmp_path):
    """Never-leave-paused: a node whose barrier RESUME never arrives
    (root died mid-barrier) auto-resumes after
    LifecycleConfig.pause_timeout_sec and records the error — frozen
    forever is the one outcome the protocol forbids."""
    port = free_port()
    cfgs = [
        _cfg("n0"),
        Config(
            lifecycle=LifecycleConfig(
                node_name="n1", pause_timeout_sec=2.0
            ),
            transport=TransportConfig(peer_timeout_sec=20.0),
        ),
    ]
    peers = _tree(port, ["n0", "n1"], cfgs)
    try:
        # inject a bare SNAP marker at the child, bypassing the root's
        # own barrier machinery — the RESUME will never come
        child_link = [
            l for l in peers[0].st.link_ids
            if l >= 0 and l != peers[0]._uplink
        ][0]
        peers[0]._send_blocking(
            child_link,
            wire.encode_lifecycle(
                wire.SNAP,
                {"op": "save", "id": "orphan", "dir": str(tmp_path)},
            ),
        )
        deadline = time.time() + 5.0
        saw_paused = False
        while time.time() < deadline:
            if peers[1]._paused:
                saw_paused = True
                break
            time.sleep(0.02)
        assert saw_paused, "child never entered the barrier"
        deadline = time.time() + 10.0
        while time.time() < deadline and peers[1]._paused:
            time.sleep(0.05)
        assert not peers[1]._paused, "child stayed paused past the deadline"
        assert (
            peers[1].metrics(canonical=True)["st_lifecycle_errors_total"]
            >= 1
        )
        # and it still works
        total = np.zeros(N)
        _add(peers, np.random.default_rng(8), total, rounds=3)
        assert _converged(peers, total)
    finally:
        for p in peers:
            p.close()


def test_ctl_cli_end_to_end(tmp_path):
    """The operator surface: ctl status/versions off the digest JSON,
    snapshot + offline verify + drain through the root's command
    directory — all file-based, no sockets into the cluster."""
    from shared_tensor_tpu import ctl as ctlmod

    ctl_dir = str(tmp_path / "ctl")
    cj = str(tmp_path / "cluster.json")
    snapdir = str(tmp_path / "snap")
    port = free_port()
    seed = jnp.zeros((N,), jnp.float32)
    root = create_or_fetch(
        "127.0.0.1", port, seed,
        Config(
            lifecycle=LifecycleConfig(node_name="root", ctl_dir=ctl_dir),
            obs=ObsConfig(digest_interval_sec=0.2, cluster_json_path=cj),
        ),
        timeout=45.0,
    )
    child = create_or_fetch(
        "127.0.0.1", port, seed,
        Config(
            lifecycle=LifecycleConfig(node_name="child"),
            obs=ObsConfig(digest_interval_sec=0.2),
        ),
        timeout=45.0,
    )
    try:
        root.add(jnp.ones((N,), jnp.float32))
        time.sleep(0.8)
        child.push_digest()
        time.sleep(0.5)
        assert ctlmod.main(["--file", cj, "status"]) == 0
        assert ctlmod.main(["--file", cj, "versions"]) == 0
        assert (
            ctlmod.main(
                ["--ctl-dir", ctl_dir, "--timeout", "60",
                 "snapshot", "--dir", snapdir]
            )
            == 0
        )
        assert ctlmod.main(["verify", "--dir", snapdir]) == 0
        with open(os.path.join(ctl_dir, "result.json")) as f:
            assert json.load(f)["ok"]
        assert (
            ctlmod.main(
                ["--ctl-dir", ctl_dir, "--timeout", "60", "drain", "child"]
            )
            == 0
        )
        deadline = time.time() + 30.0
        while time.time() < deadline and not child._stop.is_set():
            time.sleep(0.1)
        assert child._stop.is_set(), "ctl drain never reached the child"
    finally:
        child.close()
        root.close()


def test_obs_top_renders_lifecycle_rows():
    """obs.top satellite: the lifecycle gauges render as rows — per-node
    snapshot/pause/drain state and the mixed-wire-version flag — and an
    idle digest renders none of them (the rows only appear while
    something is happening)."""
    from shared_tensor_tpu.obs import top

    def node(m):
        return {"t_ns": 1, "m": m}

    busy = {
        "v": 1,
        "counters": {},
        "hists": {},
        "gmax": {},
        "gmin": {},
        "truncated": 0,
        "nodes": {
            "1": node({
                "st_wire_version": 2,
                "st_snapshot_in_progress": 1,
                "st_snapshot_shards_acked": 3,
            }),
            "2": node({
                "st_wire_version": 1,
                "st_lifecycle_paused": 1,
            }),
            "3": node({
                "st_wire_version": 2,
                "st_drain_in_progress": 1,
            }),
        },
    }
    frame = top.render(busy, None, 0.0)
    assert "lifecycle:" in frame
    assert "snapshotting (acks 3)" in frame
    assert "paused (barrier)" in frame
    assert "draining" in frame
    assert "MIXED wire versions [1, 2]" in frame
    idle = dict(busy, nodes={"1": node({"st_wire_version": 2})})
    assert "lifecycle:" not in top.render(idle, None, 0.0)


def test_wire_compat_lifecycle_refused():
    """The reference protocol has no typed control plane: the barrier
    APIs refuse loudly there instead of spraying unknown bytes."""
    port = free_port()
    seed = jnp.zeros((64,), jnp.float32)
    cfg = Config(transport=TransportConfig(wire_compat=True))
    m = create_or_fetch("127.0.0.1", port, seed, cfg, timeout=30.0)
    try:
        with pytest.raises(RuntimeError, match="native protocol"):
            m.snapshot_cluster("/tmp/nope")
        with pytest.raises(RuntimeError, match="control plane"):
            m.drain_node("whoever")
    finally:
        m.close()
