"""Observability tests (SURVEY.md §5.1/§5.5 build notes)."""

import time

import jax.numpy as jnp
import numpy as np
import pytest

from shared_tensor_tpu.config import ScalePolicy
from shared_tensor_tpu.ops import codec
from shared_tensor_tpu.utils.profiling import RateMeter, effective_bits, trace


def test_effective_bits_homogeneous_is_one():
    """Uniform residual: RMS halves per frame -> 1.0 bits/elem/frame, the
    BASELINE.md reference curve."""
    n = 4096
    rng = np.random.default_rng(0)
    r = jnp.asarray(rng.uniform(-1, 1, codec.pad_flat(jnp.zeros(n)).shape[0]).astype("f4"))
    r = r.at[n:].set(0.0)
    traj = []
    for _ in range(10):
        traj.append(float(jnp.sqrt(jnp.sum(r * r) / n)))
        _, r = codec.quantize(r, n, ScalePolicy.POW2_RMS)
    bits = effective_bits(traj)
    assert 0.8 < bits < 1.2, (bits, traj)


def test_effective_bits_edge_cases():
    assert effective_bits([]) == 0.0
    assert effective_bits([1.0]) == 0.0
    assert effective_bits([0.0, 0.0]) == 0.0
    # exact convergence caps at fp32 precision instead of inf
    assert effective_bits([1.0, 0.0]) <= 24.0


def test_rate_meter():
    m = RateMeter(window_sec=60.0)
    m.update(frames=0, bytes=0)
    time.sleep(0.05)
    m.update(frames=50, bytes=5000)
    r = m.rates()
    assert r["frames"] > 100  # ~1000/s
    assert r["bytes"] / r["frames"] == pytest.approx(100.0)


def test_rate_meter_window_spans_more_than_last_interval():
    """Eviction keeps one sample at/just before the window edge: with many
    rapid updates inside the window, rates() must span the whole window, not
    just the final update interval (ADVICE.md round-1 finding)."""
    m = RateMeter(window_sec=60.0)
    for i in range(50):
        m.update(frames=i)
    assert len(m._samples) == 50  # nothing evicted within the window
    m2 = RateMeter(window_sec=0.01)
    m2.update(frames=0)
    time.sleep(0.02)
    for i in range(1, 5):
        m2.update(frames=i)
    # The stale sample is RETAINED as the one at/before the window edge
    # (eviction only pops while the second-oldest is past the cutoff), so
    # all 5 survive here; the old inverted condition would leave exactly 2.
    assert len(m2._samples) == 5
    assert m2._samples[0][0] <= time.monotonic() - m2.window


def test_rate_meter_tolerates_counter_reset():
    """r08 satellite: a counter that goes BACKWARDS (fresh link id after a
    re-graft, re-created peer) must re-anchor the window, not emit a huge
    negative rate for the whole window span."""
    m = RateMeter(window_sec=60.0)
    m.update(frames=1000, bytes=100000)
    time.sleep(0.01)
    m.update(frames=2000, bytes=200000)
    time.sleep(0.01)
    # the re-graft: counters restart near zero on the new link
    m.update(frames=5, bytes=500)
    time.sleep(0.01)
    m.update(frames=10, bytes=1000)
    r = m.rates()
    assert r["frames"] >= 0, r
    assert r["bytes"] >= 0, r
    # and the post-reset stream is measured (~5 frames / ~10 ms)
    assert r["frames"] > 50, r
    # a reset in ONE counter re-anchors the whole sample set (mixed-epoch
    # windows are meaningless), so the untouched counter stays sane too
    m2 = RateMeter(window_sec=60.0)
    m2.update(a=100, b=100)
    time.sleep(0.01)
    m2.update(a=0, b=200)
    time.sleep(0.01)
    m2.update(a=50, b=300)
    r2 = m2.rates()
    assert r2["a"] >= 0 and r2["b"] >= 0, r2


def test_rate_meter_idle_gap_does_not_dilute():
    """After an idle gap longer than the window, rates() must reflect the
    recent window (counters interpolated at the window edge), not average
    the burst over the whole gap."""
    m = RateMeter(window_sec=0.05)
    m.update(frames=0)
    time.sleep(0.5)  # idle gap 10x the window
    m.update(frames=100)
    time.sleep(0.01)
    m.update(frames=200)
    r = m.rates()
    # Diluted-over-the-gap would be ~ (200-0)/0.51 ~ 390/s; the window
    # estimate is >= (200 - interp@edge)/window ~ 2000/s.
    assert r["frames"] > 1500, r


def test_trace_writes_profile(tmp_path):
    with trace(str(tmp_path)):
        jnp.sum(jnp.ones((128, 128))).block_until_ready()
    # the profiler must have produced a trace artifact
    produced = list(tmp_path.rglob("*"))
    assert produced, "no profile output written"
