"""The codec lab's pod-tier test bed (parallel/ici_lab.py): the 2-bit
sign2 sync step on the 8-device virtual CPU mesh must keep the production
step's semantic invariants (agreement, split horizon, idle behavior) and
reproduce the host lab's measured ordering: faster per-frame RMS decay
than 1-bit on gaussian residuals, identical trajectories on uniform."""

import jax
import jax.numpy as jnp
import numpy as np

from shared_tensor_tpu.ops.table import make_spec
from shared_tensor_tpu.parallel import add_updates, build_sync_step, init_state
from shared_tensor_tpu.parallel.ici_lab import build_sign2_sync_step
from tests._mesh import make_mesh


def _mk(n_peer=4, n_shard=2, n=4096, seed=0, dist="normal"):
    mesh = make_mesh(n_peer, n_shard)
    tpl = {"w": jnp.zeros((n,), jnp.float32)}
    spec = make_spec(tpl)
    state = init_state(mesh, spec, tpl)
    rng = np.random.default_rng(seed)
    draw = rng.standard_normal if dist == "normal" else (
        lambda size: rng.uniform(-1.0, 1.0, size)
    )
    ups = jnp.asarray(
        np.stack([draw(size=spec.total) for _ in range(n_peer)]), jnp.float32
    )
    return mesh, spec, add_updates(state, ups)


def _rms(state):
    r = np.asarray(state.residual, dtype=np.float64)
    return float(np.sqrt(np.mean(r * r)))


def test_sign2_step_reaches_agreement():
    """After residuals drain, every peer holds the same replica — the sum
    of all peers' updates (the eventual-consistency contract, delivered
    through the 2-bit wire)."""
    mesh, spec, state = _mk(dist="uniform")
    expect = np.asarray(jnp.sum(state.residual, axis=0))
    step = build_sign2_sync_step(mesh, spec)
    for _ in range(40):
        state, scales = step(state)
        if not bool(jnp.any(state.residual != 0.0)):
            break
    assert not bool(jnp.any(state.residual != 0.0)), "did not drain"
    vals = np.asarray(state.values)
    for p in range(vals.shape[0]):
        np.testing.assert_allclose(vals[p], expect, rtol=1e-4, atol=1e-5)


def test_sign2_uniform_trajectory_matches_production_step():
    """On uniform residuals |r| never exceeds 2s: the magnitude bit idles
    and the 2-bit step's state must track the production 1-bit step's
    bit-for-bit, frame by frame — how the lab design inherits the exact
    drain (mirrors the host lab's test)."""
    mesh, spec, s1 = _mk(dist="uniform")
    _, _, s2 = _mk(dist="uniform")
    step1 = build_sync_step(mesh, spec, impl="xla")
    step2 = build_sign2_sync_step(mesh, spec)
    for _ in range(30):
        s1, sc1 = step1(s1)
        s2, sc2 = step2(s2)
        np.testing.assert_array_equal(np.asarray(sc1), np.asarray(sc2))
        np.testing.assert_array_equal(
            np.asarray(s1.residual), np.asarray(s2.residual)
        )
        np.testing.assert_array_equal(
            np.asarray(s1.values), np.asarray(s2.values)
        )
        if not bool(jnp.any(s1.residual != 0.0)):
            break
    assert not bool(jnp.any(s1.residual != 0.0))


def test_sign2_decays_faster_on_gaussian():
    """The lab's device-tier claim: on gaussian residuals the ±3s level
    moves tail elements 3x faster, beating the production step's per-frame
    decay (host lab measured 0.79 vs 0.85 geometric mean over 20 frames)."""
    frames = 20
    mesh, spec, s1 = _mk(dist="normal")
    _, _, s2 = _mk(dist="normal")
    rms0 = _rms(s1)
    step1 = build_sync_step(mesh, spec, impl="xla")
    step2 = build_sign2_sync_step(mesh, spec)
    for _ in range(frames):
        # block each iteration: a deep unsynchronized dispatch queue of
        # alternating donated (production) and non-donated shard_map
        # programs intermittently SIGABRTs the XLA CPU runtime when many
        # executables are live in one process (reproduced at suite
        # position #132; every other test here syncs per-iter via
        # np.asarray and never hits it)
        s1, _ = jax.block_until_ready(step1(s1))
        s2, _ = jax.block_until_ready(step2(s2))
    d1 = (_rms(s1) / rms0) ** (1.0 / frames)
    d2 = (_rms(s2) / rms0) ** (1.0 / frames)
    assert d2 < d1 - 0.02, (d2, d1)


def test_sign2_trains_char_rnn_comparably():
    """The training-level A/B (mirrors the overlap A/B in test_trainer.py):
    the flagship char-rnn trained with 2-bit sync must reach statistically
    comparable loss to the production 1-bit sync on the SAME pinned data
    stream — the lab method works in a real training loop, not just on
    residual trajectories. Bars: both arms learned (tail well under the
    first loss), inter-arm gap small relative to loss scale and to
    within-arm noise."""
    from shared_tensor_tpu.models import char_rnn as m
    from shared_tensor_tpu.ops.table import flatten, unflatten

    cfg = m.CharRNNConfig(hidden=64, layers=1)
    text = bytes(range(32, 127)) * 40
    params = m.init_params(jax.random.key(0), cfg)
    loss_fn = lambda p, b: m.loss_fn(p, b, cfg)
    mesh = make_mesh(4, 1)
    spec = make_spec(params)
    grad_fn = jax.value_and_grad(loss_fn)

    @jax.jit
    def grads_step(values, batch, lr):
        def per_peer(row, item):
            l, g = grad_fn(unflatten(row, spec), item)
            return l, flatten(g, spec)

        losses, g = jax.vmap(per_peer)(values, batch)
        return losses, -lr * g

    steps, tail = 160, 30
    curves = {}
    # ONE precomputed batch list shared by both arms: the pinned-stream
    # invariant holds by construction, not by key re-derivation
    batches = [
        m.make_batches(
            text, batch=4, seq=16, key=jax.random.key(i), n_peer=4,
            vocab=cfg.vocab,
        )
        for i in range(steps)
    ]
    builders = {
        "sign1": lambda: build_sync_step(mesh, spec, impl="xla"),
        "sign2": lambda: build_sign2_sync_step(mesh, spec),
    }
    for name, build in builders.items():
        state = init_state(mesh, spec, params)
        sync = build()
        losses = []
        for batch in batches:
            l, upd = grads_step(state.values, batch, 0.3)
            state = add_updates(state, upd)
            state, _ = jax.block_until_ready(sync(state))
            losses.append(float(jnp.mean(l)))
        curves[name] = losses
        assert np.isfinite(np.asarray(state.values)).all()
    t1 = float(np.mean(curves["sign1"][-tail:]))
    t2 = float(np.mean(curves["sign2"][-tail:]))
    first = curves["sign1"][0]
    assert t1 < first * 0.5, (first, t1)
    assert t2 < first * 0.5, (first, t2)
    gap = abs(t1 - t2)
    noise = max(
        float(np.std(curves["sign1"][-tail:])),
        float(np.std(curves["sign2"][-tail:])),
        1e-9,
    )
    # "comparable" = the inter-arm gap is inside the within-arm noise band
    # (2 sigma over the tail) or within 10% of the loss scale. A fixed
    # %-of-scale bound alone sits BELOW one sigma of step-to-step loss
    # variation at this batch size (measured std 0.11-0.14 on a ~1.03
    # tail), so it flags ordinary training noise as divergence under
    # XLA-version fp drift (the two arms' trajectories are chaotic in it).
    assert gap <= max(0.1 * t1, 2.0 * noise) + 1e-6, (t1, t2, noise)


def test_sign2_idle_state_stays_idle():
    """Zero residuals produce zero scales and a no-op step (idle pods cost
    nothing but the collective itself)."""
    mesh = make_mesh(4, 2)
    tpl = {"w": jnp.zeros((4096,), jnp.float32)}
    spec = make_spec(tpl)
    state = init_state(mesh, spec, tpl)
    step = build_sign2_sync_step(mesh, spec)
    state2, scales = step(state)
    assert not bool(jnp.any(np.asarray(scales) != 0.0))
    np.testing.assert_array_equal(np.asarray(state2.values), 0.0)
    np.testing.assert_array_equal(np.asarray(state2.residual), 0.0)
