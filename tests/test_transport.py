"""Native transport tests: tree formation, framed streaming, redirects,
fault handling — N nodes in one process on loopback, the reference's dev
story (SURVEY.md §4.1). No JAX involved; frames are opaque bytes here."""

import socket
import time

import pytest

from shared_tensor_tpu.comm.transport import (
    EventKind,
    TransportNode,
    build_native,
)
from shared_tensor_tpu.config import TransportConfig


from tests._ports import free_port as _free_port


def _wait(cond, timeout=30.0, step=0.01):
    # default sized for a loaded 1-vCPU box running concurrent suites;
    # unloaded these conditions hold within milliseconds
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(step)
    return False


@pytest.fixture(scope="module", autouse=True)
def _built():
    build_native()


def test_master_election_and_join():
    port = _free_port()
    cfg = TransportConfig(peer_timeout_sec=10.0)
    with TransportNode("127.0.0.1", port, cfg) as master:
        assert master.is_master
        assert master.listen_port == port
        with TransportNode("127.0.0.1", port, cfg) as joiner:
            assert not joiner.is_master
            assert _wait(lambda: joiner.uplink is not None)
            assert _wait(lambda: len(master.links) == 1)
            ev = master.poll_events(timeout=1.0)
            assert any(e.kind == EventKind.LINK_UP for e in ev)


def test_frame_roundtrip():
    port = _free_port()
    cfg = TransportConfig(peer_timeout_sec=10.0)
    with TransportNode("127.0.0.1", port, cfg) as a, TransportNode(
        "127.0.0.1", port, cfg
    ) as b:
        assert _wait(lambda: b.uplink is not None and len(a.links) == 1)
        la = a.links[0]
        lb = b.uplink
        payload = b"\x01\x02\x03" * 100
        assert a.send(la, payload)
        got = None
        for _ in range(100):
            got = b.recv(lb, timeout=0.1)
            if got:
                break
        assert got == payload
        # reverse direction
        assert b.send(lb, b"pong")
        got = None
        for _ in range(100):
            got = a.recv(la, timeout=0.1)
            if got:
                break
        assert got == b"pong"
        st = a.stats(la)
        assert st.frames_out >= 1 and st.frames_in >= 1


def test_tree_redirect_third_joiner():
    """Master has max_children=2; a third joiner must be redirected to a
    child (the reference's alternating-redirect walk, src/sharedtensor.c:
    226-234) and end up as that child's child."""
    port = _free_port()
    cfg = TransportConfig(peer_timeout_sec=10.0)
    nodes = [TransportNode("127.0.0.1", port, cfg) for _ in range(4)]
    try:
        # everyone joined: every non-master has an uplink
        assert _wait(
            lambda: all(n.uplink is not None for n in nodes[1:]), timeout=30
        )
        # master has exactly 2 children; total child links across the tree = 3
        assert _wait(
            lambda: len(nodes[0].links) == 2
            and sum(
                len(n.links) - (0 if n.is_master else 1) for n in nodes
            ) == 3,
            timeout=30,
        )
    finally:
        for n in nodes:
            n.close()


def test_link_down_event_and_survival():
    """Killing a joiner must NOT kill the master (reference exits the whole
    process on any socket error — quirk Q8, fixed here)."""
    port = _free_port()
    cfg = TransportConfig(peer_timeout_sec=10.0, max_rejoin_attempts=1)
    master = TransportNode("127.0.0.1", port, cfg)
    joiner = TransportNode("127.0.0.1", port, cfg)
    try:
        assert _wait(lambda: len(master.links) == 1)
        master.poll_events(timeout=0.5)
        joiner.close()  # peer dies
        assert _wait(
            lambda: any(
                e.kind == EventKind.LINK_DOWN
                for e in master.poll_events(timeout=0.2)
            ),
            timeout=30,
        )
        assert master.links == []
        # master still accepts new joiners afterwards
        j2 = TransportNode("127.0.0.1", port, cfg)
        try:
            assert _wait(lambda: len(master.links) == 1)
        finally:
            j2.close()
    finally:
        master.close()


def test_wire_compat_frames():
    """Wire-compat mode: fixed-size raw frames (f32 scale + bitmask), no
    length prefix — byte-exact with the reference protocol (SURVEY.md §2.3)."""
    port = _free_port()
    n_elems = 240
    frame_bytes = 4 + (n_elems + 7) // 8  # 34
    cfg = TransportConfig(peer_timeout_sec=10.0, wire_compat=True)
    with TransportNode(
        "127.0.0.1", port, cfg, frame_bytes=frame_bytes
    ) as a, TransportNode(
        "127.0.0.1", port, cfg, frame_bytes=frame_bytes
    ) as b:
        assert _wait(lambda: b.uplink is not None and len(a.links) == 1)
        import struct

        payload = struct.pack("<f", 0.5) + bytes(range(30))
        assert len(payload) == frame_bytes
        assert a.send(a.links[0], payload)
        got = None
        for _ in range(100):
            got = b.recv(b.uplink, timeout=0.1)
            if got is not None and got != bytes(frame_bytes):  # skip keepalives
                break
        assert got == payload


def test_wire_compat_raw_socket_interop():
    """A plain socket speaking the reference's exact join+frame protocol can
    talk to a native node in compat mode: connect, get 'Y', stream a frame."""
    import struct

    port = _free_port()
    n_elems = 32
    frame_bytes = 4 + 4
    cfg = TransportConfig(peer_timeout_sec=10.0, wire_compat=True)
    with TransportNode("127.0.0.1", port, cfg, frame_bytes=frame_bytes) as master:
        s = socket.create_connection(("127.0.0.1", port), timeout=5)
        try:
            reply = s.recv(1)
            assert reply == b"Y"  # accepted as first child
            frame = struct.pack("<f", 0.25) + b"\xf0\x0f\xaa\x55"
            s.sendall(frame)
            assert _wait(lambda: len(master.links) == 1)
            got = None
            for _ in range(100):
                got = master.recv(master.links[0], timeout=0.1)
                if got is not None and got != bytes(frame_bytes):
                    break
            assert got == frame
            # reference peers also RECEIVE frames continuously: at minimum the
            # keepalive zero-frame arrives within ~2s (reference quirk Q2
            # behavior is load-bearing for C peers' liveness)
            s.settimeout(5)
            data = b""
            while len(data) < frame_bytes:
                data += s.recv(frame_bytes - len(data))
            assert len(data) == frame_bytes
        finally:
            s.close()


def test_bandwidth_cap():
    """Token-bucket pacing (reference README.md:31 TODO): with a 50 KB/s cap,
    sending 100 KB takes >= ~1.5s."""
    port = _free_port()
    cfg = TransportConfig(peer_timeout_sec=10.0, bandwidth_cap_bytes_per_sec=50_000)
    with TransportNode("127.0.0.1", port, cfg) as a, TransportNode(
        "127.0.0.1", port, cfg
    ) as b:
        assert _wait(lambda: b.uplink is not None and len(a.links) == 1)
        la, lb = a.links[0], b.uplink
        payload = bytes(10_000)
        t0 = time.time()
        received = 0
        sent = 0
        while received < 10:
            if sent < 10 and a.send(la, payload, timeout=0.05):
                sent += 1
            r = b.recv(lb, timeout=0.05)
            if r is not None and len(r) == len(payload):
                received += 1
        elapsed = time.time() - t0
        assert elapsed > 1.2, f"100KB at 50KB/s took only {elapsed:.2f}s"


def test_shm_lane_roundtrip_and_wrap_integrity():
    """r14 same-host lane at the raw transport level: serve + join by
    hand, then push enough variable-size payloads through a small ring
    that it WRAPS many times — every byte must arrive intact and in
    order (the mixed TCP-then-ring switch window included), and the lane
    stats must show the traffic actually rode the rings."""
    import os

    port = _free_port()
    cfg = TransportConfig(peer_timeout_sec=10.0)
    with TransportNode("127.0.0.1", port, cfg) as a, TransportNode(
        "127.0.0.1", port, cfg
    ) as b:
        assert _wait(lambda: b.uplink is not None and len(a.links) == 1)
        la, lb = a.links[0], b.uplink
        served = a.shm_serve(la, 1 << 17)  # 128 KiB ring
        assert served is not None
        name, token = served
        assert b.shm_join(lb, name, token)
        msgs = [
            bytes([i & 0xFF]) + os.urandom(16 + (i * 37) % 4000)
            for i in range(600)  # ~1.2 MB through a 128 KiB ring
        ]
        sent, rx = 0, []
        deadline = time.time() + 60
        while len(rx) < len(msgs) and time.time() < deadline:
            if sent < len(msgs) and a.send(la, msgs[sent], timeout=0.05):
                sent += 1
            g = b.recv(lb, timeout=0.01)
            if g is not None:
                rx.append(g)
        assert rx == msgs, (
            f"{sum(1 for i, r in enumerate(rx) if r != msgs[i])} of "
            f"{len(rx)} payloads corrupted/reordered across ring wraps"
        )
        sa, sb = a.shm_stats(la), b.shm_stats(lb)
        assert sa["state"] == 2 and sb["state"] == 2
        assert sa["msgs_out"] >= 1 and sb["msgs_in"] == sa["msgs_out"]
        # segment name must already be unlinked (leak-proof contract)
        assert not os.path.exists("/dev/shm/" + name)


def test_shm_join_rejects_bad_token_and_keeps_tcp():
    """Validation failure is a silent keep-TCP, never an error: a join
    with the wrong token must refuse the segment (shm_fallback path) and
    frames must keep flowing over the socket."""
    port = _free_port()
    cfg = TransportConfig(peer_timeout_sec=10.0)
    with TransportNode("127.0.0.1", port, cfg) as a, TransportNode(
        "127.0.0.1", port, cfg
    ) as b:
        assert _wait(lambda: b.uplink is not None and len(a.links) == 1)
        la, lb = a.links[0], b.uplink
        served = a.shm_serve(la, 1 << 17)
        assert served is not None
        name, token = served
        assert not b.shm_join(lb, name, token ^ 0xDEADBEEF)
        assert b.shm_join(lb, "../../etc/passwd", token) is False
        payload = b"tcp-still-fine" * 10
        assert a.send(la, payload)
        got = None
        for _ in range(100):
            got = b.recv(lb, timeout=0.1)
            if got:
                break
        assert got == payload
        st = b.shm_stats(lb)
        assert st is not None and st["state"] == 0  # never mapped


def test_shm_ring_full_backpressure_propagates_to_sender():
    """A tiny ring with a stalled reader: the lane writer blocks, the
    sendq fills, send() bounces (backpressure, not loss) — and draining
    the reader releases everything in order. The link must survive the
    whole episode (TCP keepalives hold liveness while the ring is
    full)."""
    port = _free_port()
    cfg = TransportConfig(peer_timeout_sec=10.0)
    with TransportNode("127.0.0.1", port, cfg) as a, TransportNode(
        "127.0.0.1", port, cfg
    ) as b:
        assert _wait(lambda: b.uplink is not None and len(a.links) == 1)
        la, lb = a.links[0], b.uplink
        served = a.shm_serve(la, 1 << 16)  # 64 KiB ring
        assert served is not None
        assert b.shm_join(lb, *served)
        payload = bytes(24_000)  # ~3 messages fill the ring
        accepted = 0
        bounced = False
        for _ in range(40):  # queue_depth(8) + ring(~2) << 40
            if a.send(la, payload, timeout=0.05):
                accepted += 1
            else:
                bounced = True
                break
        assert bounced, "sendq never filled — no backpressure observed"
        # drain: every accepted payload arrives intact, in order
        got = 0
        deadline = time.time() + 30
        while got < accepted and time.time() < deadline:
            g = b.recv(lb, timeout=0.2)
            if g is not None:
                assert g == payload
                got += 1
        assert got == accepted
        assert la in a.links, "link died during ring-full backpressure"


def test_simultaneous_master_election_storm():
    """N nodes race to the SAME empty rendezvous at once: exactly one must win
    the master election and everyone else must join its tree (round-2 verdict
    Weak #4 — the reference inherits this race and dies,
    src/sharedtensor.c:271-277,314; st_node_create now retries the
    bind/join race with backoff)."""
    import concurrent.futures

    port = _free_port()
    cfg = TransportConfig(peer_timeout_sec=10.0)
    n = 6
    with concurrent.futures.ThreadPoolExecutor(n) as ex:
        nodes = list(
            ex.map(lambda _: TransportNode("127.0.0.1", port, cfg), range(n))
        )
    try:
        masters = [nd for nd in nodes if nd.is_master]
        assert len(masters) == 1, f"{len(masters)} masters elected"
        joiners = [nd for nd in nodes if not nd.is_master]
        assert all(_wait(lambda nd=nd: nd.uplink is not None, 15) for nd in joiners)
        # the tree is connected: total child links == number of joiners
        assert _wait(
            lambda: sum(
                len(nd.links) - (0 if nd.is_master else 1) for nd in nodes
            ) == len(joiners),
            15,
        )
    finally:
        for nd in nodes:
            nd.close()
