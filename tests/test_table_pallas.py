"""Parity: the table-tier Pallas row kernels vs the pure-XLA table codec.

These are the PRODUCTION kernels — ops/table.py and parallel/ici.py dispatch
to them on TPU (round-2 verdict item 1: the benched kernels must be the
shipped kernels). Single-frame paths must match bit-for-bit; K-frame batch
sums may differ only by f32 summation order.

Runs in interpret mode on CPU (conftest forces JAX_PLATFORMS=cpu); the same
tests compile and pass on a real chip (ST_TEST_PLATFORM=axon).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from shared_tensor_tpu.config import ScalePolicy
from shared_tensor_tpu.ops import table as T


def _table(seed, shapes=((40, 70), (256,), (3, 5, 7)), scale_per_leaf=None):
    rng = np.random.default_rng(seed)
    tree = {}
    for i, s in enumerate(shapes):
        mag = 1.0 if scale_per_leaf is None else scale_per_leaf[i]
        tree[f"leaf{i}"] = (rng.normal(size=s) * mag).astype(np.float32)
    return tree


@pytest.mark.parametrize("per_leaf", [True, False])
@pytest.mark.parametrize(
    "policy", [ScalePolicy.POW2_RMS, ScalePolicy.RMS, ScalePolicy.ABS_MEAN]
)
def test_quantize_table_parity(per_leaf, policy):
    tree = _table(1, scale_per_leaf=[1.0, 1000.0, 0.001])
    spec = T.make_spec(tree)
    r = T.flatten(tree, spec)
    fg, rg = T.quantize_table(r, spec, policy, per_leaf, impl="xla")
    fp, rp = T.quantize_table(r, spec, policy, per_leaf, impl="pallas")
    np.testing.assert_array_equal(np.asarray(fp.scales), np.asarray(fg.scales))
    np.testing.assert_array_equal(np.asarray(fp.words), np.asarray(fg.words))
    np.testing.assert_array_equal(np.asarray(rp), np.asarray(rg))


def test_quantize_table_idle_leaf_parity():
    """A leaf whose residual is exactly zero idles (scale 0, residual kept)."""
    tree = {"a": np.ones((100,), np.float32), "b": np.zeros((2000,), np.float32)}
    spec = T.make_spec(tree)
    r = T.flatten(tree, spec)
    fg, rg = T.quantize_table(r, spec, impl="xla")
    fp, rp = T.quantize_table(r, spec, impl="pallas")
    assert float(fp.scales[1]) == 0.0
    np.testing.assert_array_equal(np.asarray(fp.scales), np.asarray(fg.scales))
    np.testing.assert_array_equal(np.asarray(fp.words), np.asarray(fg.words))
    np.testing.assert_array_equal(np.asarray(rp), np.asarray(rg))


def test_apply_table_many_parity():
    tree = _table(2)
    spec = T.make_spec(tree)
    r = T.flatten(tree, spec)
    frame, _ = T.quantize_table(r, spec, impl="xla")
    arrays = tuple(T.flatten(_table(10 + i), spec) for i in range(3))
    outs_g = T.apply_table_many(arrays, frame, spec, impl="xla")
    outs_p = T.apply_table_many(arrays, frame, spec, impl="pallas")
    for g, p in zip(outs_g, outs_p):
        np.testing.assert_array_equal(np.asarray(p), np.asarray(g))


@pytest.mark.parametrize("k", [1, 2, 5, 8])
def test_apply_table_batch_parity(k):
    tree = _table(3)
    spec = T.make_spec(tree)
    scales = []
    words = []
    r = T.flatten(tree, spec)
    for i in range(k):
        frame, r = T.quantize_table(r, spec, impl="xla")
        scales.append(np.asarray(frame.scales))
        words.append(np.asarray(frame.words))
    stacked = T.TableFrame(jnp.asarray(np.stack(scales)), jnp.asarray(np.stack(words)))
    arrays = (T.flatten(_table(30), spec), T.flatten(_table(31), spec))
    outs_g = T.apply_table_batch(arrays, stacked, spec, impl="xla")
    outs_p = T.apply_table_batch(arrays, stacked, spec, impl="pallas")
    for g, p in zip(outs_g, outs_p):
        # K-frame sums may round differently per f32 summation order
        np.testing.assert_allclose(np.asarray(p), np.asarray(g), rtol=1e-6, atol=1e-6)


def test_pallas_roundtrip_convergence():
    """Full sender->receiver loop on the Pallas tier alone: mixed-magnitude
    table converges to the target per-leaf (the README.md:41 capability).
    Uniform targets: the homogeneous regime where residual RMS halves per
    frame (SURVEY.md §6 convergence table)."""
    rng = np.random.default_rng(4)
    tree = {
        f"leaf{i}": (rng.uniform(-mag, mag, size=s)).astype(np.float32)
        for i, (s, mag) in enumerate(
            zip([(40, 70), (256,), (3, 5, 7)], [1.0, 500.0, 0.01])
        )
    }
    spec = T.make_spec(tree)
    r = T.flatten(tree, spec)
    v = jnp.zeros_like(r)
    for _ in range(80):
        frame, r = T.quantize_table(r, spec, impl="pallas")
        if not np.asarray(frame.scales).any():
            break
        v = T.apply_table_many((v,), frame, spec, impl="pallas")[0]
    target = T.flatten(tree, spec)
    # per-leaf relative convergence (each leaf's own magnitude is the yardstick)
    for leaf, got in zip(
        jax.tree.leaves(T.unflatten(target, spec)),
        jax.tree.leaves(T.unflatten(v, spec)),
    ):
        mag = float(np.abs(np.asarray(leaf)).max()) or 1.0
        np.testing.assert_allclose(
            np.asarray(got) / mag, np.asarray(leaf) / mag, rtol=0, atol=1e-4
        )


def test_ici_sync_step_pallas_parity():
    """The fused pod sync step built on the Pallas tier matches the XLA tier
    exactly (same state in, same state out) on a (4 peers x 2 shards) mesh."""
    from shared_tensor_tpu.ops.table import make_spec, flatten
    from shared_tensor_tpu.parallel.ici import build_sync_step, init_state
    from shared_tensor_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    mesh = make_mesh(4, 2)
    tree = _table(5, scale_per_leaf=[1.0, 100.0, 0.01])
    spec = make_spec(tree)
    rng = np.random.default_rng(6)
    upd = jnp.asarray(
        np.stack([np.asarray(flatten(_table(7 + p), spec)) for p in range(4)])
    )

    def run(impl):
        state = init_state(mesh, spec, template=tree)
        from shared_tensor_tpu.parallel.ici import add_updates

        state = add_updates(state, upd)
        step = build_sync_step(mesh, spec, impl=impl)
        for _ in range(3):
            state, scales = step(state)
        return np.asarray(state.values), np.asarray(state.residual), np.asarray(scales)

    vg, rg, sg = run("xla")
    vp, rp, sp = run("pallas")
    np.testing.assert_array_equal(sp, sg)
    np.testing.assert_array_equal(rp, rg)
    # values accumulate (n_peer-1) frame deltas per step; summation order may
    # differ between the XLA sum-reduction and the kernel's sequential loop
    np.testing.assert_allclose(vp, vg, rtol=1e-6, atol=1e-6)
