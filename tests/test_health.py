"""r18 fleet-health plane: time-series store, analyzer, clock offsets.

Pure-python unit coverage of the observability tentpole — no sockets, no
fleets (the end-to-end arm is benchmarks/fleet_health.py): ring bounds
and eviction honesty on the TimeSeriesStore, reset-tolerant rates against
hand-computed deltas, zipf-heat naming on synthetic digests, SLO
burn-rate alerts in BOTH directions (fire on stall, clear on recovery),
the offset-corrected staleness arithmetic, ClockSync convergence on a
simulated skew, the hardened RateMeter, the truncation-honest ``obs.top``
renderer, and the re-timestamped Perfetto export.
"""

import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from shared_tensor_tpu.obs import top as top_mod  # noqa: E402
from shared_tensor_tpu.obs import trace_export  # noqa: E402
from shared_tensor_tpu.obs.clock import ClockSync  # noqa: E402
from shared_tensor_tpu.obs.events import Event, HEALTH_EVENT_NAMES  # noqa: E402
from shared_tensor_tpu.obs.health import HealthAnalyzer  # noqa: E402
from shared_tensor_tpu.obs.timeseries import (  # noqa: E402
    TimeSeriesStore, hist_quantile,
)
from shared_tensor_tpu.utils.profiling import RateMeter  # noqa: E402

S = int(1e9)  # one second in ns


def _doc(nodes: dict, counters: dict | None = None,
         truncated: int = 0) -> dict:
    """Minimal digest doc in the aggregate.py v1 shape."""
    return {
        "v": 1,
        "nodes": {
            str(nid): {"t_ns": 0, "m": dict(m)} for nid, m in nodes.items()
        },
        "counters": dict(counters or {}),
        "hists": {},
        "gmax": {},
        "gmin": {},
        "proc": {},
        "truncated": truncated,
    }


# -- TimeSeriesStore ---------------------------------------------------------


class TestTimeSeriesStore:
    def test_ring_bounds(self):
        st = TimeSeriesStore(max_points=8)
        for i in range(50):
            st.ingest(_doc({}, {"st_frames_in_total": i}), i * S)
        vals = st.values(("cluster", "st_frames_in_total"))
        assert len(vals) == 8          # ring capped
        assert vals == list(range(42, 50))  # oldest evicted first

    def test_series_eviction_is_counted(self):
        st = TimeSeriesStore(max_series=3)
        # 4 distinct node series with staggered last-update stamps
        for i, name in enumerate(["a", "b", "c", "d"]):
            st.ingest(_doc({7: {f"st_{name}": 1.0}}), i * S)
        assert len(st) == 3
        assert st.evicted == 1
        # the least-recently-updated series ("a") is the one gone
        assert st.series(("node", 7, "st_a")) is None
        assert st.series(("node", 7, "st_d")) is not None

    def test_rate_matches_hand_computed(self):
        st = TimeSeriesStore()
        # 100 frames/s for 4 beats at 1s spacing
        for i in range(5):
            st.ingest(_doc({}, {"st_frames_in_total": 100 * i}), i * S)
        r = st.cluster_rate("st_frames_in_total", window_sec=10.0)
        assert abs(r - 100.0) < 1e-9
        # window narrower than the history: only the trailing span counts
        r2 = st.cluster_rate("st_frames_in_total", window_sec=2.0)
        assert abs(r2 - 100.0) < 1e-9

    def test_rate_tolerates_counter_reset(self):
        st = TimeSeriesStore()
        # 0, 100, 200, then the node restarts: 5, 105 — the negative
        # delta contributes ZERO, never a negative spike
        for i, v in enumerate([0, 100, 200, 5, 105]):
            st.ingest(_doc({}, {"st_frames_in_total": v}), i * S)
        r = st.cluster_rate("st_frames_in_total", window_sec=10.0)
        # gained = 100 + 100 + 0 + 100 over 4s
        assert abs(r - 75.0) < 1e-9
        assert r >= 0.0

    def test_node_series_keeps_labeled_names_verbatim(self):
        st = TimeSeriesStore()
        st.ingest(_doc({3: {'st_shard_heat_applies{shard="2"}': 10.0}}), S)
        st.ingest(_doc({3: {'st_shard_heat_applies{shard="2"}': 30.0}}), 2 * S)
        assert st.node_rate(3, 'st_shard_heat_applies{shard="2"}', 10.0) == 20.0

    def test_hist_quantile(self):
        h = {"sum": 0, "count": 100,
             "buckets": {"1": 50, "2": 90, "4": 100}}
        assert hist_quantile(h, 0.5) == 1.0
        # p99: target 99 between cum 90 (bound 2) and 100 (bound 4)
        assert abs(hist_quantile(h, 0.99) - (2 + 2 * 9 / 10)) < 1e-9
        assert hist_quantile({"count": 0, "buckets": {}}, 0.5) == 0.0


# -- RateMeter hardening -----------------------------------------------------


class TestRateMeter:
    def test_rates_match_hand_computed(self):
        m = RateMeter(window_sec=10.0)
        m.update_at(0.0, frames=0)
        m.update_at(2.0, frames=50)
        assert abs(m.rates()["frames"] - 25.0) < 1e-9

    def test_wall_clock_rewind_reanchors(self):
        m = RateMeter(window_sec=10.0)
        m.update_at(100.0, frames=1000)
        m.update_at(50.0, frames=1010)   # clock jumped BACKWARDS
        m.update_at(52.0, frames=1030)
        r = m.rates()
        assert r["frames"] >= 0.0
        assert abs(r["frames"] - 10.0) < 1e-9  # only the new timeline

    def test_counter_reset_reanchors(self):
        m = RateMeter(window_sec=10.0)
        m.update_at(0.0, frames=10_000)
        m.update_at(1.0, frames=0)       # restart: counter rewound
        m.update_at(2.0, frames=30)
        r = m.rates()
        assert abs(r["frames"] - 30.0) < 1e-9
        assert r["frames"] >= 0.0

    def test_rates_never_negative(self):
        m = RateMeter(window_sec=10.0)
        m.update_at(0.0, a=0.0)
        m.update_at(1.0, a=1e-9)  # float-noise-scale positive delta
        assert all(v >= 0.0 for v in m.rates().values())


# -- ClockSync ---------------------------------------------------------------


class TestClockSync:
    def test_converges_on_simulated_skew(self):
        skew_ns = 50_000_000  # child runs +50ms ahead of the root
        t = {"now": 0}

        def root_now():
            return t["now"]

        def child_now():
            return t["now"] + skew_ns

        root = ClockSync(root_now, is_root=True)
        child = ClockSync(child_now)
        assert root.known and root.offset_ns == 0
        assert not child.known
        for _ in range(8):
            probe = child.probe_payload()
            t["now"] += 200_000          # 0.2ms uplink transit
            reply = root.reply_payload(probe)
            t["now"] += 300_000          # 0.3ms downlink transit
            assert child.on_reply(reply)
        assert child.known
        # min-RTT bound: |error| <= rtt/2 = 0.25ms
        assert abs(child.offset_ns - skew_ns) <= child.uncertainty_ns
        assert child.uncertainty_ns <= 250_000 + 1

    def test_unconverged_parent_is_skipped(self):
        t = {"now": 0}
        parent = ClockSync(lambda: t["now"])       # NOT root: no estimate
        child = ClockSync(lambda: t["now"])
        reply = parent.reply_payload(child.probe_payload())
        assert "off_ns" not in reply
        assert not child.on_reply(reply)
        assert not child.known


# -- HealthAnalyzer: heat ----------------------------------------------------


def _heat_doc(applies: dict, t_ns: int) -> dict:
    nodes = {
        nid: {f'st_shard_heat_applies{{shard="{k}"}}': float(v)}
        for nid, (k, v) in applies.items()
    }
    return _doc(nodes)


class TestHeat:
    def test_names_hot_shard_on_zipf_writes(self):
        events = []
        a = HealthAnalyzer(skew_ratio=3.0, heat_window_sec=10.0,
                           emit=lambda *e: events.append(e))
        # shard 1 applies 100/s, shards 0 and 2 apply 10/s each
        for i in range(4):
            a.beat(_heat_doc({
                10: (0, 10 * i), 11: (1, 100 * i), 12: (2, 10 * i),
            }, i * S), i * S)
        d = a.doc()
        assert d["heat"]["hot_shard"] == 1
        assert d["heat"]["skew_ratio"] >= 3.0
        assert any(e[0] == "hot_shard" and e[1] == 1 for e in events)
        assert "hot_shard" in HEALTH_EVENT_NAMES

    def test_uniform_fleet_has_no_hot_shard(self):
        a = HealthAnalyzer(skew_ratio=3.0)
        for i in range(4):
            a.beat(_heat_doc({
                10: (0, 50 * i), 11: (1, 55 * i), 12: (2, 45 * i),
            }, i * S), i * S)
        assert a.doc()["heat"]["hot_shard"] == -1

    def test_heat_metrics_render_labeled_gauges(self):
        a = HealthAnalyzer()
        for i in range(3):
            a.beat(_heat_doc({10: (0, 10 * i), 11: (1, 90 * i)}, i * S),
                   i * S)
        m = a.metrics()
        assert m["st_heat_hot_shard"] == float(a.doc()["heat"]["hot_shard"])
        assert 'st_slo_burn_rate{window="page"}' in m


# -- HealthAnalyzer: staleness correction ------------------------------------


class TestStalenessCorrection:
    def test_offset_corrected_with_error_bound(self):
        a = HealthAnalyzer()
        # applier 1 (offset -10ms), origin 2 (offset +50ms): the raw
        # cross-clock age must widen by off_origin - off_applier = +60ms
        doc = _doc({
            1: {
                'st_staleness_seconds{link="3"}': 0.200,
                'st_staleness_origin{link="3"}': 2.0,
                "st_clock_offset_seconds": -0.010,
                "st_clock_uncertainty_seconds": 0.001,
            },
            2: {
                "st_clock_offset_seconds": 0.050,
                "st_clock_uncertainty_seconds": 0.002,
            },
        })
        a.beat(doc, S)
        rec = a.doc()["staleness"]["nodes"]["1"]
        assert abs(rec["corrected_sec"] - 0.260) < 1e-9
        assert abs(rec["unc_sec"] - 0.003) < 1e-9
        assert rec["origin"] == 2

    def test_missing_clock_keeps_raw_flagged(self):
        a = HealthAnalyzer()
        a.beat(_doc({1: {"st_staleness_seconds": 0.5}}), S)
        rec = a.doc()["staleness"]["nodes"]["1"]
        assert rec["corrected_sec"] == 0.5
        assert rec["unc_sec"] is None   # flagged, never silently trusted

    def test_corrected_clamps_at_zero(self):
        a = HealthAnalyzer()
        doc = _doc({
            1: {
                'st_staleness_seconds{link="3"}': 0.010,
                'st_staleness_origin{link="3"}': 2.0,
                "st_clock_offset_seconds": 0.0,
                "st_clock_uncertainty_seconds": 0.001,
            },
            2: {
                "st_clock_offset_seconds": -0.050,
                "st_clock_uncertainty_seconds": 0.001,
            },
        })
        a.beat(doc, S)
        assert a.doc()["staleness"]["nodes"]["1"]["corrected_sec"] == 0.0


# -- HealthAnalyzer: SLO both directions -------------------------------------


class TestSlo:
    def _analyzer(self, events):
        return HealthAnalyzer(
            objective_sec=1.0,
            budget=0.05,
            windows=(("page", 4.0, 1.0, 2.0),),
            emit=lambda *e: events.append(e),
        )

    def _beat(self, a, stale_sec, t_ns):
        a.beat(_doc({1: {"st_staleness_seconds": stale_sec}}), t_ns)

    def test_fires_on_stall_and_clears_on_recovery(self):
        events = []
        a = self._analyzer(events)
        t = 0
        for _ in range(10):                 # healthy: 0.1s staleness
            t += S // 5
            self._beat(a, 0.1, t)
        assert a.doc()["slo"]["alert"] == 0
        for _ in range(10):                 # stall: objective blown
            t += S // 5
            self._beat(a, 5.0, t)
        assert a.doc()["slo"]["alert"] == 2
        assert a.doc()["slo"]["windows"]["page"]["firing"]
        assert [e[0] for e in events].count("slo_alert_fire") == 1
        for _ in range(10):                 # recovery
            t += S // 5
            self._beat(a, 0.1, t)
        assert a.doc()["slo"]["alert"] == 0
        assert not a.doc()["slo"]["windows"]["page"]["firing"]
        assert [e[0] for e in events].count("slo_alert_clear") == 1
        assert {"slo_alert_fire", "slo_alert_clear"} <= HEALTH_EVENT_NAMES

    def test_short_blip_does_not_page(self):
        events = []
        a = self._analyzer(events)
        t = 0
        for _ in range(19):
            t += S // 5
            self._beat(a, 0.1, t)
        t += S // 5
        self._beat(a, 5.0, t)               # ONE bad beat
        # long window 4s = 20 beats, 1 bad => burn 1/s window may spike
        # but the LONG window (1/20/0.05 = 1.0x) stays under 2x: no page
        assert a.doc()["slo"]["alert"] == 0
        assert not any(e[0] == "slo_alert_fire" for e in events)

    def test_bad_beats_counter_monotonic(self):
        a = self._analyzer([])
        t = 0
        for i in range(6):
            t += S // 5
            self._beat(a, 5.0 if i % 2 else 0.1, t)
        assert a.bad_beats == 3
        assert a.metrics()["st_slo_bad_beats_total"] == 3


# -- health.json write -------------------------------------------------------


def test_health_json_written_atomically(tmp_path):
    path = tmp_path / "health.json"
    a = HealthAnalyzer(path=str(path))
    a.beat(_doc({1: {"st_staleness_seconds": 0.2}}), S)
    import json

    doc = json.loads(path.read_text())
    assert doc["v"] == 1
    assert doc["beats"] == 1
    assert "slo" in doc and "heat" in doc and "staleness" in doc
    assert not list(tmp_path.glob("*.tmp.*"))  # no droppings


# -- obs.top v2 --------------------------------------------------------------


class TestTopRender:
    def test_truncation_honesty(self):
        doc = _doc({1: {"st_frames_in_total": 5.0}},
                    {"st_frames_in_total": 10}, truncated=3)
        out = top_mod.render(doc, None, 0.0)
        assert "3 node breakdown(s) TRUNCATED" in out
        assert "totals exact" in out
        assert "breakdown truncated at the digest bound" in out

    def test_complete_breakdown_says_so(self):
        out = top_mod.render(_doc({1: {}}), None, 0.0)
        assert "breakdown complete" in out
        assert "TRUNCATED" not in out

    def test_health_slo_row_and_heat_table(self):
        doc = _doc({11: {'st_shard_heat_applies{shard="1"}': 50.0}})
        health = {
            "slo": {
                "alert": 2,
                "windows": {"page": {"burn_long": 20.0, "burn_short": 14.0,
                                      "firing": True}},
            },
            "staleness": {
                "worst": {"corrected_sec": 5.0, "unc_sec": 0.003,
                          "raw_sec": 4.95, "node": 11, "origin": 12},
            },
            "heat": {
                "hot_shard": 1,
                "shards": {
                    "0": {"score": 0.2, "apply_rate": 10.0},
                    "1": {"score": 1.0, "apply_rate": 100.0},
                },
            },
        }
        out = top_mod.render(doc, None, 0.0, health=health)
        assert "slo [PAGE]" in out
        assert "worst corrected 5.0000s ±0.0030s" in out
        assert "page* 20.0x/14.0x" in out
        assert "HOT shard 1" in out
        assert "s1!=1.00(100/s)" in out
        assert "heat" in out  # per-node heat column header

    def test_uncorrected_staleness_is_flagged(self):
        health = {
            "slo": {"alert": 0, "windows": {}},
            "staleness": {"worst": {"corrected_sec": 0.4, "unc_sec": None,
                                    "raw_sec": 0.4, "node": 1,
                                    "origin": None}},
            "heat": {"hot_shard": -1, "shards": {}},
        }
        out = top_mod.render(_doc({1: {}}), None, 0.0, health=health)
        assert "(uncorrected)" in out

    def test_sparkline_rows_from_store(self):
        st = TimeSeriesStore()
        for i in range(6):
            st.ingest(_doc({}, {"st_frames_in_total": 100 * i}), i * S)
        out = top_mod.render(_doc({}), None, 0.0, store=st)
        assert "frames/beat" in out
        assert any(ch in out for ch in top_mod._SPARK_CHARS)


# -- Perfetto export re-timestamping -----------------------------------------


def test_chrome_trace_offsets_rebase_onto_root_clock():
    # node 7 runs +50ms ahead: its instant must land at t - off
    events = [
        Event(t_ns=1_050_000_000, tier="py", name="digest_publish", node=7),
        Event(t_ns=1_000_000_000, tier="py", name="digest_publish", node=1),
    ]
    doc = trace_export.chrome_trace(
        events, flows=False, offsets_ns={7: 50_000_000}
    )
    ts = {e["pid"]: e["ts"] for e in doc["traceEvents"] if e["ph"] == "i"}
    assert ts[1] == 1_000_000_000 / 1000.0
    assert ts[7] == 1_000_000_000 / 1000.0  # rebased onto the root clock


def test_chrome_trace_unlisted_nodes_keep_raw_stamps():
    events = [Event(t_ns=2_000_000, tier="c", name="link_up", node=4)]
    doc = trace_export.chrome_trace(events, flows=False, offsets_ns={9: 99})
    inst = [e for e in doc["traceEvents"] if e["ph"] == "i"]
    assert inst[0]["ts"] == 2_000.0
