"""Bit-for-bit parity: Pallas codec kernels vs the pure-JAX golden codec.

Runs in interpret mode on CPU (conftest forces JAX_PLATFORMS=cpu); the same
tests compile and pass on a real TPU chip.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from shared_tensor_tpu.config import ScalePolicy
from shared_tensor_tpu.ops import codec, codec_pallas
from shared_tensor_tpu.ops.packing import padded_len


def _rand_resid(n, seed, scale=1.0):
    rng = np.random.default_rng(seed)
    n_pad = padded_len(n)
    r = np.zeros(n_pad, dtype=np.float32)
    r[:n] = (rng.normal(size=n) * scale).astype(np.float32)
    return r


@pytest.mark.parametrize("n", [17, 240, 1024, 4096, 40000])
def test_quantize_parity(n):
    r = _rand_resid(n, n)
    frame_g, resid_g = codec.quantize(jnp.asarray(r), n)
    frame_p, resid_p = codec_pallas.quantize(jnp.asarray(r), n)
    assert float(frame_p.scale) == float(frame_g.scale)
    np.testing.assert_array_equal(np.asarray(frame_p.words), np.asarray(frame_g.words))
    np.testing.assert_array_equal(np.asarray(resid_p), np.asarray(resid_g))


@pytest.mark.parametrize("policy", [ScalePolicy.POW2_RMS, ScalePolicy.RMS, ScalePolicy.ABS_MEAN])
def test_quantize_parity_policies(policy):
    n = 3000
    r = _rand_resid(n, 5)
    frame_g, resid_g = codec.quantize(jnp.asarray(r), n, policy)
    frame_p, resid_p = codec_pallas.quantize(jnp.asarray(r), n, policy)
    assert float(frame_p.scale) == float(frame_g.scale)
    np.testing.assert_array_equal(np.asarray(frame_p.words), np.asarray(frame_g.words))
    np.testing.assert_array_equal(np.asarray(resid_p), np.asarray(resid_g))


def test_quantize_zero_residual_parity():
    n = 1024
    z = jnp.zeros(padded_len(n), jnp.float32)
    frame_p, resid_p = codec_pallas.quantize(z, n)
    assert float(frame_p.scale) == 0.0
    np.testing.assert_array_equal(np.asarray(resid_p), 0.0)


@pytest.mark.parametrize("n", [17, 1024, 40000])
def test_apply_parity(n):
    r = _rand_resid(n, n + 1)
    v = _rand_resid(n, n + 2)
    frame, _ = codec.quantize(jnp.asarray(r), n)
    out_g = codec.apply_frame(jnp.asarray(v), frame, n)
    out_p = codec_pallas.apply_frame(jnp.asarray(v), frame, n)
    np.testing.assert_array_equal(np.asarray(out_p), np.asarray(out_g))


def test_apply_many_parity():
    n = 5000
    r = _rand_resid(n, 30)
    frame, _ = codec.quantize(jnp.asarray(r), n)
    arrays = tuple(jnp.asarray(_rand_resid(n, 40 + i)) for i in range(3))
    outs_g = codec.apply_frame_many(arrays, frame, n)
    arrays2 = tuple(jnp.asarray(_rand_resid(n, 40 + i)) for i in range(3))
    outs_p = codec_pallas.apply_frame_many(arrays2, frame, n)
    for g, p in zip(outs_g, outs_p):
        np.testing.assert_array_equal(np.asarray(p), np.asarray(g))


def test_link_convergence_with_pallas():
    """Full link loop driven by the Pallas kernels: exact convergence holds."""
    rng = np.random.default_rng(50)
    n = 2048
    target = rng.uniform(-1, 1, size=n).astype(np.float32)
    r = jnp.asarray(target)
    v = jnp.zeros(n, dtype=jnp.float32)
    for _ in range(40):
        frame, r = codec_pallas.quantize(r, n)
        if float(frame.scale) == 0.0:
            break
        v = codec_pallas.apply_frame(v, frame, n)
    assert float(jnp.max(jnp.abs(r))) == 0.0
    np.testing.assert_allclose(np.asarray(v), target, rtol=0, atol=1.5e-7)
