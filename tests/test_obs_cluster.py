"""Cluster-wide observability tests (r09 tentpole evidence).

Covers the distributed tier end to end:

- the digest algebra (obs/aggregate.py): counters by sum, histograms by
  bucket-add, gauges by labeled max/min, bounded per-node breakdowns;
- causal-path reconstruction + the Perfetto exporter
  (obs/trace_export.py) and the ``obs.top`` terminal renderer;
- v1/v2 wire interop (a trace-disabled peer in a traced tree);
- the 7-node loopback tree under chaos: every delivered update's
  reconstructed trace path is CONTIGUOUS (no hop gaps), and at a quiesced
  instant the root's cluster-digest totals equal the SUM of the per-node
  registries exactly (the acceptance bar CHAOS_r09.json re-runs as a
  committed artifact, benchmarks/cluster_chaos.py).
"""

import io
import json
import time
from contextlib import redirect_stdout

import jax.numpy as jnp
import numpy as np
import pytest

from shared_tensor_tpu import compat, obs
from shared_tensor_tpu.comm import faults, transport, wire
from shared_tensor_tpu.comm.peer import SharedTensorPeer, create_or_fetch
from shared_tensor_tpu.config import (
    Config, FaultConfig, ObsConfig, TransportConfig,
)
from shared_tensor_tpu.obs import aggregate, trace_export
from shared_tensor_tpu.obs import events as obs_events

from tests._ports import free_port as _free_port


@pytest.fixture(scope="module", autouse=True)
def _built():
    transport.build_native()


def _cfg(fault: FaultConfig | None = None, obs_cfg: ObsConfig | None = None,
         engine: bool = True, **tkw):
    tkw.setdefault("peer_timeout_sec", 15.0)
    return Config(
        transport=TransportConfig(**tkw),
        faults=fault or FaultConfig(),
        obs=obs_cfg or ObsConfig(digest_interval_sec=0.2),
        native_engine=engine,
    )


def _fresh_hub(capacity: int = 0):
    h = obs.hub()
    h.poll_native()
    h.recorder.clear()
    if capacity:
        h.recorder.set_capacity(capacity)
    return h


def _wait(pred, timeout=60.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------------
# digest algebra (obs/aggregate.py)
# ---------------------------------------------------------------------------


def test_aggregate_merge_semantics():
    a = aggregate.from_snapshot(
        1,
        {
            "st_frames_out_total": 10,
            "st_msgs_out_total": 4,
            "st_residual_norm": 2.5,
            'st_staleness_seconds{link="1"}': 0.25,
            "st_apply_seconds": {"sum": 1.0, "count": 2,
                                 "buckets": {0.01: 1, 0.1: 2}},
        },
        t_ns=100,
    )
    b = aggregate.from_snapshot(
        2,
        {
            "st_frames_out_total": 7,
            "st_residual_norm": 9.0,
            'st_staleness_seconds{link="1"}': 0.05,
            "st_apply_seconds": {"sum": 0.5, "count": 1,
                                 "buckets": {0.01: 0, 0.1: 1}},
        },
        t_ns=200,
    )
    m = aggregate.merge(a, b)
    # counters: SUM (per-link labels strip into the base name)
    assert m["counters"]["st_frames_out_total"] == 17
    assert m["counters"]["st_msgs_out_total"] == 4
    # histograms: bucket-add, sums and counts add
    h = m["hists"]["st_apply_seconds"]
    assert h["sum"] == 1.5 and h["count"] == 3
    assert h["buckets"] == {"0.01": 1, "0.1": 3}
    # gauges: labeled max/min — value AND owner
    assert m["gmax"]["st_residual_norm"] == [9.0, 2]
    assert m["gmin"]["st_residual_norm"] == [2.5, 1]
    assert m["gmax"]["st_staleness_seconds"] == [0.25, 1]
    # per-node breakdown is the union, stamped
    assert set(m["nodes"]) == {"1", "2"}
    assert m["nodes"]["2"]["t_ns"] == 200
    # the rendered exposition carries the node labels
    text = aggregate.prometheus_text(m)
    assert "st_frames_out_total 17" in text
    assert 'st_residual_norm_max{node="2"} 9' in text
    assert 'st_staleness_seconds{node="1",link="1"} 0.25' in text
    # encodes under the wire cap and round-trips
    payload = wire.encode_digest(aggregate.bounded(m))
    assert wire.decode_digest(payload)["counters"]["st_frames_out_total"] == 17


def test_aggregate_process_global_counters_dedup_by_pid():
    """PROCESS-scoped counters (ring drops, corrupt-scale zeroings) are the
    same number at every peer of a process: the digest must count each
    process once, not once per peer — 7 loopback peers reporting a ring
    drop must not inflate it 7x (review catch)."""
    snap = {"st_obs_events_dropped_total": 5, "st_frames_out_total": 3}
    a = aggregate.from_snapshot(1, snap, t_ns=1)
    b = aggregate.from_snapshot(2, snap, t_ns=2)  # same process, same value
    m = aggregate.merge(a, b)
    # peer-scoped counters sum; process-scoped dedup by pid
    assert m["counters"]["st_frames_out_total"] == 6
    assert "st_obs_events_dropped_total" not in m["counters"]
    assert aggregate.process_global_totals(m) == {
        "st_obs_events_dropped_total": 5
    }
    text = aggregate.prometheus_text(m)
    assert "st_obs_events_dropped_total 5" in text
    # full-precision rendering: %g would round this to 1.23457e+07
    big = aggregate.from_snapshot(3, {"st_frames_out_total": 12345678}, 3)
    assert "st_frames_out_total 12345678" in aggregate.prometheus_text(big)


def test_aggregate_bounded_truncates_oldest_breakdowns():
    doc = aggregate.empty()
    for i in range(aggregate.MAX_NODES + 10):
        aggregate.merge(
            doc,
            aggregate.from_snapshot(i, {"st_updates_total": 1}, t_ns=i),
        )
    aggregate.bounded(doc)
    assert len(doc["nodes"]) == aggregate.MAX_NODES
    assert doc["truncated"] == 10
    # the OLDEST breakdowns dropped; totals kept every node's contribution
    assert "0" not in doc["nodes"] and "9" not in doc["nodes"]
    assert doc["counters"]["st_updates_total"] == aggregate.MAX_NODES + 10


# ---------------------------------------------------------------------------
# path reconstruction + exporters
# ---------------------------------------------------------------------------


def _apply_ev(node, link, origin, gen, hop, t):
    return obs_events.Event(
        t, "c", "trace_apply", node, link, gen, extra=(origin << 8) | hop
    )


def test_trace_paths_and_contiguity():
    evs = [
        _apply_ev(2, 1, 1, 1000, 1, 10),
        _apply_ev(3, 2, 1, 1000, 2, 20),
        _apply_ev(4, 1, 1, 1000, 2, 21),  # sibling at the same hop depth
        _apply_ev(2, 1, 1, 2000, 1, 30),  # second generation, short path
        _apply_ev(5, 3, 1, 3000, 3, 40),  # HOLE: hops {3} misses 1..2
        obs_events.Event(15, "py", "link_up", 9, 1, 0),  # non-trace noise
    ]
    paths = trace_export.trace_paths(evs)
    assert set(paths) == {(1, 1000), (1, 2000), (1, 3000)}
    assert [r["hop"] for r in paths[(1, 1000)]] == [1, 2, 2]
    assert trace_export.contiguous(paths[(1, 1000)])
    assert trace_export.contiguous(paths[(1, 2000)])  # short but gap-free
    assert not trace_export.contiguous(paths[(1, 3000)])  # the hole
    stats = trace_export.path_stats(paths)
    assert stats["paths"] == 3 and stats["contiguous"] == 2
    assert stats["max_hops"] == 3
    assert stats["contiguous_frac"] == pytest.approx(2 / 3)


def test_chrome_trace_export_is_perfetto_loadable_shape(tmp_path):
    evs = [
        _apply_ev(2, 1, 1, 1000, 1, 10_000),
        _apply_ev(3, 2, 1, 1000, 2, 20_000),
        obs_events.Event(5_000, "py", "retransmit", 2, 1, 3),
    ]
    path = str(tmp_path / "trace.json")
    trace_export.export_file(path, evs)
    doc = json.loads(open(path).read())
    tes = doc["traceEvents"]
    # metadata names every node track
    assert any(
        t["ph"] == "M" and t["name"] == "process_name" and t["pid"] == 2
        for t in tes
    )
    # instants carry the trace args, microsecond timestamps
    inst = [t for t in tes if t["ph"] == "i" and t["name"] == "trace_apply"]
    assert len(inst) == 2 and inst[0]["args"]["origin"] == 1
    assert inst[0]["ts"] == pytest.approx(10.0)
    # the multi-hop generation became a flow (s -> t) across node tracks
    flow = [t for t in tes if t["ph"] in ("s", "t")]
    assert len(flow) == 2
    assert flow[0]["ph"] == "s" and flow[0]["pid"] == 2
    assert flow[1]["ph"] == "t" and flow[1]["pid"] == 3


def test_obs_top_renders_digest(tmp_path):
    from shared_tensor_tpu.obs import top

    doc = aggregate.from_snapshot(
        3,
        {
            "st_frames_out_total": 12,
            "st_frames_in_total": 9,
            "st_updates_total": 4,
            "st_residual_norm": 1.25,
            'st_staleness_seconds{link="2"}': 0.5,
        },
        t_ns=1,
    )
    p = tmp_path / "cluster.json"
    p.write_text(json.dumps(doc))
    out = io.StringIO()
    with redirect_stdout(out):
        rc = top.main(["--file", str(p), "--once"])
    assert rc == 0
    text = out.getvalue()
    assert "1 node(s)" in text
    assert "worst staleness 0.5000s @ node 3" in text
    # the per-node row renders its metrics
    assert any(l.strip().startswith("3 ") for l in text.splitlines())


# ---------------------------------------------------------------------------
# wire-format version gate (compat.py)
# ---------------------------------------------------------------------------


def test_wire_protocol_version_gate(monkeypatch):
    assert compat.wire_protocol_version(Config()) == compat.WIRE_VERSION_V2
    cfg = Config(obs=ObsConfig(trace_wire=False))
    assert compat.wire_protocol_version(cfg) == compat.WIRE_VERSION_V1
    monkeypatch.setenv("ST_WIRE_TRACE", "0")
    assert compat.wire_protocol_version(Config()) == compat.WIRE_VERSION_V1


def test_sync_advertises_wire_version():
    from shared_tensor_tpu.ops.table import make_spec

    spec = make_spec(np.zeros(64, np.float32))
    p2 = wire.encode_sync(spec, 2)
    assert wire.sync_wire_version(p2) == 2
    assert wire.sync_flags(p2) == 0  # r10: absent/zero flags = plain writer
    # an r09 SYNC (version byte, no r10 flags byte) reads as v2 + flags 0
    r09 = p2[:-1]
    assert wire.sync_wire_version(r09) == 2
    assert wire.sync_flags(r09) == 0
    # a pre-r09 SYNC (no trailing bytes at all) reads as v1
    legacy = p2[:-2]
    assert wire.sync_wire_version(legacy) == 1
    assert wire.sync_flags(legacy) == 0
    # and the layout fields decode identically every way
    assert wire.decode_sync(p2) == wire.decode_sync(legacy)
    # r10 subscriber flags survive the trip
    from shared_tensor_tpu.compat import SYNC_FLAG_RANGE, SYNC_FLAG_READ_ONLY

    flagged = wire.encode_sync(
        spec, 2, SYNC_FLAG_READ_ONLY | SYNC_FLAG_RANGE
    )
    assert wire.sync_flags(flagged) == (SYNC_FLAG_READ_ONLY | SYNC_FLAG_RANGE)
    assert wire.sync_wire_version(flagged) == 2


def test_v1_v2_mixed_tree_interop():
    """A trace-disabled (v1-emitting) joiner in a traced tree: both
    directions decode, both replicas converge exactly — the r09 framing is
    version-gated, never a flag-day."""
    port = _free_port()
    n = 1024
    seed = jnp.zeros((n,), jnp.float32)
    m = create_or_fetch("127.0.0.1", port, seed, _cfg())
    c = create_or_fetch(
        "127.0.0.1", port, seed,
        _cfg(obs_cfg=ObsConfig(trace_wire=False, digest_interval_sec=0.2)),
    )
    try:
        assert m._trace_wire and not c._trace_wire
        total = np.zeros(n, np.float64)
        rng = np.random.default_rng(3)
        for i in range(10):
            d = rng.normal(size=n).astype(np.float32)
            (m if i % 2 == 0 else c).add(jnp.asarray(d))
            total += d
            time.sleep(0.01)
        for p, who in ((m, "master"), (c, "joiner")):
            _wait(
                lambda p=p: np.allclose(np.asarray(p.read()), total, atol=1e-4),
                msg=f"{who} to converge across mixed framings",
            )
        # the v2->v1 direction still produced staleness telemetry at c;
        # the v1->v2 direction left m without trace stamps from c — both
        # are fine, and nothing was dropped as undecodable
        assert c.metrics(canonical=True)["st_dedup_discards_total"] == 0
    finally:
        m.close()
        c.close()


# ---------------------------------------------------------------------------
# the 7-node chaos tree (acceptance bar)
# ---------------------------------------------------------------------------


def _build_tree(port, n_nodes, seed, monkeypatch, chaos_node=6):
    """Root + (n_nodes-1) joiners, binary fan-out. ``chaos_node`` (index)
    is created under an ST_FAULT_PLAN drop schedule, so its ENGINE sender
    injects wire chaos below Python (the env table is parsed per
    st_node_create — only that node is chaotic). Default: the deep leaf
    that also originates adds — a leaf that never adds sends nothing
    upward (split horizon), so chaos on it would be vacuous."""
    peers = []
    env = faults.to_env(
        FaultConfig(enabled=True, seed=9, drop_pct=0.25, only_link=1)
    )
    for i in range(n_nodes):
        if i == chaos_node:
            monkeypatch.setenv("ST_FAULT_PLAN", env["ST_FAULT_PLAN"])
        try:
            peers.append(
                create_or_fetch(
                    "127.0.0.1", port, seed,
                    _cfg(ack_timeout_sec=0.4), timeout=60.0,
                )
            )
        finally:
            if i == chaos_node:
                monkeypatch.delenv("ST_FAULT_PLAN")
    return peers


def test_cluster_trace_paths_and_digest_totals_7_nodes(monkeypatch):
    """The acceptance bar, in-suite: a 7-node loopback tree under an
    engine-tier drop schedule. Every delivered update's reconstructed
    trace path must be contiguous (>= 99%), and at a quiesced instant the
    root's cluster-digest totals must equal the sum of the per-node
    registries EXACTLY for the quiesce-stable counters."""
    hub = _fresh_hub(capacity=200_000)
    port = _free_port()
    n = 2048
    seed = jnp.zeros((n,), jnp.float32)
    peers = _build_tree(port, 7, seed, monkeypatch)
    try:
        assert all(p._engine is not None for p in peers), "engine tier expected"
        total = np.zeros(n, np.float64)
        rng = np.random.default_rng(0)
        # updates from the root AND a deep node: multi-origin traffic, so
        # paths cross in both directions while the chaos node drops frames
        for i in range(24):
            d = rng.uniform(-0.5, 0.5, n).astype(np.float32)
            peers[0 if i % 2 else 6].add(jnp.asarray(d))
            total += d
            time.sleep(0.015)
        for i, p in enumerate(peers):
            _wait(
                lambda p=p: np.allclose(np.asarray(p.read()), total, atol=1e-4),
                timeout=90.0, msg=f"peer {i} to reconverge through chaos",
            )
        assert all(p.drain(timeout=30.0, tol=1e-30) for p in peers)

        # ---- trace-path contiguity over the whole run -------------------
        hub.poll_native()
        timeline = hub.recorder.timeline()
        paths = trace_export.trace_paths(timeline)
        stats = trace_export.path_stats(paths)
        assert stats["paths"] >= 20, stats
        assert stats["contiguous_frac"] >= 0.99, stats
        # a 7-node binary tree is 2 levels deep: root-origin updates reach
        # hop 2, leaf-origin ones hop >= 3 somewhere
        assert stats["max_hops"] >= 3, stats
        # chaos actually happened AND was repaired (drops -> retransmits)
        assert hub.recorder.counts["fault_drop"] >= 1
        assert hub.recorder.counts["retransmit"] >= 1

        # ---- digest totals == sum of registries at the quiesced instant -
        # push bottom-up a few rounds so every subtree's latest totals
        # reach the root regardless of tree shape
        for _ in range(4):  # one round per possible tree level, + margin
            for p in peers:
                if p._uplink is not None:
                    p.push_digest()
            time.sleep(0.4)
        cluster = peers[0].metrics(cluster=True)
        assert len(cluster["nodes"]) == 7, sorted(cluster["nodes"])
        snaps = [p.metrics(canonical=True) for p in peers]
        stable = (
            "st_frames_out_total", "st_frames_in_total", "st_updates_total",
            "st_msgs_out_total", "st_msgs_in_total",
            "st_retransmit_msgs_total", "st_dedup_discards_total",
            "st_traced_msgs_in_total",
        )
        for name in stable:
            want = sum(s.get(name, 0) for s in snaps)
            got = cluster["counters"].get(name, 0)
            assert got == want, (name, got, want)
        # staleness extrema carry their owning node
        gmax = cluster["gmax"].get("st_staleness_seconds")
        assert gmax is not None and gmax[0] >= 0.0
        assert int(gmax[1]) in {p.node.obs_id for p in peers}
        # and the Prometheus rendering serves the whole-tree view
        text = peers[0].cluster_prometheus_text()
        want_updates = sum(s.get("st_updates_total", 0) for s in snaps)
        assert f"st_updates_total {float(want_updates):g}" in text
    finally:
        for p in peers:
            p.close()
