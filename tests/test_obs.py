"""Unified cross-tier telemetry tests (r08 tentpole evidence).

Covers the three tentpole pieces end to end:

- the metrics registry (counters/gauges/fixed-bucket histograms, snapshot,
  Prometheus text exposition, JSONL sink) and the canonical key schema
  that supersedes the four ad-hoc metric surfaces;
- the native event ring (lock-free per-thread rings in sttransport.cpp,
  drained over ``st_obs_drain``) merged with Python-tier events on the
  shared CLOCK_MONOTONIC timebase — ONE ordered timeline spanning tiers;
- the flight recorder: under ``ST_FAULT_PLAN`` / FaultPlan chaos, every
  injected drop/dup/sever must appear in the merged timeline (exact
  counts on the Python tier, where the injector reports its tallies), and
  crash points / recv-thread exceptions / go-back-N teardowns must leave
  a postmortem dump.
"""

import json
import os
import time

import jax.numpy as jnp
import numpy as np
import pytest

from shared_tensor_tpu import obs
from shared_tensor_tpu.comm import faults, transport, wire
from shared_tensor_tpu.comm.peer import SharedTensorPeer, create_or_fetch
from shared_tensor_tpu.config import Config, FaultConfig, ObsConfig, TransportConfig
from shared_tensor_tpu.obs import events as obs_events
from shared_tensor_tpu.obs import schema

from tests._ports import free_port as _free_port


@pytest.fixture(scope="module", autouse=True)
def _built():
    transport.build_native()


def _cfg(fault: FaultConfig | None = None, engine: bool = True, **tkw):
    tkw.setdefault("peer_timeout_sec", 10.0)
    return Config(
        transport=TransportConfig(**tkw),
        faults=fault or FaultConfig(),
        native_engine=engine,
    )


def _fresh_hub():
    """Flush stale native events from earlier tests, then start clean."""
    h = obs.hub()
    h.poll_native()
    h.recorder.clear()
    return h


def _wait(pred, timeout=30.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


def test_registry_counter_gauge_histogram():
    r = obs.Registry()
    c = r.counter("st_test_total", help="a counter")
    c.inc()
    c.inc(4)
    with pytest.raises(ValueError):
        c.inc(-1)
    g = r.gauge("st_test_gauge")
    g.set(7.5)
    h = r.histogram("st_test_seconds", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    snap = r.snapshot()
    assert snap["st_test_total"] == 5
    assert snap["st_test_gauge"] == 7.5
    hs = snap["st_test_seconds"]
    assert hs["count"] == 4
    assert hs["sum"] == pytest.approx(5.555)
    # cumulative bucket counts; the +Inf bucket is implicit == count
    assert hs["buckets"] == {0.01: 1, 0.1: 2, 1.0: 3}
    # same-name re-registration returns the same instrument; a kind
    # mismatch is an error, not a silent shadow
    assert r.counter("st_test_total") is c
    with pytest.raises(TypeError):
        r.gauge("st_test_total")


def test_registry_collector_and_prometheus_text():
    r = obs.Registry()
    r.counter("st_c_total", help="help text").inc(3)
    r.histogram("st_h_seconds", buckets=(0.1, 1.0)).observe(0.05)
    r.register_collector(lambda: {"st_pulled": 11})
    snap = r.snapshot()
    assert snap["st_pulled"] == 11
    text = r.prometheus_text()
    assert "# TYPE st_c_total counter" in text
    assert "st_c_total 3" in text
    assert "# HELP st_c_total help text" in text
    assert 'st_h_seconds_bucket{le="0.1"} 1' in text
    assert 'st_h_seconds_bucket{le="+Inf"} 1' in text
    assert "st_h_seconds_count 1" in text
    assert "st_pulled 11" in text
    # a collector that raises must not take the scrape down
    r.register_collector(lambda: 1 / 0)
    assert r.snapshot()["st_c_total"] == 3


def test_registry_jsonl_sink(tmp_path):
    r = obs.Registry()
    r.counter("st_s_total").inc(2)
    path = str(tmp_path / "metrics.jsonl")
    r.start_jsonl_sink(path, interval_sec=0.05)
    time.sleep(0.2)
    r.stop_jsonl_sink()
    lines = [json.loads(l) for l in open(path) if l.strip()]
    assert lines, "sink wrote nothing"
    assert all("t_ns" in l and l["metrics"]["st_s_total"] == 2 for l in lines)
    # timestamps are the shared monotonic timebase
    assert lines[-1]["t_ns"] <= time.monotonic_ns()


# ---------------------------------------------------------------------------
# schema
# ---------------------------------------------------------------------------


def test_schema_covers_real_metrics_shape():
    """Every key the REAL peer.metrics() serves must be documented in the
    schema (satellite: one documented namespace — there is no legacy
    alias surface left to hide a stray name in)."""
    port = _free_port()
    seed = jnp.zeros((4096,), jnp.float32)
    m = create_or_fetch("127.0.0.1", port, seed, _cfg())
    c = create_or_fetch("127.0.0.1", port, seed, _cfg())
    try:
        m.add(jnp.ones((4096,), jnp.float32))
        _wait(
            lambda: c.metrics()["st_frames_in_total"] > 0,
            msg="frames to flow",
        )
        full = m.metrics()
        assert full, "metrics() produced nothing"
        # every key is in the documented schema (per-link keys strip
        # their {link=} label first)
        for k in full:
            base = k.split("{", 1)[0]
            assert base in schema.SCHEMA, f"{k} not documented in SCHEMA"
        # the delivery taxonomy plus the engine aggregates and per-link
        # wire gauges all ride the one surface
        for must in (
            "st_frames_out_total",
            "st_msgs_out_total",
            "st_inflight_msgs",
            "st_tx_slot_acquires_total",
            "st_transport_tx_acquires_total",
            "st_retransmit_msgs_total",
            "st_ack_rtt_seconds_count",
        ):
            assert must in full, f"metrics() lost {must}"
        assert any(k.startswith("st_link_wire_msgs_out_total{") for k in full)
        # the registry's Prometheus rendering includes collector metrics
        if m._obs is not None:
            text = m._obs.registry.prometheus_text()
            assert "st_frames_out_total" in text
    finally:
        m.close()
        c.close()


def test_schema_link_key_and_legacy_surface_removed():
    """r13 satellite: the r08 nested alias surface is GONE — the schema
    module no longer carries an alias table, and asking metrics() for the
    legacy shape raises instead of silently serving stale names."""
    assert not hasattr(schema, "DEPRECATED_ALIASES")
    assert not hasattr(schema, "canonicalize")
    assert schema.link_key("st_link_send_queue", 3) == 'st_link_send_queue{link="3"}'


def test_schema_lint_every_emitted_st_name_is_documented():
    """r09 satellite, HARD GATE: grep-collect every ``st_*`` name emitted
    anywhere — quoted string literals across the Python package AND the
    native sources' string tables — and fail if one is missing from
    obs/schema.py. A new cluster metric cannot ship undocumented: adding
    an instrument/collector key without a SCHEMA row fails here, by name,
    with the file that emits it."""
    import pathlib
    import re

    repo = pathlib.Path(__file__).resolve().parent.parent
    pat = re.compile(r'["\'](st_[a-z0-9_]+)["\']')
    # Non-metric st_* literals, each with a reason. ABI symbol names appear
    # as ctypes attributes (not strings), so almost none are needed — keep
    # this list honest: every entry must still occur in the scan.
    allowed_non_metrics: dict[str, str] = {
        "st_trace": "Chrome trace_event category tag (trace_export.py)",
    }
    emitted: dict[str, set[str]] = {}
    sources = list((repo / "shared_tensor_tpu").rglob("*.py")) + [
        p
        for ext in ("*.c", "*.cpp", "*.h")
        for p in (repo / "native").glob(ext)
    ]
    assert sources, "scan found no sources"
    for path in sources:
        for name in pat.findall(path.read_text(errors="replace")):
            emitted.setdefault(name, set()).add(str(path.relative_to(repo)))
    assert emitted, "scan found no st_* literals (pattern rot?)"
    undocumented = {
        name: sorted(files)
        for name, files in emitted.items()
        if name not in schema.SCHEMA and name not in allowed_non_metrics
    }
    assert not undocumented, (
        f"st_* names emitted but missing from obs/schema.py SCHEMA: "
        f"{undocumented}"
    )
    stale_allow = set(allowed_non_metrics) - set(emitted)
    assert not stale_allow, f"allowlist entries no longer emitted: {stale_allow}"
    # sanity: the r09 cluster names are among what the scan found
    for must in ("st_staleness_seconds", "st_update_hops", "st_cluster_nodes"):
        assert must in emitted, f"scan missed {must}"


def test_legacy_metrics_shape_removed():
    """r13 satellite: the r08 nested alias shape was kept "for one
    release" and overstayed three — it is now REMOVED, loudly. The
    default call serves the canonical schema; explicitly asking for the
    legacy shape raises with a pointer to the schema, and the canonical/
    cluster surfaces behave identically to before."""
    port = _free_port()
    m = create_or_fetch("127.0.0.1", port, jnp.zeros((256,), jnp.float32), _cfg())
    try:
        m.add(jnp.ones((256,), jnp.float32))
        full = m.metrics()
        assert full == m.metrics(canonical=True)
        assert "st_frames_out_total" in full
        assert "frames_out" not in full  # the alias keys are truly gone
        assert "delivery" not in full
        with pytest.raises(ValueError, match="removed"):
            m.metrics(canonical=False)
        assert isinstance(m.metrics(cluster=True), dict)
    finally:
        m.close()


# ---------------------------------------------------------------------------
# native event ring
# ---------------------------------------------------------------------------


def test_native_ring_emit_drain_and_clock_agreement():
    lib = transport._load()
    # flush anything earlier tests left behind
    obs_events.drain_native(lib=lib)
    t_py = time.monotonic_ns()
    t_c = obs_events.native_now_ns(lib=lib)
    # same CLOCK_MONOTONIC timebase: the two reads are microseconds apart
    assert abs(t_c - t_py) < 250_000_000, (t_c, t_py)
    lib.st_obs_emit(42, 14, 3, 1234)
    lib.st_obs_emit(42, 10, 3, 2)
    evs = obs_events.drain_native(lib=lib)
    mine = [e for e in evs if e.node == 42]
    assert [e.name for e in mine] == ["dedup_discard", "retransmit"]
    assert mine[0].link == 3 and mine[0].arg == 1234
    assert all(e.tier == "c" for e in mine)
    # stamped between our two clock reads and now
    assert t_py - 1_000_000 <= mine[0].t_ns <= time.monotonic_ns()
    # drained means gone
    assert not [e for e in obs_events.drain_native(lib=lib) if e.node == 42]


def test_native_ring_codes_match_python_names():
    """The numeric codes are ABI shared between sttransport.cpp and
    obs/events.py — membership codes must equal transport.EventKind."""
    assert obs_events.CODE_NAMES[int(transport.EventKind.LINK_UP)] == "link_up"
    assert obs_events.CODE_NAMES[int(transport.EventKind.LINK_DOWN)] == "link_down"
    assert obs_events.CODE_NAMES[int(transport.EventKind.BECAME_MASTER)] == "became_master"
    assert obs_events.NAME_CODES["fault_drop"] == 20
    assert obs_events.EVENT_BYTES == 32


# ---------------------------------------------------------------------------
# merged timeline under chaos (flight recorder satellite)
# ---------------------------------------------------------------------------


def test_python_tier_chaos_timeline_accounts_every_injection():
    """Every injected drop/dup/sever appears in the merged timeline, with
    both-tier timestamps in sorted order (the satellite's exact bar). The
    chaotic joiner drops/dups on its first uplink and severs it at frame
    25; go-back-N + carry re-graft then reconverge exactly."""
    hub = _fresh_hub()
    port = _free_port()
    n = 512
    seed = jnp.zeros((n,), jnp.float32)
    fc = FaultConfig(
        enabled=True, seed=8, drop_pct=0.15, dup_pct=0.15,
        sever_after_frames=25, only_link=1,
    )
    m = create_or_fetch("127.0.0.1", port, seed, _cfg(engine=False))
    c = SharedTensorPeer(
        "127.0.0.1", port, seed, _cfg(fc, engine=False, ack_timeout_sec=0.5)
    )
    try:
        c.wait_ready(30.0)
        total = np.zeros(n, np.float64)
        rng = np.random.default_rng(0)
        for _ in range(40):
            d = rng.uniform(-0.5, 0.5, n).astype(np.float32)
            c.add(jnp.asarray(d))
            total += d
            time.sleep(0.01)
        plan = c._faults
        assert plan is not None
        # stop injecting, then wait for exact reconvergence (retransmission
        # + the sever's carry re-graft re-deliver everything)
        _wait(
            lambda: np.allclose(np.asarray(m.read()), total, atol=1e-4),
            timeout=60.0, msg="master to reconverge after chaos",
        )
        injected = {k: int(v) for k, v in plan.counts.items()}
        assert injected.get("severed", 0) >= 1, injected
        assert injected.get("dropped", 0) >= 1, injected
        assert injected.get("duplicated", 0) >= 1, injected
        hub.poll_native()
        counts = hub.recorder.counts
        # exact accounting: every injected event of the three classes is on
        # the timeline (the recorder's totals are not bounded by the window)
        assert counts["fault_drop"] == injected["dropped"], (counts, injected)
        assert counts["fault_dup"] == injected["duplicated"], (counts, injected)
        assert counts["fault_sever"] == injected["severed"], (counts, injected)
        timeline = hub.recorder.timeline()
        tiers = {e.tier for e in timeline}
        assert tiers == {"c", "py"}, tiers
        # merged order is time order across tiers
        ts = [e.t_ns for e in timeline]
        assert ts == sorted(ts)
        # the native LINK_UP precedes its Python-tier handling twin
        c_up = min(e.t_ns for e in timeline
                   if e.tier == "c" and e.name == "link_up")
        py_up = min(e.t_ns for e in timeline
                    if e.tier == "py" and e.name == "link_up")
        assert c_up < py_up
        # the sever's recovery left a trace too: the transport's link_down
        # and the re-graft's second link_up are on the same timeline
        assert counts["link_down"] >= 1
    finally:
        m.close()
        c.close()


def test_native_tier_chaos_events_reach_the_timeline(monkeypatch):
    """The NATIVE injector (ST_FAULT_PLAN, C sender loop) now reports every
    hit through the event ring: a drop schedule on the engine tier must
    surface fault_drop events — and the go-back-N retransmissions that
    repair them — in the merged timeline."""
    hub = _fresh_hub()
    port = _free_port()
    n = 4096
    seed = jnp.zeros((n,), jnp.float32)
    m = create_or_fetch(
        "127.0.0.1", port, seed, _cfg(ack_timeout_sec=0.5)
    )
    env = faults.to_env(FaultConfig(enabled=True, seed=9, drop_pct=0.3,
                                    only_link=1))
    monkeypatch.setenv("ST_FAULT_PLAN", env["ST_FAULT_PLAN"])
    c = SharedTensorPeer(
        "127.0.0.1", port, seed, _cfg(ack_timeout_sec=0.5)
    )
    monkeypatch.delenv("ST_FAULT_PLAN")
    try:
        c.wait_ready(30.0)
        total = np.zeros(n, np.float64)
        rng = np.random.default_rng(1)
        for _ in range(30):
            d = rng.uniform(-0.5, 0.5, n).astype(np.float32)
            c.add(jnp.asarray(d))
            total += d
            time.sleep(0.01)
        _wait(
            lambda: np.allclose(np.asarray(m.read()), total, atol=1e-4),
            timeout=60.0, msg="master to reconverge through native drops",
        )
        hub.poll_native()
        counts = hub.recorder.counts
        assert counts["fault_drop"] >= 1, dict(counts)
        assert counts["retransmit"] >= 1, dict(counts)
        timeline = hub.recorder.timeline()
        assert {e.tier for e in timeline} == {"c", "py"}
        drops = [e for e in timeline if e.name == "fault_drop"]
        assert all(e.tier == "c" and e.link == 1 for e in drops)
        ts = [e.t_ns for e in timeline]
        assert ts == sorted(ts)
    finally:
        m.close()
        c.close()


# ---------------------------------------------------------------------------
# postmortem dumps
# ---------------------------------------------------------------------------


def test_crash_point_dumps_postmortem(tmp_path, monkeypatch):
    """The default crash action dumps the flight recorder BEFORE os._exit
    — chaos deaths leave an explainable trace, not just exit code 17."""
    monkeypatch.setenv("ST_OBS_POSTMORTEM_DIR", str(tmp_path))
    exits = []
    monkeypatch.setattr(faults.os, "_exit", lambda code: exits.append(code))
    hub = _fresh_hub()
    hub.emit("link_up", node=1, link=1)
    reg = obs.Registry()
    reg.counter("st_test_total").inc(3)
    hub.register_registry("test-peer", reg)
    try:
        plan = faults.FaultPlan(
            FaultConfig(enabled=True, crash_point="mid-burst")
        )
        plan.point("mid-burst")
        assert exits == [faults.CRASH_EXIT_CODE]
        dumps = list(tmp_path.glob("st_postmortem_*crash_point*.json"))
        assert len(dumps) == 1, dumps
        doc = json.loads(dumps[0].read_text())
        assert doc["reason"] == "crash_point:mid-burst"
        assert doc["registries"]["test-peer"]["st_test_total"] == 3
        names = [e["name"] for e in doc["timeline"]]
        assert "link_up" in names and "crash_point" in names
        assert doc["event_counts"]["crash_point"] == 1
        # timeline entries carry the merged-clock timestamps, sorted
        ts = [e["t_ns"] for e in doc["timeline"]]
        assert ts == sorted(ts)
    finally:
        hub.unregister_registry("test-peer")


def test_recv_thread_exception_dumps_postmortem(tmp_path, monkeypatch):
    """An unhandled recv-thread exception (the wedged-peer class) dumps a
    postmortem and the loop restarts — the peer keeps working after."""
    monkeypatch.setenv("ST_OBS_POSTMORTEM_DIR", str(tmp_path))
    hub = _fresh_hub()
    port = _free_port()
    seed = jnp.zeros((256,), jnp.float32)
    m = create_or_fetch("127.0.0.1", port, seed, _cfg())
    try:
        boom = {"armed": True}
        orig = m._handle_events

        def exploding():
            if boom.pop("armed", False):
                raise RuntimeError("injected recv-thread failure")
            return orig()

        monkeypatch.setattr(m, "_handle_events", exploding)
        _wait(
            lambda: list(tmp_path.glob("st_postmortem_*recv_thread*")),
            timeout=15.0, msg="postmortem dump",
        )
        doc = json.loads(
            list(tmp_path.glob("st_postmortem_*recv_thread*"))[0].read_text()
        )
        assert doc["reason"] == "recv_thread_exception"
        # the guarded restart kept the peer alive
        assert m._recv_thread.is_alive()
    finally:
        m.close()


def test_goback_teardown_dumps_postmortem(tmp_path, monkeypatch):
    """A Python-tier black-hole teardown (zero ACK progress through every
    retransmission round) leaves a postmortem + timeline events."""
    monkeypatch.setenv("ST_OBS_POSTMORTEM_DIR", str(tmp_path))
    hub = _fresh_hub()
    port = _free_port()
    n = 256
    seed = jnp.zeros((n,), jnp.float32)
    # stall EVERY frame from the start: the ledger strands, the delivery
    # timer retransmits (stalled too), and the retry limit tears down
    fc = FaultConfig(enabled=True, stall_after_frames=0, only_link=1)
    m = create_or_fetch("127.0.0.1", port, seed, _cfg(engine=False))
    c = SharedTensorPeer(
        "127.0.0.1", port, seed,
        _cfg(fc, engine=False, ack_timeout_sec=0.2, ack_retry_limit=2),
    )
    try:
        c.wait_ready(30.0)
        c.add(jnp.ones((n,), jnp.float32))
        _wait(
            lambda: list(tmp_path.glob("st_postmortem_*goback*")),
            timeout=30.0, msg="teardown postmortem",
        )
        assert hub.recorder.counts["blackhole_teardown"] >= 1
        assert hub.recorder.counts["fault_stall"] >= 1
    finally:
        m.close()
        c.close()


# ---------------------------------------------------------------------------
# kill switch
# ---------------------------------------------------------------------------


def test_obs_disabled_is_inert():
    was = obs.obs_enabled()
    obs.set_enabled(False)
    try:
        hub = obs.hub()
        hub.recorder.clear()
        hub.emit("link_up", node=1)
        assert not hub.recorder.counts
        assert hub.dump("disabled-test") is None
        port = _free_port()
        m = create_or_fetch(
            "127.0.0.1", port, jnp.zeros((64,), jnp.float32), _cfg()
        )
        try:
            assert m._obs is None  # peer pays one None-check per site
            # the canonical metrics surface is independent of obs (the
            # collector serves the schema without a registry)
            assert "st_frames_out_total" in m.metrics()
        finally:
            m.close()
        # the native ring's emission flag was flipped too
        lib = transport._load()
        obs_events.drain_native(lib=lib)
        lib.st_obs_emit(99, 14, 1, 1)
        assert not [e for e in obs_events.drain_native(lib=lib) if e.node == 99]
    finally:
        obs.set_enabled(was)


def test_peer_obs_config_disabled():
    port = _free_port()
    cfg = Config(
        transport=TransportConfig(peer_timeout_sec=10.0),
        obs=ObsConfig(enabled=False),
    )
    m = create_or_fetch("127.0.0.1", port, jnp.zeros((64,), jnp.float32), cfg)
    try:
        assert m._obs is None
        # canonical view still works without a registry (pure schema map)
        assert "st_frames_out_total" in m.metrics(canonical=True)
    finally:
        m.close()


def test_jsonl_sink_config_wires_through(tmp_path):
    path = str(tmp_path / "peer_metrics.jsonl")
    port = _free_port()
    cfg = Config(
        transport=TransportConfig(peer_timeout_sec=10.0),
        obs=ObsConfig(jsonl_path=path, jsonl_interval_sec=0.05),
    )
    m = create_or_fetch("127.0.0.1", port, jnp.zeros((64,), jnp.float32), cfg)
    try:
        _wait(lambda: os.path.exists(path) and os.path.getsize(path) > 0,
              timeout=10.0, msg="jsonl sink output")
    finally:
        m.close()
    lines = [json.loads(l) for l in open(path) if l.strip()]
    assert lines and "st_frames_out_total" in lines[-1]["metrics"]


def test_corrupt_scale_counter():
    before = wire.corrupt_scales_zeroed()
    from shared_tensor_tpu.ops.table import make_spec

    spec = make_spec(np.zeros(64, np.float32))
    w = spec.total // 32
    frame = (
        b"\x00" + b"\x01\x00\x00\x00"
        + np.full(spec.num_leaves, np.inf, "<f4").tobytes()
        + b"\x00" * (4 * w)
    )
    f = wire.decode_frame(frame, spec)
    assert float(np.asarray(f.scales)[0]) == 0.0
    assert wire.corrupt_scales_zeroed() == before + 1
