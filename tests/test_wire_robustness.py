"""Wire-robustness: garbage on a live link must not kill or poison a node.

The reference exits the whole process on any I/O hiccup (quirk Q8,
src/sharedtensor.c:61-63) and has no guard against a corrupt/hostile frame
poisoning every replica through the flood (quirk Q9: one NaN makes all
values NaN; quirk Q11: anyone who can connect can inject). Here the engine
drops undecodable messages (comm/peer.py receive loop) and the decoder
zeroes non-finite scales at the trust boundary (comm/wire.py), so the node
survives, stays finite, and keeps serving real peers.
"""

import struct
import time

import jax.numpy as jnp
import numpy as np
import pytest

from shared_tensor_tpu.comm import wire
from shared_tensor_tpu.comm.peer import SEND_WINDOW, create_or_fetch
from shared_tensor_tpu.comm.transport import TransportNode, build_native
from shared_tensor_tpu.config import Config, TransportConfig
from shared_tensor_tpu.ops.table import TableFrame, make_spec
from tests._ports import free_port as _free_port


@pytest.fixture(scope="module", autouse=True)
def _built():
    build_native()


CFG = Config(transport=TransportConfig(peer_timeout_sec=10.0))


def _wait(cond, timeout=15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


def test_garbage_injection_survival_and_convergence():
    port = _free_port()
    tpl = {"w": jnp.ones((40, 64), jnp.float32), "b": jnp.zeros((64,), jnp.float32)}
    spec = make_spec(tpl)
    fb = wire.frame_wire_bytes(spec)
    with create_or_fetch("127.0.0.1", port, tpl, CFG) as master:
        # A bare transport node joins the tree but speaks garbage instead of
        # the SYNC handshake.
        with TransportNode(
            "127.0.0.1", port, CFG.transport, frame_bytes=fb
        ) as evil:
            assert _wait(lambda: len(evil.links) == 1)
            link = evil.links[0]
            k, w = spec.num_leaves, spec.total // 32
            rng = np.random.default_rng(7)  # deterministic noise
            nan_scales = struct.pack("<" + "f" * k, *([float("nan")] * k))
            noise_words = rng.integers(0, 256, 4 * w, dtype=np.uint8).tobytes()
            # noise first byte pinned off SYNC: a random SYNC would draw a
            # legitimate REJECT + link drop, which is not what this test pins
            seq = lambda n: struct.pack("<I", n)  # wire tx_seq (in order)
            payloads = [
                b"\xff" + b"\x00" * 16,  # unknown message kind
                bytes([wire.DATA]) + b"\x01\x02\x03",  # truncated DATA
                bytes([wire.ACK]),  # ACK with missing body
                b"\xfe" + rng.integers(0, 256, 511, dtype=np.uint8).tobytes(),
                # well-formed, in-order DATA frame carrying NaN scales +
                # random bits: must decode to a no-op, not poison the
                # replica (Q9/Q11)
                bytes([wire.DATA]) + seq(1) + nan_scales + noise_words,
                bytes([wire.CHUNK]) + struct.pack("<Q", 1 << 60) + b"\xee",
                # BURST with a count that does not match the payload length
                bytes([wire.BURST]) + seq(2) + b"\x09" + b"\x00" * 40,
                # BURST of 1 frame with NaN scales: zeroed, applied as no-op
                # (seq 2: the mis-sized BURST above must NOT have consumed
                # its seq — undecodable messages await retransmission)
                bytes([wire.BURST]) + seq(2) + b"\x01" + nan_scales + noise_words,
            ]
            for p in payloads:
                assert evil.send(link, p, timeout=2.0)
            time.sleep(1.0)  # let the engine chew through all of it
        # The master survived, its replica is finite and unchanged.
        got = master.read()
        assert np.isfinite(np.asarray(got["w"])).all()
        np.testing.assert_array_equal(np.asarray(got["w"]), np.ones((40, 64)))
        assert master.ready

        # And it still serves a REAL joiner end-to-end afterward.
        with create_or_fetch("127.0.0.1", port, tpl, CFG) as joiner:
            master.add({"w": jnp.full((40, 64), 0.5, jnp.float32),
                        "b": jnp.zeros((64,), jnp.float32)})
            def converged():
                jw = np.asarray(joiner.read()["w"])
                return np.abs(jw - 1.5).max() < 1e-5
            assert _wait(converged, timeout=30.0)


def test_compat_nonfinite_scale_is_keepalive():
    """Wire-compat tier: a reference-format frame with a non-finite scale is
    treated as an idle keepalive instead of applied (the C reference would
    NaN its replica and flood that to the whole tree, quirk Q9)."""
    tpl = jnp.zeros((64,), jnp.float32)
    spec = make_spec(tpl)
    payload = struct.pack("<f", float("inf")) + b"\xaa" * (
        wire.compat_frame_bytes(spec.total_n) - 4
    )
    assert wire.decode_compat_frame(payload, spec) is None
    payload = struct.pack("<f", float("nan")) + b"\xaa" * (
        wire.compat_frame_bytes(spec.total_n) - 4
    )
    assert wire.decode_compat_frame(payload, spec) is None


def test_native_nonfinite_scales_zeroed():
    """Native tier: decode_frame zeroes exactly the non-finite scales and
    keeps finite ones (every finite f32 is inside the protocol's legal scale
    domain — residuals clamp at +/-SAT, so scales range up to 2^127)."""
    tpl = {"a": jnp.zeros((8, 128), jnp.float32), "b": jnp.zeros((128,), jnp.float32)}
    spec = make_spec(tpl)
    k, w = spec.num_leaves, spec.total // 32
    hdr = bytes([wire.DATA]) + struct.pack("<I", 1)  # kind + wire tx_seq
    scales = struct.pack("<ff", float("nan"), 0.25)
    payload = hdr + scales + b"\x00" * (4 * w)
    frame = wire.decode_frame(payload, spec)
    np.testing.assert_array_equal(
        np.asarray(frame.scales), np.asarray([0.0, 0.25], np.float32)
    )
    scales = struct.pack("<ff", 2.0**120, 1.5)
    frame = wire.decode_frame(hdr + scales + b"\x00" * (4 * w), spec)
    np.testing.assert_array_equal(
        np.asarray(frame.scales), np.asarray([2.0**120, 1.5], np.float32)
    )


def _rand_frames(spec, rng, k):
    return [
        TableFrame(
            rng.uniform(0.1, 2.0, spec.num_leaves).astype(np.float32),
            rng.integers(0, 1 << 32, spec.total // 32, dtype=np.uint64).astype(
                np.uint32
            ),
        )
        for _ in range(k)
    ]


def test_encode_into_matches_bytes_encoders():
    """The r07 pooled encoders (encode_frame_into / encode_burst_into) must
    produce byte-identical wire messages to the legacy bytes encoders —
    they fill the same layout, just into a recycled slot."""
    spec = make_spec({"a": jnp.zeros((40, 32), jnp.float32),
                      "b": jnp.zeros((64,), jnp.float32)})
    rng = np.random.default_rng(3)
    pool = wire.FramePool(wire.frame_wire_bytes(spec))
    frames = _rand_frames(spec, rng, 5)

    slot = pool.acquire()
    n = wire.encode_frame_into(frames[0], 7, slot)
    assert bytes(slot[:n]) == wire.encode_frame(frames[0], 7)
    pool.release(slot)

    slot = pool.acquire()
    n = wire.encode_burst_into(frames, spec, 9, slot)
    assert bytes(slot[:n]) == wire.encode_burst(frames, spec, 9)
    # and decode (pooled scratch) round-trips it
    scratch = wire.DecodeScratch(spec)
    out = wire.decode_burst(bytes(slot[:n]), spec, scratch)
    for a, b in zip(out, frames):
        np.testing.assert_array_equal(np.asarray(a.scales), b.scales)
        np.testing.assert_array_equal(np.asarray(a.words), b.words)
    # recycled arrays are REUSED by the next decode (the satellite's point)
    ids = {id(f.scales) for f in out} | {id(f.words) for f in out}
    scratch.recycle()
    out2 = wire.decode_burst(bytes(slot[:n]), spec, scratch)
    ids2 = {id(f.scales) for f in out2} | {id(f.words) for f in out2}
    assert ids & ids2, "scratch pool did not reuse decode arrays"
    # cap enforcement unchanged
    with pytest.raises(ValueError, match="allows 1"):
        wire.encode_burst_into(
            _rand_frames(spec, rng, wire.burst_frames_cap(spec) + 1),
            spec, 1, pool.acquire(),
        )


def test_frame_pool_acquire_release_reuses_slots():
    pool = wire.FramePool(1024, keep=2)
    a = pool.acquire()
    b = pool.acquire()
    assert pool.stats()["tx_slot_alloc_events"] == 2
    pool.release(a)
    pool.release(b)
    c = pool.acquire()
    d = pool.acquire()
    s = pool.stats()
    assert s["tx_slot_acquires"] == 4
    assert s["tx_slot_alloc_events"] == 2  # both reused
    assert len(c) == len(d) == 1024


def test_send_window_saturation_on_burst_path():
    """SEND_WINDOW saturation on the BURST path (r07 satellite): a link
    whose peer acknowledges nothing must (a) block the producer AT the
    window — the unacked ledger never exceeds SEND_WINDOW messages, (b)
    not grow the frame pool past the window's worth of slots (the ledger
    entry IS its pool slot), and (c) retransmit BYTE-IDENTICAL messages
    (go-back-N resends the ledgered slot bytes verbatim, same wire seqs).

    The black hole is a node.send wrapper that records DATA/BURST payloads
    and claims success — the sender believes it delivered, so its ledger
    fills and the delivery timer starts retransmitting the head."""
    port = _free_port()
    seed = jnp.zeros((2048,), jnp.float32)
    cfg = Config(
        transport=TransportConfig(
            peer_timeout_sec=10.0, ack_timeout_sec=0.3, ack_retry_limit=100,
        ),
        native_engine=False,  # the Python wire tier owns this ledger
        frame_burst=4,
    )
    m = create_or_fetch("127.0.0.1", port, seed, cfg)
    j = create_or_fetch("127.0.0.1", port, seed, cfg)
    try:
        assert j._engine is None and j._burst > 1
        up = j._uplink
        assert up is not None
        recorded: dict[int, list[bytes]] = {}
        real_send = j.node.send

        def blackhole(link, payload, timeout=0.1):
            b = bytes(payload)
            if link == up and b and b[0] in (wire.DATA, wire.BURST):
                recorded.setdefault(wire.data_seq(b), []).append(b)
                return True  # swallowed; ACK/handshake pass through below
            return real_send(link, payload, timeout=timeout)

        j.node.send = blackhole
        rng = np.random.default_rng(11)
        deadline = time.time() + 60.0
        peak = 0
        retx_seen = False
        while time.time() < deadline and not (
            peak >= SEND_WINDOW and retx_seen
        ):
            # keep producing residual mass so the window genuinely saturates
            j.add(jnp.asarray(rng.normal(size=2048).astype(np.float32)))
            with j._ack_mu:
                depth = len(j._unacked.get(up, ()))
            assert depth <= SEND_WINDOW, f"ledger grew past the window: {depth}"
            peak = max(peak, depth)
            retx_seen = any(len(v) >= 2 for v in recorded.values())
            time.sleep(0.02)
        assert peak >= SEND_WINDOW, f"window never saturated (peak {peak})"
        assert retx_seen, "delivery timer never retransmitted"
        # (c) every retransmission is byte-identical to the original
        for seq, blobs in recorded.items():
            for b in blobs[1:]:
                assert b == blobs[0], f"retransmit of seq {seq} differs"
        # the BURST path was actually exercised
        assert any(
            blobs[0][0] == wire.BURST for blobs in recorded.values()
        ), "no BURST message crossed the wire boundary"
        # (b) pool bounded by the window: every live slot is a ledger entry
        stats = j._tx_pool.stats()
        assert stats["tx_slot_alloc_events"] <= SEND_WINDOW + 2, stats
        allocs_before = stats["tx_slot_alloc_events"]
        for _ in range(20):  # keep pushing against the saturated window
            j.add(jnp.asarray(rng.normal(size=2048).astype(np.float32)))
            time.sleep(0.01)
        assert (
            j._tx_pool.stats()["tx_slot_alloc_events"] == allocs_before
        ), "pool grew while the window was saturated"
    finally:
        j.node.send = real_send
        j.close()
        m.close()


def test_recv_bounds_cover_max_traced_burst():
    """Every receive-buffer bound (frame_wire_bytes, which sizes the
    transport recv buffer AND the engine's recv_cap) must cover a
    MAX-SIZE v2 (traced) burst at every table size. 13 bytes short — the
    r09 review catch — means a full traced burst is silently truncated at
    the transport, rejected as undecodable without consuming its seq, and
    retransmitted byte-identical until go-back-N black-holes the link.
    Latent in benches because full-cap bursts are rare (halvings usually
    go idle long before the cap); explicit here so it stays fixed."""
    for n in (64, 2048, 1 << 17, 1 << 20, 1 << 24):
        spec = make_spec(jnp.zeros((n,), jnp.float32))
        per = wire.frame_payload_bytes(spec)
        cap = wire.burst_frames_cap(spec)
        worst = wire.BURST_HDR_T + cap * per
        assert wire.burst_wire_bytes(spec) >= worst, n
        assert wire.frame_wire_bytes(spec) >= worst, n
        assert wire.frame_wire_bytes(spec) >= wire.DATA_HDR_T + per, n
        # and the burst itself stays inside the protocol budget
        assert worst <= wire.BURST_HDR_T + wire.BURST_MAX_BYTES, n


def test_apply_saturates_no_absorbing_inf():
    """A max-scale frame applied to values already at the +/-SAT clamp must
    saturate, not overflow: inf would be an absorbing state (inf - inf = NaN
    floods tree-wide — quirk Q9's receive-path arm). All codec tiers clamp
    the apply result (ops/codec.SAT)."""
    from shared_tensor_tpu.core import SharedTensor
    from shared_tensor_tpu.ops.codec import SAT

    tpl = jnp.full((256,), SAT, jnp.float32)
    st = SharedTensor(tpl, seed_values=True)
    spec = st.spec
    w = spec.total // 32
    # scale 2^127 (the largest a legal residual can produce), all bits clear
    # => +scale everywhere
    payload = (
        bytes([wire.DATA])
        + struct.pack("<I", 1)  # wire tx_seq
        + struct.pack("<f", 2.0**127)
        + b"\x00" * (4 * w)
    )
    st.receive_frame(1, wire.decode_frame(payload, spec))
    got = np.asarray(st.snapshot_flat())
    assert np.isfinite(got).all()
    assert got.max() <= SAT
