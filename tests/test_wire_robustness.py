"""Wire-robustness: garbage on a live link must not kill or poison a node.

The reference exits the whole process on any I/O hiccup (quirk Q8,
src/sharedtensor.c:61-63) and has no guard against a corrupt/hostile frame
poisoning every replica through the flood (quirk Q9: one NaN makes all
values NaN; quirk Q11: anyone who can connect can inject). Here the engine
drops undecodable messages (comm/peer.py receive loop) and the decoder
zeroes non-finite scales at the trust boundary (comm/wire.py), so the node
survives, stays finite, and keeps serving real peers.
"""

import struct
import time

import jax.numpy as jnp
import numpy as np
import pytest

from shared_tensor_tpu.comm import wire
from shared_tensor_tpu.comm.peer import create_or_fetch
from shared_tensor_tpu.comm.transport import TransportNode, build_native
from shared_tensor_tpu.config import Config, TransportConfig
from shared_tensor_tpu.ops.table import make_spec
from tests._ports import free_port as _free_port


@pytest.fixture(scope="module", autouse=True)
def _built():
    build_native()


CFG = Config(transport=TransportConfig(peer_timeout_sec=10.0))


def _wait(cond, timeout=15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


def test_garbage_injection_survival_and_convergence():
    port = _free_port()
    tpl = {"w": jnp.ones((40, 64), jnp.float32), "b": jnp.zeros((64,), jnp.float32)}
    spec = make_spec(tpl)
    fb = wire.frame_wire_bytes(spec)
    with create_or_fetch("127.0.0.1", port, tpl, CFG) as master:
        # A bare transport node joins the tree but speaks garbage instead of
        # the SYNC handshake.
        with TransportNode(
            "127.0.0.1", port, CFG.transport, frame_bytes=fb
        ) as evil:
            assert _wait(lambda: len(evil.links) == 1)
            link = evil.links[0]
            k, w = spec.num_leaves, spec.total // 32
            rng = np.random.default_rng(7)  # deterministic noise
            nan_scales = struct.pack("<" + "f" * k, *([float("nan")] * k))
            noise_words = rng.integers(0, 256, 4 * w, dtype=np.uint8).tobytes()
            # noise first byte pinned off SYNC: a random SYNC would draw a
            # legitimate REJECT + link drop, which is not what this test pins
            seq = lambda n: struct.pack("<I", n)  # wire tx_seq (in order)
            payloads = [
                b"\xff" + b"\x00" * 16,  # unknown message kind
                bytes([wire.DATA]) + b"\x01\x02\x03",  # truncated DATA
                bytes([wire.ACK]),  # ACK with missing body
                b"\xfe" + rng.integers(0, 256, 511, dtype=np.uint8).tobytes(),
                # well-formed, in-order DATA frame carrying NaN scales +
                # random bits: must decode to a no-op, not poison the
                # replica (Q9/Q11)
                bytes([wire.DATA]) + seq(1) + nan_scales + noise_words,
                bytes([wire.CHUNK]) + struct.pack("<Q", 1 << 60) + b"\xee",
                # BURST with a count that does not match the payload length
                bytes([wire.BURST]) + seq(2) + b"\x09" + b"\x00" * 40,
                # BURST of 1 frame with NaN scales: zeroed, applied as no-op
                # (seq 2: the mis-sized BURST above must NOT have consumed
                # its seq — undecodable messages await retransmission)
                bytes([wire.BURST]) + seq(2) + b"\x01" + nan_scales + noise_words,
            ]
            for p in payloads:
                assert evil.send(link, p, timeout=2.0)
            time.sleep(1.0)  # let the engine chew through all of it
        # The master survived, its replica is finite and unchanged.
        got = master.read()
        assert np.isfinite(np.asarray(got["w"])).all()
        np.testing.assert_array_equal(np.asarray(got["w"]), np.ones((40, 64)))
        assert master.ready

        # And it still serves a REAL joiner end-to-end afterward.
        with create_or_fetch("127.0.0.1", port, tpl, CFG) as joiner:
            master.add({"w": jnp.full((40, 64), 0.5, jnp.float32),
                        "b": jnp.zeros((64,), jnp.float32)})
            def converged():
                jw = np.asarray(joiner.read()["w"])
                return np.abs(jw - 1.5).max() < 1e-5
            assert _wait(converged, timeout=30.0)


def test_compat_nonfinite_scale_is_keepalive():
    """Wire-compat tier: a reference-format frame with a non-finite scale is
    treated as an idle keepalive instead of applied (the C reference would
    NaN its replica and flood that to the whole tree, quirk Q9)."""
    tpl = jnp.zeros((64,), jnp.float32)
    spec = make_spec(tpl)
    payload = struct.pack("<f", float("inf")) + b"\xaa" * (
        wire.compat_frame_bytes(spec.total_n) - 4
    )
    assert wire.decode_compat_frame(payload, spec) is None
    payload = struct.pack("<f", float("nan")) + b"\xaa" * (
        wire.compat_frame_bytes(spec.total_n) - 4
    )
    assert wire.decode_compat_frame(payload, spec) is None


def test_native_nonfinite_scales_zeroed():
    """Native tier: decode_frame zeroes exactly the non-finite scales and
    keeps finite ones (every finite f32 is inside the protocol's legal scale
    domain — residuals clamp at +/-SAT, so scales range up to 2^127)."""
    tpl = {"a": jnp.zeros((8, 128), jnp.float32), "b": jnp.zeros((128,), jnp.float32)}
    spec = make_spec(tpl)
    k, w = spec.num_leaves, spec.total // 32
    hdr = bytes([wire.DATA]) + struct.pack("<I", 1)  # kind + wire tx_seq
    scales = struct.pack("<ff", float("nan"), 0.25)
    payload = hdr + scales + b"\x00" * (4 * w)
    frame = wire.decode_frame(payload, spec)
    np.testing.assert_array_equal(
        np.asarray(frame.scales), np.asarray([0.0, 0.25], np.float32)
    )
    scales = struct.pack("<ff", 2.0**120, 1.5)
    frame = wire.decode_frame(hdr + scales + b"\x00" * (4 * w), spec)
    np.testing.assert_array_equal(
        np.asarray(frame.scales), np.asarray([2.0**120, 1.5], np.float32)
    )


def test_apply_saturates_no_absorbing_inf():
    """A max-scale frame applied to values already at the +/-SAT clamp must
    saturate, not overflow: inf would be an absorbing state (inf - inf = NaN
    floods tree-wide — quirk Q9's receive-path arm). All codec tiers clamp
    the apply result (ops/codec.SAT)."""
    from shared_tensor_tpu.core import SharedTensor
    from shared_tensor_tpu.ops.codec import SAT

    tpl = jnp.full((256,), SAT, jnp.float32)
    st = SharedTensor(tpl, seed_values=True)
    spec = st.spec
    w = spec.total // 32
    # scale 2^127 (the largest a legal residual can produce), all bits clear
    # => +scale everywhere
    payload = (
        bytes([wire.DATA])
        + struct.pack("<I", 1)  # wire tx_seq
        + struct.pack("<f", 2.0**127)
        + b"\x00" * (4 * w)
    )
    st.receive_frame(1, wire.decode_frame(payload, spec))
    got = np.asarray(st.snapshot_flat())
    assert np.isfinite(got).all()
    assert got.max() <= SAT
