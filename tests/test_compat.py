"""Reference-named shim tests: the example.lua program shape, verbatim names
(BASELINE config 1 through the compat surface)."""

import jax.numpy as jnp
import numpy as np
import pytest

from shared_tensor_tpu import compat
from tests.test_peer import _free_port  # reuse the loopback port helper


def test_example_lua_program_shape():
    x = jnp.arange(1.0, 5.0, dtype=jnp.float32)  # torch.range(1,4):float()
    port = _free_port()
    with compat.createOrFetch("127.0.0.1", port, x) as a:
        got = a.copyToTensor()
        np.testing.assert_allclose(np.asarray(got), [1, 2, 3, 4])
        a.addFromTensor(jnp.ones_like(x))
        np.testing.assert_allclose(np.asarray(a.copyToTensor()), [2, 3, 4, 5])


def test_reference_shim_tree_serves_a_read_only_subscriber():
    """r10 interop satellite (compat surface): a tree built through the
    reference-named shim (createOrFetch / addFromTensor — a writer that
    knows nothing about the serving tier) transparently serves a read-only
    subscriber: the subscriber advertises itself through the same SYNC the
    shim's peer already speaks, gets the seed + every subsequent add, and
    the shim peer keeps its reference semantics untouched."""
    import time

    from shared_tensor_tpu import serve

    x = jnp.arange(1.0, 65.0, dtype=jnp.float32)
    port = _free_port()
    with compat.createOrFetch("127.0.0.1", port, x) as a:
        with serve.subscribe(
            "127.0.0.1", port, jnp.zeros_like(x), timeout=30.0
        ) as sub:
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                try:
                    if np.allclose(
                        np.asarray(sub.read(max_staleness=10.0)),
                        np.asarray(x), atol=1e-4,
                    ):
                        break
                except serve.StalenessError:
                    pass
                time.sleep(0.05)
            np.testing.assert_allclose(
                np.asarray(sub.read(max_staleness=10.0)), np.asarray(x),
                atol=1e-4,
            )
            a.addFromTensor(jnp.ones_like(x))
            sub.wait_fresh(serve.epoch(), timeout=20.0)
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                try:
                    if np.allclose(
                        np.asarray(sub.read(max_staleness=10.0)),
                        np.asarray(x) + 1, atol=1e-4,
                    ):
                        break
                except serve.StalenessError:
                    pass
                time.sleep(0.05)
            np.testing.assert_allclose(
                np.asarray(sub.read(max_staleness=10.0)),
                np.asarray(x) + 1, atol=1e-4,
            )
            # the shim peer's own view is untouched by the subscriber
            np.testing.assert_allclose(
                np.asarray(a.copyToTensor()), np.asarray(x) + 1, atol=1e-6
            )


def test_two_process_semantics_in_one_process():
    """Master + joiner through the compat names; joiner receives state and
    both see each other's adds (example.lua's multi-terminal story)."""
    x = jnp.arange(1.0, 5.0, dtype=jnp.float32)
    port = _free_port()
    with compat.createOrFetch("127.0.0.1", port, x) as master:
        with compat.createOrFetch("127.0.0.1", port, jnp.zeros_like(x)) as joiner:
            # joiner got the master's state through the codec stream
            deadline = 50
            for _ in range(deadline):
                if np.allclose(np.asarray(joiner.copyToTensor()), [1, 2, 3, 4], atol=1e-6):
                    break
                import time

                time.sleep(0.1)
            np.testing.assert_allclose(
                np.asarray(joiner.copyToTensor()), [1, 2, 3, 4], atol=1e-6
            )
            joiner.addFromTensor(jnp.ones_like(x))
            import time

            for _ in range(deadline):
                if np.allclose(np.asarray(master.copyToTensor()), [2, 3, 4, 5], atol=1e-6):
                    break
                time.sleep(0.1)
            np.testing.assert_allclose(
                np.asarray(master.copyToTensor()), [2, 3, 4, 5], atol=1e-6
            )
