"""Collision-free loopback port allocation for peer/transport tests.

The old helper bound port 0, read the assigned port, closed the socket, and
handed the number out — a TOCTOU: under parallel load another test (or the
OS's own ephemeral allocation) could grab the port before the node re-bound
it, and `st_node_create`'s joiner would then walk a tree it was never meant
to find (round-2 verdict Weak #4: flaky rendezvous under load).

This allocator instead hands each port out AT MOST ONCE per process, from a
pid-offset range outside Linux's default ephemeral span (32768+), probing
availability at allocation time. The remaining cross-process race window is
the probe-to-bind gap against non-test processes only, and the native layer
now retries the master-bind/join race besides.
"""

import itertools
import os
import socket

_counter = itertools.count(20000 + (os.getpid() * 61) % 9000)


def free_port() -> int:
    for port in _counter:
        if port > 32000:  # stay below the ephemeral range
            raise RuntimeError("test port range exhausted")
        s = socket.socket()
        try:
            s.bind(("127.0.0.1", port))
        except OSError:
            continue
        finally:
            s.close()
        return port
    raise AssertionError("unreachable")
