"""Deterministic fault-injection tests (ISSUE r06 tentpole evidence).

The recovery machinery this framework claims over the reference —
ledger rollback, re-graft carry, bounded-time joins, per-link
quarantine — is exercised here under *deterministic, seeded* chaos from
`comm/faults.py`, on BOTH data planes:

- the Python wire tier (``Config(native_engine=False)``): the
  :class:`FaultPlan` consulted in ``peer._send_blocking``;
- the native tier (engine + C transport): the identical schedule via the
  ``ST_FAULT_PLAN`` / ``ST_FAULT_CRASH`` env hook table, parsed per
  ``st_node_create``.

Every convergence assertion doubles as a no-lost-state proof: after the
injected chaos and its recovery, each replica must equal seed + the exact
sum of every add — the delivery contract the reference's ``exit(-1)``
cannot even state."""

import logging
import socket
import subprocess
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shared_tensor_tpu.comm import faults
from shared_tensor_tpu.comm.faults import CRASH_EXIT_CODE, FaultPlan
from shared_tensor_tpu.comm.peer import SharedTensorPeer, create_or_fetch
from shared_tensor_tpu.comm.transport import build_native
from shared_tensor_tpu.config import Config, FaultConfig, TransportConfig

from tests._ports import free_port as _free_port


@pytest.fixture(scope="module", autouse=True)
def _built():
    build_native()


def _cfg(fault: FaultConfig | None = None, engine: bool = True, **tkw):
    tkw.setdefault("peer_timeout_sec", 10.0)
    return Config(
        transport=TransportConfig(**tkw),
        faults=fault or FaultConfig(),
        native_engine=engine,
    )


def _wait_converged(peers, expect, tol=1e-6, timeout=90.0):
    """Same bar as test_peer: convergence is exact in finitely many frames;
    the window is sized for a loaded box, not for the convergence itself."""
    expect_leaves = jax.tree.leaves(expect)
    deadline = time.time() + timeout
    while time.time() < deadline:
        ok = True
        for p in peers:
            got = jax.tree.leaves(p.read())
            if not all(
                np.allclose(g, e, rtol=1e-4, atol=tol)
                for g, e in zip(got, expect_leaves)
            ):
                ok = False
                break
        if ok:
            return
        time.sleep(0.05)
    for i, p in enumerate(peers):
        got = jax.tree.leaves(p.read())
        for g, e in zip(got, expect_leaves):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(e), rtol=1e-4, atol=tol,
                err_msg=f"peer {i} did not converge",
            )


# ---------------------------------------------------------------------------
# FaultPlan unit behavior (no network)
# ---------------------------------------------------------------------------


def test_fault_plan_is_deterministic():
    """The whole schedule is a pure function of (seed, per-link frame
    sequence): two plans over the same traffic make identical decisions,
    a different seed makes different ones."""
    cfg = FaultConfig(
        enabled=True, seed=42, drop_pct=0.3, dup_pct=0.2, corrupt_pct=0.2,
    )
    payload = bytes(range(64)) * 4

    def schedule(plan, n=200):
        return [plan.on_send(1, payload) for _ in range(n)]

    a = schedule(FaultPlan(cfg))
    b = schedule(FaultPlan(cfg))
    assert a == b
    c = schedule(FaultPlan(FaultConfig(
        enabled=True, seed=43, drop_pct=0.3, dup_pct=0.2, corrupt_pct=0.2,
    )))
    assert a != c
    # and the chaos actually happened (counts drive soak bounds)
    plan = FaultPlan(cfg)
    schedule(plan)
    assert plan.counts["dropped"] > 0
    assert plan.counts["duplicated"] > 0
    assert plan.counts["corrupted"] > 0


def test_fault_plan_disabled_is_identity():
    plan = FaultPlan(FaultConfig())  # enabled=False
    payload = b"\x00payload"
    assert plan.on_send(1, payload) == ([payload], 0.0, False)
    plan.point("mid-burst")  # never fires
    assert not plan.counts


def test_fault_plan_only_link_filters():
    cfg = FaultConfig(enabled=True, seed=1, stall_after_frames=0, only_link=3)
    plan = FaultPlan(cfg)
    payload = b"\x00payload"
    # link 3 is stalled from the first frame; every other link runs clean
    assert plan.on_send(3, payload)[0] == []
    assert plan.on_send(1, payload)[0] == [payload]
    assert plan.on_send(2, payload)[0] == [payload]


def test_fault_plan_stall_and_sever_are_deterministic():
    cfg = FaultConfig(enabled=True, seed=0, stall_after_frames=2)
    plan = FaultPlan(cfg)
    p = b"\x00x" * 8
    assert plan.on_send(1, p)[0] == [p]  # frame 1
    assert plan.on_send(1, p)[0] == [p]  # frame 2
    assert plan.on_send(1, p)[0] == []   # frame 3+: swallowed
    assert plan.on_send(2, p)[0] == [p]  # per-link counters
    sev = FaultPlan(FaultConfig(enabled=True, sever_after_frames=2))
    assert sev.on_send(1, p) == ([p], 0.0, False)
    assert sev.on_send(1, p) == ([], 0.0, True)


def test_fault_plan_corrupt_preserves_kind_byte():
    rng_cfg = FaultConfig(enabled=True, seed=9, corrupt_pct=1.0)
    plan = FaultPlan(rng_cfg)
    payload = bytes([0]) + bytes(255)
    for _ in range(64):
        (out,), _, _ = plan.on_send(1, payload)
        assert out[0] == 0  # still routes as DATA
        assert len(out) == len(payload)
        diff = [i for i in range(len(out)) if out[i] != payload[i]]
        assert len(diff) == 1 and diff[0] >= len(payload) // 4


def test_fault_plan_corrupt_targets_sign_words():
    """With the frame geometry known (scale_bytes, as the peer passes it),
    every corrupt flip must land in a frame's packed sign words — never a
    scale byte: a flipped sign mis-applies ONE element by 2*scale (the
    bounded fault class the chaos soak's bound is built on), while a
    flipped scale exponent would rescale a whole frame by up to 2^127."""
    import struct

    sb = 8  # two leaves -> 8 scale bytes per frame
    wb = 16  # four sign words per frame
    plan = FaultPlan(
        FaultConfig(enabled=True, seed=4, corrupt_pct=1.0), scale_bytes=sb
    )
    data = bytes([0]) + struct.pack("<I", 1) + bytes(sb) + bytes(wb)
    burst = (
        bytes([7]) + struct.pack("<I", 1) + bytes([3]) + bytes(3 * (sb + wb))
    )
    for payload, hdr in ((data, 5), (burst, 6)):
        for _ in range(128):
            (out,), _, _ = plan.on_send(1, payload)
            diff = [i for i in range(len(out)) if out[i] != payload[i]]
            assert len(diff) == 1
            off = diff[0] - hdr
            if hdr == 6:
                off %= sb + wb  # position within its frame
            assert off >= sb, f"flip at {diff[0]} hit a scale byte"


def test_fault_plan_crash_point_callback_and_counting():
    hits = []
    plan = FaultPlan(
        FaultConfig(enabled=True, crash_point="mid-burst", crash_after=3),
        on_crash=hits.append,
    )
    for _ in range(5):
        plan.point("mid-join-walk")  # wrong point: never fires
    assert hits == []
    plan.point("mid-burst")
    plan.point("mid-burst")
    assert hits == []  # crash_after=3: first two arrivals survive
    plan.point("mid-burst")
    assert hits == ["mid-burst"]
    assert plan.counts["crashed"] == 1


def test_fault_plan_rejects_unknown_crash_point():
    with pytest.raises(ValueError, match="unknown crash point"):
        FaultPlan(FaultConfig(enabled=True, crash_point="mid-lunch"))


def test_to_env_round_trip():
    assert faults.to_env(FaultConfig()) == {}  # disabled: no injection
    env = faults.to_env(FaultConfig(
        enabled=True, seed=5, drop_pct=0.1, sever_after_frames=7,
        only_link=1, crash_point="mid-join-walk", crash_after=2,
    ))
    assert env["ST_FAULT_PLAN"] == "seed=5,drop=0.1,sever_after=7,only_link=1"
    assert env["ST_FAULT_CRASH"] == "mid-join-walk:2"
    # all-default knobs are omitted, so the native parser sees only what
    # the config actually asked for
    assert "stall_after" not in faults.to_env(
        FaultConfig(enabled=True, seed=1)
    )["ST_FAULT_PLAN"]


# ---------------------------------------------------------------------------
# Demo (a), Python tier: a severed link rolls unacked frames into the
# re-graft carry with no lost state
# ---------------------------------------------------------------------------


def test_python_tier_sever_rolls_unacked_into_carry():
    """Python wire tier: the joiner's fault plan stalls its uplink (frames
    silently swallowed while the sender believes it delivered — the exact
    failure the ACK ledger exists for), then severs it. The rolled-back
    unacked mass must ride the re-graft carry: after the automatic rejoin,
    every replica equals seed + the full delta. only_link pins the chaos to
    the first uplink (link 1); the re-grafted uplink gets a fresh id and
    runs clean, which is what lets the recovery path prove itself."""
    port = _free_port()
    seed = jnp.full((256,), 2.0, jnp.float32)
    fault = FaultConfig(
        enabled=True, seed=11,
        stall_after_frames=1,  # messages 2+ vanish on the wire
        sever_after_frames=4,  # then the link dies mid-stream (the
        # go-back-N retransmission rounds walk the per-link counter up to
        # this threshold even when the original traffic is only a couple
        # of burst messages)
        only_link=1,
    )
    m = create_or_fetch("127.0.0.1", port, seed, _cfg(engine=False))
    j = SharedTensorPeer(
        "127.0.0.1", port, jnp.zeros_like(seed),
        _cfg(fault, engine=False, ack_timeout_sec=1.0),
    )
    try:
        j.wait_ready(60.0)
        assert j._engine is None, "this test pins the Python wire tier"
        _wait_converged([j], seed)
        delta = jnp.asarray(
            np.random.default_rng(7).normal(size=(256,)).astype(np.float32)
        )
        j.add(delta)
        # chaos: message 1 delivers, later messages are swallowed
        # (ledgered, unacked); the retransmission rounds push the plan's
        # counter to the sever threshold and the link dies; the rejoin
        # re-grafts with residual = carry = everything unacked. No lost
        # state:
        _wait_converged([m, j], seed + delta, tol=1e-5)
        assert j._faults is not None
        assert j._faults.counts["severed"] >= 1
        assert j._faults.counts["stalled"] >= 1
    finally:
        j.close()
        m.close()


def test_python_tier_drop_faults_recovered_by_retransmission():
    """Random drops (seeded, heavy): every dropped message's ledger entry
    stays unacked, the go-back-N delivery timer retransmits the tail
    byte-identical, and the receiver's seq discipline applies each message
    exactly once — EXACT convergence with the link still up (no sever
    needed; wire.py tx_seq docstring's central claim)."""
    port = _free_port()
    seed = jnp.zeros((128,), jnp.float32)
    fault = FaultConfig(
        enabled=True, seed=3, drop_pct=0.5, only_link=1,
    )
    m = create_or_fetch("127.0.0.1", port, seed, _cfg(engine=False))
    j = SharedTensorPeer(
        "127.0.0.1", port, seed,
        _cfg(fault, engine=False, ack_timeout_sec=1.0),
    )
    try:
        j.wait_ready(60.0)
        delta = jnp.full((128,), 0.75, jnp.float32)
        j.add(delta)
        _wait_converged([m, j], seed + delta, tol=1e-5)
        assert j._faults.counts["dropped"] >= 1
    finally:
        j.close()
        m.close()


def test_python_tier_duplicate_is_deduped_exactly_once():
    """Documented dup semantics (r06, wire.py tx_seq): a duplicated
    DATA/BURST message carries the SAME wire seq, so the receiver's
    go-back-N acceptance discards the echo — exactly-once under dup
    faults, deterministic with dup_pct=1. (Before the seq prefix the
    protocol had no receive-side dedup and every duplicate double-counted;
    the ledger could not even represent the difference.)"""
    port = _free_port()
    seed = jnp.zeros((64,), jnp.float32)
    fault = FaultConfig(enabled=True, seed=1, dup_pct=1.0, only_link=1)
    m = create_or_fetch("127.0.0.1", port, seed, _cfg(engine=False))
    j = SharedTensorPeer(
        "127.0.0.1", port, seed, _cfg(fault, engine=False)
    )
    try:
        j.wait_ready(60.0)
        delta = jnp.full((64,), 0.5, jnp.float32)
        j.add(delta)
        # EXACTLY seed + delta on both ends: each echoed message was
        # discarded by seq, none double-applied, none lost
        _wait_converged([m, j], seed + delta, tol=1e-5)
        assert j._faults.counts["duplicated"] >= 1
    finally:
        j.close()
        m.close()


# ---------------------------------------------------------------------------
# Demo (a), native tier: same sever-into-carry path through the C transport
# ---------------------------------------------------------------------------


def test_native_tier_sever_rolls_unacked_into_carry(monkeypatch):
    """Native tier: the identical fault class injected in the C transport's
    sender loop (ST_FAULT_PLAN, parsed at st_node_create — set around ONE
    node's creation so only the joiner is chaotic). The engine's ACK ledger
    must roll the severed link's unacked frames into its carry and the
    native rejoin must re-graft them: exact convergence, no lost state."""
    port = _free_port()
    seed = jnp.full((256,), 1.0, jnp.float32)
    m = create_or_fetch("127.0.0.1", port, seed, _cfg())
    if m._engine is None:
        m.close()
        pytest.skip("native engine unavailable on this tier")
    env = faults.to_env(FaultConfig(
        enabled=True, seed=5, stall_after_frames=4, sever_after_frames=16,
        only_link=1,
    ))
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    j = SharedTensorPeer("127.0.0.1", port, jnp.zeros_like(seed), _cfg())
    for k in env:
        monkeypatch.delenv(k)
    try:
        j.wait_ready(60.0)
        assert j._engine is not None
        _wait_converged([j], seed)
        delta = jnp.asarray(
            np.random.default_rng(13).normal(size=(256,)).astype(np.float32)
        )
        j.add(delta)
        # the C sender loop swallows data frames 5..15 on link 1 and kills
        # the link at frame 16; engine stash_carry + rejoin recover all
        _wait_converged([m, j], seed + delta, tol=1e-5)
    finally:
        j.close()
        m.close()


def test_native_tier_crash_point_mid_join_walk():
    """Native crash point: a joiner subprocess armed with
    ST_FAULT_CRASH="mid-join-walk:1" must die with _exit(17) at the exact
    protocol instant (connected + hello'd, membership not granted) — and
    the master must shrug it off and keep serving (the reference's tree
    would be taken down by its exit(-1) instead)."""
    port = _free_port()
    seed = jnp.full((64,), 3.0, jnp.float32)
    m = create_or_fetch("127.0.0.1", port, seed, _cfg())
    script = (
        "import os; os.environ['JAX_PLATFORMS']='cpu';"
        "import jax.numpy as jnp;"
        "from shared_tensor_tpu.comm.peer import SharedTensorPeer;"
        "from shared_tensor_tpu.config import Config, TransportConfig;"
        f"SharedTensorPeer('127.0.0.1', {port}, jnp.zeros(64, jnp.float32),"
        "Config(transport=TransportConfig(peer_timeout_sec=10.0)))"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", script],
            env={
                **__import__("os").environ,
                "ST_FAULT_CRASH": "mid-join-walk:1",
                "JAX_PLATFORMS": "cpu",
            },
            timeout=120,
            capture_output=True,
        )
        assert proc.returncode == CRASH_EXIT_CODE, (
            proc.returncode, proc.stderr[-2000:],
        )
        # the master survived its child dying mid-walk: still serves joins
        j = create_or_fetch("127.0.0.1", port, jnp.zeros_like(seed), _cfg())
        try:
            _wait_converged([j], seed)
        finally:
            j.close()
    finally:
        m.close()


def test_python_tier_crash_points_fire_at_named_instants():
    """Python-tier protocol points: install a plan whose kill action is a
    recorder (FaultPlan(on_crash=...)) and verify each named point is
    actually reached where documented — mid-burst on the send path,
    between-apply-and-ack on the receive path."""
    port = _free_port()
    seed = jnp.zeros((64,), jnp.float32)
    m = create_or_fetch("127.0.0.1", port, seed, _cfg(engine=False))
    j = create_or_fetch("127.0.0.1", port, seed, _cfg(engine=False))
    hits_j, hits_m = [], []
    try:
        j._faults = FaultPlan(
            FaultConfig(enabled=True, crash_point="mid-burst"),
            on_crash=hits_j.append,
        )
        m._faults = FaultPlan(
            FaultConfig(enabled=True, crash_point="between-apply-and-ack"),
            on_crash=hits_m.append,
        )
        delta = jnp.full((64,), 0.25, jnp.float32)
        j.add(delta)
        _wait_converged([m, j], seed + delta, tol=1e-5)
        assert hits_j and hits_j[0] == "mid-burst"
        assert hits_m and hits_m[0] == "between-apply-and-ack"
    finally:
        j.close()
        m.close()


# ---------------------------------------------------------------------------
# Demo (b): a dead rendezvous / join target fails in bounded time
# ---------------------------------------------------------------------------


def test_dead_rendezvous_fails_in_bounded_time():
    """An accepting-but-silent rendezvous (listen backlog holds the
    connect, nobody ever speaks) used to block the joiner FOREVER in a
    blocking connect/read. With per-hop connect_timeout_sec and the total
    join_timeout_sec budget (exponential backoff + jitter between
    attempts), creation must fail with ConnectionError in bounded time."""
    silent = socket.socket()
    silent.bind(("127.0.0.1", 0))
    silent.listen(1)  # accepts into backlog; never reads, never replies
    port = silent.getsockname()[1]
    cfg = _cfg(connect_timeout_sec=0.5, join_timeout_sec=2.0)
    t0 = time.time()
    try:
        with pytest.raises(ConnectionError, match="within 2s"):
            SharedTensorPeer(
                "127.0.0.1", port, jnp.zeros((32,), jnp.float32), cfg
            )
    finally:
        silent.close()
    elapsed = time.time() - t0
    # budget 2 s + a few bounded hops of slack on a loaded box — the point
    # is "bounded", not "instant"; before r06 this hung until SIGKILL
    assert elapsed < 30.0, f"join took {elapsed:.1f}s against a 2s budget"


def test_dead_join_reply_does_not_hang_python_tier():
    """Same bound through the Python tier (the transport is shared, but the
    ConnectionError must propagate out of SharedTensorPeer.__init__ on
    this path too, with no threads left behind)."""
    silent = socket.socket()
    silent.bind(("127.0.0.1", 0))
    silent.listen(1)
    port = silent.getsockname()[1]
    before = {t.name for t in threading.enumerate()}
    try:
        with pytest.raises(ConnectionError):
            SharedTensorPeer(
                "127.0.0.1", port, jnp.zeros((32,), jnp.float32),
                _cfg(engine=False, connect_timeout_sec=0.5,
                     join_timeout_sec=1.5),
            )
    finally:
        silent.close()
    leaked = {
        t.name for t in threading.enumerate()
        if t.name.startswith("st-")
    } - before
    assert not leaked, f"join failure leaked threads: {leaked}"


# ---------------------------------------------------------------------------
# Quarantine: a stalled-but-open link is torn down and re-grafted
# ---------------------------------------------------------------------------


def test_quarantine_tears_down_stalled_link(caplog):
    """A peer that stops draining but keeps its socket open must not pin
    our sender forever: after quarantine_send_failures consecutive
    backpressure failures the link is torn down (LINK_DOWN -> rollback ->
    carry) and re-grafted, and the stalled frames arrive after all."""
    port = _free_port()
    seed = jnp.zeros((64,), jnp.float32)
    cfg = _cfg(engine=False, quarantine_send_failures=5)
    m = create_or_fetch("127.0.0.1", port, seed, cfg)
    j = create_or_fetch("127.0.0.1", port, seed, cfg)
    try:
        up = j._uplink
        assert up is not None
        real_send = j.node.send

        def stalled_send(link, payload, timeout=0.1):
            if link == up:
                time.sleep(0.01)  # a full queue that never drains
                return False
            return real_send(link, payload, timeout=timeout)

        j.node.send = stalled_send
        with caplog.at_level(logging.WARNING, "shared_tensor_tpu.peer"):
            delta = jnp.full((64,), 1.5, jnp.float32)
            j.add(delta)
            deadline = time.time() + 60.0
            while time.time() < deadline and j._uplink == up:
                time.sleep(0.05)
        j.node.send = real_send
        assert j._uplink != up, "stalled link was never quarantined"
        assert any("quarantining link" in r.message for r in caplog.records)
        # the re-grafted link delivers everything the stalled one owed
        _wait_converged([m, j], seed + delta, tol=1e-5)
    finally:
        j.close()
        m.close()


def test_handshake_traffic_is_never_faulted():
    """Chaos applies to DATA/BURST only: a plan that swallows EVERY data
    frame from the first send must still complete the join handshake
    (SYNC/CHUNK/WELCOME run clean) — injected faults exercise recovery,
    never wedge a join the protocol has no retry for."""
    port = _free_port()
    seed = jnp.full((64,), 4.0, jnp.float32)
    fault = FaultConfig(enabled=True, seed=2, stall_after_frames=0)
    m = create_or_fetch("127.0.0.1", port, seed, _cfg(engine=False))
    j = SharedTensorPeer(
        "127.0.0.1", port, jnp.zeros_like(seed), _cfg(fault, engine=False)
    )
    try:
        j.wait_ready(60.0)  # the handshake itself completed under chaos
        # the joiner still RECEIVES fine (its plan governs only its sends):
        _wait_converged([j], seed)
    finally:
        j.close()
        m.close()


# ---------------------------------------------------------------------------
# r11 multi-socket striping under chaos (per-stripe sever / stall arms)
# ---------------------------------------------------------------------------


def test_striped_link_survives_single_stripe_sever(monkeypatch):
    """A striped link with one dead SOCKET must degrade to the surviving
    stripes — messages re-route (reroutes counter), stripe_stats shows the
    death on BOTH ends, the link itself stays up, and every queued update
    still converges exactly (anything delivery-uncertain on the dead
    socket is the reassembly window's dedup or the engine's go-back-N to
    repair)."""
    port = _free_port()
    seed = jnp.full((1 << 14,), 1.0, jnp.float32)
    env = faults.to_env(FaultConfig(
        enabled=True, seed=9, sever_after_frames=3, only_link=1,
        only_stripe=2,
    ))
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    m = create_or_fetch("127.0.0.1", port, seed, _cfg(stripe_count=4))
    for k in env:
        monkeypatch.delenv(k)
    if m._engine is None:
        m.close()
        pytest.skip("native engine unavailable on this tier")
    j = SharedTensorPeer(
        "127.0.0.1", port, jnp.zeros_like(seed), _cfg(stripe_count=4)
    )
    try:
        j.wait_ready(60.0)
        _wait_converged([j], seed, tol=1e-5)
        rng = np.random.default_rng(21)
        total = np.asarray(seed)
        for _ in range(12):
            u = rng.normal(0, 0.5, 1 << 14).astype(np.float32)
            total = total + u
            m.add(jnp.asarray(u))
            time.sleep(0.01)
        _wait_converged([m, j], jnp.asarray(total), tol=1e-4)
        ss = m.node.stripe_stats(1)
        assert ss is not None and ss["stripes"] == 4
        assert ss["deaths"] >= 1, "the injected stripe sever never fired"
        assert ss["live"] == ss["stripes"] - ss["deaths"]
        assert ss["reroutes"] >= 1, "no message re-routed off the dead stripe"
        assert 1 in m.node.links, "the LINK must survive a stripe death"
        # the peer's canonical metrics carry the stripe telemetry
        mm = m.metrics(canonical=True)
        assert mm.get("st_stripe_deaths_total", 0) >= 1
    finally:
        j.close()
        m.close()


def test_striped_link_stall_tears_down_cleanly_not_wedged(monkeypatch):
    """The OTHER failure shape: a stripe that silently swallows messages
    (stall) wedges reassembly — the whole link must then go down the
    go-back-N black-hole teardown -> carry -> re-graft path in bounded
    time and converge exactly, never hang. (A swallowed stripe seq is a
    permanent hole; no per-stripe recovery exists for it by design — the
    ledger's retransmissions land behind the hole.)"""
    port = _free_port()
    seed = jnp.full((4096,), 2.0, jnp.float32)
    m = create_or_fetch("127.0.0.1", port, seed, _cfg(stripe_count=2))
    if m._engine is None:
        m.close()
        pytest.skip("native engine unavailable on this tier")
    env = faults.to_env(FaultConfig(
        enabled=True, seed=4, stall_after_frames=6, only_link=1,
        only_stripe=1,
    ))
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    j = SharedTensorPeer(
        "127.0.0.1", port, jnp.zeros_like(seed),
        _cfg(stripe_count=2, ack_timeout_sec=1.0, ack_retry_limit=2),
    )
    for k in env:
        monkeypatch.delenv(k)
    try:
        j.wait_ready(60.0)
        _wait_converged([j], seed, tol=1e-5)
        delta = jnp.asarray(
            np.random.default_rng(8).normal(size=(4096,)).astype(np.float32)
        )
        j.add(delta)
        # frames past the 6th on stripe 1 of the joiner's uplink vanish;
        # reassembly at the master wedges on the hole; the joiner's
        # go-back-N declares the link a black hole, tears it down, and the
        # carry re-grafts on a fresh (clean) link id
        _wait_converged([m, j], seed + delta, tol=1e-5, timeout=120.0)
    finally:
        j.close()
        m.close()
