"""r14 same-host shm transport lane — peer/engine-tier contract tests.

The lane is negotiated at the SYNC/WELCOME hello (compat.SYNC_FLAG_SHM +
boot-id match) and slots in BELOW the wire-seq layer: join, go-back-N seq
accounting, SNAP/RESUME lifecycle, quarantine/carry/re-graft must behave
identically whether a link's data plane rides TCP or the rings. These
tests pin exactly that:

- negotiation + fallback: a same-host pair goes lane-live; a mixed tree
  (one peer with the lane disabled — the pre-r14 stand-in, since a
  disabled peer neither advertises nor offers, byte-identical to an old
  one) silently keeps TCP and still converges exactly;
- ring-full backpressure propagates like socket backpressure (sendq
  fills, sends bounce, nothing is lost once the reader drains);
- sever/stall fault injection on a lane-live link tears down into the
  r06 quarantine/carry/re-graft path and converges exactly;
- SNAP/RESUME: a consistent-cut cluster snapshot completes across
  lane-live links (markers ride the same in-order stream).

Transport-level ring mechanics (wrap, streaming, token validation) live
in tests/test_transport.py.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shared_tensor_tpu.comm import faults
from shared_tensor_tpu.comm.peer import SharedTensorPeer, create_or_fetch
from shared_tensor_tpu.config import Config, FaultConfig, TransportConfig

from tests._ports import free_port as _free_port


def _cfg(fault: "FaultConfig | None" = None, shm: bool = True, **tkw):
    tkw.setdefault("peer_timeout_sec", 10.0)
    tkw.setdefault("shm_enabled", shm)
    return Config(
        transport=TransportConfig(**tkw),
        faults=fault or FaultConfig(),
    )


def _wait_converged(peers, expect, tol=1e-6, timeout=90.0):
    expect_leaves = jax.tree.leaves(expect)
    deadline = time.time() + timeout
    while time.time() < deadline:
        if all(
            all(
                np.allclose(g, e, rtol=1e-4, atol=tol)
                for g, e in zip(jax.tree.leaves(p.read()), expect_leaves)
            )
            for p in peers
        ):
            return
        time.sleep(0.05)
    raise AssertionError(
        "no convergence: "
        + "; ".join(
            f"peer{i} head={np.asarray(jax.tree.leaves(p.read())[0])[:4]}"
            for i, p in enumerate(peers)
        )
    )


def _shm_live(peer) -> int:
    """Count of this peer's links whose data plane is live on the rings."""
    m = peer.metrics(canonical=True)
    return sum(
        1 for k, v in m.items() if k.startswith("st_shm_active") and v == 2
    )


def _wait_lane_live(peers, want=1, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if all(_shm_live(p) >= want for p in peers):
            return True
        time.sleep(0.05)
    return False


def test_same_host_pair_negotiates_lane_and_converges():
    """The normal state of a loopback pair: the SYNC/WELCOME hello takes
    the link's data plane onto the rings (st_shm_active == 2 at BOTH
    ends), real traffic flows over them, and convergence stays exact."""
    port = _free_port()
    seed = jnp.full((1 << 13,), 1.0, jnp.float32)
    m = create_or_fetch("127.0.0.1", port, seed, _cfg())
    j = SharedTensorPeer("127.0.0.1", port, jnp.zeros_like(seed), _cfg())
    try:
        j.wait_ready(60.0)
        _wait_converged([j], seed, tol=1e-5)
        assert _wait_lane_live([m, j]), "shm lane never went live"
        rng = np.random.default_rng(3)
        total = np.asarray(seed)
        for _ in range(8):
            u = rng.normal(0, 0.5, 1 << 13).astype(np.float32)
            total = total + u
            m.add(jnp.asarray(u))
        _wait_converged([m, j], jnp.asarray(total), tol=1e-4)
        mm = m.metrics(canonical=True)
        assert mm.get("st_shm_msgs_out_total", 0) >= 1, (
            "lane live but no shm traffic — data still on TCP?"
        )
    finally:
        j.close()
        m.close()


def test_mixed_tree_pre_r14_peer_keeps_tcp():
    """Negotiation fallback: a parent with the lane disabled neither
    parses the SYNC advertisement nor offers a segment (the pre-r14
    stand-in — an old parent ignores the same trailing bytes), so the
    r14 joiner keeps TCP silently and the pair still converges exactly.
    Same in the other orientation: an r14 parent never offers to a
    non-advertising joiner."""
    port = _free_port()
    seed = jnp.full((4096,), 2.0, jnp.float32)
    m = create_or_fetch("127.0.0.1", port, seed, _cfg(shm=False))
    j = SharedTensorPeer("127.0.0.1", port, jnp.zeros_like(seed), _cfg())
    try:
        j.wait_ready(60.0)
        _wait_converged([j], seed, tol=1e-5)
        m.add(jnp.full((4096,), 1.0, jnp.float32))
        _wait_converged([m, j], jnp.full((4096,), 3.0, jnp.float32), 1e-4)
        assert _shm_live(m) == 0 and _shm_live(j) == 0
        assert "st_shm_msgs_out_total" not in m.metrics(canonical=True)
    finally:
        j.close()
        m.close()

    # reverse orientation: non-advertising joiner under an r14 parent
    port = _free_port()
    m = create_or_fetch("127.0.0.1", port, seed, _cfg())
    j = SharedTensorPeer(
        "127.0.0.1", port, jnp.zeros_like(seed), _cfg(shm=False)
    )
    try:
        j.wait_ready(60.0)
        _wait_converged([j], seed, tol=1e-5)
        assert _shm_live(m) == 0 and _shm_live(j) == 0
    finally:
        j.close()
        m.close()


def test_shm_sever_tears_down_into_carry_and_regraft(monkeypatch):
    """A sever fault firing ON a lane-live link must take the same r06
    recovery road as a TCP link: link death -> rollback -> carry ->
    re-graft (the re-grafted link, pinned chaos-free via only_link,
    re-negotiates its own fresh lane) -> exact convergence. Nothing the
    dead lane swallowed may be lost."""
    port = _free_port()
    seed = jnp.full((1 << 13,), 1.0, jnp.float32)
    m = create_or_fetch("127.0.0.1", port, seed, _cfg())
    env = faults.to_env(
        FaultConfig(enabled=True, seed=11, sever_after_frames=6,
                    only_link=1)
    )
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    j = SharedTensorPeer("127.0.0.1", port, jnp.zeros_like(seed), _cfg())
    for k in env:
        monkeypatch.delenv(k)
    try:
        j.wait_ready(60.0)
        _wait_converged([j], seed, tol=1e-5)
        assert _wait_lane_live([j]), "lane never live before the sever"
        up0 = j.node.uplink
        rng = np.random.default_rng(17)
        total = np.asarray(seed, np.float64)
        # the JOINER adds: its uplink sender (the lane writer) trips the
        # sever at the 6th data frame
        for _ in range(12):
            u = rng.normal(0, 0.5, 1 << 13).astype(np.float32)
            total = total + u
            j.add(jnp.asarray(u))
            time.sleep(0.02)
        _wait_converged(
            [m, j], jnp.asarray(total, jnp.float32), tol=1e-4, timeout=120.0
        )
        assert j.node.uplink != up0, (
            "uplink id unchanged — the sever never tore the lane-live "
            "link down"
        )
        assert _wait_lane_live([j]), "the re-grafted link has no lane"
    finally:
        j.close()
        m.close()


def test_shm_stall_blackholes_into_quarantine_path(monkeypatch):
    """The stall class (messages silently swallowed at the lane writer,
    sender believes delivered): the engine's go-back-N must declare the
    link a black hole in bounded time, tear it down, and recover every
    frame through carry/re-graft — identical to the TCP stall contract."""
    port = _free_port()
    seed = jnp.full((4096,), 2.0, jnp.float32)
    m = create_or_fetch("127.0.0.1", port, seed, _cfg())
    env = faults.to_env(
        FaultConfig(enabled=True, seed=5, stall_after_frames=4,
                    only_link=1)
    )
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    j = SharedTensorPeer(
        "127.0.0.1", port, jnp.zeros_like(seed),
        _cfg(ack_timeout_sec=1.0, ack_retry_limit=2),
    )
    for k in env:
        monkeypatch.delenv(k)
    try:
        j.wait_ready(60.0)
        _wait_converged([j], seed, tol=1e-5)
        assert _wait_lane_live([j]), "lane never live before the stall"
        up0 = j.node.uplink
        delta = jnp.full((4096,), 0.25, jnp.float32)
        total = np.asarray(seed) + 8 * np.asarray(delta)
        for _ in range(8):
            j.add(delta)
            time.sleep(0.02)
        # bounded-time teardown + exact recovery (black hole -> carry)
        _wait_converged(
            [m, j], jnp.asarray(total), tol=1e-4, timeout=120.0
        )
        jm = j.metrics(canonical=True)
        assert jm.get("st_retransmit_msgs_total", 0) >= 1, (
            "go-back-N never retransmitted into the stalled lane"
        )
        assert j.node.uplink != up0, (
            "stalled lane-live link was never torn down (black hole)"
        )
    finally:
        j.close()
        m.close()


def test_snapshot_cluster_across_live_shm_links(tmp_path):
    """r12 SNAP/RESUME across lane-live links: the barrier markers ride
    the same in-order stream as data (per-link FIFO is the consistent-cut
    property), so a cluster snapshot must complete with every shard
    captured while the lanes stay up — and streaming must resume after
    RESUME with the lanes still live."""
    port = _free_port()
    seed = jnp.zeros((4096,), jnp.float32)
    peers = [
        create_or_fetch("127.0.0.1", port, seed, _cfg())
        if i == 0
        else SharedTensorPeer("127.0.0.1", port, seed, _cfg())
        for i in range(3)
    ]
    try:
        for p in peers[1:]:
            p.wait_ready(60.0)
        assert _wait_lane_live(peers[1:]), "lanes never live in the tree"
        rng = np.random.default_rng(7)
        total = np.zeros(4096, np.float64)
        for i in range(6):
            u = rng.uniform(-0.5, 0.5, 4096).astype(np.float32)
            total += u
            peers[i % 3].add(jnp.asarray(u))
        # converge BEFORE the barrier: a joiner still re-syncing a churned
        # handshake (loaded-box join race) would miss the cut and the
        # nodes-count assertion would flake on the churn, not the lane
        _wait_converged(
            peers, jnp.asarray(total, jnp.float32), tol=1e-4, timeout=120.0
        )
        res = peers[0].snapshot_cluster(str(tmp_path), timeout=45.0)
        assert res["ok"], res
        assert res["nodes"] >= 3
        # post-RESUME: streaming continues over the SAME lanes
        for i in range(4):
            u = rng.uniform(-0.5, 0.5, 4096).astype(np.float32)
            total += u
            peers[i % 3].add(jnp.asarray(u))
        _wait_converged(
            peers, jnp.asarray(total, jnp.float32), tol=1e-4, timeout=120.0
        )
        assert all(_shm_live(p) >= 1 for p in peers[1:]), (
            "a lane died across SNAP/RESUME"
        )
    finally:
        for p in peers:
            p.close()


def test_ring_full_backpressure_bounds_not_loses():
    """A tiny ring under a burst: the writer blocks (spin -> futex), the
    sendq fills, sends bounce — and once the reader drains, EVERYTHING
    arrives in order. The lane's backpressure is the same contract as a
    full socket buffer, with the TCP keepalive holding liveness the
    whole time."""
    port = _free_port()
    seed = jnp.zeros((1 << 15,), jnp.float32)  # 32 Ki elems, ~132 KiB frames
    cfgs = dict(shm_ring_bytes=1 << 16)  # 64 KiB ring << one burst
    m = create_or_fetch("127.0.0.1", port, seed, _cfg(**cfgs))
    j = SharedTensorPeer(
        "127.0.0.1", port, jnp.zeros_like(seed), _cfg(**cfgs)
    )
    try:
        j.wait_ready(60.0)
        assert _wait_lane_live([m, j]), "shm lane never went live"
        rng = np.random.default_rng(23)
        total = np.zeros(1 << 15, np.float64)
        for _ in range(10):
            u = rng.normal(0, 0.5, 1 << 15).astype(np.float32)
            total += u
            m.add(jnp.asarray(u))
        _wait_converged(
            [m, j], jnp.asarray(total, jnp.float32), tol=1e-4, timeout=120.0
        )
        sh = [
            v for k, v in m.metrics(canonical=True).items()
            if k.startswith("st_shm_active")
        ]
        assert sh and max(sh) == 2
    finally:
        j.close()
        m.close()
