"""Protocol model checker + runtime trace conformance (r15 tentpole).

Three layers, mirroring tools/protospec's own structure:

1. the EXPLORER: every true spec explores clean (zero violations,
   quiescence reachable, graph exhausted — not truncated), twice with
   identical counts (the committed MODEL artifact pins exact numbers,
   so nondeterminism is a bug);
2. the RED TEAM: each seeded mutation — the three historical r10/r11/
   r12 protocol bugs plus the extra lane-switch ordering mutation — is
   FOUND within the documented depth bound, and its counterexample
   trace REPLAYS through the mutated spec to the violating state (a
   counterexample that can't be replayed is a checker bug);
3. CONFORMANCE: the monitor accepts the committed CHAOS_r12/CHAOS_r14
   fixture timelines (pinned from real cluster_chaos.py runs — spec
   edits can't silently diverge from shipped behavior) and rejects a
   battery of synthetic forbidden orderings, one per acceptor rule.
"""

import json
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
TOOLS = REPO / "tools"
sys.path.insert(0, str(TOOLS))

from protospec import all_specs, explore  # noqa: E402
from protospec.conformance import check_timeline, load_timeline  # noqa: E402

FIXTURES = REPO / "tests" / "fixtures"

#: the three hand-found historical bugs the checker must re-find
#: (ISSUE r15 acceptance bar) — the extra mutations are gravy
HISTORICAL = {
    "sub.fresh_no_seq",  # r10: FRESH falsely verifying over a lost tail
    "lane_stripe.requeue_before_kill",  # r11: last-stripe requeue livelock
    "snap.async_pause",  # r12: pre-pause pass leaking mass across the cut
}


def _mutation_keys():
    return {
        f"{name}.{mut}"
        for name, cls in all_specs().items()
        for mut in cls.mutations
    }


# ---- explorer: true specs -------------------------------------------------


@pytest.mark.parametrize("name", sorted(all_specs()))
def test_true_spec_explores_clean(name):
    res = explore(all_specs()[name]())
    assert res.violations == [], [v.as_dict() for v in res.violations]
    assert res.quiescent_reachable
    assert not res.truncated_by_depth, (
        f"{name}: frontier not exhausted at depth bound "
        f"{res.depth_bound} — the artifact would overclaim"
    )
    assert res.states >= 30, f"{name}: {res.states} states is a toy graph"


def test_exploration_is_deterministic():
    for name, cls in all_specs().items():
        a, b = explore(cls()), explore(cls())
        assert (a.states, a.transitions) == (b.states, b.transitions), name


# ---- red team: the seeded historical bugs ---------------------------------


def test_historical_bugs_are_encoded():
    assert HISTORICAL <= _mutation_keys()


@pytest.mark.parametrize(
    "name,mut",
    [
        (k.split(".")[0], k.split(".")[1])
        for k in sorted(_mutation_keys())
    ],
)
def test_mutation_is_found_within_bound(name, mut):
    cls = all_specs()[name]
    res = explore(cls(mutation=mut))
    assert res.violations, (
        f"{name}.{mut} NOT found within depth {res.depth_bound} — the "
        f"checker is blind to this bug class"
    )


def test_mutation_counterexamples_replay():
    """A counterexample must be a real path: replaying its action trace
    from the initial state step-by-step (each action enabled where it
    fires) must land in the reported violation."""
    for key in sorted(_mutation_keys()):
        name, mut = key.split(".")
        spec = all_specs()[name](mutation=mut)
        res = explore(spec)
        v = res.violations[0]
        s = spec.initial()
        for act in v.trace:
            assert act in spec.enabled(s), (key, act, s)
            s = spec.apply(s, act)
        if v.kind == "invariant":
            assert spec.invariants(s), (key, s)
        elif v.kind == "wedged":
            assert not spec.enabled(s) and not spec.quiescent(s), (key, s)


# ---- the committed MODEL artifact -----------------------------------------


def test_model_artifact_matches_checker():
    """MODEL_r17.json pins the explored state/transition counts; a spec
    edit that changes the graph must re-commit the artifact, not drift
    silently."""
    path = REPO / "MODEL_r17.json"
    doc = json.loads(path.read_text())
    assert doc["pass"] is True
    for name, cls in all_specs().items():
        res = explore(cls())
        pinned = doc["specs"][name]
        assert (pinned["states"], pinned["transitions"]) == (
            res.states,
            res.transitions,
        ), f"{name}: MODEL_r17.json is stale — re-run run_check.py"
        assert pinned["violations"] == []
        assert pinned["quiescent_reachable"] is True
    for key in _mutation_keys():
        assert doc["mutations"][key]["found"] is True, key


def test_run_check_cli(tmp_path):
    out = tmp_path / "MODEL.json"
    r = subprocess.run(
        [sys.executable, str(TOOLS / "protospec" / "run_check.py"),
         "--out", str(out)],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    doc = json.loads(out.read_text())
    assert doc["pass"] is True
    assert HISTORICAL <= set(doc["mutations"])


# ---- conformance: the pinned chaos fixtures -------------------------------


@pytest.mark.parametrize(
    "fixture", ["CHAOS_r12_timeline.json", "CHAOS_r14_timeline.json"]
)
def test_conformance_accepts_committed_chaos_timelines(fixture):
    """The regression pin: these timelines came from real (passing)
    cluster_chaos kill-restore runs — r12 shape and r14 --shm shape. A
    spec edit that rejects them has diverged from shipped behavior."""
    tl = load_timeline(str(FIXTURES / fixture))
    report = check_timeline(tl)
    assert report["violations"] == [], report["violations"][:10]
    assert report["events"] >= 100, "fixture lost its events"
    assert report["scopes"] >= 5, "fixture no longer routes to acceptors"


def test_conformance_cli_accepts_fixture_and_rejects_corruption(tmp_path):
    fixture = FIXTURES / "CHAOS_r12_timeline.json"
    r = subprocess.run(
        [sys.executable, str(TOOLS / "protospec" / "run_conformance.py"),
         str(fixture)],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    # corrupt the fixture: strip every lifecycle_resume so some node is
    # left paused — the monitor must go red, proving the fixture test
    # can actually fail
    doc = json.loads(fixture.read_text())
    doc["timeline"] = [
        e for e in doc["timeline"] if e["name"] != "lifecycle_resume"
    ]
    bad = tmp_path / "corrupt.json"
    bad.write_text(json.dumps(doc))
    r = subprocess.run(
        [sys.executable, str(TOOLS / "protospec" / "run_conformance.py"),
         str(bad)],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 1, r.stdout + r.stderr
    assert "paused" in r.stdout


# ---- conformance: synthetic forbidden orderings ---------------------------


def _ev(name, node=1, link=1, arg=0, detail=""):
    return {
        "t_ns": 0, "tier": "py", "name": name, "node": node, "link": link,
        "arg": arg, "detail": detail,
    }


def _violates(events, needle):
    report = check_timeline(events)
    assert report["violations"], f"accepted a timeline violating: {needle}"
    assert any(needle in v for v in report["violations"]), report["violations"]


def test_conformance_rejects_double_pause():
    _violates(
        [_ev("lifecycle_pause"), _ev("lifecycle_pause")],
        "double lifecycle_pause",
    )


def test_conformance_rejects_bare_resume():
    _violates([_ev("lifecycle_resume")], "while not paused")


def test_conformance_rejects_node_left_paused():
    _violates([_ev("lifecycle_pause")], "left paused")


def test_conformance_rejects_unpaused_capture():
    _violates([_ev("snap_shard")], "unpaused")


def test_conformance_rejects_window_traffic_after_teardown():
    _violates(
        [_ev("blackhole_teardown"), _ev("retransmit")],
        "after the link was torn down",
    )


def test_conformance_rejects_double_teardown():
    _violates(
        [_ev("blackhole_teardown"), _ev("blackhole_teardown")],
        "second blackhole_teardown",
    )


def test_conformance_rejects_resync_before_attach():
    _violates([_ev("sub_resync")], "before sub_attach")


def test_conformance_rejects_double_lane_up():
    _violates(
        [_ev("shm_lane_up"), _ev("shm_lane_up")],
        "shm_lane_up fired twice",
    )


def test_conformance_rejects_lane_up_after_fallback():
    _violates(
        [_ev("shm_fallback"), _ev("shm_lane_up")],
        "shm_lane_up after shm_fallback",
    )


def test_conformance_rejects_dead_stripe_reattach():
    _violates(
        [_ev("stripe_down", arg=2), _ev("stripe_down", arg=2)],
        "died twice",
    )


def test_conformance_rejects_drain_with_no_seal():
    _violates([_ev("drain_begin")], "no seal")


def test_conformance_accepts_legal_orderings():
    ok = [
        _ev("lifecycle_pause"),
        _ev("snap_shard"),
        _ev("lifecycle_resume"),
        _ev("sub_attach", link=2),
        _ev("sub_resync", link=2),
        _ev("retransmit", link=3),
        _ev("dedup_discard", link=3),
        _ev("blackhole_teardown", link=3),
        _ev("link_down", link=3),
        _ev("shm_lane_up", link=4),
        _ev("stripe_down", link=4, arg=0),
        _ev("stripe_down", link=4, arg=1),
        _ev("drain_begin", node=2),
        _ev("seal", node=2),
    ]
    report = check_timeline(ok)
    assert report["violations"] == [], report["violations"]
    assert report["scopes"] >= 6
