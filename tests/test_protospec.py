"""Protocol model checker + runtime trace conformance (r15 tentpole,
r19 reductions + liveness).

Four layers, mirroring tools/protospec's own structure:

1. the EXPLORER: every true spec explores clean (zero violations,
   quiescence reachable, graph exhausted — not truncated), twice with
   identical counts (the committed MODEL artifact pins exact numbers,
   so nondeterminism is a bug);
2. the RED TEAM: each seeded mutation — the historical hand-found
   protocol bugs plus the per-subsystem signature bugs — is FOUND
   within the documented depth bound, and its counterexample trace
   REPLAYS through the mutated spec to the violating state (a
   counterexample that can't be replayed is a checker bug);
3. the r19 REDUCTIONS are sound: symmetry + ample-set POR re-find
   every mutation the pre-reduction r17 artifact pinned at the
   same-or-smaller depth, agree with the unreduced explorer on every
   verdict, and the fair-lasso liveness pass goes red on a toy
   livelock (and is excused by a declared fairness assumption);
4. CONFORMANCE: the monitor accepts the committed CHAOS_r12/CHAOS_r14
   fixture timelines (pinned from real cluster_chaos.py runs — spec
   edits can't silently diverge from shipped behavior) and rejects a
   battery of synthetic forbidden orderings, one per acceptor rule —
   including the r19 reshard/global-scope acceptors.
"""

import json
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
TOOLS = REPO / "tools"
sys.path.insert(0, str(TOOLS))

from protospec import all_specs, explore  # noqa: E402
from protospec.conformance import check_timeline, load_timeline  # noqa: E402
from protospec.core import Spec  # noqa: E402

FIXTURES = REPO / "tests" / "fixtures"

#: the three hand-found historical bugs the checker must re-find
#: (ISSUE r15 acceptance bar) — the extra mutations are gravy
HISTORICAL = {
    "sub.fresh_no_seq",  # r10: FRESH falsely verifying over a lost tail
    "lane_stripe.requeue_before_kill",  # r11: last-stripe requeue livelock
    "snap.async_pause",  # r12: pre-pause pass leaking mass across the cut
}

#: the r19 reshard red-team set (ISSUE r19 acceptance bar)
RESHARD = {
    "reshard_split.split_during_fwd",
    "reshard_split.stale_grant_readopt",
    "reshard_merge.merge_drops_inflight_outbox",
    "master_handoff.two_minters_after_handoff",
}


def _mutation_keys():
    return {
        f"{name}.{mut}"
        for name, cls in all_specs().items()
        for mut in cls.mutations
    }


# ---- explorer: true specs -------------------------------------------------


@pytest.mark.parametrize("name", sorted(all_specs()))
def test_true_spec_explores_clean(name):
    res = explore(all_specs()[name]())
    assert res.violations == [], [v.as_dict() for v in res.violations]
    assert res.quiescent_reachable
    assert not res.truncated_by_depth, (
        f"{name}: frontier not exhausted at depth bound "
        f"{res.depth_bound} — the artifact would overclaim"
    )
    assert res.states >= 30, f"{name}: {res.states} states is a toy graph"


def test_exploration_is_deterministic():
    for name, cls in all_specs().items():
        a, b = explore(cls()), explore(cls())
        assert (a.states, a.transitions) == (b.states, b.transitions), name


# ---- red team: the seeded historical bugs ---------------------------------


def test_historical_bugs_are_encoded():
    assert HISTORICAL <= _mutation_keys()
    assert RESHARD <= _mutation_keys()


@pytest.mark.parametrize(
    "name,mut",
    [
        (k.split(".")[0], k.split(".")[1])
        for k in sorted(_mutation_keys())
    ],
)
def test_mutation_is_found_within_bound(name, mut):
    cls = all_specs()[name]
    res = explore(cls(mutation=mut))
    assert res.violations, (
        f"{name}.{mut} NOT found within depth {res.depth_bound} — the "
        f"checker is blind to this bug class"
    )


def test_mutation_counterexamples_replay():
    """A counterexample must be a real path: replaying its action trace
    from the initial state step-by-step (each action enabled where it
    fires) must land in the reported violation."""
    for key in sorted(_mutation_keys()):
        name, mut = key.split(".")
        spec = all_specs()[name](mutation=mut)
        res = explore(spec)
        v = res.violations[0]
        s = spec.initial()
        for act in v.trace:
            assert act in spec.enabled(s), (key, act, s)
            s = spec.apply(s, act)
        if v.kind == "invariant":
            assert spec.invariants(s), (key, s)
        elif v.kind == "wedged":
            assert not spec.enabled(s) and not spec.quiescent(s), (key, s)


# ---- the committed MODEL artifact -----------------------------------------


def test_model_artifact_matches_checker():
    """MODEL_r19.json pins the explored state/transition counts AND the
    liveness verdicts; a spec edit that changes the graph must re-commit
    the artifact, not drift silently."""
    path = REPO / "MODEL_r19.json"
    doc = json.loads(path.read_text())
    assert doc["pass"] is True
    for name, cls in all_specs().items():
        res = explore(cls())
        pinned = doc["specs"][name]
        assert (pinned["states"], pinned["transitions"]) == (
            res.states,
            res.transitions,
        ), f"{name}: MODEL_r19.json is stale — re-run run_check.py"
        assert pinned["violations"] == []
        assert pinned["quiescent_reachable"] is True
        assert pinned.get("liveness", {}) == res.liveness, name
    # the reshard family carries real liveness verdicts, all proven
    for name in ("reshard_split", "reshard_merge", "master_handoff"):
        liv = doc["specs"][name]["liveness"]
        assert liv and all(v is True for v in liv.values()), (name, liv)
    for key in _mutation_keys():
        assert doc["mutations"][key]["found"] is True, key


def test_run_check_cli(tmp_path):
    out = tmp_path / "MODEL.json"
    r = subprocess.run(
        [sys.executable, str(TOOLS / "protospec" / "run_check.py"),
         "--out", str(out)],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    doc = json.loads(out.read_text())
    assert doc["pass"] is True
    assert HISTORICAL <= set(doc["mutations"])
    assert RESHARD <= set(doc["mutations"])


# ---- r19: the reductions are sound ----------------------------------------


def test_reduced_explorer_refinds_pinned_mutations():
    """Soundness regression for symmetry + POR: with the reductions ON
    (the default), every mutation the PRE-reduction r17 artifact pinned
    is re-found at the same-or-smaller depth — a reduction that hides a
    committed counterexample is unsound, full stop."""
    doc = json.loads((REPO / "MODEL_r17.json").read_text())
    for key, pinned in sorted(doc["mutations"].items()):
        name, mut = key.split(".")
        res = explore(all_specs()[name](mutation=mut))
        assert res.violations, f"{key}: reduction hid the counterexample"
        depth = len(res.violations[0].trace)
        assert depth <= pinned["first_violation"]["depth"], (
            key, depth, pinned["first_violation"]["depth"]
        )


@pytest.mark.parametrize(
    "name", ["reshard_split", "reshard_merge", "master_handoff"]
)
def test_reduced_and_unreduced_explorers_agree(name):
    """The specs with REAL canon/ample hooks: reduced and unreduced
    exploration reach the same verdicts (safety, quiescence, liveness)
    and the reduction genuinely shrinks the graph."""
    cls = all_specs()[name]
    red = explore(cls())
    full = explore(cls(), reduction=False)
    for res in (red, full):
        assert res.violations == [], name
        assert res.quiescent_reachable and not res.truncated_by_depth
    assert red.liveness == full.liveness, name
    assert red.states < full.states, (name, red.states, full.states)
    for mut in cls.mutations:
        assert explore(cls(mutation=mut)).violations, (name, mut)
        assert explore(cls(mutation=mut), reduction=False).violations, (
            name, mut
        )


# ---- r19: liveness verdicts -----------------------------------------------


class _Spin(Spec):
    """Toy livelock for red-teaming the fair-lasso pass: `spin` cycles
    forever in the not-done region while `finish` stays enabled. With no
    fairness every cycle is fair -> the livelock is a violation; with
    weak fairness on `finish` the spin cycle is excused only if finish
    is taken — it never is, so the cycle is UNFAIR and the property
    holds (the implementation really does retry finish unconditionally
    in the scenario this models)."""

    name = "toy_spin"
    depth_bound = 8

    def __init__(self, mutation=None, fair=False):
        super().__init__(mutation)
        self._fair = fair

    def initial(self):
        return (0, 0)  # (done, tick)

    def enabled(self, s):
        return [] if s[0] else [("spin",), ("finish",)]

    def apply(self, s, act):
        if act[0] == "spin":
            return (s[0], 1 - s[1])
        return (1, s[1])

    def invariants(self, s):
        return []

    def quiescent(self, s):
        return bool(s[0])

    def liveness(self):
        return {"eventually-done": lambda s: bool(s[0])}

    def fairness(self):
        if self._fair:
            return [("finish", lambda a: a[0] == "finish")]
        return []


def test_liveness_checker_finds_livelock():
    res = explore(_Spin())
    assert res.liveness["eventually-done"] is False
    assert not res.ok
    lasso = [v for v in res.violations if v.kind == "liveness"]
    assert lasso, [v.as_dict() for v in res.violations]
    assert "spin" in lasso[0].detail


def test_liveness_checker_respects_declared_fairness():
    res = explore(_Spin(fair=True))
    assert res.liveness["eventually-done"] is True
    assert res.violations == [] and res.ok


def test_liveness_verdict_is_unknown_when_truncated():
    """A liveness check over a depth-truncated graph proves nothing —
    the verdict must be None (unknown) and the result NOT ok, never a
    silent green."""
    res = explore(all_specs()["reshard_split"](), depth_bound=3)
    assert res.truncated_by_depth
    assert res.liveness
    assert all(v is None for v in res.liveness.values())
    assert not res.ok


# ---- conformance: the pinned chaos fixtures -------------------------------


@pytest.mark.parametrize(
    "fixture", ["CHAOS_r12_timeline.json", "CHAOS_r14_timeline.json"]
)
def test_conformance_accepts_committed_chaos_timelines(fixture):
    """The regression pin: these timelines came from real (passing)
    cluster_chaos kill-restore runs — r12 shape and r14 --shm shape. A
    spec edit that rejects them has diverged from shipped behavior."""
    tl = load_timeline(str(FIXTURES / fixture))
    report = check_timeline(tl)
    assert report["violations"] == [], report["violations"][:10]
    assert report["events"] >= 100, "fixture lost its events"
    assert report["scopes"] >= 5, "fixture no longer routes to acceptors"


def test_conformance_cli_accepts_fixture_and_rejects_corruption(tmp_path):
    fixture = FIXTURES / "CHAOS_r12_timeline.json"
    r = subprocess.run(
        [sys.executable, str(TOOLS / "protospec" / "run_conformance.py"),
         str(fixture)],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    # corrupt the fixture: strip every lifecycle_resume so some node is
    # left paused — the monitor must go red, proving the fixture test
    # can actually fail
    doc = json.loads(fixture.read_text())
    doc["timeline"] = [
        e for e in doc["timeline"] if e["name"] != "lifecycle_resume"
    ]
    bad = tmp_path / "corrupt.json"
    bad.write_text(json.dumps(doc))
    r = subprocess.run(
        [sys.executable, str(TOOLS / "protospec" / "run_conformance.py"),
         str(bad)],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 1, r.stdout + r.stderr
    assert "paused" in r.stdout


# ---- conformance: synthetic forbidden orderings ---------------------------


def _ev(name, node=1, link=1, arg=0, detail=""):
    return {
        "t_ns": 0, "tier": "py", "name": name, "node": node, "link": link,
        "arg": arg, "detail": detail,
    }


def _violates(events, needle):
    report = check_timeline(events)
    assert report["violations"], f"accepted a timeline violating: {needle}"
    assert any(needle in v for v in report["violations"]), report["violations"]


def test_conformance_rejects_double_pause():
    _violates(
        [_ev("lifecycle_pause"), _ev("lifecycle_pause")],
        "double lifecycle_pause",
    )


def test_conformance_rejects_bare_resume():
    _violates([_ev("lifecycle_resume")], "while not paused")


def test_conformance_rejects_node_left_paused():
    _violates([_ev("lifecycle_pause")], "left paused")


def test_conformance_rejects_unpaused_capture():
    _violates([_ev("snap_shard")], "unpaused")


def test_conformance_rejects_window_traffic_after_teardown():
    _violates(
        [_ev("blackhole_teardown"), _ev("retransmit")],
        "after the link was torn down",
    )


def test_conformance_rejects_double_teardown():
    _violates(
        [_ev("blackhole_teardown"), _ev("blackhole_teardown")],
        "second blackhole_teardown",
    )


def test_conformance_rejects_resync_before_attach():
    _violates([_ev("sub_resync")], "before sub_attach")


def test_conformance_rejects_double_lane_up():
    _violates(
        [_ev("shm_lane_up"), _ev("shm_lane_up")],
        "shm_lane_up fired twice",
    )


def test_conformance_rejects_lane_up_after_fallback():
    _violates(
        [_ev("shm_fallback"), _ev("shm_lane_up")],
        "shm_lane_up after shm_fallback",
    )


def test_conformance_rejects_dead_stripe_reattach():
    _violates(
        [_ev("stripe_down", arg=2), _ev("stripe_down", arg=2)],
        "died twice",
    )


def test_conformance_rejects_drain_with_no_seal():
    _violates([_ev("drain_begin")], "no seal")


def test_conformance_rejects_nested_split_begin():
    _violates(
        [_ev("reshard_split_begin"), _ev("reshard_split_begin")],
        "nested reshard_split_begin",
    )


def test_conformance_rejects_overlapping_split_and_merge():
    _violates(
        [_ev("reshard_split_begin"), _ev("reshard_merge_begin")],
        "must not overlap",
    )


def test_conformance_rejects_reshard_done_without_begin():
    _violates([_ev("reshard_merge_done")], "without an open")


def test_conformance_accepts_open_split_at_end_of_run():
    # kill-restore chaos reuses node ids, so a killed node legitimately
    # leaves a begin open — the reshard acceptors carry no end-of-run
    # obligation (unlike pause/resume)
    report = check_timeline(
        [_ev("reshard_split_begin"), _ev("reshard_split_done"),
         _ev("reshard_split_begin")]
    )
    assert report["violations"] == [], report["violations"]


def test_conformance_rejects_grant_while_authority_in_flight():
    _violates(
        [_ev("reshard_master_begin"), _ev("reshard_grant", arg=1)],
        "in flight",
    )


def test_conformance_rejects_stale_minter_grant():
    _violates(
        [
            _ev("reshard_master_begin", node=1),
            _ev("reshard_master_done", node=2),
            _ev("reshard_grant", node=1, arg=1),
        ],
        "no-stale-minter",
    )


def test_conformance_rejects_nonmonotonic_grant_epoch():
    _violates(
        [_ev("reshard_grant", arg=3), _ev("reshard_grant", node=1, arg=3)],
        "epoch monotonicity",
    )


def test_conformance_master_acceptor_scope_is_global():
    # the authority acceptor must see the WHOLE timeline as one scope:
    # node 3's stale grant is only wrong relative to node 2's done, and
    # no single node observed both events
    _violates(
        [
            _ev("reshard_master_begin", node=1),
            _ev("reshard_master_done", node=2),
            _ev("reshard_grant", node=3, arg=5),
        ],
        "no-stale-minter",
    )


def test_conformance_accepts_legal_reshard_timeline():
    ok = [
        _ev("reshard_master_begin", node=1),
        _ev("reshard_master_done", node=2),
        _ev("reshard_grant", node=2, arg=1),
        _ev("reshard_split_begin", node=2),
        _ev("reshard_split_done", node=2),
        _ev("reshard_grant", node=2, arg=2),
        _ev("reshard_merge_begin", node=3),
        _ev("reshard_merge_done", node=3),
    ]
    report = check_timeline(ok)
    assert report["violations"] == [], report["violations"]


def test_conformance_accepts_legal_orderings():
    ok = [
        _ev("lifecycle_pause"),
        _ev("snap_shard"),
        _ev("lifecycle_resume"),
        _ev("sub_attach", link=2),
        _ev("sub_resync", link=2),
        _ev("retransmit", link=3),
        _ev("dedup_discard", link=3),
        _ev("blackhole_teardown", link=3),
        _ev("link_down", link=3),
        _ev("shm_lane_up", link=4),
        _ev("stripe_down", link=4, arg=0),
        _ev("stripe_down", link=4, arg=1),
        _ev("drain_begin", node=2),
        _ev("seal", node=2),
    ]
    report = check_timeline(ok)
    assert report["violations"] == [], report["violations"]
    assert report["scopes"] >= 6
