"""Device-tier codec-lab parity: the jitted JAX implementations
(ops/codec_lab_jax.py) must match the numpy lab (ops/codec_lab.py)
bit-for-bit on these seed-pinned trajectories — same scales, same packed
bytes, same decoded deltas — and keep the production codec's padding/idle
invariants. (Cross-tier scale parity carries the repo-wide octave-boundary
caveat documented in codec_lab_jax's module docstring.)"""

import jax.numpy as jnp
import numpy as np

from shared_tensor_tpu.ops import codec_lab_jax as lj
from shared_tensor_tpu.ops.codec import pad_flat
from shared_tensor_tpu.ops.codec_lab import Sign2, TopK
from shared_tensor_tpu.ops.packing import padded_len, wire_to_words, words_to_wire

N = 4096  # == padded_len(N): no pad lanes, so numpy-lab arrays align 1:1


def _r(seed, n=N):
    return np.random.default_rng(seed).standard_normal(n).astype(np.float32)


def test_sign2_parity_with_numpy_lab():
    r = _r(0)
    frame, new_np = Sign2().encode(r.copy())
    scale, words, new_jx = lj.sign2_quantize(jnp.asarray(r), N)
    assert float(scale) == frame.scale
    # identical wire bytes: LSB-first interleaved [sign, mag] bits, via the
    # real serialization helper (2*N "bit elements" on the wire)
    assert words_to_wire(np.asarray(words), 2 * N) == frame.data.tobytes()
    np.testing.assert_array_equal(np.asarray(new_jx), new_np)


def test_sign2_apply_parity_with_numpy_lab():
    r = _r(1)
    frame, _ = Sign2().encode(r.copy())
    delta_np = Sign2().decode(frame, N)
    vals = _r(2)
    out = lj.sign2_apply(
        jnp.asarray(vals),
        jnp.float32(frame.scale),
        jnp.asarray(wire_to_words(frame.data.tobytes(), 2 * N)),
        N,
    )
    np.testing.assert_array_equal(np.asarray(out), vals + delta_np)


def test_sign2_padding_and_idle_invariants():
    n = 1000
    n_pad = padded_len(n)
    r = pad_flat(jnp.asarray(_r(3, n)), n_pad)
    scale, words, new_r = lj.sign2_quantize(r, n)
    assert float(scale) > 0
    # pad lanes: residual stays exactly 0, both bits 0
    np.testing.assert_array_equal(np.asarray(new_r)[n:], 0.0)
    from shared_tensor_tpu.ops.packing import unpack_bits

    bits = np.asarray(unpack_bits(words)).reshape(n_pad, 2)
    np.testing.assert_array_equal(bits[n:], 0)
    # idle: zero residual -> untouched, apply with scale 0 is a no-op
    z = jnp.zeros(n_pad, jnp.float32)
    s0, w0, nr0 = lj.sign2_quantize(z, n)
    assert float(s0) == 0.0
    np.testing.assert_array_equal(np.asarray(nr0), 0.0)
    vals = pad_flat(jnp.asarray(_r(4, n)), n_pad)
    out = lj.sign2_apply(vals, s0, w0, n)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(vals))


def test_topk_parity_with_numpy_lab():
    k = N // 32
    r = _r(5)
    frame, new_np = TopK(k).encode(r.copy())
    idx, vals, new_jx = lj.topk_quantize(jnp.asarray(r), k)
    np.testing.assert_array_equal(np.asarray(new_jx), new_np)
    # same coordinate set (order may differ between top_k and argpartition)
    np_idx = frame.data[:, 0].view(np.uint32)
    assert set(np.asarray(idx).tolist()) == set(np_idx.tolist())
    # exact conservation on device too
    out = lj.topk_apply(jnp.asarray(new_jx), idx, vals, N)
    np.testing.assert_array_equal(np.asarray(out), r)


def test_topk_zero_residual_noop():
    z = jnp.zeros(N, jnp.float32)
    idx, vals, new_r = lj.topk_quantize(z, 8)
    np.testing.assert_array_equal(np.asarray(vals), 0.0)
    np.testing.assert_array_equal(np.asarray(new_r), 0.0)
    out = lj.topk_apply(jnp.asarray(_r(6)), idx, vals, N)
    np.testing.assert_array_equal(np.asarray(out), _r(6))
