"""The bench's no-chip fallback arm must always produce a valid, honest
number: it is what the driver records for the round if the TPU tunnel is
wedged (bench.py phase B). Runs the worker directly (fast — no supervisor
ladder, no chip attempts)."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_engine_worker_emits_valid_result():
    """Phase B's FIRST fallback arm: the native-engine 2-process loopback
    E2E (methodology-matched to the baseline's own E2E probe). It must
    attach the engine (a Python-tier rate must not masquerade as the engine
    number) and emit the standard schema."""
    env = {
        k: v
        for k, v in os.environ.items()
        if "axon" not in k.lower() and k != "PYTHONPATH"
    }
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env["ST_ENGINE_BENCH_S"] = "3"  # shrink the measure window for CI speed
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--worker", "engine"],
        capture_output=True,
        text=True,
        timeout=180,
        cwd=REPO,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "ST_BACKEND_UP cpu" in proc.stderr
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["metric"] == "sync_bandwidth_equiv_fp32_per_link"
    assert out["detail"]["codec"] == "engine-e2e"
    assert out["detail"]["backend"] == "cpu"
    # the engine E2E clears the baseline ~4x; require a generous fraction
    # even under parallel-suite load
    assert out["value"] > 0.4, out


def test_bench_host_worker_emits_valid_result():
    env = {
        k: v
        for k, v in os.environ.items()
        if "axon" not in k.lower() and k != "PYTHONPATH"
    }
    env["PYTHONPATH"] = REPO
    env["ST_TIMING_BUDGET_S"] = "3"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--worker", "host"],
        capture_output=True,
        text=True,
        timeout=120,
        cwd=REPO,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "ST_BACKEND_UP cpu" in proc.stderr
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["metric"] == "sync_bandwidth_equiv_fp32_per_link"
    assert out["detail"]["codec"] == "host"
    assert out["detail"]["backend"] == "cpu"
    # the host tier beats the reference codec ~5x per core; even a heavily
    # loaded run must clear a generous fraction of the baseline
    assert out["value"] > 0.2, out
