"""Native steady-state engine (native/stengine.cpp + comm/engine.py).

The engine is the production host-tier data plane (engine_eligible: host
tier, native protocol, no codec-tier pin), so the whole existing peer suite
already exercises it; these tests pin the engine-specific contracts — tier
parity, the Python fallback, the handoff accounting, and the throughput
claim that motivated it (round-3 verdict item 2: the Python tier's ~3 ms/
message interpreter floor capped 4 Ki tables at ~8.8 k frames/s against the
reference C loop's 78 k, reference src/sharedtensor.c:133-189).
"""

import os
import time

import numpy as np
import pytest

from shared_tensor_tpu import create_or_fetch
from shared_tensor_tpu.comm.engine import engine_eligible, load_engine
from shared_tensor_tpu.config import Config, TransportConfig

from _ports import free_port


pytestmark = pytest.mark.skipif(
    load_engine() is None, reason="native engine unavailable (no toolchain)"
)


def _mk(port, template, **cfg):
    return create_or_fetch(
        "127.0.0.1", port, template, config=Config(**cfg), timeout=30.0
    )


def test_engine_active_by_default_on_host_tier():
    assert engine_eligible(Config())
    port = free_port()
    with _mk(port, {"w": np.zeros(256, np.float32)}) as peer:
        assert peer._engine is not None, "host-tier peer should run the engine"


def test_engine_disabled_by_env(monkeypatch):
    monkeypatch.setenv("ST_NATIVE_ENGINE", "0")
    assert not engine_eligible(Config())
    port = free_port()
    with _mk(port, {"w": np.zeros(256, np.float32)}) as peer:
        assert peer._engine is None


def test_engine_disabled_by_codec_pin(monkeypatch):
    # an explicit tier pin (parity tests) must bypass the engine's C loops
    monkeypatch.setenv("ST_HOST_CODEC", "numpy")
    assert not engine_eligible(Config())


def _capture_engine_checkpoint(tmp_path):
    """Build a 2-node engine tree whose master has a GUARANTEED-nonzero
    link residual at save time: single-frame messages through a 2 KB/s
    token bucket pace the drain to ~15 frames/s, and the residual halves
    per frame (never reaching zero before f32 underflow), so a save ~0.3 s
    after the add always captures live link state. An unpaced engine
    drains 512 elements in microseconds — the race the cap removes."""
    from shared_tensor_tpu.utils import checkpoint as ckpt

    port = free_port()
    a = _mk(
        port,
        {"w": np.zeros(512, np.float32)},
        frame_burst=1,
        transport=TransportConfig(bandwidth_cap_bytes_per_sec=2000),
    )
    b = _mk(port, {"w": np.zeros(512, np.float32)})
    try:
        assert a._engine is not None
        # NON-constant delta: a constant one is the degenerate case (rms ==
        # every |element| == a power of two's mantissa -> one frame drains
        # it exactly); linspace keeps the residual halving for 100+ frames
        a.add({"w": np.linspace(0.1, 1.0, 512, dtype=np.float32)})
        time.sleep(0.2)
        path = str(tmp_path / "engine_peer.npz")
        ckpt.save_shared(a.st, path)
    finally:
        a.close()
        b.close()
    # expectations come from the FILE, not a re-snapshot: the paced link
    # keeps draining through the npz write, so live state taken after
    # save_shared can be one halving behind what was saved
    with np.load(path) as z:
        values = z["values"]
        links = {
            int(k.split("_", 1)[1]): z[k]
            for k in z.files
            if k.startswith("link_")
        }
    resid = links[min(links)]
    # the engine quantizes ahead of the paced wire (sendq depth + ACKed
    # frames): ~13 halvings by save time -> rms ~1e-4; the guard sits well
    # below that but far above f32 dust
    assert float(np.sqrt((resid * resid).mean())) > 1e-6, "resid drained"
    return path, values, links


def test_engine_checkpoint_restore_then_join(tmp_path):
    """load_shared's restore_state branch (engine tier: state lives in C)
    + the join seed: a peer joining AFTER the restore receives the full
    restored replica through the normal state-transfer handshake."""
    from shared_tensor_tpu.utils import checkpoint as ckpt

    path, values, _ = _capture_engine_checkpoint(tmp_path)
    port2 = free_port()
    a2 = _mk(port2, {"w": np.zeros(512, np.float32)})
    try:
        assert a2._engine is not None
        ckpt.load_shared(a2.st, path)
        np.testing.assert_array_equal(a2.st.snapshot_all()[0], values)
        b2 = _mk(port2, {"w": np.zeros(512, np.float32)})
        try:
            assert a2.drain(timeout=30.0, tol=1e-30)
            expect = values[:512]  # live lanes of the padded flat replica
            deadline = time.time() + 30
            while time.time() < deadline:
                if np.allclose(np.asarray(b2.read()["w"]), expect, atol=1e-5):
                    break
                time.sleep(0.05)
            np.testing.assert_allclose(
                np.asarray(b2.read()["w"]), expect, atol=1e-5
            )
        finally:
            b2.close()
    finally:
        a2.close()


def test_engine_checkpoint_restored_residual_streams(tmp_path):
    """Restoring onto a LIVE link must install the saved residual in the
    C engine AND mark the link dirty so it streams: the peer on the other
    end converges to exactly the restored residual's mass (its join
    predated the restore, so the residual is all it is owed)."""
    from shared_tensor_tpu.utils import checkpoint as ckpt

    path, values, links = _capture_engine_checkpoint(tmp_path)
    lid = min(links)
    port2 = free_port()
    a2 = _mk(port2, {"w": np.zeros(512, np.float32)})
    b2 = _mk(port2, {"w": np.zeros(512, np.float32)})
    try:
        assert a2._engine is not None and lid in a2.st.link_ids
        ckpt.load_shared(a2.st, path)
        # no snapshot probe of the restored residual: restore marks the
        # link dirty and the (uncapped) engine streams it away in
        # microseconds — b2's convergence below IS the proof it was
        # installed; only the replica is stable enough to compare
        np.testing.assert_array_equal(a2.st.snapshot_all()[0], values)
        assert a2.drain(timeout=30.0, tol=1e-30)
        expect = links[lid][:512]
        deadline = time.time() + 30
        while time.time() < deadline:
            if np.allclose(np.asarray(b2.read()["w"]), expect, atol=1e-5):
                break
            time.sleep(0.05)
        np.testing.assert_allclose(
            np.asarray(b2.read()["w"]), expect, atol=1e-5
        )
    finally:
        a2.close()
        b2.close()


def test_engine_vs_python_tier_convergence_parity():
    """Same workload through the engine and through the Python tier must
    reach the same fixed point (uniform deltas converge exactly — verify
    skill: 'known behaviors')."""
    finals = {}
    for native in (True, False):
        port = free_port()
        a = _mk(port, {"w": np.zeros(512, np.float32)}, native_engine=native)
        b = _mk(port, {"w": np.zeros(512, np.float32)}, native_engine=native)
        assert (a._engine is not None) == native
        a.add({"w": np.full(512, 0.75, np.float32)})
        b.add({"w": np.full(512, -0.25, np.float32)})
        deadline = time.time() + 30
        while time.time() < deadline:
            if np.allclose(a.read()["w"], 0.5) and np.allclose(
                b.read()["w"], 0.5
            ):
                break
            time.sleep(0.05)
        finals[native] = (a.read()["w"].copy(), b.read()["w"].copy())
        a.close()
        b.close()
    for native, (va, vb) in finals.items():
        np.testing.assert_allclose(va, 0.5, err_msg=f"native={native}")
        np.testing.assert_allclose(vb, 0.5, err_msg=f"native={native}")


def test_engine_drain_and_inflight_accounting():
    port = free_port()
    a = _mk(port, {"w": np.zeros(1024, np.float32)})
    b = _mk(port, {"w": np.zeros(1024, np.float32)})
    # diagnostic guard for a rare (~1 in 25 loaded suite runs) flake where
    # b stayed all-zero while a's drain succeeded: that combination implies
    # a had NO engine link to owe anything on (drain over zero links is
    # trivially true) — assert the attach actually happened so any
    # recurrence names the failing stage instead of the downstream compare
    assert a._engine is not None and len(a.st.link_ids) == 1, (
        a._engine, a.st.link_ids,
    )
    a.add({"w": np.linspace(-1, 1, 1024, dtype=np.float32)})
    assert a.drain(timeout=30.0), "drain must complete once residuals hit 0"
    assert a.st.inflight_total() == 0
    # everything a drained: b holds the sum
    np.testing.assert_allclose(
        b.read()["w"], np.linspace(-1, 1, 1024, dtype=np.float32), atol=1e-6
    )
    a.close()
    b.close()


def test_engine_graceful_leave_loses_nothing():
    port = free_port()
    a = _mk(port, {"w": np.zeros(256, np.float32)})
    b = _mk(port, {"w": np.zeros(256, np.float32)})
    b.add({"w": np.full(256, 2.5, np.float32)})
    assert b.drain(timeout=30.0)
    b.close()
    deadline = time.time() + 20
    while time.time() < deadline:
        if np.allclose(a.read()["w"], 2.5):
            break
        time.sleep(0.05)
    np.testing.assert_allclose(a.read()["w"], 2.5)
    a.close()


def test_engine_throughput_4ki_beats_python_floor():
    """Delivered frames/s at 4 Ki must clear the old Python-tier ceiling by
    a wide margin (measured: engine ~167 k/s vs Python ~8.8 k/s vs
    reference C 78 k/s on this class of box; threshold set far below the
    measurement for loaded-CI headroom but far above the Python tier).

    r11 note: the cascade quantizer drains a residual EXACTLY instead of
    free-running a junk tail, so a paced trickle of adds now idles the
    link between drains (correct behavior — fewer, full-value frames).
    Throughput is therefore measured under saturation: add back-to-back so
    the residual never quiesces, the regime the old 2 ms pacing happened
    to approximate before the codec got this efficient."""
    port = free_port()
    a = _mk(port, {"w": np.zeros(4096, np.float32)})
    b = _mk(port, {"w": np.zeros(4096, np.float32)})
    rng = np.random.default_rng(7)
    u = rng.standard_normal(4096).astype(np.float32)
    t_end = time.time() + 4.0
    f0 = b.st.frames_in
    t0 = time.time()
    while time.time() < t_end:
        a.add({"w": u})
    fps = (b.st.frames_in - f0) / (time.time() - t0)
    a.close()
    b.close()
    assert fps > 20_000, f"engine delivered only {fps:.0f} frames/s at 4Ki"


def test_counter_taxonomy_reconciles_across_layers():
    """Round-3 verdict Weak #6: the counters must reconcile, not just each
    be documented. Single-writer pair, drained: every dispatched codec
    frame was applied (frames_out == frames_in), every sent data message
    was acked (inflight 0, msgs_in matches msgs_out), and transport wire
    messages exceed data messages by exactly the control traffic (>=)."""
    port = free_port()
    a = _mk(port, {"w": np.zeros(2048, np.float32)})
    b = _mk(port, {"w": np.zeros(2048, np.float32)})
    # structured (homogeneous-magnitude) deltas: the residual reaches exact
    # zero in ~30 frames so drain(tol=0) completes — Gaussian tails instead
    # oscillate within +/-scale indefinitely (quirk Q3; verify skill
    # "known behaviors")
    for k in range(5):
        a.add({"w": np.linspace(-1 - k, 1 + k, 2048, dtype=np.float32)})
        time.sleep(0.05)
    # tol: staggered adds can leave SUBNORMAL residual dust (~1e-38),
    # which the pow2 scale policy flushes to idle — tol=0 would never
    # complete (see drain's docstring); 1e-30 is far below any real mass
    assert a.drain(timeout=30.0, tol=1e-30)
    time.sleep(0.5)  # b's final ACK/apply settles
    ma, mb = a.metrics(), b.metrics()
    # codec frames: all dispatched frames were applied at the receiver
    assert ma["st_frames_out_total"] == mb["st_frames_in_total"], (ma, mb)
    # data messages: everything sent was delivered and acknowledged
    assert ma["st_inflight_msgs"] == 0
    assert ma["st_msgs_out_total"] == mb["st_msgs_in_total"], (ma, mb)
    # transport wire messages include control traffic on top of data
    wire_out = sum(
        v for k, v in ma.items()
        if k.startswith("st_link_wire_msgs_out_total{")
    )
    assert wire_out >= ma["st_msgs_out_total"]
    # corruption-zeroed (all-zero-scale) frames count NOWHERE: a sender
    # never emits one (idle suppression), so counting it at the receiver
    # would present reconciliation drift exactly while an operator debugs
    # a corrupt link (the trust boundary zeroes non-finite scales)
    import types

    zeroed = types.SimpleNamespace(
        scales=np.zeros(1, np.float32),
        words=np.arange(2048 // 32, dtype=np.uint32),
    )
    fin = b.st.frames_in
    vals = np.asarray(b.read()["w"]).copy()
    b.st.receive_frames(b.node.links[0], [zeroed])
    assert b.st.frames_in == fin, "zeroed frame must not count as applied"
    np.testing.assert_array_equal(np.asarray(b.read()["w"]), vals)
    a.close()
    b.close()


@pytest.mark.parametrize("native", [True, False])
def test_engine_link_churn_loses_nothing(native):
    """Link-death churn: kill the child's uplink repeatedly while both
    sides add. Link death with both PROCESSES alive must lose nothing
    (first-hop delivery: unacked frames roll back into the LIVE carry
    slot — which keeps absorbing orphan-period adds — and the re-graft
    diff handshake re-derives the rest). Parametrized over both tiers:
    the engine's C carry and the Python tier's pseudo-link carry are
    separate implementations of the same contract."""
    port = free_port()
    a = _mk(port, {"w": np.zeros(512, np.float32)}, native_engine=native)
    b = _mk(port, {"w": np.zeros(512, np.float32)}, native_engine=native)
    assert (b._engine is not None) == native
    total = np.zeros(512, np.float32)
    try:
        for k in range(4):
            da = np.linspace(-1 - k, 1 + k, 512, dtype=np.float32)
            db = np.linspace(0.5 + k, -0.5 - k, 512, dtype=np.float32)
            a.add({"w": da})
            b.add({"w": db})
            total += da + db
            time.sleep(0.3)
            # kill the live link out from under the engine (transport-level
            # drop: both processes survive, re-graft re-derives the diff)
            links = b.node.links
            if links:
                b.node.drop_link(links[0])
            time.sleep(0.3)
        # wait for the final re-graft + convergence
        deadline = time.time() + 60
        while time.time() < deadline:
            va, vb = a.read()["w"], b.read()["w"]
            if np.allclose(va, total, atol=1e-4) and np.allclose(
                vb, total, atol=1e-4
            ):
                break
            time.sleep(0.2)
        np.testing.assert_allclose(a.read()["w"], total, atol=1e-4)
        np.testing.assert_allclose(b.read()["w"], total, atol=1e-4)
    finally:
        a.close()
        b.close()


@pytest.mark.parametrize("native", [True, False])
def test_engine_midstream_leave_loses_nothing(native):
    """peer.leave() mid-stream (seal -> drain -> close) must lose NOTHING
    even while siblings stream hard. The leaver MUST be an INTERIOR node
    (max_children=1 chain a <- b <- c): the loss window only exists there —
    a frame applied+ACKed at b floods into b's OTHER link's residual, and
    without the seal one landing between drain's last check and close dies
    with that residual while its sender, holding b's ACK, never re-sends.
    A leaf leaver floods nowhere and would pass seal-less. No hard kills
    here, so the final sum is EXACT. Parametrized over both tiers (the
    seal and the live carry have separate engine/Python implementations).
    """
    port = free_port()
    chain = dict(
        transport=TransportConfig(max_children=1), native_engine=native
    )
    a = _mk(port, {"w": np.zeros(1024, np.float32)}, **chain)
    b = _mk(port, {"w": np.zeros(1024, np.float32)}, **chain)
    c = _mk(port, {"w": np.zeros(1024, np.float32)}, **chain)
    # chain: master a took b; c was redirected through b — b is interior
    assert len(b.node.links) == 2, b.node.links
    total = np.zeros(1024, np.float64)
    stop = {"v": False}

    import threading

    def hammer(peer, seed):
        rng = np.random.default_rng(seed)
        while not stop["v"]:
            lo, hi = sorted(rng.uniform(-1, 1, size=2))
            d = np.linspace(lo, hi, 1024, dtype=np.float32)
            peer.add({"w": d})
            with lock:
                total_acc.append(d.astype(np.float64))
            time.sleep(0.01)

    lock = threading.Lock()
    total_acc: list = []
    threads = [
        threading.Thread(target=hammer, args=(a, 1)),
        threading.Thread(target=hammer, args=(c, 2)),
    ]
    for t in threads:
        t.start()
    time.sleep(0.5)
    b.add({"w": np.full(1024, 0.5, np.float32)})
    assert b.leave(timeout=30.0)  # mid-stream: a and c still hammering
    time.sleep(0.5)
    stop["v"] = True
    for t in threads:
        t.join()
    with lock:
        total = np.sum(total_acc, axis=0) + 0.5
    # quiesce and drain both survivors
    assert a.drain(timeout=60.0, tol=1e-30)
    assert c.drain(timeout=60.0, tol=1e-30)
    time.sleep(1.0)
    np.testing.assert_allclose(a.read()["w"], total, atol=1e-3)
    np.testing.assert_allclose(c.read()["w"], total, atol=1e-3)
    a.close()
    c.close()


def test_engine_forwards_unknown_messages_without_disruption():
    """An unknown message kind arriving on an engine-attached link must be
    forwarded to Python's control path (logged + dropped there) while the
    data stream keeps flowing — the engine owns only DATA/BURST/ACK."""
    port = free_port()
    a = _mk(port, {"w": np.zeros(256, np.float32)})
    b = _mk(port, {"w": np.zeros(256, np.float32)})
    try:
        link = b.node.links[0]
        for _ in range(3):
            b.node.send(link, bytes([99]) + b"garbage", timeout=1.0)
        b.add({"w": np.full(256, 1.25, np.float32)})
        deadline = time.time() + 20
        while time.time() < deadline:
            if np.allclose(a.read()["w"], 1.25):
                break
            time.sleep(0.05)
        np.testing.assert_allclose(a.read()["w"], 1.25)
        # and the reverse direction still works after the garbage
        a.add({"w": np.full(256, -0.25, np.float32)})
        deadline = time.time() + 20
        while time.time() < deadline:
            if np.allclose(b.read()["w"], 1.0):
                break
            time.sleep(0.05)
        np.testing.assert_allclose(b.read()["w"], 1.0)
    finally:
        a.close()
        b.close()
