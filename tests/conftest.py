"""Test environment: force an 8-device virtual CPU mesh.

The reference's dev story is N peers on one machine (SURVEY.md §4.1); ours is
the same plus N virtual devices in one process. Tests never need a real TPU —
Pallas kernels run in interpret mode on CPU, and the sharded/collective path
runs on the virtual device mesh. The identical tests pass unmodified on real
TPU hardware.

Must set env vars before jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
prev = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (
        prev + " --xla_force_host_platform_device_count=8"
    ).strip()
