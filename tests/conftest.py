"""Test environment: force an 8-device virtual CPU mesh.

The reference's dev story is N peers on one machine (SURVEY.md §4.1); ours is
the same plus N virtual devices in one process. Tests never need a real TPU —
Pallas kernels run in interpret mode on CPU, and the sharded/collective path
runs on the virtual device mesh. The identical tests pass unmodified on real
TPU hardware.

Must set env vars before jax is imported anywhere.
"""

import os

# Force, don't setdefault: the ambient environment pins JAX_PLATFORMS to the
# real TPU plugin; tests must run on the virtual CPU mesh regardless.
# ST_TEST_PLATFORM overrides (e.g. ST_TEST_PLATFORM=axon pytest ... to run
# the suite compiled on a real chip).
_platform = os.environ.get("ST_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
prev = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (
        prev + " --xla_force_host_platform_device_count=8"
    ).strip()

# A pytest plugin may have imported jax before this conftest ran, in which
# case the env var alone is too late; the config update below still works as
# long as no backend has been initialized yet (they init lazily).
import jax  # noqa: E402

jax.config.update("jax_platforms", _platform)
