"""Numpy host-codec tier vs the golden XLA table codec.

The numpy tier (ops/codec_np.py) is the production codec for CPU peers.
Sign bits, packing, and error feedback must be bit-identical to the golden
tier given the same scales; scales may differ by 1 ulp (different f32
summation order), so cross-tier checks deliver frames across tiers and
assert semantic equivalence.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from shared_tensor_tpu.config import ScalePolicy
from shared_tensor_tpu.ops import codec_np as NP
from shared_tensor_tpu.ops import table as T


def _tree(seed, mags=(1.0, 800.0, 0.005)):
    rng = np.random.default_rng(seed)
    return {
        f"l{i}": (rng.uniform(-m, m, size=s)).astype(np.float32)
        for i, (s, m) in enumerate(zip([(30, 50), (257,), (4, 9)], mags))
    }


@pytest.mark.parametrize("per_leaf", [True, False])
@pytest.mark.parametrize(
    "policy", [ScalePolicy.POW2_RMS, ScalePolicy.RMS, ScalePolicy.ABS_MEAN]
)
def test_quantize_np_matches_golden(per_leaf, policy):
    tree = _tree(1)
    spec = T.make_spec(tree)
    r = np.asarray(T.flatten(tree, spec))
    fg, rg = T.quantize_table(jnp.asarray(r), spec, policy, per_leaf, impl="xla")
    s_np, w_np, r_np = NP.quantize_table_np(r, spec, policy, per_leaf)
    # scales agree to 1 ulp; POW2 floor makes them exactly equal in practice
    np.testing.assert_allclose(s_np, np.asarray(fg.scales), rtol=3e-7)
    if np.array_equal(s_np, np.asarray(fg.scales)):
        np.testing.assert_array_equal(w_np, np.asarray(fg.words))
        np.testing.assert_array_equal(r_np, np.asarray(rg))


def test_apply_np_matches_golden():
    tree = _tree(2)
    spec = T.make_spec(tree)
    r = np.asarray(T.flatten(tree, spec))
    s, w, _ = NP.quantize_table_np(r, spec)
    arrays = tuple(np.asarray(T.flatten(_tree(10 + i), spec)) for i in range(3))
    out_np = NP.apply_table_many_np(arrays, s, w, spec)
    frame = T.TableFrame(jnp.asarray(s), jnp.asarray(w))
    out_g = T.apply_table_many(
        tuple(jnp.asarray(a) for a in arrays), frame, spec, impl="xla"
    )
    for a, b in zip(out_np, out_g):
        np.testing.assert_array_equal(a, np.asarray(b))


def test_cross_tier_link_convergence():
    """A link whose sender is the numpy tier and whose receiver is the XLA
    tier (and the reverse direction simultaneously) converges exactly like a
    same-tier link — the wire format is the contract, not the impl."""
    tree = _tree(3)
    spec = T.make_spec(tree)
    target = np.asarray(T.flatten(tree, spec))
    r_np = target.copy()  # numpy sender's residual
    v_xla = jnp.zeros(spec.total, jnp.float32)  # xla receiver's replica
    for _ in range(160):
        s, w, r_np = NP.quantize_table_np(r_np, spec)
        if not s.any():
            break
        v_xla = T.apply_table_many(
            (v_xla,), T.TableFrame(jnp.asarray(s), jnp.asarray(w)), spec, impl="xla"
        )[0]
    np.testing.assert_allclose(np.asarray(v_xla), target, rtol=1e-4, atol=1e-5)

    r_xla = jnp.asarray(target)  # xla sender
    v_np = np.zeros(spec.total, np.float32)  # numpy receiver
    for _ in range(160):
        f, r_xla = T.quantize_table(r_xla, spec, impl="xla")
        s = np.asarray(f.scales)
        if not s.any():
            break
        v_np = NP.apply_table_many_np((v_np,), s, np.asarray(f.words), spec)[0]
    np.testing.assert_allclose(v_np, target, rtol=1e-4, atol=1e-5)


def test_batch_np_equals_sequential():
    tree = _tree(4)
    spec = T.make_spec(tree)
    r = np.asarray(T.flatten(tree, spec))
    frames = []
    for _ in range(6):
        s, w, r = NP.quantize_table_np(r, spec)
        frames.append((s, w))
    v_seq = np.asarray(T.flatten(_tree(20), spec))
    for s, w in frames:
        v_seq = NP.apply_table_many_np((v_seq,), s, w, spec)[0]
    v_batch = NP.apply_table_batch_np(
        (np.asarray(T.flatten(_tree(20), spec)),),
        np.stack([s for s, _ in frames]),
        np.stack([w for _, w in frames]),
        spec,
    )[0]
    np.testing.assert_allclose(v_batch, v_seq, rtol=1e-6, atol=1e-6)


def test_accumulate_np_sanitizes():
    tree = {"a": np.zeros(100, np.float32)}
    spec = T.make_spec(tree)
    v = np.zeros(spec.total, np.float32)
    u = np.full(spec.total, np.nan, np.float32)
    (out,) = NP.accumulate_table_np((v,), u, spec)
    assert np.isfinite(out).all() and (out == 0).all()


def test_fused_quantize_partials_matches_separate_passes():
    """stc_quantize_ef_partials must equal stc_quantize followed by
    stc_scale_partials on the new residual: identical rout/words always;
    partials to tight float tolerance (summation order differs by design).
    Exercises whatever ISA path the host dispatches (AVX-512 where
    available — the production path this would otherwise leave untested)."""
    from shared_tensor_tpu.ops import codec_np as cn

    lib = cn._native()  # declares stc_quantize_ef_partials' signature
    if lib is None:
        pytest.skip("native codec unavailable")
    rng = np.random.default_rng(11)
    # ragged leaves: full words, partial tail word, padding — every loop arm
    template = {
        "a": np.zeros(300, np.float32),   # n % 32 != 0
        "b": np.zeros(1024, np.float32),  # whole words (AVX path)
        "c": np.zeros(7, np.float32),     # tiny tail-only leaf
    }
    from shared_tensor_tpu.ops.table import make_spec

    spec = make_spec(template)
    offs, ns, padded = cn._layout(spec)
    L = spec.num_leaves
    r = cn.flatten_np(
        {k: rng.standard_normal(v.shape).astype(np.float32) for k, v in template.items()},
        spec,
    )
    for scale_case in ("normal", "zero-leaf"):
        scales = cn.compute_scales_np(r, spec)
        if scale_case == "zero-leaf":
            scales = scales.copy()
            scales[0] = 0.0
        # separate passes
        out_a = np.empty(spec.total, np.float32)
        words_a = np.empty(spec.total // 32, np.uint32)
        lib.stc_quantize(r, out_a, offs, ns, padded, L, scales, words_a)
        amax_a = np.zeros(L); ss_a = np.zeros(L); sabs_a = np.zeros(L)
        lib.stc_scale_partials(out_a, offs, ns, L, amax_a, ss_a, sabs_a)
        # fused
        out_b = np.empty(spec.total, np.float32)
        words_b = np.empty(spec.total // 32, np.uint32)
        amax_b = np.zeros(L); ss_b = np.zeros(L); sabs_b = np.zeros(L)
        lib.stc_quantize_ef_partials(
            r, out_b, offs, ns, padded, L, scales, words_b,
            amax_b, ss_b, sabs_b,
        )
        np.testing.assert_array_equal(out_b, out_a, err_msg=scale_case)
        np.testing.assert_array_equal(words_b, words_a, err_msg=scale_case)
        np.testing.assert_array_equal(amax_b, amax_a, err_msg=scale_case)
        np.testing.assert_allclose(ss_b, ss_a, rtol=1e-12, err_msg=scale_case)
        np.testing.assert_allclose(
            sabs_b, sabs_a, rtol=1e-12, err_msg=scale_case
        )
        # aliased in-place form (how the engine calls it)
        out_c = r.copy()
        words_c = np.empty(spec.total // 32, np.uint32)
        amax_c = np.zeros(L); ss_c = np.zeros(L); sabs_c = np.zeros(L)
        lib.stc_quantize_ef_partials(
            out_c, out_c, offs, ns, padded, L, scales, words_c,
            amax_c, ss_c, sabs_c,
        )
        np.testing.assert_array_equal(out_c, out_a, err_msg=scale_case)
        np.testing.assert_array_equal(words_c, words_a, err_msg=scale_case)
