"""r16 cluster-sharded tensor (shared_tensor_tpu/shard).

What these tests pin down, in the order the subsystem composes:

- the shard map's geometry/epoch-merge discipline and the word-range
  slice codec's bit-compatibility with the main codec's apply rule
  (value += scale[leaf] * (1 - 2*bit) on live lanes, ±SAT saturation);
- the FWD wire frame: burst encode/decode round trip, the verbatim-
  relay restamp discipline, the spec-derived frame cap, and the
  corrupt-scale zeroing guard every other data kind already has;
- map negotiation over the tolerant SYNC/WELCOME hello: claims route up
  the tree, grants flood down, and every node converges on the union of
  the owned slices while holding ONLY its slice (the memory contract);
- mixed-tree interop in BOTH orientations (r14 discipline): a sharded
  joiner under a classic tree falls back to the full-replica protocol
  and still converges; a classic WRITER under a sharded tree is
  rejected LOUDLY (no node can seed a full replica — detectably broken,
  not silently wrong), while read-only subscribers interop fine;
- owner drain -> handoff: a leaving owner transfers slice + epoch +
  end-to-end dedup state to its parent, the cluster's routes flip, and
  no mass is lost or double-applied across the transfer;
- the sharded snapshot/restore round trip: per-node shard files, the
  MANIFEST.json exactly-one-owner coverage audit, and a killed owner
  restored from disk under takeover semantics with its values intact.
"""

import dataclasses
import os
import time

import numpy as np
import pytest

from shared_tensor_tpu.comm import wire
from shared_tensor_tpu.config import (
    Config,
    LifecycleConfig,
    ScalePolicy,
    ShardConfig,
    TransportConfig,
)
from shared_tensor_tpu.ops.table import make_spec
from shared_tensor_tpu.shard import (
    ShardGather,
    ShardMap,
    create_or_fetch_sharded,
)
from shared_tensor_tpu.shard.map import OwnerEntry
from shared_tensor_tpu.shard.state import SliceCodec
from shared_tensor_tpu.utils import checkpoint as ckpt
from tests._ports import free_port

TMPL = {
    "w": np.zeros(4096, np.float32),
    "b": np.zeros(512, np.float32),
}
SPEC = make_spec(TMPL)
TOTAL = SPEC.total  # padded element count
WORDS = TOTAL // 32


def _cfg(idx: int, n: int = 3, name: str = "", restore: str = "") -> Config:
    return Config(
        shard=ShardConfig(n_shards=n, shard_index=idx, restore_dir=restore),
        lifecycle=LifecycleConfig(node_name=name),
        transport=TransportConfig(peer_timeout_sec=20.0),
    )


def _flat_ref(tree: dict) -> np.ndarray:
    from shared_tensor_tpu.ops.codec_np import flatten_np

    return np.asarray(flatten_np(tree, SPEC), np.float32)


def _add_rounds(handles, rng, ref, rounds=3):
    for i in range(rounds):
        for h in handles:
            d = {
                "w": rng.standard_normal(4096).astype(np.float32),
                "b": rng.standard_normal(512).astype(np.float32),
            }
            ref["w"] += d["w"]
            ref["b"] += d["b"]
            h.add(d)


def _drain_all(handles, timeout=90.0):
    for h in handles:
        assert h.drain(timeout=timeout), "drain timed out"


def _gather_matches(source, ref, atol=2e-3):
    with ShardGather(source, TMPL) as g:
        tree = g.read_tree(max_staleness=60.0)
    np.testing.assert_allclose(tree["w"], ref["w"], atol=atol)
    np.testing.assert_allclose(tree["b"], ref["b"], atol=atol)


# ---- units: map / codec / wire --------------------------------------------


def test_shard_map_geometry_and_epoch_merge():
    m = ShardMap(WORDS, 3)
    assert m.validate() == []
    # contiguous exact cover, word->shard agrees with the ranges
    lo = 0
    for k, (wlo, wcnt) in enumerate(m.ranges):
        assert wlo == lo
        assert m.shard_of_word(wlo) == k
        assert m.shard_of_word(wlo + wcnt - 1) == k
        lo = wlo + wcnt
    assert lo == WORDS
    # epoch merge: higher epoch wins, lower/equal is ignored
    assert m.merge_entry(1, OwnerEntry(2, 7, "h", 1))
    assert not m.merge_entry(1, OwnerEntry(2, 9, "x", 2))
    assert not m.merge_entry(1, OwnerEntry(1, 9, "x", 2))
    assert m.owners[1].owner == 7
    # doc round trip preserves owners; geometry mismatch is loud
    m2 = ShardMap.from_doc(m.as_doc())
    assert m2.owners[1].epoch == 2
    with pytest.raises(ValueError, match="geometry"):
        m2.merge_doc(ShardMap(WORDS, 4).as_doc())


def test_slice_codec_quantize_apply_bit_compat():
    """One quantize step's wire frame applies back EXACTLY like the main
    codec rule, and error feedback makes the ladder lossless: target
    converges to the original residual mass."""
    rng = np.random.default_rng(7)
    c = SliceCodec(SPEC, WORDS // 3, WORDS // 3)
    resid = (rng.standard_normal(c.n_el) * c.live).astype(np.float32)
    want = resid.copy()
    target = np.zeros(c.n_el, np.float32)
    for _ in range(6000):
        scales, words, resid = c.quantize(resid, ScalePolicy.POW2_RMS)
        if not scales.any():
            break
        # the explicit apply rule, element by element
        bits = np.unpackbits(
            words.view(np.uint8), bitorder="little"
        ).astype(np.float32)
        manual = target + scales[c.leaf_of] * c.live * (1.0 - 2.0 * bits)
        c.apply(target, scales, words)
        np.testing.assert_array_equal(target, manual.astype(np.float32))
    # the documented drain caveat (state.py): the ladder goes idle when
    # each segment's RMS pow2-floors to 0 (rms < 2^-126); single elements
    # can sit up to ~sqrt(n_live) above that — still denormal dust
    assert float(np.max(np.abs(resid))) < 2.0**-126 * np.sqrt(c.n_el)
    np.testing.assert_allclose(target, want, atol=5e-5)


def test_fwd_wire_roundtrip_restamp_and_caps():
    rng = np.random.default_rng(3)
    wcnt = WORDS // 3
    L = SPEC.num_leaves
    frames = [
        (
            rng.standard_normal(L).astype(np.float32) ** 2,
            rng.integers(0, 2**32, wcnt, dtype=np.uint32),
        )
        for _ in range(5)
    ]
    payload = wire.encode_fwd(frames, 4, seq=9, origin=42, fwd_seq=1234)
    assert payload[0] == wire.FWD
    got, word_lo, seq, origin, fwd_seq = wire.decode_fwd(payload, SPEC)
    assert (word_lo, seq, origin, fwd_seq) == (4, 9, 42, 1234)
    assert len(got) == 5
    for (s0, w0), (s1, w1) in zip(frames, got):
        np.testing.assert_array_equal(s0, s1)
        np.testing.assert_array_equal(w0, w1)
    # relay restamp touches ONLY the per-link seq; the end-to-end
    # identity and every frame byte stay verbatim
    buf = bytearray(payload)
    wire.fwd_restamp(buf, 77)
    got2, _wlo, seq2, origin2, fwd2 = wire.decode_fwd(bytes(buf), SPEC)
    assert (seq2, origin2, fwd2) == (77, 42, 1234)
    np.testing.assert_array_equal(got2[0][0], got[0][0])
    # a non-finite scale zeroes its leaf instead of NaN-ing the owner
    bad = bytearray(payload)
    np.frombuffer(bad, "<f4", count=L, offset=wire.FWD_HDR)  # layout check
    bad[wire.FWD_HDR : wire.FWD_HDR + 4] = np.float32("nan").tobytes()
    gotb, *_ = wire.decode_fwd(bytes(bad), SPEC)
    assert gotb[0][0][0] == 0.0
    # spec-derived cap: always >= 1, never exceeds the receive bound
    cap = wire.fwd_frames_cap(SPEC, wcnt)
    assert 1 <= cap <= wire.FWD_BURST_FRAMES
    per = 4 * L + 4 * wcnt
    assert wire.FWD_HDR + cap * per <= wire.frame_wire_bytes(SPEC)
    # truncated / ragged bodies are rejected, not misparsed
    with pytest.raises(ValueError):
        wire.decode_fwd(payload[:-3], SPEC)


# ---- cluster: negotiation, convergence, memory contract -------------------


def test_map_negotiation_and_owner_routed_convergence():
    """3 nodes claim 3 shards through the SYNC/WELCOME hello; every
    node's out-of-shard writes ride owner-routed FWD frames (relayed,
    never re-quantized) and the cluster converges on the union — while
    NO node ever holds the full table (the memory contract)."""
    port = free_port()
    handles = [
        create_or_fetch_sharded("127.0.0.1", port, TMPL, _cfg(i))
        for i in range(3)
    ]
    try:
        assert all(h.sharded for h in handles)
        m = handles[0].node.map_doc()
        assert ShardMap.from_doc(m).fully_owned()
        rng = np.random.default_rng(0)
        ref = {"w": np.zeros(4096, np.float32),
               "b": np.zeros(512, np.float32)}
        _add_rounds(handles, rng, ref, rounds=3)
        _drain_all(handles)
        # per-node resident state is the owned slice (plus drained
        # outboxes = freed): strictly below half the full table
        full = TOTAL * 4
        for h in handles:
            assert h.node.alloc_bytes() < full // 2
            assert h.node.owned_words() < WORDS
        assert sum(h.node.owned_words() for h in handles) == WORDS
        _gather_matches(handles[0].node, ref)
        # owner routing actually relayed (leaf->leaf crosses the master)
        relayed = sum(
            int(h.node.metrics().get("st_shard_fwd_relayed_total", 0))
            for h in handles
        )
        assert relayed > 0
    finally:
        for h in reversed(handles):
            h.close()


def test_partial_gather_reads_covering_shards_only():
    port = free_port()
    handles = [
        create_or_fetch_sharded("127.0.0.1", port, TMPL, _cfg(i, n=2))
        for i in range(2)
    ]
    try:
        ref = {"w": np.zeros(4096, np.float32),
               "b": np.zeros(512, np.float32)}
        rng = np.random.default_rng(5)
        _add_rounds(handles, rng, ref, rounds=2)
        _drain_all(handles)
        flat_ref = _flat_ref(ref)
        lo, hi = 100, 1500  # inside shard 0 only
        with ShardGather(handles[0].node, TMPL, elements=(lo, hi)) as g:
            assert len(g.legs) == 1
            flat, worst = g.read(max_staleness=60.0)
        assert np.isfinite(worst)
        np.testing.assert_allclose(flat, flat_ref[lo:hi], atol=2e-3)
    finally:
        for h in reversed(handles):
            h.close()


# ---- mixed-tree interop, both orientations --------------------------------


def test_sharded_joiner_falls_back_under_classic_tree():
    from shared_tensor_tpu.comm.peer import create_or_fetch

    port = free_port()
    classic = create_or_fetch("127.0.0.1", port, TMPL, Config())
    try:
        h = create_or_fetch_sharded("127.0.0.1", port, TMPL, _cfg(1))
        try:
            assert not h.sharded  # tolerant fallback, not an error
            d = np.ones(4096, np.float32)
            h.add({"w": d, "b": np.zeros(512, np.float32)})
            deadline = time.time() + 30
            while time.time() < deadline:
                if np.allclose(
                    np.asarray(classic.read()["w"]), d, atol=1e-3
                ):
                    break
                time.sleep(0.05)
            np.testing.assert_allclose(
                np.asarray(classic.read()["w"]), d, atol=1e-3
            )
        finally:
            h.close()
    finally:
        classic.close()


def test_classic_writer_rejected_by_sharded_tree_loudly():
    from shared_tensor_tpu.comm.peer import SpecMismatch, create_or_fetch

    port = free_port()
    h0 = create_or_fetch_sharded("127.0.0.1", port, TMPL, _cfg(0, n=2))
    try:
        assert h0.sharded
        with pytest.raises(ConnectionError, match="sharded") as ei:
            p = create_or_fetch(
                "127.0.0.1", port, TMPL, Config(), timeout=15
            )
            p.close()
        assert isinstance(ei.value, SpecMismatch)
    finally:
        h0.close()


def test_read_only_subscriber_interops_with_sharded_owner():
    from shared_tensor_tpu.serve.subscriber import Subscriber

    port = free_port()
    handles = [
        create_or_fetch_sharded("127.0.0.1", port, TMPL, _cfg(i, n=2))
        for i in range(2)
    ]
    try:
        ref = {"w": np.zeros(4096, np.float32),
               "b": np.zeros(512, np.float32)}
        rng = np.random.default_rng(11)
        _add_rounds(handles, rng, ref, rounds=2)
        _drain_all(handles)
        s_lo, s_hi = handles[0].node.map.element_range(0)
        lo, hi = s_lo + 32, min(s_hi, s_lo + 1056)
        cfg = Config()
        cfg = dataclasses.replace(
            cfg, serve=dataclasses.replace(cfg.serve, range=(lo, hi))
        )
        # the rendezvous port is the master — owner of shard 0
        with Subscriber("127.0.0.1", port, TMPL, cfg) as sub:
            sub.wait_ready(30.0)
            deadline = time.time() + 30
            flat_ref = _flat_ref(ref)
            while time.time() < deadline:
                flat, _st, _ver = sub.read_flat(60.0)
                p_lo, _p_hi = sub.range_elements
                got = flat[lo - p_lo : hi - p_lo]
                if np.allclose(got, flat_ref[lo:hi], atol=2e-3):
                    break
                time.sleep(0.05)
            np.testing.assert_allclose(got, flat_ref[lo:hi], atol=2e-3)
    finally:
        for h in reversed(handles):
            h.close()


# ---- drain-handoff --------------------------------------------------------


def test_owner_drain_handoff_preserves_mass_and_routes():
    """A leaving owner hands its slice to the parent; the successor owns
    it at a HIGHER epoch, the full view is preserved, and post-handoff
    writes toward the moved shard land at the successor."""
    port = free_port()
    handles = [
        create_or_fetch_sharded("127.0.0.1", port, TMPL, _cfg(i))
        for i in range(3)
    ]
    try:
        rng = np.random.default_rng(2)
        ref = {"w": np.zeros(4096, np.float32),
               "b": np.zeros(512, np.float32)}
        _add_rounds(handles, rng, ref, rounds=2)
        _drain_all(handles)
        leaver = handles[2]
        moved = leaver.node.owned_shards()
        assert moved
        epoch_before = leaver.node.map.owners[moved[0]].epoch
        assert leaver.leave(timeout=60.0)
        live = handles[:2]
        # the successor (the leaver's parent) owns the moved shard now
        deadline = time.time() + 30
        while time.time() < deadline:
            owners = {
                s for h in live for s in h.node.owned_shards()
            }
            if set(moved) <= owners:
                break
            time.sleep(0.05)
        all_owned = sorted(
            s for h in live for s in h.node.owned_shards()
        )
        assert all_owned == list(range(3)), all_owned
        succ = next(
            h for h in live if set(moved) <= set(h.node.owned_shards())
        )
        assert succ.node.map.owners[moved[0]].epoch > epoch_before
        assert (
            int(succ.node.metrics().get("st_shard_handoffs_total", 0)) > 0
        )
        _gather_matches(succ.node, ref)
        # post-handoff writes toward the moved shard land and converge
        _add_rounds(live, rng, ref, rounds=1)
        _drain_all(live)
        _gather_matches(live[0].node, ref)
    finally:
        for h in reversed(handles):
            try:
                h.close()
            except Exception:
                pass


# ---- snapshot / restore ---------------------------------------------------


def test_sharded_snapshot_restore_roundtrip(tmp_path):
    """Quiesced capture -> MANIFEST.json with per-shard rows -> coverage
    audit clean -> kill an owner -> restore from disk under takeover
    semantics: the reborn node re-claims its shard at a higher epoch
    with its values intact, and the cluster converges again."""
    snap = str(tmp_path / "snap")
    port = free_port()
    h0 = create_or_fetch_sharded(
        "127.0.0.1", port, TMPL, _cfg(0, n=2, name="m")
    )
    h1 = create_or_fetch_sharded(
        "127.0.0.1", port, TMPL, _cfg(1, n=2, name="n1")
    )
    try:
        rng = np.random.default_rng(4)
        ref = {"w": np.zeros(4096, np.float32),
               "b": np.zeros(512, np.float32)}
        _add_rounds([h0, h1], rng, ref, rounds=2)
        _drain_all([h0, h1])
        entries = [
            e
            for e in (h.node.save_shards(snap) for h in (h0, h1))
            if e is not None
        ]
        assert len(entries) == 2
        assert all(e["shards"] for e in entries)
        ckpt.write_manifest(snap, "r16-test", entries)
        assert ckpt.verify_shard_coverage(snap, 2) == []
        # an N-shard audit against a SHORT manifest is loud
        assert ckpt.verify_shard_coverage(snap, 3) != []

        before = h1.node.owned_shards()
        h1.close()  # hard kill: no handoff, no drain
        h1 = None
        h1 = create_or_fetch_sharded(
            "127.0.0.1",
            port,
            TMPL,
            _cfg(1, n=2, name="n1", restore=snap),
        )
        assert h1.sharded
        deadline = time.time() + 30
        while time.time() < deadline:
            if h1.node.owned_shards() == before:
                break
            time.sleep(0.05)
        assert h1.node.owned_shards() == before
        _gather_matches(h0.node, ref)
        # the restored node keeps serving writes
        _add_rounds([h0, h1], rng, ref, rounds=1)
        _drain_all([h0, h1])
        _gather_matches(h1.node, ref)
    finally:
        for h in (h1, h0):
            if h is not None:
                h.close()


def test_st_shard_0_pins_classic_protocol(monkeypatch):
    from shared_tensor_tpu.comm.peer import SharedTensorPeer

    monkeypatch.setenv("ST_SHARD", "0")
    port = free_port()
    h = create_or_fetch_sharded("127.0.0.1", port, TMPL, _cfg(0))
    try:
        assert not h.sharded
        assert isinstance(h.peer, SharedTensorPeer)
    finally:
        h.close()


def test_deposit_twins_expose_saturated_writer():
    # r19 writer-side heat twins: a lone master owning shard 0 of 2 has
    # no owner to drain shard 1's outbox toward, so every add() with
    # shard-1 mass coalesces into ONE pending residual — the
    # post-coalesce st_shard_fwd_msgs_out_total flatlines while the
    # pre-coalesce st_shard_heat_deposit_* twins keep counting the true
    # write pressure (the saturation signature the gauges exist for)
    from shared_tensor_tpu.obs import schema as _sch

    port = free_port()
    h = create_or_fetch_sharded("127.0.0.1", port, TMPL, _cfg(0, n=2))
    try:
        assert h.sharded
        node = h.node
        elo, ehi = node.map.element_range(1)
        seg_bytes = (ehi - elo) * 4
        # leaves flatten alphabetically (b then w), so w's TAIL is what
        # lands in shard 1's element range
        d = {
            "w": np.zeros(4096, np.float32),
            "b": np.zeros(512, np.float32),
        }
        d["w"][-1] = 1.0
        for _ in range(8):
            h.add(d)
        out = node._collect()
        assert out[_sch.shard_key("st_shard_heat_deposit_msgs", 1)] == 8
        assert (
            out[_sch.shard_key("st_shard_heat_deposit_bytes", 1)]
            == 8 * seg_bytes
        )
        # saturated: nothing drained, the coalesced residual is all there is
        assert out.get("st_shard_fwd_msgs_out_total", 0) == 0
        # owned in-shard applies never count as deposits (b flattens
        # into shard 0's range, which this lone master owns)
        h.add({
            "w": np.zeros(4096, np.float32),
            "b": np.ones(512, np.float32),
        })
        out = node._collect()
        assert _sch.shard_key("st_shard_heat_deposit_msgs", 0) not in out
    finally:
        h.close()
