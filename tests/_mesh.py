"""make_mesh for tests: skip (not fail) when the backend is too small.

The suite normally runs on the 8-device virtual CPU mesh (conftest.py), where
every mesh shape fits. Under ``ST_TEST_PLATFORM=axon`` the same tests compile
on the real chip — of which this environment has exactly one — so tests whose
mesh needs more devices than exist must SKIP, exactly like the existing
8-device guard in test_hierarchical.py, rather than fail the on-chip run.
"""

import os

import pytest

from shared_tensor_tpu.parallel.mesh import make_mesh as _make_mesh

# Only a deliberate real-hardware run may shrink the suite. On the default
# virtual CPU mesh a too-small backend means the 8-device setup itself broke,
# and that must FAIL, not quietly skip the whole sharded/collective tier.
_REAL_HW = os.environ.get("ST_TEST_PLATFORM", "cpu") != "cpu"


def make_mesh(n_peer=None, n_shard: int = 1, **kw):
    try:
        return _make_mesh(n_peer, n_shard, **kw)
    except ValueError as e:
        if _REAL_HW and "needs" in str(e) and "devices" in str(e):
            pytest.skip(str(e))
        raise
