"""The native codec's worker pool and the k-frame fused apply kernel.

Contracts pinned here (stcodec.c "worker pool" + "k-frame fused apply"
headers):
  - every elementwise op (quantize, apply, add) is BIT-exact under any
    ST_CODEC_THREADS value — chunk boundaries never change results;
  - scale partials are deterministic per layout (fixed 2 Mi-element chunk
    grouping) and within the documented ~1-ulp tier tolerance of the
    serial pass; with the production POW2_RMS policy the resulting scales
    are exactly equal in practice;
  - stc_apply_frames is bit-identical to BOTH legacy receive paths: the
    k = 1 fused single-frame apply and the k > 1 accumulate-delta + add
    pipeline (same per-element summation order by construction), and its
    fused partials match a standalone rescan of its output.

The thread-count cases run in subprocesses because the pool caches
ST_CODEC_THREADS at first use (one pool per process for its lifetime).
"""

import ctypes
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from shared_tensor_tpu.ops import codec_np as NP
from shared_tensor_tpu.ops import table as T

pytestmark = pytest.mark.skipif(
    NP._native() is None, reason="native codec unavailable"
)


def _layout_arrays(spec):
    return NP._layout(spec)


def _big_tree(seed):
    # one leaf above the 4 Mi parallel threshold + an odd-sized straggler,
    # so chunked dispatch, partial words, and padding all engage
    rng = np.random.default_rng(seed)
    return {
        "w": rng.standard_normal(5 * 1024 * 1024 + 17).astype(np.float32),
        "b": rng.standard_normal(1000).astype(np.float32),
    }


_CHILD = r"""
import json, sys
import numpy as np
from shared_tensor_tpu.ops import codec_np as NP
from shared_tensor_tpu.ops import table as T

rng = np.random.default_rng(7)
tree = {
    "w": rng.standard_normal(5 * 1024 * 1024 + 17).astype(np.float32),
    "b": rng.standard_normal(1000).astype(np.float32),
}
spec = T.make_spec(tree)
flat = NP.flatten_np(tree, spec)
lib = NP._native()
assert lib is not None
offs, ns, padded = NP._layout(spec)
L = spec.num_leaves

s = NP.compute_scales_np(flat, spec)
out = np.empty(spec.total, np.float32)
words = np.zeros(spec.total // 32, np.uint32)
lib.stc_quantize(flat, out, offs, ns, padded, L, s, words)
ap = np.empty(spec.total, np.float32)
lib.stc_apply_frame(flat, ap, offs, ns, padded, L, s, words)
am = np.zeros(L); ss = np.zeros(L); sb = np.zeros(L)
lib.stc_scale_partials(out, offs, ns, L, am, ss, sb)
au = np.empty(spec.total, np.float32)
lib.stc_accumulate_update_to(au, flat, out, offs, ns, padded, L)

import hashlib
def h(a):
    return hashlib.sha256(np.ascontiguousarray(a).tobytes()).hexdigest()
print(json.dumps({
    "scales": s.tolist(),
    "h_out": h(out), "h_words": h(words), "h_ap": h(ap), "h_au": h(au),
    "ss": ss.tolist(), "sabs": sb.tolist(), "amax": am.tolist(),
}))
"""


def _run_child(threads: int) -> dict:
    env = dict(os.environ, ST_CODEC_THREADS=str(threads), JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-c", _CHILD],
        env=env,
        capture_output=True,
        text=True,
        timeout=240,
    )
    assert r.returncode == 0, r.stderr
    return json.loads(r.stdout.strip().splitlines()[-1])


def test_threaded_codec_matches_serial_bitwise():
    serial = _run_child(1)
    threaded = _run_child(4)  # forced: correctness is core-count-independent
    # elementwise outputs: bit-exact under any split
    for key in ("h_out", "h_words", "h_ap", "h_au"):
        assert serial[key] == threaded[key], key
    # production scales: pow2 floor absorbs the ~1-ulp partial difference
    assert serial["scales"] == threaded["scales"]
    # partials: deterministic chunk grouping, tier tolerance vs serial
    np.testing.assert_allclose(serial["ss"], threaded["ss"], rtol=1e-9)
    np.testing.assert_allclose(serial["sabs"], threaded["sabs"], rtol=1e-9)
    np.testing.assert_allclose(serial["amax"], threaded["amax"], rtol=0)


def _quantize_frames(flat, spec, k):
    """k successive error-feedback frames off one residual."""
    lib = NP._native()
    offs, ns, padded = _layout_arrays(spec)
    L = spec.num_leaves
    r = flat.copy()
    scales = np.zeros((k, L), np.float32)
    words = np.zeros((k, spec.total // 32), np.uint32)
    for f in range(k):
        s = NP.compute_scales_np(r, spec)
        out = np.empty(spec.total, np.float32)
        lib.stc_quantize(r, out, offs, ns, padded, L, s, words[f])
        scales[f] = s
        r = out
    return scales, words


@pytest.mark.parametrize("k", [1, 2, 5])
def test_apply_frames_bitwise_matches_legacy_paths(k):
    tree = {
        "a": np.linspace(-3, 3, 30 * 50, dtype=np.float32).reshape(30, 50),
        "b": (np.arange(257, dtype=np.float32) - 128) / 7,
    }
    spec = T.make_spec(tree)
    flat = NP.flatten_np(tree, spec)
    lib = NP._native()
    offs, ns, padded = _layout_arrays(spec)
    L = spec.num_leaves
    scales, words = _quantize_frames(flat, spec, k)
    # zero one frame's scales entirely (idle/corruption-zeroed frame) and,
    # for k > 1, one single leaf of another frame (per-leaf idle)
    if k > 1:
        scales[1] = 0.0
        scales[0][1] = 0.0

    target = NP.flatten_np(
        {
            "a": np.full((30, 50), 0.25, np.float32),
            "b": np.full(257, -1.5, np.float32),
        },
        spec,
    )

    # legacy delta path
    delta = np.zeros(spec.total, np.float32)
    for f in range(k):
        if not scales[f].any():
            continue
        lib.stc_accumulate_delta(
            delta, offs, ns, padded, L, scales[f], words[f]
        )
    want = np.empty(spec.total, np.float32)
    lib.stc_add_to(want, target, delta, spec.total)

    got = np.empty(spec.total, np.float32)
    lib.stc_apply_frames(
        target, got, offs, ns, padded, L, spec.total // 32, k,
        np.ascontiguousarray(scales), np.ascontiguousarray(words),
        None, None, None,
    )
    np.testing.assert_array_equal(got, want)

    if k == 1:
        # also bit-identical to the k=1 fused single-frame apply
        want1 = np.empty(spec.total, np.float32)
        lib.stc_apply_frame(
            target, want1, offs, ns, padded, L, scales[0], words[0]
        )
        np.testing.assert_array_equal(got, want1)

    # fused partials == standalone rescan of the output
    am = np.zeros(L)
    ssq = np.zeros(L)
    sab = np.zeros(L)
    got2 = np.empty(spec.total, np.float32)
    lib.stc_apply_frames(
        target, got2, offs, ns, padded, L, spec.total // 32, k,
        np.ascontiguousarray(scales), np.ascontiguousarray(words),
        am.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ssq.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        sab.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
    )
    np.testing.assert_array_equal(got2, got)
    am2 = np.zeros(L)
    ss2 = np.zeros(L)
    sb2 = np.zeros(L)
    lib.stc_scale_partials(got, offs, ns, L, am2, ss2, sb2)
    np.testing.assert_allclose(ssq, ss2, rtol=1e-12)
    np.testing.assert_allclose(sab, sb2, rtol=1e-12)
    np.testing.assert_array_equal(am, am2)


def test_accumulate_update_to_partials_matches_rescan():
    tree = _big_tree(11)
    spec = T.make_spec(tree)
    flat = NP.flatten_np(tree, spec)
    upd = NP.flatten_np(_big_tree(12), spec)
    # poison the update with the sanitizer's cases
    upd[3] = np.nan
    upd[70] = np.inf
    upd[71] = -np.inf
    lib = NP._native()
    offs, ns, padded = _layout_arrays(spec)
    L = spec.num_leaves
    am = np.zeros(L)
    ssq = np.zeros(L)
    sab = np.zeros(L)
    got = np.empty(spec.total, np.float32)
    lib.stc_accumulate_update_to_partials(
        got, flat, upd, offs, ns, padded, L, am, ssq, sab
    )
    want = np.empty(spec.total, np.float32)
    lib.stc_accumulate_update_to(want, flat, upd, offs, ns, padded, L)
    np.testing.assert_array_equal(got, want)
    am2 = np.zeros(L)
    ss2 = np.zeros(L)
    sb2 = np.zeros(L)
    lib.stc_scale_partials(got, offs, ns, L, am2, ss2, sb2)
    np.testing.assert_allclose(ssq, ss2, rtol=1e-9)
    np.testing.assert_allclose(sab, sb2, rtol=1e-9)
    np.testing.assert_array_equal(am, am2)


def test_host_tier_batch_apply_uses_fused_kernel():
    """apply_table_batch_np's k>1 result is unchanged by the kernel swap
    (regression pin: fused kernel vs the numpy semantic reference)."""
    tree = _tree_small(3)
    spec = T.make_spec(tree)
    flat = NP.flatten_np(tree, spec)
    scales, words = _quantize_frames(flat, spec, 3)
    arrays = tuple(
        NP.flatten_np(_tree_small(20 + i), spec) for i in range(2)
    )
    got = NP.apply_table_batch_np(arrays, scales, words, spec)
    # numpy semantic reference (force the no-native path)
    lib, NP._LIB = NP._LIB, None
    try:
        want = NP.apply_table_batch_np(arrays, scales, words, spec)
    finally:
        NP._LIB = lib
    for g, w in zip(got, want):
        np.testing.assert_allclose(g, w, rtol=0, atol=0)


def _tree_small(seed):
    rng = np.random.default_rng(seed)
    return {
        "a": rng.standard_normal((30, 50)).astype(np.float32),
        "b": rng.standard_normal(257).astype(np.float32),
    }
