"""Pod-tier (ICI) sync tests on the 8-device virtual CPU mesh.

SURVEY.md §4.2 tier 2: the sharded/collective path runs on
``--xla_force_host_platform_device_count=8`` CPU devices; identical code runs
on a real v5e-8. The semantic yardsticks come from the reference codec
(SURVEY.md §6.2 convergence table, Appendix B).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shared_tensor_tpu.config import ScalePolicy
from shared_tensor_tpu.ops.table import (
    apply_table,
    make_spec,
    flatten,
    quantize_table,
)
from shared_tensor_tpu.parallel import (
    add_updates,
    build_sync_step,
    frame_ici_bytes,
    init_state,
    read_peer,
    rows_per_shard,
)
from shared_tensor_tpu.parallel.mesh import make_mesh as make_mesh_strict
from tests._mesh import make_mesh


def template(key=0, shape=(40, 64)):
    k1, k2 = jax.random.split(jax.random.PRNGKey(key))
    return {
        "w": jax.random.normal(k1, shape, jnp.float32),
        "b": jax.random.normal(k2, (shape[1],), jnp.float32) * 1e-3,
    }


def test_mesh_shapes():
    assert rows_per_shard(2048, 4) == 4
    with pytest.raises(ValueError):
        rows_per_shard(1024, 3)  # 8 rows not divisible by 3
    with pytest.raises(ValueError):
        make_mesh_strict(16, 1)  # more devices than exist
    mesh = make_mesh(4, 2)  # skips here on a <8-device backend
    assert mesh.shape == {"peer": 4, "shard": 2}


def test_parity_with_golden_codec():
    """One pod step == per-peer golden quantize + cross-apply of every other
    peer's frame, bit-for-bit (n_shard=1)."""
    mesh = make_mesh(2, 1)
    tpl = template()
    spec = make_spec(tpl)
    state = init_state(mesh, spec, tpl)
    # give each peer a distinct pending update
    ups = jnp.stack(
        [flatten(jax.tree.map(lambda x: 0.1 * x, tpl), spec),
         flatten(jax.tree.map(lambda x: -0.3 * x, tpl), spec)]
    )
    state = add_updates(state, ups)
    v0 = np.asarray(state.values)
    r0 = np.asarray(state.residual)

    step = build_sync_step(mesh, spec)
    state2, scales = jax.block_until_ready(step(state))
    # golden: quantize each peer's residual, apply to the *other* peer
    frames, resids = [], []
    for p in range(2):
        f, r2 = quantize_table(jnp.asarray(r0[p]), spec)
        frames.append(f)
        resids.append(r2)
    for p in range(2):
        expect_v = apply_table(jnp.asarray(v0[p]), frames[1 - p], spec)
        np.testing.assert_array_equal(np.asarray(state2.values[p]), np.asarray(expect_v))
        np.testing.assert_array_equal(
            np.asarray(state2.residual[p]), np.asarray(resids[p])
        )
        np.testing.assert_array_equal(np.asarray(scales[p]), np.asarray(frames[p].scales))


@pytest.mark.parametrize("n_shard", [2, 4])
def test_sharded_matches_unsharded(n_shard):
    """Sharding the table over the shard axis must not change the math."""
    tpl = template(3)
    spec = make_spec(tpl)
    ups = jnp.stack(
        [flatten(jax.tree.map(lambda x: (0.05 * (p + 1)) * x, tpl), spec) for p in range(2)]
    )
    results = []
    for ns in (1, n_shard):
        mesh = make_mesh(2, ns)
        state = add_updates(init_state(mesh, spec, tpl), ups)
        step = build_sync_step(mesh, spec)
        state2, scales = jax.block_until_ready(step(state))
        results.append((np.asarray(state2.values), np.asarray(state2.residual), np.asarray(scales)))
    (v1, r1, s1), (v2, r2, s2) = results
    # partial-sum order differs across shards; pow2 flooring absorbs it
    np.testing.assert_array_equal(s1, s2)
    np.testing.assert_array_equal(v1, v2)
    np.testing.assert_array_equal(r1, r2)


def test_conservation_invariant():
    """values_p + sum_{q != p} residual_q is invariant under sync steps:
    nothing is lost or double-counted on the way to eventual consistency
    (the reference cannot even promise this — quirk Q7 races lose updates)."""
    mesh = make_mesh(4, 2)
    tpl = template(1)
    spec = make_spec(tpl)
    state = init_state(mesh, spec, tpl)
    key = jax.random.PRNGKey(7)
    ups = jax.random.normal(key, (4, spec.total)) * (
        jnp.arange(1, 5)[:, None].astype(jnp.float32)
    )
    # zero the padding lanes like a real flatten would
    from shared_tensor_tpu.ops.table import _live_mask_flat

    ups = ups * jnp.asarray(_live_mask_flat(spec), jnp.float32)
    state = add_updates(state, ups)

    def ledger(st):
        v = np.asarray(st.values)
        r = np.asarray(st.residual)
        return np.stack([v[p] + r.sum(0) - r[p] for p in range(4)])

    before = ledger(state)
    step = build_sync_step(mesh, spec)
    for _ in range(3):
        state, _ = step(state)
    after = ledger(jax.block_until_ready(state))
    np.testing.assert_allclose(after, before, rtol=0, atol=1e-4)


def test_eventual_consistency_convergence():
    """After updates quiesce, every replica converges to seed + sum of all
    peers' updates — the README.md:24 contract, at the reference's measured
    rate (~1 bit/elem/frame ⇒ exact fp32 in a few dozen frames, BASELINE.md)."""
    mesh = make_mesh(4, 1)
    tpl = template(2)
    spec = make_spec(tpl)
    state = init_state(mesh, spec, tpl)
    key = jax.random.PRNGKey(11)
    ups = jax.random.uniform(key, (4, spec.total), minval=-1.0, maxval=1.0)
    from shared_tensor_tpu.ops.table import _live_mask_flat

    ups = ups * jnp.asarray(_live_mask_flat(spec), jnp.float32)
    state = add_updates(state, ups)
    expect = flatten(tpl, spec) + ups.sum(0)
    step = build_sync_step(mesh, spec)
    for _ in range(64):
        state, scales = step(state)
    state = jax.block_until_ready(state)
    v = np.asarray(state.values)
    for p in range(4):
        np.testing.assert_allclose(v[p], np.asarray(expect), rtol=0, atol=1e-5)
    # converged peers idle at scale 0 (no wasted ICI traffic; quirk Q2 fixed)
    assert float(np.abs(np.asarray(state.residual)).max()) < 1e-6


def test_exact_allreduce_arm():
    """compressed=False delivers every pending residual exactly in one step
    (BASELINE config 4's comparison arm)."""
    mesh = make_mesh(4, 2)
    tpl = template(4)
    spec = make_spec(tpl)
    state = init_state(mesh, spec, tpl)
    ups = jnp.stack(
        [flatten(jax.tree.map(lambda x: (0.2 * (p + 1)) * x, tpl), spec) for p in range(4)]
    )
    state = add_updates(state, ups)
    expect = flatten(tpl, spec) + ups.sum(0)
    step = build_sync_step(mesh, spec, compressed=False)
    state, scales = jax.block_until_ready(step(state))
    v = np.asarray(state.values)
    for p in range(4):
        np.testing.assert_allclose(v[p], np.asarray(expect), rtol=1e-6, atol=1e-5)
    assert np.all(np.asarray(state.residual) == 0)


def test_idle_peers_send_nothing():
    mesh = make_mesh(2, 1)
    tpl = template(5)
    spec = make_spec(tpl)
    state = init_state(mesh, spec, tpl)
    v0 = np.asarray(state.values)  # snapshot: step() donates its input
    step = build_sync_step(mesh, spec)
    state2, scales = jax.block_until_ready(step(state))
    assert np.all(np.asarray(scales) == 0)
    np.testing.assert_array_equal(np.asarray(state2.values), v0)


def test_add_updates_sanitizes():
    """NaN/inf updates must not poison the pod (quirk Q9 fixed)."""
    mesh = make_mesh(2, 1)
    tpl = template(6)
    spec = make_spec(tpl)
    state = init_state(mesh, spec, tpl)
    bad = jnp.full((2, spec.total), jnp.nan)
    state = add_updates(state, bad)
    assert np.isfinite(np.asarray(state.values)).all()
    step = build_sync_step(mesh, spec)
    state, scales = jax.block_until_ready(step(state))
    assert np.isfinite(np.asarray(state.values)).all()


def test_read_peer_roundtrip():
    mesh = make_mesh(2, 2)
    tpl = template(8)
    spec = make_spec(tpl)
    state = init_state(mesh, spec, tpl)
    out = read_peer(state, spec, 1)
    for ka in tpl:
        np.testing.assert_array_equal(np.asarray(out[ka]), np.asarray(tpl[ka]))


def test_frame_ici_bytes_model():
    tpl = template(9)
    spec = make_spec(tpl)
    comp = frame_ici_bytes(spec, 8, compressed=True)
    exact = frame_ici_bytes(spec, 8, compressed=False)
    # ~1 bit/elem vs fp32 wire: the >=10x headroom (BASELINE.md)
    assert exact / comp > 8


def test_global_scale_mode():
    """per_leaf=False reproduces the reference's single-global-scale frames."""
    mesh = make_mesh(2, 1)
    tpl = template(10)
    spec = make_spec(tpl)
    ups = jnp.stack([flatten(tpl, spec) * 0.1, flatten(tpl, spec) * 0.2])
    state = add_updates(init_state(mesh, spec, tpl), ups)
    r0 = np.asarray(state.residual)
    step = build_sync_step(mesh, spec, per_leaf=False)
    state2, scales = jax.block_until_ready(step(state))
    for p in range(2):
        f, _ = quantize_table(jnp.asarray(r0[p]), spec, ScalePolicy.POW2_RMS, False)
        np.testing.assert_array_equal(np.asarray(scales[p]), np.asarray(f.scales)[:1])


@pytest.mark.parametrize("n_peer,n_shard", [(1, 1), (4, 1), (4, 2)])
def test_sync_phases_compose_to_sync_step(n_peer, n_shard):
    """build_sync_phases is the fused step split in two: composing
    apply_gathered(values, *send(residual)[1:]) immediately must be
    bit-for-bit build_sync_step (the overlap training mode's correctness
    anchor, train/async_sgd.py overlap=True). The (1, 1) case runs on a
    single real chip (ST_TEST_PLATFORM=axon), compiling the shard_map +
    Pallas phase path on hardware."""
    from shared_tensor_tpu.parallel import build_sync_phases

    tpl = template(11)
    spec = make_spec(tpl)
    mesh = make_mesh(n_peer, n_shard)
    ups = jnp.stack(
        [
            flatten(jax.tree.map(lambda x: (0.07 * (p + 1)) * x, tpl), spec)
            for p in range(n_peer)
        ]
    )
    state = add_updates(init_state(mesh, spec, tpl), ups)
    fused, scales_f = jax.block_until_ready(build_sync_step(mesh, spec)(state))

    state2 = add_updates(init_state(make_mesh(n_peer, n_shard), spec, tpl), ups)
    send, apply_gathered = build_sync_phases(mesh, spec)

    @jax.jit
    def composed(st):
        r2, words_all, scales_all = send(st.residual)
        v2 = apply_gathered(st.values, words_all, scales_all)
        return v2, r2, scales_all

    v2, r2, scales = jax.block_until_ready(composed(state2))
    np.testing.assert_array_equal(np.asarray(v2), np.asarray(fused.values))
    np.testing.assert_array_equal(np.asarray(r2), np.asarray(fused.residual))
    np.testing.assert_array_equal(np.asarray(scales), np.asarray(scales_f))
