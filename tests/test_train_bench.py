"""Smoke test for benchmarks/train_bench.py (VERDICT.md round-1 item 4):
the artifact must always be one parseable JSON line with all three arms."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_train_bench_emits_all_arms():
    env = dict(os.environ)
    env["ST_TRAIN_BENCH_BUDGET_S"] = "120"
    env.pop("JAX_PLATFORMS", None)
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "benchmarks", "train_bench.py"),
            "--platform", "cpu", "--peers", "2", "--tiny",
            "--batch", "2", "--seq", "32",
        ],
        capture_output=True,
        text=True,
        timeout=180,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    out = json.loads(line)
    assert out["metric"] == "train_step_bench"
    assert set(out["arms"]) == {
        "sync_off", "compressed", "compressed_overlap", "exact"
    }
    for name, arm in out["arms"].items():
        assert "error" not in arm, (name, arm)
        assert arm["tokens_per_s"] > 0
    assert out["arms"]["compressed"].get("sync_overhead_pct") is not None
