"""SharedTensor core tests: replica/link semantics in-process.

Simulates what the reference example.lua does across processes (SURVEY.md
§4.1) by wiring SharedTensor objects' frames directly to each other.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from shared_tensor_tpu.core import SharedTensor


def _tree(seed=0):
    # uniform(-1,1): quiesces to exact zero in ~30 frames (heavy-tailed data
    # converges but takes hundreds of frames to reach scale==0 — same as the
    # C reference; see BASELINE.md convergence table)
    rng = np.random.default_rng(seed)
    return {
        "w": rng.uniform(-1, 1, size=(20, 30)).astype(np.float32),
        "b": rng.uniform(-1, 1, size=(50,)).astype(np.float32),
    }


def _quiet(f, tol=1e-6):
    return f is None or float(np.max(np.asarray(f.scales))) < tol


def _pump(a, b, la, lb, steps=120):
    """Bidirectional frame exchange until both links are (effectively) idle.

    "Idle" = no frame or all scales below tolerance: converged elements
    oscillate within +/-scale (quirk Q3, inherited), so tiny scales persist
    asymptotically rather than hitting exact zero — same as the C reference.
    """
    for _ in range(steps):
        fa = a.make_frame(la)
        fb = b.make_frame(lb)
        if fa is not None:
            b.receive_frame(lb, fa)
        if fb is not None:
            a.receive_frame(la, fb)
        if _quiet(fa) and _quiet(fb):
            return
    raise AssertionError("links did not quiesce")


def test_seeded_state_transfer():
    """Master seeds, joiner starts empty; after frames quiesce the joiner's
    replica equals the master's — the reference join mechanism (SURVEY §5.4)."""
    t = _tree(0)
    master = SharedTensor(t, seed_values=True)
    joiner = SharedTensor(t, seed_values=False)
    master.new_link(1, seed=True)
    joiner.new_link(1, seed=False)
    _pump(master, joiner, 1, 1)
    got = joiner.read()
    for k in t:
        np.testing.assert_allclose(np.asarray(got[k]), t[k], rtol=0, atol=1e-5)


def test_concurrent_adds_converge():
    """Both peers add updates; both replicas converge to seed + sum of all
    updates (the README.md:24 eventual-consistency contract)."""
    t = _tree(1)
    a = SharedTensor(t, seed_values=True)
    b = SharedTensor(t, seed_values=False)
    a.new_link(1, seed=True)
    b.new_link(1, seed=False)
    _pump(a, b, 1, 1)

    ua = {k: np.full_like(v, 0.5) for k, v in t.items()}
    ub = {k: np.full_like(v, 0.25) for k, v in t.items()}
    a.add(ua)
    b.add(ub)
    _pump(a, b, 1, 1)

    want = {k: t[k] + 0.75 for k in t}
    for st in (a, b):
        got = st.read()
        for k in t:
            np.testing.assert_allclose(np.asarray(got[k]), want[k], rtol=0, atol=1e-5)


def test_three_node_chain_floods():
    """a - b - c chain: an update at a reaches c through b's re-quantizing
    flood (reference sync_in split horizon, src/sharedtensor.c:124-127)."""
    t = _tree(2)
    a = SharedTensor(t, seed_values=True)
    b = SharedTensor(t, seed_values=False)
    c = SharedTensor(t, seed_values=False)
    a.new_link(10, seed=True)
    b.new_link(10, seed=False)  # b's uplink to a
    b.new_link(20, seed=True)  # b's downlink to c (seeded: b may hold state)
    c.new_link(20, seed=False)

    def pump_all(steps=160):
        for _ in range(steps):
            active = False
            for src, dst, l in ((a, b, 10), (b, a, 10), (b, c, 20), (c, b, 20)):
                f = src.make_frame(l)
                if f is not None:
                    dst.receive_frame(l, f)
                    active = active or not _quiet(f)
            if not active:
                return
        raise AssertionError("chain did not quiesce")

    pump_all()
    for st in (b, c):
        got = st.read()
        for k in t:
            np.testing.assert_allclose(np.asarray(got[k]), t[k], rtol=0, atol=1e-5)

    # now a local add at a propagates to c
    a.add({k: np.full_like(v, 1.0) for k, v in t.items()})
    pump_all()
    got = c.read()
    for k in t:
        np.testing.assert_allclose(np.asarray(got[k]), t[k] + 1.0, rtol=0, atol=1e-5)


def test_drop_link_and_regraft():
    """Peer death must not corrupt survivors; a re-grafted peer recovers full
    state (fixes reference quirk Q8: exit(-1) on any disconnect)."""
    t = _tree(3)
    a = SharedTensor(t, seed_values=True)
    b = SharedTensor(t, seed_values=False)
    a.new_link(1, seed=True)
    b.new_link(1, seed=False)
    _pump(a, b, 1, 1)

    a.drop_link(1)  # b died mid-stream
    a.add({k: np.full_like(v, 2.0) for k, v in t.items()})  # survivor keeps working

    c = SharedTensor(t, seed_values=False)  # b's replacement re-grafts
    a.new_link(2, seed=True)
    c.new_link(2, seed=False)
    _pump(a, c, 2, 2)
    got = c.read()
    for k in t:
        np.testing.assert_allclose(np.asarray(got[k]), t[k] + 2.0, rtol=0, atol=1e-5)


def test_regraft_carry_algebra():
    """The peer handshake's re-graft accounting (comm/peer.py): a child that
    lost its parent while holding an undelivered uplink residual X re-grafts
    onto a new parent. The handshake sends snapshot = replica - X; the parent
    diff-seeds its downlink with (parent - snapshot); at WELCOME the child
    seeds its uplink with (replica_now - snapshot), covering X plus anything
    added mid-handshake. Afterwards both converge to the union — nothing
    lost, nothing double-counted."""
    t = _tree(7)
    parent = SharedTensor(t, seed_values=True)
    child = SharedTensor(t, seed_values=True)  # had full state before orphaning

    # child's updates that never reached its dead parent:
    x = {k: np.full_like(v, 0.5) for k, v in t.items()}
    child.new_link(9, seed=False)  # the (dead) old uplink
    child.add(x)
    carry = child.drop_link(9)  # what the dead link still owed upward

    # --- handshake (mirrors SharedTensorPeer._start_join / WELCOME) ---
    snap = child.snapshot_flat() - carry
    # mid-handshake activity: child gets another local update
    y = {k: np.full_like(v, -0.25) for k, v in t.items()}
    child.add(y)
    parent.new_link_diff(2, snap)  # parent side, at DONE
    child.new_link_diff(2, snap)  # child side, at WELCOME: residual = X + Y

    # parent also moved on while the child was orphaned
    z = {k: np.full_like(v, 1.0) for k, v in t.items()}
    parent.add(z)

    _pump(parent, child, 2, 2)
    want = {k: t[k] + 0.5 - 0.25 + 1.0 for k in t}
    for st in (parent, child):
        got = st.read()
        for k in t:
            np.testing.assert_allclose(
                np.asarray(got[k]), want[k], rtol=0, atol=1e-5
            )


def test_zero_template_no_hang():
    """All-zero shared tensor: reference quirk Q4 busy-waits forever; here
    links simply idle (no frames) and reads return zeros immediately."""
    t = {"a": np.zeros(100, np.float32)}
    master = SharedTensor(t, seed_values=True)
    master.new_link(1, seed=True)
    assert master.make_frame(1) is None
    np.testing.assert_array_equal(np.asarray(master.read()["a"]), 0.0)


def test_size_mismatch_raises():
    t = _tree(5)
    st = SharedTensor(t, seed_values=True)
    bad = {"w": np.zeros((2, 2), np.float32), "b": np.zeros(50, np.float32)}
    with pytest.raises(Exception):
        st.add(bad)


def test_metrics_counters():
    t = _tree(6)
    a = SharedTensor(t, seed_values=True)
    a.new_link(1, seed=True)
    f = a.make_frame(1)
    assert f is not None and a.frames_out == 1
    a.receive_frame(1, f)  # loopback (just exercises the counter)
    assert a.frames_in == 1
    a.add(t)
    assert a.updates == 1
    assert a.residual_rms(1) >= 0.0


def test_zero_scale_frames_count_nowhere():
    """Corruption-zeroed (all-zero-scale) frames are no-ops that must not
    bump frames_in on ANY tier — the engine's taxonomy rule (stengine.cpp
    apply_batch), now pinned for the Python tier too (ADVICE r04 item 1):
    a quiesced pair satisfies sender.frames_out == receiver.frames_in."""
    from shared_tensor_tpu.ops.table import TableFrame

    t = _tree(16)
    a = SharedTensor(t, seed_values=True)
    a.new_link(1, seed=True)
    real = a.make_frame(1)
    assert real is not None
    zero = TableFrame(
        np.zeros_like(np.asarray(real.scales)),
        np.asarray(real.words),  # bits without scales decode to nothing
    )
    before = np.asarray(a.snapshot_flat()).copy()
    a.receive_frame(1, zero)
    assert a.frames_in == 0
    np.testing.assert_array_equal(np.asarray(a.snapshot_flat()), before)
    # batched path: zero frames inside a batch are applied-as-nothing and
    # excluded from the count
    a.receive_frames(1, [real, zero, zero])
    assert a.frames_in == 1


def test_receive_frames_backlog_contract(monkeypatch):
    """The batched receive path's contract (round-2 verdict item 8): a burst
    of K frames from one link lands in exactly ONE batched device dispatch
    (receive_frames pads K to a power of two), and its effect equals applying
    the frames sequentially."""
    import shared_tensor_tpu.core as core_mod

    t = _tree(7)
    sender = SharedTensor(t, seed_values=True)
    sender.new_link(1, seed=True)
    frames = []
    for _ in range(50):
        f = sender.make_frame(1)
        if f is None:
            break
        frames.append(f)
    assert len(frames) >= 20  # enough of a burst to be meaningful

    # sequential ground truth (fresh receiver with one extra link to check
    # the flood path too)
    seq = SharedTensor(t)
    seq.new_link(1, seed=False)
    seq.new_link(2, seed=False)
    for f in frames:
        seq.receive_frame(1, f)

    batched = SharedTensor(t)
    batched.new_link(1, seed=False)
    batched.new_link(2, seed=False)
    calls = {"batch": 0, "single": 0}
    import shared_tensor_tpu.ops.codec_np as np_mod

    orig_batch = core_mod.apply_table_batch
    orig_many = core_mod.apply_table_many
    orig_batch_np = np_mod.apply_table_batch_np

    def counting_batch(*a, **kw):
        calls["batch"] += 1
        return orig_batch(*a, **kw)

    def counting_many(*a, **kw):
        calls["single"] += 1
        return orig_many(*a, **kw)

    def counting_batch_np(*a, **kw):
        calls["batch"] += 1
        return orig_batch_np(*a, **kw)

    monkeypatch.setattr(core_mod, "apply_table_batch", counting_batch)
    monkeypatch.setattr(core_mod, "apply_table_many", counting_many)
    # numpy host tier routes through codec_np (apply_table_many_np is
    # implemented via the batch function, so counting batch alone is exact)
    monkeypatch.setattr(np_mod, "apply_table_batch_np", counting_batch_np)
    batched.receive_frames(1, frames)

    assert calls == {"batch": 1, "single": 0}, calls
    assert batched.frames_in == len(frames)
    # summed one-dispatch delta == sequential application (codec deltas are
    # pure adds; tolerance covers f32 summation-order differences)
    np.testing.assert_allclose(
        np.asarray(batched.snapshot_flat()),
        np.asarray(seq.snapshot_flat()),
        rtol=1e-6,
        atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(batched._links[2]), np.asarray(seq._links[2]),
        rtol=1e-6, atol=1e-6,
    )


def test_host_tier_read_returns_copies():
    """In-place edits on a read() snapshot must NOT reach the replica: the
    host tier's numpy unflatten would alias the live buffer if it returned
    views, silently diverging the tree (the device tier is immune — jnp
    arrays are immutable)."""
    tpl = {"w": np.ones((8, 16), np.float32)}
    st = SharedTensor(tpl, seed_values=True)
    snap = st.read()
    arr = np.asarray(snap["w"])
    if arr.flags.writeable:
        arr += 99.0
    got = np.asarray(st.read()["w"])
    np.testing.assert_array_equal(got, np.ones((8, 16), np.float32))


def test_burst_equals_sequential_frames():
    """begin_frame_burst(k) must produce exactly the frames k sequential
    quantizes would (successive halvings of the same residual), leave the
    same final residual, and roll back whole on nack."""
    from shared_tensor_tpu.ops.codec_np import quantize_table_np

    tpl = np.linspace(-1.0, 1.0, 300).astype(np.float32)
    st = SharedTensor(tpl, seed_values=True)
    st.new_link(7, seed=True)  # residual = full replica
    r_golden = np.asarray(st._links[7]).copy()
    out = st.begin_frame_burst(7, 6)
    assert out is not None
    seq, frames = out
    assert 1 <= len(frames) <= 6
    for f in frames:
        s, w, r_golden = quantize_table_np(r_golden, st.spec)
        np.testing.assert_array_equal(np.asarray(f.scales), s)
        np.testing.assert_array_equal(np.asarray(f.words), w)
    np.testing.assert_array_equal(np.asarray(st._links[7]), r_golden)
    # nack rolls the WHOLE burst back into the residual, bit-for-bit
    pre = np.asarray(st._links[7]).copy()
    st.nack_frame(7)
    rolled = np.asarray(st._links[7])
    assert not np.array_equal(rolled, pre)
    # re-bursting after rollback reproduces the identical frames
    out2 = st.begin_frame_burst(7, len(frames))
    for f, g in zip(frames, out2[1]):
        np.testing.assert_array_equal(np.asarray(f.words), np.asarray(g.words))


def test_burst_idle_and_exhaustion():
    """A burst stops early when the residual quantizes to nothing: an idle
    link yields zero frames; a converged-mid-burst link yields fewer than k."""
    tpl = np.zeros(300, np.float32)
    st = SharedTensor(tpl, seed_values=True)
    st.new_link(1, seed=False)  # zero residual: idle
    seq, frames = st.begin_frame_burst(1, 8)
    assert frames == []
    assert st.inflight_total() == 0  # no ledger entry for a no-op burst
    # uniform residual converges exactly in ~27 frames (BASELINE.md): a
    # 255-frame burst must stop at exhaustion, not pad with idle frames
    rng = np.random.default_rng(3)
    st2 = SharedTensor(tpl, seed_values=True)
    st2.new_link(1, residual=rng.uniform(-1, 1, st2.spec.total).astype(np.float32))
    _, frames2 = st2.begin_frame_burst(1, 255)
    assert 0 < len(frames2) < 255
    assert float(np.abs(np.asarray(st2._links[1])).max()) == 0.0
