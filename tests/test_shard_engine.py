"""r17 engine-tier shard data plane (stengine.cpp st_shard_* +
shard/engine_lane.py).

What these tests pin down, composing upward:

- KERNEL PARITY: the native slice codec (st_slice_quantize /
  st_slice_apply / st_slice_cascade) is byte-equal to the numpy
  SliceCodec on shared random state — scales, word planes, residuals and
  applies, across all three scale policies and full drain ladders. The
  two lanes emit byte-identical FWD frames by construction, which is
  what makes mixed trees and checkpoints lane-blind.
- DEDUP DECISIONS: an engine-lane owner discards an end-to-end
  (origin, fwd_seq) duplicate exactly like the python tier — driven
  deterministically through a real member handshake from a bare
  transport node, covering the per-link go-back-N acceptance and the
  cumulative-ACK re-announce along the way.
- VERBATIM RELAY: a FWD addressed to a shard an engine-lane node does
  NOT own is forwarded toward the owner with only the per-link seq
  re-stamped (the owner applies it — the end-to-end identity survived
  the hop) and counted in st_shard_fwd_relayed_total.
- MIXED-TREE INTEROP, both orientations: engine-lane owner under a
  python-lane writer and vice versa converge exactly (the wire is
  identical, so each side is oblivious to the other's lane).
- ADMISSION CONTROL (ROADMAP 1(d)): ShardConfig.outbox_limit_bytes
  bounds resident outbox bytes at add() — blocking until drained, or
  raising ShardBackpressure — so a writer outrunning a stalled link
  stays inside the alloc bound WITHOUT the chaos harness's polling loop.
"""

import time

import numpy as np
import pytest

from shared_tensor_tpu.comm import wire
from shared_tensor_tpu.comm.transport import TransportNode
from shared_tensor_tpu.compat import SYNC_FLAG_SHARD, wire_protocol_version
from shared_tensor_tpu.config import (
    Config,
    ScalePolicy,
    ShardConfig,
    TransportConfig,
)
from shared_tensor_tpu.ops.codec_np import _layout
from shared_tensor_tpu.ops.table import make_spec
from shared_tensor_tpu.shard import (
    ShardBackpressure,
    ShardGather,
    create_or_fetch_sharded,
)
from shared_tensor_tpu.shard.engine_lane import (
    load_shard_lib,
    shard_engine_eligible,
)
from shared_tensor_tpu.shard.state import SliceCodec
from tests._ports import free_port

TMPL = {
    "w": np.zeros(4096, np.float32),
    "b": np.zeros(512, np.float32),
}
SPEC = make_spec(TMPL)
WORDS = SPEC.total // 32

_POLICIES = [
    (ScalePolicy.POW2_RMS, 0),
    (ScalePolicy.RMS, 1),
    (ScalePolicy.ABS_MEAN, 2),
]


def _lib():
    lib = load_shard_lib()
    if lib is None:
        pytest.skip("native engine unavailable")
    return lib


def _cfg(idx: int, n: int = 2, engine: bool = True, **shard_kw) -> Config:
    return Config(
        shard=ShardConfig(
            n_shards=n, shard_index=idx, engine_lane=engine, **shard_kw
        ),
        transport=TransportConfig(
            peer_timeout_sec=20.0, ack_timeout_sec=0.4
        ),
    )


# ---- kernel parity ---------------------------------------------------------


def test_slice_kernels_byte_equal_numpy():
    """st_slice_quantize / st_slice_apply == SliceCodec, bit for bit,
    through whole drain ladders on shared random state."""
    lib = _lib()
    offs, ns, padded = _layout(SPEC)
    for seed in range(3):
        rng = np.random.default_rng(seed)
        wlo = int(rng.integers(0, WORDS - 2))
        wcnt = int(rng.integers(1, WORDS - wlo))
        sc = SliceCodec(SPEC, wlo, wcnt)
        r0 = (
            rng.standard_normal(sc.n_el) * rng.uniform(0.1, 10)
        ).astype(np.float32) * sc.live
        for pol, code in _POLICIES:
            rp, rc = r0.copy(), r0.copy()
            for _ in range(80):
                s_py, w_py, rp = sc.quantize(rp, pol)
                s_c = np.zeros(SPEC.num_leaves, np.float32)
                w_c = np.zeros(wcnt, np.uint32)
                nz = lib.st_slice_quantize(
                    offs, ns, padded, SPEC.num_leaves, wlo, wcnt, code,
                    rc, s_c, w_c,
                )
                assert np.array_equal(s_py, s_c)
                assert nz == int(bool(s_py.any()))
                if not s_py.any():
                    break
                assert np.array_equal(w_py, w_c)
                assert np.array_equal(rp, rc)
            t_py = rng.standard_normal(sc.n_el).astype(np.float32)
            t_c = t_py.copy()
            s1 = np.abs(rng.standard_normal(SPEC.num_leaves)).astype(
                np.float32
            )
            w1 = rng.integers(0, 2**32, wcnt, dtype=np.uint32)
            sc.apply(t_py, s1, w1)
            lib.st_slice_apply(
                offs, ns, padded, SPEC.num_leaves, wlo, wcnt, t_c,
                np.ascontiguousarray(s1), np.ascontiguousarray(w1),
            )
            assert np.array_equal(t_py, t_c)


def test_cascade_message_byte_equal_numpy():
    """st_slice_cascade (the pump's whole message build: measure ->
    amax-anchored halving schedule -> fused quantize) emits frames
    byte-equal to state.py's measure + cascade_rows + quantize_at —
    the engine and python FWD planes put identical bytes on the wire."""
    lib = _lib()
    offs, ns, padded = _layout(SPEC)
    L = SPEC.num_leaves
    for seed in range(3):
        rng = np.random.default_rng(100 + seed)
        wlo = int(rng.integers(0, WORDS - 4))
        wcnt = int(rng.integers(2, WORDS - wlo))
        sc = SliceCodec(SPEC, wlo, wcnt)
        per = L * 4 + wcnt * 4
        k = 16
        for pol, code in _POLICIES:
            rp = (
                rng.standard_normal(sc.n_el) * rng.uniform(0.5, 5)
            ).astype(np.float32) * sc.live
            rc = rp.copy()
            for _msg in range(6):  # several messages: the ladder re-anchors
                scales, amaxes = sc.measure(rp, pol)
                rows = sc.cascade_rows(scales, amaxes, k)
                py_frames = []
                for row in rows:
                    w_py, rp = sc.quantize_at(rp, row)
                    py_frames.append((row, w_py))
                buf = np.zeros(k * per, np.uint8)
                nf = lib.st_slice_cascade(
                    offs, ns, padded, L, wlo, wcnt, code, k, rc, buf
                )
                assert nf == len(py_frames)
                assert np.array_equal(rp, rc)  # residual after EF
                for f, (row, w_py) in enumerate(py_frames):
                    fs = buf[f * per:f * per + L * 4].view(np.float32)
                    fw = buf[f * per + L * 4:(f + 1) * per].view(np.uint32)
                    assert np.array_equal(row, fs)
                    assert np.array_equal(w_py, fw)
                if nf == 0:
                    break


# ---- dedup decisions + verbatim relay (crafted member) ---------------------


def _fake_member_join(node: TransportNode, cfg: Config, shard_claim=-1):
    """Run the real member handshake from a bare transport node: SYNC
    (shard flag + claim tail) + DONE, then drain until WELCOME."""
    node.send(
        node.uplink,
        wire.encode_sync(
            SPEC, wire_protocol_version(cfg), SYNC_FLAG_SHARD,
            shard=shard_claim,
        ),
        timeout=1.0,
    )
    node.send(node.uplink, bytes([wire.DONE]), timeout=1.0)
    deadline = time.time() + 10.0
    while time.time() < deadline:
        payload = node.recv(node.uplink, timeout=0.2)
        if payload and payload[0] == wire.WELCOME:
            assert wire.welcome_flags(payload) & SYNC_FLAG_SHARD
            return
    raise AssertionError("no WELCOME from the engine-lane owner")


def _drain_acks(node: TransportNode, link: int, budget=5.0):
    acks = []
    deadline = time.time() + budget
    while time.time() < deadline and len(acks) < 16:
        payload = node.recv(link, timeout=0.1)
        if payload and payload[0] == wire.ACK:
            acks.append(wire.decode_ack(payload))
        elif payload is None and acks:
            break
    return acks


def test_engine_owner_dedup_and_ack_reannounce():
    """An engine-lane owner applies a FWD once, discards the re-routed
    duplicate via the (origin, fwd_seq) window — counting it — and keeps
    the cumulative ACK advancing (re-announced on the link-level dup)."""
    if not shard_engine_eligible(_cfg(0)):
        pytest.skip("engine lane ineligible")
    port = free_port()
    h0 = create_or_fetch_sharded(
        "127.0.0.1", port, TMPL, _cfg(0), timeout=30.0
    )
    member = None
    try:
        assert h0.node._lane is not None
        cfg = _cfg(1)
        member = TransportNode(
            "127.0.0.1", port, cfg.transport,
            frame_bytes=wire.frame_wire_bytes(SPEC),
        )
        _fake_member_join(member, cfg)
        up = member.uplink
        # shard 0 is the master's; quantize one frame of a known delta
        m = h0.node.map
        wlo, wcnt = m.word_range(0)
        sc = SliceCodec(SPEC, wlo, wcnt)
        rng = np.random.default_rng(7)
        delta = rng.standard_normal(sc.n_el).astype(np.float32) * sc.live
        scales, words, _r = sc.quantize(delta.copy())
        expected = sc.zeros()
        sc.apply(expected, scales, words)
        origin = 0xBEEF
        payload = wire.encode_fwd([(scales, words)], wlo, 0, origin, 1)
        # link seq 1: applied
        buf = bytearray(payload)
        wire.fwd_restamp(buf, 1)
        member.send(up, bytes(buf), timeout=1.0)
        # link seq 2, SAME (origin, fwd_seq): the re-route duplicate —
        # accepted at the link layer, discarded by the e2e window
        buf = bytearray(payload)
        wire.fwd_restamp(buf, 2)
        member.send(up, bytes(buf), timeout=1.0)
        # link seq 2 again: a LINK-level duplicate (our ACK was lost in
        # this story) — discarded unapplied, ACK re-announced
        member.send(up, bytes(buf), timeout=1.0)
        deadline = time.time() + 10.0
        while time.time() < deadline:
            c = h0.node._lane.counters()
            if int(c[3]) >= 1 and int(c[1]) >= 1:
                break
            time.sleep(0.05)
        c = h0.node._lane.counters()
        assert int(c[1]) == 1, "exactly one FWD applied"
        assert int(c[3]) == 1, "exactly one e2e dedup discard"
        acks = _drain_acks(member, up)
        assert acks and max(acks) == 2, acks
        got = h0.node.read_owned()[0][2]
        assert np.array_equal(got, expected)
    finally:
        if member is not None:
            member.close()
        h0.close()


def test_engine_relay_forwards_verbatim_toward_owner():
    """A FWD landing on an engine-lane node that does NOT own its shard
    relays toward the owner (per-link seq re-stamped, identity intact —
    the owner applies it) and counts st_shard_fwd_relayed_total."""
    port = free_port()
    h0 = create_or_fetch_sharded(  # master, owns shard 0
        "127.0.0.1", port, TMPL, _cfg(0), timeout=30.0
    )
    h1 = create_or_fetch_sharded(  # owns shard 1
        "127.0.0.1", port, TMPL, _cfg(1), timeout=30.0
    )
    member = None
    try:
        assert h1.node._lane is not None
        # join as a member UNDER h1 is not steerable on one rendezvous —
        # instead send the relay case through h1's own uplink position:
        # craft a member under the MASTER and address shard 1 (owned by
        # h1): the master does not own it and must relay down the route
        # its announce learned
        cfg = _cfg(1)
        member = TransportNode(
            "127.0.0.1", port, cfg.transport,
            frame_bytes=wire.frame_wire_bytes(SPEC),
        )
        _fake_member_join(member, cfg)
        up = member.uplink
        m = h0.node.map
        wlo, wcnt = m.word_range(1)
        sc = SliceCodec(SPEC, wlo, wcnt)
        rng = np.random.default_rng(11)
        delta = rng.standard_normal(sc.n_el).astype(np.float32) * sc.live
        scales, words, _r = sc.quantize(delta.copy())
        expected = h1.node.read_owned()[1][2].copy()
        sc.apply(expected, scales, words)
        payload = wire.encode_fwd([(scales, words)], wlo, 0, 0xCAFE, 1)
        buf = bytearray(payload)
        wire.fwd_restamp(buf, 1)
        member.send(up, bytes(buf), timeout=1.0)
        deadline = time.time() + 10.0
        relayer = h0.node._lane
        while time.time() < deadline:
            if int(h1.node._lane.counters()[1]) >= 1:
                break
            time.sleep(0.05)
        assert int(relayer.counters()[2]) == 1, "one verbatim relay"
        got = h1.node.read_owned()[1][2]
        assert np.array_equal(got, expected)
    finally:
        if member is not None:
            member.close()
        h1.close()
        h0.close()


# ---- mixed-tree interop ----------------------------------------------------


@pytest.mark.parametrize("orient", ["engine_owner", "python_owner"])
def test_mixed_lane_pair_converges_exactly(orient):
    """Engine-lane and python-lane nodes interop in both orientations —
    the FWD wire is lane-blind (the parity tests above make it
    byte-identical), so each side cannot tell what the other runs."""
    port = free_port()
    owner_engine = orient == "engine_owner"
    h0 = create_or_fetch_sharded(
        "127.0.0.1", port, TMPL, _cfg(0, engine=owner_engine), timeout=30.0
    )
    h1 = create_or_fetch_sharded(
        "127.0.0.1", port, TMPL, _cfg(1, engine=not owner_engine),
        timeout=30.0,
    )
    try:
        assert (h0.node._lane is not None) == owner_engine
        assert (h1.node._lane is not None) == (not owner_engine)
        rng = np.random.default_rng(3)
        ref = np.zeros(SPEC.total, np.float64)
        from shared_tensor_tpu.ops.codec_np import flatten_np

        for _ in range(4):
            for h in (h0, h1):
                d = {
                    "w": rng.standard_normal(4096).astype(np.float32),
                    "b": rng.standard_normal(512).astype(np.float32),
                }
                h.add(d)
                ref += flatten_np(d, SPEC)
        assert h0.node.drain(timeout=60.0)
        assert h1.node.drain(timeout=60.0)
        with ShardGather(h0.node, TMPL) as g:
            got = flatten_np(g.read_tree(max_staleness=60.0), SPEC)
        assert float(np.max(np.abs(got - ref))) < 1e-3
    finally:
        h1.close()
        h0.close()


# ---- admission control (ROADMAP 1(d)) --------------------------------------


@pytest.mark.parametrize("engine", [False, True])
def test_outbox_admission_bounds_writer(engine):
    """A writer outrunning a ROUTELESS target (nobody owns the shard =
    the chaotic-link limit case: zero drain) stays inside
    outbox_limit_bytes — blocking add() times out into
    ShardBackpressure, and "raise" refuses immediately. The resident
    outbox bytes never exceed the bound."""
    if engine and not shard_engine_eligible(_cfg(0)):
        pytest.skip("engine lane ineligible")
    port = free_port()
    wlo, wcnt = None, None
    slice_bytes = None
    h0 = None
    try:
        # 2 shards; nobody claims shard 1 -> its outbox can never drain
        h0 = create_or_fetch_sharded(
            "127.0.0.1", port, TMPL,
            _cfg(
                0, engine=engine,
                outbox_limit_bytes=1,  # below one slice: second add gated
                outbox_overflow="block",
                outbox_block_timeout_sec=0.5,
            ),
            timeout=30.0,
        )
        m = h0.node.map
        elo, ehi = m.element_range(1)
        slice_bytes = (ehi - elo) * 4
        d = np.zeros(SPEC.total, np.float32)
        d[elo:ehi] = 1.0
        # the projection counts one slice per target shard: with
        # limit=1 < slice_bytes the very first add is refused after the
        # block timeout
        t0 = time.monotonic()
        with pytest.raises(ShardBackpressure):
            h0.add({"w": d[:4096], "b": d[4096:4608]})
        assert time.monotonic() - t0 >= 0.4  # it genuinely blocked first
        outbox = (
            h0.node._lane.outbox_bytes()
            if engine
            else h0.node.state.outbox_bytes()
        )
        assert outbox <= 1  # nothing was admitted past the bound
    finally:
        if h0 is not None:
            h0.close()


def test_outbox_admission_raise_policy():
    port = free_port()
    h0 = create_or_fetch_sharded(
        "127.0.0.1", port, TMPL,
        _cfg(
            0, engine=False, outbox_limit_bytes=1, outbox_overflow="raise",
        ),
        timeout=30.0,
    )
    try:
        m = h0.node.map
        elo, ehi = m.element_range(1)
        d = np.zeros(SPEC.total, np.float32)
        d[elo:ehi] = 1.0
        with pytest.raises(ShardBackpressure):
            h0.add({"w": d[:4096], "b": d[4096:4608]})
    finally:
        h0.close()
