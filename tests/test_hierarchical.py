"""Hierarchical tier tests: two pods (disjoint device subsets of the 8-device
virtual mesh) bridged through the TCP tree in one process — the multi-host
story at test scale (ICI inside each pod, the reference's tree between
pods)."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests._mesh import make_mesh
from shared_tensor_tpu.train import HierarchicalTrainer
from tests.test_peer import _free_port


def _template():
    return {"w": jnp.zeros((8,), jnp.float32)}


def _quad_loss(p, b):
    # pull w toward the batch target; async-DP mixes the pods' targets
    return jnp.mean((p["w"] - b) ** 2)


def _meshes():
    devs = jax.devices()
    return make_mesh(2, 1, devices=devs[:2]), make_mesh(2, 1, devices=devs[2:4])


def _settle(fn, cond, timeout=15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        fn()
        if cond():
            return True
        time.sleep(0.1)
    return False


def test_add_propagates_between_pods():
    mesh_a, mesh_b = _meshes()
    port = _free_port()
    a = HierarchicalTrainer.create(mesh_a, "127.0.0.1", port, _template(), _quad_loss)
    try:
        b = HierarchicalTrainer.create(mesh_b, "127.0.0.1", port, _template(), _quad_loss)
        try:
            # pod A: every device peer adds 1s (out-of-band update)
            a.pod.add(jnp.ones((a.pod.n_peer, a.pod.spec.total), jnp.float32))
            # intra-pod sync + bridge exchanges until B sees ~2.0 per slot
            # (2 device peers x +1 each)
            def pump():
                batch = jnp.zeros((2, 8), jnp.float32)
                a.step(batch, lr=0.0)
                b.step(batch, lr=0.0)

            ok = _settle(
                pump,
                lambda: np.allclose(
                    np.asarray(b.read(0)["w"]), 2.0, atol=0.05
                ),
            )
            assert ok, np.asarray(b.read(0)["w"])
        finally:
            b.close()
    finally:
        a.close()


def test_two_pod_training_converges_to_mixture():
    """Pod A trains toward +2, pod B toward -2; through the bridge both
    models settle near the mixture (0) instead of their local target —
    proof the cross-pod deltas actually steer training. Pod B runs the
    overlap sync mode (collective under the backward pass) against pod A's
    fused mode: the modes must interoperate through the bridge."""
    mesh_a, mesh_b = _meshes()
    port = _free_port()
    a = HierarchicalTrainer.create(mesh_a, "127.0.0.1", port, _template(), _quad_loss)
    try:
        b = HierarchicalTrainer.create(
            mesh_b, "127.0.0.1", port, _template(), _quad_loss, overlap=True
        )
        try:
            ta = jnp.full((2, 8), 2.0)
            tb = jnp.full((2, 8), -2.0)
            for _ in range(150):
                a.step(ta, lr=0.05)
                b.step(tb, lr=0.05)
                time.sleep(0.002)  # let tree frames flow
            # During live opposing training the pods disagree only by the
            # in-flight delta mass (local-only would sit at +2/-2)...
            wa = float(jnp.mean(a.read(0)["w"]))
            wb = float(jnp.mean(b.read(0)["w"]))
            assert abs(wa) < 1.6, wa
            assert abs(wb) < 1.6, wb
            # ...and once updates stop, the backlog drains and both pods
            # agree — the reference's eventual-consistency contract
            # (README.md:24, "values may overshoot temporarily").
            def quiesce():
                a.step(ta, lr=0.0)
                b.step(tb, lr=0.0)

            def agreed():
                va = float(jnp.mean(a.read(0)["w"]))
                vb = float(jnp.mean(b.read(0)["w"]))
                return abs(va - vb) < 0.05

            assert _settle(quiesce, agreed), (
                float(jnp.mean(a.read(0)["w"])),
                float(jnp.mean(b.read(0)["w"])),
            )
        finally:
            b.close()
    finally:
        a.close()


def test_layout_mismatch_rejected():
    from shared_tensor_tpu.comm.peer import create_or_fetch
    from shared_tensor_tpu.train import PodTrainer

    mesh_a, _ = _meshes()
    port = _free_port()
    peer = create_or_fetch("127.0.0.1", port, _template())
    try:
        pod = PodTrainer(mesh_a, {"x": jnp.zeros((3, 3))}, _quad_loss)
        with pytest.raises(ValueError, match="layout"):
            HierarchicalTrainer(pod, peer)
    finally:
        peer.close()


def test_pod_bridge_churn_mid_training():
    """Kill a POD BRIDGE peer mid-training (round-2 verdict item 7): four
    2-device pods form the tree; the one that is a mid-tree parent dies while
    every pod is actively training; its orphan re-grafts (LINK_DOWN ->
    carry-residual -> rejoin) under live PodTrainers, and the survivors
    converge to agreement once updates stop — no pod's progress is lost."""
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 devices")
    meshes = [make_mesh(2, 1, devices=devs[2 * i : 2 * i + 2]) for i in range(4)]
    port = _free_port()
    from shared_tensor_tpu.config import Config, TransportConfig

    cfg = Config(
        transport=TransportConfig(peer_timeout_sec=5.0, max_rejoin_attempts=16)
    )
    pods = {}
    try:
        for name, mesh in zip("mabc", meshes):
            pods[name] = HierarchicalTrainer.create(
                mesh, "127.0.0.1", port, _template(), _quad_loss, peer_config=cfg
            )
        targets = {"m": 2.0, "a": -2.0, "b": 1.0, "c": -1.0}
        batches = {n: jnp.full((2, 8), t) for n, t in targets.items()}
        # train everyone a bit so real residual mass is in flight
        for _ in range(30):
            for n, tr in pods.items():
                tr.step(batches[n], lr=0.05)
            time.sleep(0.002)
        # the mid-tree parent: a non-master bridge peer with a child link
        parent = next(
            n for n, tr in pods.items()
            if not tr.peer.is_master and len(tr.peer.node.links) > 1
        )
        pods.pop(parent).close()
        survivors = pods

        # keep training through the churn (the orphan re-grafts underneath)
        for _ in range(30):
            for n, tr in survivors.items():
                tr.step(batches[n], lr=0.05)
            time.sleep(0.002)

        # stop updating; all surviving pods must agree (eventual consistency
        # across the re-grafted tree, reference README.md:24)
        def quiesce():
            for n, tr in survivors.items():
                tr.step(batches[n], lr=0.0)

        def agreed():
            means = [float(jnp.mean(tr.read(0)["w"])) for tr in survivors.values()]
            return max(means) - min(means) < 0.05

        # 120 s: each poll iteration runs three jitted pod steps plus tree
        # frames on this 1-vCPU box; under concurrent-suite load 60 s left
        # too little margin (observed flake) while convergence itself is
        # geometric and finishes in a few seconds unloaded.
        assert _settle(quiesce, agreed, timeout=120), {
            n: dict(
                mean=float(jnp.mean(tr.read(0)["w"])),
                uplink=tr.peer.node.uplink,
                links=tr.peer.node.links,
                master=tr.peer.is_master,
                err=str(tr.peer._error),
            )
            for n, tr in survivors.items()
        }
        # and training actually mixed: the agreed consensus cannot equal
        # EVERY pod's own target simultaneously (targets differ by >= 1.0),
        # so agreement alone proves cross-pod deltas steered the models; no
        # per-pod distance assertion (the consensus may legitimately settle
        # near one pod's target depending on kill timing and mixing order)
    finally:
        for tr in pods.values():
            tr.close()
