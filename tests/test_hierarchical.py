"""Hierarchical tier tests: two pods (disjoint device subsets of the 8-device
virtual mesh) bridged through the TCP tree in one process — the multi-host
story at test scale (ICI inside each pod, the reference's tree between
pods)."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shared_tensor_tpu.parallel.mesh import make_mesh
from shared_tensor_tpu.train import HierarchicalTrainer
from tests.test_peer import _free_port


def _template():
    return {"w": jnp.zeros((8,), jnp.float32)}


def _quad_loss(p, b):
    # pull w toward the batch target; async-DP mixes the pods' targets
    return jnp.mean((p["w"] - b) ** 2)


def _meshes():
    devs = jax.devices()
    return make_mesh(2, 1, devices=devs[:2]), make_mesh(2, 1, devices=devs[2:4])


def _settle(fn, cond, timeout=15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        fn()
        if cond():
            return True
        time.sleep(0.1)
    return False


def test_add_propagates_between_pods():
    mesh_a, mesh_b = _meshes()
    port = _free_port()
    a = HierarchicalTrainer.create(mesh_a, "127.0.0.1", port, _template(), _quad_loss)
    try:
        b = HierarchicalTrainer.create(mesh_b, "127.0.0.1", port, _template(), _quad_loss)
        try:
            # pod A: every device peer adds 1s (out-of-band update)
            a.pod.add(jnp.ones((a.pod.n_peer, a.pod.spec.total), jnp.float32))
            # intra-pod sync + bridge exchanges until B sees ~2.0 per slot
            # (2 device peers x +1 each)
            def pump():
                batch = jnp.zeros((2, 8), jnp.float32)
                a.step(batch, lr=0.0)
                b.step(batch, lr=0.0)

            ok = _settle(
                pump,
                lambda: np.allclose(
                    np.asarray(b.read(0)["w"]), 2.0, atol=0.05
                ),
            )
            assert ok, np.asarray(b.read(0)["w"])
        finally:
            b.close()
    finally:
        a.close()


def test_two_pod_training_converges_to_mixture():
    """Pod A trains toward +2, pod B toward -2; through the bridge both
    models settle near the mixture (0) instead of their local target —
    proof the cross-pod deltas actually steer training."""
    mesh_a, mesh_b = _meshes()
    port = _free_port()
    a = HierarchicalTrainer.create(mesh_a, "127.0.0.1", port, _template(), _quad_loss)
    try:
        b = HierarchicalTrainer.create(mesh_b, "127.0.0.1", port, _template(), _quad_loss)
        try:
            ta = jnp.full((2, 8), 2.0)
            tb = jnp.full((2, 8), -2.0)
            for _ in range(150):
                a.step(ta, lr=0.05)
                b.step(tb, lr=0.05)
                time.sleep(0.002)  # let tree frames flow
            # During live opposing training the pods disagree only by the
            # in-flight delta mass (local-only would sit at +2/-2)...
            wa = float(jnp.mean(a.read(0)["w"]))
            wb = float(jnp.mean(b.read(0)["w"]))
            assert abs(wa) < 1.6, wa
            assert abs(wb) < 1.6, wb
            # ...and once updates stop, the backlog drains and both pods
            # agree — the reference's eventual-consistency contract
            # (README.md:24, "values may overshoot temporarily").
            def quiesce():
                a.step(ta, lr=0.0)
                b.step(tb, lr=0.0)

            def agreed():
                va = float(jnp.mean(a.read(0)["w"]))
                vb = float(jnp.mean(b.read(0)["w"]))
                return abs(va - vb) < 0.05

            assert _settle(quiesce, agreed), (
                float(jnp.mean(a.read(0)["w"])),
                float(jnp.mean(b.read(0)["w"])),
            )
        finally:
            b.close()
    finally:
        a.close()


def test_layout_mismatch_rejected():
    from shared_tensor_tpu.comm.peer import create_or_fetch
    from shared_tensor_tpu.train import PodTrainer

    mesh_a, _ = _meshes()
    port = _free_port()
    peer = create_or_fetch("127.0.0.1", port, _template())
    try:
        pod = PodTrainer(mesh_a, {"x": jnp.zeros((3, 3))}, _quad_loss)
        with pytest.raises(ValueError, match="layout"):
            HierarchicalTrainer(pod, peer)
    finally:
        peer.close()
