"""Executable evidence for the GSPMD multi-host tier (VERDICT.md round-1
item 8): two REAL processes form a jax.distributed cluster over loopback,
build the global (peer, shard) mesh with parallel/mesh.py, and run a real
cross-process collective. This is the jax.distributed analog of the
reference's N-processes-on-localhost dev story (SURVEY.md §4.1)."""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent(
    """
    import os, sys
    port, pid = sys.argv[1], int(sys.argv[2])
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from shared_tensor_tpu.parallel.mesh import init_multihost, make_mesh

    idx = init_multihost(f"127.0.0.1:{port}", 2, pid)
    assert idx == pid, (idx, pid)
    assert jax.process_count() == 2
    assert len(jax.devices()) == 4  # 2 procs x 2 virtual devices
    # documented idempotency: a second call must no-op, not raise
    assert init_multihost(f"127.0.0.1:{port}", 2, pid) == pid

    # a real cross-process collective through the coordinator
    from jax.experimental import multihost_utils
    got = multihost_utils.broadcast_one_to_all(np.int32(7 * pid + 3))
    assert int(got) == 3, got  # everyone sees process 0's value

    # the pod mesh spans both processes; psum over the peer axis must sum
    # contributions from devices this process cannot address directly
    from shared_tensor_tpu.parallel.ici import shard_map  # version-shimmed
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh(4, 1)
    local = np.full((2, 8), float(pid + 1), "f4")  # proc0 rows=1, proc1 rows=2
    x = multihost_utils.host_local_array_to_global_array(
        local, mesh, P("peer", None)
    )
    f = jax.jit(
        shard_map(
            lambda a: jax.lax.psum(a, "peer"),
            mesh=mesh, in_specs=P("peer", None), out_specs=P(),
        )
    )
    total = f(x)
    # 2 devices hold 1.0 rows + 2 devices hold 2.0 rows -> psum = 6.0
    np.testing.assert_allclose(
        np.asarray(total.addressable_data(0)), np.full((1, 8), 6.0, "f4")
    )
    print("MULTIHOST_OK", pid)
    """
)


from tests._ports import free_port as _free_port


def test_two_process_gspmd_mesh(tmp_path):
    port = _free_port()
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(port), str(pid)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, cwd=REPO,
        )
        for pid in (0, 1)
    ]
    outs = []
    for pid, p in enumerate(procs):
        out, err = p.communicate(timeout=150)
        outs.append((pid, p.returncode, out, err))
    for pid, rc, out, err in outs:
        assert rc == 0, f"proc {pid} rc={rc}\n{err[-1500:]}"
        assert f"MULTIHOST_OK {pid}" in out
