"""Pod async-DP trainer tests on the 8-device virtual CPU mesh
(SURVEY.md §4.2 tier 2): the full fused grads + add_updates + compressed
sync step — BASELINE config 2's shape (char-rnn, 4 peers, compression on)
at test scale."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shared_tensor_tpu.models import char_rnn as m
from tests._mesh import make_mesh
from shared_tensor_tpu.train import PodTrainer

CFG = m.CharRNNConfig(vocab=64, embed=16, hidden=32, layers=1)
TEXT = b"the quick brown fox jumps over the lazy dog. " * 60


def _trainer(n_peer=4, n_shard=1, **kw):
    mesh = make_mesh(n_peer, n_shard)
    params = m.init_params(jax.random.key(0), CFG)
    loss = lambda p, b: m.loss_fn(p, b, CFG)
    return PodTrainer(mesh, params, loss, **kw)


def _batches(key, n_peer, batch=4, seq=16):
    return m.make_batches(
        TEXT, batch=batch, seq=seq, key=key, n_peer=n_peer, vocab=CFG.vocab
    )


def test_train_step_runs_and_loss_decreases():
    tr = _trainer(n_peer=4)
    first = last = None
    for i in range(80):
        batch = tr.shard_batch(_batches(jax.random.key(i), 4))
        losses, scales = tr.step(batch, lr=0.3)
        mean = float(jnp.mean(losses))
        first = mean if first is None else first
        last = mean
    assert losses.shape == (4,)
    assert scales.shape[0] == 4
    assert last < first * 0.7, (first, last)


def test_peers_stay_consistent_under_compression():
    """Replicas drift only within the codec's bounded overshoot — after
    training quiesces (no more updates), pure sync steps pull all replicas
    together to within a few final-frame scales (reference README.md:24's
    eventual consistency; quirk Q3's +/-scale oscillation is the floor —
    converged elements keep bouncing within +/-scale, so spread is bounded,
    not zero)."""
    tr = _trainer(n_peer=4)
    for i in range(10):
        batch = tr.shard_batch(_batches(jax.random.key(i), 4))
        tr.step(batch, lr=0.3)
    # Quiesce: no new grads, keep syncing via zero-lr steps on a fixed batch.
    batch = tr.shard_batch(_batches(jax.random.key(99), 4))
    for _ in range(60):
        _, scales = tr.step(batch, lr=0.0)
    floor = float(jnp.max(scales))
    spread = tr.replica_spread()
    # 8 scales per other-peer link: the +/-scale oscillation of quirk Q3
    # superposes across links and is trajectory-dependent — XLA-version fp
    # drift moves which elements sit mid-oscillation at the final step
    # (measured 17x floor on jax 0.4.37 vs ~12x when the 4x bound was
    # calibrated); the scale-PROPORTIONAL shape of the bound is the claim
    assert spread <= max(8 * (4 - 1) * floor, 1e-6), (spread, floor)
    assert spread < 0.02, spread


def test_exact_arm_keeps_replicas_identical():
    """compressed=False is the exact-allreduce comparison arm (BASELINE
    config 4): replicas must agree to float rounding after every step
    (exactly equal is impossible: peer p computes (v+u_p)+(S-u_p), whose
    rounding differs per peer)."""
    tr_exact = _trainer(n_peer=4, compressed=False)
    for i in range(5):
        batch = tr_exact.shard_batch(_batches(jax.random.key(i), 4))
        tr_exact.step(batch, lr=0.3)
    v = np.asarray(tr_exact.state.values)
    np.testing.assert_allclose(v[0], v[1], atol=1e-5)
    np.testing.assert_allclose(v[0], v[3], atol=1e-5)
    # and residuals fully drain every step
    assert float(jnp.max(jnp.abs(tr_exact.state.residual))) == 0.0


def test_compressed_tracks_exact_training():
    """Compression must not wreck optimization: compressed-arm loss stays
    within a modest factor of the exact arm on the same data stream."""
    tr_c = _trainer(n_peer=4, compressed=True)
    tr_e = _trainer(n_peer=4, compressed=False)
    for i in range(25):
        b = _batches(jax.random.key(i), 4)
        lc, _ = tr_c.step(tr_c.shard_batch(b), lr=0.3)
        le, _ = tr_e.step(tr_e.shard_batch(b), lr=0.3)
    assert float(jnp.mean(lc)) < float(jnp.mean(le)) * 1.35 + 0.1


def test_sharded_table_trains():
    """peer x shard mesh: the replica buffer itself is sharded (quirk Q6
    fix); training must still run and learn."""
    tr = _trainer(n_peer=4, n_shard=2)
    first = last = None
    for i in range(15):
        batch = tr.shard_batch(_batches(jax.random.key(i), 4))
        losses, _ = tr.step(batch, lr=0.3)
        mean = float(jnp.mean(losses))
        first = mean if first is None else first
        last = mean
    assert last < first, (first, last)


def test_read_returns_template_structure():
    tr = _trainer(n_peer=2)
    params = tr.read(0)
    assert set(params.keys()) == {"embed", "lstm", "proj"}
    assert params["embed"].shape == (CFG.vocab, CFG.embed)


def test_no_sync_arm_diverges_replicas():
    """sync=False isolation baseline: peers training on different data must
    drift apart (sanity check that sync is what keeps them together)."""
    tr = _trainer(n_peer=4, sync=False)
    for i in range(5):
        batch = tr.shard_batch(_batches(jax.random.key(i), 4))
        tr.step(batch, lr=0.3)
    assert tr.replica_spread() > 1e-4


def test_optax_optimizer_trains():
    """optax momentum per peer: loss decreases and per-peer optimizer state
    is carried across steps."""
    import optax

    tr = _trainer(n_peer=4, optimizer=optax.sgd(0.3, momentum=0.9))
    first = last = None
    for i in range(40):
        batch = tr.shard_batch(_batches(jax.random.key(i), 4))
        losses, _ = tr.step(batch)
        mean = float(jnp.mean(losses))
        first = mean if first is None else first
        last = mean
    assert last < first * 0.8, (first, last)
    assert tr.opt_state is not None


def test_overlap_trainer_trains_and_stays_consistent():
    """overlap=True (collective under the backward pass): loss decreases,
    replicas stay mutually consistent, and after training stops the extra
    sync steps drain every replica to the same point (the one-step-later
    delivery must not strand any mass)."""
    tr = _trainer(n_peer=4, overlap=True)
    first = last = None
    for i in range(80):
        batch = tr.shard_batch(_batches(jax.random.key(i), 4))
        losses, scales = tr.step(batch, lr=0.3)
        mean = float(jnp.mean(losses))
        first = mean if first is None else first
        last = mean
    assert last < first * 0.9, (first, last)
    # drain: sync-only steps deliver the in-flight tail. Heavy-tailed grad
    # residuals drain their outliers only +/-scale per frame (same as the C
    # reference), so the bar is "shrinks like the fused trainer does", not
    # exact zero: measured fused-mode spread after the same 40 drains is
    # ~0.017 on this config.
    from shared_tensor_tpu.parallel.ici import build_sync_step

    spread0 = tr.replica_spread()
    drain = build_sync_step(tr.mesh, tr.spec)
    for _ in range(40):
        tr.state, _ = drain(tr.state)
    spread = tr.replica_spread()
    assert spread < 0.05 and spread < spread0, (spread0, spread)
    assert np.isfinite(np.asarray(tr.state.values)).all()


def test_overlap_vs_fused_convergence_ab():
    """Convergence A/B (round-3 verdict item 4): the overlap arm's one-step-
    delayed delivery must be *statistically* indistinguishable from fused —
    not just compose-parity (bit-identical composition is pinned elsewhere;
    this trains both arms on the SAME pinned data stream to comparable
    loss). Bars: tail losses within 10% of each other, and both arms
    actually learned (tail well under the initial loss)."""
    steps = 240
    tail = 40
    curves = {}
    for overlap in (False, True):
        tr = _trainer(n_peer=4, overlap=overlap)
        losses = []
        for i in range(steps):
            batch = tr.shard_batch(_batches(jax.random.key(i), 4))
            l, _ = tr.step(batch, lr=0.3)
            losses.append(float(jnp.mean(l)))
        curves[overlap] = losses
        assert np.isfinite(np.asarray(tr.state.values)).all()
    fused_tail = float(np.mean(curves[False][-tail:]))
    over_tail = float(np.mean(curves[True][-tail:]))
    first = curves[False][0]
    # both arms learned
    assert fused_tail < first * 0.5, (first, fused_tail)
    assert over_tail < first * 0.5, (first, over_tail)
    # and to statistically comparable loss: the inter-arm gap must be small
    # relative to the loss scale AND small relative to within-arm noise
    gap = abs(fused_tail - over_tail)
    noise = max(
        float(np.std(curves[False][-tail:])),
        float(np.std(curves[True][-tail:])),
        1e-9,
    )
    assert gap <= 0.1 * fused_tail + 1e-6, (fused_tail, over_tail)
    assert gap <= 3.0 * noise, (gap, noise)


def test_overlap_requires_compressed_sync():
    import pytest

    with pytest.raises(ValueError):
        _trainer(n_peer=2, overlap=True, compressed=False)


def test_sync_every_paces_exchanges():
    """sync_every=2: off-beat steps run the no-sync program (scales all 0,
    updates pile into the residual); the beat step delivers the accumulated
    sum as one frame. Training still converges and replicas stay bounded."""
    tr = _trainer(n_peer=4, sync_every=2)
    first = last = None
    beat_scales, off_scales = [], []
    for i in range(60):
        batch = tr.shard_batch(_batches(jax.random.key(i), 4))
        losses, scales = tr.step(batch, lr=0.3)
        mean = float(jnp.mean(losses))
        first = mean if first is None else first
        last = mean
        (beat_scales if tr.steps % 2 == 0 else off_scales).append(
            float(jnp.max(scales))
        )
    assert last < first * 0.9, (first, last)
    assert all(s == 0.0 for s in off_scales)  # off-beats exchange nothing
    assert any(s > 0.0 for s in beat_scales)  # beats carry the frames
    assert np.isfinite(np.asarray(tr.state.values)).all()
