"""The codec lab (ops/codec_lab.py — reference README.md:45's "try
different compression methods" TODO): every method must keep the two
invariants the framework's semantics rest on, and the documented
convergence orderings must actually hold on real trajectories."""

import numpy as np
import pytest

from shared_tensor_tpu.ops.codec_lab import Sign1, Sign2, TopK, standard_lab

N = 4096


def _codecs():
    return standard_lab(N)


@pytest.mark.parametrize("codec", _codecs(), ids=lambda c: c.name)
def test_conservation(codec):
    """residual_in == decode(frame) + residual_out to within 1 ulp of the
    sent magnitude (the f32 subtraction rounds when exponents differ — the
    production codec documents the same receiver-side ~1 ulp); TopK ships
    exact f32 copies, so for it the identity is bit-for-bit."""
    rng = np.random.default_rng(0)
    r = rng.standard_normal(N).astype(np.float32)
    frame, new_r = codec.encode(r)
    delta = codec.decode(frame, N)
    if codec.name.startswith("topk"):
        np.testing.assert_array_equal(delta + new_r, r)
    else:
        # ulp(sent) <= ulp(1.5 * scale) <= 2^-22 * scale
        bound = frame.scale * 2.0**-22
        np.testing.assert_allclose(delta + new_r, r, rtol=0, atol=bound)


@pytest.mark.parametrize("codec", _codecs(), ids=lambda c: c.name)
def test_idle_on_zero_residual(codec):
    r = np.zeros(N, np.float32)
    frame, new_r = codec.encode(r)
    np.testing.assert_array_equal(new_r, r)
    np.testing.assert_array_equal(codec.decode(frame, N), np.zeros(N, np.float32))
    assert frame.payload_bytes <= 4  # idle frames cost at most the header


@pytest.mark.parametrize("codec", _codecs(), ids=lambda c: c.name)
def test_payload_bytes_honest(codec):
    """The Pareto's bytes axis must match what the data actually occupies."""
    rng = np.random.default_rng(1)
    r = rng.standard_normal(N).astype(np.float32)
    frame, _ = codec.encode(r)
    assert frame.payload_bytes == 4 + frame.data.nbytes


def _frames_to_drain(codec, r, max_frames=200):
    for i in range(max_frames):
        if not r.any():
            return i
        frame, r = codec.encode(r)
        if frame.payload_bytes <= 4 and r.any():
            pytest.fail(f"{codec.name} idled on a nonzero residual")
    return max_frames


def test_sign1_exact_convergence_unchanged():
    """The lab baseline reproduces the production codec's signature
    behavior: a uniform residual drains to exactly zero in ~27 frames
    (SURVEY.md App. B; pinned on the production tier in test_codec.py)."""
    rng = np.random.default_rng(2)
    r = rng.uniform(-1.0, 1.0, N).astype(np.float32)
    frames = _frames_to_drain(Sign1(), r)
    assert 20 <= frames <= 35, frames


def test_sign2_uniform_trajectory_identical_to_sign1():
    """On a uniform residual |r| never exceeds 2s, so Sign2's magnitude
    bit is idle and its trajectory must be BIT-identical to Sign1's —
    which is how Sign2 inherits the exact-drain property."""
    rng = np.random.default_rng(3)
    r1 = rng.uniform(-1.0, 1.0, N).astype(np.float32)
    r2 = r1.copy()
    s1, s2 = Sign1(), Sign2()
    for _ in range(30):
        if not r1.any():
            break
        f1, r1 = s1.encode(r1)
        f2, r2 = s2.encode(r2)
        assert f1.scale == f2.scale
        np.testing.assert_array_equal(r1, r2)
    assert not r1.any() and not r2.any()


def _rms(r):
    return float(np.sqrt(np.mean(r.astype(np.float64) ** 2)))


def test_sign2_faster_per_frame_on_gaussian():
    """The 2-bit quantizer's point: on dense gaussian residuals (where the
    magnitude bit fires in the tails) it decays faster per frame than
    Sign1 — it pays 2x the bytes for latency. Design-sweep measurement:
    ~0.79 vs ~0.85 geometric-mean decay over 20 frames."""
    rng = np.random.default_rng(7)
    r0 = rng.standard_normal(1 << 14).astype(np.float32)

    def decay(codec, frames=20):
        r = r0.copy()
        for _ in range(frames):
            _, r = codec.encode(r)
        return (_rms(r) / _rms(r0)) ** (1.0 / frames)

    d1, d2 = decay(Sign1()), decay(Sign2())
    assert d2 < d1 - 0.02, (d2, d1)


def test_topk_full_k_converges_in_one_frame():
    """k == n ships the whole residual exactly."""
    rng = np.random.default_rng(4)
    r = rng.standard_normal(N).astype(np.float32)
    frame, new_r = TopK(N).encode(r)
    assert not new_r.any()
    np.testing.assert_array_equal(TopK(N).decode(frame, N), r)


def test_topk_wins_on_heavy_tailed_residuals_per_byte():
    """The trade the lab exists to measure: on a heavy-tailed residual
    (few coordinates carry most of the RMS), sparse exact transfer beats
    dense 1-bit per byte sent; Sign1 keeps dense-noise workloads."""

    def rms_after_budget(codec, r, byte_budget):
        spent = 0
        while spent < byte_budget:
            frame, r = codec.encode(r)
            spent += frame.payload_bytes
            if frame.payload_bytes <= 4:
                break
        return float(np.sqrt(np.mean(r.astype(np.float64) ** 2)))

    rng = np.random.default_rng(5)
    heavy = (rng.standard_t(1.2, N) * 1e-3).astype(np.float32)
    heavy[rng.integers(0, N, 8)] += rng.choice([-100.0, 100.0], 8).astype(
        np.float32
    )
    budget = 3 * (4 + N // 8)  # three sign1 frames' worth of bytes
    r_sign = rms_after_budget(Sign1(), heavy.copy(), budget)
    r_topk = rms_after_budget(TopK(N // 32), heavy.copy(), budget)
    assert r_topk < r_sign, (r_topk, r_sign)


def test_topk_indices_exact_past_2_24():
    """Index transport must be exact for any table size this framework
    ships (PARETO_r03 goes to 64 Mi): a float32 round-trip would corrupt
    indices past 2^24 — the lab views u32 bit patterns instead."""
    n = (1 << 24) + 64
    r = np.zeros(n, np.float32)
    r[-1] = 5.0  # index 2^24 + 63: not representable in f32
    frame, new_r = TopK(1).encode(r)
    assert not new_r.any()
    delta = TopK(1).decode(frame, n)
    assert delta[-1] == 5.0
    assert np.count_nonzero(delta) == 1
