"""End-to-end peer-engine tests: N full nodes in one process on loopback —
the reference's entire dev story (SURVEY.md §4.1: multiple processes on
localhost IS how shared-tensor is tested; here multiple nodes in one process).

Covers BASELINE config 1 (the example.lua round-trip: createOrFetch +
addFromTensor/copyToTensor, 2-node loopback), the eventual-consistency
contract (reference README.md:24: after traffic quiesces every replica equals
seed + sum of all updates), table sync, and fault handling the reference
lacks (join-with-state, peer death without process death)."""

import socket
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shared_tensor_tpu.comm.peer import SpecMismatch, create_or_fetch
from shared_tensor_tpu.comm.transport import build_native
from shared_tensor_tpu.config import CodecConfig, Config, TransportConfig


@pytest.fixture(scope="module", autouse=True)
def _built():
    build_native()


from tests._ports import free_port as _free_port


CFG = Config(transport=TransportConfig(peer_timeout_sec=10.0))


def _wait_converged(peers, expect, tol=1e-6, timeout=90.0):
    """Poll until every peer's replica equals ``expect`` within tol (the
    codec converges *exactly* in finitely many frames for fp32 data —
    BASELINE.md: ~28 frames for U(-1,1)). The window is sized for a loaded
    1-vCPU box running concurrent suites — convergence itself takes <1s
    unloaded; slow must not read as wrong."""
    expect_leaves = jax.tree.leaves(expect)
    deadline = time.time() + timeout
    while time.time() < deadline:
        ok = True
        for p in peers:
            got = jax.tree.leaves(p.read())
            if not all(
                np.allclose(g, e, rtol=1e-4, atol=tol)
                for g, e in zip(got, expect_leaves)
            ):
                ok = False
                break
        if ok:
            return
        time.sleep(0.05)
    for i, p in enumerate(peers):
        got = jax.tree.leaves(p.read())
        for g, e in zip(got, expect_leaves):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(e), rtol=1e-4, atol=tol,
                err_msg=f"peer {i} did not converge",
            )


def test_example_lua_roundtrip():
    """BASELINE config 1: the reference's example.lua on loopback — master
    seeds a 4x5x6x2 float32, a second node fetches it, both add deltas, both
    converge to the common sum (reference example.lua:1-26)."""
    port = _free_port()
    seed = jnp.arange(1.0, 241.0, dtype=jnp.float32).reshape(4, 5, 6, 2)
    with create_or_fetch("127.0.0.1", port, seed, CFG) as master:
        assert master.is_master
        np.testing.assert_array_equal(np.asarray(master.read()), np.asarray(seed))
        with create_or_fetch(
            "127.0.0.1", port, jnp.zeros_like(seed), CFG
        ) as joiner:
            assert not joiner.is_master
            # joiner receives the seeded state through the codec stream
            _wait_converged([joiner], seed)
            # both sides add; everyone converges to seed + both deltas
            d1 = jnp.full_like(seed, 1.0)
            d2 = jnp.full_like(seed, 0.5)
            master.add(d1)
            joiner.add(d2)
            _wait_converged([master, joiner], seed + d1 + d2)
            m = master.metrics()
            assert (
                m["st_frames_out_total"] > 0 and m["st_frames_in_total"] > 0
            )


def test_four_peer_tree_consistency():
    """4 peers (so one is redirected below the master's children): every
    replica converges to seed + sum of every peer's update through split-
    horizon flooding with per-hop re-quantization."""
    port = _free_port()
    seed = {"w": jnp.ones((16, 8), jnp.float32), "b": jnp.zeros((8,), jnp.float32)}
    peers = [create_or_fetch("127.0.0.1", port, seed, CFG)]
    try:
        for _ in range(3):
            peers.append(
                create_or_fetch(
                    "127.0.0.1", port, jax.tree.map(jnp.zeros_like, seed), CFG
                )
            )
        _wait_converged(peers, seed)
        rng = np.random.default_rng(0)
        total = jax.tree.map(jnp.asarray, seed)
        for i, p in enumerate(peers):
            delta = {
                "w": jnp.asarray(
                    rng.normal(size=(16, 8)).astype(np.float32) * (i + 1)
                ),
                "b": jnp.asarray(rng.normal(size=(8,)).astype(np.float32)),
            }
            p.add(delta)
            total = jax_tree_add(total, delta)
        _wait_converged(peers, total, tol=1e-5)
    finally:
        for p in peers:
            p.close()


def jax_tree_add(a, b):
    return jax.tree.map(lambda x, y: x + y, a, b)


def test_mixed_magnitude_table_sync():
    """The reference README's top TODO (README.md:41): a table with 1000:1
    magnitude spread syncs accurately because each leaf gets its own scale
    (single-scale degrades to ~0.15 bits/frame — BASELINE.md)."""
    port = _free_port()
    seed = {
        "big": jnp.full((256,), 1000.0, jnp.float32),
        "small": jnp.full((256,), 1.0, jnp.float32),
    }
    with create_or_fetch("127.0.0.1", port, seed, CFG) as master:
        with create_or_fetch(
            "127.0.0.1", port, jax.tree.map(jnp.zeros_like, seed), CFG
        ) as joiner:
            # converges exactly — with one global scale the small leaf would
            # still be at ~24% error after 48 frames (generous timeout: under
            # parallel suite load a 1-vCPU box schedules these peers slowly)
            _wait_converged([joiner], seed, timeout=60.0)


def test_regraft_after_parent_death():
    """A mid-tree node CRASHES (no drain); its orphaned child re-grafts
    through the rendezvous walk onto a surviving node (diff-seeded handshake
    + carried residual — the reference exit(-1)s the whole tree instead,
    quirk Q8).

    Asserts the crash arm of the delivery contract (core.SharedTensor):
    state that finished propagating before the crash is NEVER lost, the
    survivors always re-converge to exact agreement, and the racing updates
    land 0..4 times total (mass in flight through the crashing interior node
    at that instant may be dropped; everything else propagates).

    Topology: master M with children A and B (max_children=2), C redirected
    under one of them. Killing C's parent forces a real re-graft."""
    port = _free_port()
    seed = jnp.ones((256,), jnp.float32)
    cfg = Config(
        transport=TransportConfig(peer_timeout_sec=5.0, max_rejoin_attempts=8)
    )
    m = create_or_fetch("127.0.0.1", port, seed, cfg)
    peers = {"m": m}
    try:
        for name in ("a", "b", "c"):
            peers[name] = create_or_fetch(
                "127.0.0.1", port, jnp.zeros_like(seed), cfg
            )
        # round 1: fully propagated BEFORE the crash -> can never be lost
        for p in peers.values():
            p.add(jnp.full((256,), 0.5, jnp.float32))
        settled = jnp.full((256,), 1.0 + 4 * 0.5, jnp.float32)
        _wait_converged(list(peers.values()), settled)
        parent_name = next(
            n for n, p in peers.items()
            if not p.is_master and len(p.node.links) > 1
        )
        # round 2: updates racing the crash
        for p in peers.values():
            p.add(jnp.full((256,), 0.25, jnp.float32))
        peers.pop(parent_name).close()
        survivors = list(peers.values())
        # 120 s (like the hierarchical churn test): under full-suite load on
        # one core the regraft (5 s peer timeout + rejoin backoff) plus the
        # re-delivery drain intermittently exceeded 90 s (~2 in 20 loaded
        # runs; never reproducible in isolation).
        deadline = time.time() + 120
        while time.time() < deadline:
            vals = [np.asarray(p.read()) for p in survivors]
            spread = max(np.max(np.abs(v - vals[0])) for v in vals)
            if spread < 1e-4:
                break
            time.sleep(0.1)
        vals = [np.asarray(p.read()) for p in survivors]
        spread = max(np.max(np.abs(v - vals[0])) for v in vals)
        assert spread < 1e-4, f"survivor replicas diverged by {spread}"
        # at-least-once: each racing update lands 0..2 times (lost through
        # the crashing interior node, once normally, or twice when a
        # delivered-but-unACKed frame is rolled back and re-delivered
        # through the re-graft) — never corrupted, never diverging
        lo, hi = 1.0 + 4 * 0.5 - 1e-4, 1.0 + 4 * 0.5 + 2 * 4 * 0.25 + 1e-4
        for v in vals:
            assert lo <= v.min() and v.max() <= hi, (
                f"replica outside contract bounds [{lo}, {hi}]: "
                f"min {v.min()} max {v.max()}"
            )
    finally:
        for p in peers.values():
            p.close()


@pytest.mark.parametrize("native", [True, False])
def test_compat_leaf_regraft_keeps_orphan_adds(native):
    """Wire-compat re-graft of a LEAF whose parent died: the reference
    protocol has no diff handshake, so the leaf resets to fresh-joiner
    state — which must mean replica == carry (a true fresh joiner with
    pending adds holds them in values AND residual), NOT replica == 0.
    A zero reset desyncs the leaf by exactly the carry forever: the carry
    floods to every OTHER peer and split horizon never returns it
    (core.SharedTensor.regraft_reset_to_carry; the engine analog is
    st_engine_compat_regraft — both tiers parametrized here).

    Topology: master M + children A, B; C redirected under one of them.
    Kill C's parent, wait until C is orphaned, then add at C — the add is
    guaranteed undelivered (it lands in the live carry slot) — and assert
    every survivor INCLUDING C converges to the full sum.

    peer_timeout is 15 s, deliberately above this box's worst loaded-run
    scheduler stalls: the test pins the SINGLE-event leaf re-graft, whose
    outcome is exact; a SECONDARY spurious liveness timeout during the
    recovery window fires the documented compat interior re-seed
    double-count (README.md delivery-contract notes) — a real, documented
    protocol property, but a different scenario than this test's subject
    (one full-suite run in ~15 observed exactly that: a survivor at
    settled+carry+settled). Multi-event compat churn is bounded by
    SOAK_COMPAT_r04.json's envelope instead."""
    port = _free_port()
    seed = jnp.ones((256,), jnp.float32)
    cfg = Config(
        native_engine=native,
        transport=TransportConfig(
            peer_timeout_sec=15.0, max_rejoin_attempts=8, wire_compat=True
        ),
    )
    m = create_or_fetch("127.0.0.1", port, seed, cfg)
    peers = {"m": m}
    try:
        for name in ("a", "b", "c"):
            peers[name] = create_or_fetch(
                "127.0.0.1", port, jnp.zeros_like(seed), cfg
            )
        # the tier under test must actually be the one running: a silent
        # engine-construction fallback would vacuously re-test python
        for p in peers.values():
            assert (p._engine is not None) == native
        for p in peers.values():
            p.add(jnp.full((256,), 0.5, jnp.float32))
        settled = jnp.full((256,), 1.0 + 4 * 0.5, jnp.float32)
        _wait_converged(list(peers.values()), settled)
        # the interior child (2 links: uplink + its own child)
        parent_name = next(
            n for n, p in peers.items()
            if not p.is_master and len(p.node.links) > 1
        )
        candidates = [p for n, p in peers.items() if n != "m"]
        before = {id(p): p._uplink for p in candidates}
        peers.pop(parent_name).close()
        # the orphan is whichever non-master survivor loses its uplink; if
        # it re-grafts between polls (new link id) the add below just rides
        # the new uplink — also covered by the contract, only less pointed
        orphan = None
        deadline = time.time() + 90
        while orphan is None and time.time() < deadline:
            orphan = next(
                (p for p in candidates if p._uplink != before[id(p)]), None
            )
            time.sleep(0.05)
        assert orphan is not None, "orphan never detected parent death"
        # now guaranteed-undelivered: this add exists only in C's replica
        # and its live carry slot
        orphan.add(jnp.full((256,), 0.25, jnp.float32))
        survivors = list(peers.values())
        expect = jnp.full((256,), 1.0 + 4 * 0.5 + 0.25, jnp.float32)
        # generous: re-graft needs the 5 s timeout + the rejoin walk, and
        # under 2-worker xdist on this 1-vCPU box the whole sequence is
        # scheduled against a concurrent full suite (one observed 120 s
        # miss in ~10 loaded runs; 180 s follows the churn tests' margin)
        _wait_converged(survivors, expect, tol=1e-4, timeout=180.0)
    finally:
        for p in peers.values():
            p.close()


def test_graceful_leave_loses_nothing():
    """drain() + close() = the zero-loss arm of the delivery contract: after
    a successful drain, EVERY update the leaving node ever merged — its own
    and the in-transit mass it was flooding — lives in its neighbors'
    replicas, so the survivors converge to the full sum."""
    port = _free_port()
    seed = jnp.ones((128,), jnp.float32)
    cfg = Config(
        transport=TransportConfig(peer_timeout_sec=5.0, max_rejoin_attempts=8)
    )
    m = create_or_fetch("127.0.0.1", port, seed, cfg)
    peers = {"m": m}
    try:
        for name in ("a", "b", "c"):
            peers[name] = create_or_fetch(
                "127.0.0.1", port, jnp.zeros_like(seed), cfg
            )
        parent_name = next(
            n for n, p in peers.items()
            if not p.is_master and len(p.node.links) > 1
        )
        for p in peers.values():
            p.add(jnp.full((128,), 0.25, jnp.float32))
        leaver = peers.pop(parent_name)
        # drain() guarantees everything the LEAVER holds is delivered; for a
        # deterministic zero-loss assertion the peers streaming INTO it must
        # quiesce first (a frame landing between drain-true and close is the
        # leaver's to flood, and a closing node can't flood it)
        deadline = time.time() + 30
        while time.time() < deadline:
            if all(
                p.st.inflight_total() == 0
                and all(p.st.residual_rms(l) == 0.0 for l in p.st.link_ids)
                for p in peers.values()
            ):
                break
            time.sleep(0.05)
        assert leaver.drain(timeout=30.0), "drain did not complete"
        leaver.close()
        expect = jnp.full((128,), 1.0 + 4 * 0.25, jnp.float32)
        _wait_converged(list(peers.values()), expect, timeout=40.0)
    finally:
        for p in peers.values():
            p.close()


def test_spec_mismatch_rejected():
    """Joining with a different table layout must fail loudly at join time
    (reference THError 'Not the right size!' src/sharedtensor.c:335 — but
    only after corrupting the unframed stream)."""
    port = _free_port()
    with create_or_fetch(
        "127.0.0.1", port, jnp.ones((64,), jnp.float32), CFG
    ):
        with pytest.raises((SpecMismatch, TimeoutError)):
            p = create_or_fetch(
                "127.0.0.1",
                port,
                jnp.ones((128,), jnp.float32),
                CFG,
                timeout=10.0,
            )
            p.close()


def test_peer_death_survival_and_convergence():
    """A peer dying must not kill the tree (reference quirk Q8: exit(-1)
    everywhere), and the survivors keep syncing."""
    port = _free_port()
    seed = jnp.ones((128,), jnp.float32)
    cfg = Config(
        transport=TransportConfig(peer_timeout_sec=5.0, max_rejoin_attempts=8)
    )
    master = create_or_fetch("127.0.0.1", port, seed, cfg)
    victim = create_or_fetch("127.0.0.1", port, jnp.zeros_like(seed), cfg)
    survivor = create_or_fetch("127.0.0.1", port, jnp.zeros_like(seed), cfg)
    try:
        _wait_converged([victim, survivor], seed)
        victim.close()
        time.sleep(0.2)
        master.add(jnp.full((128,), 2.0, jnp.float32))
        _wait_converged([master, survivor], seed + 2.0, timeout=30.0)
    finally:
        master.close()
        survivor.close()


def test_idle_links_quiesce():
    """After convergence, links go quiet (no residual mass left). The
    reference instead emits one zero-scale frame per second per link forever
    (quirk Q2)."""
    port = _free_port()
    seed = jnp.ones((64,), jnp.float32)
    with create_or_fetch("127.0.0.1", port, seed, CFG) as a:
        with create_or_fetch("127.0.0.1", port, jnp.zeros_like(seed), CFG) as b:
            _wait_converged([b], seed)
            time.sleep(0.5)
            f0 = a.st.frames_out
            time.sleep(1.0)
            # allow a stray in-flight frame, but no steady 1/s drumbeat
            assert a.st.frames_out - f0 <= 1


def test_master_death_failover():
    """The MASTER dies; its orphans cannot rejoin (nobody owns the
    rendezvous) — one of them must claim the rendezvous address and become
    the new master (the OS arbitrates the sibling race), the other rejoins
    it, replicas re-converge, and a brand-new peer can still join the tree
    at the original address. The reference tree is dead at this point
    (quirk Q8: master death kills every process)."""
    port = _free_port()
    seed = jnp.ones((128,), jnp.float32)
    cfg = Config(
        transport=TransportConfig(peer_timeout_sec=5.0, max_rejoin_attempts=4)
    )
    m = create_or_fetch("127.0.0.1", port, seed, cfg)
    peers = [m]
    try:
        a = create_or_fetch("127.0.0.1", port, jnp.zeros_like(seed), cfg)
        peers.append(a)
        b = create_or_fetch("127.0.0.1", port, jnp.zeros_like(seed), cfg)
        peers.append(b)
        _wait_converged(peers, np.ones(128, np.float32))
        peers.remove(m)
        m.close()
        # Wait for the tree to HEAL AND QUIESCE before adding: one sibling
        # claims the rendezvous, the other re-grafts onto it, and no frame
        # from the churn is still in flight. Adds issued mid-churn can
        # legitimately land twice (delivered-but-unACKed frames roll back
        # into the carry residual and re-deliver — the at-least-once arm of
        # the delivery contract, same as test_regraft_after_parent_death).
        def healed():
            return (
                (a.is_master or b.is_master)
                and a.ready and b.ready
                and len(a.node.links) >= 1 and len(b.node.links) >= 1
                and a.st.inflight_total() == 0 and b.st.inflight_total() == 0
            )

        deadline = time.time() + 90  # suite convention: loaded-box window
        while time.time() < deadline and not healed():
            time.sleep(0.1)
        assert healed(), (
            "tree did not heal: "
            f"a(master={a.is_master}, links={a.node.links}, err={a._error}) "
            f"b(master={b.is_master}, links={b.node.links}, err={b._error})"
        )
        a.add(jnp.full((128,), 0.5, jnp.float32))
        b.add(jnp.full((128,), 0.25, jnp.float32))
        expect = np.full(128, 1.75, np.float32)
        _wait_converged([a, b], expect)
        # the healed tree still serves new joiners at the original address
        c = create_or_fetch("127.0.0.1", port, jnp.zeros_like(seed), cfg)
        peers.append(c)
        _wait_converged([a, b, c], expect)
    finally:
        for p in peers:
            p.close()


def test_isolation_is_recoverable():
    """REJOIN_FAILED is a status, not a sentence: a node that can neither
    join nor claim the rendezvous reports isolation (wait_ready raises), but
    the native layer keeps cycling and the error clears when the tree comes
    back. Forced deterministically by squatting the rendezvous with a
    listener that drops every connection (join fails fast, bind fails with
    EADDRINUSE)."""
    import threading

    port = _free_port()
    seed = jnp.full((64,), 2.0, jnp.float32)
    cfg = Config(
        transport=TransportConfig(peer_timeout_sec=2.0, max_rejoin_attempts=2)
    )
    m = create_or_fetch("127.0.0.1", port, seed, cfg)
    a = create_or_fetch("127.0.0.1", port, jnp.zeros_like(seed), cfg)
    try:
        _wait_converged([a], seed)
        m.close()
        # squat: listener that accepts and immediately drops (fast join
        # failure) while holding the port (bind failure for the orphan)
        squat = socket.socket()
        squat.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        squat.bind(("127.0.0.1", port))
        squat.listen(16)
        stop = threading.Event()

        def drop_all():
            squat.settimeout(0.2)
            while not stop.is_set():
                try:
                    c, _ = squat.accept()
                    c.close()
                except OSError:
                    continue

        t = threading.Thread(target=drop_all, daemon=True)
        t.start()
        try:
            deadline = time.time() + 60
            while time.time() < deadline and a._error is None:
                time.sleep(0.05)
            assert a._error is not None, "isolation was never reported"
            with pytest.raises(ConnectionError):
                a.wait_ready(timeout=0.1)
        finally:
            stop.set()
            squat.close()
            t.join(timeout=5)
        # the rendezvous is free again: the node heals (claims it, or joins
        # whoever does) and the error clears
        deadline = time.time() + 60
        while time.time() < deadline and not (a._error is None and a.ready):
            time.sleep(0.1)
        assert a._error is None, a._error
        a.wait_ready(timeout=5)
        a.add(jnp.full((64,), 0.5, jnp.float32))
        np.testing.assert_allclose(
            np.asarray(a.read()), np.full(64, 2.5, np.float32), rtol=1e-5
        )
    finally:
        a.close()


def test_frame_burst_knob():
    """Config.frame_burst: 0 = auto — the ENGINE tier fills the wire
    message budget at every size (throughput is monotone in K up to the
    cap); the PYTHON fallback tier bursts only small tables (each burst
    frame is a synchronous numpy rescan). 1 = stream single frames,
    K = force (clamped to the per-spec wire bound)."""
    from shared_tensor_tpu.comm import wire
    from shared_tensor_tpu.comm.engine import engine_eligible

    small = jnp.zeros((1000,), jnp.float32)  # padded 1024
    big = jnp.zeros((1 << 17,), jnp.float32)

    eng = engine_eligible(Config())
    auto_small = (lambda b: b == wire.BURST_MAX_FRAMES) if eng else (
        lambda b: b == 128
    )
    auto_big = (lambda b: b == wire.BURST_MAX_FRAMES) if eng else (
        lambda b: b == 1
    )
    for tpl, cfg, expect in [
        (small, Config(), auto_small),
        (small, Config(frame_burst=1), lambda b: b == 1),
        (small, Config(frame_burst=7), lambda b: b == 7),
        (small, Config(frame_burst=10_000), lambda b: b == wire.BURST_MAX_FRAMES),
        (big, Config(), auto_big),  # cap at 128Ki is 255
        (big, Config(frame_burst=64), lambda b: b == 64),
        (
            small,
            Config(codec=CodecConfig(suppress_zero_frames=False)),
            lambda b: b == 1,  # burst has no idle frames; honor the knob
        ),
    ]:
        port = _free_port()
        p = create_or_fetch("127.0.0.1", port, tpl, cfg)
        try:
            assert expect(p._burst), (cfg, p._burst)
        finally:
            p.close()


def test_device_tier_burst_path(monkeypatch):
    """Device-tier K-frame bursts (round-3 verdict item 3): with the XLA
    tier pinned (ST_HOST_CODEC=xla -> host_tier_active False, engine
    ineligible), peers take the begin_frame_burst_device path — K frames
    per ONE dispatch + ONE fetch + ONE wire message. Convergence must hold
    and the message economy must show (data messages << codec frames)."""
    monkeypatch.setenv("ST_HOST_CODEC", "xla")
    from tests._ports import free_port

    port = free_port()
    tmpl = {"w": np.zeros(2048, np.float32)}
    a = create_or_fetch("127.0.0.1", port, tmpl, timeout=30.0)
    b = create_or_fetch("127.0.0.1", port, tmpl, timeout=30.0)
    try:
        assert a._engine is None and b._engine is None
        assert a._burst_device > 1  # auto: min(16, wire cap)
        # linspace deltas need ~28 halvings to converge (BASELINE curve) —
        # a power-of-two uniform delta would finish in ONE frame and prove
        # nothing about bursting
        da = np.linspace(-1, 1, 2048, dtype=np.float32)
        db = np.linspace(0.5, -0.5, 2048, dtype=np.float32)
        a.add({"w": da})
        b.add({"w": db})
        want = da + db
        deadline = time.time() + 30
        while time.time() < deadline:
            if np.allclose(
                np.asarray(a.read()["w"]), want, atol=1e-6
            ) and np.allclose(np.asarray(b.read()["w"]), want, atol=1e-6):
                break
            time.sleep(0.1)
        np.testing.assert_allclose(np.asarray(a.read()["w"]), want, atol=1e-6)
        np.testing.assert_allclose(np.asarray(b.read()["w"]), want, atol=1e-6)
        m = a.metrics()
        assert m["st_frames_out_total"] > 0
        # burst economy: strictly fewer wire data messages than frames
        assert m["st_msgs_out_total"] < m["st_frames_out_total"], m
    finally:
        a.close()
        b.close()


def test_duplicate_link_up_is_logged_noop(caplog):
    """A replayed/duplicate LINK_UP must not kill the daemon recv thread
    (ADVICE r04 item 2 / r05 item 1): the attach entry points raise
    DuplicateLink on a duplicate link id, and _handle_events runs on the
    recv thread — the event is a logged no-op because the link being
    attached is already the state the event asks for."""
    import logging

    from shared_tensor_tpu.comm.transport import Event, EventKind
    from shared_tensor_tpu.core import DuplicateLink

    port = _free_port()
    seed = jnp.full((64,), 1.0, jnp.float32)
    m = create_or_fetch("127.0.0.1", port, seed)
    a = create_or_fetch("127.0.0.1", port, jnp.zeros_like(seed))
    try:
        _wait_converged([a], seed)
        up = a._uplink
        assert up is not None
        dup = Event(EventKind.LINK_UP, up, True)
        # the raw entry point does raise the dedicated type (a ValueError
        # subclass) on the duplicate id...
        with pytest.raises(DuplicateLink):
            if a._engine is not None:
                a._engine.new_link(up, seed=False)
            else:
                a.st.new_link(up, seed=False)
        # ...and the event path swallows exactly that type as a logged
        # warning. Note a duplicate *uplink* LINK_UP in native mode goes
        # through _start_join (handshake restart), so exercise the guard
        # with the raise itself: stub poll_events to replay the event and
        # the compat-style direct-attach body to hit the narrow catch.
        orig = a._on_link_up

        def raising(ev):
            raise DuplicateLink(f"link {ev.link_id} already exists")

        a._on_link_up = raising
        try:
            a.node.poll_events = lambda timeout=0.0: [dup]
            with caplog.at_level(
                logging.WARNING, logger="shared_tensor_tpu.peer"
            ):
                assert a._handle_events() is True  # no raise escapes
            assert any(
                "duplicate LINK_UP" in r.message for r in caplog.records
            )
        finally:
            a._on_link_up = orig
            a.node.poll_events = type(a.node).poll_events.__get__(a.node)
        # peer still functional after the duplicate event
        m.add(jnp.ones((64,), jnp.float32))
        _wait_converged([a], seed + 1.0)
    finally:
        a.close()
        m.close()


def test_non_duplicate_link_up_error_keeps_recv_thread_alive(caplog):
    """A NON-DuplicateLink error escaping _on_link_up must be logged loudly
    — it is a real attach failure, not a replay — but must NOT kill the
    daemon recv thread (ADVICE r05 item 1: at HEAD any attach-path error
    raised NameError on the recv thread and wedged the peer, the exact
    failure the duplicate guard was meant to prevent)."""
    import logging

    from shared_tensor_tpu.comm.transport import Event, EventKind

    port = _free_port()
    seed = jnp.full((64,), 1.0, jnp.float32)
    m = create_or_fetch("127.0.0.1", port, seed)
    a = create_or_fetch("127.0.0.1", port, jnp.zeros_like(seed))
    try:
        _wait_converged([a], seed)
        up = a._uplink
        assert up is not None
        ev = Event(EventKind.LINK_UP, up, True)
        orig = a._on_link_up

        def raising(_ev):
            raise RuntimeError("attach blew up for a non-duplicate reason")

        a._on_link_up = raising
        try:
            a.node.poll_events = lambda timeout=0.0: [ev]
            with caplog.at_level(
                logging.ERROR, logger="shared_tensor_tpu.peer"
            ):
                assert a._handle_events() is True  # no raise escapes
            assert any(
                "LINK_UP handling failed" in r.message
                for r in caplog.records
            )
        finally:
            a._on_link_up = orig
            a.node.poll_events = type(a.node).poll_events.__get__(a.node)
        # the recv thread survived: the peer still applies new frames
        assert a._recv_thread.is_alive()
        m.add(jnp.ones((64,), jnp.float32))
        _wait_converged([a], seed + 1.0)
    finally:
        a.close()
        m.close()


def test_engine_repr_after_destroy_is_string():
    """repr() of a destroyed EngineTensor must be a plain string, never a
    native call on a NULL handle: pytest's failure reporting (saferepr)
    walks whatever locals a failing test left behind, and an unguarded
    st_engine_counters(NULL) SIGSEGV'd the entire suite process at report
    time (VERDICT r05 Weak #2)."""
    port = _free_port()
    seed = jnp.full((64,), 1.0, jnp.float32)
    m = create_or_fetch("127.0.0.1", port, seed)
    try:
        if m._engine is None:
            pytest.skip("native engine unavailable on this tier")
        eng = m._engine
        assert "destroyed" not in repr(eng)
        m.close()  # destroys the engine
        r = repr(eng)
        assert isinstance(r, str) and "destroyed" in r
        # counters after destroy: zeros, not a crash (22-wide since the
        # r11 adaptive-precision widening)
        assert eng._counters().tolist() == [0] * 22
        assert eng.link_obs(1) is None
        assert eng.link_ids == ()
        assert eng.inflight_total() == 0
        # mutating calls raise a Python error instead of faulting
        with pytest.raises(RuntimeError):
            eng.add(jnp.zeros((64,), jnp.float32))
        with pytest.raises(RuntimeError):
            eng.snapshot_flat()
    finally:
        m.close()  # idempotent
