"""Golden tests for the pure-JAX codec against an independent numpy replica of
the reference arithmetic (reference src/sharedtensor.c:106-111, :145-177;
restated in SURVEY.md Appendix B), plus the measured convergence invariants
from BASELINE.md (residual RMS halves per frame on homogeneous data; exact
fp32 convergence in ~28 frames for U(-1,1))."""

import numpy as np
import pytest

import jax.numpy as jnp

from shared_tensor_tpu.config import ScalePolicy
from shared_tensor_tpu.ops import (
    Frame,
    apply_frame,
    apply_frame_many,
    pack_bits,
    pad_flat,
    padded_len,
    quantize,
    unpack_bits,
    wire_to_words,
    words_to_wire,
)


# --- numpy replica of the reference codec (independent golden) -------------


def ref_quantize(residual: np.ndarray, n: int):
    """Sender half, reference arithmetic: scale = 2^floor(log2(rms)), bit set
    (=> -scale) iff residual <= 0, error feedback into residual."""
    r = residual.astype(np.float32).copy()
    live = r[:n]
    rms = np.sqrt(np.float64(np.sum(live.astype(np.float64) ** 2)) / n)
    scale = np.float32(2.0 ** np.floor(np.log2(rms))) if rms > 0 else np.float32(0.0)
    bits = np.zeros(len(r), dtype=np.int32)
    if scale > 0:
        for i in range(n):
            if live[i] > 0:
                live[i] -= scale
            else:
                bits[i] = 1
                live[i] += scale
        r[:n] = live
    return scale, bits, r


def ref_apply(values: np.ndarray, scale, bits, n: int):
    out = values.astype(np.float32).copy()
    for i in range(n):
        out[i] += scale - bits[i] * 2 * scale
    return out


def ref_pack_bytes(bits: np.ndarray, n: int) -> bytes:
    """Reference wire bitmask: bit i at byte[i/8], position i%8, LSB-first
    (src/sharedtensor.c:171)."""
    buf = bytearray((n + 7) // 8)
    for i in range(n):
        if bits[i]:
            buf[i // 8] |= 1 << (i % 8)
    return bytes(buf)


# --- packing ----------------------------------------------------------------


def test_pack_unpack_roundtrip():
    rng = np.random.default_rng(0)
    bits = rng.integers(0, 2, size=2048).astype(np.int32)
    words = pack_bits(jnp.asarray(bits))
    assert words.dtype == jnp.uint32 and words.shape == (64,)
    out = unpack_bits(words)
    np.testing.assert_array_equal(np.asarray(out), bits)


def test_wire_layout_matches_reference():
    """Little-endian serialization of LSB-first uint32 words must be
    byte-identical to the reference's uint8 bitmask."""
    rng = np.random.default_rng(1)
    for n in [1, 7, 8, 33, 1000, 1024]:
        n_pad = padded_len(n)
        bits = np.zeros(n_pad, dtype=np.int32)
        bits[:n] = rng.integers(0, 2, size=n)
        words = np.asarray(pack_bits(jnp.asarray(bits)))
        assert words_to_wire(words, n) == ref_pack_bytes(bits, n)


def test_wire_roundtrip():
    rng = np.random.default_rng(2)
    n = 777
    n_pad = padded_len(n)
    bits = np.zeros(n_pad, dtype=np.int32)
    bits[:n] = rng.integers(0, 2, size=n)
    words = np.asarray(pack_bits(jnp.asarray(bits)))
    back = wire_to_words(words_to_wire(words, n), n_pad)
    # bits below n must survive; padding bits are zero-filled
    out = np.asarray(unpack_bits(jnp.asarray(back)))
    np.testing.assert_array_equal(out[:n], bits[:n])


# --- quantize golden --------------------------------------------------------


@pytest.mark.parametrize("n", [5, 240, 1024, 5000])
def test_quantize_matches_reference(n):
    rng = np.random.default_rng(n)
    n_pad = padded_len(n)
    r = np.zeros(n_pad, dtype=np.float32)
    r[:n] = rng.normal(size=n).astype(np.float32)

    g_scale, g_bits, g_resid = ref_quantize(r, n)
    frame, new_resid = quantize(jnp.asarray(r), n)

    assert float(frame.scale) == pytest.approx(float(g_scale), rel=0, abs=0)
    np.testing.assert_array_equal(
        np.asarray(unpack_bits(frame.words))[:n], g_bits[:n]
    )
    np.testing.assert_array_equal(np.asarray(new_resid), g_resid)
    # padding invariant
    assert not np.any(np.asarray(new_resid)[n:])


def test_apply_matches_reference():
    rng = np.random.default_rng(7)
    n = 500
    n_pad = padded_len(n)
    r = np.zeros(n_pad, dtype=np.float32)
    r[:n] = rng.normal(size=n).astype(np.float32)
    v = np.zeros(n_pad, dtype=np.float32)
    v[:n] = rng.normal(size=n).astype(np.float32)

    frame, _ = quantize(jnp.asarray(r), n)
    scale = float(frame.scale)
    bits = np.asarray(unpack_bits(frame.words))
    golden = ref_apply(v, scale, bits, n)
    out = apply_frame(jnp.asarray(v), frame, n)
    np.testing.assert_array_equal(np.asarray(out), golden)
    assert not np.any(np.asarray(out)[n:])


def test_zero_residual_is_idle():
    n = 1024
    r = jnp.zeros(n, dtype=jnp.float32)
    frame, new_r = quantize(r, n)
    assert float(frame.scale) == 0.0
    np.testing.assert_array_equal(np.asarray(new_r), np.zeros(n))


def test_zero_counts_as_negative():
    """Quirk Q3 (kept deliberately): an exactly-converged element still gets a
    sign bit (set => -scale) and oscillates within +/-scale."""
    n = 1024
    r = np.full(n, 1.0, dtype=np.float32)
    r[0] = 0.0
    frame, _ = quantize(jnp.asarray(r), n)
    bits = np.asarray(unpack_bits(frame.words))
    assert bits[0] == 1 and bits[1] == 0


def test_scale_is_power_of_two():
    rng = np.random.default_rng(3)
    for seed in range(5):
        r = rng.normal(size=1024).astype(np.float32) * 10.0**seed
        frame, _ = quantize(jnp.asarray(r), 1024)
        s = float(frame.scale)
        assert s > 0 and np.log2(s) == np.floor(np.log2(s))


# --- convergence invariants (BASELINE.md measured behavior) -----------------


def test_residual_rms_halves_per_frame():
    """Homogeneous U(-1,1): each frame carries ~1 bit/element; residual RMS
    must shrink by ~half per frame (BASELINE.md convergence table)."""
    rng = np.random.default_rng(11)
    n = 4096
    r = jnp.asarray(rng.uniform(-1, 1, size=n).astype(np.float32))
    prev_rms = float(jnp.sqrt(jnp.mean(r * r)))
    for _ in range(10):
        frame, r = quantize(r, n)
        rms = float(jnp.sqrt(jnp.mean(r * r)))
        assert rms <= prev_rms * 0.65  # ~0.5 expected, generous bound
        prev_rms = rms


def test_exact_convergence_through_link():
    """One-way link: receiver starts at 0, sender residual = target. After
    ~30 frames the sender residual is exactly zero (BASELINE: 'exact fp32 by
    frame ~28') and the receiver matches the target to within 1 ulp (receiver
    accumulation ``v += s`` rounds independently of the sender's ``r -= s``,
    so bit-exactness is only guaranteed for the residual)."""
    rng = np.random.default_rng(12)
    n = 2048
    target = rng.uniform(-1, 1, size=n).astype(np.float32)
    r = jnp.asarray(target)
    v = jnp.zeros(n, dtype=jnp.float32)
    for _ in range(40):
        frame, r = quantize(r, n)
        if float(frame.scale) == 0.0:
            break
        v = apply_frame(v, frame, n)
    assert float(jnp.max(jnp.abs(r))) == 0.0
    np.testing.assert_allclose(np.asarray(v), target, rtol=0, atol=1.5e-7)


def test_per_frame_movement_bounded_by_scale():
    """Every element moves by exactly +/-scale per frame — the documented
    overshoot bound (reference README.md:24)."""
    rng = np.random.default_rng(13)
    n = 1024
    v0 = jnp.zeros(n, dtype=jnp.float32)
    r = jnp.asarray(rng.normal(size=n).astype(np.float32))
    frame, _ = quantize(r, n)
    v1 = apply_frame(v0, frame, n)
    moves = np.abs(np.asarray(v1) - np.asarray(v0))
    np.testing.assert_allclose(moves, float(frame.scale))


def test_apply_frame_many_floods_all_arrays():
    rng = np.random.default_rng(14)
    n = 1024
    arrays = tuple(
        jnp.asarray(rng.normal(size=n).astype(np.float32)) for _ in range(3)
    )
    r = jnp.asarray(rng.normal(size=n).astype(np.float32))
    frame, _ = quantize(r, n)
    outs = apply_frame_many(arrays, frame, n)
    for a, o in zip(arrays, outs):
        expected = apply_frame(a, frame, n)
        np.testing.assert_array_equal(np.asarray(o), np.asarray(expected))


def test_pad_flat_roundtrip():
    x = jnp.arange(240, dtype=jnp.float32).reshape(4, 5, 6, 2)
    from shared_tensor_tpu.ops import unpad

    flat = pad_flat(x)
    assert flat.shape[0] == padded_len(240) and flat.shape[0] % 1024 == 0
    np.testing.assert_array_equal(np.asarray(unpad(flat, x.shape)), np.asarray(x))


def test_mixed_magnitude_degradation():
    """The failure mode that motivates table sync (README.md:41, BASELINE:
    1000:1 mix -> small half stuck at ~24% error): with ONE global scale the
    small-magnitude half must still be far from converged after 48 frames."""
    rng = np.random.default_rng(15)
    n = 2048
    target = np.concatenate(
        [
            rng.uniform(-1, 1, size=n // 2) * 1000.0,
            rng.uniform(-1, 1, size=n // 2),
        ]
    ).astype(np.float32)
    r = jnp.asarray(target)
    v = jnp.zeros(n, dtype=jnp.float32)
    for _ in range(48):
        frame, r = quantize(r, n)
        v = apply_frame(v, frame, n)
    small_err = np.abs(np.asarray(v)[n // 2 :] - target[n // 2 :])
    small_rel = np.mean(small_err / np.abs(target[n // 2 :]).clip(1e-6))
    large_err = np.abs(np.asarray(v)[: n // 2] - target[: n // 2])
    large_rel = np.mean(large_err / np.abs(target[: n // 2]).clip(1e-6))
    assert large_rel < 0.01
    assert small_rel > 0.05  # still poorly converged -> table sync needed
