"""Table sync (per-leaf scale) tests — the reference README.md:41 TODO turned
capability, exercised against the single-scale golden codec and the
mixed-magnitude failure mode it fixes (BASELINE.md)."""

import numpy as np

import jax.numpy as jnp

from shared_tensor_tpu.ops import codec
from shared_tensor_tpu.ops.packing import padded_len, unpack_bits
from shared_tensor_tpu.ops.table import (
    accumulate_table,
    apply_table,
    apply_table_many,
    flatten,
    make_spec,
    quantize_table,
    unflatten,
)


def _tree(seed=0, scales=(1.0, 1.0, 1.0)):
    # uniform data: converges to exact zero quickly (gaussian tails take
    # hundreds of frames, same as the C reference — see BASELINE.md)
    rng = np.random.default_rng(seed)
    return {
        "w": (rng.uniform(-1, 1, size=(40, 30)) * scales[0]).astype(np.float32),
        "b": (rng.uniform(-1, 1, size=(77,)) * scales[1]).astype(np.float32),
        "emb": (rng.uniform(-1, 1, size=(10, 11, 3)) * scales[2]).astype(np.float32),
    }


def test_flatten_roundtrip():
    t = _tree()
    spec = make_spec(t)
    flat = flatten(t, spec)
    assert flat.shape[0] == spec.total and spec.total % 1024 == 0
    back = unflatten(flat, spec)
    for k in t:
        np.testing.assert_array_equal(np.asarray(back[k]), t[k])
    # padding invariant
    live = flat.shape[0]
    assert spec.total_n == sum(v.size for v in t.values())


def test_single_leaf_matches_scalar_codec():
    """A one-leaf table must reproduce codec.quantize bit-for-bit."""
    rng = np.random.default_rng(3)
    n = 3000
    x = rng.normal(size=n).astype(np.float32)
    spec = make_spec(x)
    flat = flatten(x, spec)
    tframe, tresid = quantize_table(flat, spec)

    n_pad = padded_len(n)
    r = np.zeros(n_pad, np.float32)
    r[:n] = x
    gframe, gresid = codec.quantize(jnp.asarray(r), n)

    assert float(tframe.scales[0]) == float(gframe.scale)
    np.testing.assert_array_equal(np.asarray(tframe.words), np.asarray(gframe.words))
    np.testing.assert_array_equal(np.asarray(tresid), np.asarray(gresid))


def test_per_leaf_scales_differ():
    t = _tree(seed=1, scales=(1000.0, 1.0, 0.001))
    spec = make_spec(t)
    frame, _ = quantize_table(flatten(t, spec), spec)
    s = np.asarray(frame.scales)
    # dict leaves flatten in sorted key order: b (x1), emb (x0.001), w (x1000)
    assert s[2] > 100 * s[0] > 100 * s[1] > 0


def test_table_link_convergence():
    """One-way link over a mixed-magnitude table: with per-leaf scales, BOTH
    magnitude groups converge fast — the exact scenario that stalls the
    reference's single global scale (BASELINE.md: 24% error after 48 frames;
    here every leaf is exact after ~35)."""
    t = _tree(seed=2, scales=(1000.0, 1.0, 0.001))
    spec = make_spec(t)
    target = flatten(t, spec)
    resid = target
    values = jnp.zeros(spec.total, jnp.float32)
    for _ in range(64):
        frame, resid = quantize_table(resid, spec)
        if not bool(jnp.any(frame.scales > 0)):
            break
        values = apply_table(values, frame, spec)
    got = unflatten(values, spec)
    for k in t:
        tol = 1e-5 * max(1.0, float(np.abs(t[k]).max()))
        np.testing.assert_allclose(np.asarray(got[k]), t[k], rtol=0, atol=tol)


def test_idle_leaf_keeps_residual():
    """A leaf with zero residual idles (scale 0) while other leaves stream."""
    t = {"a": np.zeros(100, np.float32), "b": np.ones(100, np.float32)}
    spec = make_spec(t)
    frame, resid = quantize_table(flatten(t, spec), spec)
    s = np.asarray(frame.scales)
    assert s[0] == 0.0 and s[1] > 0
    back = unflatten(resid, spec)
    np.testing.assert_array_equal(np.asarray(back["a"]), 0.0)


def test_apply_many_and_accumulate():
    t = _tree(seed=4)
    spec = make_spec(t)
    flat = flatten(t, spec)
    frame, _ = quantize_table(flat, spec)
    a1 = jnp.zeros(spec.total, jnp.float32)
    a2 = flat
    o1, o2 = apply_table_many((a1, a2), frame, spec)
    e1 = apply_table(a1, frame, spec)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(e1))

    u1, u2 = accumulate_table((a1, a2), flat, spec)
    np.testing.assert_allclose(np.asarray(u1), np.asarray(flat))
    np.testing.assert_allclose(np.asarray(u2), np.asarray(flat) * 2)


def test_accumulate_sanitizes_table():
    t = {"a": np.ones(10, np.float32)}
    spec = make_spec(t)
    bad = np.full(10, np.nan, np.float32)
    flat = flatten({"a": np.ones(10, np.float32)}, spec)
    out, = accumulate_table((flat,), flatten({"a": bad}, spec), spec)
    np.testing.assert_array_equal(np.asarray(unflatten(out, spec)["a"]), 1.0)


def test_global_scale_mode():
    """per_leaf=False: one scale over the whole table (reference behavior),
    replicated across the frame's scales vector."""
    t = _tree(seed=7, scales=(1000.0, 1.0, 0.001))
    spec = make_spec(t)
    frame, _ = quantize_table(flatten(t, spec), spec, per_leaf=False)
    s = np.asarray(frame.scales)
    assert s[0] == s[1] == s[2] > 0


def test_flatten_rejects_wrong_sizes():
    t = _tree(seed=8)
    spec = make_spec(t)
    bad = dict(t)
    bad["b"] = np.zeros(12, np.float32)
    try:
        flatten(bad, spec)
        raise AssertionError("expected ValueError")
    except ValueError as e:
        assert "elements" in str(e)


def test_flatten_rejects_wrong_structure():
    t = _tree(seed=9)
    spec = make_spec(t)
    as_list = list(t.values())  # same leaf sizes, different structure
    try:
        flatten(as_list, spec)
        raise AssertionError("expected ValueError")
    except ValueError as e:
        assert "structure" in str(e)


def test_apply_table_batch_matches_sequential():
    """Batched K-frame apply (one dispatch) must equal K sequential applies
    — and zero-scale padding frames must be exact no-ops."""
    import jax

    from shared_tensor_tpu.config import ScalePolicy
    from shared_tensor_tpu.ops.table import TableFrame, apply_table_batch

    tpl = {
        "a": jax.random.normal(jax.random.key(0), (37,)),
        "b": jax.random.normal(jax.random.key(1), (5, 9)) * 100.0,
    }
    spec = make_spec(tpl)
    frames = []
    resid = flatten(tpl, spec)  # live-masked by construction
    for _ in range(5):
        f, resid = quantize_table(resid, spec, ScalePolicy.POW2_RMS, True)
        frames.append(f)

    values0 = flatten({"a": jnp.zeros((37,)), "b": jnp.zeros((5, 9))}, spec)
    seq = values0
    for f in frames:
        seq = apply_table(seq, f, spec)

    # pad with 3 zero-scale no-op frames to k=8
    k = 8
    scales = np.zeros((k, spec.num_leaves), np.float32)
    words = np.zeros((k, spec.total // 32), np.uint32)
    for i, f in enumerate(frames):
        scales[i] = np.asarray(f.scales)
        words[i] = np.asarray(f.words)
    words[6] = 0xFFFFFFFF  # garbage bits under zero scale must not matter
    stacked = TableFrame(jnp.asarray(scales), jnp.asarray(words))
    (batched,) = apply_table_batch((values0,), stacked, spec)
    np.testing.assert_allclose(np.asarray(batched), np.asarray(seq), rtol=1e-6, atol=1e-6)


def test_receive_frames_batch_floods_other_links():
    """core.receive_frames applies the summed delta to the replica AND other
    links' residuals (split horizon), identically to one-at-a-time."""
    import numpy as np

    from shared_tensor_tpu.config import ScalePolicy
    from shared_tensor_tpu.core import SharedTensor
    from shared_tensor_tpu.ops.table import quantize_table

    tpl = {"w": jnp.zeros((64,), jnp.float32)}
    sender = SharedTensor(tpl)
    sender.new_link(1, seed=False)
    sender.add({"w": jnp.linspace(-1, 1, 64)})

    frames = [sender.make_frame(1) for _ in range(4)]
    frames = [f for f in frames if f is not None]

    a = SharedTensor(tpl)
    a.new_link(1, seed=False)
    a.new_link(2, seed=False)
    b = SharedTensor(tpl)
    b.new_link(1, seed=False)
    b.new_link(2, seed=False)

    for f in frames:
        a.receive_frame(1, f)
    b.receive_frames(1, frames)

    np.testing.assert_allclose(
        np.asarray(a.snapshot_flat()), np.asarray(b.snapshot_flat()), atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(a._links[2]), np.asarray(b._links[2]), atol=1e-6
    )
    assert b.frames_in == len(frames)
