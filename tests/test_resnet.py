"""ResNet async-DP arm tests (BASELINE config 4 at test scale): the model
itself, plus compressed-delta vs exact-allreduce training through the pod
trainer on the virtual CPU mesh."""

import jax
import jax.numpy as jnp
import pytest

from shared_tensor_tpu.models import resnet as r
from tests._mesh import make_mesh
from shared_tensor_tpu.train import PodTrainer

TINY = r.ResNetConfig(stages=(1, 1), width=8, classes=4)


def _data(key, n=8, hw=8, classes=4, n_peer=None):
    k1, k2, k3 = jax.random.split(key, 3)
    count = (n_peer or 1) * n
    # Learnable synthetic task: class-dependent mean shift + noise.
    labels = jax.random.randint(k1, (count,), 0, classes)
    base = jax.random.normal(k2, (count, hw, hw, 3)) * 0.3
    shift = (labels[:, None, None, None] - (classes - 1) / 2) * 0.5
    x = base + shift
    if n_peer is not None:
        return x.reshape(n_peer, n, hw, hw, 3), labels.reshape(n_peer, n)
    return x, labels


def test_forward_shape_and_finite():
    params = r.init_params(jax.random.key(0), TINY)
    x, _ = _data(jax.random.key(1))
    logits = r.forward(params, x, TINY)
    assert logits.shape == (8, TINY.classes)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_blocks_start_as_identity():
    """Zero-init of scale2 means each residual branch contributes nothing at
    init — logits must be unchanged if block conv weights change."""
    params = r.init_params(jax.random.key(0), TINY)
    x, _ = _data(jax.random.key(1))
    before = r.forward(params, x, TINY)
    params["blocks"][0]["conv2"] = params["blocks"][0]["conv2"] + 1.0
    after = r.forward(params, x, TINY)
    assert jnp.allclose(before, after)


def test_imagenet_stem_downsamples():
    cfg = r.ResNetConfig(stages=(1,), width=8, classes=4, stem_kernel=7, stem_stride=2, stem_pool=True)
    params = r.init_params(jax.random.key(0), cfg)
    x = jnp.zeros((2, 32, 32, 3))
    logits = r.forward(params, x, cfg)
    assert logits.shape == (2, 4)


@pytest.mark.parametrize("compressed", [True, False])
def test_async_dp_trains(compressed):
    """8-peer async-DP SGD (the config-4 shape): loss decreases under both
    the compressed-delta and exact-allreduce arms."""
    mesh = make_mesh(8, 1)
    params = r.init_params(jax.random.key(0), TINY)
    tr = PodTrainer(
        mesh, params, lambda p, b: r.loss_fn(p, b, TINY), compressed=compressed
    )
    first = last = None
    for i in range(12):
        batch = tr.shard_batch(_data(jax.random.key(i), n=8, n_peer=8))
        losses, _ = tr.step(batch, lr=0.05)
        mean = float(jnp.mean(losses))
        first = mean if first is None else first
        last = mean
    assert last < first, (first, last)
