"""char-rnn model tests (SURVEY.md §4.2 tier 1 style: hermetic, CPU).

The model is the flagship workload (BASELINE config 2; reference
README.md:37's unrealized char-rnn TODO)."""

import jax
import jax.numpy as jnp
import pytest

from shared_tensor_tpu.models import char_rnn as m

TINY = m.CharRNNConfig(vocab=64, embed=16, hidden=32, layers=2)


def test_forward_shape_and_finite():
    params = m.init_params(jax.random.key(0), TINY)
    tokens = jax.random.randint(jax.random.key(1), (3, 7), 0, TINY.vocab)
    logits = m.forward(params, tokens, TINY)
    assert logits.shape == (3, 7, TINY.vocab)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_param_count_matches_pytree():
    params = m.init_params(jax.random.key(0), TINY)
    n = sum(x.size for x in jax.tree.leaves(params))
    assert n == TINY.param_count


def test_initial_loss_near_uniform():
    """Untrained model should be close to -log(1/vocab) on random data."""
    params = m.init_params(jax.random.key(0), TINY)
    x = jax.random.randint(jax.random.key(1), (4, 16), 0, TINY.vocab)
    y = jax.random.randint(jax.random.key(2), (4, 16), 0, TINY.vocab)
    loss = m.loss_fn(params, (x, y), TINY)
    assert abs(float(loss) - jnp.log(TINY.vocab)) < 0.5


def test_sgd_learns_repeating_pattern():
    """A few plain SGD steps must cut the loss on a trivially predictable
    stream — guards the whole backward path."""
    cfg = TINY
    params = m.init_params(jax.random.key(0), cfg)
    text = bytes(range(8)) * 200
    x, y = m.make_batches(text, batch=8, seq=16, key=jax.random.key(3))

    grad = jax.jit(jax.grad(lambda p: m.loss_fn(p, (x, y), cfg)))
    loss0 = float(m.loss_fn(params, (x, y), cfg))
    for _ in range(100):
        g = grad(params)
        params = jax.tree.map(lambda p, gg: p - 0.5 * gg, params, g)
    loss1 = float(m.loss_fn(params, (x, y), cfg))
    assert loss1 < loss0 * 0.5, (loss0, loss1)


def test_sample_shape_dtype_and_range():
    params = m.init_params(jax.random.key(0), TINY)
    prompt = jnp.asarray([1, 2, 3], jnp.int32)
    out = m.sample(params, jax.random.key(1), prompt, TINY, length=11)
    assert out.shape == (11,)
    assert out.dtype in (jnp.int32, jnp.int64)
    assert bool(jnp.all((out >= 0) & (out < TINY.vocab)))


def test_make_batches_targets_shifted():
    text = bytes(range(256)) * 4
    x, y = m.make_batches(text, batch=4, seq=8, key=jax.random.key(0))
    assert x.shape == (4, 8) and y.shape == (4, 8)
    # y is x shifted by one within the byte ramp (mod 256 at wrap)
    assert bool(jnp.all((y - x) % 256 == 1))


def test_make_batches_peer_axis():
    text = b"hello world " * 100
    x, y = m.make_batches(text, batch=2, seq=4, key=jax.random.key(0), n_peer=3)
    assert x.shape == (3, 2, 4) and y.shape == (3, 2, 4)
