"""ASan + UBSan + TSan over the native data plane.

The r07 zero-copy plane moved real lifetime management into C: tx slots
shared by codec threads, the go-back-N ledger, and the transport's
scatter-gather sender via refcounts and release callbacks (stengine.cpp
TxSlot, sttransport.cpp OutMsg). A use-after-free or misaligned access
there is silent on x86 until it corrupts a heap — exactly what the
sanitizers catch deterministically. This test builds the whole native trio
with -fsanitize=address,undefined (``make -C native sanitize``) and runs
one chaos_soak arm against it: injected drop/stall/sever chaos drives slot
refs through every path (send, retransmit, rollback, teardown) while ASan
watches every byte.

The TSan arms (r13 concurrency-correctness tentpole) build the trio with
``make -C native tsan`` and drive the engine, striping/sign2 and lifecycle
suites under ThreadSanitizer: ASan sees lifetime bugs, TSan sees ORDERING
bugs — the codec-pool seqlock, the obs SPSC rings, the tx-slot refcounts
and the stripe reassembly are exactly where a missing happens-before edge
is silent on x86. native/tsan.supp is the suppressions file; its target
state is EMPTY and every entry needs a written justification.

Two toolchain landmines this file works around, both reproduced in
isolation (gcc-10 libtsan):
  - steady-clock condvar waits go through pthread_cond_clockwait, which
    this libtsan does not intercept — the native tier pins its waits to
    the system clock instead (native/st_cv.h);
  - fork() while OpenBLAS's thread pool is live deadlocks inside TSan's
    fork handling, and ``import numpy.testing`` runs ``lscpu`` via
    subprocess at import time — the TSan arms export
    OPENBLAS_NUM_THREADS=1 so the pool never exists.

Slow-marked: tier-1 runs ``-m 'not slow'``; this is the nightly/CI arm
(ARTIFACTS.md). Run directly with
``pytest tests/test_sanitizers.py -m slow``.
"""

import json
import os
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
NATIVE = REPO / "native"


def _runtime(name: str):
    """Path to the compiler's sanitizer runtime, or None. The PRELOADed
    runtime must match the compiler that built the .so's, which is why
    this asks the same gcc the Makefile uses instead of globbing /usr."""
    try:
        out = subprocess.run(
            ["gcc", f"-print-file-name={name}"],
            capture_output=True, text=True, timeout=30,
        ).stdout.strip()
    except (OSError, subprocess.TimeoutExpired):
        return None
    p = pathlib.Path(out)
    return p if p.is_absolute() and p.exists() else None


def _san_env(asan, ubsan):
    env = dict(os.environ)
    env.update(
        {
            "LD_PRELOAD": f"{asan} {ubsan}",
            "ASAN_OPTIONS": "detect_leaks=0,abort_on_error=1",
            "UBSAN_OPTIONS": "print_stacktrace=1,halt_on_error=1",
            "ST_NATIVE_DIR": str(NATIVE / "san"),
            "JAX_PLATFORMS": "cpu",
        }
    )
    return env


# ---- TSan arms (r13) ------------------------------------------------------


def _tsan_env(tsan, log_path):
    env = dict(os.environ)
    env.update(
        {
            "LD_PRELOAD": str(tsan),
            # halt_on_error=0: collect every report in one run (the gate
            # asserts ZERO in our libs, so partial evidence beats
            # first-hit abort). exitcode=0: the pass/fail verdict comes
            # from _tsan_reports' scoped assertion — uninstrumented
            # third-party reports (XLA/absl, see _OURS) must not flip the
            # suite's own exit code. Reports go to log_path.<pid> — chaos
            # children inherit the env, so their reports land too.
            "TSAN_OPTIONS": (
                f"suppressions={NATIVE / 'tsan.supp'},halt_on_error=0,"
                f"exitcode=0,log_path={log_path}"
            ),
            "ST_NATIVE_DIR": str(NATIVE / "tsan"),
            "JAX_PLATFORMS": "cpu",
            # no OpenBLAS worker pool: fork (subprocess tests, and the
            # lscpu probe numpy.testing runs at import) deadlocks inside
            # gcc-10 libtsan when those threads exist (module docstring)
            "OPENBLAS_NUM_THREADS": "1",
        }
    )
    return env


#: a report block is OURS when any frame lands in the native tier; blocks
#: entirely inside third-party stacks (XLA/absl, CPython, libc) are
#: structural false positives — absl::Mutex and the ld.so/CPython internals
#: synchronize via raw futexes libtsan cannot see, while its GLOBAL
#: malloc/memcpy interceptors still record their accesses. The gate's
#: contract is the native tier; scoping the assertion (rather than
#: suppressing) keeps native/tsan.supp's target-state-empty policy honest.
_OURS = ("libstcodec", "libstengine", "libsttransport",
         "stcodec.c", "stengine.cpp", "sttransport.cpp")


def _tsan_reports(log_path) -> str:
    import glob

    out = []
    for p in sorted(glob.glob(str(log_path) + "*")):
        text = pathlib.Path(p).read_text(errors="replace")
        for block in text.split("==================")[1:]:
            if "WARNING: ThreadSanitizer" not in block:
                continue
            # judge a report by its ACCESS/lock stack frames only: the
            # "As if synchronized via sleep" footnote may cite a nanosleep
            # inside OUR libs while both racing accesses are third-party
            frames = []
            skipping = False
            for line in block.splitlines():
                if "As if synchronized via sleep" in line:
                    skipping = True
                elif skipping and not line.strip():
                    skipping = False
                elif not skipping and line.lstrip().startswith("#"):
                    frames.append(line)
            if any(lib in f for f in frames for lib in _OURS):
                out.append(f"==== {p}\n{block[:6000]}")
    return "\n".join(out)


def _run_tsan_arm(tmp_path, pytest_args, timeout=540):
    tsan = _runtime("libtsan.so")
    if tsan is None:
        pytest.skip("gcc TSan runtime unavailable")
    build = subprocess.run(
        ["make", "-C", str(NATIVE), "tsan"],
        capture_output=True, text=True, timeout=300,
    )
    if build.returncode != 0:
        pytest.skip(f"tsan build failed: {build.stderr[-500:]}")
    log_path = tmp_path / "tsan_report"
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", *pytest_args, "-q",
         "-p", "no:cacheprovider"],
        env=_tsan_env(tsan, log_path), capture_output=True, text=True,
        timeout=timeout, cwd=str(REPO),
    )
    reports = _tsan_reports(log_path)
    assert not reports, f"unsuppressed TSan report(s):\n{reports}"
    assert proc.returncode == 0, (
        proc.returncode, proc.stdout[-2000:], proc.stderr[-2000:],
    )


@pytest.mark.slow
def test_engine_suite_under_tsan(tmp_path):
    """r13 tentpole: the engine's whole lock graph — Engine::mu/add_mu/
    wmu/cmu, the tx-slot refcounts, the codec pool's seqlock — under
    ThreadSanitizer while the full engine suite (pair convergence, drain,
    churn, graceful leave, counter taxonomy) drives it."""
    _run_tsan_arm(tmp_path, ["tests/test_engine.py"])


@pytest.mark.slow
def test_striped_sign2_suite_under_tsan(tmp_path):
    """r13 tentpole: the r11 lock-free planes — per-stripe sender/receiver
    threads, the reassembly window, the sign2 cascade kernels, the
    precision governor — under TSan with the per-stripe chaos tests
    severing/stalling sockets beneath them."""
    # governor_stays_quiet is deselected HERE ONLY: its physical
    # precondition ("an uncapped loopback link is frame-bound — sends
    # never backpressure") is false under TSan's ~10x slowdown, where the
    # sendq genuinely backs up and an upshift becomes CORRECT behavior —
    # an environment-induced semantic change, not a race or a flake.
    _run_tsan_arm(
        tmp_path,
        [
            "tests/test_sign2.py", "tests/test_faults.py", "-k",
            "(sign2 or cascade or governor or stripe)"
            " and not governor_stays_quiet",
        ],
    )


@pytest.mark.slow
def test_lifecycle_suite_under_tsan(tmp_path):
    """r13 tentpole: the r12 lifecycle plane — the pause gate's pass-
    boundary handshake, snapshot_ex's one-mutex bulk captures racing the
    codec threads, restore under load — under TSan through the whole
    lifecycle suite (snapshot barrier, in-place restore, kill-restore,
    routed drain)."""
    _run_tsan_arm(
        tmp_path,
        [
            "tests/test_lifecycle.py",
            "tests/test_checkpoint.py::"
            "test_engine_snapshot_roundtrip_sign2_cascade_inflight",
        ],
    )


@pytest.mark.slow
def test_shm_suite_under_tsan(tmp_path):
    """r14 tentpole: the same-host shm lane's concurrency surface — the
    cross-process ring atomics and futex protocol, the lane-writer
    promotion window (Lane::tx_mu), the SWITCH-marker handoff between the
    socket receiver and the ring drain thread, the recv_zc loan registry —
    under TSan through the shm transport + peer-tier negotiation suites
    (fault teardown and SNAP/RESUME across live lanes included)."""
    _run_tsan_arm(
        tmp_path,
        [
            "tests/test_shm.py",
            "tests/test_transport.py", "-k",
            "shm or roundtrip or link_down",
        ],
    )


@pytest.mark.slow
def test_obs_suite_under_asan_ubsan():
    """r08 satellite: the obs event ring is lock-free SPSC code shared by
    every native thread — exactly where a memory-order bug is silent on
    x86 until it isn't. Run the whole obs test file (ring drain, chaos
    timelines, postmortems) against the sanitizer builds: ASan/UBSan watch
    every ring write/drain while the chaos tests hammer them from the
    transport + engine threads."""
    asan = _runtime("libasan.so")
    ubsan = _runtime("libubsan.so")
    if asan is None or ubsan is None:
        pytest.skip("gcc sanitizer runtimes unavailable")
    build = subprocess.run(
        ["make", "-C", str(NATIVE), "sanitize"],
        capture_output=True, text=True, timeout=300,
    )
    if build.returncode != 0:
        pytest.skip(f"sanitize build failed: {build.stderr[-500:]}")
    proc = subprocess.run(
        [
            sys.executable, "-m", "pytest", "tests/test_obs.py", "-q",
            "-p", "no:cacheprovider",
        ],
        env=_san_env(asan, ubsan), capture_output=True, text=True,
        timeout=540, cwd=str(REPO),
    )
    err_tail = proc.stderr[-4000:]
    assert "AddressSanitizer" not in proc.stderr, err_tail
    assert "runtime error:" not in proc.stderr, err_tail  # UBSan findings
    assert proc.returncode == 0, (proc.returncode, proc.stdout[-2000:], err_tail)


@pytest.mark.slow
def test_cluster_trace_suite_under_asan_ubsan():
    """r09 satellite: the cluster-trace paths are new native hot code —
    per-message trace parsing in the engine receiver, the widened counters
    ABI, st_engine_link_obs, st_obs_emit2's reserved word — plus the
    digest parse paths on the control plane. Run the whole cluster test
    file (7-node chaos tree included) against the sanitizer builds so
    ASan/UBSan watch every trace-header read and ring write while the
    chaos schedule drops frames under it."""
    asan = _runtime("libasan.so")
    ubsan = _runtime("libubsan.so")
    if asan is None or ubsan is None:
        pytest.skip("gcc sanitizer runtimes unavailable")
    build = subprocess.run(
        ["make", "-C", str(NATIVE), "sanitize"],
        capture_output=True, text=True, timeout=300,
    )
    if build.returncode != 0:
        pytest.skip(f"sanitize build failed: {build.stderr[-500:]}")
    proc = subprocess.run(
        [
            sys.executable, "-m", "pytest", "tests/test_obs_cluster.py",
            "-q", "-p", "no:cacheprovider",
        ],
        env=_san_env(asan, ubsan), capture_output=True, text=True,
        timeout=540, cwd=str(REPO),
    )
    err_tail = proc.stderr[-4000:]
    assert "AddressSanitizer" not in proc.stderr, err_tail
    assert "runtime error:" not in proc.stderr, err_tail  # UBSan findings
    assert proc.returncode == 0, (proc.returncode, proc.stdout[-2000:], err_tail)


@pytest.mark.slow
def test_serve_suite_under_asan_ubsan():
    """r10 satellite: the subscriber link mode is new native hot code —
    the unledgered sender branch (per-frame RDATA encoding off the
    msg.scales/words buffers, range slicing arithmetic, FRESH sends from
    under the engine mutex) and the widened counters ABI. Run the whole
    serve test file (resync-under-drop chaos included) against the
    sanitizer builds so ASan/UBSan watch every range offset and buffer
    copy while the chaos schedule drops frames under it."""
    asan = _runtime("libasan.so")
    ubsan = _runtime("libubsan.so")
    if asan is None or ubsan is None:
        pytest.skip("gcc sanitizer runtimes unavailable")
    build = subprocess.run(
        ["make", "-C", str(NATIVE), "sanitize"],
        capture_output=True, text=True, timeout=300,
    )
    if build.returncode != 0:
        pytest.skip(f"sanitize build failed: {build.stderr[-500:]}")
    proc = subprocess.run(
        [
            sys.executable, "-m", "pytest", "tests/test_serve.py", "-q",
            "-p", "no:cacheprovider",
        ],
        env=_san_env(asan, ubsan), capture_output=True, text=True,
        timeout=540, cwd=str(REPO),
    )
    err_tail = proc.stderr[-4000:]
    assert "AddressSanitizer" not in proc.stderr, err_tail
    assert "runtime error:" not in proc.stderr, err_tail  # UBSan findings
    assert proc.returncode == 0, (proc.returncode, proc.stdout[-2000:], err_tail)


@pytest.mark.slow
def test_striped_adaptive_suite_under_asan_ubsan():
    """r11 satellite: striping + adaptive precision are new native hot
    code on every plane — per-stripe sender/receiver threads and the
    reassembly window (sttransport.cpp), the sign2 pack/unpack kernels and
    the cascade quantizer (stcodec.c), the precision-bit frame format and
    the governor's beat (stengine.cpp). Run the sign2 suite (kernel
    parity, pinned/mixed pairs, the governor-upshift loop) AND the
    per-stripe chaos tests (sever -> degrade-to-survivors, stall ->
    go-back-N teardown) against the sanitizer builds so ASan/UBSan watch
    every stripe buffer handoff and 2-bit plane write while the faults
    fire under them."""
    asan = _runtime("libasan.so")
    ubsan = _runtime("libubsan.so")
    if asan is None or ubsan is None:
        pytest.skip("gcc sanitizer runtimes unavailable")
    build = subprocess.run(
        ["make", "-C", str(NATIVE), "sanitize"],
        capture_output=True, text=True, timeout=300,
    )
    if build.returncode != 0:
        pytest.skip(f"sanitize build failed: {build.stderr[-500:]}")
    proc = subprocess.run(
        [
            sys.executable, "-m", "pytest", "tests/test_sign2.py",
            "tests/test_faults.py", "-q", "-k",
            "sign2 or cascade or governor or stripe",
            "-p", "no:cacheprovider",
        ],
        env=_san_env(asan, ubsan), capture_output=True, text=True,
        timeout=540, cwd=str(REPO),
    )
    err_tail = proc.stderr[-4000:]
    assert "AddressSanitizer" not in proc.stderr, err_tail
    assert "runtime error:" not in proc.stderr, err_tail  # UBSan findings
    assert proc.returncode == 0, (proc.returncode, proc.stdout[-2000:], err_tail)


@pytest.mark.slow
def test_lifecycle_suite_under_asan_ubsan():
    """r12 satellite: the lifecycle plane adds native surface — the
    sender's pause gate, st_engine_snapshot_ex/restore_ex's one-mutex
    bulk copies (values + every residual + per-link aux) racing the codec
    threads, and the governor-state restore path. Run the lifecycle suite
    (snapshot barrier under load, in-place restore, kill-restore restart,
    routed drain, the subscriber arm) plus the engine checkpoint
    round-trip against the sanitizer builds so ASan/UBSan watch every
    capture while the data plane is live under it."""
    asan = _runtime("libasan.so")
    ubsan = _runtime("libubsan.so")
    if asan is None or ubsan is None:
        pytest.skip("gcc sanitizer runtimes unavailable")
    build = subprocess.run(
        ["make", "-C", str(NATIVE), "sanitize"],
        capture_output=True, text=True, timeout=300,
    )
    if build.returncode != 0:
        pytest.skip(f"sanitize build failed: {build.stderr[-500:]}")
    proc = subprocess.run(
        [
            sys.executable, "-m", "pytest", "tests/test_lifecycle.py",
            "tests/test_checkpoint.py::"
            "test_engine_snapshot_roundtrip_sign2_cascade_inflight",
            "-q", "-p", "no:cacheprovider",
        ],
        env=_san_env(asan, ubsan), capture_output=True, text=True,
        timeout=540, cwd=str(REPO),
    )
    err_tail = proc.stderr[-4000:]
    assert "AddressSanitizer" not in proc.stderr, err_tail
    assert "runtime error:" not in proc.stderr, err_tail  # UBSan findings
    assert proc.returncode == 0, (proc.returncode, proc.stdout[-2000:], err_tail)


@pytest.mark.slow
def test_chaos_soak_native_arm_under_asan_ubsan():
    asan = _runtime("libasan.so")
    ubsan = _runtime("libubsan.so")
    if asan is None or ubsan is None:
        pytest.skip("gcc sanitizer runtimes unavailable")
    build = subprocess.run(
        ["make", "-C", str(NATIVE), "sanitize"],
        capture_output=True, text=True, timeout=300,
    )
    if build.returncode != 0:
        pytest.skip(f"sanitize build failed: {build.stderr[-500:]}")

    env = _san_env(asan, ubsan)
    env.update(
        {
            # one native arm, short window: the chaos classes (drop, stall,
            # sever -> rollback -> carry -> re-graft) all fire within
            # seconds; ASan costs ~2-5x wall clock on top
            "ST_CHAOS_ARMS": "native",
            "ST_CHAOS_SECONDS": "6",
        }
    )
    proc = subprocess.run(
        [sys.executable, str(REPO / "benchmarks" / "chaos_soak.py")],
        env=env, capture_output=True, text=True, timeout=540, cwd=str(REPO),
    )
    err_tail = proc.stderr[-4000:]
    assert "AddressSanitizer" not in proc.stderr, err_tail
    assert "runtime error:" not in proc.stderr, err_tail  # UBSan findings
    assert proc.returncode == 0, (proc.returncode, err_tail)
    # the soak's own delivery-contract verdict must hold under sanitizers
    # (chaos_soak prints ONE indented JSON document)
    stdout = proc.stdout
    out = json.loads(stdout[stdout.index("{"):])
    assert out["arms"]["native"]["pass"], out


@pytest.mark.slow
def test_shard_engine_suite_under_asan_ubsan():
    """r17 satellite: the engine-tier shard plane is new concurrent
    native code on the hot path — two plane threads sharing a TxSlot
    ring with ownership-transferred rx buffers (st_node_recv_take),
    in-place seq re-stamps on relayed frames, and fused cascade/apply
    kernels over synthetic slice layouts. Run the engine-lane test file
    (kernel parity, dedup/relay crafted members, mixed-lane interop,
    admission control) under ASan+UBSan so a lifetime or aliasing bug in
    the zero-copy relay path turns the suite red, not production."""
    asan = _runtime("libasan.so")
    ubsan = _runtime("libubsan.so")
    if asan is None or ubsan is None:
        pytest.skip("gcc sanitizer runtimes unavailable")
    build = subprocess.run(
        ["make", "-C", str(NATIVE), "sanitize"],
        capture_output=True, text=True, timeout=300,
    )
    if build.returncode != 0:
        pytest.skip(f"sanitize build failed: {build.stderr[-500:]}")
    proc = subprocess.run(
        [
            sys.executable, "-m", "pytest", "tests/test_shard_engine.py",
            "-q", "-p", "no:cacheprovider",
        ],
        env=_san_env(asan, ubsan), capture_output=True, text=True,
        timeout=540, cwd=str(REPO),
    )
    err_tail = proc.stderr[-4000:]
    assert "AddressSanitizer" not in proc.stderr, err_tail
    assert "runtime error:" not in proc.stderr, err_tail  # UBSan findings
    assert proc.returncode == 0, (proc.returncode, proc.stdout[-2000:], err_tail)


@pytest.mark.slow
def test_shard_suite_under_asan_ubsan():
    """r16 satellite: the cluster-sharded tensor pushes a NEW data kind
    (wire.FWD, 21-byte header + k variable-size frames) through the
    native transport — recv-bound sizing, the fault injector's widened
    is_data set, and relay paths that re-stamp a buffer in place before
    re-sending it. Run the whole shard test file (map negotiation, mixed
    interop, drain-handoff, snapshot/restore) against the sanitizer
    builds so ASan/UBSan watch every FWD framing offset and relay copy."""
    asan = _runtime("libasan.so")
    ubsan = _runtime("libubsan.so")
    if asan is None or ubsan is None:
        pytest.skip("gcc sanitizer runtimes unavailable")
    build = subprocess.run(
        ["make", "-C", str(NATIVE), "sanitize"],
        capture_output=True, text=True, timeout=300,
    )
    if build.returncode != 0:
        pytest.skip(f"sanitize build failed: {build.stderr[-500:]}")
    proc = subprocess.run(
        [
            sys.executable, "-m", "pytest", "tests/test_shard.py", "-q",
            "-p", "no:cacheprovider",
        ],
        env=_san_env(asan, ubsan), capture_output=True, text=True,
        timeout=540, cwd=str(REPO),
    )
    err_tail = proc.stderr[-4000:]
    assert "AddressSanitizer" not in proc.stderr, err_tail
    assert "runtime error:" not in proc.stderr, err_tail  # UBSan findings
    assert proc.returncode == 0, (proc.returncode, proc.stdout[-2000:], err_tail)
