"""Read-path serving tier (r10): subscriber join, verified bounded-staleness
reads, range subscription, gap->resync repair, and the lock-free read
discipline (reads never touch the data plane).

Staleness semantics under test are the serving contract: every
``read(max_staleness=s)`` either returns state VERIFIED at most ``s``
seconds behind (r09 origin stamps / FRESH drain marks) or raises
StalenessError — never silent staleness.
"""

import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from shared_tensor_tpu import serve
from shared_tensor_tpu.comm.peer import create_or_fetch
from shared_tensor_tpu.config import (
    Config, FaultConfig, ServeConfig, TransportConfig,
)
from tests._ports import free_port


def _poll(fn, deadline=45.0, every=0.02):
    """Retry fn() (StalenessError tolerated — the subscriber may be mid
    seed/resync) until truthy or the deadline."""
    t0 = time.monotonic()
    last = None
    while time.monotonic() - t0 < deadline:
        try:
            last = fn()
            if last:
                return last
        except serve.StalenessError:
            pass
        time.sleep(every)
    return last


def test_subscriber_joins_reads_and_tracks_writes():
    """A read-only leaf joins a live tree, receives the seed through the
    normal codec stream, and tracks writes with verified freshness — while
    the writer keeps ZERO delivery ledger for it (the unledgered-link
    contract: read-only leaves cost writers no ACK state)."""
    port = free_port()
    n = 512
    with create_or_fetch(
        "127.0.0.1", port, jnp.arange(n, dtype=jnp.float32)
    ) as m:
        with serve.subscribe(
            "127.0.0.1", port, jnp.zeros(n, jnp.float32), timeout=30.0
        ) as sub:
            assert _poll(lambda: np.allclose(
                np.asarray(sub.read(max_staleness=10.0)), np.arange(n),
                atol=1e-4,
            ))
            m.add(jnp.ones(n, jnp.float32))
            ep = serve.epoch()
            sub.wait_fresh(ep, timeout=20.0)
            assert _poll(lambda: np.allclose(
                np.asarray(sub.read(max_staleness=10.0)), np.arange(n) + 1,
                atol=1e-4,
            ))
            # unledgered: no RETAINED in-flight state toward the subscriber
            # (a frame is transiently ledgered only within one send call)
            assert _poll(lambda: m.st.inflight_total() == 0, deadline=10.0)
            wm = m.metrics(canonical=True)
            assert wm["st_sub_links"] == 1
            assert wm["st_sub_msgs_out_total"] >= 1
            sm = sub.metrics()
            assert sm["st_read_total"] >= 2
            assert sm["st_read_stale_total"] == 0
            # freshness was VERIFIED (stamp or FRESH mark), not assumed
            assert 0 <= sm["st_sub_freshness_seconds"] < 30.0


def test_read_raises_not_stale_silently_when_writers_vanish():
    """Kill the only writer: within one staleness bound the subscriber's
    reads must START RAISING StalenessError — the reads-refuse-or-verify
    contract. (Idle-but-alive writers keep reads fresh via FRESH marks;
    a dead one cannot, and that difference must be loud.)"""
    port = free_port()
    n = 128
    m = create_or_fetch("127.0.0.1", port, jnp.arange(n, dtype=jnp.float32))
    sub = serve.subscribe(
        "127.0.0.1", port, jnp.zeros(n, jnp.float32), timeout=30.0
    )
    try:
        assert _poll(
            lambda: sub.read(max_staleness=10.0) is not None, deadline=20.0
        )
        # an IDLE writer keeps freshness verifiable (FRESH beats)
        time.sleep(0.8)
        assert sub.read(max_staleness=0.6) is not None
        m.close()
        time.sleep(1.0)
        with pytest.raises(serve.StalenessError):
            sub.read(max_staleness=0.5)
        assert sub.metrics()["st_read_stale_total"] >= 1
    finally:
        sub.close()
        m.close()


def test_range_subscription_buffers_only_its_pages():
    """Paged subscription: the subscriber buffers ONLY the subscribed
    word-aligned element range, converges on it, and the writer forwards
    range-filtered RDATA (satellite: the paged-HBM discipline)."""
    port = free_port()
    n = 4096
    lo, hi = 1024, 2048
    with create_or_fetch(
        "127.0.0.1", port, jnp.arange(n, dtype=jnp.float32)
    ) as m:
        cfg = Config(serve=ServeConfig(range=(lo, hi)))
        with serve.subscribe(
            "127.0.0.1", port, jnp.zeros(n, jnp.float32), cfg, timeout=30.0
        ) as sub:
            assert sub.range_elements == (lo, hi)
            assert sub._vals.size == hi - lo  # pages only, not the table
            assert _poll(lambda: np.allclose(
                sub.read(max_staleness=10.0), np.arange(lo, hi), atol=1e-4
            ))
            m.add(jnp.full((n,), 3.0, jnp.float32))
            assert _poll(lambda: np.allclose(
                sub.read(max_staleness=10.0), np.arange(lo, hi) + 3,
                atol=1e-4,
            ))
            assert sub.metrics()["st_sub_range_words"] == (hi - lo) // 32
            assert m.metrics(canonical=True)["st_sub_msgs_out_total"] >= 1


def test_gap_triggers_resync_and_reads_stay_honest_under_drop_chaos():
    """25%-drop chaos on an (unledgered) subscriber link: every swallowed
    message is a seq gap, the subscriber re-seeds via the resync
    handshake, reads either verify their bound or raise, and the value
    converges exactly once the chaos quiesces. Python-tier writer so the
    FaultConfig wire knobs inject directly."""
    port = free_port()
    n = 256
    cfg_w = Config(
        faults=FaultConfig(enabled=True, seed=7, drop_pct=0.25),
        native_engine=False,
    )
    m = create_or_fetch("127.0.0.1", port, jnp.zeros(n, jnp.float32), cfg_w)
    assert m._engine is None  # the python wire boundary is where drops land
    sub = serve.subscribe(
        "127.0.0.1", port, jnp.zeros(n, jnp.float32), timeout=30.0
    )
    try:
        total = np.zeros(n)
        rng = np.random.default_rng(1)
        for _ in range(25):
            d = rng.uniform(-1, 1, n).astype(np.float32)
            m.add(jnp.asarray(d))
            total += d
            time.sleep(0.02)
        assert _poll(lambda: np.allclose(
            np.asarray(sub.read(max_staleness=1.0)), total, atol=1e-3
        ), deadline=60.0)
        sm = sub.metrics()
        assert sm["st_sub_resyncs_total"] >= 1, "drops never forced a resync?"
        assert sm["st_sub_gap_discards_total"] >= 1
        # the writer was never wedged: its ledger RETAINS nothing for the
        # subscriber (transient within-send entries aside) and its add
        # path stayed live through every resync
        assert _poll(lambda: m.st.inflight_total() == 0, deadline=10.0)
    finally:
        sub.close()
        m.close()


def test_reads_outlive_the_data_plane():
    """The core.py satellite's structural proof: a read touches ONLY the
    published double buffer (core.SnapshotPublisher) — no transport, no
    engine mutex, no apply lock. Strongest demonstration: reads still
    serve (within their bound) after close() tore the whole data plane
    down."""
    port = free_port()
    n = 128
    with create_or_fetch(
        "127.0.0.1", port, jnp.arange(n, dtype=jnp.float32)
    ) as m:
        sub = serve.subscribe(
            "127.0.0.1", port, jnp.zeros(n, jnp.float32), timeout=30.0
        )
        assert _poll(lambda: sub.read(max_staleness=10.0) is not None)
        sub.close()  # recv thread joined, transport node closed
        v = sub.read(max_staleness=30.0)  # still serves: no data plane left
        assert np.allclose(np.asarray(v), np.arange(n), atol=1e-4)
        assert _poll(lambda: m.st.inflight_total() == 0, deadline=10.0)


def test_concurrent_reads_never_block_add():
    """Regression (core.py satellite): reader threads hammering the
    serving handle must not block a writer's add() — the old snapshot
    path copied under the data-plane lock; serve reads swap references.
    Bound is deliberately generous (box noise): an add is microseconds,
    a lock-coupled read storm would push it to the staleness bound."""
    port = free_port()
    n = 1024
    with create_or_fetch(
        "127.0.0.1", port, jnp.zeros(n, jnp.float32)
    ) as m:
        with serve.subscribe(
            "127.0.0.1", port, jnp.zeros(n, jnp.float32), timeout=30.0
        ) as sub:
            handle = sub.serving_handle(max_staleness=30.0)
            assert _poll(lambda: handle.refresh() or True)
            stop = threading.Event()
            reads = [0]

            def reader():
                while not stop.is_set():
                    handle.params()
                    try:
                        handle.refresh()
                    except serve.StalenessError:
                        pass
                    reads[0] += 1

            threads = [threading.Thread(target=reader) for _ in range(4)]
            for t in threads:
                t.start()
            worst = 0.0
            try:
                for i in range(20):
                    t0 = time.monotonic()
                    m.add(jnp.full((n,), 0.01, jnp.float32))
                    worst = max(worst, time.monotonic() - t0)
            finally:
                stop.set()
                for t in threads:
                    t.join()
            assert reads[0] > 0
            assert worst < 1.0, f"add() blocked {worst:.3f}s under read load"


def test_writer_join_under_subscriber_is_refused():
    """A subscriber is a LEAF: it seeds nobody. A writer pointed at the
    subscriber's own listen port must fail its join loudly instead of
    grafting under a read-only node and waiting forever for state."""
    port = free_port()
    n = 64
    with create_or_fetch(
        "127.0.0.1", port, jnp.zeros(n, jnp.float32)
    ) as _m:
        with serve.subscribe(
            "127.0.0.1", port, jnp.zeros(n, jnp.float32), timeout=30.0
        ) as sub:
            sub_port = sub.node.listen_port
            cfg = Config(
                transport=TransportConfig(join_timeout_sec=3.0),
            )
            with pytest.raises(ConnectionError):
                create_or_fetch(
                    "127.0.0.1", sub_port, jnp.zeros(n, jnp.float32), cfg,
                    timeout=8.0,
                )


def test_subscriber_cannot_become_master():
    """A read-only replica must not claim an empty rendezvous (it would
    serve zeros forever and orphan real writers behind it)."""
    port = free_port()
    with pytest.raises(ConnectionError):
        serve.Subscriber("127.0.0.1", port, jnp.zeros(64, jnp.float32))


def test_mixed_tree_v2_writers_legacy_peer_and_ranged_subscriber(monkeypatch):
    """Satellite: a v2 writer tree with one read-only subscriber AND one
    legacy peer interops — the legacy (pre-range, v1-pinned emission) peer
    still gets the full flood, the subscriber gets exactly its range."""
    port = free_port()
    n = 2048
    lo, hi = 512, 1024
    with create_or_fetch(
        "127.0.0.1", port, jnp.zeros(n, jnp.float32)
    ) as master:
        # legacy writer peer: pinned to v1 emission (the pre-r09 escape
        # hatch — no trace stamps, no flags beyond the version byte)
        monkeypatch.setenv("ST_WIRE_TRACE", "0")
        legacy = create_or_fetch(
            "127.0.0.1", port, jnp.zeros(n, jnp.float32)
        )
        monkeypatch.delenv("ST_WIRE_TRACE")
        cfg = Config(serve=ServeConfig(range=(lo, hi)))
        sub = serve.subscribe(
            "127.0.0.1", port, jnp.zeros(n, jnp.float32), cfg, timeout=30.0
        )
        try:
            master.add(jnp.arange(n, dtype=jnp.float32))
            # legacy peer converges on the FULL table
            deadline = time.monotonic() + 45.0
            while time.monotonic() < deadline:
                if np.allclose(
                    np.asarray(legacy.read()), np.arange(n), atol=1e-3
                ):
                    break
                time.sleep(0.05)
            np.testing.assert_allclose(
                np.asarray(legacy.read()), np.arange(n), atol=1e-3
            )
            # subscriber converges on exactly its pages
            assert _poll(lambda: np.allclose(
                sub.read(max_staleness=10.0), np.arange(lo, hi), atol=1e-3
            ))
            # and a legacy-originated write floods everywhere too
            legacy.add(jnp.ones(n, jnp.float32))
            assert _poll(lambda: np.allclose(
                sub.read(max_staleness=10.0), np.arange(lo, hi) + 1,
                atol=1e-3,
            ), deadline=60.0)
        finally:
            sub.close()
            legacy.close()


def test_serving_handle_hot_swap_identity():
    """The hot-swap contract: params() is reference-stable between
    refreshes (an in-flight forward pass can never see a half-swapped
    tree), and refresh() swaps in one reference assignment."""
    port = free_port()
    n = 256
    with create_or_fetch(
        "127.0.0.1", port, jnp.zeros(n, jnp.float32)
    ) as m:
        with serve.subscribe(
            "127.0.0.1", port, jnp.zeros(n, jnp.float32), timeout=30.0
        ) as sub:
            handle = sub.serving_handle(max_staleness=30.0)
            assert _poll(lambda: handle.refresh() or handle.params() is not None)
            p1 = handle.params()
            assert p1 is handle.params()  # no per-call copies
            m.add(jnp.ones(n, jnp.float32))
            sub.wait_fresh(serve.epoch(), timeout=20.0)
            assert _poll(lambda: handle.refresh(), deadline=20.0)
            p2 = handle.params()
            assert p2 is not p1
            assert np.allclose(np.asarray(p2), 1.0, atol=1e-4)
