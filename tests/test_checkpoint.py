"""Checkpoint/resume tests (SURVEY.md §5.4 — capability the reference
lacks entirely)."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shared_tensor_tpu.core import SharedTensor
from shared_tensor_tpu.models import char_rnn as m
from shared_tensor_tpu.parallel.ici import init_state
from tests._mesh import make_mesh
from shared_tensor_tpu.train import PodTrainer
from shared_tensor_tpu.utils import checkpoint as ckpt


def _template():
    return {
        "a": jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
        "b": jnp.ones((4,), jnp.float32),
    }


def test_shared_roundtrip(tmp_path):
    st = SharedTensor(_template(), seed_values=True)
    st.new_link(1)
    st.add({"a": jnp.full((2, 3), 0.5), "b": jnp.zeros((4,))})
    path = str(tmp_path / "st.npz")
    ckpt.save_shared(st, path)

    st2 = SharedTensor(_template())
    st2.new_link(1, seed=False)
    ckpt.load_shared(st2, path)
    np.testing.assert_array_equal(
        np.asarray(st2.snapshot_flat()), np.asarray(st.snapshot_flat())
    )
    np.testing.assert_array_equal(
        np.asarray(st2._links[1]), np.asarray(st._links[1])
    )
    # restored replica unflattens to the right pytree values
    got = st2.read()
    np.testing.assert_allclose(np.asarray(got["a"]), np.arange(6).reshape(2, 3) + 0.5)


def test_shared_layout_mismatch_rejected(tmp_path):
    st = SharedTensor(_template(), seed_values=True)
    path = str(tmp_path / "st.npz")
    ckpt.save_shared(st, path)
    other = SharedTensor({"x": jnp.zeros((5,))})
    with pytest.raises(ValueError, match="layout"):
        ckpt.load_shared(other, path)


def test_engine_snapshot_roundtrip_sign2_cascade_inflight(monkeypatch):
    """r12 satellite: the r04-era checkpoint path predates the r11 state —
    sign2 (2-bit) wire frames, cascade quantize, the per-link precision
    governor. Pin a pair with sign2 forced on (ST_SIGN2=2), stall the
    joiner's uplink so 32 cascade-quantized sign2 messages sit LEDGERED
    (in flight, error feedback already debited from the residual) and the
    send window closes, then require snapshot_ex → restore_ex →
    snapshot_ex to round-trip values, every residual, and the per-link
    aux (seqs, precision capability) BYTE-EXACT — the one-mutex capture
    must be atomic against all of it. Also pins restore_ex's governor
    restore: a crafted prec/gov_prev survives into the next snapshot."""
    from shared_tensor_tpu.comm import faults
    from shared_tensor_tpu.comm.peer import create_or_fetch
    from shared_tensor_tpu.config import Config, FaultConfig, TransportConfig
    from tests._ports import free_port

    monkeypatch.setenv("ST_SIGN2", "2")
    env = faults.to_env(
        FaultConfig(enabled=True, seed=3, stall_after_frames=0, only_link=1)
    )
    port = free_port()
    seed = jnp.zeros((2048,), jnp.float32)
    # ack_timeout 0: the stalled link must KEEP its ledger (a go-back-N
    # teardown would roll the in-flight state away mid-test)
    cfg = Config(transport=TransportConfig(ack_timeout_sec=0.0))
    master = create_or_fetch("127.0.0.1", port, seed, cfg, timeout=30.0)
    monkeypatch.setenv("ST_FAULT_PLAN", env["ST_FAULT_PLAN"])
    child = create_or_fetch("127.0.0.1", port, seed, cfg, timeout=30.0)
    monkeypatch.delenv("ST_FAULT_PLAN")
    try:
        if child._engine is None:
            pytest.skip("native engine unavailable")
        rng = np.random.default_rng(2)
        for _ in range(40):
            child.add(jnp.asarray(rng.uniform(-1, 1, 2048).astype(np.float32)))
        # the stalled uplink ledgers every message unacked; production
        # stops when either the 32-deep window closes or the cascade
        # drains the residual — both leave a deep in-flight ledger
        deadline = time.time() + 20.0
        while time.time() < deadline and child.st.inflight_total() < 8:
            time.sleep(0.05)
        # wait for production to STOP (window closed or residual drained):
        # the byte-exact round trip below needs the sender quiescent, or a
        # post-restore quantize would legitimately mutate the residual
        last = -1
        while time.time() < deadline:
            cur = child.st.frames_out
            if cur == last:
                break
            last = cur
            time.sleep(0.3)
        inflight = child.st.inflight_total()
        assert inflight >= 8, f"no in-flight ledger built up ({inflight})"
        eng = child._engine
        v1, l1, a1 = eng.snapshot_ex()
        assert a1[1]["sign2"], "peer sign2 capability missing from aux"
        assert a1[1]["tx_seq"] >= inflight and a1[1]["rx_count"] == 0
        eng.restore_ex(v1, l1, a1)
        v2, l2, a2 = eng.snapshot_ex()
        np.testing.assert_array_equal(v1, v2)
        assert set(l1) == set(l2)
        for lid in l1:
            np.testing.assert_array_equal(l1[lid], l2[lid])
        assert a1 == a2
        # governor restore: crafted precision + previous-RMS sample survive
        crafted = {1: dict(a1[1], prec=2, gov_prev=0.25)}
        eng.restore_ex(v1, l1, crafted)
        assert eng.link_precision(1) == 2
        _, _, a3 = eng.snapshot_ex()
        assert a3[1]["prec"] == 2
        assert a3[1]["gov_prev"] == pytest.approx(0.25)
    finally:
        child.close()
        master.close()


def test_pod_roundtrip_resumes_training(tmp_path):
    """Save mid-training, restore onto a fresh mesh state, continue — the
    loss trajectory must continue from the checkpoint, not restart."""
    cfg = m.CharRNNConfig(vocab=64, embed=16, hidden=32, layers=1)
    text = b"abcdefgh" * 200
    mesh = make_mesh(4, 1)
    params = m.init_params(jax.random.key(0), cfg)
    loss = lambda p, b: m.loss_fn(p, b, cfg)
    tr = PodTrainer(mesh, params, loss)
    for i in range(10):
        batch = tr.shard_batch(
            m.make_batches(text, 4, 16, jax.random.key(i), n_peer=4, vocab=64)
        )
        l1, _ = tr.step(batch, lr=0.3)
    path = str(tmp_path / "pod.npz")
    ckpt.save_pod(tr.state, tr.spec, path)

    tr2 = PodTrainer(mesh, params, loss)
    tr2.state = ckpt.load_pod(path, mesh, tr2.spec)
    np.testing.assert_array_equal(
        np.asarray(tr2.state.values), np.asarray(tr.state.values)
    )
    batch = tr2.shard_batch(
        m.make_batches(text, 4, 16, jax.random.key(99), n_peer=4, vocab=64)
    )
    l2, _ = tr2.step(batch, lr=0.3)
    # resumed loss is near the trained loss, far below a fresh model's
    fresh = PodTrainer(mesh, params, loss)
    l0, _ = fresh.step(batch, lr=0.0)
    assert float(jnp.mean(l2)) < float(jnp.mean(l0)) * 0.8


def test_pod_peer_count_mismatch_rejected(tmp_path):
    mesh = make_mesh(4, 1)
    st = init_state(mesh, PodTrainer(mesh, _template(), lambda p, b: 0.0).spec)
    spec = PodTrainer(mesh, _template(), lambda p, b: 0.0).spec
    path = str(tmp_path / "pod.npz")
    ckpt.save_pod(st, spec, path)
    mesh2 = make_mesh(2, 1)
    with pytest.raises(ValueError, match="peers"):
        ckpt.load_pod(path, mesh2, spec)


def test_trainer_adam_resume_bit_equal(tmp_path):
    """Train 2k steps straight vs train k, save_trainer, restore into a FRESH
    trainer, train k more: state, optimizer moments, and every subsequent
    step must be bit-equal (round-2 verdict Weak #5 — dropping opt_state made
    Adam resume with reset moments, silently changing training)."""
    import optax

    cfg = m.CharRNNConfig(vocab=64, embed=16, hidden=32, layers=1)
    text = b"abcdefgh" * 200
    mesh = make_mesh(4, 1)
    params = m.init_params(jax.random.key(0), cfg)
    loss = lambda p, b: m.loss_fn(p, b, cfg)
    opt = optax.adam(3e-3)

    def batches(i):
        return m.make_batches(text, 4, 16, jax.random.key(i), n_peer=4, vocab=64)

    k = 5
    ref = PodTrainer(mesh, params, loss, optimizer=opt)
    for i in range(2 * k):
        ref.step(ref.shard_batch(batches(i)))

    tr = PodTrainer(mesh, params, loss, optimizer=opt)
    for i in range(k):
        tr.step(tr.shard_batch(batches(i)))
    path = str(tmp_path / "trainer.npz")
    ckpt.save_trainer(tr, path)

    tr2 = PodTrainer(mesh, params, loss, optimizer=opt)
    ckpt.load_trainer(tr2, path)
    assert tr2.steps == k
    for i in range(k, 2 * k):
        tr2.step(tr2.shard_batch(batches(i)))

    np.testing.assert_array_equal(
        np.asarray(tr2.state.values), np.asarray(ref.state.values)
    )
    np.testing.assert_array_equal(
        np.asarray(tr2.state.residual), np.asarray(ref.state.residual)
    )
    for a, b in zip(jax.tree.leaves(tr2.opt_state), jax.tree.leaves(ref.opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_trainer_optimizer_mismatch_rejected(tmp_path):
    import optax

    mesh = make_mesh(2, 1)
    tr = PodTrainer(mesh, _template(), lambda p, b: 0.0, optimizer=optax.adam(1e-3))
    path = str(tmp_path / "trainer.npz")
    ckpt.save_trainer(tr, path)
    plain = PodTrainer(mesh, _template(), lambda p, b: 0.0)
    with pytest.raises(ValueError, match="optimizer"):
        ckpt.load_trainer(plain, path)


def test_pod_sharded_roundtrip_per_shard_io(tmp_path):
    """save_pod_sharded / load_pod_sharded (round-3 verdict item 6): the
    sharded pod state checkpoints WITHOUT materializing the full table on
    one host — each shard is its own file sized ~total/n_shards, and the
    restore callback reads one shard at a time. Table sized so the full
    buffer (4 MiB x 2 arrays x 4 peers) exceeds a deliberately tiny 'host
    budget' of one shard."""
    import os

    from shared_tensor_tpu.ops.table import make_spec
    from shared_tensor_tpu.parallel.ici import add_updates

    mesh = make_mesh(4, 2)
    template = {"w": jnp.zeros((1 << 20,), jnp.float32)}  # 4 MiB/peer
    spec = make_spec(template)
    state = init_state(mesh, spec, template)
    upd = (
        jax.random.normal(jax.random.key(0), state.values.shape)
        .astype(jnp.float32)
    )
    state = add_updates(state, upd)

    path = str(tmp_path / "pod_ckpt")
    ckpt.save_pod_sharded(state, spec, path)

    files = [f for f in os.listdir(path) if f.startswith("shard_")]
    assert len(files) == 8, files  # one per device of the 4x2 mesh
    full_bytes = state.values.size * 4
    for f in files:
        sz = os.path.getsize(os.path.join(path, f))
        # each file holds 2 arrays of total/8 f32s (plus npz framing): far
        # under the full table — the per-shard-I/O claim, falsifiable here
        assert sz < full_bytes // 2, (f, sz, full_bytes)
    restored = ckpt.load_pod_sharded(path, mesh, spec)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(restored.values)),
        np.asarray(jax.device_get(state.values)),
    )
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(restored.residual)),
        np.asarray(jax.device_get(state.residual)),
    )
    # restored arrays carry the mesh sharding (not single-device commits)
    assert restored.values.sharding == state.values.sharding

    # stale-shard immunity: re-save DIFFERENT state on a coarser mesh into
    # the same directory (the old 4x2 shard files linger — save never
    # deletes other layouts' files); load must serve only the manifested
    # files, never a stale one. Also covers the n_shard=1 filename case
    # (slice(None) bounds must normalize, not embed 'None').
    mesh2 = make_mesh(4, 1)
    state2 = init_state(mesh2, spec, template)
    state2 = add_updates(
        state2,
        jax.random.normal(jax.random.key(1), state2.values.shape).astype(
            jnp.float32
        ),
    )
    ckpt.save_pod_sharded(state2, spec, path)
    assert len([f for f in os.listdir(path) if f.startswith("shard_")]) > 4
    restored2 = ckpt.load_pod_sharded(path, mesh2, spec)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(restored2.values)),
        np.asarray(jax.device_get(state2.values)),
    )


def test_pod_sharded_rejects_wrong_layout(tmp_path):
    from shared_tensor_tpu.ops.table import make_spec

    mesh = make_mesh(4, 2)
    template = {"w": jnp.zeros((1 << 14,), jnp.float32)}
    spec = make_spec(template)
    state = init_state(mesh, spec, template)
    path = str(tmp_path / "pod_ckpt")
    ckpt.save_pod_sharded(state, spec, path)
    other = make_spec({"w": jnp.zeros((1 << 13,), jnp.float32)})
    with pytest.raises(ValueError):
        ckpt.load_pod_sharded(path, mesh, other)


def test_pod_sharded_training_resume_bit_equal(tmp_path):
    """Resume from a sharded checkpoint mid-training and continue: the
    continued run must match an uninterrupted one bit-for-bit (same data
    stream, deterministic step)."""
    cfg = m.CharRNNConfig(vocab=64, embed=16, hidden=32, layers=1)
    text = b"a quick brown fox jumps over the lazy dog. " * 40
    mesh = make_mesh(4, 2)
    params = m.init_params(jax.random.key(0), cfg)
    loss = lambda p, b: m.loss_fn(p, b, cfg)

    def batches(i):
        return m.make_batches(
            text, 2, 8, jax.random.key(i), n_peer=4, vocab=cfg.vocab,
        )

    tr = PodTrainer(mesh, params, loss)
    for i in range(3):
        tr.step(tr.shard_batch(batches(i)), lr=0.2)
    path = str(tmp_path / "mid")
    ckpt.save_pod_sharded(tr.state, tr.spec, path)
    # continue the original
    for i in range(3, 6):
        tr.step(tr.shard_batch(batches(i)), lr=0.2)
    # resume a fresh trainer from the sharded checkpoint
    tr2 = PodTrainer(mesh, params, loss)
    tr2.state = ckpt.load_pod_sharded(path, mesh, tr2.spec)
    for i in range(3, 6):
        tr2.step(tr2.shard_batch(batches(i)), lr=0.2)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(tr.state.values)),
        np.asarray(jax.device_get(tr2.state.values)),
    )
