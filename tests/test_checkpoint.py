"""Checkpoint/resume tests (SURVEY.md §5.4 — capability the reference
lacks entirely)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shared_tensor_tpu.core import SharedTensor
from shared_tensor_tpu.models import char_rnn as m
from shared_tensor_tpu.parallel.ici import init_state
from tests._mesh import make_mesh
from shared_tensor_tpu.train import PodTrainer
from shared_tensor_tpu.utils import checkpoint as ckpt


def _template():
    return {
        "a": jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
        "b": jnp.ones((4,), jnp.float32),
    }


def test_shared_roundtrip(tmp_path):
    st = SharedTensor(_template(), seed_values=True)
    st.new_link(1)
    st.add({"a": jnp.full((2, 3), 0.5), "b": jnp.zeros((4,))})
    path = str(tmp_path / "st.npz")
    ckpt.save_shared(st, path)

    st2 = SharedTensor(_template())
    st2.new_link(1, seed=False)
    ckpt.load_shared(st2, path)
    np.testing.assert_array_equal(
        np.asarray(st2.snapshot_flat()), np.asarray(st.snapshot_flat())
    )
    np.testing.assert_array_equal(
        np.asarray(st2._links[1]), np.asarray(st._links[1])
    )
    # restored replica unflattens to the right pytree values
    got = st2.read()
    np.testing.assert_allclose(np.asarray(got["a"]), np.arange(6).reshape(2, 3) + 0.5)


def test_shared_layout_mismatch_rejected(tmp_path):
    st = SharedTensor(_template(), seed_values=True)
    path = str(tmp_path / "st.npz")
    ckpt.save_shared(st, path)
    other = SharedTensor({"x": jnp.zeros((5,))})
    with pytest.raises(ValueError, match="layout"):
        ckpt.load_shared(other, path)


def test_pod_roundtrip_resumes_training(tmp_path):
    """Save mid-training, restore onto a fresh mesh state, continue — the
    loss trajectory must continue from the checkpoint, not restart."""
    cfg = m.CharRNNConfig(vocab=64, embed=16, hidden=32, layers=1)
    text = b"abcdefgh" * 200
    mesh = make_mesh(4, 1)
    params = m.init_params(jax.random.key(0), cfg)
    loss = lambda p, b: m.loss_fn(p, b, cfg)
    tr = PodTrainer(mesh, params, loss)
    for i in range(10):
        batch = tr.shard_batch(
            m.make_batches(text, 4, 16, jax.random.key(i), n_peer=4, vocab=64)
        )
        l1, _ = tr.step(batch, lr=0.3)
    path = str(tmp_path / "pod.npz")
    ckpt.save_pod(tr.state, tr.spec, path)

    tr2 = PodTrainer(mesh, params, loss)
    tr2.state = ckpt.load_pod(path, mesh, tr2.spec)
    np.testing.assert_array_equal(
        np.asarray(tr2.state.values), np.asarray(tr.state.values)
    )
    batch = tr2.shard_batch(
        m.make_batches(text, 4, 16, jax.random.key(99), n_peer=4, vocab=64)
    )
    l2, _ = tr2.step(batch, lr=0.3)
    # resumed loss is near the trained loss, far below a fresh model's
    fresh = PodTrainer(mesh, params, loss)
    l0, _ = fresh.step(batch, lr=0.0)
    assert float(jnp.mean(l2)) < float(jnp.mean(l0)) * 0.8


def test_pod_peer_count_mismatch_rejected(tmp_path):
    mesh = make_mesh(4, 1)
    st = init_state(mesh, PodTrainer(mesh, _template(), lambda p, b: 0.0).spec)
    spec = PodTrainer(mesh, _template(), lambda p, b: 0.0).spec
    path = str(tmp_path / "pod.npz")
    ckpt.save_pod(st, spec, path)
    mesh2 = make_mesh(2, 1)
    with pytest.raises(ValueError, match="peers"):
        ckpt.load_pod(path, mesh2, spec)


def test_trainer_adam_resume_bit_equal(tmp_path):
    """Train 2k steps straight vs train k, save_trainer, restore into a FRESH
    trainer, train k more: state, optimizer moments, and every subsequent
    step must be bit-equal (round-2 verdict Weak #5 — dropping opt_state made
    Adam resume with reset moments, silently changing training)."""
    import optax

    cfg = m.CharRNNConfig(vocab=64, embed=16, hidden=32, layers=1)
    text = b"abcdefgh" * 200
    mesh = make_mesh(4, 1)
    params = m.init_params(jax.random.key(0), cfg)
    loss = lambda p, b: m.loss_fn(p, b, cfg)
    opt = optax.adam(3e-3)

    def batches(i):
        return m.make_batches(text, 4, 16, jax.random.key(i), n_peer=4, vocab=64)

    k = 5
    ref = PodTrainer(mesh, params, loss, optimizer=opt)
    for i in range(2 * k):
        ref.step(ref.shard_batch(batches(i)))

    tr = PodTrainer(mesh, params, loss, optimizer=opt)
    for i in range(k):
        tr.step(tr.shard_batch(batches(i)))
    path = str(tmp_path / "trainer.npz")
    ckpt.save_trainer(tr, path)

    tr2 = PodTrainer(mesh, params, loss, optimizer=opt)
    ckpt.load_trainer(tr2, path)
    assert tr2.steps == k
    for i in range(k, 2 * k):
        tr2.step(tr2.shard_batch(batches(i)))

    np.testing.assert_array_equal(
        np.asarray(tr2.state.values), np.asarray(ref.state.values)
    )
    np.testing.assert_array_equal(
        np.asarray(tr2.state.residual), np.asarray(ref.state.residual)
    )
    for a, b in zip(jax.tree.leaves(tr2.opt_state), jax.tree.leaves(ref.opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_trainer_optimizer_mismatch_rejected(tmp_path):
    import optax

    mesh = make_mesh(2, 1)
    tr = PodTrainer(mesh, _template(), lambda p, b: 0.0, optimizer=optax.adam(1e-3))
    path = str(tmp_path / "trainer.npz")
    ckpt.save_trainer(tr, path)
    plain = PodTrainer(mesh, _template(), lambda p, b: 0.0)
    with pytest.raises(ValueError, match="optimizer"):
        ckpt.load_trainer(plain, path)
