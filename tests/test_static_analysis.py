"""Cross-tier lints + the clang analyze build (r13 tentpole).

Each lint gets the r09 schema-lint negative-test discipline: it must pass
on the real tree AND fail, by name, on a seeded violation written to a
temp copy — a lint that cannot go red is decoration, not a gate. The
seeded trees copy only the files each lint reads (tools/lint_*.py parse
fixed relative paths under --repo).

The analyze smoke compiles all three native files under clang's
-Wthread-safety -Werror (the st_annotations.h contract) and runs the
checked-in .clang-tidy; both skip when clang is absent (this image ships
gcc only — the TSan arm in test_sanitizers.py is the dynamic half that
always runs).
"""

import pathlib
import shutil
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
TOOLS = REPO / "tools"
sys.path.insert(0, str(TOOLS))

import analyze_clang  # noqa: E402
import lint_abi  # noqa: E402
import lint_events  # noqa: E402
import lint_locks  # noqa: E402
import lint_metrics  # noqa: E402
import lint_spec  # noqa: E402
import lint_wire  # noqa: E402

#: every file any lint reads, relative to the repo root
_LINT_INPUTS = [
    "native/stengine.cpp",
    "native/sttransport.cpp",
    "shared_tensor_tpu/comm/wire.py",
    "shared_tensor_tpu/comm/engine.py",
    "shared_tensor_tpu/comm/transport.py",
    "shared_tensor_tpu/compat.py",
    "shared_tensor_tpu/obs/events.py",
    "shared_tensor_tpu/obs/schema.py",
    "shared_tensor_tpu/shard/node.py",
    "shared_tensor_tpu/shard/engine_lane.py",
    "shared_tensor_tpu/obs/health.py",
]


def _seed_tree(tmp_path: pathlib.Path, full_package: bool = False):
    root = tmp_path / "repo"
    for rel in _LINT_INPUTS:
        dst = root / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(REPO / rel, dst)
    if full_package:  # lint_metrics rglobs the whole package + native/
        for src in (REPO / "shared_tensor_tpu").rglob("*.py"):
            rel = src.relative_to(REPO)
            dst = root / rel
            dst.parent.mkdir(parents=True, exist_ok=True)
            shutil.copy(src, dst)
        for ext in ("*.c", "*.cpp", "*.h"):
            for src in (REPO / "native").glob(ext):
                dst = root / "native" / src.name
                dst.parent.mkdir(parents=True, exist_ok=True)
                shutil.copy(src, dst)
    return root


def _edit(root: pathlib.Path, rel: str, old: str, new: str) -> None:
    p = root / rel
    text = p.read_text()
    assert old in text, f"seed-edit anchor missing from {rel}: {old!r}"
    p.write_text(text.replace(old, new))


def _cli(tool: str, repo: pathlib.Path) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, str(TOOLS / tool), "--repo", str(repo)],
        capture_output=True, text=True, timeout=120,
    )


# ---- green on the real tree (importable form + the CLI wiring) ------------


@pytest.mark.parametrize(
    "mod", [lint_abi, lint_wire, lint_events, lint_metrics, lint_locks]
)
def test_lint_passes_on_tree(mod):
    findings = mod.run(REPO)
    assert findings == [], findings


def test_lint_cli_green_exit_codes():
    for tool in ("lint_abi.py", "lint_wire.py", "lint_events.py",
                 "lint_metrics.py", "lint_locks.py"):
        r = _cli(tool, REPO)
        assert r.returncode == 0, (tool, r.stdout, r.stderr)
        assert "OK" in r.stdout


# ---- red on seeded violations ---------------------------------------------


def test_wire_lint_flags_renumbered_kind(tmp_path):
    root = _seed_tree(tmp_path)
    _edit(root, "native/stengine.cpp",
          "constexpr uint8_t kAck = 6;", "constexpr uint8_t kAck = 5;")
    findings = lint_wire.run(root)
    assert any("kAck" in f and "ACK" in f for f in findings), findings
    r = _cli("lint_wire.py", root)
    assert r.returncode == 1 and "kAck" in r.stdout


def test_wire_lint_flags_fault_injector_kind_set(tmp_path):
    # a data kind the fault injector no longer matches: chaos silently
    # stops covering it at the native wire boundary
    root = _seed_tree(tmp_path)
    _edit(root, "native/sttransport.cpp",
          "kind0 == 11", "kind0 == 7")
    findings = lint_wire.run(root)
    assert any("is_data" in f for f in findings), findings


def test_wire_lint_flags_fwd_missing_from_injector(tmp_path):
    # r16: the sharded tree's WHOLE data plane rides FWD frames — an
    # is_data set that loses kind 17 silently exempts every sharded
    # cluster from wire chaos
    root = _seed_tree(tmp_path)
    _edit(root, "native/sttransport.cpp",
          "kind0 == 17", "kind0 == 11")
    findings = lint_wire.run(root)
    assert any("is_data" in f for f in findings), findings


def test_wire_lint_flags_shard_hello_flag_drift(tmp_path):
    # r16: the shard capability bit's wire/compat twin declaration — a
    # drift silently degrades every sharded join to the full-replica
    # fallback (same class as the shm flag below)
    root = _seed_tree(tmp_path)
    _edit(root, "shared_tensor_tpu/compat.py",
          "SYNC_FLAG_SHARD = 0x10", "SYNC_FLAG_SHARD = 0x20")
    findings = lint_wire.run(root)
    assert any("SYNC_FLAG_SHARD" in f and "SHARD_FLAG" in f
               for f in findings), findings


def test_wire_lint_flags_fwd_header_drift(tmp_path):
    # r16: FWD's fixed header (kind + five u32) — a drifted constant
    # desyncs decode_fwd's length check and fwd_restamp's offset
    root = _seed_tree(tmp_path)
    _edit(root, "shared_tensor_tpu/comm/wire.py",
          "FWD_HDR = 21", "FWD_HDR = 25")
    findings = lint_wire.run(root)
    assert any("FWD_HDR" in f for f in findings), findings


def test_abi_lint_flags_shard_queue_depth_drift(tmp_path):
    # r16: the transport send-queue depth is declared three times (native
    # config default, TransportNode default, shard/node.py QUEUE_DEPTH);
    # the shard pump's control-traffic headroom math reads the last one,
    # and a drift re-opens the ACK-starvation wedge
    root = _seed_tree(tmp_path)
    _edit(root, "shared_tensor_tpu/shard/node.py",
          "QUEUE_DEPTH = 8", "QUEUE_DEPTH = 4")
    findings = lint_abi.run(root)
    assert any("queue-depth drift" in f for f in findings), findings


def test_wire_lint_flags_shard_fwd_kind_drift(tmp_path):
    # r17: the engine-tier shard plane re-declares wire.FWD as kFwd — a
    # renumbered kind makes the native receiver treat every FWD as an
    # unknown control message (whole data plane deferred to Python)
    root = _seed_tree(tmp_path)
    _edit(root, "native/stengine.cpp",
          "constexpr uint8_t kFwd = 17;", "constexpr uint8_t kFwd = 18;")
    findings = lint_wire.run(root)
    assert any("kFwd" in f and "FWD" in f for f in findings), findings


def test_wire_lint_flags_shard_fwd_header_drift(tmp_path):
    # r17: kFwdHdr is the verbatim relay's restamp geometry — a size
    # drift shifts the re-stamped seq into the word_lo field
    root = _seed_tree(tmp_path)
    _edit(root, "native/stengine.cpp",
          "constexpr size_t kFwdHdr = 21;", "constexpr size_t kFwdHdr = 25;")
    findings = lint_wire.run(root)
    assert any("kFwdHdr" in f for f in findings), findings


def test_abi_lint_flags_shard_counter_width_drift(tmp_path):
    # r17: the st_shard_counters out-array widening class (the exact
    # st_engine_counters 8->22 history, now on the shard plane's ABI):
    # a python buffer narrower than the native out14 promise reads
    # garbage past the allocation
    root = _seed_tree(tmp_path)
    _edit(root, "shared_tensor_tpu/shard/engine_lane.py",
          "out = np.zeros(14, np.uint64)", "out = np.zeros(12, np.uint64)")
    findings = lint_abi.run(root)
    assert any("st_shard_counters" in f and "14" in f
               for f in findings), findings


def test_abi_lint_flags_shard_abi_signature_drift(tmp_path):
    # r17: a dropped argtypes parameter on the shard ABI reads stack
    # garbage (the silent-mismatch class the lint exists for)
    root = _seed_tree(tmp_path)
    _edit(root, "shared_tensor_tpu/shard/engine_lane.py",
          "lib.st_shard_member_attach.argtypes = [\n"
          "        ctypes.c_void_p, ctypes.c_int32, ctypes.c_uint64, ctypes.c_uint64,\n"
          "    ]",
          "lib.st_shard_member_attach.argtypes = [\n"
          "        ctypes.c_void_p, ctypes.c_int32, ctypes.c_uint64,\n"
          "    ]")
    findings = lint_abi.run(root)
    assert any("st_shard_member_attach" in f and "count" in f
               for f in findings), findings


def test_wire_lint_flags_v3_header_drift(tmp_path):
    # r14: the aligned v3 header is ONE size on both tiers; a drifted
    # kHdrV3 makes every exact-length framing test reject v3 messages
    root = _seed_tree(tmp_path)
    _edit(root, "native/stengine.cpp",
          "constexpr size_t kHdrV3 = 24;", "constexpr size_t kHdrV3 = 32;")
    findings = lint_wire.run(root)
    assert any("kHdrV3" in f and "HDR_V3" in f for f in findings), findings


def test_wire_lint_flags_switch_marker_drift(tmp_path):
    # r14: the in-stream SWITCH marker length — a drift means an
    # upgraded receiver parses the marker as a (huge) frame length
    root = _seed_tree(tmp_path)
    _edit(root, "native/sttransport.cpp",
          "constexpr uint32_t kShmSwitchLen = 0xFFFFFFFDu;",
          "constexpr uint32_t kShmSwitchLen = 0xFFFFFFFEu;")
    findings = lint_wire.run(root)
    assert any("kShmSwitchLen" in f for f in findings), findings


def test_wire_lint_flags_sendmmsg_batch_drift(tmp_path):
    root = _seed_tree(tmp_path)
    _edit(root, "native/sttransport.cpp",
          "constexpr int kCoalesce = 16;", "constexpr int kCoalesce = 64;")
    findings = lint_wire.run(root)
    assert any("kCoalesce" in f and "SENDMMSG_BATCH" in f
               for f in findings), findings


def test_wire_lint_flags_shm_hello_flag_drift(tmp_path):
    # the wire/compat twin declaration: the runtime assert catches this
    # on import, but the lint must catch it statically (a seeded tree is
    # never imported — and neither is a broken branch in CI until the
    # suite runs)
    root = _seed_tree(tmp_path)
    _edit(root, "shared_tensor_tpu/compat.py",
          "SYNC_FLAG_SHM = 0x08", "SYNC_FLAG_SHM = 0x10")
    findings = lint_wire.run(root)
    assert any("SYNC_FLAG_SHM" in f and "SHM_FLAG" in f
               for f in findings), findings


def test_event_lint_flags_unknown_and_drifted_code(tmp_path):
    root = _seed_tree(tmp_path)
    # stengine re-declares kEvQuarantine; renumbering it yields BOTH an
    # unknown code and a cross-file drift — the lint must name both
    _edit(root, "native/stengine.cpp",
          "constexpr uint32_t kEvQuarantine = 12;",
          "constexpr uint32_t kEvQuarantine = 55;")
    findings = lint_events.run(root)
    assert any("55" in f and "CODE_NAMES" in f for f in findings), findings
    assert any("drifted" in f for f in findings), findings


def test_event_lint_flags_renamed_shm_event(tmp_path):
    # r14: the shm chaos tallies key on the EXACT names shm_lane_up /
    # shm_fallback — a rename keeps the numeric code valid (no unknown-
    # code finding) yet silently zeroes every tally; the lint must red
    root = _seed_tree(tmp_path)
    _edit(root, "shared_tensor_tpu/obs/events.py",
          '34: "shm_lane_up"', '34: "shm_lane_went_up"')
    findings = lint_events.run(root)
    assert any("shm_lane_up" in f for f in findings), findings


def test_event_lint_flags_renamed_health_event(tmp_path):
    # r18: the fleet_health bench tallies key on the EXACT names in
    # HEALTH_EVENT_NAMES — a rename on the declaring side must red
    root = _seed_tree(tmp_path)
    _edit(root, "shared_tensor_tpu/obs/events.py",
          '"slo_alert_fire"', '"slo_alert_fired"')
    findings = lint_events.run(root)
    assert any("slo_alert_fire" in f for f in findings), findings


def test_event_lint_flags_health_emit_outside_set(tmp_path):
    # r18, the other direction: the analyzer emitting an event name the
    # pinned set does not know means nothing downstream can tally it
    root = _seed_tree(tmp_path)
    _edit(root, "shared_tensor_tpu/obs/health.py",
          'self._event(\n                "hot_shard"',
          'self._event(\n                "hot_shard_named"')
    findings = lint_events.run(root)
    assert any("hot_shard_named" in f and "HEALTH_EVENT_NAMES" in f
               for f in findings), findings


def test_abi_lint_flags_dropped_shm_declaration(tmp_path):
    # r14 bidirectional-family rule: a native st_node_shm_* entry point
    # with no ctypes declaration = the lane silently never negotiates
    root = _seed_tree(tmp_path)
    _edit(root, "shared_tensor_tpu/comm/transport.py",
          "lib.st_node_shm_join.restype", "lib.st_node_shm_join_x.restype")
    _edit(root, "shared_tensor_tpu/comm/transport.py",
          "lib.st_node_shm_join.argtypes",
          "lib.st_node_shm_join_x.argtypes")
    findings = lint_abi.run(root)
    assert any(
        "st_node_shm_join" in f and "bidirectional" in f for f in findings
    ), findings
    # ...and the renamed python-side declaration is itself flagged as
    # having no native definition (the pre-existing direction)
    assert any("st_node_shm_join_x" in f for f in findings), findings


def test_abi_lint_flags_shm_stats_width_drift(tmp_path):
    # the out-array discipline covers the new shm stats: native writing
    # past the promised out8 width must red exactly like st_engine_counters
    root = _seed_tree(tmp_path)
    _edit(root, "native/sttransport.cpp",
          "out8[7] = sl->rx_waits.load();",
          "out8[7] = sl->rx_waits.load();\n  out8[8] = 0;")
    findings = lint_abi.run(root)
    assert any(
        "st_node_shm_stats" in f and "out8" in f for f in findings
    ), findings


def test_abi_lint_flags_narrowed_counter_buffer(tmp_path):
    # the recurring widening class: native writes out22[21], python
    # allocates fewer slots -> garbage reads beyond the buffer
    root = _seed_tree(tmp_path)
    _edit(root, "shared_tensor_tpu/comm/engine.py",
          "out = np.zeros(22, np.uint64)", "out = np.zeros(18, np.uint64)")
    findings = lint_abi.run(root)
    assert any("st_engine_counters" in f and "18" in f for f in findings), (
        findings
    )


def test_abi_lint_flags_dropped_argtype(tmp_path):
    root = _seed_tree(tmp_path)
    _edit(root, "shared_tensor_tpu/comm/engine.py",
          "ctypes.c_void_p, ctypes.c_int32, ctypes.c_void_p,\n"
          "            ctypes.c_int32, ctypes.c_uint64,",
          "ctypes.c_void_p, ctypes.c_int32, ctypes.c_void_p,\n"
          "            ctypes.c_int32,")
    findings = lint_abi.run(root)
    assert any(
        "st_engine_attach" in f and "count" in f for f in findings
    ), findings


def test_abi_lint_flags_retyped_struct_field(tmp_path):
    root = _seed_tree(tmp_path)
    _edit(root, "shared_tensor_tpu/comm/transport.py",
          '("bandwidth_cap_bps", ctypes.c_int64)',
          '("bandwidth_cap_bps", ctypes.c_int32)')
    findings = lint_abi.run(root)
    assert any("StConfigC" in f for f in findings), findings


def test_metrics_lint_flags_undocumented_name(tmp_path):
    root = _seed_tree(tmp_path, full_package=True)
    _edit(root, "shared_tensor_tpu/comm/peer.py",
          "def metrics(",
          'UNDOC = "st_totally_undocumented_metric"\n    def metrics(')
    findings = lint_metrics.run(root)
    assert any("st_totally_undocumented_metric" in f for f in findings), (
        findings
    )


def test_metrics_lint_flags_legacy_alias_reintroduction(tmp_path):
    root = _seed_tree(tmp_path, full_package=True)
    _edit(root, "shared_tensor_tpu/comm/peer.py",
          "def metrics(",
          'LEGACY = {"frames_out": 0}\n    def metrics(')
    findings = lint_metrics.run(root)
    assert any("frames_out" in f and "legacy" in f for f in findings), (
        findings
    )


def test_metrics_lint_flags_dynamic_fstring_name(tmp_path):
    # r15: a dynamically-built st_* name never appears verbatim in any
    # source line, so the literal grep is blind to it — the emitted
    # metric ships undocumented. The f-string form is the one the
    # labeled-gauge code would most naturally grow into.
    root = _seed_tree(tmp_path, full_package=True)
    _edit(root, "shared_tensor_tpu/comm/peer.py",
          "def metrics(",
          'DYN = f"st_dyn_gauge_{0}"\n    def metrics(')
    findings = lint_metrics.run(root)
    assert any(
        "st_dyn_gauge_" in f and "dynamically-built" in f for f in findings
    ), findings


def test_metrics_lint_flags_dynamic_concat_name(tmp_path):
    root = _seed_tree(tmp_path, full_package=True)
    _edit(root, "shared_tensor_tpu/comm/peer.py",
          "def metrics(",
          'DYN = "st_dyn_" + "suffix"\n    def metrics(')
    findings = lint_metrics.run(root)
    assert any(
        "st_dyn_" in f and "dynamically-built" in f for f in findings
    ), findings


def test_locks_lint_flags_blocking_send_under_ledger_lock(tmp_path):
    # the deadlock shape r13's native annotations forbid, at the python
    # tier: a blocking wire send under _ack_mu — the recv thread pops
    # ACKs under the same lock, so a full send buffer can never drain
    root = _seed_tree(tmp_path, full_package=True)
    _edit(root, "shared_tensor_tpu/comm/peer.py",
          "with self._ack_mu:\n            mo = sum(self._acked.values())",
          "with self._ack_mu:\n"
          "            self._send_blocking(1, b'x')\n"
          "            mo = sum(self._acked.values())")
    findings = lint_locks.run(root)
    assert any(
        "_send_blocking" in f and "_ack_mu" in f for f in findings
    ), findings


def test_locks_lint_flags_engine_abi_call_under_lock(tmp_path):
    root = _seed_tree(tmp_path, full_package=True)
    _edit(root, "shared_tensor_tpu/comm/peer.py",
          "with self._ack_mu:\n            mo = sum(self._acked.values())",
          "with self._ack_mu:\n"
          "            self._engine.pause(True)\n"
          "            mo = sum(self._acked.values())")
    findings = lint_locks.run(root)
    assert any(
        "engine-ABI" in f and "_ack_mu" in f for f in findings
    ), findings


def test_locks_lint_skips_closures_under_lock(tmp_path):
    # a closure DEFINED under a lock runs later — flagging it would
    # make the lint unadoptable (callbacks are registered under locks
    # all over the obs tier)
    root = _seed_tree(tmp_path, full_package=True)
    _edit(root, "shared_tensor_tpu/comm/peer.py",
          "with self._ack_mu:\n            mo = sum(self._acked.values())",
          "with self._ack_mu:\n"
          "            cb = lambda: self._send_blocking(1, b'x')\n"
          "            mo = sum(self._acked.values())")
    findings = lint_locks.run(root)
    assert findings == [], findings


# ---- spec/mutation registry drift lint (r19) ------------------------------


def _seed_spec_tree(tmp_path: pathlib.Path) -> pathlib.Path:
    """Everything lint_spec reads: the spec modules, the committed MODEL
    artifacts, and README's mutation table."""
    root = tmp_path / "repo"
    (root / "tools" / "protospec").mkdir(parents=True)
    for src in (REPO / "tools" / "protospec").glob("spec_*.py"):
        shutil.copy(src, root / "tools" / "protospec" / src.name)
    for src in REPO.glob("MODEL_r*.json"):
        shutil.copy(src, root / src.name)
    shutil.copy(REPO / "README.md", root / "README.md")
    return root


def test_spec_lint_green_on_tree():
    assert lint_spec.run(REPO) == []
    r = _cli("lint_spec.py", REPO)
    assert r.returncode == 0 and "OK" in r.stdout, (r.stdout, r.stderr)


def test_spec_lint_flags_phantom_mutation(tmp_path):
    # a MODEL artifact citing a mutation the spec no longer codes: the
    # committed red-team coverage claim would be a lie
    import json
    root = _seed_spec_tree(tmp_path)
    p = root / "MODEL_r19.json"
    doc = json.loads(p.read_text())
    doc["mutations"]["reshard_split.ghost_never_coded"] = (
        doc["mutations"]["reshard_split.split_during_fwd"]
    )
    p.write_text(json.dumps(doc))
    findings = lint_spec.run(root)
    assert any(
        "phantom mutation" in f and "ghost_never_coded" in f
        for f in findings
    ), findings
    r = _cli("lint_spec.py", root)
    assert r.returncode == 1 and "ghost_never_coded" in r.stdout


def test_spec_lint_flags_phantom_spec(tmp_path):
    import json
    root = _seed_spec_tree(tmp_path)
    p = root / "MODEL_r19.json"
    doc = json.loads(p.read_text())
    doc["mutations"]["reshard_teleport.any_mutation"] = (
        doc["mutations"]["reshard_split.split_during_fwd"]
    )
    p.write_text(json.dumps(doc))
    findings = lint_spec.run(root)
    assert any(
        "phantom spec" in f and "reshard_teleport" in f for f in findings
    ), findings


def test_spec_lint_flags_undocumented_mutation(tmp_path):
    # a coded mutation README never cites: invisible red-team coverage —
    # seeded as a new Spec subclass so the dict-literal arm is exercised
    root = _seed_spec_tree(tmp_path)
    p = root / "tools" / "protospec" / "spec_reshard.py"
    p.write_text(
        p.read_text()
        + "\n\nclass _SeededSpec(Spec):\n"
        + '    name = "reshard_seeded"\n'
        + '    mutations = {"sneaky_uncited_mutation": None}\n'
    )
    findings = lint_spec.run(root)
    assert any(
        "undocumented mutation" in f
        and "reshard_seeded.sneaky_uncited_mutation" in f
        for f in findings
    ), findings


def test_spec_lint_resolves_dict_extension_idiom():
    # shard_engine extends shard's mutations via dict(Base.mutations,
    # extra=...) — the static resolution must see through it (the tree
    # being green already proves the base keys; pin the extension key)
    registry, findings = lint_spec._coded_registry(REPO)
    assert findings == []
    assert "relay_restamp_identity" in registry["shard_engine"]
    assert "no_dedup_transfer" in registry["shard_engine"]
    assert "split_during_fwd" in registry["reshard_split"]


# ---- libclang thread-safety gate (r19, probe-gated) -----------------------

_LIBCLANG_REASON = analyze_clang.probe()


@pytest.mark.skipif(
    _LIBCLANG_REASON is not None, reason=str(_LIBCLANG_REASON)
)
def test_analyze_clang_green_on_tree():
    """The r13 -Wthread-safety contract, actually executed: all three
    native TUs parse clean under the libclang front-end."""
    assert analyze_clang.run(REPO) == []


@pytest.mark.skipif(
    _LIBCLANG_REASON is not None, reason=str(_LIBCLANG_REASON)
)
def test_analyze_clang_flags_unguarded_access(tmp_path):
    # drop the lock guard around a ST_GUARDED_BY(mu) field init — the
    # gate must red on the exact class it exists for
    root = _seed_tree(tmp_path, full_package=True)
    _edit(root, "native/stengine.cpp",
          "    StLockGuard lk(e->mu);\n    e->values.assign",
          "    e->values.assign")
    findings = analyze_clang.run(root)
    assert any(
        "values" in f and ("warning" in f or "error" in f)
        for f in findings
    ), findings


def test_analyze_clang_probe_cli_is_honest():
    r = subprocess.run(
        [sys.executable, str(TOOLS / "analyze_clang.py"), "--probe"],
        capture_output=True, text=True, timeout=60,
    )
    if _LIBCLANG_REASON is None:
        assert r.returncode == 0 and "usable" in r.stdout
    else:
        # the SKIPPED path must print the provisioning command, not
        # silently pass
        assert r.returncode == 1 and "pip install libclang" in r.stdout


# ---- clang analyze / clang-tidy smoke (skipped without clang) -------------


def _have(tool: str) -> bool:
    return shutil.which(tool) is not None


@pytest.mark.skipif(not _have("clang"), reason="clang not installed")
def test_native_analyze_build_is_clean():
    """All three native files must compile clean under
    -Wthread-safety -Werror — the st_annotations.h lock contract is a
    build gate wherever clang exists, not documentation."""
    r = subprocess.run(
        ["make", "-C", str(REPO / "native"), "analyze"],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr


@pytest.mark.skipif(not _have("clang-tidy"), reason="clang-tidy not installed")
def test_native_clang_tidy_is_clean():
    r = subprocess.run(
        ["make", "-C", str(REPO / "native"), "tidy"],
        capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stdout + r.stderr


def test_tsan_supp_entries_are_justified():
    """The suppressions file's target state is empty; any entry must carry
    the written (a)/(b)/(c) justification block the header demands."""
    text = (REPO / "native" / "tsan.supp").read_text()
    entries = [
        l for l in text.splitlines()
        if l.strip() and not l.strip().startswith("#")
    ]
    for entry in entries:
        kind, _, pat = entry.partition(":")
        assert kind in ("race", "mutex", "signal", "deadlock", "thread",
                        "called_from_lib"), f"malformed suppression {entry!r}"
        # justification discipline: the pattern must be discussed in a
        # comment block naming report, reason and removal condition
        assert pat.strip() in text.split(entry)[0], (
            f"suppression {entry!r} has no written justification above it"
        )
    # the file documents the policy itself
    assert "TARGET STATE: EMPTY" in text
