"""Shared plumbing for the cross-tier lints (tools/lint_*.py).

These lints follow the r09 schema-lint discipline (tests/test_obs.py
``test_schema_lint_every_emitted_st_name_is_documented``): parse BOTH sides
of a contract from source text — never import the module under test, so a
seeded-violation tree (tests/test_static_analysis.py) lints exactly like
the real one — and fail by NAME with the file that violates. Every lint is
a standalone script: ``python tools/lint_X.py [--repo DIR]`` exits 0 clean
/ 1 with findings on stdout, and ``run(repo) -> list[str]`` is the
importable form the tests and suite gate use.
"""

from __future__ import annotations

import pathlib
import re
import sys


def read(repo: pathlib.Path, rel: str) -> str:
    return (repo / rel).read_text(errors="replace")


def strip_c_comments(text: str) -> str:
    """Drop // and /* */ comments (string literals in the native sources
    never contain comment markers; good enough for constant/decl parsing)."""
    text = re.sub(r"/\*.*?\*/", " ", text, flags=re.S)
    return re.sub(r"//[^\n]*", " ", text)


def strip_py_comments(text: str) -> str:
    return re.sub(r"#[^\n]*", " ", text)


def c_int(tok: str) -> int:
    """Parse a C integer literal (decimal or hex, optional u/l suffix)."""
    tok = tok.strip().rstrip("uUlL")
    return int(tok, 16) if tok.lower().startswith("0x") else int(tok, 10)


def main(run, repo_flag_default: str = ".") -> None:
    repo = pathlib.Path(repo_flag_default)
    args = sys.argv[1:]
    if args and args[0] == "--repo":
        repo = pathlib.Path(args[1])
    elif args:
        repo = pathlib.Path(args[0])
    findings = run(repo.resolve())
    name = pathlib.Path(sys.argv[0]).name
    if findings:
        for f in findings:
            print(f"{name}: {f}")
        print(f"{name}: FAIL ({len(findings)} finding(s))")
        sys.exit(1)
    print(f"{name}: OK")
    sys.exit(0)
