#!/usr/bin/env python
"""Event-code lint: obs/events.py CODE_NAMES vs the native kEv* constants.

The obs event codes are ABI across three surfaces — sttransport.cpp,
stengine.cpp (which re-declares the engine-side subset) and
obs/events.py's CODE_NAMES decode table. A native event emitted under a
code the Python table does not know decodes as ``code_N`` (a timeline
nobody can read); two native files disagreeing on one name is worse — the
same number means two different events. Both drifts become red gates here.

Checked:
  - every kEv* value in each native file is a key in CODE_NAMES;
  - kEv* constants sharing a name across the two native files agree;
  - no two kEv* in one file share a value;
  - transport.py EventKind (membership kinds 1..4) ⊆ CODE_NAMES.
"""

from __future__ import annotations

import pathlib
import re

if __package__ in (None, ""):
    import _lintlib as L
else:
    from . import _lintlib as L


#: kEv-prefixed constants that are NOT event codes (each with a reason;
#: a stale entry here is itself worth a look).
NOT_A_CODE = {
    "kEvRingCap",  # per-thread ring capacity, not a code
}


def _kev(text: str) -> dict[str, int]:
    return {
        name: L.c_int(val)
        for name, val in re.findall(
            r"constexpr\s+uint32_t\s+(kEv\w+)\s*=\s*(\d+)\s*;",
            text,
        )
        if name not in NOT_A_CODE
    }


def _code_names(text: str) -> dict[int, str]:
    m = re.search(r"CODE_NAMES[^=]*=\s*\{(.*?)\n\}", text, flags=re.S)
    if not m:
        return {}
    return {
        int(code): name
        for code, name in re.findall(r'(\d+)\s*:\s*"([a-z0-9_]+)"', m.group(1))
    }


def run(repo: pathlib.Path) -> list[str]:
    findings: list[str] = []
    files = {
        "native/sttransport.cpp": _kev(
            L.strip_c_comments(L.read(repo, "native/sttransport.cpp"))
        ),
        "native/stengine.cpp": _kev(
            L.strip_c_comments(L.read(repo, "native/stengine.cpp"))
        ),
    }
    names = _code_names(L.read(repo, "shared_tensor_tpu/obs/events.py"))

    if not names:
        findings.append("obs/events.py CODE_NAMES parse failed (pattern rot?)")
        return findings
    if sum(len(v) for v in files.values()) < 10:
        findings.append("parse floor: fewer than 10 kEv* constants across "
                        "the native files (pattern rot?)")

    for fname, kev in files.items():
        seen: dict[int, str] = {}
        for cname, val in kev.items():
            if val in seen:
                findings.append(
                    f"{fname}: {cname} and {seen[val]} share code {val}"
                )
            seen[val] = cname
            if val not in names:
                findings.append(
                    f"{fname}: {cname} = {val} has no obs/events.py "
                    f"CODE_NAMES entry (would decode as code_{val})"
                )
    shared = set(files["native/sttransport.cpp"]) & set(
        files["native/stengine.cpp"]
    )
    for cname in sorted(shared):
        a = files["native/sttransport.cpp"][cname]
        b = files["native/stengine.cpp"][cname]
        if a != b:
            findings.append(
                f"{cname} drifted between native files: sttransport.cpp "
                f"says {a}, stengine.cpp says {b}"
            )

    # r14 shm lane: the two lane events must exist under their canonical
    # NAMES, not just any name — the chaos arms and the shm tests tally
    # the timeline by name, so a silent rename (the numeric code still
    # valid) would zero their counts without a red anywhere else
    by_name = {v: k for k, v in names.items()}
    for want in ("shm_lane_up", "shm_fallback"):
        if want not in by_name:
            findings.append(
                f"obs/events.py CODE_NAMES lost the '{want}' event — the "
                f"shm chaos tallies key on this exact name"
            )

    # r18 health events: the analyzer's alert/heat events are python-tier
    # names pinned by HEALTH_EVENT_NAMES, and every name the analyzer
    # actually emits must be in that set — a rename on either side would
    # silently zero the fleet_health bench's timeline tallies (which key
    # on these exact names), with no red anywhere else
    epy = L.read(repo, "shared_tensor_tpu/obs/events.py")
    hm = re.search(
        r"HEALTH_EVENT_NAMES\s*=\s*frozenset\(\s*\{(.*?)\}", epy, flags=re.S
    )
    if not hm:
        findings.append(
            "obs/events.py HEALTH_EVENT_NAMES parse failed (pattern rot?)"
        )
        health_names: set[str] = set()
    else:
        health_names = set(re.findall(r'"([a-z0-9_]+)"', hm.group(1)))
        for want in ("slo_alert_fire", "slo_alert_clear", "hot_shard"):
            if want not in health_names:
                findings.append(
                    f"obs/events.py HEALTH_EVENT_NAMES lost '{want}' — the "
                    f"fleet_health bench tallies key on this exact name"
                )
    hpy = L.strip_py_comments(L.read(repo, "shared_tensor_tpu/obs/health.py"))
    emitted = set(re.findall(r'self\._event\(\s*"([a-z0-9_]+)"', hpy))
    if not emitted:
        findings.append(
            "obs/health.py emits no events (self._event parse rot?)"
        )
    for name in sorted(emitted - health_names):
        findings.append(
            f"obs/health.py emits '{name}' which is not in "
            f"obs/events.py HEALTH_EVENT_NAMES"
        )

    # membership kinds: transport.py's EventKind enum doubles as timeline
    # codes 1..4 (Node::emit feeds both surfaces with one number)
    tpy = L.strip_py_comments(
        L.read(repo, "shared_tensor_tpu/comm/transport.py")
    )
    m = re.search(r"class EventKind\(.*?\):\n((?:\s+\w+ = \d+\n)+)", tpy)
    if not m:
        findings.append("transport.py EventKind parse failed (pattern rot?)")
    else:
        for kname, val in re.findall(r"(\w+) = (\d+)", m.group(1)):
            if int(val) not in names:
                findings.append(
                    f"transport.py EventKind.{kname} = {val} missing from "
                    f"obs/events.py CODE_NAMES"
                )
    return findings


if __name__ == "__main__":
    L.main(run)
