/* Analyzer-only <stdatomic.h> for tools/analyze_clang.py.
 *
 * The pip libclang wheel ships NO builtin headers, so the front-end
 * borrows gcc's include dirs — but gcc's stdatomic.h expands the C11
 * atomic generics to __atomic_* builtins, which clang REJECTS on
 * _Atomic-qualified lvalues (clang routes _Atomic through its
 * __c11_atomic_* builtins instead). This shim is the clang spelling of
 * the same header, covering exactly the operations the native C tier
 * uses (stcodec.c). It is -isystem'd AHEAD of the gcc dirs by
 * analyze_clang.py only — no build ever sees it.
 */
#ifndef ST_ANALYZE_STDATOMIC_H_
#define ST_ANALYZE_STDATOMIC_H_

#ifndef __clang__
#error "analyzer shim: only the libclang front-end may include this"
#endif

typedef enum memory_order {
  memory_order_relaxed = __ATOMIC_RELAXED,
  memory_order_consume = __ATOMIC_CONSUME,
  memory_order_acquire = __ATOMIC_ACQUIRE,
  memory_order_release = __ATOMIC_RELEASE,
  memory_order_acq_rel = __ATOMIC_ACQ_REL,
  memory_order_seq_cst = __ATOMIC_SEQ_CST
} memory_order;

#define ATOMIC_VAR_INIT(value) (value)
#define atomic_init __c11_atomic_init

#define atomic_load_explicit __c11_atomic_load
#define atomic_store_explicit __c11_atomic_store
#define atomic_exchange_explicit __c11_atomic_exchange
#define atomic_fetch_add_explicit __c11_atomic_fetch_add
#define atomic_fetch_sub_explicit __c11_atomic_fetch_sub
#define atomic_fetch_or_explicit __c11_atomic_fetch_or
#define atomic_fetch_and_explicit __c11_atomic_fetch_and
#define atomic_compare_exchange_weak_explicit(obj, exp, des, suc, fail) \
  __c11_atomic_compare_exchange_weak(obj, exp, des, suc, fail)
#define atomic_compare_exchange_strong_explicit(obj, exp, des, suc, fail) \
  __c11_atomic_compare_exchange_strong(obj, exp, des, suc, fail)

#define atomic_load(obj) atomic_load_explicit(obj, memory_order_seq_cst)
#define atomic_store(obj, des) \
  atomic_store_explicit(obj, des, memory_order_seq_cst)
#define atomic_exchange(obj, des) \
  atomic_exchange_explicit(obj, des, memory_order_seq_cst)
#define atomic_fetch_add(obj, arg) \
  atomic_fetch_add_explicit(obj, arg, memory_order_seq_cst)
#define atomic_fetch_sub(obj, arg) \
  atomic_fetch_sub_explicit(obj, arg, memory_order_seq_cst)
#define atomic_compare_exchange_weak(obj, exp, des)                       \
  atomic_compare_exchange_weak_explicit(obj, exp, des,                    \
                                        memory_order_seq_cst,             \
                                        memory_order_seq_cst)
#define atomic_compare_exchange_strong(obj, exp, des)                     \
  atomic_compare_exchange_strong_explicit(obj, exp, des,                  \
                                          memory_order_seq_cst,          \
                                          memory_order_seq_cst)

#define atomic_thread_fence(order) __c11_atomic_thread_fence(order)
#define atomic_signal_fence(order) __c11_atomic_signal_fence(order)

typedef _Atomic _Bool atomic_bool;
typedef _Atomic int atomic_int;
typedef _Atomic unsigned int atomic_uint;
typedef _Atomic long atomic_long;
typedef _Atomic unsigned long atomic_ulong;
typedef _Atomic long long atomic_llong;
typedef _Atomic unsigned long long atomic_ullong;

#endif /* ST_ANALYZE_STDATOMIC_H_ */
