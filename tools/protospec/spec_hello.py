"""SYNC/WELCOME capability-hello spec (comm/wire.py encode_sync /
encode_welcome, comm/peer.py handshake handlers, compat.py flag rules).

The handshake is a tolerant-extension protocol: every generation since
r09 appends trailing bytes older peers ignore, and EVERY capability
mismatch must silently resolve to the least-capable common behavior —
v1/v2/v3 framing by exact length, 1-bit vs sign2 codec by advertised
decode capability, TCP vs the same-host shm lane by flag + boot-id +
segment validation. The failure mode this spec exists to rule out is a
HALF-negotiated link: one side emitting framing the other rejects, a
parent upshifting to sign2 toward a 1-bit child, or one end moving its
data plane to the rings while the other keeps reading TCP.

The explorer enumerates the full generation/capability product — joiner
and parent each drawn from r09/r10/r11/r14 with every legal flag
combination, same-host or cross-host, segment validation succeeding or
failing (the adversary's branch) — and checks the resolved link
agreement in every outcome:

- ``decodable-emission``: each side's DATA/BURST emission version is in
  the peer's decode set (v3 only toward a peer that advertised the r14
  capability flag);
- ``sign2-decodable``: sign2 emission only toward a peer that
  advertised SYNC_FLAG_SIGN2;
- ``lane-agreement``: both ends resolve the same lane, and shm implies
  both-r14 + both-enabled + boot-id match + validated join;
- ``ranged-implies-ro``: a range subscription only on a read-only link;
- ``ledger-agreement``: the link is unledgered iff the joiner
  advertised READ_ONLY.

Generations model the shipped decoders: every peer decodes v1+v2
(pre-r09 peers are out of support — compat.py documents ST_WIRE_TRACE=0
as the manual escape hatch for those); only r14 peers decode v3.
"""

from __future__ import annotations

from typing import NamedTuple

from .core import Spec, TraceAcceptor

R09, R10, R11, R14 = 9, 10, 11, 14


def _joiner_cfgs():
    out = []
    for gen in (R09, R10, R11, R14):
        for v in (1, 2):
            for ro in (0, 1) if gen >= R10 else (0,):
                for rng in (0, 1) if ro else (0,):
                    for sign2 in (0, 1) if gen >= R11 else (0,):
                        for shm in (0, 1) if gen >= R14 else (0,):
                            for host in (0, 1) if shm else (0,):
                                out.append(
                                    (gen, v, ro, rng, sign2, shm, host)
                                )
    return tuple(out)


def _parent_cfgs():
    out = []
    for gen in (R09, R10, R11, R14):
        for pin in (0, 1):
            for s2cap in (0, 1) if gen >= R11 else (0,):
                for shmcap in (0, 1) if gen >= R14 else (0,):
                    for host in (0, 1) if shmcap else (0,):
                        out.append((gen, pin, s2cap, shmcap, host))
    return tuple(out)


J_CFGS = _joiner_cfgs()
P_CFGS = _parent_cfgs()


class HelloState(NamedTuple):
    j: tuple  # (gen, v_emit, ro, rng, sign2, shm, host) or ()
    p: tuple  # (gen, pin_v1, sign2cap, shmcap, host) or ()
    phase: int  # 0 pick / 1 sync sent / 2 welcome sent / 3 join pending
    #             4 resolved
    p_seen_flags: tuple  # parent's view: (ro, rng, sign2flag, shmflag)
    w_flags: tuple  # joiner's view of WELCOME: (sign2flag, shmflag)
    offer: int  # 0 none / 1 offer shipped in the WELCOME tail
    j_lane: str  # "", "tcp", "shm"
    p_lane: str


class HelloSpec(Spec):
    name = "hello"
    depth_bound = 8
    mutations: dict[str, str] = {}

    def initial(self):
        return HelloState((), (), 0, (), (), 0, "", "")

    def enabled(self, s: HelloState):
        if s.phase == 0:
            return [("pick", j, p) for j in J_CFGS for p in P_CFGS]
        if s.phase == 1:
            return [("welcome",)]
        if s.phase == 2:
            if s.offer:
                # segment validation is the adversary's branch: a failed
                # open/map/token check MUST degrade to TCP (shm_fallback)
                return [("join_ok",), ("join_fail",)]
            return [("no_offer",)]
        if s.phase == 3:
            return [("serve_deadline",)]
        return []

    def apply(self, s: HelloState, a):
        kind = a[0]
        if kind == "pick":
            return s._replace(j=a[1], p=a[2], phase=1)
        if kind == "welcome":
            jgen, _, ro, rng, sign2, shm, jhost = s.j
            pgen, _, s2cap, shmcap, phost = s.p
            # the parent reads the SYNC flags its generation knows about
            mask_ro = pgen >= R10
            mask_s2 = pgen >= R11
            mask_shm = pgen >= R14
            seen = (
                ro if mask_ro else 0,
                rng if mask_ro else 0,
                sign2 if mask_s2 else 0,
                # _peer_r14: own shm enabled AND the joiner's flag
                (shm if shmcap else 0) if mask_shm else 0,
            )
            # WELCOME flags: parent's own capabilities (peer.py: SIGN2
            # iff sign2 armed; SHM iff _shm_ok — host match NOT required
            # for the flag, it marks the parent a v3 decoder)
            wf = (
                1 if (pgen >= R11 and s2cap) else 0,
                1 if (pgen >= R14 and shmcap) else 0,
            )
            # segment offer iff host identity matched (peer.py _peer_shm)
            offer = int(
                bool(seen[3]) and shm and jhost == phost and shmcap
            )
            return s._replace(
                phase=2, p_seen_flags=seen, w_flags=wf, offer=offer
            )
        jgen, _, ro, rng, sign2, shm, jhost = s.j
        # the joiner reads the WELCOME flags its generation knows about
        j_sees_shm = bool(
            s.w_flags and s.w_flags[1] and jgen >= R14 and shm
        )
        if kind == "join_ok":
            if j_sees_shm:
                # joiner validated the segment: both planes move to rings
                return s._replace(phase=4, j_lane="shm", p_lane="shm")
            # offer present but the joiner cannot read it (pre-r14 or
            # ST_SHM=0): the tail is ignored, parent's serve deadline
            # closes the unjoined lane
            return s._replace(phase=3, j_lane="tcp")
        if kind == "join_fail":
            # map/token validation failed -> shm_fallback, keep TCP; the
            # parent's lane never activates (joined flag never set)
            return s._replace(phase=3, j_lane="tcp")
        if kind == "no_offer":
            return s._replace(phase=4, j_lane="tcp", p_lane="tcp")
        if kind == "serve_deadline":
            return s._replace(phase=4, p_lane="tcp")
        raise AssertionError(a)

    # -- resolved-link properties -------------------------------------------

    @staticmethod
    def _decodes(gen: int) -> frozenset:
        return frozenset((1, 2, 3)) if gen >= R14 else frozenset((1, 2))

    def _resolved(self, s: HelloState) -> dict:
        jgen, jv, ro, rng, sign2, shm, _ = s.j
        pgen, pin, s2cap, _, _ = s.p
        p_emit = (
            3
            if s.p_seen_flags[3]
            else (1 if pin else 2)
            if pgen >= R09
            else 1
        )
        j_saw_shm_flag = bool(s.w_flags[1] and jgen >= R14 and shm)
        j_emit = 3 if j_saw_shm_flag else jv
        p_sign2_emit = bool(
            pgen >= R11 and s2cap and s.p_seen_flags[2] and not ro
        )
        return {
            "p_emit": p_emit,
            "j_emit": j_emit,
            "p_sign2_emit": p_sign2_emit,
            "unledgered": bool(s.p_seen_flags[0]),
            "ranged": bool(s.p_seen_flags[1]),
        }

    def invariants(self, s: HelloState):
        if s.phase != 4:
            return []
        bad = []
        jgen, _, ro, rng, sign2, shm, jhost = s.j
        pgen, _, _, shmcap, phost = s.p
        r = self._resolved(s)
        if r["p_emit"] not in self._decodes(jgen):
            bad.append(
                f"decodable-emission: parent emits v{r['p_emit']} toward "
                f"a gen-{jgen} joiner"
            )
        if r["j_emit"] not in self._decodes(pgen):
            bad.append(
                f"decodable-emission: joiner emits v{r['j_emit']} toward "
                f"a gen-{pgen} parent"
            )
        if r["p_sign2_emit"] and not (jgen >= R11 and sign2):
            bad.append(
                "sign2-decodable: 2-bit emission toward a 1-bit joiner"
            )
        if s.j_lane != s.p_lane:
            bad.append(
                f"lane-agreement: joiner={s.j_lane!r} parent={s.p_lane!r}"
            )
        if s.j_lane == "shm" and not (
            jgen >= R14 and pgen >= R14 and shm and shmcap and jhost == phost
        ):
            bad.append(
                "lane-agreement: shm lane without both-r14 + both-enabled "
                "+ host match"
            )
        if r["ranged"] and not r["unledgered"]:
            bad.append("ranged-implies-ro: RANGE accepted on a writer link")
        if r["unledgered"] != bool(ro and pgen >= R10):
            bad.append(
                "ledger-agreement: parent's ledger mode disagrees with "
                "the joiner's advertised READ_ONLY"
            )
        return bad

    def quiescent(self, s: HelloState):
        return s.phase == 4


class HelloAcceptor(TraceAcceptor):
    """One (node, link) handshake scope over the recorded lane events:
    negotiation happens once per link, so at most one lane verdict
    (shm_lane_up XOR shm_fallback) may fire — spec_lane.LaneAcceptor
    already enforces the lane rules; this acceptor adds the subscriber
    pairing (sub_attach precedes any data-plane verdict on a sub
    link)."""

    def __init__(self, scope: str = ""):
        super().__init__(scope)
        self._verdicts = 0

    def step(self, event: dict) -> None:
        if event["name"] in ("shm_lane_up", "shm_fallback"):
            self._verdicts += 1
            if self._verdicts > 1:
                self._flag("more than one shm negotiation verdict per link")


SPECS = [HelloSpec]
