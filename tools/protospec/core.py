"""Spec interface + exhaustive explorer (the protospec engine).

A spec is a *closed* transition system: a hashable initial state, an
``enabled(state)`` enumeration of every action any participant — node,
timer, or adversarial network — may take, and a pure
``apply(state, action)``. The adversary is not a separate layer: a spec
that allows the network to drop a message simply enumerates the drop as
an enabled action, so exhaustive BFS over actions IS exhaustive
adversarial exploration (delay falls out of interleaving: "not
delivered yet" is always a reachable ordering).

The explorer is deliberately plain: breadth-first over canonicalized
states (``canon`` is the per-spec symmetry reduction — e.g. sorting
interchangeable peers), a seen-set of state hashes, invariants checked
at every state, and three verdict classes:

- **invariant**: a reached state violates a named safety property;
- **wedged**: a reached non-quiescent state has NO enabled action —
  the model-level shape of a livelock/deadlock (a real spin-forever is
  modeled as "the blocked action is not enabled", so the wedge is a
  missing successor, not an infinite path);
- **no-quiescence**: the whole bounded graph contains no quiescent
  state (the protocol cannot finish even with a cooperative adversary).

Counterexamples are reconstructed from a predecessor map and reported
as the action path from the initial state.

States are value objects (tuples of primitives / frozensets); specs
never mutate them. Determinism matters: the committed MODEL artifact
pins exact state/transition counts, so ``enabled`` must return a
deterministically ordered list.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Hashable, Iterable, Optional


class Spec:
    """Base class for protocol specs. Subclasses define the five hooks
    and two documented bounds:

    - ``name``: artifact/report key;
    - ``depth_bound``: BFS depth the checker explores to (committed in
      MODEL_r17.json — "verified to depth D" is the honest claim);
    - ``mutations``: mutation name -> the historical bug it seeds
      (constructed via ``Spec(mutation=name)``).
    """

    name: str = "base"
    depth_bound: int = 32
    mutations: dict[str, str] = {}

    def __init__(self, mutation: Optional[str] = None):
        if mutation is not None and mutation not in self.mutations:
            raise ValueError(
                f"{self.name}: unknown mutation {mutation!r} "
                f"(have {sorted(self.mutations)})"
            )
        self.mutation = mutation

    # -- transition system ---------------------------------------------------

    def initial(self) -> Hashable:
        raise NotImplementedError

    def enabled(self, state) -> list:
        """Deterministically ordered list of hashable actions."""
        raise NotImplementedError

    def apply(self, state, action) -> Hashable:
        raise NotImplementedError

    # -- verdicts ------------------------------------------------------------

    def invariants(self, state) -> list[str]:
        """Names of every safety property this state violates."""
        return []

    def quiescent(self, state) -> bool:
        """The protocol has finished cleanly in this state."""
        raise NotImplementedError

    def canon(self, state) -> Hashable:
        """Symmetry reduction: map a state to its equivalence-class
        representative (default: identity)."""
        return state


@dataclasses.dataclass
class Violation:
    kind: str  # "invariant" | "wedged" | "no-quiescence"
    detail: str
    depth: int
    trace: tuple  # action path from the initial state

    def as_dict(self) -> dict:
        return {
            "kind": self.kind,
            "detail": self.detail,
            "depth": self.depth,
            "trace": [repr(a) for a in self.trace],
        }


@dataclasses.dataclass
class ExploreResult:
    spec: str
    mutation: Optional[str]
    states: int
    transitions: int
    depth_bound: int
    max_depth_reached: int
    truncated_by_depth: bool
    quiescent_reachable: bool
    violations: list[Violation]

    @property
    def ok(self) -> bool:
        return not self.violations and self.quiescent_reachable

    def as_dict(self) -> dict:
        return {
            "spec": self.spec,
            "mutation": self.mutation,
            "states": self.states,
            "transitions": self.transitions,
            "depth_bound": self.depth_bound,
            "max_depth_reached": self.max_depth_reached,
            "truncated_by_depth": self.truncated_by_depth,
            "quiescent_reachable": self.quiescent_reachable,
            "violations": [v.as_dict() for v in self.violations],
        }


def _trace(parent: dict, key) -> tuple:
    path = []
    while True:
        pkey, act = parent[key]
        if act is None:
            break
        path.append(act)
        key = pkey
    return tuple(reversed(path))


def explore(
    spec: Spec,
    depth_bound: Optional[int] = None,
    max_states: int = 2_000_000,
    max_violations: int = 4,
) -> ExploreResult:
    """Exhaustive BFS of ``spec`` to its depth bound.

    Stops collecting counterexamples after ``max_violations`` (the first
    few traces are what a human debugs; the count in the artifact stays
    honest via ``violations != []``). ``max_states`` is a hard memory
    backstop — hitting it raises, because a truncated-by-memory run
    must never masquerade as an exhaustive one.
    """
    bound = spec.depth_bound if depth_bound is None else depth_bound
    init = spec.initial()
    ckey = spec.canon(init)
    seen: set = {ckey}
    parent: dict = {ckey: (None, None)}
    frontier: list = [(init, ckey)]
    violations: list[Violation] = []
    quiescent = spec.quiescent(init)
    states, transitions, depth = 1, 0, 0
    truncated = False

    bad = spec.invariants(init)
    for b in bad[: max(0, max_violations - len(violations))]:
        violations.append(Violation("invariant", b, 0, ()))

    while frontier:
        if depth >= bound:
            truncated = True
            break
        nxt: list = []
        for state, key in frontier:
            acts = spec.enabled(state)
            if not acts:
                if not spec.quiescent(state) and len(violations) < max_violations:
                    violations.append(
                        Violation(
                            "wedged",
                            f"non-quiescent state has no enabled action: "
                            f"{state!r}",
                            depth,
                            _trace(parent, key),
                        )
                    )
                continue
            for act in acts:
                t = spec.apply(state, act)
                transitions += 1
                tkey = spec.canon(t)
                if tkey in seen:
                    continue
                seen.add(tkey)
                parent[tkey] = (key, act)
                states += 1
                if states > max_states:
                    raise RuntimeError(
                        f"{spec.name}: exceeded {max_states} states — the "
                        f"model must shrink (an exhausted-memory run is "
                        f"not an exhaustive one)"
                    )
                for b in spec.invariants(t):
                    if len(violations) < max_violations:
                        violations.append(
                            Violation(
                                "invariant", b, depth + 1,
                                _trace(parent, tkey),
                            )
                        )
                if spec.quiescent(t):
                    quiescent = True
                nxt.append((t, tkey))
        frontier = nxt
        if frontier:
            depth += 1

    if not quiescent and len(violations) < max_violations:
        violations.append(
            Violation(
                "no-quiescence",
                f"no quiescent state reachable within depth {bound}",
                depth,
                (),
            )
        )
    return ExploreResult(
        spec=spec.name,
        mutation=spec.mutation,
        states=states,
        transitions=transitions,
        depth_bound=bound,
        max_depth_reached=depth,
        truncated_by_depth=truncated,
        quiescent_reachable=quiescent,
        violations=violations,
    )


# -- trace-acceptor base -----------------------------------------------------


class TraceAcceptor:
    """Runtime-conformance counterpart of a spec: a monitor that replays
    one SCOPE of a recorded timeline (one node's lifecycle, one link's
    window, ...) through the spec's legal orderings. ``step`` consumes
    one event dict (obs/events.Event.as_dict shape) and records any
    violation; ``finish`` closes end-of-run obligations ("no node left
    paused" is only checkable at the end).

    Acceptors must be PERMISSIVE about events they don't model (a
    timeline is a lossy projection of the run — the ring can drop
    records under overflow) and STRICT about orderings the spec forbids:
    an accepted violating trace is worse than a rejected honest one.
    """

    scope: str = ""

    def __init__(self, scope: str = ""):
        self.scope = scope
        self.violations: list[str] = []

    def _flag(self, msg: str) -> None:
        self.violations.append(f"[{self.scope}] {msg}")

    def step(self, event: dict) -> None:
        raise NotImplementedError

    def finish(self) -> list[str]:
        return self.violations


def iter_events(timeline: Iterable[Any]) -> Iterable[dict]:
    """Normalize a timeline (Event objects or dicts) to dicts with the
    Event.as_dict keys present (detail/extra defaulted)."""
    for e in timeline:
        if isinstance(e, dict):
            d = dict(e)
        else:  # obs/events.Event
            d = e.as_dict()
        d.setdefault("detail", "")
        d.setdefault("extra", 0)
        d.setdefault("node", 0)
        d.setdefault("link", 0)
        d.setdefault("arg", 0)
        yield d
