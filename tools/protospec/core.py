"""Spec interface + exhaustive explorer (the protospec engine).

A spec is a *closed* transition system: a hashable initial state, an
``enabled(state)`` enumeration of every action any participant — node,
timer, or adversarial network — may take, and a pure
``apply(state, action)``. The adversary is not a separate layer: a spec
that allows the network to drop a message simply enumerates the drop as
an enabled action, so exhaustive BFS over actions IS exhaustive
adversarial exploration (delay falls out of interleaving: "not
delivered yet" is always a reachable ordering).

The explorer is deliberately plain: breadth-first over canonicalized
states (``canon`` is the per-spec symmetry reduction — e.g. sorting
interchangeable peers), a seen-set of state hashes, invariants checked
at every state, and four verdict classes:

- **invariant**: a reached state violates a named safety property;
- **wedged**: a reached non-quiescent state has NO enabled action —
  the model-level shape of a livelock/deadlock (a real spin-forever is
  modeled as "the blocked action is not enabled", so the wedge is a
  missing successor, not an infinite path);
- **no-quiescence**: the whole bounded graph contains no quiescent
  state (the protocol cannot finish even with a cooperative adversary);
- **liveness** (r19): a fairness-bounded "always eventually" property
  fails — the explored graph contains a reachable FAIR cycle no state
  of which satisfies the property's good-set (a lasso the adversary can
  drive forever without ever converging/resuming). See ``Spec.liveness``
  / ``Spec.fairness`` and the SCC pass below.

The r19 reshard models dwarf the r15–r17 state spaces, so the explorer
carries two REDUCTIONS, both off-by-default per spec (identity hooks):

- **symmetry** (``canon``, r15): states are deduplicated by their
  canonical representative — a spec with interchangeable node/shard
  identities maps each state to the least relabeling, and the explorer
  never expands two states in the same orbit.
- **partial-order** (``ample``, r19): at each state the spec may
  nominate an AMPLE SUBSET of the enabled actions whose members commute
  with (and neither disable nor are disabled by) every action left out
  — pure-local steps like an in-flight delivery that touches one
  channel. The explorer expands only the ample set, and enforces the
  classic soundness provisos DYNAMICALLY rather than trusting the spec:
  the reduction is dropped at any state where (C2-invisibility) an
  ample action changes the invariant verdicts or the quiescence of its
  successor, or (C3-cycle) an ample successor lands on an
  already-seen state — the standard cycle proviso, conservatively
  triggered by cross edges too, so an action can never be deferred
  around a loop forever. Independence itself (C1) is the spec's
  declared contract; the reduction-soundness regression in
  tests/test_protospec.py re-finds every seeded mutation with the
  reductions on.

Counterexamples are reconstructed from a predecessor map and reported
as the action path from the initial state; liveness counterexamples are
lassos (stem trace + the cycle's actions in the detail).

States are value objects (tuples of primitives / frozensets); specs
never mutate them. Determinism matters: the committed MODEL artifact
pins exact state/transition counts, so ``enabled`` must return a
deterministically ordered list.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Hashable, Iterable, Optional


class Spec:
    """Base class for protocol specs. Subclasses define the five hooks
    and two documented bounds:

    - ``name``: artifact/report key;
    - ``depth_bound``: BFS depth the checker explores to (committed in
      MODEL_r19.json — "verified to depth D" is the honest claim);
    - ``mutations``: mutation name -> the historical bug it seeds
      (constructed via ``Spec(mutation=name)``).
    """

    name: str = "base"
    depth_bound: int = 32
    mutations: dict[str, str] = {}

    def __init__(self, mutation: Optional[str] = None):
        if mutation is not None and mutation not in self.mutations:
            raise ValueError(
                f"{self.name}: unknown mutation {mutation!r} "
                f"(have {sorted(self.mutations)})"
            )
        self.mutation = mutation

    # -- transition system ---------------------------------------------------

    def initial(self) -> Hashable:
        raise NotImplementedError

    def enabled(self, state) -> list:
        """Deterministically ordered list of hashable actions."""
        raise NotImplementedError

    def apply(self, state, action) -> Hashable:
        raise NotImplementedError

    # -- verdicts ------------------------------------------------------------

    def invariants(self, state) -> list[str]:
        """Names of every safety property this state violates."""
        return []

    def quiescent(self, state) -> bool:
        """The protocol has finished cleanly in this state."""
        raise NotImplementedError

    def canon(self, state) -> Hashable:
        """Symmetry reduction: map a state to its equivalence-class
        representative (default: identity)."""
        return state

    # -- r19 reduction / liveness hooks --------------------------------------

    def ample(self, state, acts: list) -> list:
        """Partial-order reduction: return a subset of ``acts`` whose
        members are independent of every action left out (commute with
        them and neither disable nor are disabled by them). Returning
        ``acts`` unchanged (the default) disables the reduction at this
        state. The explorer enforces the invisibility and cycle provisos
        dynamically and falls back to full expansion when they fail, so
        a spec only vouches for INDEPENDENCE, not for the global
        soundness conditions."""
        return acts

    def liveness(self) -> dict:
        """Fairness-bounded "always eventually" properties: name -> a
        good-state predicate. A property FAILS iff the explored graph
        contains a reachable fair cycle (see ``fairness``) none of whose
        states satisfies the predicate — i.e. some infinite fair
        adversary schedule avoids the good set forever. Default: no
        liveness properties (the r15–r17 wedged/no-quiescence verdicts
        still apply)."""
        return {}

    def fairness(self) -> list:
        """Weak-fairness constraints: ``[(name, action_predicate)]``.
        A cycle is FAIR iff for every constraint either (a) some edge of
        the cycle takes a matching action, or (b) some state on the
        cycle has no matching action enabled (so the constraint is not
        continuously enabled and weak fairness demands nothing). Actions
        left unmatched by every constraint — adversary drops, dup
        redeliveries, stale replays — may be scheduled forever, which is
        exactly the adversarial schedule liveness must survive."""
        return []


@dataclasses.dataclass
class Violation:
    kind: str  # "invariant" | "wedged" | "no-quiescence" | "liveness"
    detail: str
    depth: int
    trace: tuple  # action path from the initial state

    def as_dict(self) -> dict:
        return {
            "kind": self.kind,
            "detail": self.detail,
            "depth": self.depth,
            "trace": [repr(a) for a in self.trace],
        }


@dataclasses.dataclass
class ExploreResult:
    spec: str
    mutation: Optional[str]
    states: int
    transitions: int
    depth_bound: int
    max_depth_reached: int
    truncated_by_depth: bool
    quiescent_reachable: bool
    violations: list[Violation]
    # r19: liveness verdicts, property name -> True (holds) / False
    # (fair counterexample lasso found) / None (graph truncated by the
    # depth bound, so cycles beyond the horizon are unknowable — an
    # honest "not checked", never a silent pass).
    liveness: dict = dataclasses.field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return (
            not self.violations
            and self.quiescent_reachable
            and all(v is True for v in self.liveness.values())
        )

    def as_dict(self) -> dict:
        return {
            "spec": self.spec,
            "mutation": self.mutation,
            "states": self.states,
            "transitions": self.transitions,
            "depth_bound": self.depth_bound,
            "max_depth_reached": self.max_depth_reached,
            "truncated_by_depth": self.truncated_by_depth,
            "quiescent_reachable": self.quiescent_reachable,
            "violations": [v.as_dict() for v in self.violations],
            "liveness": dict(self.liveness),
        }


def _trace(parent: dict, key) -> tuple:
    path = []
    while True:
        pkey, act = parent[key]
        if act is None:
            break
        path.append(act)
        key = pkey
    return tuple(reversed(path))


def explore(
    spec: Spec,
    depth_bound: Optional[int] = None,
    max_states: int = 2_000_000,
    max_violations: int = 4,
    reduction: bool = True,
) -> ExploreResult:
    """Exhaustive BFS of ``spec`` to its depth bound.

    Stops collecting counterexamples after ``max_violations`` (the first
    few traces are what a human debugs; the count in the artifact stays
    honest via ``violations != []``). ``max_states`` is a hard memory
    backstop — hitting it raises, because a truncated-by-memory run
    must never masquerade as an exhaustive one.

    ``reduction=False`` bypasses BOTH reductions (``canon`` becomes
    identity, ``ample`` is ignored) so tests can A/B the reduced graph
    against ground truth. Specs with identity hooks (all of r15–r17)
    produce bit-identical results either way — the committed
    MODEL artifacts stay reproducible.

    When ``spec.liveness()`` declares properties, the explorer retains
    the successor graph and — only after true frontier exhaustion —
    runs an SCC pass per property: a strongly connected component of
    the ¬good-induced subgraph that contains a cycle and admits a FAIR
    schedule (see ``Spec.fairness``) is a lasso the adversary can drive
    forever, reported as a ``liveness`` violation with the stem trace
    and the cycle's actions.
    """
    bound = spec.depth_bound if depth_bound is None else depth_bound
    if reduction:
        canon = spec.canon
    else:
        def canon(s):
            return s
    live_props = spec.liveness()
    init = spec.initial()
    ckey = canon(init)
    seen: set = {ckey}
    parent: dict = {ckey: (None, None)}
    frontier: list = [(init, ckey)]
    violations: list[Violation] = []
    quiescent = spec.quiescent(init)
    states, transitions, depth = 1, 0, 0
    truncated = False
    # Liveness needs the full edge set (including edges into
    # already-seen states — exactly the ones that close cycles) and a
    # concrete representative per canonical key.
    succs: dict = {ckey: []} if live_props else {}
    rep: dict = {ckey: init} if live_props else {}

    bad = spec.invariants(init)
    for b in bad[: max(0, max_violations - len(violations))]:
        violations.append(Violation("invariant", b, 0, ()))

    while frontier:
        if depth >= bound:
            truncated = True
            break
        nxt: list = []
        for state, key in frontier:
            acts = spec.enabled(state)
            if not acts:
                if not spec.quiescent(state) and len(violations) < max_violations:
                    violations.append(
                        Violation(
                            "wedged",
                            f"non-quiescent state has no enabled action: "
                            f"{state!r}",
                            depth,
                            _trace(parent, key),
                        )
                    )
                continue
            expand = acts
            if reduction:
                cand = spec.ample(state, acts)
                if cand and len(cand) < len(acts):
                    # Dynamic provisos. C2 (invisibility): an ample
                    # action must not change any verdict. C3 (cycle):
                    # no ample successor may land on an already-seen
                    # state, else an excluded action could be deferred
                    # around a loop forever. Either failure → full
                    # expansion at this state.
                    s_inv = spec.invariants(state)
                    s_qui = spec.quiescent(state)
                    ok = True
                    for act in cand:
                        t = spec.apply(state, act)
                        tkey = canon(t)
                        if (
                            tkey in seen
                            or spec.invariants(t) != s_inv
                            or spec.quiescent(t) != s_qui
                        ):
                            ok = False
                            break
                    if ok:
                        expand = cand
            for act in expand:
                t = spec.apply(state, act)
                transitions += 1
                tkey = canon(t)
                if live_props:
                    succs[key].append((act, tkey))
                if tkey in seen:
                    continue
                seen.add(tkey)
                parent[tkey] = (key, act)
                if live_props:
                    succs[tkey] = []
                    rep[tkey] = t
                states += 1
                if states > max_states:
                    raise RuntimeError(
                        f"{spec.name}: exceeded {max_states} states — the "
                        f"model must shrink (an exhausted-memory run is "
                        f"not an exhaustive one)"
                    )
                for b in spec.invariants(t):
                    if len(violations) < max_violations:
                        violations.append(
                            Violation(
                                "invariant", b, depth + 1,
                                _trace(parent, tkey),
                            )
                        )
                if spec.quiescent(t):
                    quiescent = True
                nxt.append((t, tkey))
        frontier = nxt
        if frontier:
            depth += 1

    if not quiescent and len(violations) < max_violations:
        violations.append(
            Violation(
                "no-quiescence",
                f"no quiescent state reachable within depth {bound}",
                depth,
                (),
            )
        )

    live_verdicts: dict = {}
    if live_props:
        if truncated:
            # Cycles beyond the depth horizon are unknowable; "not
            # checked" must never read as "holds".
            live_verdicts = {name: None for name in live_props}
        else:
            fair = spec.fairness()
            for name, good in sorted(live_props.items()):
                lasso = _fair_lasso(succs, rep, good, fair, spec)
                live_verdicts[name] = lasso is None
                if lasso is not None and len(violations) < max_violations:
                    entry, cycle_acts = lasso
                    violations.append(
                        Violation(
                            "liveness",
                            f"{name}: fair cycle avoids good set forever; "
                            f"cycle actions {[repr(a) for a in cycle_acts]}",
                            len(_trace(parent, entry)),
                            _trace(parent, entry),
                        )
                    )

    return ExploreResult(
        spec=spec.name,
        mutation=spec.mutation,
        states=states,
        transitions=transitions,
        depth_bound=bound,
        max_depth_reached=depth,
        truncated_by_depth=truncated,
        quiescent_reachable=quiescent,
        violations=violations,
        liveness=live_verdicts,
    )


def _fair_lasso(succs: dict, rep: dict, good, fair: list, spec: Spec):
    """Find a fair cycle in the ¬good-induced subgraph of the explored
    state graph, or None if every ¬good cycle is unfair.

    Iterative Tarjan over the subgraph of states whose representative
    fails ``good``. A component with at least one internal edge (size >
    1 or a self-loop) carries an infinite schedule; that schedule can be
    made to traverse EVERY internal state and edge (strong
    connectivity), so the component admits a fair cycle iff for every
    weak-fairness constraint either some internal edge's action matches
    it or some member state has no matching enabled action. Returns
    ``(entry_key, cycle_actions)`` — the first-discovered member as the
    stem target plus a concrete action cycle inside the component.
    """
    nodes = [k for k in succs if not good(rep[k])]
    node_set = set(nodes)
    index: dict = {}
    low: dict = {}
    on_stack: set = set()
    stack: list = []
    counter = [0]

    def strong(root):
        # Iterative Tarjan; yields SCCs as lists of keys.
        work = [(root, 0)]
        path = []
        out = []
        while work:
            v, pi = work.pop()
            if pi == 0:
                index[v] = low[v] = counter[0]
                counter[0] += 1
                stack.append(v)
                on_stack.add(v)
                path.append(v)
            recurse = False
            edges = succs[v]
            for i in range(pi, len(edges)):
                w = edges[i][1]
                if w not in node_set:
                    continue
                if w not in index:
                    work.append((v, i + 1))
                    work.append((w, 0))
                    recurse = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if recurse:
                continue
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                out.append(comp)
            path.pop()
            if path:
                low[path[-1]] = min(low[path[-1]], low[v])
        return out

    for start in nodes:
        if start in index:
            continue
        for comp in strong(start):
            comp_set = set(comp)
            internal = [
                (u, act, w)
                for u in comp
                for act, w in succs[u]
                if w in comp_set
            ]
            if not internal:
                continue  # trivial SCC, no cycle
            fair_ok = True
            for _, pred in fair:
                if any(pred(act) for _, act, _ in internal):
                    continue
                if any(
                    not any(pred(a) for a in spec.enabled(rep[u]))
                    for u in comp
                ):
                    continue
                fair_ok = False
                break
            if not fair_ok:
                continue
            return comp[0], _cycle_actions(succs, comp_set, comp[0])
    return None


def _cycle_actions(succs: dict, comp_set: set, entry) -> tuple:
    """Walk intra-component successors from ``entry`` until a state
    repeats; return the actions of the closed portion of the walk."""
    seen_at: dict = {entry: 0}
    acts: list = []
    cur = entry
    while True:
        act, nxt = next(
            (a, w) for a, w in succs[cur] if w in comp_set
        )
        acts.append(act)
        if nxt in seen_at:
            return tuple(acts[seen_at[nxt]:])
        seen_at[nxt] = len(acts)
        cur = nxt


# -- trace-acceptor base -----------------------------------------------------


class TraceAcceptor:
    """Runtime-conformance counterpart of a spec: a monitor that replays
    one SCOPE of a recorded timeline (one node's lifecycle, one link's
    window, ...) through the spec's legal orderings. ``step`` consumes
    one event dict (obs/events.Event.as_dict shape) and records any
    violation; ``finish`` closes end-of-run obligations ("no node left
    paused" is only checkable at the end).

    Acceptors must be PERMISSIVE about events they don't model (a
    timeline is a lossy projection of the run — the ring can drop
    records under overflow) and STRICT about orderings the spec forbids:
    an accepted violating trace is worse than a rejected honest one.
    """

    scope: str = ""

    def __init__(self, scope: str = ""):
        self.scope = scope
        self.violations: list[str] = []

    def _flag(self, msg: str) -> None:
        self.violations.append(f"[{self.scope}] {msg}")

    def step(self, event: dict) -> None:
        raise NotImplementedError

    def finish(self) -> list[str]:
        return self.violations


def iter_events(timeline: Iterable[Any]) -> Iterable[dict]:
    """Normalize a timeline (Event objects or dicts) to dicts with the
    Event.as_dict keys present (detail/extra defaulted)."""
    for e in timeline:
        if isinstance(e, dict):
            d = dict(e)
        else:  # obs/events.Event
            d = e.as_dict()
        d.setdefault("detail", "")
        d.setdefault("extra", 0)
        d.setdefault("node", 0)
        d.setdefault("link", 0)
        d.setdefault("arg", 0)
        yield d
