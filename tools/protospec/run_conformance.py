#!/usr/bin/env python
"""Trace-conformance CLI: replay one or more recorded timelines (flight
recorder JSON — a bare event list, a ``{"timeline": [...]}`` fixture,
or an obs postmortem file) through the protocol specs' trace acceptors.

Exit 0 iff every timeline is accepted. A rejection names the scope and
the forbidden ordering — either the implementation drifted from the
spec or the spec no longer describes shipped behavior.

Usage: python tools/protospec/run_conformance.py TIMELINE.json [...]
"""

from __future__ import annotations

import pathlib
import sys

if __package__ in (None, ""):
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    from protospec.conformance import check_timeline, load_timeline
else:
    from .conformance import check_timeline, load_timeline


def main() -> int:
    paths = sys.argv[1:]
    if not paths:
        print(__doc__)
        return 2
    ok = True
    for path in paths:
        report = check_timeline(load_timeline(path))
        print(
            f"{path}: {report['events']} events, "
            f"{report['routed_events']} routed, {report['scopes']} scopes "
            f"— {'PASS' if report['pass'] else 'FAIL'}"
        )
        for v in report["violations"]:
            print(f"  {v}")
        ok = ok and report["pass"]
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
