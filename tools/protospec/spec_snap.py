"""SNAP/SNAP_ACK/RESUME consistent-cut barrier spec (comm/peer.py r12).

A 3-node chain (root 0 -> 1 -> 2): the root pauses its own production
and floods a SNAP marker down; every node on SNAP pauses, forwards the
marker only after its own pre-cut pipeline is EMPTY, waits for its
child's SNAP_ACK plus local quiesce, captures, and acks up; the root
RESUMEs top-down. Channels are FIFO (TCP) — that FIFO ordering is what
makes the marker a consistent-cut marker, and the spec's job is to
check the sender-side discipline that keeps the marker LAST among
pre-cut data.

Production is modeled as the engine's two-phase sender: ``begin_pass``
debits a link residual into an in-flight pass (the codec/encode pass
holding mass in its frame buffer), ``complete_pass`` enqueues it on the
wire. The TRUE spec's pause is synchronous across the pass boundary
(peer.py ``_set_paused``: the C ``sender_pass`` counter handshake /
python ``_send_pass`` twin), so a SNAP marker can only be flooded once
no pass is in flight.

Mutation ``async_pause`` (the historical r12 bug, found by hand in
review round 12): the marker flood skips the pass-boundary wait — a
pass already in flight when the pause flag lands completes AFTER the
marker, its mass debited from the captured residual but applied past
the receiver's capture: in neither shard, lost on restore. The ghost
counter ``lost`` detects exactly that delivery.

Failure never wedges: the root times out and RESUMEs anyway; a node
whose RESUME is lost (root crash is an enabled adversary action)
auto-resumes after its pause deadline. Invariant ``paused-implies-
barrier`` plus quiescence reachability are the never-leave-paused rule.
"""

from __future__ import annotations

from typing import NamedTuple

from .core import Spec, TraceAcceptor

CHAN_CAP = 4
PRODUCE_CAP = 1  # units per producer (nodes 0 and 1)


class SnapState(NamedTuple):
    # per node 0..2
    paused: tuple  # bool x3
    bar: tuple  # 0 idle / 1 in barrier / 2 captured x3
    res: tuple  # down-link residual mass (node 2 has no down link)
    pas: tuple  # mass held by an in-flight sender pass
    marked: tuple  # SNAP flooded to the child (nodes 0,1)
    waiting: tuple  # child SNAP_ACK outstanding (nodes 0,1)
    applied: tuple  # mass applied locally (nodes 1,2 receive)
    prod: tuple  # units produced so far (nodes 0,1)
    chan_down: tuple  # FIFO per link: 0->1, 1->2
    chan_up: tuple  # FIFO per link: 1->0, 2->1 (SNAP_ACKs)
    started: bool
    alive0: bool  # root alive (crash is an adversary action)
    lost: int  # ghost: pre-cut debited mass applied past a capture


def _t(t, i, v):
    return t[:i] + (v,) + t[i + 1 :]


class SnapSpec(Spec):
    name = "snap"
    depth_bound = 36
    mutations = {
        "async_pause": (
            "r12: the SNAP marker flood skips the synchronous pass-"
            "boundary handshake — an in-flight pre-pause sender pass "
            "enqueues its debited mass BEHIND the marker and the "
            "receiver applies it after its capture (mass in neither "
            "shard)"
        ),
    }

    def initial(self):
        return SnapState(
            paused=(False,) * 3,
            bar=(0,) * 3,
            res=(0, 0, 0),
            pas=(0, 0, 0),
            marked=(False, False, False),
            waiting=(False, False, False),
            applied=(0, 0, 0),
            prod=(0, 0, 0),
            chan_down=((), ()),
            chan_up=((), ()),
            started=False,
            alive0=True,
            lost=0,
        )

    # -- enabled -------------------------------------------------------------

    def enabled(self, s: SnapState):
        acts = []
        for i in (0, 1):
            up = s.alive0 if i == 0 else True
            if up and not s.paused[i] and s.prod[i] < PRODUCE_CAP:
                acts.append(("produce", i))
            if up and s.res[i] > 0 and s.pas[i] == 0 and not s.paused[i]:
                acts.append(("begin_pass", i))
            if up and s.pas[i] > 0 and len(s.chan_down[i]) < CHAN_CAP:
                acts.append(("complete_pass", i))
            # marker flood: paused, in barrier, child not yet marked, own
            # pre-cut pipeline delivered (no data in the down channel =
            # the unacked ledger drained). The TRUE spec additionally
            # demands the pass boundary (pas == 0); the async_pause
            # mutation is exactly that missing wait.
            if (
                up
                and s.bar[i] == 1
                and s.paused[i]
                and not s.marked[i]
                and not any(m[0] == "d" for m in s.chan_down[i])
                and (self.mutation == "async_pause" or s.pas[i] == 0)
                and len(s.chan_down[i]) < CHAN_CAP
            ):
                acts.append(("mark", i))
        if s.alive0 and not s.started and s.bar == (0, 0, 0):
            acts.append(("snap_start",))
        # capture: in barrier, subtree acked, locally quiesced (no pass
        # in flight, down channel drained — peer.py _lc_quiesced)
        for i in (0, 1, 2):
            up = s.alive0 if i == 0 else True
            has_child = i < 2
            if (
                up
                and s.bar[i] == 1
                and (not has_child or (s.marked[i] and not s.waiting[i]))
                and s.pas[i] == 0
                and (not has_child or not s.chan_down[i])
                and (i == 0 or len(s.chan_up[i - 1]) < CHAN_CAP)
                and (i != 0 or len(s.chan_down[0]) < CHAN_CAP)
            ):
                acts.append(("capture", i))
        if s.alive0 and s.bar[0] == 1 and len(s.chan_down[0]) < CHAN_CAP:
            acts.append(("root_timeout",))
        for i in (1, 2):
            if s.paused[i]:
                acts.append(("pause_timeout", i))
        if s.alive0 and s.bar[0] != 0:
            acts.append(("crash_root",))
        for li in (0, 1):
            # a dead root's sockets died with it — crash_root already
            # cleared its channels, so plain non-emptiness is the guard
            if s.chan_down[li]:
                acts.append(("deliver_down", li))
        for li in (0, 1):
            if s.chan_up[li]:
                acts.append(("deliver_up", li))
        return acts

    # -- apply ---------------------------------------------------------------

    def apply(self, s: SnapState, a):
        kind = a[0]
        if kind == "produce":
            i = a[1]
            return s._replace(
                res=_t(s.res, i, s.res[i] + 1), prod=_t(s.prod, i, s.prod[i] + 1)
            )
        if kind == "begin_pass":
            i = a[1]
            return s._replace(
                res=_t(s.res, i, 0), pas=_t(s.pas, i, s.res[i])
            )
        if kind == "complete_pass":
            i = a[1]
            behind = s.paused[i] and s.marked[i]
            msg = ("d", s.pas[i], behind)
            return s._replace(
                pas=_t(s.pas, i, 0),
                chan_down=_t(s.chan_down, i, s.chan_down[i] + (msg,)),
            )
        if kind == "snap_start":
            return s._replace(
                started=True, bar=_t(s.bar, 0, 1), paused=_t(s.paused, 0, True)
            )
        if kind == "mark":
            i = a[1]
            return s._replace(
                marked=_t(s.marked, i, True),
                waiting=_t(s.waiting, i, True),
                chan_down=_t(s.chan_down, i, s.chan_down[i] + (("snap",),)),
            )
        if kind == "capture":
            i = a[1]
            if i == 0:
                # root capture completes the barrier: RESUME floods down
                return s._replace(
                    bar=_t(s.bar, 0, 0),
                    paused=_t(s.paused, 0, False),
                    chan_down=_t(
                        s.chan_down, 0, s.chan_down[0] + (("resume",),)
                    ),
                )
            return s._replace(
                bar=_t(s.bar, i, 2),
                chan_up=_t(s.chan_up, i - 1, s.chan_up[i - 1] + (("ack",),)),
            )
        if kind == "root_timeout":
            return s._replace(
                bar=_t(s.bar, 0, 0),
                paused=_t(s.paused, 0, False),
                waiting=_t(s.waiting, 0, False),
                chan_down=_t(s.chan_down, 0, s.chan_down[0] + (("resume",),)),
            )
        if kind == "pause_timeout":
            i = a[1]
            return s._replace(
                paused=_t(s.paused, i, False), bar=_t(s.bar, i, 0)
            )
        if kind == "crash_root":
            # the root dies: its sockets — and every message on them —
            # die with it (TCP, not a lossy channel), and its local
            # barrier state dies too (a dead node is not "paused")
            return s._replace(
                alive0=False,
                paused=_t(s.paused, 0, False),
                bar=_t(s.bar, 0, 0),
                pas=_t(s.pas, 0, 0),
                chan_down=((), s.chan_down[1]),
                chan_up=((), s.chan_up[1]),
            )
        if kind == "deliver_down":
            li = a[1]
            j = li + 1  # receiver node
            msg = s.chan_down[li][0]
            chan = _t(s.chan_down, li, s.chan_down[li][1:])
            if msg[0] == "d":
                lost = s.lost
                if s.bar[j] == 2 and s.paused[j] and msg[2]:
                    lost += msg[1]  # the cut already captured j: this
                    # pre-cut debit lands in neither shard
                return s._replace(
                    chan_down=chan,
                    applied=_t(s.applied, j, s.applied[j] + msg[1]),
                    lost=lost,
                )
            if msg[0] == "snap":
                if s.bar[j] != 0:
                    return s._replace(chan_down=chan)  # duplicate marker
                return s._replace(
                    chan_down=chan,
                    bar=_t(s.bar, j, 1),
                    paused=_t(s.paused, j, True),
                )
            # resume: release, forward down, clear barrier state
            out = s._replace(
                chan_down=chan,
                bar=_t(s.bar, j, 0),
                paused=_t(s.paused, j, False),
            )
            if j == 1 and out.marked[1] and len(out.chan_down[1]) < CHAN_CAP:
                out = out._replace(
                    chan_down=_t(
                        out.chan_down, 1, out.chan_down[1] + (("resume",),)
                    )
                )
            return out
        if kind == "deliver_up":
            li = a[1]
            parent = li  # chan_up[0]: 1->0, chan_up[1]: 2->1
            chan = _t(s.chan_up, li, s.chan_up[li][1:])
            if parent == 0 and not s.alive0:
                return s._replace(chan_up=chan)
            return s._replace(
                chan_up=chan, waiting=_t(s.waiting, parent, False)
            )
        raise AssertionError(a)

    # -- verdicts ------------------------------------------------------------

    def invariants(self, s: SnapState):
        bad = []
        if s.lost:
            bad.append(
                "snap-conservation: pre-cut debited mass was applied "
                "after the receiver's capture (in neither shard)"
            )
        for i in (0, 1, 2):
            if s.paused[i] and s.bar[i] == 0:
                bad.append(
                    f"paused-implies-barrier: node {i} paused with no "
                    f"active barrier"
                )
        return bad

    def quiescent(self, s: SnapState):
        return (
            s.started
            and s.bar == (0, 0, 0)
            and not any(s.paused)
            and s.chan_down == ((), ())
            and s.chan_up == ((), ())
            and s.pas == (0, 0, 0)
        )


# -- trace acceptor ----------------------------------------------------------


class LifecycleAcceptor(TraceAcceptor):
    """One node's lifecycle scope replayed against the barrier's legal
    orderings (comm/peer.py emits lifecycle_pause / lifecycle_resume on
    every _set_paused edge, snap_begin on barrier entry, snap_shard at
    capture, snap_done at the root's finish):

    - pause/resume strictly alternate (a double pause without a resume
      is a torn barrier; a bare resume is a state machine the spec
      cannot produce);
    - snap_shard (the capture) only while paused — a capture on an
      unpaused node is not a consistent cut;
    - end of run: the node must not be left paused (the r12
      never-leave-paused rule, checkable only at finish).
    """

    def __init__(self, scope: str = ""):
        super().__init__(scope)
        self._paused = False

    def step(self, event: dict) -> None:
        name = event["name"]
        if name == "lifecycle_pause":
            if self._paused:
                self._flag("double lifecycle_pause without a resume")
            self._paused = True
        elif name == "lifecycle_resume":
            if not self._paused:
                self._flag("lifecycle_resume while not paused")
            self._paused = False
        elif name == "snap_shard" and not self._paused:
            self._flag("snap_shard captured on an unpaused node")

    def finish(self) -> list[str]:
        if self._paused:
            self._flag("node left paused at end of run")
        return self.violations


SPECS = [SnapSpec]
