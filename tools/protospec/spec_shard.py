"""Cluster-shard spec: owner-routed forwarding + shard-handoff-on-drain
(shared_tensor_tpu/shard/node.py, the r16 tentpole).

A 3-node chain A -> B -> C over one shard s: A the writer (origin of
out-of-shard mass), B the relay (and handoff successor — C's parent),
C the shard's owner. Mass units carry identities so exactly-once and
conservation are set algebra:

- A produces units; each rides a wire.FWD message toward the owner.
  The A->B hop is the per-link go-back-N discipline collapsed to
  exactly-once delivery (in-order accept + cumulative ACK filters
  per-link duplicates — spec_gbn already model-checks that layer);
  what this spec keeps adversarial is the LAST hop's at-least-once
  window: a unit in B's ledger may be re-delivered (re-route /
  retransmission racing the ACK), and the TRUE owner discards the
  duplicate via its end-to-end (origin, fwd_seq) dedup set;
- handoff-on-drain: C snapshots its slice INTO the ho message (state
  chunks + the dedup window ride along, per-link FIFO), B adopts at
  ho_done and mints the next epoch, C releases. The handoff window is
  where both bugs live:

  * ``no_dedup_transfer`` seeds the double-apply: the successor adopts
    WITHOUT the dedup window, so a re-routed duplicate of a unit the
    old owner applied-but-never-acked re-applies at the successor
    (exactly the mutation node.py's ho_dedup transfer exists to kill);
  * ``apply_during_handoff`` seeds the conservation bug: the old owner
    keeps applying frames AFTER its slice snapshot shipped — the
    applied mass is not in the transferred bytes and dies with the
    released slice, while the sender's ledger was already ACK-debited
    (node.py's _ho_sent routing-onward discipline exists to kill it).

Invariants: ``exactly-once`` (no unit applied twice at any owner
authority), ``conservation`` (every produced unit is applied at the
CURRENT owner or retained in a channel / ledger / parked buffer /
in-flight handoff — never silently destroyed), ``exactly-one-owner``
(the epoch mint: never two simultaneous authorities for s). Quiescence:
every produced unit applied exactly once at the current owner, all
channels and ledgers empty, no handoff in flight.
"""

from __future__ import annotations

from typing import NamedTuple

from .core import Spec

P = 2  # units A produces (ids 1..P)


class ShardState(NamedTuple):
    prod: int  # units produced so far at A
    chan_ab: frozenset  # A->B in flight (per-link layer: exactly-once)
    led_bc: frozenset  # B->C ledgered, unacked (the at-least-once hop)
    chan_bc: frozenset  # B->C in flight
    applied_c: frozenset  # C's slice content (while C is the authority)
    dedup_c: frozenset  # C's end-to-end seen set
    parked_c: frozenset  # frames C holds mid-handoff (route pending)
    chan_cb: frozenset  # C->B relays (post-snapshot forwarding)
    applied_b: frozenset  # B's slice content (post-adopt)
    dedup_b: frozenset  # B's end-to-end seen set (post-adopt)
    owner: int  # 0 = C is the authority, 1 = B (post-adopt)
    ho: int  # 0 none / 1 ho message in flight / 2 complete
    ho_mass: frozenset  # slice snapshot riding the ho message
    ho_dedup: frozenset  # dedup window riding the ho message
    double: int  # ghost: double-applies observed
    lost: frozenset  # ghost: units destroyed


class ShardSpec(Spec):
    name = "shard"
    depth_bound = 26
    mutations = {
        "no_dedup_transfer": (
            "r16: handoff ships the slice WITHOUT the end-to-end dedup "
            "window — a re-routed duplicate of a unit the old owner "
            "applied-but-never-acked double-applies at the successor"
        ),
        "apply_during_handoff": (
            "r16: the old owner keeps applying FWDs after its slice "
            "snapshot shipped — the mass is absent from the transferred "
            "bytes and dies with the released slice while the sender's "
            "ledger was ACK-debited (silent cluster-mass loss)"
        ),
    }

    def initial(self):
        e = frozenset()
        return ShardState(0, e, e, e, e, e, e, e, e, e, 0, 0, e, e, 0, e)

    def enabled(self, s: ShardState):
        acts = []
        if s.prod < P:
            acts.append(("produce",))
        for u in sorted(s.chan_ab):
            acts.append(("deliver_ab", u))
        for u in sorted(s.chan_bc):
            acts.append(("deliver_bc", u))
        for u in sorted(s.led_bc - s.chan_bc):
            # retransmission / re-route: a ledgered unit already
            # delivered once goes back in flight byte-identical — the
            # at-least-once window the owner's dedup must close
            acts.append(("redeliver_bc", u))
        for u in sorted(s.led_bc):
            if u in s.dedup_c or u in s.parked_c or u in s.dedup_b:
                acts.append(("ack_bc", u))
        for u in sorted(s.chan_cb):
            acts.append(("deliver_cb", u))
        if s.owner == 0 and s.ho == 0:
            acts.append(("ho_start",))
        if s.ho == 1:
            acts.append(("ho_complete",))
        return acts

    def apply(self, s: ShardState, a):
        kind = a[0]
        if kind == "produce":
            u = s.prod + 1
            return s._replace(prod=u, chan_ab=s.chan_ab | {u})
        if kind == "deliver_ab":
            u = a[1]
            # B relays toward the owner (or applies, once B IS the
            # owner): the relay ledgers the unit for the lossy hop
            s = s._replace(chan_ab=s.chan_ab - {u})
            if s.owner == 1:
                return self._apply_at_b(s, u)
            return s._replace(
                led_bc=s.led_bc | {u}, chan_bc=s.chan_bc | {u}
            )
        if kind in ("deliver_bc", "redeliver_bc"):
            u = a[1]
            s = s._replace(chan_bc=s.chan_bc - {u})
            if s.owner == 1:
                # C released: the frame relays back toward the new
                # owner under its unchanged identity
                return s._replace(chan_cb=s.chan_cb | {u})
            if s.ho == 1 and self.mutation != "apply_during_handoff":
                # TRUE spec: the snapshot already shipped — hold the
                # frame for onward routing, never the dying slice
                return s._replace(parked_c=s.parked_c | {u})
            if u in s.dedup_c:
                return s  # end-to-end duplicate: discarded
            dbl = s.double + (1 if u in s.applied_c else 0)
            return s._replace(
                applied_c=s.applied_c | {u},
                dedup_c=s.dedup_c | {u},
                double=dbl,
            )
        if kind == "ack_bc":
            u = a[1]
            return s._replace(led_bc=s.led_bc - {u})
        if kind == "deliver_cb":
            u = a[1]
            s = s._replace(chan_cb=s.chan_cb - {u})
            return self._apply_at_b(s, u)
        if kind == "ho_start":
            dedup = (
                frozenset()
                if self.mutation == "no_dedup_transfer"
                else s.dedup_c
            )
            return s._replace(ho=1, ho_mass=s.applied_c, ho_dedup=dedup)
        if kind == "ho_complete":
            # B adopts the shipped snapshot + dedup window and mints the
            # next epoch; C releases. Anything C applied AFTER the
            # snapshot left is not in ho_mass — it dies with the slice
            # (reachable only under apply_during_handoff); parked frames
            # route onward now that the successor announced
            lost = s.applied_c - s.ho_mass
            return s._replace(
                ho=2,
                owner=1,
                applied_b=s.ho_mass,
                dedup_b=s.ho_dedup,
                applied_c=frozenset(),
                dedup_c=frozenset(),
                chan_cb=s.chan_cb | s.parked_c,
                parked_c=frozenset(),
                lost=s.lost | lost,
            )
        raise AssertionError(a)

    def _apply_at_b(self, s: ShardState, u):
        if u in s.dedup_b:
            return s
        dbl = s.double + (1 if u in s.applied_b else 0)
        return s._replace(
            applied_b=s.applied_b | {u},
            dedup_b=s.dedup_b | {u},
            double=dbl,
        )

    def invariants(self, s: ShardState):
        bad = []
        if s.double:
            bad.append(
                "exactly-once: a unit was applied twice at an owner "
                "authority (end-to-end dedup window breached)"
            )
        if s.lost:
            bad.append(
                "conservation: debited mass destroyed across the "
                f"handoff (units {sorted(s.lost)} applied at the old "
                f"owner after its snapshot shipped)"
            )
        # every produced unit must be SOMEWHERE: applied at the current
        # authority, or retained in a channel/ledger/parked buffer/the
        # in-flight handoff snapshot
        applied = s.applied_b if s.owner == 1 else s.applied_c
        held = (
            applied
            | s.chan_ab
            | s.led_bc
            | s.chan_bc
            | s.chan_cb
            | s.parked_c
            | (s.ho_mass if s.ho == 1 else frozenset())
            | (s.applied_c if s.owner == 1 else frozenset())
            | s.lost  # already reported above; keep the report single
        )
        missing = frozenset(range(1, s.prod + 1)) - held
        if missing:
            bad.append(
                f"conservation: units {sorted(missing)} vanished with "
                f"no channel, ledger, slice, or handoff holding them"
            )
        # exactly-one-owner: the authority moves ATOMICALLY at adopt
        # (ho_complete) — a state where C still applies while B holds
        # the minted slice would show up as double-apply or loss above;
        # structurally the single `owner` field cannot split, so what
        # is checked is that post-adopt C's slice is empty
        if s.owner == 1 and s.applied_c:
            bad.append(
                "exactly-one-owner: the released owner still holds "
                "slice content after the successor adopted"
            )
        return bad

    def quiescent(self, s: ShardState):
        applied = s.applied_b if s.owner == 1 else s.applied_c
        return (
            s.prod == P
            and applied == frozenset(range(1, P + 1))
            and not s.chan_ab
            and not s.led_bc
            and not s.chan_bc
            and not s.chan_cb
            and not s.parked_c
            and s.ho != 1
        )


class EngineShardState(NamedTuple):
    """ShardState + the r17 engine-lane fields (appended so the base
    spec's field accesses and _replace calls keep working)."""

    prod: int
    chan_ab: frozenset
    led_bc: frozenset
    chan_bc: frozenset
    applied_c: frozenset
    dedup_c: frozenset
    parked_c: frozenset
    chan_cb: frozenset
    applied_b: frozenset
    dedup_b: frozenset
    owner: int
    ho: int
    ho_mass: frozenset
    ho_dedup: frozenset
    double: int
    lost: frozenset
    route_b: int  # 1 = B knows the next hop toward the owner
    park_b: frozenset  # frames parked at the RELAY awaiting a route


class EngineShardSpec(ShardSpec):
    """r17 engine-lane extension of the shard spec: the native plane's
    new interleavings layered on the same identity algebra.

    - RELAY-SIDE PARKING: the engine plane parks a routeless frame at
      ANY node (node.py's python loop parks too, but its route map and
      message handling are one thread — the plane's receiver races the
      control plane's set_route, so park/heal is a genuine interleaving
      here). ``route_lose``/``route_heal`` model the control plane
      purging and re-announcing the route; a healed park re-LEDGERS the
      frame (shard_dispatch_bytes re-packs into a fresh tx slot under a
      fresh per-link seq — the end-to-end identity unchanged).
    - VERBATIM-RELAY RESTAMP: the relay may only re-stamp the per-link
      seq. The ``relay_restamp_identity`` mutation models the buggy
      relay that re-mints (origin, fwd_seq) while re-routing — the
      duplicate then bypasses the owner's dedup window and
      double-applies, exactly what the verbatim discipline (and the
      byte-range the restamp is allowed to touch) exists to prevent.
    """

    name = "shard_engine"
    depth_bound = 30
    mutations = dict(
        ShardSpec.mutations,
        relay_restamp_identity=(
            "r17: the relay re-stamps MORE than the per-link seq — a "
            "re-routed duplicate arrives under a fresh (origin, "
            "fwd_seq) identity, bypasses the owner's end-to-end dedup "
            "window and double-applies"
        ),
    )

    def initial(self):
        e = frozenset()
        return EngineShardState(
            0, e, e, e, e, e, e, e, e, e, 0, 0, e, e, 0, e, 1, e
        )

    def enabled(self, s):
        acts = list(super().enabled(s))
        if s.route_b:
            acts.append(("route_lose",))
        else:
            acts.append(("route_heal",))
        return acts

    def apply(self, s, a):
        kind = a[0]
        if kind == "route_lose":
            return s._replace(route_b=0)
        if kind == "route_heal":
            # parked frames re-ledger toward the owner under their
            # unchanged identity (shard_dispatch_bytes)
            return s._replace(
                route_b=1,
                led_bc=s.led_bc | s.park_b,
                chan_bc=s.chan_bc | s.park_b,
                park_b=frozenset(),
            )
        if kind == "deliver_ab" and s.owner == 0 and not s.route_b:
            # engine lane: the relay has no route — the frame parks at B
            # (bounded, loud) until the control plane heals the route
            u = a[1]
            return s._replace(
                chan_ab=s.chan_ab - {u}, park_b=s.park_b | {u}
            )
        if (
            kind == "redeliver_bc"
            and self.mutation == "relay_restamp_identity"
            and s.owner == 0
            and s.ho != 1
        ):
            # the buggy relay re-minted the end-to-end identity: the
            # owner's dedup window cannot recognize the duplicate
            u = a[1]
            s = s._replace(chan_bc=s.chan_bc - {u})
            dbl = s.double + (1 if u in s.applied_c else 0)
            return s._replace(
                applied_c=s.applied_c | {u},
                dedup_c=s.dedup_c | {u},
                double=dbl,
            )
        return super().apply(s, a)

    def invariants(self, s):
        bad = super().invariants(s)
        # park_b retention: base conservation's `held` does not know the
        # relay park — re-check the full union here
        applied = s.applied_b if s.owner == 1 else s.applied_c
        held = (
            applied
            | s.chan_ab
            | s.led_bc
            | s.chan_bc
            | s.chan_cb
            | s.parked_c
            | s.park_b
            | (s.ho_mass if s.ho == 1 else frozenset())
            | (s.applied_c if s.owner == 1 else frozenset())
            | s.lost
        )
        missing = frozenset(range(1, s.prod + 1)) - held
        base_cons = [b for b in bad if "vanished" in b]
        if base_cons and not missing:
            # the unit is in the relay park — retained, not vanished
            bad = [b for b in bad if "vanished" not in b]
        return bad

    def quiescent(self, s):
        return super().quiescent(s) and not s.park_b


SPECS = [ShardSpec, EngineShardSpec]
