"""r14 same-host lane specs: the SWITCH-marker ordering on unstriped
links, and the striped lane's writer promotion / ring backpressure /
stripe-death requeue discipline (native/sttransport.cpp, "Ordering
across the lane switch" + the r11 stripe-death notes).

**LaneSwitchSpec** — an unstriped link moving its data plane from TCP
to the shared-memory ring. The sender writes an in-stream SWITCH
marker as its LAST data-plane byte on TCP, then emits on the ring; the
receiver enables ring delivery only once the marker arrives in-stream
(``rx_go``), so the TCP-before / ring-after order is exact. Invariant
``switch-order``: the delivered sequence is exactly the send order —
no data crosses the SWITCH marker out of order. Mutation
``early_ring_delivery`` red-teams the invariant: a receiver that polls
the ring before the marker delivers post-switch data ahead of the TCP
tail.

**LaneStripeSpec** — the striped lane: one SPSC ring, its single
writer the lowest-index LIVE stripe's sender (promoting across stripe
deaths), bounded link sendq, bounded ring (full ring = backpressure,
never drop). A write failure kills the failing stripe FIRST; only then
is the in-hand message re-routed (survivors) or dropped into the
teardown carry (no survivors — the link's death, go-back-N's business).

Mutation ``requeue_before_kill`` (the historical r11 bug, found by hand
in review round 11): the failing writer requeues BEFORE killing its
stripe — with the sendq full and no surviving sender to drain it, the
requeue spins forever while the stripe still counts as alive: the
last-stripe livelock, which the explorer reports as a wedged state
(pending work, no enabled action).

Conservation here is identity-based: every produced message is
delivered exactly once or carried into teardown — never both, never
neither.
"""

from __future__ import annotations

from typing import NamedTuple

from .core import Spec, TraceAcceptor

M = 3  # messages produced
Q = 2  # link sendq capacity
R = 2  # ring capacity (records)
FAILS = 2  # adversary write-failure budget
S = 2  # stripes


# -- unstriped SWITCH ordering ----------------------------------------------


class SwitchState(NamedTuple):
    sent: int  # 1..sent emitted
    phase: int  # 0 = tcp, 1 = ring (sender side)
    tcp: tuple  # FIFO: ("d", seq) | ("switch",)
    ring: tuple  # FIFO: seqs
    rx_go: bool
    delivered: tuple  # seqs in delivery order


class LaneSwitchSpec(Spec):
    name = "lane_switch"
    depth_bound = 20
    mutations = {
        "early_ring_delivery": (
            "receiver polls the ring before the in-stream SWITCH marker "
            "arrives — post-switch data overtakes the TCP tail"
        ),
    }

    def initial(self):
        return SwitchState(0, 0, (), (), False, ())

    def enabled(self, s: SwitchState):
        acts = []
        if s.sent < M:
            if s.phase == 0 and len(s.tcp) < 4:
                acts.append(("send_tcp",))
            if s.phase == 1 and len(s.ring) < R:
                acts.append(("send_ring",))
        if s.phase == 0 and len(s.tcp) < 4:
            acts.append(("switch",))
        if s.tcp:
            acts.append(("deliver_tcp",))
        if s.ring and (s.rx_go or self.mutation == "early_ring_delivery"):
            acts.append(("poll_ring",))
        return acts

    def apply(self, s: SwitchState, a):
        kind = a[0]
        if kind == "send_tcp":
            seq = s.sent + 1
            return s._replace(sent=seq, tcp=s.tcp + (("d", seq),))
        if kind == "switch":
            return s._replace(phase=1, tcp=s.tcp + (("switch",),))
        if kind == "send_ring":
            seq = s.sent + 1
            return s._replace(sent=seq, ring=s.ring + (seq,))
        if kind == "deliver_tcp":
            msg = s.tcp[0]
            if msg[0] == "switch":
                return s._replace(tcp=s.tcp[1:], rx_go=True)
            return s._replace(
                tcp=s.tcp[1:], delivered=s.delivered + (msg[1],)
            )
        if kind == "poll_ring":
            return s._replace(
                ring=s.ring[1:], delivered=s.delivered + (s.ring[0],)
            )
        raise AssertionError(a)

    def invariants(self, s: SwitchState):
        if s.delivered != tuple(range(1, len(s.delivered) + 1)):
            return [
                "switch-order: data crossed the SWITCH marker out of order"
            ]
        return []

    def quiescent(self, s: SwitchState):
        return (
            s.sent == M
            and s.phase == 1
            and not s.tcp
            and not s.ring
            and len(s.delivered) == M
        )


# -- striped lane: promotion, backpressure, the requeue discipline -----------


class StripeState(NamedTuple):
    produced: int
    sendq: tuple  # seqs queued on the link
    hand: int  # seq popped by the current lane writer (0 = none)
    ring: tuple  # seqs published, FIFO
    stripes: tuple  # alive flags
    fails: int  # adversary budget spent
    delivered: tuple
    carried: frozenset  # rolled into teardown at link death
    alive: bool  # link alive
    stuck: bool  # mutation only: writer spinning in requeue


class LaneStripeSpec(Spec):
    name = "lane_stripe"
    depth_bound = 26
    mutations = {
        "requeue_before_kill": (
            "r11: a failed lane write requeues BEFORE killing its "
            "stripe — on a full sendq with no surviving sender the "
            "requeue spins forever (the last-stripe livelock)"
        ),
    }

    def initial(self):
        return StripeState(
            0, (), 0, (), (True,) * S, 0, (), frozenset(), True, False
        )

    def enabled(self, s: StripeState):
        if s.stuck:
            # the writer thread is spinning in requeue; only the reader
            # still runs — and draining the ring cannot free the sendq
            return [("drain",)] if s.ring else []
        acts = []
        live = any(s.stripes)
        if s.alive and s.produced < M and len(s.sendq) < Q:
            acts.append(("enqueue",))
        if s.alive and live and s.hand == 0 and s.sendq:
            acts.append(("pop",))
        if s.alive and live and s.hand != 0:
            if len(s.ring) < R:
                acts.append(("write_ok",))
            if s.fails < FAILS:
                acts.append(("write_fail",))
        if s.ring:
            acts.append(("drain",))
        return acts

    def apply(self, s: StripeState, a):
        kind = a[0]
        if kind == "enqueue":
            seq = s.produced + 1
            return s._replace(produced=seq, sendq=s.sendq + (seq,))
        if kind == "pop":
            return s._replace(hand=s.sendq[0], sendq=s.sendq[1:])
        if kind == "write_ok":
            return s._replace(hand=0, ring=s.ring + (s.hand,))
        if kind == "drain":
            return s._replace(
                ring=s.ring[1:], delivered=s.delivered + (s.ring[0],)
            )
        if kind == "write_fail":
            writer = s.stripes.index(True)
            if self.mutation == "requeue_before_kill":
                if len(s.sendq) >= Q:
                    # the historical wedge: requeue blocks on the full
                    # sendq while the stripe still counts as alive
                    return s._replace(fails=s.fails + 1, stuck=True)
                s = s._replace(
                    sendq=(s.hand,) + s.sendq, hand=0, fails=s.fails + 1
                )
                stripes = s.stripes[:writer] + (False,) + s.stripes[writer + 1 :]
                if any(stripes):
                    return s._replace(stripes=stripes)
                return s._replace(
                    stripes=stripes,
                    alive=False,
                    carried=s.carried | set(s.sendq),
                    sendq=(),
                )
            # TRUE spec: kill the stripe FIRST, then route what's in hand
            stripes = s.stripes[:writer] + (False,) + s.stripes[writer + 1 :]
            s = s._replace(stripes=stripes, fails=s.fails + 1)
            if any(stripes):
                return s  # writer role promotes; the in-hand message
                # re-routes through the new writer (hand retained)
            return s._replace(
                alive=False,
                carried=s.carried | set(s.sendq) | {s.hand},
                hand=0,
                sendq=(),
            )
        raise AssertionError(a)

    def invariants(self, s: StripeState):
        bad = []
        if len(set(s.delivered)) != len(s.delivered):
            bad.append("stripe-seq: a message was delivered twice")
        if s.delivered != tuple(sorted(s.delivered)):
            bad.append("stripe-seq: lane delivery out of stripe-seq order")
        if set(s.delivered) & s.carried:
            bad.append("conservation: a delivered message was also carried")
        outstanding = set(s.sendq) | set(s.ring) | ({s.hand} - {0})
        everywhere = set(s.delivered) | s.carried | outstanding
        if set(range(1, s.produced + 1)) - everywhere:
            bad.append("conservation: a produced message vanished")
        return bad

    def quiescent(self, s: StripeState):
        # a dead link ends production (the peer re-grafts — the carry's
        # business, modeled in spec_drain): quiescence then only needs
        # every produced message delivered or carried
        done = set(s.delivered) | s.carried == set(range(1, s.produced + 1))
        return (
            (s.produced == M or not s.alive)
            and not s.sendq
            and s.hand == 0
            and not s.ring
            and done
        )


# -- trace acceptor ----------------------------------------------------------


class LaneAcceptor(TraceAcceptor):
    """One (node, link) lane scope: the negotiation runs once per link,
    so shm_lane_up fires at most once and never alongside shm_fallback;
    stripe deaths are permanent and per-index (a repeated index means a
    dead stripe was re-attached — the r11 third-review-round class);
    nothing lane- or stripe-scoped fires after the link went down."""

    def __init__(self, scope: str = ""):
        super().__init__(scope)
        self._lane_up = 0
        self._fallback = 0
        self._dead_stripes: set[int] = set()
        self._down = False

    def step(self, event: dict) -> None:
        name = event["name"]
        if name == "link_down":
            self._down = True
            return
        if name in ("shm_lane_up", "shm_fallback", "stripe_down") and self._down:
            self._flag(f"{name} after link_down")
            return
        if name == "shm_lane_up":
            self._lane_up += 1
            if self._lane_up > 1:
                self._flag("shm_lane_up fired twice on one link")
            if self._fallback:
                self._flag("shm_lane_up after shm_fallback on one link")
        elif name == "shm_fallback":
            self._fallback += 1
            if self._lane_up:
                self._flag("shm_fallback after shm_lane_up on one link")
        elif name == "stripe_down":
            idx = event["arg"]
            if idx in self._dead_stripes:
                self._flag(f"stripe {idx} died twice (dead-index re-attach)")
            self._dead_stripes.add(idx)


SPECS = [LaneSwitchSpec, LaneStripeSpec]
