#!/usr/bin/env python
"""Model-checker gate: exhaustively explore every protocol spec to its
documented depth bound, then red-team the checker itself by asserting
every seeded historical-bug mutation (r10 fresh_no_seq, r11
requeue_before_kill, r12 async_pause, the r16/r17 handoff races, the
r19 reshard quartet) is FOUND within the same bound.

Exit 0 iff every TRUE spec explores clean (zero violations, quiescence
reachable, liveness verdicts green, not truncated) AND every mutation
is caught. Writes the state/transition counts as the round's MODEL
artifact (default MODEL_r19.json) — the committed artifact pins the
exact counts, so a spec edit that silently changes the explored space
shows up as a diff, not a mystery.

r19: specs run as parallel per-spec units (``--jobs N``, or the
``ST_SUITE_MODEL_JOBS`` env knob; default min(4, nproc)) and each unit
reports a suite-style wall-clock line::

    gate model/<spec>: <sec>s rc=<rc>

so suite_load.sh's budget accounting sees the enlarged model set
per-spec, not as one opaque blob. Output order stays deterministic
(sorted by spec) regardless of completion order.

Usage: python tools/protospec/run_check.py [--out MODEL_r19.json]
                                           [--jobs N]
"""

from __future__ import annotations

import json
import os
import pathlib
import sys
import time

if __package__ in (None, ""):
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    from protospec import all_specs, explore
else:
    from . import all_specs, explore


def default_jobs() -> int:
    env = os.environ.get("ST_SUITE_MODEL_JOBS", "").strip()
    if env:
        return max(1, int(env))
    return min(4, os.cpu_count() or 1)


def check_spec(name: str) -> dict:
    """One gate unit: the true spec + every mutation. Returns a
    picklable report (runs in a worker process under --jobs)."""
    cls = all_specs()[name]
    t0 = time.monotonic()
    res = explore(cls())
    unit = {
        "name": name,
        "spec": res.as_dict(),
        "mutations": {},
        "lines": [],
        "ok": res.ok and not res.truncated_by_depth,
    }
    status = "OK" if unit["ok"] else "FAIL"
    live = (
        " liveness=" + ",".join(
            f"{k}:{'ok' if v else ('?' if v is None else 'FAIL')}"
            for k, v in sorted(res.liveness.items())
        )
        if res.liveness
        else ""
    )
    unit["lines"].append(
        f"spec {name}: {res.states} states / {res.transitions} "
        f"transitions to depth {res.max_depth_reached} "
        f"(bound {res.depth_bound}) — "
        f"{len(res.violations)} violation(s), quiescent="
        f"{res.quiescent_reachable}{live} [{status}]"
    )
    for v in res.violations:
        unit["lines"].append(f"  {v.kind}: {v.detail}")
        if v.trace:
            unit["lines"].append(
                f"    trace: {' -> '.join(repr(a) for a in v.trace)}"
            )
    for mut in sorted(cls.mutations):
        mres = explore(cls(mutation=mut))
        found = bool(mres.violations)
        if not found:
            unit["ok"] = False
        first = mres.violations[0] if found else None
        unit["mutations"][f"{name}.{mut}"] = {
            "seeds": cls.mutations[mut],
            "found": found,
            "states": mres.states,
            "transitions": mres.transitions,
            "first_violation": first.as_dict() if first else None,
        }
        unit["lines"].append(
            f"  mutation {name}.{mut}: "
            + (
                f"FOUND at depth {first.depth} ({first.kind}: "
                f"{first.detail})"
                if found
                else "NOT FOUND — the checker cannot see this bug class"
            )
        )
    unit["duration_sec"] = round(time.monotonic() - t0, 3)
    return unit


def run(out_path: str | None, jobs: int | None = None) -> int:
    jobs = default_jobs() if jobs is None else max(1, jobs)
    names = sorted(all_specs())
    doc: dict = {"artifact": "protospec model check", "specs": {},
                 "mutations": {}, "gate": {}}
    t0 = time.monotonic()
    if jobs > 1 and len(names) > 1:
        import concurrent.futures

        with concurrent.futures.ProcessPoolExecutor(
            max_workers=min(jobs, len(names))
        ) as pool:
            units = {u["name"]: u for u in pool.map(check_spec, names)}
    else:
        units = {n: check_spec(n) for n in names}
    ok = True
    for name in names:
        u = units[name]
        ok = ok and u["ok"]
        doc["specs"][name] = u["spec"]
        doc["mutations"].update(u["mutations"])
        doc["gate"][name] = {
            "duration_sec": u["duration_sec"], "rc": 0 if u["ok"] else 1,
        }
        for line in u["lines"]:
            print(line)
        print(
            f"gate model/{name}: {u['duration_sec']}s "
            f"rc={0 if u['ok'] else 1}"
        )
    doc["duration_sec"] = round(time.monotonic() - t0, 3)
    doc["jobs"] = jobs
    doc["pass"] = ok
    if out_path:
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
        print(f"wrote {out_path}")
    print(f"model check: {'PASS' if ok else 'FAIL'} ({doc['duration_sec']}s)")
    return 0 if ok else 1


def main() -> int:
    out = None
    jobs = None
    args = sys.argv[1:]
    i = 0
    while i < len(args):
        if args[i] == "--out":
            out = args[i + 1]
            i += 2
        elif args[i] == "--jobs":
            jobs = int(args[i + 1])
            i += 2
        else:
            out = args[i]
            i += 1
    return run(out, jobs)


if __name__ == "__main__":
    sys.exit(main())
