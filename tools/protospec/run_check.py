#!/usr/bin/env python
"""Model-checker gate: exhaustively explore every protocol spec to its
documented depth bound, then red-team the checker itself by asserting
the three seeded historical-bug mutations (r10 fresh_no_seq, r11
requeue_before_kill, r12 async_pause — plus the extra lane-switch
ordering mutation) are each FOUND within the same bound.

Exit 0 iff every TRUE spec explores clean (zero violations, quiescence
reachable, not truncated by the state backstop) AND every mutation is
caught. Writes the state/transition counts as the round's MODEL
artifact (default MODEL_r17.json) — the committed artifact pins the
exact counts, so a spec edit that silently changes the explored space
shows up as a diff, not a mystery.

Usage: python tools/protospec/run_check.py [--out MODEL_r17.json]
"""

from __future__ import annotations

import json
import pathlib
import sys
import time

if __package__ in (None, ""):
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    from protospec import all_specs, explore
else:
    from . import all_specs, explore


def run(out_path: str | None) -> int:
    doc: dict = {"artifact": "protospec model check", "specs": {},
                 "mutations": {}}
    ok = True
    t0 = time.monotonic()
    for name, cls in sorted(all_specs().items()):
        res = explore(cls())
        doc["specs"][name] = res.as_dict()
        status = "OK" if res.ok and not res.truncated_by_depth else "FAIL"
        if status == "FAIL":
            ok = False
        print(
            f"spec {name}: {res.states} states / {res.transitions} "
            f"transitions to depth {res.max_depth_reached} "
            f"(bound {res.depth_bound}) — "
            f"{len(res.violations)} violation(s), quiescent="
            f"{res.quiescent_reachable} [{status}]"
        )
        for v in res.violations:
            print(f"  {v.kind}: {v.detail}")
            if v.trace:
                print(f"    trace: {' -> '.join(repr(a) for a in v.trace)}")
        for mut in sorted(cls.mutations):
            mres = explore(cls(mutation=mut))
            found = bool(mres.violations)
            if not found:
                ok = False
            first = mres.violations[0] if found else None
            doc["mutations"][f"{name}.{mut}"] = {
                "seeds": cls.mutations[mut],
                "found": found,
                "states": mres.states,
                "transitions": mres.transitions,
                "first_violation": first.as_dict() if first else None,
            }
            print(
                f"  mutation {name}.{mut}: "
                + (
                    f"FOUND at depth {first.depth} ({first.kind}: "
                    f"{first.detail})"
                    if found
                    else "NOT FOUND — the checker cannot see this bug class"
                )
            )
    doc["duration_sec"] = round(time.monotonic() - t0, 3)
    doc["pass"] = ok
    if out_path:
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
        print(f"wrote {out_path}")
    print(f"model check: {'PASS' if ok else 'FAIL'} ({doc['duration_sec']}s)")
    return 0 if ok else 1


def main() -> int:
    out = None
    args = sys.argv[1:]
    if args and args[0] == "--out":
        out = args[1]
    elif args:
        out = args[0]
    return run(out)


if __name__ == "__main__":
    sys.exit(main())
