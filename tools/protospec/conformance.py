"""Runtime trace conformance: replay a recorded flight-recorder
timeline (obs/recorder.FlightRecorder) through the protocol specs'
trace acceptors.

The explorer (core.explore) checks the MODEL exhaustively; this module
checks the LIVE SYSTEM still behaves like the model: every event
sequence a real run records, projected onto a protocol scope (one
node's lifecycle, one endpoint's link window, one link's lane), must be
an ordering the spec allows. A conformance failure means either the
implementation drifted from the spec or the spec no longer describes
the shipped protocol — both are findings; neither is ignorable.

Scopes and their acceptors (each defined next to its spec):

- per node:         spec_snap.LifecycleAcceptor (pause/resume/capture)
- per node:         spec_drain.DrainAcceptor   (drain_begin -> seal)
- per (node, link): spec_gbn.LinkAcceptor      (go-back-N teardown)
- per (node, link): spec_gbn.SubAcceptor       (attach-before-resync)
- per (node, link): spec_lane.LaneAcceptor     (lane/stripe lifecycle)
- per (node, link): spec_hello.HelloAcceptor   (one negotiation verdict)
- per node:         spec_reshard.ReshardAcceptor (staged split/merge
                    transfer ordering: begin/done pairing, no nesting,
                    no split/merge overlap)
- global:           spec_reshard.MasterAuthorityAcceptor (grant-epoch
                    monotonicity, sealed-while-in-flight, only the new
                    master mints after the authority lands)

The "global" scope kind (r19) keys ONE acceptor for the whole timeline:
master-authority discipline is a cross-node property — two nodes both
minting is exactly what no per-node projection can see.

Events the specs don't model pass through untouched — a timeline is a
lossy projection (the native ring drops under overflow and the
recorder window is bounded), so acceptors are permissive about absence
and strict about forbidden orderings. ``check_timeline`` is the
library entry; ``run_conformance.py`` is the CLI; cluster_chaos.py
gates its chaos arms on it.
"""

from __future__ import annotations

import json
from typing import Any, Iterable

from .core import iter_events
from .spec_drain import DrainAcceptor
from .spec_gbn import LinkAcceptor, SubAcceptor
from .spec_hello import HelloAcceptor
from .spec_lane import LaneAcceptor
from .spec_reshard import MasterAuthorityAcceptor, ReshardAcceptor
from .spec_snap import LifecycleAcceptor

#: event name -> (acceptor class, scope kind). "node" scopes key on the
#: node id; "link" scopes on (node, link); "global" keys one acceptor
#: for the whole timeline. One event may drive several acceptors
#: (link_down closes both the window and the lane).
_ROUTES: list = [
    (
        frozenset(
            {
                "lifecycle_pause",
                "lifecycle_resume",
                "snap_begin",
                "snap_shard",
                "snap_done",
            }
        ),
        LifecycleAcceptor,
        "node",
    ),
    (frozenset({"drain_begin", "seal"}), DrainAcceptor, "node"),
    (
        frozenset(
            {
                "retransmit",
                "dedup_discard",
                "send_window_stall",
                "blackhole_teardown",
                "link_down",
            }
        ),
        LinkAcceptor,
        "link",
    ),
    (frozenset({"sub_attach", "sub_resync"}), SubAcceptor, "link"),
    (
        frozenset(
            {"shm_lane_up", "shm_fallback", "stripe_down", "link_down"}
        ),
        LaneAcceptor,
        "link",
    ),
    (frozenset({"shm_lane_up", "shm_fallback"}), HelloAcceptor, "link"),
    (
        frozenset(
            {
                "reshard_split_begin",
                "reshard_split_done",
                "reshard_merge_begin",
                "reshard_merge_done",
            }
        ),
        ReshardAcceptor,
        "node",
    ),
    (
        frozenset(
            {"reshard_master_begin", "reshard_master_done", "reshard_grant"}
        ),
        MasterAuthorityAcceptor,
        "global",
    ),
]


def check_timeline(timeline: Iterable[Any]) -> dict:
    """Replay ``timeline`` (Event objects or their as_dict shapes)
    through every spec acceptor. Returns a report dict; the gate
    condition is ``report["violations"] == []``."""
    acceptors: dict[tuple, Any] = {}
    events = 0
    routed = 0
    for e in iter_events(timeline):
        events += 1
        name = e["name"]
        hit = False
        for names, cls, kind in _ROUTES:
            if name not in names:
                continue
            if kind == "node":
                key = (cls.__name__, e["node"])
                scope = f"{cls.__name__} node={e['node']}"
            elif kind == "link":
                key = (cls.__name__, e["node"], e["link"])
                scope = f"{cls.__name__} node={e['node']} link={e['link']}"
            else:  # global: one acceptor for the whole timeline
                key = (cls.__name__,)
                scope = cls.__name__
            acc = acceptors.get(key)
            if acc is None:
                acc = acceptors[key] = cls(scope)
            acc.step(e)
            hit = True
        routed += hit
    violations: list[str] = []
    for acc in acceptors.values():
        violations.extend(acc.finish())
    return {
        "events": events,
        "routed_events": routed,
        "scopes": len(acceptors),
        "violations": violations,
        "pass": not violations,
    }


def load_timeline(path: str) -> list[dict]:
    """Read a timeline file: either a bare JSON list of event dicts or
    an object with a ``timeline`` key (the postmortem / fixture
    shape)."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        doc = doc.get("timeline", [])
    if not isinstance(doc, list):
        raise ValueError(f"{path}: not a timeline (list or {{'timeline': …}})")
    return doc
