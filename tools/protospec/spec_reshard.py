"""Elastic-resharding specs: live split, merge, and master-authority
handoff (ROADMAP item 1, modeled BEFORE implementation — r20 lands
against these invariants and the conformance acceptors below).

Three specs over the r16 migration primitive (ho_meta/ho_state/ho_ack
staging + epoch-minted grants + dedup-window transfer):

- ``reshard_split``: owner C hands HALF its word-range to successor B
  while writer A keeps producing FWD mass into the moving half. The
  staging is two-phase like the real protocol: ``split_meta`` captures
  the end-to-end dedup window (and mints the grant epoch),
  ``split_ship`` captures the slice mass, ``split_complete`` adopts.
  From meta onward the TRUE owner parks arriving frames for onward
  routing. A split may ABORT (drain cancelled) — the minted grant
  stays in flight as replayable residue, which is exactly the r16
  stale-grant split-brain surface:

  * ``split_during_fwd`` seeds the double-apply: the owner keeps
    applying between meta and ship — the unit lands in the shipped
    mass but NOT in the shipped dedup window, so the at-least-once
    last hop re-applies it at the successor;
  * ``stale_grant_readopt`` seeds the split-brain: the successor
    adopts on a REPLAYED grant instead of consulting the directory's
    current entry — two simultaneous authorities.

- ``reshard_merge``: the symmetric fold — B's half folds back into C
  while FWD mass is in flight to BOTH halves. In-flight mass toward
  the folding half parks at B from merge_meta onward and must be
  relayed onward at merge_complete;

  * ``merge_drops_inflight_outbox`` clears the parked in-flight mass
    at the fold instead of relaying it — silent cluster-mass loss.

- ``master_handoff``: grant-minting + map-epoch authority moves to a
  successor under the same staged epoch discipline (so ``drain_node``
  works on the master). The TRUE spec SEALS the old minter at
  ma_meta; the successor adopts the mint cursor shipped at ma_ship.

  * ``two_minters_after_handoff`` skips the seal — both nodes mint,
    and the collided epoch (or the two live minter flags) trips
    no-stale-minter.

Invariants across the family: exactly-one-owner-at-every-epoch,
debited-mass conservation across the split/merge boundary,
exactly-once application, no-stale-minter, epoch monotonicity.

Liveness (r19 explorer): fairness-bounded "always eventually"
verdicts — ``eventually-converges`` (no fair adversary schedule avoids
quiescence forever), ``eventually-resumes`` (parked mass cannot stay
parked forever), ``eventually-exactly-one-minter``. Adversary actions
(redelivery, stale-grant replay) carry NO fairness constraint — the
verdicts must survive an adversary that schedules them forever.

Symmetry: units of the same target half are interchangeable —
``canon`` relabels them by membership signature so the explorer never
expands two states in the same orbit. POR: ``produce``/``request``
commute with every other action (fresh-identity, nothing disabled), so
the ample set defers all interleavings until production is done.
"""

from __future__ import annotations

from typing import NamedTuple

from .core import Spec, TraceAcceptor

P = 2  # units produced into the moving half (split spec)
M = 2  # grant mints the split spec allows (bounds the abort/re-split loop)
R = 2  # grant requests the master spec serves
REPLAYS = 2  # stale-replay budget (bounded adversary keeps the graph finite)


def _relabel(state, set_fields: tuple, n: int, cls_of=None):
    """Symmetry canon: units with the same target class and the same
    membership signature across every set field are interchangeable —
    relabel each class's units in signature order and rebuild the
    state. Equivalent-up-to-relabeling states collapse to one key."""
    sig = {}
    for u in range(1, n + 1):
        sig[u] = (
            (0 if cls_of is None else cls_of(u)),
            tuple(u in getattr(state, f) for f in set_fields),
        )
    order = sorted(range(1, n + 1), key=lambda u: (sig[u][0], sig[u][1], 0))
    # canonical id for each old id: same class keeps its class's id pool
    pools: dict = {}
    for u in range(1, n + 1):
        pools.setdefault(0 if cls_of is None else cls_of(u), []).append(u)
    newid = {}
    taken: dict = {k: 0 for k in pools}
    for u in order:
        c = 0 if cls_of is None else cls_of(u)
        newid[u] = pools[c][taken[c]]
        taken[c] += 1
    repl = {
        f: frozenset(newid[u] for u in getattr(state, f)) for f in set_fields
    }
    return state._replace(**repl)


# ---------------------------------------------------------------------------
# reshard_split
# ---------------------------------------------------------------------------


class SplitState(NamedTuple):
    prod: int
    chan_ab: frozenset  # A->B in flight (per-link layer: exactly-once)
    led_bc: frozenset  # B->C ledgered, unacked (the at-least-once hop)
    chan_bc: frozenset  # B->C in flight
    applied_c: frozenset  # C's moving-half slice (while C is authority)
    dedup_c: frozenset  # C's end-to-end seen set
    parked_c: frozenset  # frames C holds from split_meta onward
    chan_cb: frozenset  # C->B relays (post-release forwarding)
    applied_b: frozenset  # B's adopted half
    dedup_b: frozenset  # B's end-to-end seen set (post-adopt)
    auth_c: int  # C believes it owns the moving half
    auth_b: int  # B believes it owns the moving half
    phase: int  # 0 idle / 1 meta (dedup captured) / 2 shipped / 3 done
    sp_dedup: frozenset  # dedup window captured at split_meta
    sp_mass: frozenset  # slice mass captured at split_ship
    sp_epoch: int  # epoch the live split minted (0 = none)
    grants: frozenset  # minted grant epochs in flight (replayable residue)
    dir_epoch: int  # directory's current epoch for the half
    minted: int  # total grants minted (bounded by M)
    double: int  # ghost: double-applies observed
    lost: frozenset  # ghost: units destroyed


_SPLIT_SETS = (
    "chan_ab", "led_bc", "chan_bc", "applied_c", "dedup_c", "parked_c",
    "chan_cb", "applied_b", "dedup_b", "sp_dedup", "sp_mass", "lost",
)


class ReshardSplitSpec(Spec):
    name = "reshard_split"
    depth_bound = 26
    mutations = {
        "split_during_fwd": (
            "the owner keeps applying FWDs between split_meta and "
            "split_ship — the unit rides the shipped mass but not the "
            "meta-captured dedup window, and the at-least-once last hop "
            "re-applies it at the successor (double-apply)"
        ),
        "stale_grant_readopt": (
            "the successor adopts on a REPLAYED grant instead of the "
            "directory's current entry — a grant left over from an "
            "aborted split re-creates the authority: two simultaneous "
            "owners (the r16 split-brain class)"
        ),
    }

    def initial(self):
        e = frozenset()
        return SplitState(
            0, e, e, e, e, e, e, e, e, e, 1, 0, 0, e, e, 0, e, 0, 0, 0, e
        )

    def enabled(self, s: SplitState):
        acts = []
        if s.prod < P:
            acts.append(("produce",))
        for u in sorted(s.chan_ab):
            acts.append(("deliver_ab", u))
        for u in sorted(s.chan_bc):
            acts.append(("deliver_bc", u))
        for u in sorted(s.led_bc - s.chan_bc):
            acts.append(("redeliver_bc", u))
        for u in sorted(s.led_bc):
            if u in s.dedup_c or u in s.parked_c or u in s.dedup_b:
                acts.append(("ack_bc", u))
        for u in sorted(s.chan_cb):
            acts.append(("deliver_cb", u))
        if s.auth_c and s.phase == 0 and s.minted < M:
            acts.append(("split_meta",))
        if s.phase == 1:
            acts.append(("split_ship",))
        if s.phase == 2:
            acts.append(("split_complete",))
        if s.phase in (1, 2):
            acts.append(("split_abort",))
        for g in sorted(s.grants):
            acts.append(("grant_stale", g))
        return acts

    def apply(self, s: SplitState, a):
        kind = a[0]
        if kind == "produce":
            u = s.prod + 1
            return s._replace(prod=u, chan_ab=s.chan_ab | {u})
        if kind == "deliver_ab":
            u = a[1]
            s = s._replace(chan_ab=s.chan_ab - {u})
            if s.auth_b and not s.auth_c:
                return self._apply_at_b(s, u)
            return s._replace(led_bc=s.led_bc | {u}, chan_bc=s.chan_bc | {u})
        if kind in ("deliver_bc", "redeliver_bc"):
            u = a[1]
            s = s._replace(chan_bc=s.chan_bc - {u})
            if not s.auth_c:
                # C released: relay onward to the new owner, identity
                # unchanged (the verbatim discipline)
                return s._replace(chan_cb=s.chan_cb | {u})
            if s.phase in (1, 2) and not (
                self.mutation == "split_during_fwd" and s.phase == 1
            ):
                # TRUE spec: from meta onward, arriving frames park for
                # onward routing — the dying slice never grows
                return s._replace(parked_c=s.parked_c | {u})
            if u in s.dedup_c:
                return s  # end-to-end duplicate: discarded
            dbl = s.double + (1 if u in s.applied_c else 0)
            return s._replace(
                applied_c=s.applied_c | {u},
                dedup_c=s.dedup_c | {u},
                double=dbl,
            )
        if kind == "ack_bc":
            u = a[1]
            return s._replace(led_bc=s.led_bc - {u})
        if kind == "deliver_cb":
            u = a[1]
            s = s._replace(chan_cb=s.chan_cb - {u})
            return self._apply_at_b(s, u)
        if kind == "split_meta":
            e = s.minted + 1
            return s._replace(
                phase=1,
                sp_dedup=s.dedup_c,
                sp_epoch=e,
                grants=s.grants | {e},
                minted=e,
            )
        if kind == "split_ship":
            return s._replace(phase=2, sp_mass=s.applied_c)
        if kind == "split_complete":
            # B adopts the shipped mass + the META-captured dedup
            # window; C releases; parked frames route onward; the live
            # grant is consumed and the directory epoch advances
            return s._replace(
                phase=3,
                auth_c=0,
                auth_b=1,
                applied_b=s.sp_mass,
                dedup_b=s.sp_dedup,
                applied_c=frozenset(),
                dedup_c=frozenset(),
                chan_cb=s.chan_cb | s.parked_c,
                parked_c=frozenset(),
                grants=s.grants - {s.sp_epoch},
                dir_epoch=s.sp_epoch,
                sp_epoch=0,
            )
        if kind == "split_abort":
            # drain cancelled: C stays the authority and applies what
            # it parked; the minted grant stays IN FLIGHT — the stale
            # residue the true spec must be immune to
            applied, dedup = s.applied_c, s.dedup_c
            for u in sorted(s.parked_c):
                if u not in dedup:
                    applied, dedup = applied | {u}, dedup | {u}
            return s._replace(
                phase=0,
                sp_dedup=frozenset(),
                sp_mass=frozenset(),
                sp_epoch=0,
                applied_c=applied,
                dedup_c=dedup,
                parked_c=frozenset(),
            )
        if kind == "grant_stale":
            g = a[1]
            if self.mutation == "stale_grant_readopt" and g != s.sp_epoch:
                # the buggy successor trusts the grant message instead
                # of the directory's current entry
                return s._replace(grants=s.grants - {g}, auth_b=1)
            # TRUE spec: the grant is acted on via the directory's
            # CURRENT entry — a grant that is not the live split's is
            # stale and discarded
            return s._replace(grants=s.grants - {g})
        raise AssertionError(a)

    def _apply_at_b(self, s: SplitState, u):
        if u in s.dedup_b:
            return s
        dbl = s.double + (1 if u in s.applied_b else 0)
        return s._replace(
            applied_b=s.applied_b | {u},
            dedup_b=s.dedup_b | {u},
            double=dbl,
        )

    def invariants(self, s: SplitState):
        bad = []
        if s.auth_c and s.auth_b:
            bad.append(
                "exactly-one-owner: two simultaneous authorities for "
                "the moving half (stale-grant split-brain)"
            )
        if s.double:
            bad.append(
                "exactly-once: a unit was applied twice at an owner "
                "authority (end-to-end dedup window breached)"
            )
        if s.lost:
            bad.append(
                f"conservation: units {sorted(s.lost)} destroyed across "
                f"the split boundary"
            )
        applied = s.applied_b if s.auth_b else s.applied_c
        held = (
            applied
            | s.chan_ab
            | s.led_bc
            | s.chan_bc
            | s.chan_cb
            | s.parked_c
            | (s.sp_mass if s.phase == 2 else frozenset())
            | (s.applied_c if s.auth_b else frozenset())
            | s.lost
        )
        missing = frozenset(range(1, s.prod + 1)) - held
        if missing:
            bad.append(
                f"conservation: units {sorted(missing)} vanished with "
                f"no channel, ledger, park, or staged mass holding them"
            )
        return bad

    def quiescent(self, s: SplitState):
        applied = s.applied_b if s.auth_b else s.applied_c
        return (
            s.prod == P
            and applied == frozenset(range(1, P + 1))
            and not s.chan_ab
            and not s.led_bc
            and not s.chan_bc
            and not s.chan_cb
            and not s.parked_c
            and s.phase in (0, 3)
        )

    def canon(self, s: SplitState):
        return _relabel(s, _SPLIT_SETS, s.prod)

    def ample(self, s: SplitState, acts):
        # produce mints a FRESH identity: it commutes with every other
        # action and neither disables nor is disabled by any — the
        # classic safe ample singleton
        prod = [a for a in acts if a[0] == "produce"]
        return prod if prod else acts

    def liveness(self):
        return {
            "eventually-converges": self.quiescent,
            "eventually-resumes": lambda s: (
                not s.parked_c and s.phase not in (1, 2)
            ),
        }

    def fairness(self):
        return [
            (k, (lambda a, _k=k: a[0] == _k))
            for k in (
                "produce", "deliver_ab", "deliver_bc", "ack_bc",
                "deliver_cb", "split_ship", "split_complete",
            )
        ]


# ---------------------------------------------------------------------------
# reshard_merge
# ---------------------------------------------------------------------------

MP = 3  # merge spec units: odd -> folding half (B), even -> staying half (C)


def _hi(u: int) -> bool:
    return u % 2 == 1


class MergeState(NamedTuple):
    prod: int
    chan_ab: frozenset  # A->B in flight
    led_bc: frozenset  # B->C ledgered, unacked (at-least-once hop)
    chan_bc: frozenset  # B->C in flight
    applied_b: frozenset  # folding half's slice at B
    dedup_b: frozenset
    parked_b: frozenset  # folding-half frames arriving from merge_meta on
    applied_c: frozenset  # staying half (and post-fold: everything)
    dedup_c: frozenset
    phase: int  # 0 idle / 1 meta / 2 shipped / 3 folded
    mg_dedup: frozenset
    mg_mass: frozenset
    double: int
    lost: frozenset


_MERGE_SETS = (
    "chan_ab", "led_bc", "chan_bc", "applied_b", "dedup_b", "parked_b",
    "applied_c", "dedup_c", "mg_dedup", "mg_mass", "lost",
)


class ReshardMergeSpec(Spec):
    name = "reshard_merge"
    depth_bound = 24
    mutations = {
        "merge_drops_inflight_outbox": (
            "merge_complete CLEARS the mass parked in flight toward the "
            "folding half instead of relaying it to the surviving owner "
            "— the sender's ledger was already debited: silent "
            "cluster-mass loss"
        ),
    }

    def initial(self):
        e = frozenset()
        return MergeState(0, e, e, e, e, e, e, e, e, 0, e, e, 0, e)

    def enabled(self, s: MergeState):
        acts = []
        if s.prod < MP:
            acts.append(("produce",))
        for u in sorted(s.chan_ab):
            acts.append(("deliver_ab", u))
        for u in sorted(s.chan_bc):
            acts.append(("deliver_bc", u))
        for u in sorted(s.led_bc - s.chan_bc):
            acts.append(("redeliver_bc", u))
        for u in sorted(s.led_bc):
            if u in s.dedup_c:
                acts.append(("ack_bc", u))
        if s.phase == 0:
            acts.append(("merge_meta",))
        if s.phase == 1:
            acts.append(("merge_ship",))
        if s.phase == 2:
            acts.append(("merge_complete",))
        return acts

    def apply(self, s: MergeState, a):
        kind = a[0]
        if kind == "produce":
            u = s.prod + 1
            return s._replace(prod=u, chan_ab=s.chan_ab | {u})
        if kind == "deliver_ab":
            u = a[1]
            s = s._replace(chan_ab=s.chan_ab - {u})
            if not _hi(u):
                # staying-half mass forwards to C over the lossy hop
                return s._replace(
                    led_bc=s.led_bc | {u}, chan_bc=s.chan_bc | {u}
                )
            if s.phase in (1, 2):
                # folding half mid-fold: park for onward routing
                return s._replace(parked_b=s.parked_b | {u})
            if s.phase == 3:
                # B released its half: relay onward to C
                return s._replace(
                    led_bc=s.led_bc | {u}, chan_bc=s.chan_bc | {u}
                )
            if u in s.dedup_b:
                return s
            dbl = s.double + (1 if u in s.applied_b else 0)
            return s._replace(
                applied_b=s.applied_b | {u},
                dedup_b=s.dedup_b | {u},
                double=dbl,
            )
        if kind in ("deliver_bc", "redeliver_bc"):
            u = a[1]
            s = s._replace(chan_bc=s.chan_bc - {u})
            if u in s.dedup_c:
                return s
            dbl = s.double + (1 if u in s.applied_c else 0)
            return s._replace(
                applied_c=s.applied_c | {u},
                dedup_c=s.dedup_c | {u},
                double=dbl,
            )
        if kind == "ack_bc":
            u = a[1]
            return s._replace(led_bc=s.led_bc - {u})
        if kind == "merge_meta":
            return s._replace(phase=1, mg_dedup=s.dedup_b)
        if kind == "merge_ship":
            return s._replace(phase=2, mg_mass=s.applied_b)
        if kind == "merge_complete":
            s = s._replace(
                phase=3,
                applied_c=s.applied_c | s.mg_mass,
                dedup_c=s.dedup_c | s.mg_dedup,
                applied_b=frozenset(),
                dedup_b=frozenset(),
            )
            if self.mutation == "merge_drops_inflight_outbox":
                return s._replace(
                    parked_b=frozenset(), lost=s.lost | s.parked_b
                )
            # TRUE spec: parked in-flight mass relays onward to the
            # surviving owner under its unchanged identity
            return s._replace(
                led_bc=s.led_bc | s.parked_b,
                chan_bc=s.chan_bc | s.parked_b,
                parked_b=frozenset(),
            )
        raise AssertionError(a)

    def invariants(self, s: MergeState):
        bad = []
        if s.double:
            bad.append(
                "exactly-once: a unit was applied twice at an owner "
                "authority (dedup window not transferred at the fold)"
            )
        if s.lost:
            bad.append(
                f"conservation: in-flight mass {sorted(s.lost)} toward "
                f"the folding half dropped at merge-complete"
            )
        held = (
            s.applied_b
            | s.applied_c
            | s.chan_ab
            | s.led_bc
            | s.chan_bc
            | s.parked_b
            | (s.mg_mass if s.phase == 2 else frozenset())
            | s.lost
        )
        missing = frozenset(range(1, s.prod + 1)) - held
        if missing:
            bad.append(
                f"conservation: units {sorted(missing)} vanished with "
                f"no channel, ledger, park, or staged mass holding them"
            )
        if s.phase == 3 and s.applied_b:
            bad.append(
                "exactly-one-owner: the folded half still holds slice "
                "content after the surviving owner adopted"
            )
        return bad

    def quiescent(self, s: MergeState):
        if s.prod != MP or s.chan_ab or s.led_bc or s.chan_bc or s.parked_b:
            return False
        all_u = frozenset(range(1, MP + 1))
        if s.phase == 0:
            return (
                s.applied_b == frozenset(u for u in all_u if _hi(u))
                and s.applied_c == frozenset(u for u in all_u if not _hi(u))
            )
        return s.phase == 3 and s.applied_c == all_u

    def canon(self, s: MergeState):
        return _relabel(s, _MERGE_SETS, s.prod, cls_of=lambda u: u % 2)

    def ample(self, s: MergeState, acts):
        prod = [a for a in acts if a[0] == "produce"]
        return prod if prod else acts

    def liveness(self):
        return {
            "eventually-converges": self.quiescent,
            "eventually-resumes": lambda s: (
                not s.parked_b and s.phase not in (1, 2)
            ),
        }

    def fairness(self):
        return [
            (k, (lambda a, _k=k: a[0] == _k))
            for k in (
                "produce", "deliver_ab", "deliver_bc", "ack_bc",
                "merge_ship", "merge_complete",
            )
        ]


# ---------------------------------------------------------------------------
# master_handoff
# ---------------------------------------------------------------------------


class MasterState(NamedTuple):
    reqs: int  # grant requests arrived (bounded by R)
    pending: int  # unserved requests
    minter0: int  # old master believes it mints
    minter1: int  # successor believes it mints
    minted0: int  # old master's mint cursor (max epoch it minted)
    minted1: int  # successor's mint cursor
    ever: frozenset  # every epoch ever minted (collision ghost source)
    flight: frozenset  # grant epochs in flight toward the directory
    dir_epoch: int  # directory's applied epoch (monotone)
    phase: int  # 0 idle / 1 meta (sealed) / 2 shipped / 3 handed off
    ma_cursor: int  # mint cursor snapshot shipped at ma_ship
    replays: int  # stale-replay budget used (bounded adversary)
    collide: int  # ghost: an epoch was minted twice
    regress: int  # ghost: the directory applied a non-monotone epoch


class MasterHandoffSpec(Spec):
    name = "master_handoff"
    depth_bound = 18
    mutations = {
        "two_minters_after_handoff": (
            "ma_meta does not SEAL the old master — after the authority "
            "ships, both nodes believe they mint: the successor reuses "
            "epochs the old master already minted (no-stale-minter)"
        ),
    }

    def initial(self):
        return MasterState(
            0, 0, 1, 0, 0, 0, frozenset(), frozenset(), 0, 0, 0, 0, 0, 0
        )

    def enabled(self, s: MasterState):
        acts = []
        if s.reqs < R:
            acts.append(("request",))
        if s.pending and s.minter0:
            acts.append(("mint0",))
        if s.pending and s.minter1:
            acts.append(("mint1",))
        for g in sorted(s.flight):
            acts.append(("grant_deliver", g))
        if s.replays < REPLAYS:
            for g in sorted(s.ever - s.flight):
                acts.append(("replay", g))
        if s.phase == 0 and s.minter0:
            acts.append(("ma_meta",))
        if s.phase == 1:
            acts.append(("ma_ship",))
        if s.phase == 2:
            acts.append(("ma_complete",))
        return acts

    def apply(self, s: MasterState, a):
        kind = a[0]
        if kind == "request":
            return s._replace(reqs=s.reqs + 1, pending=s.pending + 1)
        if kind in ("mint0", "mint1"):
            cur = s.minted0 if kind == "mint0" else s.minted1
            e = cur + 1
            s = s._replace(
                pending=s.pending - 1,
                flight=s.flight | {e},
                collide=s.collide + (1 if e in s.ever else 0),
                ever=s.ever | {e},
            )
            if kind == "mint0":
                return s._replace(minted0=e)
            return s._replace(minted1=e)
        if kind == "grant_deliver":
            g = a[1]
            s = s._replace(flight=s.flight - {g})
            if g > s.dir_epoch:
                return s._replace(dir_epoch=g)
            # stale (replayed or collided) grant: the directory's epoch
            # check discards it — applying it would be the regress
            return s
        if kind == "replay":
            return s._replace(
                flight=s.flight | {a[1]}, replays=s.replays + 1
            )
        if kind == "ma_meta":
            if self.mutation == "two_minters_after_handoff":
                return s._replace(phase=1)  # no seal: keeps minting
            return s._replace(phase=1, minter0=0)  # SEAL the old minter
        if kind == "ma_ship":
            return s._replace(phase=2, ma_cursor=s.minted0)
        if kind == "ma_complete":
            return s._replace(
                phase=3, minter1=1, minted1=s.ma_cursor
            )
        raise AssertionError(a)

    def invariants(self, s: MasterState):
        bad = []
        if s.minter0 and s.minter1:
            bad.append(
                "no-stale-minter: two nodes simultaneously believe they "
                "mint grants"
            )
        if s.collide:
            bad.append(
                "no-stale-minter: an epoch was minted twice (the old "
                "master kept minting past the shipped cursor)"
            )
        if s.regress:
            bad.append(
                "epoch-monotonic: the directory applied a non-monotone "
                "epoch"
            )
        return bad

    def quiescent(self, s: MasterState):
        return (
            s.reqs == R
            and s.pending == 0
            and not s.flight
            and s.phase in (0, 3)
        )

    def ample(self, s: MasterState, acts):
        req = [a for a in acts if a[0] == "request"]
        return req if req else acts

    def liveness(self):
        return {
            "eventually-converges": self.quiescent,
            "eventually-exactly-one-minter": lambda s: (
                bool(s.minter0) != bool(s.minter1)
            ),
        }

    def fairness(self):
        return [
            (k, (lambda a, _k=k: a[0] == _k))
            for k in (
                "request", "mint0", "mint1", "grant_deliver",
                "ma_ship", "ma_complete",
            )
        ]


# ---------------------------------------------------------------------------
# conformance acceptors (the r20 implementation lands against these)
# ---------------------------------------------------------------------------


class ReshardAcceptor(TraceAcceptor):
    """Per-node staged-transfer ordering for split/merge timelines:
    a ``*_done`` must close a matching open ``*_begin``, staged
    transfers never nest, and split and merge never overlap on one
    node. PERMISSIVE about everything else — a killed node legitimately
    leaves a begin open (kill-restore chaos reuses node ids), so there
    is no end-of-run obligation."""

    def __init__(self, scope: str = ""):
        super().__init__(scope)
        self.open: str = ""  # "", "split", "merge"

    def step(self, event: dict) -> None:
        name = event.get("name", "")
        if name not in (
            "reshard_split_begin", "reshard_split_done",
            "reshard_merge_begin", "reshard_merge_done",
        ):
            return
        kind = "split" if "split" in name else "merge"
        if name.endswith("_begin"):
            if self.open == kind:
                self._flag(f"nested reshard_{kind}_begin with one open")
            elif self.open:
                self._flag(
                    f"reshard_{kind}_begin while a {self.open} transfer "
                    f"is open (staged transfers must not overlap)"
                )
            self.open = kind
        else:
            if self.open != kind:
                self._flag(
                    f"reshard_{kind}_done without an open "
                    f"reshard_{kind}_begin"
                )
            self.open = ""


class MasterAuthorityAcceptor(TraceAcceptor):
    """Global epoch discipline for master-authority timelines: grant
    epochs mint strictly monotonically, a master ``done`` closes an
    open ``begin``, no grant mints while the authority is in flight,
    and after the authority lands only the NEW master mints."""

    def __init__(self, scope: str = ""):
        super().__init__(scope)
        self.max_epoch = 0
        self.in_flight = False
        self.master = None  # node id of the current minting authority

    def step(self, event: dict) -> None:
        name = event.get("name", "")
        node = event.get("node", 0)
        arg = event.get("arg", 0)
        if name == "reshard_grant":
            if self.in_flight:
                self._flag(
                    "reshard_grant minted while the master authority "
                    "is in flight (the old minter must be sealed)"
                )
            if self.master is not None and node != self.master:
                self._flag(
                    f"reshard_grant from node {node} after the "
                    f"authority moved to node {self.master} "
                    f"(no-stale-minter)"
                )
            if arg <= self.max_epoch:
                self._flag(
                    f"reshard_grant epoch {arg} <= already-minted "
                    f"{self.max_epoch} (epoch monotonicity)"
                )
            self.max_epoch = max(self.max_epoch, arg)
        elif name == "reshard_master_begin":
            if self.in_flight:
                self._flag("nested reshard_master_begin")
            self.in_flight = True
        elif name == "reshard_master_done":
            if not self.in_flight:
                self._flag(
                    "reshard_master_done without an open "
                    "reshard_master_begin"
                )
            self.in_flight = False
            self.master = node


SPECS = [ReshardSplitSpec, ReshardMergeSpec, MasterHandoffSpec]
