"""Drain-node spec: seal -> drain -> close with child re-graft
(comm/peer.py leave()/drain_node, the r06 carry/re-graft discipline).

A 3-node chain G <- T <- C (G the surviving parent, T the drain target,
C its child). Mass units carry identities so conservation and
exactly-once are set algebra, not counters:

- C and T produce units; up-flow rides ledgered links (a unit stays in
  its sender's ledger until the receiver ACKs it — the ACK implies the
  receiver APPLIED it);
- the routed drain command SEALS T: sealed ingress is discarded
  WITHOUT acking, so every in-flight unit stays in C's ledger and rolls
  back into C's carry when the link dies — the sender's mass is never
  half-applied at a dying node;
- T closes only after draining everything it OWES: its own residual
  and its unacked uplink ledger must be empty (the close guard — the
  "drain everything it owes" rule);
- C re-grafts to G; the join's diff semantics deliver exactly the
  units G lacks (carry minus G's state), so redelivery cannot
  double-apply.

Invariants: ``exactly-once`` (no unit applied twice at G),
``conservation`` (every produced unit is applied at G or retained in a
ledger / residual / carry / channel — never silently dropped),
``closed-owing-nothing`` (a closed T with undrained mass). Quiescence:
T closed, C re-grafted, G holding every produced unit, all channels
and ledgers empty.
"""

from __future__ import annotations

from typing import NamedTuple

from .core import Spec, TraceAcceptor

C_CAP = 2  # units produced at C (ids 1..2)
T_ID = 3  # the one unit produced at T


class DrainState(NamedTuple):
    mode: int  # T: 0 normal / 1 sealed / 2 closed
    regrafted: bool
    prod_c: int
    prod_t: int
    applied_t: frozenset
    applied_g: frozenset
    res_t: frozenset  # applied at T, not yet forwarded to G
    led_c: frozenset  # C->T unacked
    led_t: frozenset  # T->G unacked
    led_cg: frozenset  # C->G unacked (post-regraft)
    carry_c: frozenset
    chan_ct: tuple
    chan_tg: tuple
    chan_cg: tuple
    ack_tc: tuple
    ack_gt: tuple
    ack_gc: tuple
    double_apply: int


class DrainSpec(Spec):
    name = "drain"
    depth_bound = 30
    mutations: dict[str, str] = {}

    def initial(self):
        e = frozenset()
        return DrainState(
            0, False, 0, 0, e, e, e, e, e, e, e, (), (), (), (), (), (), 0
        )

    def enabled(self, s: DrainState):
        acts = []
        if s.prod_c < C_CAP:
            acts.append(("produce_c",))
        if s.mode == 0 and s.prod_t < 1:
            acts.append(("produce_t",))
        if s.mode < 2 and s.res_t:
            acts.append(("fwd_t",))
        if s.mode == 0:
            acts.append(("drain_cmd",))
        if s.mode == 1 and not s.res_t and not s.led_t:
            acts.append(("close_t",))
        if s.mode == 2 and not s.regrafted:
            acts.append(("regraft",))
        for ch in ("chan_ct", "chan_tg", "chan_cg", "ack_tc", "ack_gt",
                   "ack_gc"):
            if getattr(s, ch):
                acts.append(("deliver", ch))
        return acts

    def apply(self, s: DrainState, a):
        kind = a[0]
        if kind == "produce_c":
            uid = s.prod_c + 1
            s = s._replace(prod_c=uid)
            if s.regrafted:
                return s._replace(
                    led_cg=s.led_cg | {uid}, chan_cg=s.chan_cg + (uid,)
                )
            if s.mode < 2:
                return s._replace(
                    led_c=s.led_c | {uid}, chan_ct=s.chan_ct + (uid,)
                )
            # orphaned (uplink dead, not yet re-grafted): the unit lands
            # in the live carry slot and rides the re-graft
            return s._replace(carry_c=s.carry_c | {uid})
        if kind == "produce_t":
            return s._replace(
                prod_t=1,
                applied_t=s.applied_t | {T_ID},
                res_t=s.res_t | {T_ID},
            )
        if kind == "fwd_t":
            uid = min(s.res_t)
            return s._replace(
                res_t=s.res_t - {uid},
                led_t=s.led_t | {uid},
                chan_tg=s.chan_tg + (uid,),
            )
        if kind == "drain_cmd":
            return s._replace(mode=1)
        if kind == "close_t":
            # the CT and TG links die with T: C rolls its unacked ledger
            # into the carry (LINK_DOWN -> rollback), sockets clear
            return s._replace(
                mode=2,
                carry_c=s.carry_c | s.led_c,
                led_c=frozenset(),
                chan_ct=(),
                chan_tg=(),
                ack_tc=(),
                ack_gt=(),
            )
        if kind == "regraft":
            # diff join: the handshake's parent-minus-child seeding means
            # exactly the units G lacks stream over the new link
            to_send = s.carry_c - s.applied_g
            return s._replace(
                regrafted=True,
                carry_c=frozenset(),
                led_cg=s.led_cg | to_send,
                chan_cg=s.chan_cg + tuple(sorted(to_send)),
            )
        if kind == "deliver":
            ch = a[1]
            q = getattr(s, ch)
            uid, rest = q[0], q[1:]
            s = s._replace(**{ch: rest})
            if ch == "chan_ct":
                if s.mode == 0:
                    return s._replace(
                        applied_t=s.applied_t | {uid},
                        res_t=s.res_t | {uid},
                        ack_tc=s.ack_tc + (uid,),
                    )
                return s  # sealed: discard WITHOUT acking — the unit
                # stays in C's ledger and survives into the carry
            if ch == "chan_tg":
                if uid in s.applied_g:
                    return s._replace(
                        double_apply=s.double_apply + 1,
                        ack_gt=s.ack_gt + (uid,),
                    )
                return s._replace(
                    applied_g=s.applied_g | {uid}, ack_gt=s.ack_gt + (uid,)
                )
            if ch == "chan_cg":
                if uid in s.applied_g:
                    return s._replace(
                        double_apply=s.double_apply + 1,
                        ack_gc=s.ack_gc + (uid,),
                    )
                return s._replace(
                    applied_g=s.applied_g | {uid}, ack_gc=s.ack_gc + (uid,)
                )
            if ch == "ack_tc":
                return s._replace(led_c=s.led_c - {uid})
            if ch == "ack_gt":
                return s._replace(led_t=s.led_t - {uid})
            if ch == "ack_gc":
                return s._replace(led_cg=s.led_cg - {uid})
        raise AssertionError(a)

    def invariants(self, s: DrainState):
        bad = []
        if s.double_apply:
            bad.append("exactly-once: a unit was applied twice at G")
        produced = set(range(1, s.prod_c + 1)) | (
            {T_ID} if s.prod_t else set()
        )
        held = (
            s.applied_g
            | s.led_c
            | s.led_t
            | s.led_cg
            | s.res_t
            | s.carry_c
            | set(s.chan_ct)
            | set(s.chan_tg)
            | set(s.chan_cg)
        )
        if produced - held:
            bad.append(
                "conservation: a produced unit is neither applied at G "
                "nor retained anywhere"
            )
        if s.mode == 2 and (s.res_t or s.led_t):
            bad.append("closed-owing-nothing: T closed with undrained mass")
        return bad

    def quiescent(self, s: DrainState):
        # T's own unit is optional: a drain command landing before T
        # ever produced simply drains a unit-less node (the app stops
        # adding at seal time — leave() semantics)
        produced = set(range(1, s.prod_c + 1)) | (
            {T_ID} if s.prod_t else set()
        )
        return (
            s.mode == 2
            and s.regrafted
            and s.prod_c == C_CAP
            and s.applied_g == produced
            and not (s.led_c or s.led_t or s.led_cg or s.res_t or s.carry_c)
            and not (s.chan_ct or s.chan_tg or s.chan_cg)
            and not (s.ack_tc or s.ack_gt or s.ack_gc)
        )


class DrainAcceptor(TraceAcceptor):
    """One node's drain scope: a routed drain is accepted once
    (drain_begin), and the seal it promises must actually fire before
    the run ends — a drain_begin with no seal is a target that
    acknowledged the command and never left."""

    def __init__(self, scope: str = ""):
        super().__init__(scope)
        self._drains = 0
        self._seals = 0

    def step(self, event: dict) -> None:
        name = event["name"]
        if name == "drain_begin":
            self._drains += 1
            if self._drains > 1:
                self._flag("drain_begin accepted twice on one node")
        elif name == "seal":
            self._seals += 1

    def finish(self) -> list[str]:
        if self._drains and not self._seals:
            self._flag("drain_begin with no seal before end of run")
        return self.violations


SPECS = [DrainSpec]
