"""Per-link window specs: the ledgered go-back-N protocol and the
unledgered subscriber stream with verifiable FRESH marks.

Modeled against the protocol documentation in comm/wire.py (module
docstring: the tx_seq/ACK/go-back-N rules) and the serve-tier FRESH
format note (wire.py: FRESH carries ``last_seq`` so the mark is
verifiable on an unledgered link).

**GbnSpec** — one sender, one receiver, both channel directions fully
adversarial (drop / duplicate / reorder at any step; delay is
interleaving). Sender keeps every unacked seq in its ledger bounded by
a window, retransmits the head on (non-deterministic) timeout, and
tears the link down into the carry after ``retry_limit`` fruitless
rounds. Receiver applies only ``seq == rx+1``, discards duplicates
without re-applying, discards past a gap without acking. Invariants:

- ``exactly-once``: no seq is ever applied twice;
- ``in-order``: the applied set is exactly ``{1..rx}``;
- ``conservation``: every produced seq is applied, retained in the
  ledger, or rolled back into the carry — mass is never silently lost
  (the debited-residual conservation rule at link scope).

**SubSpec** — the r10 unledgered subscriber link: loss is repaired by
resync (control-plane re-seed), not retransmission, and freshness is
only believable when VERIFIED. The FRESH mark carries the link's last
data tx_seq; the subscriber accepts it only when it has applied exactly
that many messages — otherwise the stream tail was swallowed and the
mark must trigger a resync instead (the one gap no later data message
can expose on an idle tree). Invariant ``verified-fresh-is-true``: a
subscriber in the verified-fresh state is byte-current with its parent.

Mutation ``fresh_no_seq`` (the historical r10 bug, found by hand in
review round 10): the mark's seq check is dropped — a FRESH after a
swallowed tail then falsely verifies freshness over diverged state.
"""

from __future__ import annotations

from typing import NamedTuple

from .core import Spec, TraceAcceptor

# model bounds: 3 messages, window 2, 2 retransmission rounds before
# teardown, at most 3 messages in flight per direction (dup cap — the
# cap is what keeps the graph finite: a full pipe drops the extra copy,
# which the protocol must already survive)
P, W, RETRY, CHAN_CAP = 3, 2, 2, 3


class GbnState(NamedTuple):
    produced: int  # seqs 1..produced exist
    ledger: tuple  # unacked seqs (ordered)
    carry: frozenset  # seqs rolled back at teardown
    applied: tuple  # (seq, times_applied) sorted — times>1 is the bug
    rx: int  # receiver's last in-order accepted seq
    acked: int  # sender's view of the cumulative ack
    retx_rounds: int
    chan_data: tuple  # seqs in flight sender->receiver
    chan_ack: tuple  # cumulative-ack values in flight receiver->sender
    alive: bool


def _applied_inc(applied: tuple, seq: int) -> tuple:
    d = dict(applied)
    d[seq] = d.get(seq, 0) + 1
    return tuple(sorted(d.items()))


class GbnSpec(Spec):
    name = "gbn"
    depth_bound = 64  # exhausts the capped graph (run_check demands the
    # frontier empties — bounded-to-depth is a weaker claim than the
    # artifact commits to)
    mutations: dict[str, str] = {}

    def initial(self):
        return GbnState(0, (), frozenset(), (), 0, 0, 0, (), (), True)

    def enabled(self, s: GbnState):
        acts = []
        if s.alive:
            if s.produced < P and len(s.ledger) < W:
                acts.append(("send",))
            if s.ledger:
                acts.append(("timeout",))
        for i in range(len(s.chan_data)):
            acts.append(("deliver_data", i))
            acts.append(("drop_data", i))
            if len(s.chan_data) < CHAN_CAP:
                acts.append(("dup_data", i))
        for i in range(len(s.chan_ack)):
            acts.append(("deliver_ack", i))
            acts.append(("drop_ack", i))
        return acts

    def apply(self, s: GbnState, a):
        kind = a[0]
        if kind == "send":
            seq = s.produced + 1
            return s._replace(
                produced=seq,
                ledger=s.ledger + (seq,),
                chan_data=s.chan_data + (seq,)
                if len(s.chan_data) < CHAN_CAP
                else s.chan_data,  # full pipe: the message is "in the
                # socket buffer", still ledgered — timeout re-offers it
            )
        if kind == "timeout":
            if s.retx_rounds < RETRY:
                chan = s.chan_data
                if len(chan) < CHAN_CAP:
                    chan = chan + (s.ledger[0],)  # byte-identical head retx
                return s._replace(chan_data=chan, retx_rounds=s.retx_rounds + 1)
            # black-hole teardown: roll the whole unacked tail into carry
            return s._replace(
                ledger=(),
                carry=s.carry | set(s.ledger),
                chan_data=(),
                chan_ack=(),
                alive=False,
            )
        if kind == "deliver_data":
            i = a[1]
            seq = s.chan_data[i]
            chan = s.chan_data[:i] + s.chan_data[i + 1 :]
            if not s.alive:
                return s._replace(chan_data=chan)
            if seq == s.rx + 1:  # in order: apply + cumulative ack
                ack = s.chan_ack
                if len(ack) < CHAN_CAP:
                    ack = ack + (seq,)
                return s._replace(
                    chan_data=chan,
                    applied=_applied_inc(s.applied, seq),
                    rx=seq,
                    chan_ack=ack,
                )
            # duplicate (<= rx) or gap (> rx+1): discard unapplied; a dup
            # re-acks the current cumulative count so a lost ACK heals
            if seq <= s.rx and len(s.chan_ack) < CHAN_CAP:
                return s._replace(chan_data=chan, chan_ack=s.chan_ack + (s.rx,))
            return s._replace(chan_data=chan)
        if kind == "drop_data":
            i = a[1]
            return s._replace(chan_data=s.chan_data[:i] + s.chan_data[i + 1 :])
        if kind == "dup_data":
            return s._replace(chan_data=s.chan_data + (s.chan_data[a[1]],))
        if kind == "deliver_ack":
            i = a[1]
            v = s.chan_ack[i]
            chan = s.chan_ack[:i] + s.chan_ack[i + 1 :]
            if not s.alive or v <= s.acked:
                return s._replace(chan_ack=chan)
            return s._replace(
                chan_ack=chan,
                acked=v,
                ledger=tuple(q for q in s.ledger if q > v),
                retx_rounds=0,  # forward progress resets the round count
            )
        if kind == "drop_ack":
            i = a[1]
            return s._replace(chan_ack=s.chan_ack[:i] + s.chan_ack[i + 1 :])
        raise AssertionError(a)

    def invariants(self, s: GbnState):
        bad = []
        if any(n > 1 for _, n in s.applied):
            bad.append("exactly-once: a seq was applied twice")
        if {q for q, _ in s.applied} != set(range(1, s.rx + 1)):
            bad.append("in-order: applied set is not the prefix {1..rx}")
        kept = {q for q, _ in s.applied} | set(s.ledger) | s.carry
        if set(range(1, s.produced + 1)) - kept:
            bad.append(
                "conservation: a produced seq is neither applied nor "
                "ledgered nor carried"
            )
        return bad

    def quiescent(self, s: GbnState):
        return (
            not s.chan_data
            and not s.chan_ack
            and not s.ledger
            and (s.produced == P or not s.alive)
        )


# -- unledgered subscriber stream + FRESH marks ------------------------------


class SubState(NamedTuple):
    sent: int  # parent's data tx_seq (1..sent emitted)
    applied: int  # subscriber applied exactly seqs 1..applied
    chan: tuple  # in flight: ("d", seq) | ("f", last_seq)
    fresh_at: int  # 0, or the mark seq the subscriber VERIFIED fresh at
    sent_at_mark: int  # ghost: parent's sent when that mark was emitted
    resyncs: int


class SubSpec(Spec):
    name = "sub"
    depth_bound = 24
    mutations = {
        "fresh_no_seq": (
            "r10: FRESH marks verified without the last_seq check — a "
            "mark after a swallowed stream tail falsely verifies "
            "freshness over diverged state"
        ),
    }

    def initial(self):
        return SubState(0, 0, (), 0, 0, 0)

    def enabled(self, s: SubState):
        acts = []
        if s.sent < P and len(s.chan) < CHAN_CAP:
            acts.append(("send",))
        if len(s.chan) < CHAN_CAP:
            acts.append(("fresh",))  # idle-link drain mark, any time
        for i in range(len(s.chan)):
            acts.append(("deliver", i))
            acts.append(("drop", i))  # unledgered: loss is a seq gap
        if s.resyncs < 2 and s.applied < s.sent:
            acts.append(("resync",))
        return acts

    def apply(self, s: SubState, a):
        kind = a[0]
        if kind == "send":
            seq = s.sent + 1
            return s._replace(sent=seq, chan=s.chan + (("d", seq),))
        if kind == "fresh":
            # the mark carries the link's last data tx_seq (wire.py FRESH
            # format note); the ghost field remembers the parent's true
            # state so the invariant can judge a verification
            return s._replace(chan=s.chan + (("f", s.sent),))
        if kind == "drop":
            i = a[1]
            return s._replace(chan=s.chan[:i] + s.chan[i + 1 :])
        if kind == "resync":
            # control-plane re-seed (SYNC/CHUNK/DONE ride TCP, chaos
            # never touches them — r06 rule): the subscriber becomes
            # current and the stream restarts from the parent's seq
            return s._replace(
                applied=s.sent,
                chan=tuple(m for m in s.chan if m[0] != "d"),
                fresh_at=0,
                resyncs=s.resyncs + 1,
            )
        if kind == "deliver":
            i = a[1]
            m = s.chan[i]
            chan = s.chan[:i] + s.chan[i + 1 :]
            if m[0] == "d":
                if m[1] == s.applied + 1:
                    return s._replace(chan=chan, applied=m[1])
                return s._replace(chan=chan)  # gap/dup: discard (resync
                # is the repair path, enumerated separately)
            # FRESH mark: verifiable acceptance — the TRUE spec accepts
            # it only when applied == the mark's last_seq
            last_seq = m[1]
            if self.mutation == "fresh_no_seq" or s.applied == last_seq:
                return s._replace(
                    chan=chan, fresh_at=last_seq, sent_at_mark=last_seq
                )
            return s._replace(chan=chan)  # mismatch: resync, never verify
        raise AssertionError(a)

    def invariants(self, s: SubState):
        bad = []
        if s.fresh_at and s.applied < s.sent_at_mark:
            bad.append(
                "verified-fresh-is-true: subscriber verified fresh at a "
                "mark whose stream tail it never applied"
            )
        if s.applied > s.sent:
            bad.append("applied beyond the parent's stream")
        return bad

    def quiescent(self, s: SubState):
        return s.sent == P and not s.chan and s.applied == s.sent


# -- trace acceptors ---------------------------------------------------------


class LinkAcceptor(TraceAcceptor):
    """One (node, link) scope of a recorded timeline, checked against
    the go-back-N teardown rules: at most one black-hole verdict per
    link id (transport link ids are never reused within a process), and
    a torn-down link stays silent — retransmit / dedup / window-stall
    events after its teardown mean the implementation kept driving a
    window the protocol declared dead."""

    _WINDOW_EVENTS = frozenset(
        {"retransmit", "dedup_discard", "send_window_stall"}
    )

    def __init__(self, scope: str = ""):
        super().__init__(scope)
        self._teardowns = 0
        self._down = False

    def step(self, event: dict) -> None:
        name = event["name"]
        if name == "blackhole_teardown":
            self._teardowns += 1
            if self._teardowns > 1:
                self._flag("second blackhole_teardown on one link id")
            self._down = True
        elif name == "link_down":
            self._down = True
        elif name in self._WINDOW_EVENTS and self._down:
            self._flag(f"{name} after the link was torn down")


class SubAcceptor(TraceAcceptor):
    """One (node, link) subscriber scope: a resync re-runs the handshake
    on an ATTACHED link, so sub_resync before any sub_attach is an
    ordering the serve tier cannot produce."""

    def __init__(self, scope: str = ""):
        super().__init__(scope)
        self._attached = False

    def step(self, event: dict) -> None:
        if event["name"] == "sub_attach":
            self._attached = True
        elif event["name"] == "sub_resync" and not self._attached:
            self._flag("sub_resync before sub_attach")


SPECS = [GbnSpec, SubSpec]
