"""protospec — executable state-machine specs of the wire protocols,
an exhaustive adversarial explorer, and a runtime trace-conformance
monitor (r15 tentpole).

Every correctness bug the r10–r12 review rounds hand-found was a
protocol-INTERLEAVING bug, not a data race: the pre-pause in-flight
sender pass that leaked mass across the SNAP cut (r12), the last-stripe
requeue livelock (r11), the FRESH mark that falsely verified freshness
over a swallowed stream tail (r10). r13 made the data-race class
machine-checked (TSan + annotations); this package does the same for the
protocol class, three ways:

1. **Specs** (``spec_*.py``): small declarative models of the
   load-bearing protocols — the SYNC/WELCOME capability hello, the
   per-link go-back-N window (ledgered and unledgered/FRESH modes), the
   SNAP/SNAP_ACK/RESUME consistent-cut barrier, drain-node
   seal→drain→close, and the r14 lane switch (SWITCH marker, stripe
   promotion, ring backpressure). Each spec is states + enabled actions
   + safety invariants + a quiescence predicate, written against the
   PROTOCOL documentation in comm/wire.py / comm/peer.py /
   sttransport.cpp — never importing the implementation.

2. **Explorer** (``core.py``): exhaustive BFS of a spec's state graph
   under an adversarial network (drop / duplicate / reorder / delay /
   crash wherever the spec's channel model allows them), with state
   hashing + per-spec symmetry canonicalization and a stated depth
   bound. Checks every invariant in every reached state, flags wedged
   states (pending work, no enabled action), and proves quiescence
   reachable. Each spec also carries MUTATIONS encoding the three
   historical bugs; ``run_check.py`` asserts the explorer finds every
   mutation within the bound and none on the true specs, and commits
   the state/transition counts as MODEL_r19.json.

3. **Conformance** (``conformance.py``): the same specs replayed as
   trace ACCEPTORS over real flight-recorder timelines (obs/recorder),
   wired into benchmarks/cluster_chaos.py and suite_load.sh — the
   explorer checks the model exhaustively, the live system checks the
   model still describes it.

Import with the repo's ``tools/`` directory on sys.path
(``import protospec``), the same convention as the lint scripts.
"""

from .core import ExploreResult, Spec, Violation, explore  # noqa: F401

__all__ = ["Spec", "Violation", "ExploreResult", "explore", "all_specs"]


def all_specs():
    """name -> spec CLASS for every true spec (mutations via
    ``cls(mutation=...)``; ``cls.mutations`` names what each seeds)."""
    from . import (
        spec_drain,
        spec_gbn,
        spec_hello,
        spec_lane,
        spec_reshard,
        spec_shard,
        spec_snap,
    )

    out = {}
    for mod in (
        spec_hello, spec_gbn, spec_snap, spec_drain, spec_lane, spec_shard,
        spec_reshard,
    ):
        for cls in mod.SPECS:
            out[cls.name] = cls
    return out
