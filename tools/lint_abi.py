#!/usr/bin/env python
"""ABI lint: ctypes declarations vs the native C declarations.

The ctypes boundary is where this repo's recurring silent-mismatch class
lives: the ``st_engine_counters`` out-array widened 8 -> 12 -> 16 -> 18 ->
22 across rounds, and each widening had to touch stengine.cpp, engine.py's
buffer size and the index map in the same commit — nothing but review
checked they agree. Same for every argtypes list (a dropped or re-typed
parameter reads garbage off the stack, usually *plausible* garbage) and
the ctypes.Structure mirrors of the native config/event/stats structs.

Checked, per function the Python tier declares argtypes for:
  - a native definition exists (stengine.cpp or sttransport.cpp);
  - parameter COUNTS match;
  - parameter KINDS match position-by-position (pointer pointee dtype for
    ndpointers, integer width/signedness for scalars, double, funcptr;
    c_void_p is the deliberate wildcard — nullable pointers use it);
  - restype matches.
Plus:
  - out-array widths: a native parameter named ``outN`` promises N slots;
    the max literal index the native body writes must be N-1, and every
    Python buffer allocated for that call must hold exactly N;
  - ctypes.Structure mirrors (_StConfigC/_StEventC/_StStatsC) match the
    native struct field-for-field.
"""

from __future__ import annotations

import pathlib
import re

if __package__ in (None, ""):
    import _lintlib as L
else:
    from . import _lintlib as L

# ---- native side -----------------------------------------------------------

_NATIVE_RET = {
    "void": "void",
    "void*": "ptr:any",
    "int32_t": "i32",
    "int64_t": "i64",
    "uint32_t": "u32",
    "uint64_t": "u64",
    "double": "f64",
}


def _norm_ptr(base: str) -> str:
    base = base.replace("const", "").replace("struct", "").strip()
    return {
        "void": "ptr:any",
        "char": "ptr:char",
        "uint8_t": "ptr:char",  # byte buffers cross as c_char_p/void_p
        "float": "ptr:float",
        "double": "ptr:double",
        "int32_t": "ptr:int32",
        "int64_t": "ptr:int64",
        "uint32_t": "ptr:uint32",
        "uint64_t": "ptr:uint64",
        "StConfigC": "ptr:struct:StConfigC",
        "StEventC": "ptr:struct:StEventC",
        "StStatsC": "ptr:struct:StStatsC",
    }.get(base, f"ptr:{base}")


def _parse_native_params(raw: str) -> list[tuple[str, str]]:
    """-> [(kind, param_name_or_empty)] — splits at depth-0 commas so
    function-pointer parameters stay whole."""
    params, depth, cur = [], 0, ""
    for ch in raw:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            params.append(cur)
            cur = ""
        else:
            cur += ch
    if cur.strip():
        params.append(cur)
    out: list[tuple[str, str]] = []
    for p in params:
        p = p.strip()
        if not p or p == "void":
            continue
        if "(*" in p:  # function pointer
            out.append(("funcptr", ""))
            continue
        # drop defaulted args and comments already stripped; split name
        m = re.match(
            r"(?:const\s+)?(?:struct\s+)?([A-Za-z_]\w+)\s*(\*?)\s*"
            r"(?:const\s+)?([A-Za-z_]\w*)?$",
            p.replace("* ", "*").replace(" *", "*").replace("*", "* ", 1)
            if "*" in p
            else p,
        )
        if not m:
            out.append((f"unparsed:{p}", ""))
            continue
        base, star, name = m.group(1), m.group(2), m.group(3) or ""
        if base == "int":
            base = "int32_t"  # the ABI uses int only for st_node_create port
        out.append((_norm_ptr(base) if star else _NATIVE_RET.get(base, base),
                    name))
    return out


def native_functions(text: str) -> dict[str, dict]:
    """name -> {ret, params: [(kind, name)], body} for every st_* function
    DEFINITION (brace-balanced bodies; handles multi-line signatures)."""
    out: dict[str, dict] = {}
    for m in re.finditer(r"\b(st_\w+)\s*\(", text):
        name = m.group(1)
        # must be a definition at statement level: find matching ')' then '{'
        i, depth = m.end(), 1
        while i < len(text) and depth:
            if text[i] == "(":
                depth += 1
            elif text[i] == ")":
                depth -= 1
            i += 1
        j = i
        while j < len(text) and text[j] in " \t\n":
            j += 1
        if j >= len(text) or text[j] != "{":
            continue  # a call or declaration, not a definition
        # return type: the token(s) just before the name
        head = text[: m.start()].rsplit(";", 1)[-1].rsplit("}", 1)[-1]
        head = head.replace("__attribute__((visibility(\"default\")))", " ")
        head = head.replace("extern \"C\"", " ").strip()
        ret_tok = head.split()[-1] if head.split() else "void"
        ret = _NATIVE_RET.get(
            ret_tok.replace("*", "") + ("*" if "*" in ret_tok else ""),
            _NATIVE_RET.get(ret_tok, ret_tok),
        )
        if ret_tok.endswith("*"):
            ret = "ptr:any" if ret_tok == "void*" else _norm_ptr(
                ret_tok[:-1]
            )
        # body: brace-balanced span starting at j
        k, depth = j + 1, 1
        while k < len(text) and depth:
            if text[k] == "{":
                depth += 1
            elif text[k] == "}":
                depth -= 1
            k += 1
        if name not in out:  # first definition wins (no overloads in C)
            out[name] = {
                "ret": ret,
                "params": _parse_native_params(text[m.end() : i - 1]),
                "body": text[j:k],
            }
    return out


# ---- python side -----------------------------------------------------------

_PY_KIND = {
    "ctypes.c_void_p": "ptr:any",
    "ctypes.c_char_p": "ptr:char",
    "ctypes.c_int32": "i32",
    "ctypes.c_int64": "i64",
    "ctypes.c_uint32": "u32",
    "ctypes.c_uint64": "u64",
    "ctypes.c_double": "f64",
    "ctypes.c_int": "i32",
    "_f32p": "ptr:float",
    "_u8p": "ptr:char",
    "_u32p": "ptr:uint32",
    "_u64p": "ptr:uint64",
    "_i32p": "ptr:int32",
    "_i64p": "ptr:int64",
    "None": "void",
}


def _py_kind(tok: str) -> str:
    tok = tok.strip()
    m = re.match(r"ctypes\.POINTER\(ctypes\.(c_\w+)\)", tok)
    if m:
        return {
            "c_int32": "ptr:int32",
            "c_int64": "ptr:int64",
            "c_uint32": "ptr:uint32",
            "c_uint64": "ptr:uint64",
            "c_float": "ptr:float",
            "c_double": "ptr:double",
        }.get(m.group(1), f"ptr:{m.group(1)}")
    m = re.match(r"ctypes\.POINTER\((_\w+)\)", tok)
    if m:
        return f"ptr:struct:{m.group(1).lstrip('_')}"
    if tok in ("_StConfigC", "_StEventC", "_StStatsC"):
        return f"ptr:struct:{tok.lstrip('_')}"
    return _PY_KIND.get(tok, f"unparsed:{tok}")


def _split_top(raw: str) -> list[str]:
    parts, depth, cur = [], 0, ""
    for ch in raw:
        if ch in "([":
            depth += 1
        elif ch in ")]":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append(cur)
            cur = ""
        else:
            cur += ch
    if cur.strip():
        parts.append(cur)
    return [p.strip() for p in parts if p.strip()]


def py_declarations(text: str) -> dict[str, dict]:
    decls: dict[str, dict] = {}
    for name, val in re.findall(
        r"lib\.(st_\w+)\.restype\s*=\s*([^\n]+)", text
    ):
        decls.setdefault(name, {})["ret"] = _py_kind(val.strip())
    for name, raw in re.findall(
        r"lib\.(st_\w+)\.argtypes\s*=\s*\[(.*?)\]", text, flags=re.S
    ):
        decls.setdefault(name, {})["params"] = [
            _py_kind(t) for t in _split_top(raw)
        ]
    return decls


def _compatible(py: str, nat: str) -> bool:
    if py == nat:
        return True
    wild = {"ptr:any"}  # nullable/void pointers cross as c_void_p
    if py in wild and nat.startswith(("ptr:", "funcptr")):
        return True
    if nat in wild and py.startswith(("ptr:", "funcptr")):
        return True
    # ctypes strings / raw byte buffers
    if {py, nat} <= {"ptr:char", "ptr:uint8"}:
        return True
    return False


def _struct_fields_native(text: str, name: str) -> list[str]:
    m = re.search(r"struct\s+%s\s*\{(.*?)\};" % name, text, flags=re.S)
    if not m:
        return []
    out = []
    for line in m.group(1).split(";"):
        line = line.strip()
        if not line:
            continue
        toks = line.split()
        base = toks[0]
        for fname in re.findall(r"(\w+)\s*(?:,|$)", " ".join(toks[1:])):
            out.append(_NATIVE_RET.get(base, base))
    return out


def _struct_fields_py(text: str, name: str) -> list[str]:
    m = re.search(
        r"class %s\(ctypes\.Structure\):\s*_fields_\s*=\s*\[(.*?)\]"
        % name,
        text,
        flags=re.S,
    )
    if not m:
        return []
    return [
        _py_kind("ctypes." + t)
        for t in re.findall(r'\(\s*"\w+"\s*,\s*ctypes\.(c_\w+)\s*\)',
                            m.group(0))
    ]


def run(repo: pathlib.Path) -> list[str]:
    findings: list[str] = []
    nat_text = L.strip_c_comments(
        L.read(repo, "native/stengine.cpp")
    ) + L.strip_c_comments(L.read(repo, "native/sttransport.cpp"))
    nat = native_functions(nat_text)
    py_sources = {
        "comm/engine.py": L.strip_py_comments(
            L.read(repo, "shared_tensor_tpu/comm/engine.py")
        ),
        "comm/transport.py": L.strip_py_comments(
            L.read(repo, "shared_tensor_tpu/comm/transport.py")
        ),
        # r17: the shard plane's ctypes surface (st_shard_*/st_slice_*)
        # — the st_shard_counters out14 widening class is checked by the
        # same outN rule as st_engine_counters
        "shard/engine_lane.py": L.strip_py_comments(
            L.read(repo, "shared_tensor_tpu/shard/engine_lane.py")
        ),
    }
    py: dict[str, dict] = {}
    py_file: dict[str, str] = {}
    for fname, text in py_sources.items():
        for name, decl in py_declarations(text).items():
            py.setdefault(name, {}).update(decl)
            py_file[name] = fname

    if len(nat) < 20:
        findings.append(
            f"parse floor: only {len(nat)} native st_* definitions found "
            f"(pattern rot?)"
        )
    if len(py) < 20:
        findings.append(
            f"parse floor: only {len(py)} ctypes declarations found "
            f"(pattern rot?)"
        )

    for name in sorted(py):
        where = py_file.get(name, "?")
        if name not in nat:
            findings.append(
                f"{where} declares {name} but no native definition exists"
            )
            continue
        pd, nd = py[name], nat[name]
        if "params" in pd:
            nparams = [k for k, _ in nd["params"]]
            if len(pd["params"]) != len(nparams):
                findings.append(
                    f"{name}: argtypes count {len(pd['params'])} != native "
                    f"parameter count {len(nparams)} "
                    f"({where} vs native declaration)"
                )
            else:
                for i, (pk, nk) in enumerate(zip(pd["params"], nparams)):
                    if not _compatible(pk, nk):
                        findings.append(
                            f"{name}: param {i} type mismatch — ctypes "
                            f"{pk} vs native {nk} ({where})"
                        )
        if "ret" in pd and not _compatible(pd["ret"], nd["ret"]):
            findings.append(
                f"{name}: restype {pd['ret']} vs native return "
                f"{nd['ret']} ({where})"
            )

    # ---- out-array widths (the st_engine_counters widening class) --------
    for name, nd in sorted(nat.items()):
        for kind, pname in nd["params"]:
            m = re.match(r"out(\d+)$", pname)
            if not m:
                continue
            width = int(m.group(1))
            idxs = [
                int(i)
                for i in re.findall(r"\b%s\[(\d+)\]" % pname, nd["body"])
            ]
            if idxs and max(idxs) != width - 1:
                findings.append(
                    f"{name}: native body writes {pname}[{max(idxs)}] but "
                    f"the parameter name promises exactly {width} slots"
                )
            # every python allocation feeding this call must hold width
            for fname, text in py_sources.items():
                for alloc in re.findall(
                    r"(?:np\.(?:zeros|empty)\(\s*(\d+)|"
                    r"\(ctypes\.c_uint64 \* (\d+)\)\(\))"
                    r"(?:(?!def )[\s\S]){0,400}?lib\.%s\(" % name,
                    text,
                ):
                    n = int(alloc[0] or alloc[1])
                    if n != width:
                        findings.append(
                            f"{name}: {fname} allocates a {n}-slot buffer "
                            f"for the native {width}-slot {pname}"
                        )
    # engine.py's _counters buffer feeds st_engine_counters through a
    # helper; check its documented consumer indices stay in range
    eng = py_sources["comm/engine.py"]
    m = re.search(r"np\.zeros\((\d+), np\.uint64\)\s*\n\s*if self\._h:"
                  r"\s*\n\s*self\._lib\.st_engine_counters", eng)
    if m and "st_engine_counters" in nat:
        width = int(m.group(1))
        cidx = [int(i) for i in re.findall(r"\bc\[(\d+)\]", eng)]
        if cidx and max(cidx) >= width:
            findings.append(
                f"engine.py indexes c[{max(cidx)}] of the "
                f"{width}-slot counter snapshot"
            )

    # ---- reverse presence: the r14 shm ABI family ------------------------
    # The original lint only walks Python -> native (an argtypes list with
    # no native definition). The shm lane added native entry points whose
    # ONLY caller is the negotiation path in peer.py — a native shm
    # function that silently loses its ctypes declaration (or gets
    # renamed on one side) would turn the whole lane into permanent
    # TCP-fallback with no red anywhere. Families listed here must be
    # declared on BOTH sides.
    _BIDIRECTIONAL_FAMILIES = ("st_node_shm_",)
    for name in sorted(nat):
        if name.startswith(_BIDIRECTIONAL_FAMILIES) and name not in py:
            findings.append(
                f"{name}: native definition exists but no ctypes "
                f"declaration does — the shm lane would silently never "
                f"negotiate (bidirectional-family rule)"
            )
    if not any(n.startswith("st_node_shm_") for n in nat):
        findings.append(
            "parse floor: no native st_node_shm_* definitions found "
            "(pattern rot, or the r14 lane ABI was removed?)"
        )

    # ---- r16 shard-tier queue-depth twin declaration ---------------------
    # ShardNode's FWD pump keeps control-traffic headroom in the per-link
    # transport send queue (_queue_room: pumps stop at depth - keep so
    # cumulative ACKs and shard control messages always have slots — a
    # pump that races them for the last slot starves the very ACKs that
    # drain its own ledger). The depth is declared THREE times: the
    # native config default (sttransport.cpp), TransportNode's python
    # default, and shard/node.py's QUEUE_DEPTH. A silent drift either
    # starves the pump (python > native) or re-opens the ACK-starvation
    # wedge (python < native).
    shard_text = L.strip_py_comments(
        L.read(repo, "shared_tensor_tpu/shard/node.py")
    )
    depths = {}
    m = re.search(r"int32_t\s+queue_depth\s*=\s*(\d+)\s*;", nat_text)
    if m:
        depths["sttransport.cpp queue_depth"] = int(m.group(1))
    m = re.search(
        r"queue_depth:\s*int\s*=\s*(\d+)", py_sources["comm/transport.py"]
    )
    if m:
        depths["transport.py queue_depth default"] = int(m.group(1))
    m = re.search(r"^QUEUE_DEPTH\s*=\s*(\d+)", shard_text, re.M)
    if m:
        depths["shard/node.py QUEUE_DEPTH"] = int(m.group(1))
    if len(depths) != 3:
        findings.append(
            f"queue-depth twin declaration: only {sorted(depths)} parsed "
            f"(pattern rot?)"
        )
    elif len(set(depths.values())) != 1:
        findings.append(
            f"queue-depth drift across the shard ABI: {depths} — the FWD "
            f"pump's control-traffic headroom math desyncs from the "
            f"native send queue"
        )

    # ---- ctypes.Structure mirrors ----------------------------------------
    t_nat = L.strip_c_comments(L.read(repo, "native/sttransport.cpp"))
    t_py = py_sources["comm/transport.py"]
    for sname in ("StConfigC", "StEventC", "StStatsC"):
        nf = _struct_fields_native(t_nat, sname)
        pf = _struct_fields_py(t_py, "_" + sname)
        if not nf or not pf:
            findings.append(f"{sname}: struct parse failed (pattern rot?)")
            continue
        if nf != pf:
            findings.append(
                f"{sname}: field layout drifted — native {nf} vs "
                f"ctypes {pf}"
            )
    return findings


if __name__ == "__main__":
    L.main(run)
