#!/usr/bin/env python
"""lint_spec: spec/mutation registry drift lint (the r09 schema-lint
discipline applied to protospec).

The protospec red-team story rests on two registries staying in sync:

- the CODE registry: every ``Spec`` subclass in
  ``tools/protospec/spec_*.py`` declares ``name`` and a ``mutations``
  dict — the set of seeded historical bugs the checker must re-find;
- the DOCUMENTED registry: the committed ``MODEL_r*.json`` artifacts
  and README's "Protocol specs & model checking" table cite mutations
  as ``spec.mutation`` tokens.

Drift in either direction is a lie: a PHANTOM mutation (cited in the
artifact/README but absent from code) claims red-team coverage that no
longer exists; an UNDOCUMENTED mutation (coded but never cited in
README) is invisible to the reader deciding whether a bug class is
covered. Both are findings.

Like every lint here, this PARSES source (ast) — it never imports the
modules under test, so a broken spec file is a finding, not a crash
somewhere else. ``dict(Base.mutations, extra=...)`` extension (the
spec_shard idiom) is resolved statically through same-module bases.
"""

from __future__ import annotations

import ast
import json
import pathlib
import re

import _lintlib


def _class_mutations(tree: ast.Module) -> dict[str, tuple]:
    """class name -> (spec_name | None, own mutation names, base class
    names) for every class in one spec module."""
    out: dict[str, tuple] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        spec_name = None
        muts: set[str] | None = None
        mut_base: str | None = None
        for stmt in node.body:
            if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1):
                continue
            tgt = stmt.targets[0]
            if not isinstance(tgt, ast.Name):
                continue
            if tgt.id == "name" and isinstance(stmt.value, ast.Constant):
                if isinstance(stmt.value.value, str):
                    spec_name = stmt.value.value
            if tgt.id == "mutations":
                v = stmt.value
                if isinstance(v, ast.Dict):
                    muts = {
                        k.value
                        for k in v.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)
                    }
                elif (
                    isinstance(v, ast.Call)
                    and isinstance(v.func, ast.Name)
                    and v.func.id == "dict"
                    and len(v.args) == 1
                    and isinstance(v.args[0], ast.Attribute)
                    and v.args[0].attr == "mutations"
                    and isinstance(v.args[0].value, ast.Name)
                ):
                    # dict(Base.mutations, extra=..., ...) — the
                    # extension idiom; base resolved after the pass
                    mut_base = v.args[0].value.id
                    muts = {kw.arg for kw in v.keywords if kw.arg}
        bases = [b.id for b in node.bases if isinstance(b, ast.Name)]
        if mut_base:
            bases = [mut_base] + bases
        out[node.name] = (spec_name, muts, bases)
    return out


def _coded_registry(repo: pathlib.Path) -> tuple[dict[str, set], list[str]]:
    """spec name -> mutation names, from ast over spec_*.py."""
    findings: list[str] = []
    registry: dict[str, set] = {}
    for path in sorted((repo / "tools" / "protospec").glob("spec_*.py")):
        try:
            tree = ast.parse(path.read_text(errors="replace"), filename=str(path))
        except SyntaxError as exc:
            findings.append(f"{path.name}: unparseable spec module ({exc})")
            continue
        classes = _class_mutations(tree)

        def resolve(cls: str, seen: frozenset = frozenset()) -> set:
            if cls not in classes or cls in seen:
                return set()
            spec_name, muts, bases = classes[cls]
            inherited: set = set()
            for b in bases:
                inherited |= resolve(b, seen | {cls})
            return inherited | (muts or set())

        for cls, (spec_name, muts, bases) in classes.items():
            if spec_name is None:
                continue  # acceptor / helper class, not a spec
            registry[spec_name] = resolve(cls)
    return registry, findings


_TOKEN = re.compile(r"`([a-z][a-z0-9_]*)\.([a-z][a-z0-9_]*)`")


def _cited(repo: pathlib.Path, spec_names: set) -> dict[str, set]:
    """``spec.mutation`` token -> the sources citing it, from every
    committed MODEL_r*.json plus README's backticked tokens (filtered
    to known spec names — `obs.recorder` is a module path, not a
    mutation)."""
    cites: dict[str, set] = {}
    for path in sorted(repo.glob("MODEL_r*.json")):
        try:
            doc = json.loads(path.read_text(errors="replace"))
        except (json.JSONDecodeError, UnicodeDecodeError) as exc:
            cites.setdefault(f"!{path.name}", set()).add(str(exc))
            continue
        for tok in doc.get("mutations", {}):
            cites.setdefault(tok, set()).add(path.name)
    readme = repo / "README.md"
    if readme.is_file():
        for m in _TOKEN.finditer(readme.read_text(errors="replace")):
            if m.group(1) in spec_names:
                cites.setdefault(f"{m.group(1)}.{m.group(2)}", set()).add(
                    "README.md"
                )
    return cites


def run(repo: str | pathlib.Path = ".") -> list[str]:
    repo = pathlib.Path(repo)
    registry, findings = _coded_registry(repo)
    if not registry:
        findings.append("no spec modules found under tools/protospec/")
        return findings
    coded = {
        f"{spec}.{mut}" for spec, muts in registry.items() for mut in muts
    }
    cites = _cited(repo, set(registry))
    for tok in sorted(cites):
        if tok.startswith("!"):
            findings.append(f"{tok[1:]}: unreadable MODEL artifact")
            continue
        spec, _, mut = tok.partition(".")
        if spec not in registry:
            findings.append(
                f"phantom spec: {tok} cited in {sorted(cites[tok])} but "
                f"no spec named {spec!r} exists in tools/protospec/"
            )
        elif tok not in coded:
            findings.append(
                f"phantom mutation: {tok} cited in {sorted(cites[tok])} "
                f"but {spec!r} codes no such mutation "
                f"(have {sorted(registry[spec])})"
            )
    documented = {t for t, srcs in cites.items() if "README.md" in srcs}
    for tok in sorted(coded - documented):
        findings.append(
            f"undocumented mutation: {tok} is coded in tools/protospec/ "
            f"but never cited in README.md's spec table — the red-team "
            f"coverage a reader can see must match the code"
        )
    return findings


if __name__ == "__main__":
    _lintlib.main(run)
