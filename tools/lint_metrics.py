#!/usr/bin/env python
"""Metric-name lint: every emitted st_* name is documented; legacy alias
keys stay dead.

Two contracts, both red gates:

1. (the r09 schema-lint, promoted from test-only to a suite gate) every
   ``st_*`` string literal in the Python package AND the native sources
   must be a documented obs/schema.py SCHEMA name — a new metric cannot
   ship undocumented.
2. (r13) the r08 legacy nested ``peer.metrics()`` alias surface was
   removed after overstaying its "one release" by three; this lint
   forbids the alias machinery (``DEPRECATED_ALIASES``/``canonicalize``)
   and the legacy metric keys from reappearing as dict keys in the
   delivery-metrics modules. Resurrecting a parallel non-schema namespace
   should fail CI by name, not slip in as "compat".
"""

from __future__ import annotations

import pathlib
import re

if __package__ in (None, ""):
    import _lintlib as L
else:
    from . import _lintlib as L

#: Non-metric st_* literals, each with a reason. Kept honest by the
#: staleness check below: every entry must still occur in the scan.
ALLOWED_NON_METRICS: dict[str, str] = {
    "st_trace": "Chrome trace_event category tag (trace_export.py)",
}

#: The removed r08 legacy alias keys (and the machinery that served
#: them). Any of these reappearing as a metrics dict key in the modules
#: below is a finding.
BANNED_TOKENS = ("DEPRECATED_ALIASES", "canonicalize")
BANNED_LEGACY_KEYS = (
    "frames_out", "frames_in", "updates", "msgs_out", "msgs_in",
    "inflight_msgs", "wire_msgs_out", "wire_msgs_in", "residual_rms",
    "delivery",
)
#: Modules whose dict-literal keys are metric names (the old nested shape
#: lived here). Other modules use these words freely as attributes.
LEGACY_KEY_SCOPE = ("shared_tensor_tpu/comm/peer.py",)


def run(repo: pathlib.Path) -> list[str]:
    findings: list[str] = []
    pat = re.compile(r'["\'](st_[a-z0-9_]+)["\']')
    sources = sorted((repo / "shared_tensor_tpu").rglob("*.py")) + [
        p
        for ext in ("*.c", "*.cpp", "*.h")
        for p in sorted((repo / "native").glob(ext))
    ]
    if not sources:
        return ["scan found no sources (wrong --repo?)"]
    schema_text = L.read(repo, "shared_tensor_tpu/obs/schema.py")
    documented = set(pat.findall(schema_text))
    if len(documented) < 20:
        findings.append(
            f"parse floor: only {len(documented)} documented st_* names in "
            f"obs/schema.py (pattern rot?)"
        )
    emitted: dict[str, set[str]] = {}
    for path in sources:
        rel = str(path.relative_to(repo))
        if rel == "shared_tensor_tpu/obs/schema.py":
            continue
        for name in pat.findall(path.read_text(errors="replace")):
            emitted.setdefault(name, set()).add(rel)
    if not emitted:
        findings.append("scan found no st_* literals (pattern rot?)")
    for name in sorted(emitted):
        if name in documented or name in ALLOWED_NON_METRICS:
            continue
        findings.append(
            f"undocumented metric name {name!r} emitted in "
            f"{sorted(emitted[name])} — add a SCHEMA row or an "
            f"ALLOWED_NON_METRICS entry with a reason"
        )
    for stale in sorted(set(ALLOWED_NON_METRICS) - set(emitted)):
        findings.append(f"allowlist entry {stale!r} is no longer emitted "
                        f"anywhere — remove it")

    # legacy alias surface must stay dead
    for rel in ("shared_tensor_tpu/obs/schema.py",) + LEGACY_KEY_SCOPE:
        text = L.strip_py_comments(L.read(repo, rel))
        for tok in BANNED_TOKENS:
            if re.search(r"\b%s\b" % tok, text):
                findings.append(
                    f"{rel}: legacy alias machinery {tok!r} reintroduced "
                    f"(removed r13 — the canonical schema is the only "
                    f"metrics surface)"
                )
    for rel in LEGACY_KEY_SCOPE:
        text = L.strip_py_comments(L.read(repo, rel))
        for key in BANNED_LEGACY_KEYS:
            if re.search(r'["\']%s["\']\s*:' % key, text):
                findings.append(
                    f"{rel}: legacy metrics key {key!r} used as a dict "
                    f"key again (removed r13 — use the st_* schema name)"
                )
    return findings


if __name__ == "__main__":
    L.main(run)
