#!/usr/bin/env python
"""Metric-name lint: every emitted st_* name is documented; legacy alias
keys stay dead.

Three contracts, all red gates:

1. (the r09 schema-lint, promoted from test-only to a suite gate) every
   ``st_*`` string literal in the Python package AND the native sources
   must be a documented obs/schema.py SCHEMA name — a new metric cannot
   ship undocumented.
2. (r13) the r08 legacy nested ``peer.metrics()`` alias surface was
   removed after overstaying its "one release" by three; this lint
   forbids the alias machinery (``DEPRECATED_ALIASES``/``canonicalize``)
   and the legacy metric keys from reappearing as dict keys in the
   delivery-metrics modules. Resurrecting a parallel non-schema namespace
   should fail CI by name, not slip in as "compat".
3. (r15) DYNAMICALLY-BUILT ``st_*`` names — f-strings with a placeholder
   inside the name, ``%``/``.format`` on an st_ literal, or string
   concatenation extending an st_ prefix — evade contract 1's literal
   grep entirely: the emitted name never appears in any source line, so
   an undocumented metric ships invisibly. Base names must be complete
   literals (labels are appended via schema.link_key, which this lint
   does not flag — the base literal is intact); any construction site
   that builds the NAME itself is a finding unless allowlisted with a
   reason.
"""

from __future__ import annotations

import pathlib
import re

if __package__ in (None, ""):
    import _lintlib as L
else:
    from . import _lintlib as L

#: Non-metric st_* literals, each with a reason. Kept honest by the
#: staleness check below: every entry must still occur in the scan.
ALLOWED_NON_METRICS: dict[str, str] = {
    "st_trace": "Chrome trace_event category tag (trace_export.py)",
}

#: Dynamic-construction sites that are NOT metric names, keyed by the
#: st_ prefix of the literal involved, each with a reason. Kept honest
#: the same way: a stale entry fails the lint.
ALLOWED_DYNAMIC: dict[str, str] = {
    "st_postmortem_": "postmortem FILENAME prefix (obs/recorder.py), "
                      "not a metric name",
}

#: Construction patterns that build an st_* NAME at runtime — each
#: evades the literal grep above (the f-string/format/concat result
#: never appears verbatim in source). The captured group is the st_
#: prefix used for the allowlist lookup.
DYNAMIC_PATTERNS = (
    # f"st_foo_{x}" / f'st_foo_{x}...' — placeholder inside the name
    (re.compile(r'''[fF]["'](st_[a-zA-Z0-9_]*)\{'''),
     "f-string with a placeholder inside the st_ name"),
    # "st_foo_%s" % ... / "st_foo_{}".format(...)
    (re.compile(r'''["'](st_[a-zA-Z0-9_]*)%[sd]'''),
     "%-formatting inside the st_ name"),
    (re.compile(r'''["'](st_[a-zA-Z0-9_]*)\{?\}?["']\s*\.\s*format\('''),
     ".format() on an st_ literal"),
    # "st_foo_" + x — an st_ literal extended on its right (the
    # x + "st_foo" direction produces a name whose st_ part IS the
    # literal, which the schema scan above already sees whole)
    (re.compile(r'''["'](st_[a-zA-Z0-9_]*)["']\s*\+'''),
     "concatenation extending an st_ literal"),
)

#: The removed r08 legacy alias keys (and the machinery that served
#: them). Any of these reappearing as a metrics dict key in the modules
#: below is a finding.
BANNED_TOKENS = ("DEPRECATED_ALIASES", "canonicalize")
BANNED_LEGACY_KEYS = (
    "frames_out", "frames_in", "updates", "msgs_out", "msgs_in",
    "inflight_msgs", "wire_msgs_out", "wire_msgs_in", "residual_rms",
    "delivery",
)
#: Modules whose dict-literal keys are metric names (the old nested shape
#: lived here). Other modules use these words freely as attributes.
LEGACY_KEY_SCOPE = ("shared_tensor_tpu/comm/peer.py",)


def run(repo: pathlib.Path) -> list[str]:
    findings: list[str] = []
    pat = re.compile(r'["\'](st_[a-z0-9_]+)["\']')
    sources = sorted((repo / "shared_tensor_tpu").rglob("*.py")) + [
        p
        for ext in ("*.c", "*.cpp", "*.h")
        for p in sorted((repo / "native").glob(ext))
    ]
    if not sources:
        return ["scan found no sources (wrong --repo?)"]
    schema_text = L.read(repo, "shared_tensor_tpu/obs/schema.py")
    documented = set(pat.findall(schema_text))
    if len(documented) < 20:
        findings.append(
            f"parse floor: only {len(documented)} documented st_* names in "
            f"obs/schema.py (pattern rot?)"
        )
    emitted: dict[str, set[str]] = {}
    for path in sources:
        rel = str(path.relative_to(repo))
        if rel == "shared_tensor_tpu/obs/schema.py":
            continue
        for name in pat.findall(path.read_text(errors="replace")):
            emitted.setdefault(name, set()).add(rel)
    if not emitted:
        findings.append("scan found no st_* literals (pattern rot?)")
    for name in sorted(emitted):
        if name in documented or name in ALLOWED_NON_METRICS:
            continue
        findings.append(
            f"undocumented metric name {name!r} emitted in "
            f"{sorted(emitted[name])} — add a SCHEMA row or an "
            f"ALLOWED_NON_METRICS entry with a reason"
        )
    for stale in sorted(set(ALLOWED_NON_METRICS) - set(emitted)):
        findings.append(f"allowlist entry {stale!r} is no longer emitted "
                        f"anywhere — remove it")

    # contract 3: dynamically-built st_* names (python sources only —
    # the native tier has no runtime string building on metric names)
    dynamic_hits: set[str] = set()
    for path in sources:
        if path.suffix != ".py":
            continue
        rel = str(path.relative_to(repo))
        text = L.strip_py_comments(path.read_text(errors="replace"))
        for pat, what in DYNAMIC_PATTERNS:
            for m in pat.finditer(text):
                prefix = m.group(1)
                dynamic_hits.add(prefix)
                if prefix in ALLOWED_DYNAMIC:
                    continue
                findings.append(
                    f"{rel}: dynamically-built metric name "
                    f"{prefix + '...'!r} ({what}) — the literal grep "
                    f"cannot see the emitted name, so it ships "
                    f"undocumented; build the full name as a literal "
                    f"(labels go through schema.link_key) or add an "
                    f"ALLOWED_DYNAMIC entry with a reason"
                )
    for stale in sorted(set(ALLOWED_DYNAMIC) - dynamic_hits):
        findings.append(
            f"ALLOWED_DYNAMIC entry {stale!r} no longer matches any "
            f"construction site — remove it"
        )

    # legacy alias surface must stay dead
    for rel in ("shared_tensor_tpu/obs/schema.py",) + LEGACY_KEY_SCOPE:
        text = L.strip_py_comments(L.read(repo, rel))
        for tok in BANNED_TOKENS:
            if re.search(r"\b%s\b" % tok, text):
                findings.append(
                    f"{rel}: legacy alias machinery {tok!r} reintroduced "
                    f"(removed r13 — the canonical schema is the only "
                    f"metrics surface)"
                )
    for rel in LEGACY_KEY_SCOPE:
        text = L.strip_py_comments(L.read(repo, rel))
        for key in BANNED_LEGACY_KEYS:
            if re.search(r'["\']%s["\']\s*:' % key, text):
                findings.append(
                    f"{rel}: legacy metrics key {key!r} used as a dict "
                    f"key again (removed r13 — use the st_* schema name)"
                )
    return findings


if __name__ == "__main__":
    L.main(run)
