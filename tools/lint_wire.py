#!/usr/bin/env python
"""Wire-kind lint: comm/wire.py constants vs the native receive switch.

The native engine re-declares the wire message kinds and header sizes as
C constants (stengine.cpp ``kData``/``kAck``/... , ``kDataHdrV1``/...),
and the transport's fault injector hardcodes the data-kind set its wire
boundary recognizes. A drift between any of these and comm/wire.py is a
SILENT interop break (a renumbered kind decodes as garbage or as a
different message class) — exactly the mismatch class this lint makes a
red gate instead of a debugging session.

Checked, by name:
  - every mapped k* constant in stengine.cpp equals its wire.py twin —
    r14 included: the aligned v3 header size (kHdrV3 / HDR_V3);
  - every mapped k* constant in sttransport.cpp equals its wire.py twin
    (r14: the SWITCH marker length kShmSwitchLen / SHM_SWITCH_LEN and
    the sendmmsg batch cap kCoalesce / SENDMMSG_BATCH);
  - the SHM hello flag bit is identical in wire.py (SHM_FLAG) and
    compat.py (SYNC_FLAG_SHM) — the import-time assert enforces this at
    runtime, but a seeded-violation tree never imports, so the lint
    re-checks it statically;
  - sttransport.cpp's ``is_data`` kind-literal set == {DATA, BURST, RDATA};
  - stengine.cpp's RDATA header-size ternary == (RDATA_HDR_T, RDATA_HDR).
"""

from __future__ import annotations

import pathlib
import re

if __package__ in (None, ""):
    import _lintlib as L
else:
    from . import _lintlib as L

#: native constant (stengine.cpp) -> python constant (comm/wire.py).
#: Adding a new shared kind means adding a row here — the parse-floor
#: check below fails if the native constant exists unmapped.
NATIVE_TO_WIRE = {
    "kData": "DATA",
    "kAck": "ACK",
    "kBurst": "BURST",
    "kFresh": "FRESH",
    "kRData": "RDATA",
    "kDataHdrV1": "DATA_HDR",
    "kBurstHdrV1": "BURST_HDR",
    "kTraceBytes": "TRACE_BYTES",
    # r14: ONE aligned header for v3 DATA/BURST — a size drift means
    # every exact-length framing test on the other tier rejects the
    # message as undecodable (the burst_wire_bytes failure class)
    "kHdrV3": "HDR_V3",
    # r17: the engine-tier shard plane speaks wire.FWD natively — a kind
    # or header-size drift desyncs the verbatim-relay restamp offset and
    # every decode_fwd length check between the two lanes
    "kFwd": "FWD",
    "kFwdHdr": "FWD_HDR",
}

#: sttransport.cpp constants with wire.py twins (r14 satellite): the
#: unstriped lane's in-stream SWITCH marker length and the sendmmsg
#: batch cap. Same parse, different file.
TRANSPORT_TO_WIRE = {
    "kShmSwitchLen": "SHM_SWITCH_LEN",
    "kCoalesce": "SENDMMSG_BATCH",
}


def _native_constants(text: str) -> dict[str, int]:
    """Every ``constexpr <int type> kName = <literal>;`` (incl. multi-
    declarator lines like ``kDataHdrV1 = 5, kBurstHdrV1 = 6;``)."""
    out: dict[str, int] = {}
    for m in re.finditer(
        r"constexpr\s+(?:uint8_t|uint32_t|uint64_t|size_t|int)\s+([^;]+);",
        text,
    ):
        for name, val in re.findall(r"(k\w+)\s*=\s*(0x[0-9a-fA-F]+|\d+)",
                                    m.group(1)):
            out[name] = L.c_int(val)
    return out


def _py_constants(text: str) -> dict[str, int]:
    out: dict[str, int] = {}
    for name, val in re.findall(
        r"(?m)^([A-Z][A-Z0-9_]*)\s*=\s*(0x[0-9a-fA-F]+|\d+)\s*$", text
    ):
        # first binding wins (wire.py defines each exactly once)
        out.setdefault(name, L.c_int(val))
    # one resolution pass for derived constants (DATA_HDR_T = DATA_HDR +
    # TRACE_BYTES and friends): sums of already-known names/literals
    for name, expr in re.findall(
        r"(?m)^([A-Z][A-Z0-9_]*)\s*=\s*([A-Z0-9_+ ]+?)\s*$", text
    ):
        if name in out:
            continue
        total = 0
        for term in expr.split("+"):
            term = term.strip()
            if term.isdigit():
                total += int(term)
            elif term in out:
                total += out[term]
            else:
                total = None
                break
        if total is not None:
            out[name] = total
    return out


def run(repo: pathlib.Path) -> list[str]:
    findings: list[str] = []
    engine = L.strip_c_comments(L.read(repo, "native/stengine.cpp"))
    transport = L.strip_c_comments(L.read(repo, "native/sttransport.cpp"))
    wire = L.strip_py_comments(
        L.read(repo, "shared_tensor_tpu/comm/wire.py")
    )
    compat = L.strip_py_comments(L.read(repo, "shared_tensor_tpu/compat.py"))
    nat = _native_constants(engine)
    tnat = _native_constants(transport)
    py = _py_constants(wire)
    pycompat = _py_constants(compat)

    if len(nat) < 5:
        findings.append(
            f"parse floor: only {len(nat)} k* constants found in "
            f"stengine.cpp (pattern rot?)"
        )
    if len(tnat) < 2:
        findings.append(
            f"parse floor: only {len(tnat)} k* constants found in "
            f"sttransport.cpp (pattern rot?)"
        )
    for src, table, consts in (
        ("stengine.cpp", NATIVE_TO_WIRE, nat),
        ("sttransport.cpp", TRANSPORT_TO_WIRE, tnat),
    ):
        for cname, pyname in table.items():
            if cname not in consts:
                findings.append(f"{src} no longer defines {cname} "
                                f"(update the mapping if renamed)")
                continue
            if pyname not in py:
                findings.append(f"comm/wire.py no longer defines {pyname}")
                continue
            if consts[cname] != py[pyname]:
                findings.append(
                    f"kind/size mismatch: {src} {cname}={consts[cname]} "
                    f"vs wire.py {pyname}={py[pyname]}"
                )

    # the r14 shm hello flag bit is declared twice by necessity (compat
    # cannot be imported from wire — the import cycle note at both
    # sites); the runtime assert only fires on import, which a seeded
    # lint tree never does, so the tie is re-checked statically here
    if py.get("SHM_FLAG") != pycompat.get("SYNC_FLAG_SHM"):
        findings.append(
            f"SHM hello flag drift: wire.py SHM_FLAG={py.get('SHM_FLAG')} "
            f"vs compat.py SYNC_FLAG_SHM={pycompat.get('SYNC_FLAG_SHM')} — "
            f"every shm negotiation would silently fall back to TCP"
        )

    # r16: the shard hello flag bit has the same twin-declaration shape
    # (wire.SHARD_FLAG gates the SYNC claim tail; compat.SYNC_FLAG_SHARD
    # is the documented capability bit) — a drift would silently degrade
    # every sharded join to the classic full-replica fallback
    if py.get("SHARD_FLAG") != pycompat.get("SYNC_FLAG_SHARD"):
        findings.append(
            f"shard hello flag drift: wire.py SHARD_FLAG="
            f"{py.get('SHARD_FLAG')} vs compat.py SYNC_FLAG_SHARD="
            f"{pycompat.get('SYNC_FLAG_SHARD')} — every sharded join "
            f"would silently fall back to the full-replica protocol"
        )

    # r16: the FWD header size must equal its fixed layout (kind byte +
    # five u32 fields — wire.py _FWD_FMT); a drifted constant desyncs
    # every decode_fwd length check and the fwd_restamp offset discipline
    if py.get("FWD_HDR") != 21:
        findings.append(
            f"wire.py FWD_HDR={py.get('FWD_HDR')} != 21 (kind + 5 u32 "
            f"fields) — decode_fwd/fwd_restamp offsets desync"
        )

    # the transport fault injector's data-kind set (link_sender_loop
    # ``is_data``): the literals it matches must be exactly the data kinds
    # wire.py defines — a new data kind that is not added there silently
    # escapes chaos coverage at the native wire boundary. r16 adds FWD:
    # a sharded cluster's whole data plane rides FWD frames, so the set
    # now has four members.
    m = re.search(r"bool\s+is_data\s*=(.*?);", transport, flags=re.S)
    if not m:
        findings.append("sttransport.cpp: is_data expression not found "
                        "(pattern rot?)")
    else:
        lits = {int(v) for v in re.findall(r"kind0\s*==\s*(\d+)", m.group(1))}
        want = {
            py.get("DATA"), py.get("BURST"), py.get("RDATA"), py.get("FWD"),
        }
        if lits != want:
            findings.append(
                f"sttransport.cpp is_data kind set {sorted(lits)} != "
                f"wire.py data kinds {sorted(x for x in want if x is not None)}"
            )

    # the ranged-subscriber RDATA header ternary in the engine sender must
    # match wire.py's RDATA_HDR_T/RDATA_HDR pair
    m = re.search(r"hdr\s*=\s*e->trace_wire\s*\?\s*(\d+)\s*:\s*(\d+)", engine)
    if not m:
        findings.append("stengine.cpp: RDATA header ternary not found "
                        "(pattern rot?)")
    else:
        t, v1 = int(m.group(1)), int(m.group(2))
        if (t, v1) != (py.get("RDATA_HDR_T"), py.get("RDATA_HDR")):
            findings.append(
                f"RDATA header sizes: stengine.cpp ({t}, {v1}) != wire.py "
                f"(RDATA_HDR_T={py.get('RDATA_HDR_T')}, "
                f"RDATA_HDR={py.get('RDATA_HDR')})"
            )
    return findings


if __name__ == "__main__":
    L.main(run)
