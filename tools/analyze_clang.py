#!/usr/bin/env python
"""Hermetic clang front-end for the native -Wthread-safety gate.

The r13 lock-annotation work (native/st_annotations.h: ST_GUARDED_BY,
StMutex/StLockGuard) targets clang's -Wthread-safety analysis, but the
image ships gcc only — ``make -C native analyze`` had NEVER actually
executed, so the annotations were written blind. This tool closes that
debt without a clang driver binary: the pip-provisioned ``libclang``
wheel (clang.cindex) is a full C/C++ front-end, and -Wthread-safety is
a front-end analysis — parsing the TU is running the gate.

Two impedance mismatches vs. a real clang driver, both handled here:

- the wheel ships no builtin headers and no driver to locate the
  system C++ ones, so the include search list is lifted verbatim from
  the gcc driver (``g++ -E -v``) plus gcc's builtin include dir;
- gcc's SIMD intrinsics headers (emmintrin/immintrin) use gcc-only
  builtins clang cannot parse, so the TUs are parsed with
  ``-DST_ANALYZE_NO_SIMD`` — the native sources gate their intrinsics
  includes/bodies on it and the scalar reference paths get analyzed
  (the thread-safety annotations the gate exists for are not in the
  SIMD bodies).

``run(repo)`` returns findings (any clang diagnostic of severity
warning or above in repo sources — the gate is -Werror in spirit);
``--probe`` exits 0/1 on whether the front-end is usable at all, so
suite_load.sh can stay SKIPPED-no-clang honestly when it is not.
"""

from __future__ import annotations

import pathlib
import subprocess
import sys

import _lintlib

#: TUs the Makefile's analyze target covers, with language mode.
_UNITS = [
    ("native/sttransport.cpp", "c++"),
    ("native/stengine.cpp", "c++"),
    ("native/stcodec.c", "c"),
]

_WARN_FLAGS = ["-Wall", "-Wextra", "-Wthread-safety"]


def _driver_includes(lang: str) -> list[str]:
    """The gcc driver's include search list for ``lang`` (c or c++) —
    libclang has no driver, so borrow gcc's."""
    driver = "g++" if lang == "c++" else "gcc"
    try:
        out = subprocess.run(
            [driver, "-E", "-x", lang, "-", "-v"],
            input="",
            capture_output=True,
            text=True,
            timeout=30,
        ).stderr
    except (OSError, subprocess.TimeoutExpired):
        return []
    dirs: list[str] = []
    grab = False
    for line in out.splitlines():
        if line.startswith("#include <...> search starts here"):
            grab = True
            continue
        if line.startswith("End of search list"):
            break
        if grab:
            d = line.strip().split(" ")[0]
            if pathlib.Path(d).is_dir():
                dirs.append(str(pathlib.Path(d).resolve()))
    return dirs


def _load_cindex():
    try:
        from clang import cindex  # pip "libclang" wheel
    except ImportError:
        return None
    try:
        cindex.Index.create()
    except Exception:
        return None
    return cindex


def probe() -> str | None:
    """None if the front-end is usable, else the reason it is not."""
    if _load_cindex() is None:
        return (
            "libclang front-end unavailable — provision with: "
            "python -m pip install libclang"
        )
    return None


def _parse_args(repo: pathlib.Path, lang: str) -> list[str]:
    args = ["-x", lang, "-std=c++17" if lang == "c++" else "-std=c11",
            "-pthread", "-fsyntax-only", "-DST_ANALYZE_NO_SIMD",
            "-I", str(repo / "native")]
    args += _WARN_FLAGS
    # the shim stdatomic.h must shadow gcc's (clang rejects gcc's
    # __atomic_* expansion on _Atomic lvalues)
    shim = pathlib.Path(__file__).resolve().parent / "analyze_include"
    if lang == "c" and shim.is_dir():
        args += ["-isystem", str(shim)]
    for d in _driver_includes(lang):
        args += ["-isystem", d]
    return args


def run(repo: str | pathlib.Path = ".") -> list[str]:
    repo = pathlib.Path(repo)
    cindex = _load_cindex()
    if cindex is None:
        return [
            "analyze_clang: libclang front-end unavailable "
            "(python -m pip install libclang)"
        ]
    findings: list[str] = []
    index = cindex.Index.create()
    for rel, lang in _UNITS:
        path = repo / rel
        if not path.is_file():
            findings.append(f"{rel}: missing translation unit")
            continue
        try:
            tu = index.parse(str(path), args=_parse_args(repo, lang))
        except cindex.TranslationUnitLoadError as exc:
            findings.append(f"{rel}: front-end failed to parse ({exc})")
            continue
        for d in tu.diagnostics:
            if d.severity < cindex.Diagnostic.Warning:
                continue
            loc = d.location
            where = (
                f"{loc.file.name}:{loc.line}:{loc.column}"
                if loc.file
                else rel
            )
            # system headers are the toolchain's business, not ours
            if loc.file is not None:
                f = str(pathlib.Path(loc.file.name).resolve())
                if not f.startswith(str(repo.resolve()) + "/"):
                    continue
            sev = {2: "warning", 3: "error", 4: "fatal"}.get(
                d.severity, "diag"
            )
            findings.append(f"{where}: {sev}: {d.spelling}")
    return findings


def main() -> int:
    if "--probe" in sys.argv[1:]:
        reason = probe()
        if reason:
            print(f"analyze_clang --probe: {reason}")
            return 1
        print("analyze_clang --probe: libclang front-end usable")
        return 0
    return _lintlib.main(run)


if __name__ == "__main__":
    sys.exit(main())
