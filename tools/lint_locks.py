#!/usr/bin/env python
"""Python-tier lock-discipline lint — the r13 native hierarchy's twin.

The native tier's lock order is machine-checked by clang thread-safety
annotations (st_annotations.h: Engine::mu -> add_mu/TxPool::mu ->
transport queues; leaves hold no further locks and NEVER block). The
Python tier has the same discipline by convention only — this lint
makes it a gate:

    While holding a peer/obs/core lock, code must not
      (a) perform a blocking wire operation (socket send/recv — the
          recv thread ACKs under the same locks, so a full send buffer
          held under the ledger lock deadlocks the ACK path that would
          drain it), or
      (b) call into the engine ABI (st_engine_* via the EngineTensor
          wrapper — the native side takes Engine::mu, and python-lock ->
          engine-mutex nests AGAINST the established order: the engine's
          codec threads call back up into python-side collectors that
          take these same locks).

Checked locks (attribute names of ``with self.<lock>:`` /
``with <obj>._mu:`` blocks): the peer ledger lock ``_ack_mu``, the core
state lock ``_lock``, and the obs/pool ``_mu`` family. ``_lc_api_mu``
is exempt by design — it serializes lifecycle API CALLERS across a
result wait and is documented to be held across waits (comm/peer.py).

Blocking set: ``_send_blocking`` / ``sendall`` / ``recv`` /
``recv_into`` / ``connect`` / ``accept`` (wire I/O), plus any call on
an ``_engine`` attribute (the ABI wrapper) except the documented
non-blocking reads in ENGINE_SAFE.

Nested function/lambda bodies inside a with-block are skipped: a
closure defined under a lock usually runs after it (and a closure that
doesn't is invisible to any static scope analysis — the TSan arm owns
that residue).

Like every lint here (tools/_lintlib.py): parses source text/AST only,
never imports, ``run(repo) -> list[str]``, CLI exits 1 with findings.
Red-tested on seeded violations in tests/test_static_analysis.py.
"""

from __future__ import annotations

import ast
import pathlib

if __package__ in (None, ""):
    import _lintlib as L
else:
    from . import _lintlib as L

#: lock attribute names whose critical sections must stay non-blocking
LOCK_ATTRS = frozenset({"_ack_mu", "_lock", "_mu", "_state_lock"})

#: blocking wire-operation method names (attribute position of a call)
BLOCKING = frozenset(
    {"_send_blocking", "sendall", "recv", "recv_into", "connect", "accept"}
)

#: engine-ABI wrapper methods that are documented NON-blocking reads
#: (plain field loads / out-param counter copies, no Engine::mu wait
#: that can nest against a python lock in practice): everything else on
#: an ``_engine`` attribute is treated as an ABI entry.
ENGINE_SAFE = frozenset({"is_destroyed"})

#: (file, line) sites exempted with a written reason. Kept honest: a
#: stale entry (site moved/removed) fails the lint.
ALLOWED_SITES: dict[tuple[str, int], str] = {}


def _attr_chain(node: ast.AST) -> list[str]:
    chain = []
    while isinstance(node, ast.Attribute):
        chain.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        chain.append(node.id)
    return list(reversed(chain))


class _Visitor(ast.NodeVisitor):
    def __init__(self, rel: str, findings: list[str]):
        self.rel = rel
        self.findings = findings
        self.held: list[str] = []

    # a closure body under a lock runs later (see module docstring)
    def _skip(self, node):
        held, self.held = self.held, []
        self.generic_visit(node)
        self.held = held

    def visit_FunctionDef(self, node):
        self._skip(node)

    def visit_AsyncFunctionDef(self, node):
        self._skip(node)

    def visit_Lambda(self, node):
        self._skip(node)

    def visit_With(self, node):
        locks = []
        for item in node.items:
            e = item.context_expr
            if isinstance(e, ast.Attribute) and e.attr in LOCK_ATTRS:
                locks.append(e.attr)
            elif isinstance(e, ast.Name) and e.id in LOCK_ATTRS:
                locks.append(e.id)
        self.held.extend(locks)
        self.generic_visit(node)
        if locks:
            del self.held[-len(locks):]

    def visit_Call(self, node):
        if self.held and isinstance(node.func, ast.Attribute):
            site = (self.rel, node.lineno)
            chain = _attr_chain(node.func)
            method = chain[-1]
            via_engine = "_engine" in chain[:-1]
            bad = None
            if method in BLOCKING:
                bad = f"blocking wire call {'.'.join(chain)}"
            elif via_engine and method not in ENGINE_SAFE:
                bad = f"engine-ABI call {'.'.join(chain)}"
            if bad and site not in ALLOWED_SITES:
                self.findings.append(
                    f"{self.rel}:{node.lineno}: {bad} while holding "
                    f"{'+'.join(self.held)} — blocking I/O and engine "
                    f"ABI entries must run unlocked (lint_locks.py "
                    f"module docstring; add an ALLOWED_SITES entry with "
                    f"a reason only if the nesting is provably safe)"
                )
        self.generic_visit(node)


def run(repo: pathlib.Path) -> list[str]:
    findings: list[str] = []
    sources = sorted((repo / "shared_tensor_tpu").rglob("*.py"))
    if not sources:
        return ["scan found no sources (wrong --repo?)"]
    seen_sites: set[tuple[str, int]] = set()
    for path in sources:
        rel = str(path.relative_to(repo))
        try:
            tree = ast.parse(path.read_text(errors="replace"))
        except SyntaxError as e:
            findings.append(f"{rel}: unparseable ({e})")
            continue
        v = _Visitor(rel, findings)
        v.visit(tree)
        for (f, ln) in ALLOWED_SITES:
            if f == rel:
                seen_sites.add((f, ln))
    for site in sorted(set(ALLOWED_SITES) - seen_sites):
        findings.append(
            f"ALLOWED_SITES entry {site} names a file outside the scan — "
            f"remove it"
        )
    return findings


if __name__ == "__main__":
    L.main(run)
