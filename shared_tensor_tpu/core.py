"""SharedTensor: the process-local replica + per-link codec state.

This is the TPU-native equivalent of the reference's ``SharedTensor`` struct
(reference src/sharedtensor.c:30-39: full replica ``values[]`` plus one
residual buffer per tree link) and its update semantics (``addFromInternal``
:334-344; flood-on-receive :124-127). Differences by design:

- State is a pytree ("table") of tensors with per-leaf codec scales, not one
  flat buffer — the reference README's "table sync" TODO (README.md:41) is
  first-class here.
- Links are dynamic: the reference hard-codes exactly 3 (up/left/right) and
  pre-accumulates updates into *unconnected* slots so a late joiner can be
  seeded (SURVEY.md §5.4). Here a new link's residual is explicitly seeded
  with the current replica — the same state-transfer-through-the-codec
  mechanism, made explicit — so any number of links works and a dropped peer
  can re-graft anywhere (fixes reference quirk Q8 / README.md:33).
- All array updates are functional JAX ops guarded by one mutex; the
  reference's unsynchronized concurrent ``float +=`` races (quirk Q7, lost
  updates) are gone by construction while the *approximate* semantics stay in
  the codec.

The object is deliberately transport-agnostic: the peer engine (comm/) calls
``make_frame``/``receive_frame``; tests drive it in-process.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

import jax
import jax.numpy as jnp

from .config import CodecConfig
import numpy as np

from .ops.table import (
    TableFrame,
    TableSpec,
    accumulate_table,
    apply_table_batch,
    apply_table_many,
    flatten,
    make_spec,
    quantize_table,
    unflatten,
)


class SharedTensor:
    """Replica + per-link residuals for one shared table of tensors.

    Reference API mapping (src/sharedtensor.c:455-465):
      ``copyToTensor`` -> :meth:`read` (snapshot), ``addFromTensor`` ->
      :meth:`add`, link fan-out -> :meth:`new_link`/:meth:`receive_frame`.
    """

    def __init__(
        self,
        template: Any,
        codec: CodecConfig | None = None,
        seed_values: bool = False,
    ):
        self.spec: TableSpec = make_spec(template)
        self.codec = codec or CodecConfig()
        self._lock = threading.Lock()
        if seed_values:
            self.values = flatten(template, self.spec)
        else:
            self.values = jnp.zeros(self.spec.total, jnp.float32)
        self._links: dict[int, jnp.ndarray] = {}
        # observability (SURVEY.md §5.5: the reference has none)
        self.frames_out = 0
        self.frames_in = 0
        self.updates = 0

    # -- links -------------------------------------------------------------

    def new_link(
        self,
        link_id: int,
        seed: bool = True,
        residual: Optional[jnp.ndarray] = None,
    ) -> None:
        """Open a link. ``seed=True`` preloads the residual with the full
        current replica, so the peer on the other end receives complete
        state-to-date through normal codec frames — the reference's join /
        state-transfer mechanism (src/sharedtensor.c:379-381 master seeding;
        §5.4), generalized to any link at any time (rejoin support).

        ``residual`` overrides the seed with an explicit starting residual:
        the peer engine uses this to carry a dead uplink's undelivered
        residual onto the re-grafted uplink, so a node's pending updates
        survive its parent's death instead of being lost."""
        with self._lock:
            if link_id in self._links:
                raise ValueError(f"link {link_id} already exists")
            if residual is not None:
                if residual.shape != (self.spec.total,):
                    raise ValueError(
                        f"residual shape {residual.shape} != ({self.spec.total},)"
                    )
                self._links[link_id] = jnp.asarray(residual, jnp.float32)
            elif seed:
                self._links[link_id] = self.values
            else:
                self._links[link_id] = jnp.zeros(self.spec.total, jnp.float32)

    def new_link_diff(self, link_id: int, peer_snapshot: jnp.ndarray) -> None:
        """Open a downstream link toward a peer whose replica currently equals
        ``peer_snapshot``, seeding the residual with (our replica − theirs) —
        the delta that, once streamed, converges them to our state. A fresh
        joiner's snapshot is all-zero, making this exactly the reference's
        seed-with-full-replica join (src/sharedtensor.c:379-381); a re-grafted
        peer with live state receives only what it is missing (the reference
        cannot re-graft at all, quirk Q8)."""
        with self._lock:
            if link_id in self._links:
                raise ValueError(f"link {link_id} already exists")
            snap = jnp.asarray(peer_snapshot, jnp.float32)
            if snap.shape != (self.spec.total,):
                raise ValueError(
                    f"snapshot shape {snap.shape} != ({self.spec.total},)"
                )
            self._links[link_id] = self.values - snap

    def drop_link(self, link_id: int) -> Optional[jnp.ndarray]:
        """Close a link (peer died or left); returns its undelivered residual
        (or None if unknown). The peer engine re-seeds a replacement uplink
        with it so pending updates survive re-grafting. The reference instead
        kills the whole process on any link failure (quirk Q8)."""
        with self._lock:
            return self._links.pop(link_id, None)

    @property
    def link_ids(self) -> tuple[int, ...]:
        with self._lock:
            return tuple(self._links)

    def snapshot_all(self) -> tuple[jnp.ndarray, dict[int, jnp.ndarray]]:
        """Consistent point-in-time view of (replica, {link: residual}) under
        ONE lock acquisition — the checkpoint primitive. Separate
        snapshot_flat + per-link reads would let a concurrent frame land
        between them, tearing the error-feedback invariant on restore."""
        with self._lock:
            return self.values, dict(self._links)

    # -- user API ----------------------------------------------------------

    def read(self) -> Any:
        """Snapshot of the replica as the caller's pytree structure
        (reference l_copyToTensor, src/sharedtensor.c:435-446)."""
        return unflatten(self.values, self.spec)

    def snapshot_flat(self) -> jnp.ndarray:
        """Atomic snapshot of the padded flat replica (handshake / checkpoint
        use). Values arrays are replaced, never mutated, so the reference's
        torn-read hazard (§5.2) cannot occur."""
        with self._lock:
            return self.values

    def add(self, delta: Any) -> None:
        """Merge an additive update: replica and every link residual receive
        it (reference addFromInternal, src/sharedtensor.c:334-344)."""
        update = flatten(delta, self.spec)
        with self._lock:
            ids = tuple(self._links)
            arrays = (self.values, *(self._links[i] for i in ids))
            out = accumulate_table(arrays, update, self.spec)
            self.values = out[0]
            for i, r in zip(ids, out[1:]):
                self._links[i] = r
            self.updates += 1

    # -- sync engine hooks -------------------------------------------------

    def make_frame(self, link_id: int) -> Optional[TableFrame]:
        """Quantize this link's residual into a frame and apply error
        feedback. Returns None when every leaf's scale is 0 and the codec
        suppresses idle frames (fixing reference quirk Q2 — it transmits
        1 zero-scale frame/s/link forever)."""
        with self._lock:
            resid = self._links.get(link_id)
            if resid is None:
                return None  # link dropped concurrently (peer death race)
            frame, new_resid = quantize_table(
                resid,
                self.spec,
                self.codec.scale_policy,
                self.codec.per_leaf_scale,
            )
            # Storing unconditionally is safe: at scale 0 the new residual is
            # identical to the old one.
            self._links[link_id] = new_resid
        # One device->host transfer serves both the idle check and the wire
        # encoding (the frame is bytes-bound anyway). Doing the idle check as
        # its own jnp.any() would cost a second blocking sync per frame —
        # measured 2-3 frames/s through a high-latency device tunnel.
        scales, words = jax.device_get((frame.scales, frame.words))
        if self.codec.suppress_zero_frames and not scales.any():
            return None
        self.frames_out += 1
        return TableFrame(scales, words)

    def receive_frame(self, link_id: int, frame: TableFrame) -> None:
        """Apply an incoming frame to the replica and to every *other* link's
        residual (split-horizon flood with per-hop re-quantization, reference
        sync_in src/sharedtensor.c:124-127). ``link_id`` may be unknown
        (already-dropped peer): the frame still applies to the replica."""
        with self._lock:
            others = tuple(i for i in self._links if i != link_id)
            arrays = (self.values, *(self._links[i] for i in others))
            out = apply_table_many(arrays, frame, self.spec)
            self.values = out[0]
            for i, r in zip(others, out[1:]):
                self._links[i] = r
            self.frames_in += 1

    def receive_frames(self, link_id: int, frames: list[TableFrame]) -> None:
        """Batched :meth:`receive_frame`: apply K queued frames from one link
        in a single device dispatch (their summed delta — codec deltas are
        pure adds and commute). K is padded with zero-scale no-op frames to
        the next power of two so jit specializes on O(log K) shapes. This is
        the receive path's defense against dispatch-overhead backlog: a
        sender can emit frames faster than a busy device can absorb
        one-dispatch-per-frame (see ops/table.py apply_table_batch)."""
        if not frames:
            return
        if len(frames) == 1:
            return self.receive_frame(link_id, frames[0])
        k = 1
        while k < len(frames):
            k *= 2
        scales = np.zeros((k, self.spec.num_leaves), np.float32)
        words = np.zeros((k, self.spec.total // 32), np.uint32)
        for i, f in enumerate(frames):
            scales[i] = np.asarray(f.scales)
            words[i] = np.asarray(f.words)
        stacked = TableFrame(jnp.asarray(scales), jnp.asarray(words))
        with self._lock:
            others = tuple(i for i in self._links if i != link_id)
            arrays = (self.values, *(self._links[i] for i in others))
            out = apply_table_batch(arrays, stacked, self.spec)
            self.values = out[0]
            for i, r in zip(others, out[1:]):
                self._links[i] = r
            self.frames_in += len(frames)

    # -- introspection -----------------------------------------------------

    def residual_rms(self, link_id: int) -> float:
        with self._lock:
            r = self._links.get(link_id)
        if r is None:
            return 0.0
        return float(jnp.sqrt(jnp.sum(r * r) / self.spec.total_n))

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"SharedTensor(leaves={self.spec.num_leaves}, n={self.spec.total_n}, "
            f"links={list(self._links)}, out={self.frames_out}, in={self.frames_in})"
        )
