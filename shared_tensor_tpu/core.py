"""SharedTensor: the process-local replica + per-link codec state.

This is the TPU-native equivalent of the reference's ``SharedTensor`` struct
(reference src/sharedtensor.c:30-39: full replica ``values[]`` plus one
residual buffer per tree link) and its update semantics (``addFromInternal``
:334-344; flood-on-receive :124-127). Differences by design:

- State is a pytree ("table") of tensors with per-leaf codec scales, not one
  flat buffer — the reference README's "table sync" TODO (README.md:41) is
  first-class here.
- Links are dynamic: the reference hard-codes exactly 3 (up/left/right) and
  pre-accumulates updates into *unconnected* slots so a late joiner can be
  seeded (SURVEY.md §5.4). Here a new link's residual is explicitly seeded
  with the current replica — the same state-transfer-through-the-codec
  mechanism, made explicit — so any number of links works and a dropped peer
  can re-graft anywhere (fixes reference quirk Q8 / README.md:33).
- All array updates are functional JAX ops guarded by one mutex; the
  reference's unsynchronized concurrent ``float +=`` races (quirk Q7, lost
  updates) are gone by construction while the *approximate* semantics stay in
  the codec.

The object is deliberately transport-agnostic: the peer engine (comm/) calls
``make_frame``/``receive_frame``; tests drive it in-process.
"""

from __future__ import annotations

import glob
import importlib.util
import os
import pkgutil
import threading
from typing import Any, Optional

import jax
import jax.numpy as jnp

from .config import CodecConfig
import numpy as np

from .ops.table import (
    TableFrame,
    TableSpec,
    accumulate_table,
    apply_table_batch,
    apply_table_many,
    flatten,
    make_spec,
    quantize_table,
    quantize_table_burst,
    unflatten,
)


def _accelerator_plausible() -> bool:
    """Cheap no-backend-init probe: could this process see an accelerator?
    Checks device nodes (TPU /dev/accel*, /dev/vfio; GPU /dev/nvidia*) and
    installed jax plugin packages. False means the host is CPU-only and the
    host tier can activate WITHOUT initializing the XLA CPU client (whose
    thread pool contends with the C codec loops — measured 2.7x, see
    SharedTensor.__init__)."""
    for pat in (
        "/dev/accel*",      # TPU
        "/dev/nvidia*",     # NVIDIA GPU
        "/dev/kfd",         # AMD ROCm compute
        "/dev/dri/renderD*",  # GPU render nodes (ROCm without kfd exposure)
        "/dev/vfio/*",      # passthrough devices
    ):
        if glob.glob(pat):
            return True
    try:
        spec = importlib.util.find_spec("jax_plugins")
        if spec is not None and spec.submodule_search_locations:
            if any(pkgutil.iter_modules(list(spec.submodule_search_locations))):
                return True
        # PJRT plugins may register ONLY via the entry-point group (no
        # jax_plugins namespace package, no matching /dev node — e.g.
        # jax-metal): missing them would silently demote an accelerator
        # host to the numpy tier.
        import importlib.metadata as _md

        if any(True for _ in _md.entry_points(group="jax_plugins")):
            return True
    except Exception:
        return True  # can't tell: be conservative, ask the real backend
    return False


def host_tier_active() -> bool:
    """Will a SharedTensor built now run the host (numpy/C) codec tier?
    The same decision SharedTensor.__init__ makes, callable without
    constructing one. Initializes no jax backend when JAX_PLATFORMS is set
    or the host is detectably CPU-only (_accelerator_plausible); only a
    host with accelerator hardware/plugins present falls through to
    jax.default_backend() — and such a host is about to initialize that
    backend for the device tier anyway."""
    mode = os.environ.get("ST_HOST_CODEC", "auto")
    if mode != "auto":
        return mode == "numpy"
    plat = jax.config.jax_platforms
    if plat:
        return str(plat).split(",")[0] == "cpu"
    if not _accelerator_plausible():
        return True
    return jax.default_backend() == "cpu"


class DuplicateLink(ValueError):
    """A link id that is already attached. Its own type so the recv-thread
    event loop can treat a replayed LINK_UP as a logged no-op WITHOUT
    swallowing unrelated ValueErrors from the attach path (a masked real
    error there silently desyncs the peer — ADVICE r04 item 2 follow-up)."""


class SnapshotPublisher:
    """Lock-free double-buffered snapshot publication (r10 serving tier).

    The snapshot paths the reference's ``copyToTensor`` maps to all copy
    under the data-plane lock — ``EngineTensor.read()`` holds the engine
    mutex for a full-table memcpy, so a serving loop polling it would
    stall the quantize/apply threads that share that mutex exactly when
    traffic is heaviest. The serve tier reads from THIS instead: the
    writer side (the subscriber's apply thread) builds a fresh snapshot
    and :meth:`publish`\\ es it as one reference swap; readers
    :meth:`acquire` the current (array, meta) tuple with zero locks — a
    single attribute read, atomic under the GIL — so a read can never
    block an apply (or an ``add()`` upstream) by more than the one
    buffer swap the writer itself performs.

    The published array is owned by the publisher's consumers: the writer
    must hand over a COPY (or an array it will no longer mutate) — that
    copy is the "double buffer"."""

    __slots__ = ("_cur",)

    def __init__(self):
        self._cur: tuple = (None, 0, 0)  # (array, freshness_ns, version)

    def publish(self, array, freshness_ns: int, version: int) -> None:
        self._cur = (array, int(freshness_ns), int(version))

    def touch(self, freshness_ns: int) -> None:
        """Refresh the freshness mark WITHOUT a new array (idle FRESH
        beats: the state didn't change, only its verified age did)."""
        arr, old, ver = self._cur
        if freshness_ns > old:
            self._cur = (arr, int(freshness_ns), ver)

    def acquire(self) -> tuple:
        """(array, freshness_ns, version) — the latest published snapshot,
        read lock-free. array is None until the first publish."""
        return self._cur


class SharedTensor:
    """Replica + per-link residuals for one shared table of tensors.

    Reference API mapping (src/sharedtensor.c:455-465):
      ``copyToTensor`` -> :meth:`read` (snapshot), ``addFromTensor`` ->
      :meth:`add`, link fan-out -> :meth:`new_link`/:meth:`receive_frame`.
    """

    def __init__(
        self,
        template: Any,
        codec: CodecConfig | None = None,
        seed_values: bool = False,
    ):
        self.spec: TableSpec = make_spec(template)
        self.codec = codec or CodecConfig()
        self._lock = threading.Lock()
        # Host-codec tier selection: on an accelerator backend the codec runs
        # as device (Pallas/XLA) ops; on a CPU backend the numpy tier
        # (ops/codec_np.py) is the production path — XLA-CPU's pack/unpack
        # lowering is an order of magnitude off numpy's C loops, enough to
        # stall links via TCP backpressure at 16Mi elements (measured).
        # ST_HOST_CODEC=numpy|xla overrides (parity tests pin either).
        # CPU backend specifically — on any accelerator (TPU or GPU) the
        # codec must stay a device computation; only a host-only backend
        # should fall back to host loops. host_tier_active prefers the
        # configured platform string over jax.default_backend(): the latter
        # INITIALIZES the backend, and a live XLA CPU client's thread pool
        # contends with the host tier's C loops (measured on a 1-vCPU host:
        # 2.7x slower frames). A host-tier node must never start a backend.
        self._np = host_tier_active()
        if seed_values:
            if self._np:
                from .ops.codec_np import flatten_np

                self.values = flatten_np(template, self.spec)
            else:
                self.values = flatten(template, self.spec)
        else:
            self.values = (
                np.zeros(self.spec.total, np.float32)
                if self._np
                else jnp.zeros(self.spec.total, jnp.float32)
            )
        self._links: dict[int, jnp.ndarray] = {}
        # Per-link ledger of dispatched-but-unacknowledged frame deltas,
        # keyed by frame sequence number (insertion-ordered): each entry is
        # old_residual - new_residual, i.e. exactly what that frame delivers.
        # Quantizing applies error feedback immediately, but delivery is not
        # certain until the RECEIVER acknowledges (wire.ACK): the frame can
        # die in the sender pipeline, the native send queue, or the socket.
        # If the link dies first, every unacknowledged delta is rolled back
        # into the residual (drop_link/nack_frame), so a re-grafted uplink
        # re-owes it. Each ledger entry is the FRAME itself (device-side,
        # ~n/8 bytes): a frame's delta is exactly scale*(1-2*bit), so
        # re-APPLYING the frame to the residual undoes its error feedback
        # bit-for-bit — 32x less memory than materializing the delta, which
        # matters at pipeline depth 8+ on multi-Mi tables.
        #
        # Delivery contract this buys (stated precisely because the flood
        # makes it subtle): FIRST-HOP delivery is guaranteed — an update is
        # never lost between this node and a live neighbor. Mass that was
        # acknowledged by an INTERIOR node which then crashes before flooding
        # it onward can still be lost tree-wide (a per-hop ack cannot witness
        # end-to-end flood completion, and the codec's gradual residual drain
        # admits no exact frame->content mapping to ack transitively).
        # Therefore: state that has finished propagating is never lost; a
        # graceful leave (peer.drain() then close()) loses nothing; a CRASH
        # of an interior node may drop the in-transit mass sitting in its RX
        # queue/residuals at that instant, after which the tree still repairs
        # to agreement via the re-graft diff handshake. The reference kills
        # the entire tree on any death (quirk Q8), so every arm of this
        # contract is strictly stronger.
        self._inflight: dict[int, dict[int, tuple[TableFrame, ...]]] = {}
        self._frame_seq = 0
        # observability (SURVEY.md §5.5: the reference has none).
        # ONE meaning per counter (peer.metrics() documents the full
        # taxonomy): frames_out = non-idle codec frames handed toward the
        # wire — counted at fetch on the pipelined device path
        # (finish_frame) and at quantize on the burst path
        # (begin_frame_burst); same set of frames, timing differs by at
        # most the pipeline depth. frames_in = codec frames applied from
        # the wire. Idle (all-zero-scale) frames count in neither.
        self.frames_out = 0
        self.frames_in = 0
        self.updates = 0

    @property
    def host_tier(self) -> bool:
        """True when the codec runs as synchronous host (numpy/C) work rather
        than async device dispatch — callers tune pipelining accordingly."""
        return self._np

    # -- links -------------------------------------------------------------

    def _asarray(self, x) -> Any:
        """Array in this tier's native type (numpy on CPU, jax on device)."""
        return (
            np.asarray(x, np.float32)
            if self._np
            else jnp.asarray(x, jnp.float32)
        )

    def _zeros(self) -> Any:
        return (
            np.zeros(self.spec.total, np.float32)
            if self._np
            else jnp.zeros(self.spec.total, jnp.float32)
        )

    def new_link(
        self,
        link_id: int,
        seed: bool = True,
        residual: Optional[jnp.ndarray] = None,
    ) -> None:
        """Open a link. ``seed=True`` preloads the residual with the full
        current replica, so the peer on the other end receives complete
        state-to-date through normal codec frames — the reference's join /
        state-transfer mechanism (src/sharedtensor.c:379-381 master seeding;
        §5.4), generalized to any link at any time (rejoin support).

        ``residual`` overrides the seed with an explicit starting residual:
        the peer engine uses this to carry a dead uplink's undelivered
        residual onto the re-grafted uplink, so a node's pending updates
        survive its parent's death instead of being lost."""
        with self._lock:
            if link_id in self._links:
                raise DuplicateLink(f"link {link_id} already exists")
            if residual is not None:
                if residual.shape != (self.spec.total,):
                    raise ValueError(
                        f"residual shape {residual.shape} != ({self.spec.total},)"
                    )
                self._links[link_id] = self._asarray(residual)
            elif seed:
                self._links[link_id] = self.values
            else:
                self._links[link_id] = self._zeros()

    def new_link_diff(self, link_id: int, peer_snapshot: jnp.ndarray) -> None:
        """Open a downstream link toward a peer whose replica currently equals
        ``peer_snapshot``, seeding the residual with (our replica − theirs) —
        the delta that, once streamed, converges them to our state. A fresh
        joiner's snapshot is all-zero, making this exactly the reference's
        seed-with-full-replica join (src/sharedtensor.c:379-381); a re-grafted
        peer with live state receives only what it is missing (the reference
        cannot re-graft at all, quirk Q8)."""
        with self._lock:
            if link_id in self._links:
                raise DuplicateLink(f"link {link_id} already exists")
            snap = self._asarray(peer_snapshot)
            if snap.shape != (self.spec.total,):
                raise ValueError(
                    f"snapshot shape {snap.shape} != ({self.spec.total},)"
                )
            self._links[link_id] = self.values - snap

    def stash_carry(self, link_id: int, carry_id: int) -> bool:
        """Move a dead link's residual (unacked frames rolled back) into the
        live carry pseudo-slot ``carry_id``, merging with any existing carry
        — ONE lock acquisition. A multi-step pop/merge/create would leave a
        window where a concurrent add() finds neither the dead link nor the
        carry slot, and that orphan mass would later be erased tree-wide by
        the re-graft diff (the loss the live slot exists to prevent).
        Returns False if ``link_id`` is unknown (mid-handshake death)."""
        with self._lock:
            resid = self._links.pop(link_id, None)
            if resid is None:
                return False
            inflight = self._inflight.pop(link_id, {})
            resid = self._unapply(resid, inflight)
            prev = self._links.pop(carry_id, None)
            if prev is not None:
                resid = resid + prev
            self._links[carry_id] = resid
            return True

    def take_link_and_snapshot(
        self, link_id: int
    ) -> tuple[Optional[jnp.ndarray], jnp.ndarray]:
        """drop_link + replica snapshot under ONE lock acquisition. The
        peer's re-graft uses this on its carry pseudo-link: an add() landing
        between a separate drop and snapshot would appear in the snapshot
        but not the carry — presenting orphan-period mass as tree-known
        state, which the parent's diff seed then erases tree-wide."""
        with self._lock:
            resid = self._links.pop(link_id, None)
            inflight = self._inflight.pop(link_id, {})
            if resid is not None:
                resid = self._unapply(resid, inflight)
            return resid, self.values

    def drop_link(self, link_id: int) -> Optional[jnp.ndarray]:
        """Close a link (peer died or left); returns its undelivered residual
        (or None if unknown) INCLUDING any unacknowledged in-flight frame
        deltas — those frames were quantized but never delivered, so their
        error feedback is rolled back into what the replacement link owes.
        The peer engine re-seeds a re-grafted uplink with this so pending
        updates survive a parent's death. The reference instead kills the
        whole process on any link failure (quirk Q8)."""
        with self._lock:
            resid = self._links.pop(link_id, None)
            inflight = self._inflight.pop(link_id, {})
            if resid is not None:
                resid = self._unapply(resid, inflight)
            return resid

    def _unapply(self, resid: jnp.ndarray, frames: dict) -> jnp.ndarray:
        """Roll back unacknowledged frames: a frame's delta is exactly
        scale*(1-2*bit), so re-applying it to the residual restores the
        pre-quantize state bit-for-bit (see the ledger comment above).
        Ledger entries are tuples of frames (a burst rolls back whole)."""
        if self._np:
            from .ops.codec_np import apply_table_many_np

            for entry in frames.values():
                for f in entry:
                    resid = apply_table_many_np(
                        (resid,), np.asarray(f.scales), np.asarray(f.words),
                        self.spec,
                    )[0]
            return resid
        for entry in frames.values():
            for f in entry:
                resid = apply_table_many((resid,), f, self.spec)[0]
        return resid

    @property
    def link_ids(self) -> tuple[int, ...]:
        with self._lock:
            return tuple(self._links)

    def inflight_total(self) -> int:
        """Number of dispatched MESSAGES (ledger entries — a burst counts
        once, however many frames it carries) not yet acknowledged by their
        receivers, across all links (0 = everything sent has landed)."""
        with self._lock:
            return sum(len(q) for q in self._inflight.values())

    def snapshot_all(self) -> tuple[jnp.ndarray, dict[int, jnp.ndarray]]:
        """Consistent point-in-time view of (replica, {link: residual}) under
        ONE lock acquisition — the checkpoint primitive. Separate
        snapshot_flat + per-link reads would let a concurrent frame land
        between them, tearing the error-feedback invariant on restore."""
        with self._lock:
            return self.values, dict(self._links)

    # -- user API ----------------------------------------------------------

    def read(self) -> Any:
        """Snapshot of the replica as the caller's pytree structure
        (reference l_copyToTensor, src/sharedtensor.c:435-446)."""
        if self._np:
            from .ops.codec_np import unflatten_np

            return unflatten_np(self.values, self.spec)
        return unflatten(self.values, self.spec)

    def reset_values(self) -> None:
        """Zero the replica (keep links/residuals). The wire-compat re-graft
        path uses this: the reference protocol has no diff handshake, so a
        re-grafted uplink re-seeds us with the parent's FULL replica —
        fresh-joiner semantics (zeroed state, undelivered residual carried
        onto the new uplink) are the only exact ones expressible in-protocol
        (see peer._handle_events)."""
        with self._lock:
            self.values = self._zeros()

    def regraft_reset_to_carry(self, carry_id: int, new_link_id: int) -> None:
        """The wire-compat leaf re-graft, as ONE atomic step: consume the
        carry pseudo-slot, set the replica to EXACTLY the carry, and open
        the new uplink with the carry as its residual.

        Fresh-joiner semantics under the reference protocol mean the parent
        re-seeds us with its full replica additively — so our replica must
        start at precisely the mass the tree does NOT yet know (the carry),
        the way a true fresh joiner with pending adds holds them in values
        AND residual (add(): both sides). Resetting to zero instead loses
        the carry from this node forever: it streams up and floods to every
        OTHER peer (split horizon never returns it), ending with the tree
        at state+carry and this node at state. Atomicity for the same
        reason as stash_carry: a concurrent add() must land either in
        (carry -> values+residual) or in (values+new residual), never
        partially."""
        with self._lock:
            if new_link_id in self._links:
                raise DuplicateLink(f"link {new_link_id} already exists")
            carry = self._links.pop(carry_id, None)
            if carry is None:
                self.values = self._zeros()
                self._links[new_link_id] = self._zeros()
            else:
                # arrays are functional (replaced, never mutated) on both
                # tiers, so values and the residual may share storage
                self.values = carry
                self._links[new_link_id] = carry

    def snapshot_flat(self) -> jnp.ndarray:
        """Atomic snapshot of the padded flat replica (handshake / checkpoint
        use). Values arrays are replaced, never mutated, so the reference's
        torn-read hazard (§5.2) cannot occur."""
        with self._lock:
            return self.values

    def add(self, delta: Any) -> None:
        """Merge an additive update: replica and every link residual receive
        it (reference addFromInternal, src/sharedtensor.c:334-344)."""
        if self._np:
            from .ops.codec_np import flatten_np

            update = flatten_np(delta, self.spec)
        else:
            update = flatten(delta, self.spec)
        with self._lock:
            ids = tuple(self._links)
            arrays = (self.values, *(self._links[i] for i in ids))
            if self._np:
                from .ops.codec_np import accumulate_table_np

                out = accumulate_table_np(arrays, np.asarray(update), self.spec)
            else:
                out = accumulate_table(arrays, update, self.spec)
            self.values = out[0]
            for i, r in zip(ids, out[1:]):
                self._links[i] = r
            self.updates += 1

    def mask_link_residual(self, link_id: int, elo: int, ehi: int) -> None:
        """Zero a link's residual OUTSIDE [elo, ehi) — the r10 range-
        subscription discipline: adds/floods refill the full residual, but
        a ranged subscriber link's receiver will never get the out-of-range
        mass, so the sender drops it before scale selection instead of
        letting it decay through frames of useless traffic (the native
        engine does the same in its subscriber branch). Functional replace,
        never an in-place mutation — snapshots may share storage."""
        with self._lock:
            r = self._links.get(link_id)
            if r is None:
                return
            if self._np:
                m = np.array(r, np.float32, copy=True)
                m[:elo] = 0.0
                m[ehi:] = 0.0
            else:
                m = jnp.asarray(r).at[:elo].set(0.0).at[ehi:].set(0.0)
            self._links[link_id] = m

    # -- sync engine hooks -------------------------------------------------

    def begin_frame(self, link_id: int) -> Optional[tuple[int, TableFrame]]:
        """Dispatch one sender step for a link: quantize the residual into a
        frame (device arrays, NOT yet fetched) and apply error feedback.
        Returns (seq, frame), or None if the link was dropped concurrently
        (peer death race). ``seq`` identifies the frame in the in-flight
        ledger; the caller must eventually :meth:`ack_frame` it (delivered or
        provably no-op) or let nack/drop roll it back.

        Split from :meth:`finish_frame` so the peer engine can double-buffer:
        dispatch frame t+1's quantize before fetching/sending frame t, so the
        device computes while the host does the transfer + socket write
        (round-2 verdict Weak #2: the serialized path left the device idle
        during every send)."""
        with self._lock:
            resid = self._links.get(link_id)
            if resid is None:
                return None
            if self._np:
                from .ops.codec_np import quantize_table_np

                scales, words, new_resid = quantize_table_np(
                    resid,
                    self.spec,
                    self.codec.scale_policy,
                    self.codec.per_leaf_scale,
                )
                frame = TableFrame(scales, words)
            else:
                frame, new_resid = quantize_table(
                    resid,
                    self.spec,
                    self.codec.scale_policy,
                    self.codec.per_leaf_scale,
                )
            # Storing unconditionally is safe: at scale 0 the new residual is
            # identical to the old one.
            self._links[link_id] = new_resid
            self._frame_seq += 1
            seq = self._frame_seq
            # the frame IS its own delivery record; re-applied on nack/drop
            self._inflight.setdefault(link_id, {})[seq] = (frame,)
        return seq, frame

    def begin_frame_burst(
        self, link_id: int, k: int
    ) -> Optional[tuple[int, list[TableFrame]]]:
        """Quantize up to ``k`` successive frames of a link's residual in one
        call — each frame halves what the previous one left (the same
        sequence the streaming path would produce one message at a time),
        stopping early when the residual quantizes to all-zero scales. The
        burst is ONE in-flight ledger entry / ONE wire message / ONE
        receiver ACK. Host (numpy) tier only: the loop is synchronous host
        work. Returns (seq, frames) with 0..k frames (0 = link idle)."""
        from .ops.codec_np import quantize_table_np

        with self._lock:
            resid = self._links.get(link_id)
            if resid is None:
                return None
            frames: list[TableFrame] = []
            for _ in range(k):
                scales, words, new_resid = quantize_table_np(
                    resid,
                    self.spec,
                    self.codec.scale_policy,
                    self.codec.per_leaf_scale,
                )
                if not scales.any():
                    break  # idle: nothing left the codec can express
                frames.append(TableFrame(scales, words))
                resid = new_resid
            self._links[link_id] = resid
            self._frame_seq += 1
            seq = self._frame_seq
            if frames:
                self._inflight.setdefault(link_id, {})[seq] = tuple(frames)
            self.frames_out += len(frames)
        return seq, frames

    def begin_frame_burst_device(
        self, link_id: int, k: int
    ) -> Optional[tuple[int, TableFrame]]:
        """Device-tier burst: K successive halvings quantized in ONE jitted
        dispatch (ops/table.quantize_table_burst), fetched later with ONE
        device->host sync (:meth:`finish_frame_burst`). One ledger entry /
        wire message / receiver ACK, like the host burst. Returns
        (seq, stacked TableFrame with leading K axis) — device arrays, not
        yet fetched."""
        with self._lock:
            resid = self._links.get(link_id)
            if resid is None:
                return None
            frames, new_resid = quantize_table_burst(
                resid,
                self.spec,
                k,
                self.codec.scale_policy,
                self.codec.per_leaf_scale,
            )
            self._links[link_id] = new_resid
            self._frame_seq += 1
            seq = self._frame_seq
            # ledger rollback re-applies per frame; zero-scale tail frames
            # are exact no-ops so storing all K is correct
            self._inflight.setdefault(link_id, {})[seq] = tuple(
                TableFrame(frames.scales[i], frames.words[i]) for i in range(k)
            )
        return seq, frames

    def finish_frame_burst(
        self, frames: TableFrame
    ) -> Optional[list[TableFrame]]:
        """Fetch a dispatched burst with one blocking sync and trim the
        all-zero-scale tail (once a frame quantizes to zero scales, every
        later scan step is a no-op — zeros appear only as a suffix).
        Returns None for a fully idle burst (suppressed, like
        finish_frame)."""
        scales, words = jax.device_get((frames.scales, frames.words))
        k_eff = 0
        for i in range(scales.shape[0]):
            if not scales[i].any():
                break
            k_eff = i + 1
        if k_eff == 0:
            return None
        self.frames_out += k_eff
        return [TableFrame(scales[i], words[i]) for i in range(k_eff)]

    def ack_frame(self, link_id: int, seq: int) -> None:
        """Frame ``seq`` is accounted for — the receiver acknowledged it, or
        it was an idle no-op (zero delta) that never hit the wire: forget its
        in-flight delta."""
        with self._lock:
            q = self._inflight.get(link_id)
            if q is not None:
                q.pop(seq, None)

    def nack_frame(self, link_id: int) -> None:
        """Delivery failed but the link still exists: roll every outstanding
        frame's error feedback back into the residual (the deltas were never
        received, so the link's peer is still owed them)."""
        with self._lock:
            q = self._inflight.pop(link_id, None)
            resid = self._links.get(link_id)
            if resid is None or not q:
                return
            self._links[link_id] = self._unapply(resid, q)

    def finish_frame(self, frame: TableFrame) -> Optional[TableFrame]:
        """Fetch a dispatched frame to host memory. Returns None for an idle
        frame (every leaf at scale 0) when the codec suppresses them (fixing
        reference quirk Q2 — it transmits 1 zero-scale frame/s/link forever).

        One device->host transfer serves both the idle check and the wire
        encoding (the frame is bytes-bound anyway). Doing the idle check as
        its own jnp.any() would cost a second blocking sync per frame —
        measured 2-3 frames/s through a high-latency device tunnel."""
        scales, words = jax.device_get((frame.scales, frame.words))
        if self.codec.suppress_zero_frames and not scales.any():
            return None
        self.frames_out += 1
        return TableFrame(scales, words)

    def make_frame(self, link_id: int) -> Optional[TableFrame]:
        """begin_frame + finish_frame in one call, acknowledged immediately —
        the caller takes delivery responsibility (tests, simple callers)."""
        out = self.begin_frame(link_id)
        if out is None:
            return None
        seq, frame = out
        fetched = self.finish_frame(frame)
        self.ack_frame(link_id, seq)
        return fetched

    def receive_frame(self, link_id: int, frame: TableFrame) -> None:
        """Apply an incoming frame to the replica and to every *other* link's
        residual (split-horizon flood with per-hop re-quantization, reference
        sync_in src/sharedtensor.c:124-127). ``link_id`` may be unknown
        (already-dropped peer): the frame still applies to the replica.

        Corruption-zeroed (all-zero-scale) frames apply as no-ops and count
        NOWHERE — the same taxonomy rule the engine tier enforces
        (stengine.cpp apply_batch): a quiesced pair must satisfy
        sender.frames_out == receiver.frames_in on every tier, or the
        divergence reads as a phantom discrepancy exactly when an operator
        is debugging a corrupt link."""
        if not np.asarray(frame.scales).any():
            return
        with self._lock:
            others = tuple(i for i in self._links if i != link_id)
            arrays = (self.values, *(self._links[i] for i in others))
            if self._np:
                from .ops.codec_np import apply_table_many_np

                out = apply_table_many_np(
                    arrays,
                    np.asarray(frame.scales),
                    np.asarray(frame.words),
                    self.spec,
                )
            else:
                out = apply_table_many(arrays, frame, self.spec)
            self.values = out[0]
            for i, r in zip(others, out[1:]):
                self._links[i] = r
            self.frames_in += 1

    def receive_frames(self, link_id: int, frames: list[TableFrame]) -> None:
        """Batched :meth:`receive_frame`: apply K queued frames from one link
        in a single device dispatch (their summed delta — codec deltas are
        pure adds and commute). K is padded with zero-scale no-op frames to
        the next power of two so jit specializes on O(log K) shapes. This is
        the receive path's defense against dispatch-overhead backlog: a
        sender can emit frames faster than a busy device can absorb
        one-dispatch-per-frame (see ops/table.py apply_table_batch)."""
        if not frames:
            return
        if len(frames) == 1:
            return self.receive_frame(link_id, frames[0])
        # all-zero-scale frames apply as no-ops and count nowhere (the
        # engine tier's taxonomy rule — see receive_frame)
        applied = sum(1 for f in frames if np.asarray(f.scales).any())
        if applied == 0:
            return
        if self._np:
            scales = np.stack([np.asarray(f.scales) for f in frames])
            words = np.stack([np.asarray(f.words) for f in frames])
            from .ops.codec_np import apply_table_batch_np

            with self._lock:
                others = tuple(i for i in self._links if i != link_id)
                arrays = (self.values, *(self._links[i] for i in others))
                out = apply_table_batch_np(arrays, scales, words, self.spec)
                self.values = out[0]
                for i, r in zip(others, out[1:]):
                    self._links[i] = r
                self.frames_in += applied
            return
        k = 1
        while k < len(frames):
            k *= 2
        scales = np.zeros((k, self.spec.num_leaves), np.float32)
        words = np.zeros((k, self.spec.total // 32), np.uint32)
        for i, f in enumerate(frames):
            scales[i] = np.asarray(f.scales)
            words[i] = np.asarray(f.words)
        stacked = TableFrame(jnp.asarray(scales), jnp.asarray(words))
        with self._lock:
            others = tuple(i for i in self._links if i != link_id)
            arrays = (self.values, *(self._links[i] for i in others))
            out = apply_table_batch(arrays, stacked, self.spec)
            self.values = out[0]
            for i, r in zip(others, out[1:]):
                self._links[i] = r
            self.frames_in += applied

    # -- introspection -----------------------------------------------------

    def state_version(self) -> int:
        """Monotone change counter for the replica: bumps on every local
        add and every applied foreign frame. Cheap (two counter reads) —
        the peer's ranged-subscriber send path uses it to skip the
        full-table residual mask on passes where nothing moved
        (peer._send_sub)."""
        return self.updates + self.frames_in

    def residual_rms(self, link_id: int) -> float:
        with self._lock:
            r = self._links.get(link_id)
        if r is None:
            return 0.0
        if self._np:
            # numpy on the host tier: drain()/metrics() call this, and a
            # jnp reduction here would initialize the XLA CPU backend —
            # undoing the tier's no-backend invariant for the process's
            # whole lifetime (2.7x frame-rate contention, see __init__).
            r = np.asarray(r, np.float64)
            return float(np.sqrt(np.dot(r, r) / self.spec.total_n))
        return float(jnp.sqrt(jnp.sum(r * r) / self.spec.total_n))

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"SharedTensor(leaves={self.spec.num_leaves}, n={self.spec.total_n}, "
            f"links={list(self._links)}, out={self.frames_out}, in={self.frames_in})"
        )
