"""shared-tensor-tpu: a TPU-native distributed shared tensor with
high-performance approximate (1-bit error-feedback) updates for asynchronous
data-parallel machine learning.

TPU-first re-design of the capabilities of Hello1024/shared-tensor (a 477-line
C / Lua-Torch7 system — see SURVEY.md): the codec runs as Pallas kernels on
HBM, intra-pod sync rides ICI collectives over a GSPMD-sharded array, and the
peer tier is a native C++ TCP transport with the same self-organizing
binary-tree overlay and wire format.
"""

from .config import CodecConfig, Config, MeshConfig, ScalePolicy, TransportConfig
from .core import SharedTensor

__version__ = "0.1.0"


def create_or_fetch(host, port, template, config=None, timeout=30.0):
    """The reference entry point (sharedtensor.createOrFetch) — see
    comm/peer.py. Imported lazily so codec-only users don't pay for the
    native transport build."""
    from .comm.peer import create_or_fetch as _cof

    return _cof(host, port, template, config, timeout)


__all__ = [
    "Config",
    "CodecConfig",
    "TransportConfig",
    "MeshConfig",
    "ScalePolicy",
    "SharedTensor",
    "create_or_fetch",
    "__version__",
]
