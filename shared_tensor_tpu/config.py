"""Typed configuration for shared-tensor-tpu.

The reference has no config system at all — its total configuration surface is
the three positional args of ``createOrFetch(host, port, tensor)`` plus
hard-coded constants (reference src/sharedtensor.c:349-352, :323; SURVEY.md
§5.6). This module realizes the survey's build note: a small typed config
covering rendezvous, mesh axes, codec policy, pacing (the reference README's
bandwidth-limit TODO), and fault timeouts (its disconnect-handling TODO).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional


class ScalePolicy(enum.Enum):
    """How the per-frame quantization scale is chosen from the residual.

    POW2_RMS is the reference policy: ``2^floor(log2(rms(residual)))``
    (reference src/sharedtensor.c:153-159). RMS skips the power-of-2 floor
    (slightly faster convergence, loses the cheap-to-compare property);
    ABS_MEAN uses mean(|r|) like signSGD-EF literature.
    """

    POW2_RMS = "pow2_rms"
    RMS = "rms"
    ABS_MEAN = "abs_mean"


@dataclasses.dataclass(frozen=True)
class CodecConfig:
    """Approximate-delta codec configuration.

    The reference codec is fixed: 1 sign bit per element, one global scale per
    frame chosen by POW2_RMS, error feedback via a per-link residual
    (reference src/sharedtensor.c:106-111, :145-177; SURVEY.md App. B). Those
    are the defaults here. ``per_leaf_scale`` realizes the reference README's
    "table sync" TODO (README.md:41): one scale per pytree leaf instead of one
    for the whole flat buffer, fixing the 1000:1 mixed-magnitude degradation
    measured in BASELINE.md.
    """

    scale_policy: ScalePolicy = ScalePolicy.POW2_RMS
    per_leaf_scale: bool = True
    #: Skip sending when scale == 0 (fixes reference quirk Q2, which sleeps 1s
    #: but still transmits an idle frame). Safe in wire-compat mode too: the
    #: native transport emits a zero-scale compat keepalive frame per
    #: keepalive interval when a link is idle — the reference's own idle
    #: behavior, which its peers' liveness expects — so the codec layer never
    #: needs to synthesize idle frames itself.
    suppress_zero_frames: bool = True


@dataclasses.dataclass(frozen=True)
class TransportConfig:
    """Host (DCN/TCP) transport configuration — the peer tier.

    The reference transport is hand-rolled blocking TCP with no pacing,
    backlog 5, and exit(-1) on any error (SURVEY.md §2.3, quirks Q8/Q10).
    """

    #: Max outgoing wire bytes/sec per link; 0 = unlimited. Realizes the
    #: reference README.md:31 bandwidth-limiting TODO (token bucket in the
    #: native transport).
    bandwidth_cap_bytes_per_sec: int = 0
    #: Listen backlog (reference uses 5; quirk Q10 — join storms get refused).
    listen_backlog: int = 128
    #: Seconds of link silence before a peer is declared dead and the link
    #: torn down + re-grafted (fixes reference README.md:33 / quirk Q8 —
    #: reference kills the whole process instead).
    peer_timeout_sec: float = 30.0
    #: Reconnect/rejoin attempts before giving up.
    max_rejoin_attempts: int = 8
    #: Speak the reference's exact wire format: raw host-endian float scale +
    #: LSB-first bitmask frames, 'Y'/'N'+sockaddr join protocol
    #: (SURVEY.md §2.3 wire spec). Enables interop A/B against C peers. Idle
    #: links emit one zero-scale keepalive frame per keepalive interval (the
    #: reference's quirk-Q2 behavior, which C peers' liveness relies on) —
    #: handled inside the native transport.
    wire_compat: bool = False
    #: Tree fan-out: children per node before the listener redirects joiners
    #: down the tree (the reference hard-codes 2 — its binary tree,
    #: src/sharedtensor.c:201-231). 1 builds a chain (interop tests route a
    #: joiner THROUGH an interior node this way); 1..16 (0 would silently
    #: close every join; >16 would be silently clamped by the native layer).
    max_children: int = 2

    def __post_init__(self):
        if not 1 <= self.max_children <= 16:
            raise ValueError(
                f"max_children must be in 1..16, got {self.max_children}"
            )


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Pod-tier (intra-slice) configuration: how the shared array is laid out
    across the local device mesh and which collective strategy syncs it."""

    #: Mesh axis name over which the shared array is sharded.
    shard_axis: str = "shard"
    #: Mesh axis name over which data-parallel peers (devices acting as
    #: independent workers) exchange compressed deltas.
    peer_axis: str = "peer"


@dataclasses.dataclass(frozen=True)
class Config:
    """Top-level config. ``rendezvous`` replaces the reference's
    (host, port) positional pair; everything else is new surface the
    reference hard-codes."""

    rendezvous_host: str = "127.0.0.1"
    rendezvous_port: int = 50000
    codec: CodecConfig = dataclasses.field(default_factory=CodecConfig)
    transport: TransportConfig = dataclasses.field(default_factory=TransportConfig)
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    #: Background sync frame pacing: target seconds between frames per link;
    #: 0 = free-running (reference behavior: fill all bandwidth, README.md:31).
    sync_interval_sec: float = 0.0
    #: Outstanding quantized-but-unsent frames per link in the sender
    #: pipeline. Each is dispatched on device and its device->host copy
    #: started asynchronously before older frames finish sending, so frame
    #: transfers overlap compute AND each other — on a high-latency
    #: device link (PCIe queue, TPU tunnel) throughput is bounded by
    #: bandwidth instead of round-trip latency. 1 = plain double buffering.
    send_pipeline_depth: int = 8
    #: Frames per wire message on the host (CPU) tier, native mode only.
    #: Successive codec frames are successive halvings of the same residual,
    #: so a sender can quantize K frames back-to-back and ship them as ONE
    #: message; the receiver's batched apply delivers them in one pass. For
    #: small tables the per-message engine cost (Python dispatch, framing,
    #: ACK) dominates the O(n) codec math, and bursting restores the frame
    #: rate (the reference's best case: its bare C loop hits 78k frames/s at
    #: 4 Ki elements, BASELINE.md). 0 = auto (burst small tables, stream
    #: big ones); 1 = always single-frame messages; K>1 = force K.
    frame_burst: int = 0
    #: Frames per wire message on the DEVICE tier (accelerator-backed
    #: peers), native mode only. K successive halvings quantize in ONE
    #: jitted dispatch and fetch with ONE device->host sync, so a
    #: high-latency device link (PCIe queue, TPU tunnel: ~8 ms/frame round
    #: trip, which capped E2E at 109 f/s at any pipeline depth) carries K
    #: frames per round trip instead of one. 0 = auto (16, wire-capped);
    #: 1 = single-frame messages (the pure pipelined path).
    device_frame_burst: int = 0
    #: Run the host-tier steady-state loop (quantize, encode, send, receive,
    #: flood apply, ACK ledger) in the native engine (native/stengine.cpp) —
    #: two C threads calling the same stcodec.c loops, no per-message
    #: interpreter cost. Python keeps handshakes and membership. Applies to
    #: host-tier native-protocol nodes only; the numpy tier remains the
    #: fallback (and ST_NATIVE_ENGINE=0 pins it, e.g. for parity tests).
    native_engine: bool = True


DEFAULT = Config()
