"""Typed configuration for shared-tensor-tpu.

The reference has no config system at all — its total configuration surface is
the three positional args of ``createOrFetch(host, port, tensor)`` plus
hard-coded constants (reference src/sharedtensor.c:349-352, :323; SURVEY.md
§5.6). This module realizes the survey's build note: a small typed config
covering rendezvous, mesh axes, codec policy, pacing (the reference README's
bandwidth-limit TODO), and fault timeouts (its disconnect-handling TODO).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional


class ScalePolicy(enum.Enum):
    """How the per-frame quantization scale is chosen from the residual.

    POW2_RMS is the reference policy: ``2^floor(log2(rms(residual)))``
    (reference src/sharedtensor.c:153-159). RMS skips the power-of-2 floor
    (slightly faster convergence, loses the cheap-to-compare property);
    ABS_MEAN uses mean(|r|) like signSGD-EF literature.
    """

    POW2_RMS = "pow2_rms"
    RMS = "rms"
    ABS_MEAN = "abs_mean"


@dataclasses.dataclass(frozen=True)
class CodecConfig:
    """Approximate-delta codec configuration.

    The reference codec is fixed: 1 sign bit per element, one global scale per
    frame chosen by POW2_RMS, error feedback via a per-link residual
    (reference src/sharedtensor.c:106-111, :145-177; SURVEY.md App. B). Those
    are the defaults here. ``per_leaf_scale`` realizes the reference README's
    "table sync" TODO (README.md:41): one scale per pytree leaf instead of one
    for the whole flat buffer, fixing the 1000:1 mixed-magnitude degradation
    measured in BASELINE.md.
    """

    scale_policy: ScalePolicy = ScalePolicy.POW2_RMS
    per_leaf_scale: bool = True
    #: Skip sending when scale == 0 (fixes reference quirk Q2, which sleeps 1s
    #: but still transmits an idle frame). Safe in wire-compat mode too: the
    #: native transport emits a zero-scale compat keepalive frame per
    #: keepalive interval when a link is idle — the reference's own idle
    #: behavior, which its peers' liveness expects — so the codec layer never
    #: needs to synthesize idle frames itself.
    suppress_zero_frames: bool = True
    #: r11 telemetry-adaptive link precision (native engine, native
    #: framing): the per-link residual-RMS telemetry (st_residual_norm's
    #: source) drives each link's wire precision — a link whose residual
    #: stops decaying upshifts to the sign2 2-bit codec (sign + magnitude
    #: bit selecting +/-s or +/-3s; the measured-best lab codec, promoted
    #: from parallel/ici_lab.py), a quiet link downshifts back to 1-bit.
    #: Emission is capability-gated per link (compat.SYNC_FLAG_SIGN2 /
    #: WELCOME flags), so mixed trees with pre-r11 or python-tier peers
    #: stay 1-bit toward those peers automatically; decoders on this
    #: release accept both widths unconditionally. ST_SIGN2=0 in the
    #: environment force-disables (the A/B / escape hatch, like
    #: ST_WIRE_TRACE).
    adaptive_precision: bool = True
    #: Governor thresholds/beat: upshift after 2 consecutive beats where
    #: the link's residual RMS GROWS past up_ratio * previous (the link is
    #: falling behind the mass arriving — chaos, retransmission storms, a
    #: saturated peer); downshift after 2 beats below down_ratio *
    #: previous (or quiesced). A healthy saturated link (flat rms at the
    #: wire's equilibrium) deliberately stays 1-bit.
    precision_up_ratio: float = 1.05
    precision_down_ratio: float = 0.5
    precision_interval_sec: float = 0.1
    #: r11 cascade quantize (native engine): frames quantized per MEMORY
    #: PASS over the residual. Frame 0's scales are measured as always;
    #: frames 1..k-1 take the halving schedule the measured sequence
    #: converges to, so K frames cost one table read + one write instead
    #: of K (the measured 1 Mi wall was the pass count, not bandwidth).
    #: Scales ride the wire — receivers are oblivious, any peer decodes.
    #: 1 = the r10 per-frame re-measured schedule. The committed sweep
    #: (ENGINE_SWEEP_r11.json, 1 Mi loopback) reads 47.5 GB/s equiv @1,
    #: 71.7 @8, then flat within box noise through 32 — the amortization
    #: saturates by ~8; 32 stays the default for the finer drain lattice
    #: (the extra sub-rms refinement levels are free in the same pass and
    #: the endgame merges them in fewer single-frame passes).
    cascade_frames: int = 32


@dataclasses.dataclass(frozen=True)
class TransportConfig:
    """Host (DCN/TCP) transport configuration — the peer tier.

    The reference transport is hand-rolled blocking TCP with no pacing,
    backlog 5, and exit(-1) on any error (SURVEY.md §2.3, quirks Q8/Q10).
    """

    #: Max outgoing wire bytes/sec per link; 0 = unlimited. Realizes the
    #: reference README.md:31 bandwidth-limiting TODO (token bucket in the
    #: native transport).
    bandwidth_cap_bytes_per_sec: int = 0
    #: Listen backlog (reference uses 5; quirk Q10 — join storms get refused).
    listen_backlog: int = 128
    #: Seconds of link silence before a peer is declared dead and the link
    #: torn down + re-grafted (fixes reference README.md:33 / quirk Q8 —
    #: reference kills the whole process instead).
    peer_timeout_sec: float = 30.0
    #: Reconnect/rejoin attempts before giving up.
    max_rejoin_attempts: int = 8
    #: Speak the reference's exact wire format: raw host-endian float scale +
    #: LSB-first bitmask frames, 'Y'/'N'+sockaddr join protocol
    #: (SURVEY.md §2.3 wire spec). Enables interop A/B against C peers. Idle
    #: links emit one zero-scale keepalive frame per keepalive interval (the
    #: reference's quirk-Q2 behavior, which C peers' liveness relies on) —
    #: handled inside the native transport.
    wire_compat: bool = False
    #: Tree fan-out: children per node before the listener redirects joiners
    #: down the tree (the reference hard-codes 2 — its binary tree,
    #: src/sharedtensor.c:201-231). 1 builds a chain (interop tests route a
    #: joiner THROUGH an interior node this way); 1..16 (0 would silently
    #: close every join; >16 would be silently clamped by the native layer).
    max_children: int = 2
    #: Per-attempt bound on connect() AND on the join-walk reply read. The
    #: reference (and this framework before r06) used a blocking connect: a
    #: rendezvous that silently drops packets — or accepts and never speaks —
    #: blocked the joiner FOREVER. 0 = legacy blocking connect.
    connect_timeout_sec: float = 5.0
    #: Total budget for the create-time join-or-become-master loop
    #: (exponential backoff with +/-50% jitter between attempts, so joiner
    #: herds and the two master-election races don't re-collide in
    #: lockstep). Past the budget, creation fails with a ConnectionError
    #: instead of retrying forever. 0 = default (30 s).
    join_timeout_sec: float = 30.0
    #: Go-back-N delivery timer (native framing only; see comm/wire.py's
    #: tx_seq docstring). When the OLDEST unacked DATA/BURST message on a
    #: live link goes unacknowledged this long, the sender retransmits the
    #: whole unacked tail byte-identical (same seqs — the receiver dedups,
    #: so a spurious retransmit is harmless). On a healthy TCP link ACKs
    #: arrive in milliseconds and this never fires; it exists for
    #: boundaries that can swallow a message whole (fault injection, dying
    #: proxies). After ``ack_retry_limit`` fruitless rounds the link is
    #: torn down into the LINK_DOWN -> rollback -> carry -> re-graft path.
    #: 0 = disabled (a silently-lost message then strands its ledger
    #: entries until the link dies).
    ack_timeout_sec: float = 5.0
    #: Retransmission rounds with zero ACK progress before the link is
    #: declared a black hole and torn down for re-graft. Values <= 0
    #: coerce to 1 round, identically on both data planes.
    ack_retry_limit: int = 8
    #: r11 multi-socket link striping (native framing only): each logical
    #: link runs over this many TCP connections, with messages round-robin
    #: striped across them (a per-message stripe sequence reassembles the
    #: stream in order at the receiver) and per-stripe sender/receiver
    #: threads — on fat pipes / loopback one stream's kernel path is a
    #: single-core bottleneck. A dead stripe degrades the link to the
    #: survivors when its loss is visible to the SENDER: messages still in
    #: hand re-route to the surviving sockets. A stripe that dies with
    #: already-written-but-undelivered wire data leaves a stripe-seq hole
    #: no survivor can fill — that link tears down cleanly via the
    #: engine's go-back-N (quarantine -> carry -> re-graft), it does not
    #: wedge; the LAST stripe's death is the link's either way. Joining
    #: with stripe_count > 1 uses the STT4 hello, which a
    #: pre-r11 acceptor rejects — keep 1 (the default; wire-identical to
    #: r10) to join older trees. 1..8.
    stripe_count: int = 1
    #: Per-link send quarantine: after this many CONSECUTIVE failed send
    #: attempts (~0.1 s each — i.e. ~N/10 seconds of a full send queue with
    #: zero drained bytes) the link is torn down and re-grafted instead of
    #: retried hot. A peer that stops draining but keeps its socket open
    #: would otherwise wedge our sender until peer_timeout_sec with frames
    #: pinned in its dead queue; quarantine converts the stall into the
    #: LINK_DOWN -> carry -> re-graft path the ledger already handles
    #: losslessly. 0 = never quarantine (retry until liveness timeout).
    quarantine_send_failures: int = 100
    #: r14 same-host shared-memory lane. When both ends of a link are on
    #: one host (boot-id match, advertised through the tolerant SYNC/
    #: WELCOME capability extension — compat.SYNC_FLAG_SHM), the link's
    #: DATA plane moves into SPSC rings in a mapped /dev/shm segment and
    #: the TCP connection stays up as the control/liveness/teardown
    #: channel — join, go-back-N seq accounting, SNAP/RESUME, quarantine/
    #: carry/re-graft semantics are untouched. Negotiation is fail-safe:
    #: any mismatch (pre-r14 peer, cross-host, /dev/shm unavailable,
    #: validation failure) silently keeps the link on TCP, with a
    #: ``shm_fallback`` timeline event recording why. ``ST_SHM=0`` in the
    #: environment force-disables the lane (the A/B escape hatch, like
    #: ST_SIGN2/ST_WIRE_TRACE).
    shm_enabled: bool = True
    #: CAP on bytes per shm ring DIRECTION (two rings per link). The peer
    #: sizes each link's rings to its table — twice the max traced sign2
    #: burst, floored at 1 MiB — and this cap bounds that (the sizing
    #: matters both ways on one memory system: a ring smaller than a
    #: burst runs the lane in lockstep — measured -9% at 16 Mi elements —
    #: while one much larger than needed cycles through DRAM instead of
    #: staying cache-resident — measured -8% at 1 Mi). Messages larger
    #: than the ring still STREAM through it correctly; tmpfs allocates
    #: pages lazily, so links touch only their high-water mark. Clamped
    #: to 64 KiB .. 1 GiB and page-rounded by the native layer.
    shm_ring_bytes: int = 1 << 26

    def __post_init__(self):
        if not 1 <= self.max_children <= 16:
            raise ValueError(
                f"max_children must be in 1..16, got {self.max_children}"
            )
        if not 1 <= self.stripe_count <= 8:
            raise ValueError(
                f"stripe_count must be in 1..8, got {self.stripe_count}"
            )
        if self.shm_ring_bytes < (1 << 16):
            raise ValueError(
                f"shm_ring_bytes must be >= 64 KiB, got {self.shm_ring_bytes}"
            )


@dataclasses.dataclass(frozen=True)
class FaultConfig:
    """Deterministic, seedable fault injection at the wire boundary
    (``comm/faults.py``; disabled by default — production pays only a
    None-check per send).

    The same fault classes exist on BOTH tiers, with tier-specific
    injection: on the Python wire tier this config injects directly (the
    peer consults a :class:`~shared_tensor_tpu.comm.faults.FaultPlan` in
    its send path); on the native-engine tier the engine's C send path
    never traverses that boundary, so the WIRE knobs must be rendered into
    the ``ST_FAULT_PLAN`` / ``ST_FAULT_CRASH`` environment hook table
    around node creation
    (:func:`~shared_tensor_tpu.comm.faults.to_env` renders this config
    into those strings; the peer logs a loud warning if wire faults are
    configured on an engine-tier peer with no env table set). The crash
    points fire on both tiers either way. Faults apply to DATA/BURST frames only — handshake and
    ACK traffic stays clean, so every injected fault exercises the recovery
    machinery (ledger rollback, carry, re-graft, quarantine) rather than
    wedging a join. The reference's only failure story is exit(-1) on any
    socket error; this layer exists to drive every recovery path this
    framework claims, deterministically, in tests and the chaos soak.
    """

    #: Master switch; False = zero injection, identical to no plan at all.
    enabled: bool = False
    #: RNG seed — the whole schedule is a pure function of (seed, per-link
    #: frame sequence), so runs are reproducible.
    seed: int = 0
    #: Probability a data frame is silently dropped at the wire (sender
    #: believes it delivered; its ledger entry stays unacked).
    drop_pct: float = 0.0
    #: Probability a data frame is sent twice (the receiver's tx_seq dedup
    #: discards the echo — exactly-once; see comm/wire.py).
    dup_pct: float = 0.0
    #: Probability a data frame is truncated to a random shorter length
    #: (well-framed short message: the receiver's decode rejects it without
    #: consuming its seq, and the sender's go-back-N retransmit re-delivers
    #: it whole — exact recovery). Native framing only; compat framing is
    #: fixed-size and would shear.
    truncate_pct: float = 0.0
    #: Probability a payload bit is flipped. PYTHON tier: the flip is
    #: geometry-aware (faults.corrupt) and lands in a frame's packed sign
    #: words — mis-applies ONE element by 2*scale, the bounded fault class
    #: convergence bounds are built on. NATIVE tier: the C injector is
    #: geometry-blind (it can hit seq/scale bytes, and a flipped finite
    #: scale EXPONENT rescales a whole frame by up to 2^127) — survival /
    #: decode-guard chaos only, never use it under a convergence-bound
    #: assertion.
    corrupt_pct: float = 0.0
    #: Probability a data frame send is delayed by ``delay_sec``.
    delay_pct: float = 0.0
    delay_sec: float = 0.005
    #: >= 0: every data frame past the Nth (per link) is silently swallowed
    #: — a stalled link whose sender keeps ledgering. Deterministic; the
    #: rollback/carry tests are built on this.
    stall_after_frames: int = -1
    #: > 0: hard-kill the link at its Nth data frame (transport-level sever
    #: -> LINK_DOWN -> carry -> re-graft).
    sever_after_frames: int = 0
    #: > 0: restrict ALL faults to this one link id — "stall or sever an
    #: individual link". Link ids are per-node and allocated from 1, so a
    #: joiner's first uplink is link 1; a re-grafted uplink gets a fresh id
    #: and runs clean, which is how the deterministic carry tests let the
    #: recovery path prove itself. 0 = every link.
    only_link: int = 0
    #: >= 0: restrict ALL (native-tier) faults to this stripe index of each
    #: striped link — the r11 per-stripe chaos arm. ``sever_after_frames``
    #: then kills just that SOCKET: the link must degrade to the surviving
    #: stripes (messages re-route) instead of dying. -1 = every stripe.
    only_stripe: int = -1
    #: Named protocol point at which to kill the peer process (os._exit):
    #: "mid-join-walk" (SYNC sent, snapshot not), "mid-burst" (frames
    #: ledgered, message not yet on the wire), "between-apply-and-ack"
    #: (mass applied + flooded, ACK not sent — the at-least-once window).
    #: "" = never. Tests may override the kill action via FaultPlan(on_crash=...).
    crash_point: str = ""
    #: Fire the crash on the Nth arrival at the point (1 = first).
    crash_after: int = 1


@dataclasses.dataclass(frozen=True)
class ObsConfig:
    """Unified telemetry (r08; ``shared_tensor_tpu/obs``). The subsystem is
    ON by default — the native event ring only records rare protocol /
    recovery / fault events and the OBS_r08 gate holds the hot-path cost
    under 2% — and ``ST_OBS=0`` in the environment force-disables it
    process-wide regardless of this config (the bench's A/B knob)."""

    #: Master switch for THIS peer's Python-tier instrumentation (registry
    #: histograms, event emission, native-ring draining). The native ring
    #: itself is process-wide (env ST_OBS).
    enabled: bool = True
    #: How often this peer's recv loop drains the native event ring into
    #: the process flight recorder. Small enough that a 2048-event
    #: per-thread ring survives chaos bursts; large enough to stay off the
    #: drain mutex.
    native_drain_interval_sec: float = 0.2
    #: Background JSONL metrics sink: one snapshot line per interval
    #: appended to this path ("" = no sink).
    jsonl_path: str = ""
    jsonl_interval_sec: float = 5.0
    #: r09 trace propagation: stamp outgoing DATA/BURST messages with the
    #: v2 wire framing's 13-byte trace context (origin node, origin
    #: monotonic ns, hop count — compat.WIRE_VERSION). Decoders accept
    #: both framings regardless; ST_WIRE_TRACE=0 force-pins v1 emission
    #: (e.g. to join a tree of pre-r09 peers). The obs-overhead gate holds
    #: the stamping cost inside the same <2% budget (OBS_r09).
    trace_wire: bool = True
    #: r09 in-band metric aggregation: how often this peer piggybacks its
    #: subtree's bounded metrics digest up the tree on the existing link
    #: (counters merged by sum, histograms by bucket-add, gauges by
    #: labeled max/min — obs/aggregate.py). The root's
    #: ``peer.metrics(cluster=True)`` / Prometheus exposition then serve a
    #: live whole-tree view. 0 = digests off. Native framing only (the
    #: reference compat protocol has no typed control messages).
    digest_interval_sec: float = 0.5
    #: Root-side live cluster view: when set, a peer with no uplink (the
    #: tree root) writes the merged cluster digest JSON to this path every
    #: digest interval — the file ``python -m shared_tensor_tpu.obs.top``
    #: tails for its terminal dashboard. "" = don't write.
    cluster_json_path: str = ""
    #: r18 fleet health plane (root-side, obs/health.py): when set, the
    #: tree root runs the health analyzer every digest beat — time-series
    #: store, per-shard heat, staleness SLO burn-rate alerts — and writes
    #: the machine-readable health document to this path (atomic replace,
    #: same discipline as cluster_json_path). "" = analyzer off.
    health_json_path: str = ""
    #: Ring depth per time-series (beats kept). 256 beats at the default
    #: 0.5s digest interval is ~2 minutes of history.
    health_history: int = 256
    #: Staleness SLO objective: a digest beat is "bad" when the fleet's
    #: worst offset-corrected staleness exceeds this many seconds.
    staleness_slo_sec: float = 1.0
    #: SLO error budget: the tolerated bad-beat fraction (burn rate 1.0
    #: means burning exactly the budget).
    slo_budget: float = 0.01
    #: Multi-window burn-rate severities: (name, long_sec, short_sec,
    #: threshold). A severity fires when BOTH windows burn past the
    #: threshold and clears when the short window recovers.
    slo_windows: tuple = (
        ("page", 60.0, 5.0, 14.4),
        ("ticket", 300.0, 30.0, 6.0),
    )
    #: Zipf-skew naming bar: the hot shard must out-rate the mean of the
    #: other shards by this factor before health.json names it.
    heat_skew_ratio: float = 3.0
    #: r18 clock plane: how often a non-root node probes its uplink with a
    #: wire.CLOCK offset sample (obs/clock.py; chaos-exempt control op).
    #: 0 = clock sync off (staleness stays raw).
    clock_sync_interval_sec: float = 1.0
    #: TEST/BENCH ONLY — simulated clock skew in seconds applied to this
    #: node's cross-node-comparable stamps (trace stamps, clock probes).
    #: Lets a single-host harness prove the offset estimator recovers a
    #: known skew. Env ``ST_CLOCK_SKEW_SEC`` overrides. 0 = off.
    clock_skew_sim_sec: float = 0.0


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Read-path serving tier (r10; ``shared_tensor_tpu/serve``): read-only
    subscriber leaves with bounded-staleness reads, verified against the
    r09 origin stamps. Consumed by :class:`serve.Subscriber` and by the
    WRITER side's FRESH-beat pacing for subscriber links."""

    #: Default staleness bound for ``Subscriber.read()`` when the call
    #: passes none: the read raises StalenessError unless the subscriber
    #: can PROVE its state is at most this many seconds behind (latest
    #: applied origin stamp, or the parent's FRESH drain mark — same-host
    #: CLOCK_MONOTONIC semantics, the r09 staleness caveat).
    max_staleness_sec: float = 1.0
    #: How often a writer sends a FRESH mark on an IDLE subscriber link
    #: (residual fully drained — "as of t you have everything"). Without
    #: it, a quiet tree would read as ever-staler even though the
    #: subscriber is exactly current. Bounds the staleness floor an idle
    #: subscriber can verify.
    fresh_interval_sec: float = 0.25
    #: Minimum seconds between subscriber resync handshakes (a seq gap on
    #: the unledgered subscriber link triggers a fresh SYNC/DONE re-seed;
    #: under sustained drop chaos this caps the re-seed storm).
    resync_min_interval_sec: float = 0.25
    #: Element range [lo, hi) to subscribe to (page/embedding-style reads);
    #: rounded outward to 32-element word boundaries on the wire. None =
    #: the full table.
    range: Optional[tuple[int, int]] = None


@dataclasses.dataclass(frozen=True)
class LifecycleConfig:
    """r12 cluster lifecycle: consistent-cut snapshot/restore, bounded-time
    restart, drain-node, and the ``python -m shared_tensor_tpu.ctl``
    operator surface. The snapshot barrier is root-initiated
    (``peer.snapshot_cluster``): a quiesce marker (wire.SNAP) floods down
    the tree on the control plane, every node pauses NEW production,
    drains its in-flight ledgers to empty, writes a per-node shard file
    and acks up; the root assembles ``MANIFEST.json`` with per-node sha256
    digests and releases the barrier (wire.RESUME)."""

    #: Stable node name used for shard files (``shard_<name>.npz``) and as
    #: the ``ctl drain`` target. "" = ``node-<obs_id>`` (process-unique but
    #: NOT stable across restarts — set explicit names in any deployment
    #: that intends to restore).
    node_name: str = ""
    #: Shard file to restore from BEFORE joining the tree (the full-cluster
    #: restart path): values load into the replica, and a non-master node's
    #: checkpointed uplink residual (+ old carry) becomes the re-graft
    #: carry, so the join's diff handshake re-delivers exactly the owed
    #: mass — no retransmission storm, no double-apply (README "Cluster
    #: lifecycle"). "" = fresh start.
    restore_path: str = ""
    #: Root-side operator command channel: when set, a peer with no uplink
    #: polls ``<ctl_dir>/cmd.json`` for commands written by
    #: ``python -m shared_tensor_tpu.ctl`` (snapshot / restore / drain) and
    #: writes ``<ctl_dir>/result.json`` back. File-based like
    #: ObsConfig.cluster_json_path, so the CLI needs no socket into the
    #: cluster. "" = disabled.
    ctl_dir: str = ""
    #: Root-side budget for one whole-cluster snapshot/restore barrier
    #: (marker flood + drain-to-quiesce + shard I/O + acks). Past it the
    #: root RESUMEs the tree anyway and reports failure — a lifecycle
    #: operation may fail, but it must never leave the cluster paused.
    snapshot_timeout_sec: float = 60.0
    #: Safety net on every non-root node: if a barrier's RESUME never
    #: arrives (root died mid-barrier), unpause after this long and log —
    #: same never-leave-paused rule as the root's timeout.
    pause_timeout_sec: float = 30.0
    #: leave() budget for a routed ``ctl drain <node>`` (seal + drain +
    #: close on the target node).
    drain_grace_sec: float = 30.0


@dataclasses.dataclass(frozen=True)
class ShardConfig:
    """r16 cluster-sharded tensor (``shared_tensor_tpu/shard``): the table
    is partitioned into contiguous word ranges, each owned by exactly one
    cluster node. Per-node memory is the owned slice plus transient
    outboxes — O(total / n_shards) at steady state — instead of a full
    replica; a writer's out-of-shard delta rides owner-routed wire.FWD
    frames toward the shard's owner (no per-hop re-quantization), and
    readers assemble views by subscribing to owner shards (shard.gather).
    """

    #: Number of contiguous shards the master partitions the word space
    #: into at creation. 0 = sharding off (the classic full-replica
    #: protocol; ``create_or_fetch_sharded`` then returns a classic peer).
    n_shards: int = 0
    #: The shard index this node claims at join (the master claims its own
    #: index locally). -1 = a member that owns no shard: it still joins
    #: the tree, routes FWD traffic and may write/read, but holds no
    #: slice. Claims are arbitrated by the master; a taken index is
    #: DENIED and creation fails.
    shard_index: int = -1
    #: The address OTHER nodes (gather legs, takeover peers) should dial
    #: to reach THIS node's listener — recorded in the node's OwnerEntry
    #: at claim/handoff time. "" = advertise the rendezvous host argument,
    #: which is correct exactly when every node shares one host (the
    #: loopback cluster); multi-host deployments must set each node's
    #: reachable address here or every gather toward a non-master owner
    #: dials the wrong machine.
    advertise_host: str = ""
    #: Restart path: directory holding a sharded-snapshot MANIFEST.json
    #: (utils/checkpoint.write_manifest ``shards`` entries). The node
    #: loads its shard's slice/outboxes/dedup state BEFORE joining and
    #: claims with takeover semantics (the master re-grants the index at
    #: a higher epoch). "" = fresh start.
    restore_dir: str = ""
    #: Tree fan-out for sharded nodes (SEPARATE from
    #: TransportConfig.max_children): owner nodes also serve read-only
    #: subscriber leaves on the same listener, so they need slots beyond
    #: the tree's writer fan-out. This matters more than for classic
    #: trees: the transport redirects joiners DOWN the tree when slots
    #: fill, which is harmless for a full-replica subscription (any node
    #: serves the whole table) but breaks a gather leg that must land on
    #: one specific owner — so the sharded default sits near the
    #: transport's cap (16) and shard.gather documents the residual
    #: limit.
    max_children: int = 12
    #: Budget for the join-time claim round trip (SYNC -> map -> claim ->
    #: grant flood). Past it, creation fails instead of waiting forever.
    claim_timeout_sec: float = 20.0
    #: Bound on FWD messages parked while a shard's route is unknown
    #: (owner not yet granted, route purged by a LINK_DOWN, owner being
    #: restored). Overflow drops the OLDEST parked message and counts it
    #: (st_shard_park_drops_total) — loud bounded loss, never unbounded
    #: memory.
    park_cap: int = 4096
    #: r17 engine-tier shard plane: run the FWD hot loop (outbox pump,
    #: verbatim relay, owner dedup+apply, go-back-N) in the native engine
    #: (shard/engine_lane.py) when the lib is available. False pins the
    #: r16 python-tier plane — the semantic reference, wire-identical;
    #: the ST_SHARD_ENGINE=0 env escape hatch pins it process-wide.
    engine_lane: bool = True
    #: r17 library-side writer admission control (ROADMAP 1(d)): bound on
    #: resident per-target-shard outbox bytes. An add() whose
    #: out-of-shard deposits would exceed it waits for the FWD plane to
    #: drain room (outbox_overflow="block") or raises ShardBackpressure
    #: ("raise") — the backpressure that previously lived only in the
    #: chaos harness's alloc-polling loop. 0 = unlimited (the r16
    #: behavior: one outbox per remote shard can accumulate). The
    #: projection is conservative at slice granularity: each target shard
    #: of the delta counts one full outbox slice.
    outbox_limit_bytes: int = 0
    #: "block" (wait up to outbox_block_timeout_sec, then raise) or
    #: "raise" (fail the add() immediately).
    outbox_overflow: str = "block"
    #: How long a blocking add() waits for outbox room before raising
    #: ShardBackpressure (a stalled link should fail the writer loudly,
    #: never wedge it forever).
    outbox_block_timeout_sec: float = 30.0


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Pod-tier (intra-slice) configuration: how the shared array is laid out
    across the local device mesh and which collective strategy syncs it."""

    #: Mesh axis name over which the shared array is sharded.
    shard_axis: str = "shard"
    #: Mesh axis name over which data-parallel peers (devices acting as
    #: independent workers) exchange compressed deltas.
    peer_axis: str = "peer"


@dataclasses.dataclass(frozen=True)
class Config:
    """Top-level config. ``rendezvous`` replaces the reference's
    (host, port) positional pair; everything else is new surface the
    reference hard-codes."""

    rendezvous_host: str = "127.0.0.1"
    rendezvous_port: int = 50000
    codec: CodecConfig = dataclasses.field(default_factory=CodecConfig)
    transport: TransportConfig = dataclasses.field(default_factory=TransportConfig)
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    #: Deterministic fault injection (tests / chaos soak); disabled default.
    faults: FaultConfig = dataclasses.field(default_factory=FaultConfig)
    #: Unified telemetry (metrics registry + event timeline + flight
    #: recorder); enabled default, <2% hot-path cost (OBS_r08 gate).
    obs: ObsConfig = dataclasses.field(default_factory=ObsConfig)
    #: Read-path serving tier (r10): subscriber staleness bounds, FRESH
    #: beat pacing, range subscription.
    serve: ServeConfig = dataclasses.field(default_factory=ServeConfig)
    #: Cluster lifecycle (r12): node naming, restart-restore, operator
    #: command channel, barrier timeouts.
    lifecycle: LifecycleConfig = dataclasses.field(
        default_factory=LifecycleConfig
    )
    #: Cluster-sharded tensor (r16): shard count, this node's claim,
    #: restart-restore, routing bounds. n_shards=0 keeps the classic
    #: full-replica protocol.
    shard: ShardConfig = dataclasses.field(default_factory=ShardConfig)
    #: Background sync frame pacing: target seconds between frames per link;
    #: 0 = free-running (reference behavior: fill all bandwidth, README.md:31).
    sync_interval_sec: float = 0.0
    #: Outstanding quantized-but-unsent frames per link in the sender
    #: pipeline. Each is dispatched on device and its device->host copy
    #: started asynchronously before older frames finish sending, so frame
    #: transfers overlap compute AND each other — on a high-latency
    #: device link (PCIe queue, TPU tunnel) throughput is bounded by
    #: bandwidth instead of round-trip latency. 1 = plain double buffering.
    send_pipeline_depth: int = 8
    #: Warm slots the r07 zero-copy frame pool keeps per peer (wire.FramePool
    #: ``keep``): released send slots beyond this are freed, bounding an
    #: idle peer's high-water memory while keeping steady-state sends
    #: allocation-free. The pool itself is bounded by the go-back-N send
    #: window (peer.SEND_WINDOW live slots per link, worst case); slots are
    #: wire-message-sized (up to ~16 MiB at the largest burst), so ``keep``
    #: trades idle memory against re-allocation on bursty duty cycles.
    frame_pool_keep: int = 4
    #: Frames per wire message on the host (CPU) tier, native mode only.
    #: Successive codec frames are successive halvings of the same residual,
    #: so a sender can quantize K frames back-to-back and ship them as ONE
    #: message; the receiver's batched apply delivers them in one pass. For
    #: small tables the per-message engine cost (Python dispatch, framing,
    #: ACK) dominates the O(n) codec math, and bursting restores the frame
    #: rate (the reference's best case: its bare C loop hits 78k frames/s at
    #: 4 Ki elements, BASELINE.md). 0 = auto (burst small tables, stream
    #: big ones); 1 = always single-frame messages; K>1 = force K.
    frame_burst: int = 0
    #: Frames per wire message on the DEVICE tier (accelerator-backed
    #: peers), native mode only. K successive halvings quantize in ONE
    #: jitted dispatch and fetch with ONE device->host sync, so a
    #: high-latency device link (PCIe queue, TPU tunnel: ~8 ms/frame round
    #: trip, which capped E2E at 109 f/s at any pipeline depth) carries K
    #: frames per round trip instead of one. 0 = auto (16, wire-capped);
    #: 1 = single-frame messages (the pure pipelined path).
    device_frame_burst: int = 0
    #: Run the host-tier steady-state loop (quantize, encode, send, receive,
    #: flood apply, ACK ledger) in the native engine (native/stengine.cpp) —
    #: two C threads calling the same stcodec.c loops, no per-message
    #: interpreter cost. Python keeps handshakes and membership. Applies to
    #: host-tier native-protocol nodes only; the numpy tier remains the
    #: fallback (and ST_NATIVE_ENGINE=0 pins it, e.g. for parity tests).
    native_engine: bool = True


DEFAULT = Config()
