"""Serialized on-demand builds of the native/ libraries.

Both the transport (comm/transport.py) and the host codec (ops/codec_np.py)
run ``make`` on first load so an edited source can never keep serving a
previously-built .so. Two peer processes starting concurrently against stale
sources would otherwise both rebuild the same .so in place while a third
dlopens the partially-written file; an inter-process flock around the make
(and the subsequent dlopen in the callers, which only happens after their
own locked make returned) serializes that.
"""

from __future__ import annotations

import contextlib
import fcntl
import os
import pathlib
import subprocess

#: ST_NATIVE_DIR redirects every loader (transport, codec, engine) to an
#: alternate prebuilt library directory — e.g. ``native/san`` for the
#: ASan+UBSan builds (``make -C native sanitize``; tests/test_sanitizers.py).
#: When set, run_make() is a no-op: the alternate directory is built by its
#: owner and has no Makefile of its own.
_OVERRIDE = os.environ.get("ST_NATIVE_DIR")
NATIVE_DIR = (
    pathlib.Path(_OVERRIDE).resolve()
    if _OVERRIDE
    else pathlib.Path(__file__).resolve().parent.parent / "native"
)


@contextlib.contextmanager
def build_lock():
    """Inter-process exclusive lock scoped to the native/ build directory."""
    lock_path = NATIVE_DIR / ".build.lock"
    with open(lock_path, "w") as f:
        fcntl.flock(f, fcntl.LOCK_EX)
        try:
            yield
        finally:
            fcntl.flock(f, fcntl.LOCK_UN)


def run_make(target: str | None = None, force: bool = False) -> None:
    """make -C native/ [target], serialized across processes. No-op under
    ST_NATIVE_DIR (prebuilt alternate directory — see module docstring)."""
    if _OVERRIDE:
        return
    cmd = ["make", "-C", str(NATIVE_DIR)]
    if force:
        cmd.append("-B")
    if target:
        cmd.append(target)
    with build_lock():
        subprocess.run(cmd, check=True, capture_output=True)
