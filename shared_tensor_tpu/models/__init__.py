"""Workload models for the shared-tensor training story (BASELINE configs
2 and 4). The reference is model-agnostic parameter sync (SURVEY.md §5.7);
these models exist because its README names them as the intended workloads
(char-rnn, reference README.md:37) and benchmark arms (ResNet async-DP)."""

from . import char_rnn, resnet
from .char_rnn import (
    CharRNNConfig,
    encode_corpus,
    forward,
    init_params,
    loss_fn,
    make_batches,
    sample,
)
from .resnet import ResNetConfig

__all__ = [
    "char_rnn",
    "resnet",
    "CharRNNConfig",
    "ResNetConfig",
    "init_params",
    "forward",
    "loss_fn",
    "sample",
    "make_batches",
    "encode_corpus",
]
