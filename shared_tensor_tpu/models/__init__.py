"""Workload models for the shared-tensor training story (BASELINE configs
2 and 4). The reference is model-agnostic parameter sync (SURVEY.md §5.7);
these models exist because its README names them as the intended workloads
(char-rnn, reference README.md:37) and benchmark arms (ResNet async-DP)."""

from .char_rnn import CharRNNConfig, forward, init_params, loss_fn, make_batches, sample

__all__ = [
    "CharRNNConfig",
    "init_params",
    "forward",
    "loss_fn",
    "sample",
    "make_batches",
]
