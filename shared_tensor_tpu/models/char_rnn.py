"""char-rnn: the flagship workload model (BASELINE config 2).

The reference lists "More complete examples, including char-rnn" as an
unrealized TODO (reference README.md:37); its intended workload is
asynchronous data-parallel SGD where each worker trains a local model and
merges parameter deltas through the shared tensor (reference README.md:13-19,
example.lua:14-26). This module supplies that model, TPU-first:

- A multi-layer LSTM over byte-level tokens, the classic Karpathy char-rnn
  architecture, written as pure functions on an explicit parameter pytree —
  the pytree is exactly what the shared-tensor table syncs (ops/table.py).
- All matmuls run in bfloat16 with float32 accumulation
  (``preferred_element_type``) so they land on the MXU; gate math, cell state
  and parameters stay float32 on the VPU.
- Time recurrence is a single ``lax.scan`` per layer (compiler-friendly: one
  traced step, static shapes), with the input projection for ALL timesteps
  hoisted out of the scan as one large [T*B, E] x [E, 4H] matmul — inside the
  scan only the [B, H] x [H, 4H] recurrent matmul remains. Dimensions default
  to multiples of 128 to match MXU/VPU tiling.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class CharRNNConfig:
    """Defaults are the flagship size (Karpathy's char-rnn default is a
    2-layer LSTM with 128 hidden units; we default larger and MXU-aligned)."""

    vocab: int = 256  # byte-level: any text works with no tokenizer
    embed: int = 256
    hidden: int = 512
    layers: int = 2

    @property
    def param_count(self) -> int:
        n = self.vocab * self.embed
        d = self.embed
        for _ in range(self.layers):
            n += (d + self.hidden + 1) * 4 * self.hidden
            d = self.hidden
        n += (self.hidden + 1) * self.vocab
        return n


def init_params(key: jax.Array, cfg: CharRNNConfig) -> Any:
    """Parameter pytree. Scaled-normal init; forget-gate bias starts at 1 so
    gradients flow through time from step one (standard LSTM practice)."""
    ks = jax.random.split(key, 2 + cfg.layers)
    params: dict[str, Any] = {
        "embed": jax.random.normal(ks[0], (cfg.vocab, cfg.embed), jnp.float32)
        * 0.08,
    }
    lstm = []
    d = cfg.embed
    for li in range(cfg.layers):
        kx, kh = jax.random.split(ks[1 + li])
        # Gate order along the 4H axis: [input, forget, cell(g), output].
        b = jnp.zeros((4 * cfg.hidden,), jnp.float32)
        b = b.at[cfg.hidden : 2 * cfg.hidden].set(1.0)
        lstm.append(
            {
                "wx": jax.random.normal(kx, (d, 4 * cfg.hidden), jnp.float32)
                * (1.0 / jnp.sqrt(d)),
                "wh": jax.random.normal(kh, (cfg.hidden, 4 * cfg.hidden), jnp.float32)
                * (1.0 / jnp.sqrt(cfg.hidden)),
                "b": b,
            }
        )
        d = cfg.hidden
    params["lstm"] = lstm
    params["proj"] = {
        "w": jax.random.normal(ks[-1], (cfg.hidden, cfg.vocab), jnp.float32)
        * (1.0 / jnp.sqrt(cfg.hidden)),
        "b": jnp.zeros((cfg.vocab,), jnp.float32),
    }
    return params


def _mm(a: jnp.ndarray, w: jnp.ndarray) -> jnp.ndarray:
    """bf16 x bf16 -> f32 matmul (MXU path)."""
    return jax.lax.dot(
        a.astype(jnp.bfloat16),
        w.astype(jnp.bfloat16),
        precision=None,
        preferred_element_type=jnp.float32,
    )


def _cell(
    layer: dict, h: jnp.ndarray, c: jnp.ndarray, gx_t: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """One LSTM cell update given the input half of the gate pre-activation
    ``gx_t`` = x @ wx + b (shared by training and sampling paths)."""
    gates = gx_t + _mm(h, layer["wh"])
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return h, c


def _lstm_layer(layer: dict, xs: jnp.ndarray, hidden: int) -> jnp.ndarray:
    """Run one LSTM layer over xs: f32[T, B, D] -> f32[T, B, H].

    The input half of the gate pre-activation (xs @ wx + b) has no recurrent
    dependency, so it is computed for every timestep in one big MXU matmul;
    the scan body carries only (h, c) and the [B,H]x[H,4H] matmul.
    """
    t, b_sz, d = xs.shape
    gx = _mm(xs.reshape(t * b_sz, d), layer["wx"]).reshape(t, b_sz, 4 * hidden)
    gx = gx + layer["b"]

    def step(carry, gx_t):
        h, c = _cell(layer, *carry, gx_t)
        return (h, c), h

    h0 = jnp.zeros((b_sz, hidden), jnp.float32)
    (_, _), hs = jax.lax.scan(step, (h0, h0), gx)
    return hs


@partial(jax.jit, static_argnames=("cfg",))
def forward(params: Any, tokens: jnp.ndarray, cfg: CharRNNConfig) -> jnp.ndarray:
    """Logits for next-token prediction: int32[B, T] -> f32[B, T, vocab]."""
    # mode="clip": out-of-vocab ids clamp instead of producing NaN embeddings
    # (jnp.take's default fill mode poisons the whole table via the flood
    # otherwise — the Q9 class of failure).
    x = jnp.take(params["embed"], tokens, axis=0, mode="clip")  # [B, T, E]
    xs = jnp.swapaxes(x, 0, 1)  # [T, B, E] for scan
    for layer in params["lstm"]:
        xs = _lstm_layer(layer, xs, cfg.hidden)
    logits = _mm(
        xs.reshape(-1, cfg.hidden), params["proj"]["w"]
    ) + params["proj"]["b"]
    t, b_sz = xs.shape[0], xs.shape[1]
    return jnp.swapaxes(logits.reshape(t, b_sz, cfg.vocab), 0, 1)


def loss_fn(params: Any, batch: tuple[jnp.ndarray, jnp.ndarray], cfg: CharRNNConfig) -> jnp.ndarray:
    """Mean next-char cross-entropy. ``batch`` = (inputs, targets), both
    int32[B, T]."""
    inputs, targets = batch
    logits = forward(params, inputs, cfg)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


@partial(jax.jit, static_argnames=("cfg", "length"))
def sample(
    params: Any,
    key: jax.Array,
    prompt: jnp.ndarray,
    cfg: CharRNNConfig,
    length: int = 256,
    temperature: float = 1.0,
) -> jnp.ndarray:
    """Autoregressive sampling: int32[P] prompt -> int32[length] continuation.

    Single-token steps keep (h, c) per layer in carry; the whole generation is
    one ``lax.scan`` (no Python loop, one compile).
    """

    def step_token(state, tok):
        # Batch-of-1 shapes so the exact training cell (_cell) is reused.
        hs, cs = state
        x = params["embed"][tok][None, :]
        new_h, new_c = [], []
        for li, layer in enumerate(params["lstm"]):
            gx = _mm(x, layer["wx"]) + layer["b"]
            h, c = _cell(layer, hs[li], cs[li], gx)
            new_h.append(h)
            new_c.append(c)
            x = h
        logits = (_mm(x, params["proj"]["w"]) + params["proj"]["b"])[0]
        return (tuple(new_h), tuple(new_c)), logits

    zeros = tuple(
        jnp.zeros((1, cfg.hidden), jnp.float32) for _ in range(cfg.layers)
    )
    state = (zeros, zeros)

    state, logits = jax.lax.scan(step_token, state, prompt)
    last_logits = logits[-1]

    def gen(carry, k):
        state, logits = carry
        tok = jax.random.categorical(k, logits / temperature)
        state, logits = step_token(state, tok)
        return (state, logits), tok

    keys = jax.random.split(key, length)
    _, toks = jax.lax.scan(gen, (state, last_logits), keys)
    return toks


def encode_corpus(text: bytes, vocab: int | None = None) -> jnp.ndarray:
    """One-time byte-text -> int32 device array conversion. Convert the
    corpus ONCE and pass the array to make_batches in the training loop —
    re-uploading a multi-MB corpus every step would dominate step time.
    ``vocab`` folds bytes into a smaller id space (tests / tiny models)."""
    data = jnp.frombuffer(text, dtype=jnp.uint8).astype(jnp.int32)
    if vocab is not None:
        data = data % vocab
    return data


def make_batches(
    text: bytes | jnp.ndarray,
    batch: int,
    seq: int,
    key: jax.Array,
    n_peer: int | None = None,
    vocab: int | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Random (inputs, targets) windows from a byte corpus. With ``n_peer``,
    returns [n_peer, batch, seq] so each pod peer trains on its own slice —
    the reference's N-workers-on-one-corpus story (example.lua:6-12).
    ``text`` may be raw bytes (converted on the fly; fine for tests) or the
    device array from :func:`encode_corpus` (training loops). ``vocab`` folds
    ids on the gathered windows, so it works for both input kinds."""
    if len(text) < seq + 2:
        raise ValueError(
            f"corpus has {len(text)} tokens; need at least seq+2 = {seq + 2}"
        )
    data = encode_corpus(text) if isinstance(text, bytes) else text
    if vocab is not None:
        # Fold AFTER gathering (below) would also work, but folding the ids
        # here keeps y's shifted-by-one relation to x exact under the fold.
        data = data % vocab
    count = (n_peer or 1) * batch
    starts = jax.random.randint(key, (count,), 0, data.shape[0] - seq - 1)
    idx = starts[:, None] + jnp.arange(seq)[None, :]
    x = data[idx]
    y = data[idx + 1]
    if n_peer is not None:
        x = x.reshape(n_peer, batch, seq)
        y = y.reshape(n_peer, batch, seq)
    return x, y
