"""ResNet-18: the async-DP benchmark arm model (BASELINE config 4:
"ResNet-18 data-parallel async SGD, 8 peers, compressed-delta vs exact
allreduce").

The reference is model-agnostic parameter sync (SURVEY.md §5.7) — this model
exists purely as the convergence-comparison workload. TPU-first choices:

- convs in bfloat16 with float32 accumulation (``preferred_element_type``) so
  they tile onto the MXU; NHWC layout (TPU-native).
- BatchNorm uses current-batch statistics only (training mode): the
  normalization is a pure function of (params, batch), so the whole model
  stays functional and every learnable tensor lives in the shared table. No
  running-stat mutable state to special-case in the sync.
- The default geometry is the CIFAR variant (3x3 stem, no maxpool) so tests
  and benches run on 32x32 inputs; ``stem_stride``/``stem_pool`` give the
  ImageNet stem.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stages: tuple[int, ...] = (2, 2, 2, 2)  # ResNet-18: two basic blocks/stage
    width: int = 64
    classes: int = 10
    stem_kernel: int = 3
    stem_stride: int = 1
    stem_pool: bool = False  # True for the ImageNet 7x7/s2 + maxpool stem


def _conv_init(key, kh, kw, cin, cout):
    fan_in = kh * kw * cin
    return jax.random.normal(key, (kh, kw, cin, cout), jnp.float32) * jnp.sqrt(
        2.0 / fan_in
    )


def init_params(key: jax.Array, cfg: ResNetConfig) -> Any:
    keys = iter(jax.random.split(key, 4 + sum(cfg.stages) * 3))
    w = cfg.width
    params: dict[str, Any] = {
        "stem": {
            "conv": _conv_init(next(keys), cfg.stem_kernel, cfg.stem_kernel, 3, w),
            "scale": jnp.ones((w,), jnp.float32),
            "bias": jnp.zeros((w,), jnp.float32),
        }
    }
    blocks = []
    cin = w
    for si, depth in enumerate(cfg.stages):
        cout = w * (2**si)
        for bi in range(depth):
            stride = 2 if (si > 0 and bi == 0) else 1
            blk = {
                "conv1": _conv_init(next(keys), 3, 3, cin, cout),
                "scale1": jnp.ones((cout,), jnp.float32),
                "bias1": jnp.zeros((cout,), jnp.float32),
                "conv2": _conv_init(next(keys), 3, 3, cout, cout),
                # zero-init the residual branch's last norm scale: each block
                # starts as identity (standard trick, stabilizes early async
                # training where peers see each other's noisy deltas)
                "scale2": jnp.zeros((cout,), jnp.float32),
                "bias2": jnp.zeros((cout,), jnp.float32),
            }
            if stride != 1 or cin != cout:
                blk["proj"] = _conv_init(next(keys), 1, 1, cin, cout)
            blocks.append(blk)
            cin = cout
    params["blocks"] = blocks
    params["head"] = {
        "w": jax.random.normal(next(keys), (cin, cfg.classes), jnp.float32)
        * (1.0 / jnp.sqrt(cin)),
        "b": jnp.zeros((cfg.classes,), jnp.float32),
    }
    return params


def _conv(x, w, stride=1):
    # Both operands bf16 (MXU path; XLA accumulates bf16 convs in f32
    # internally), cast back to f32 for the norm/activation VPU math.
    # preferred_element_type=f32 would be cleaner but its conv transpose
    # (gradient) rule rejects the mixed-dtype cotangent.
    out = jax.lax.conv_general_dilated(
        x.astype(jnp.bfloat16),
        w.astype(jnp.bfloat16),
        window_strides=(stride, stride),
        padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return out.astype(jnp.float32)


def _bn(x, scale, bias):
    """Batch statistics over (N, H, W) — training-mode BatchNorm as a pure
    function; f32 throughout (VPU)."""
    mean = jnp.mean(x, axis=(0, 1, 2), keepdims=True)
    var = jnp.var(x, axis=(0, 1, 2), keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + 1e-5) * scale + bias


@partial(jax.jit, static_argnames=("cfg",))
def forward(params: Any, images: jnp.ndarray, cfg: ResNetConfig) -> jnp.ndarray:
    """f32[N, H, W, 3] -> logits f32[N, classes]."""
    x = _conv(images, params["stem"]["conv"], cfg.stem_stride)
    x = jax.nn.relu(_bn(x, params["stem"]["scale"], params["stem"]["bias"]))
    if cfg.stem_pool:
        x = jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME"
        )
    bi = 0
    for si, depth in enumerate(cfg.stages):
        for b in range(depth):
            blk = params["blocks"][bi]
            stride = 2 if (si > 0 and b == 0) else 1
            y = jax.nn.relu(_bn(_conv(x, blk["conv1"], stride), blk["scale1"], blk["bias1"]))
            y = _bn(_conv(y, blk["conv2"]), blk["scale2"], blk["bias2"])
            sc = _conv(x, blk["proj"], stride) if "proj" in blk else x
            x = jax.nn.relu(sc + y)
            bi += 1
    x = jnp.mean(x, axis=(1, 2))  # global average pool
    return jax.lax.dot(
        x.astype(jnp.bfloat16),
        params["head"]["w"].astype(jnp.bfloat16),
        preferred_element_type=jnp.float32,
    ) + params["head"]["b"]


def loss_fn(params: Any, batch: tuple[jnp.ndarray, jnp.ndarray], cfg: ResNetConfig) -> jnp.ndarray:
    """Mean softmax cross-entropy; ``batch`` = (images f32[N,H,W,3], labels
    int32[N])."""
    images, labels = batch
    logits = forward(params, images, cfg)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))
