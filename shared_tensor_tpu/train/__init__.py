"""Training tier: async data-parallel SGD over the pod's compressed sync
(the reference's intended workload, README.md:13-19, made a first-class
subsystem)."""

from .async_sgd import PodTrainer, build_train_step
from .hierarchical import HierarchicalTrainer

__all__ = ["PodTrainer", "build_train_step", "HierarchicalTrainer"]
