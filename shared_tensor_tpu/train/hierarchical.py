"""Hierarchical tier: pods synced over ICI internally, bridged over the
DCN/TCP peer tree externally.

This composes the framework's two communication tiers into the reference's
actual multi-machine scenario (reference README.md:26: peers on mutually
reachable hosts, one port per tensor), at pod granularity: each *pod* (a
device mesh running PodTrainer's fused compressed sync) acts as ONE peer in
the TCP tree (comm/peer.py — the reference's self-organizing binary-tree
overlay, src/sharedtensor.c:192-332). Updates thus flow

  device peer --ICI all-gather (1-bit frames)--> pod replica mean
  pod --TCP tree codec frames (1-bit, error feedback)--> other pods

with the codec's error-feedback at BOTH levels and no synchronization
between them — a pod never blocks on the WAN; cross-pod deltas arrive
whenever the tree delivers them (the reference's async contract,
README.md:24, held end-to-end).

Bridge semantics (all additive, order-free):

- push: the pod's net training progress since the last push — the change of
  the pod-mean replica — is `add()`ed into the tree exactly like a worker's
  local update (reference addFromTensor, src/sharedtensor.c:334-344).
- pull: whatever the tree delivered since the last pull (other pods'
  deltas; measured against what we already pushed) is applied to every
  device replica's values, residuals untouched — split horizon at the pod
  boundary.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from ..comm.peer import SharedTensorPeer
from ..ops.table import unflatten
from ..parallel.ici import apply_external
from .async_sgd import PodTrainer


class HierarchicalTrainer:
    """Wraps a PodTrainer and a SharedTensorPeer into one training-loop
    peer. ``sync_every`` pod steps between tree exchanges (the analog of
    the reference's natural TCP backpressure pacing: residual mass simply
    accumulates between frames).

    Contract: at construction the pod replicas must equal the peer-tier
    replica (the bridge tracks *deltas* on both sides from that common
    point; a mismatched start is permanently baked into this pod's model).
    Use :meth:`create` — it seeds a master pod from the template and a
    joiner pod from the state the tree streamed over — rather than wiring
    the pieces manually."""

    @classmethod
    def create(
        cls,
        mesh,
        host: str,
        port: int,
        template: Any,
        loss_fn,
        sync_every: int = 1,
        peer_config=None,
        timeout: float = 30.0,
        pod_sync_every: int = 1,
        **pod_kwargs,
    ) -> "HierarchicalTrainer":
        """create_or_fetch at pod granularity: become the master pod (seeded
        from ``template``) or join the tree and start the pod from the
        replica state the tree transferred (the reference's
        state-transfer-through-codec join, src/sharedtensor.c:379-391).

        Two pacing knobs, one per tier: ``sync_every`` = pod steps between
        TREE exchanges (this class's own pacing); ``pod_sync_every`` = pod
        steps between INTRA-POD ICI exchanges (threaded to
        ``PodTrainer.sync_every`` — it cannot ride ``pod_kwargs`` because
        the name collides with this function's parameter)."""
        pod_kwargs.setdefault("sync_every", pod_sync_every)
        from ..comm.peer import create_or_fetch

        peer = create_or_fetch(host, port, template, peer_config, timeout)
        try:
            # ONE snapshot seeds both the pod and the bridge bookkeeping.
            # Codec frames keep streaming into peer.st after create_or_fetch
            # returns (a joiner returns at WELCOME, mid state-transfer); any
            # frame applied between "seed the pod" and "record what the pod
            # has seen" would be counted as seen but never applied — a
            # permanent silent divergence (ADVICE.md round-1 high finding).
            snap = peer.st.snapshot_flat()
            pod = PodTrainer(
                mesh, unflatten(snap, peer.st.spec), loss_fn, **pod_kwargs
            )
            return cls(pod, peer, sync_every, _peer_seen=snap)
        except BaseException:
            peer.close()
            raise

    def __init__(
        self,
        pod: PodTrainer,
        peer: SharedTensorPeer,
        sync_every: int = 1,
        _peer_seen: jnp.ndarray | None = None,
    ):
        if peer.st.spec.layout_digest() != pod.spec.layout_digest():
            raise ValueError("pod table layout != peer table layout")
        self.pod = pod
        self.peer = peer
        self.sync_every = max(1, int(sync_every))
        # What the pod has already incorporated of the peer-tier replica,
        # and what the peer tier already has of the pod's progress.
        # ``_peer_seen`` must be the exact snapshot the pod was seeded from
        # (create() passes it); deriving it from the pod itself keeps the
        # invariant for manual wiring too — a fresh peer.st.snapshot_flat()
        # here would silently absorb frames applied since the pod seed.
        self._peer_seen = (
            _peer_seen if _peer_seen is not None else self._pod_mean_of(pod)
        )
        self._pod_pushed = self._pod_mean()
        self.exchanges = 0

    @staticmethod
    def _pod_mean_of(pod: PodTrainer) -> jnp.ndarray:
        return jnp.mean(pod.state.values, axis=0)

    def _pod_mean(self) -> jnp.ndarray:
        return self._pod_mean_of(self.pod)

    def step(self, batch: Any, lr: float = 1e-2):
        losses, scales = self.pod.step(batch, lr)
        if self.pod.steps % self.sync_every == 0:
            self.exchange()
        return losses, scales

    def exchange(self) -> None:
        """One push+pull against the tree. Non-blocking beyond the device
        reductions: `add` enqueues into link residuals; frames stream in the
        peer engine's background threads."""
        # pull: tree progress since last seen (excludes our own pushes,
        # which are already in _peer_seen via the push bookkeeping below)
        snap = self.peer.st.snapshot_flat()
        incoming = snap - self._peer_seen
        # push: pod training progress since last push. MUST go through the
        # peer object's add (not st.add): it wakes the send loop — a direct
        # st.add leaves frames waiting for the next keepalive tick.
        mean = self._pod_mean()
        outgoing = mean - self._pod_pushed
        self.peer.add(unflatten(outgoing, self.pod.spec))
        # The peer replica now includes our push; remember both.
        self._peer_seen = snap + outgoing
        self._pod_pushed = mean + incoming  # after applying incoming below
        apply = jax.device_get(incoming)  # host hop: peer tier is host-side
        self.pod.state = apply_external(self.pod.state, jnp.asarray(apply))
        self.exchanges += 1

    def read(self, peer: int = 0) -> Any:
        return self.pod.read(peer)

    def close(self) -> None:
        self.peer.close()
