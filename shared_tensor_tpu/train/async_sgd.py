"""Async data-parallel SGD over the pod tier: the reference's training story,
fused into one XLA program per step.

The reference's workload is N workers each looping {copyToTensor; compute a
local update; addFromTensor} while peer updates stream in asynchronously
(reference README.md:13-19, example.lua:14-26). On a pod, each device along
the ``peer`` mesh axis is one such worker; a training step is

  1. every peer computes grads of its own replica on its own batch
     (``jax.vmap`` over the peer axis — GSPMD keeps each peer's compute on
     its own device, zero cross-device traffic);
  2. ``add_updates``: the scaled update lands in the peer's replica (visible
     immediately, like ``addFromTensor``) and its outgoing residual;
  3. the fused compressed sync step (parallel/ici.py): 1-bit quantize +
     all-gather over ICI + split-horizon apply.

One ``jax.jit`` covers all three, so XLA overlaps the codec/collective with
backward-pass compute where the schedule allows. Compute never blocks on
host round-trips — the async-semantics contract (reference README.md:24)
holds step-to-step: a peer's update is visible locally at once and reaches
others compressed, with bounded +/-scale overshoot.

``sync_every > 1`` trades freshness for bandwidth exactly like the
reference's natural backpressure pacing (its TCP link simply falls behind and
residuals accumulate, reference src/sharedtensor.c:176-177): local steps
accumulate into the residual and one compressed frame carries their sum.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import CodecConfig, MeshConfig, ScalePolicy
from ..ops.table import TableSpec, flatten, make_spec, unflatten
from ..parallel.ici import (
    PeerSyncState,
    add_updates,
    add_updates_raw,
    build_sync_phases,
    build_sync_step,
    init_state,
    read_peer,
)


def build_train_step(
    mesh: Mesh,
    spec: TableSpec,
    loss_fn: Callable[[Any, Any], jnp.ndarray],
    policy: ScalePolicy = ScalePolicy.POW2_RMS,
    per_leaf: bool = True,
    compressed: bool = True,
    sync: bool = True,
    config: MeshConfig | None = None,
    optimizer=None,
    overlap: bool = False,
):
    """Compile ``(state, opt_state, batch, lr) -> (state', opt_state',
    per-peer loss, scales)``.

    ``loss_fn(params, batch_item) -> scalar`` sees the caller's parameter
    pytree; ``batch`` carries a leading peer axis on every leaf. ``lr`` is a
    traced scalar so schedules don't retrigger compilation. ``sync=False``
    builds the no-communication arm (pure local SGD — the isolation baseline
    for convergence comparisons).

    ``optimizer`` is any optax GradientTransformation, applied per peer to
    the FLAT gradient vector (each peer keeps its own momentum/Adam state;
    ``lr`` is then ignored — the transform owns the step size). The transform
    must be elementwise (momentum/adam/rmsprop/...), since it sees the padded
    flat buffer, not the parameter tree. Its additive updates flow through
    the same path as plain SGD deltas: visible locally at once, compressed
    toward the group.

    ``overlap=True`` (compressed sync only) reorders the fused program so
    the ICI all-gather has no data dependency on this step's compute: the
    CURRENT residual is quantized + gathered first, grads run in the middle,
    and the gathered frames + local update land at the end — XLA's latency-
    hiding scheduler then runs the collective under the backward pass
    instead of serializing after it (the reference's "compute never waits
    for sync", README.md:24; SURVEY.md §7.4 hard part 1). The local update
    is delivered one step later; eventual consistency is unchanged.
    ``apply_gathered(values, *send(residual)[1:])`` composed immediately is
    bit-for-bit the non-overlap sync (tests pin this)."""
    cfg = config or MeshConfig()
    if overlap and (not sync or not compressed):
        raise ValueError("overlap=True requires sync=True and compressed=True")
    sync_raw = (
        build_sync_step(
            mesh,
            spec,
            policy=policy,
            per_leaf=per_leaf,
            compressed=compressed,
            config=cfg,
            jit_compile=False,
        )
        if sync and not overlap
        else None
    )
    phases = (
        build_sync_phases(
            mesh, spec, policy=policy, per_leaf=per_leaf, config=cfg
        )
        if sync and overlap
        else None
    )
    k = spec.num_leaves if per_leaf else 1
    grad_fn = jax.value_and_grad(loss_fn)

    def per_peer(values_row: jnp.ndarray, batch_item):
        params = unflatten(values_row, spec)
        loss, grads = grad_fn(params, batch_item)
        return loss, flatten(grads, spec)

    def _step(state: PeerSyncState, opt_state, batch, lr):
        if phases is not None:
            # OVERLAP mode: quantize + all-gather the residual as it stands —
            # no data dependency on this step's grads, so XLA's latency-
            # hiding scheduler runs the collective under the backward pass.
            # The local update below rides the NEXT step's frame (async
            # semantics unchanged: a frame carries whatever residual mass
            # exists at frame time, exactly like the reference's streams).
            send, apply_gathered = phases
            r2, words_all, scales_all = send(state.residual)
            losses, g = jax.vmap(per_peer)(state.values, batch)
            if optimizer is None:
                updates = -lr * g
            else:
                updates, opt_state = jax.vmap(optimizer.update)(
                    g, opt_state, state.values
                )
            v2 = apply_gathered(state.values, words_all, scales_all)
            state = add_updates_raw(PeerSyncState(v2, r2), updates)
            return state, opt_state, losses, scales_all
        losses, g = jax.vmap(per_peer)(state.values, batch)
        if optimizer is None:
            updates = -lr * g
        else:
            updates, opt_state = jax.vmap(optimizer.update)(
                g, opt_state, state.values
            )
        state = add_updates_raw(state, updates)
        if sync_raw is not None:
            state, scales = sync_raw(state)
        else:
            scales = jnp.zeros((state.values.shape[0], k), jnp.float32)
        return state, opt_state, losses, scales

    return jax.jit(_step, donate_argnums=(0,) if optimizer is None else (0, 1))


@dataclasses.dataclass
class PodTrainer:
    """Convenience wrapper owning the sharded state + compiled step.

    ``create_or_fetch`` for the pod tier: construct with a parameter template
    and every peer starts from that seed, replicas kept eventually-consistent
    by the compressed sync (the in-pod analog of comm/peer.py's
    ``create_or_fetch`` — SURVEY.md §2.2 row 1)."""

    mesh: Mesh
    template: Any
    loss_fn: Callable[[Any, Any], jnp.ndarray]
    codec: CodecConfig = dataclasses.field(default_factory=CodecConfig)
    mesh_config: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    compressed: bool = True
    sync: bool = True
    optimizer: Any = None  # optax GradientTransformation (see build_train_step)
    overlap: bool = False  # collective under the backward pass (see build_train_step)
    #: Pod steps per sync exchange. With k > 1, k-1 steps run the no-sync
    #: program (updates accumulate in the residual — the module docstring's
    #: freshness-for-bandwidth trade, the analog of the reference's natural
    #: TCP backpressure pacing) and every k-th step syncs the accumulated sum
    #: as ONE frame.
    sync_every: int = 1

    def __post_init__(self):
        self.spec: TableSpec = make_spec(self.template)
        self.state: PeerSyncState = init_state(
            self.mesh, self.spec, self.template, self.mesh_config
        )
        self.n_peer: int = self.mesh.shape[self.mesh_config.peer_axis]
        self.opt_state = (
            None
            if self.optimizer is None
            else jax.vmap(self.optimizer.init)(self.state.values)
        )
        self.sync_every = max(1, int(self.sync_every))
        kw = dict(
            policy=self.codec.scale_policy,
            per_leaf=self.codec.per_leaf_scale,
            compressed=self.compressed,
            config=self.mesh_config,
            optimizer=self.optimizer,
        )
        self._step = build_train_step(
            self.mesh, self.spec, self.loss_fn,
            sync=self.sync, overlap=self.overlap, **kw,
        )
        # the off-beat program for sync_every > 1: identical step, no
        # exchange — updates pile into the residual until the sync beat
        self._step_local = (
            build_train_step(self.mesh, self.spec, self.loss_fn, sync=False, **kw)
            if self.sync and self.sync_every > 1
            else None
        )
        self.steps = 0

    def shard_batch(self, batch: Any) -> Any:
        """Pin a [n_peer, ...] batch pytree to the peer axis so each peer's
        slice lives on its own devices before the step runs."""
        ax = self.mesh_config.peer_axis

        def put(x):
            sh = NamedSharding(self.mesh, P(ax, *([None] * (x.ndim - 1))))
            return jax.device_put(x, sh)

        return jax.tree.map(put, batch)

    def step(self, batch: Any, lr: float = 1e-2):
        """One fused train step (+sync on every ``sync_every``-th call).
        Returns (per-peer losses f32[n_peer], per-peer-leaf scales); state
        advances in place. With an optax ``optimizer``, ``lr`` is ignored
        (the transform owns the step size)."""
        fn = self._step
        if self._step_local is not None and (self.steps + 1) % self.sync_every:
            fn = self._step_local
        self.state, self.opt_state, losses, scales = fn(
            self.state, self.opt_state, batch, jnp.float32(lr)
        )
        self.steps += 1
        return losses, scales

    def read(self, peer: int = 0) -> Any:
        """Peer ``peer``'s current replica as the template pytree (reference
        copyToTensor, src/sharedtensor.c:435-446)."""
        return read_peer(self.state, self.spec, peer)

    def add(self, updates: jax.Array) -> None:
        """Out-of-band additive update, [n_peer, spec.total] flat (reference
        addFromTensor outside the training loop)."""
        self.state = add_updates(self.state, updates)

    def replica_spread(self) -> float:
        """Max abs deviation of any replica from the peer mean — the
        eventual-consistency observable (0 when fully converged/synced)."""
        v = self.state.values
        return float(jnp.max(jnp.abs(v - jnp.mean(v, axis=0, keepdims=True))))
