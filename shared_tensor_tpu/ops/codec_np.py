"""Numpy codec tier: the host-side (CPU) production implementation of the
table codec.

Three codec tiers now exist, one per execution environment:

- ``ops/table.py`` (pure XLA)     — the golden semantics, any backend;
- ``ops/codec_pallas.py``         — fused TPU kernels (the accelerator tier);
- this module (vectorized numpy)  — the HOST tier: CPU peers, whose XLA-CPU
  pack/unpack lowering is many passes and single-digit-MB/s (measured: a CPU
  peer absorbed 16Mi-element frames at ~1.3/s, stalling the whole link via
  TCP backpressure, while the reference's tight C loop does 202M elem/s on
  one core — BASELINE.md). ``np.packbits``/``np.unpackbits`` ARE that tight C
  loop, and the arithmetic is 2-3 memory-bandwidth passes.

Wire compatibility is bit-exact: ``np.packbits(bitorder="little")`` produces
byte ``i`` bit ``j`` = element ``8i+j`` — the LSB-first layout of
ops/packing.py and of the reference (src/sharedtensor.c:106-111,166-174) —
and little-endian bytes viewed as ``<u4`` are exactly the packed words.
Sign bits and error feedback are bit-identical to the XLA tier given the
same scale; the SCALE itself may differ by 1 ulp from XLA's (different f32
summation order in the RMS reduction), which the POW2 floor collapses in all
but boundary cases — and either scale is a valid codec step carried verbatim
on the wire, so cross-tier links interoperate exactly.

All functions take/return host numpy arrays and are synchronous — a CPU
peer's frame path has no device round-trips at all.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

from .. import _build
from ..config import ScalePolicy
from .codec import SAT as _SAT
from .table import TableSpec

# ---- native tier (native/stcodec.c) ---------------------------------------
#
# The per-element loops run as compiled C when native/libstcodec.so is
# available (built on demand, like the transport); numpy remains the
# always-available fallback and the semantic reference. ST_HOST_CODEC=numpy
# additionally pins pure numpy (parity tests).

_NATIVE_DIR = _build.NATIVE_DIR
_LIB: Optional[ctypes.CDLL] = None
_LIB_TRIED = False

# ALIGNED: the C kernels (and their AVX paths) assume natural alignment;
# a misaligned view (e.g. an offset np.frombuffer) must fail loudly here
# rather than reach the library as UB.
_i64p = np.ctypeslib.ndpointer(np.int64, flags="C,ALIGNED")
_f32p = np.ctypeslib.ndpointer(np.float32, flags="C,ALIGNED")
_u32p = np.ctypeslib.ndpointer(np.uint32, flags="C,ALIGNED")


def _native() -> Optional[ctypes.CDLL]:
    global _LIB, _LIB_TRIED
    if _LIB is not None or _LIB_TRIED:
        return _LIB
    _LIB_TRIED = True
    if os.environ.get("ST_HOST_CODEC") == "numpy":
        return None
    path = _NATIVE_DIR / "libstcodec.so"
    try:
        # Always run make (mtime-based no-op when fresh) so edited sources
        # never keep serving a stale .so; flock-serialized across processes
        # (_build.run_make). ISA safety is runtime-dispatched inside the
        # library itself (__builtin_cpu_supports in stcodec.c), so a .so
        # built elsewhere is portable — no -march=native rebuild hazard.
        _build.run_make(target="libstcodec.so")
        lib = ctypes.CDLL(str(path))
        lib.stc_quantize.restype = None
        lib.stc_quantize.argtypes = [
            _f32p, _f32p, _i64p, _i64p, _i64p, ctypes.c_int64, _f32p, _u32p,
        ]
        lib.stc_accumulate_delta.restype = None
        lib.stc_accumulate_delta.argtypes = [_f32p, _i64p, _i64p, _i64p, ctypes.c_int64, _f32p, _u32p]
        lib.stc_add_inplace.restype = None
        lib.stc_add_inplace.argtypes = [_f32p, _f32p, ctypes.c_int64]
        lib.stc_add_to.restype = None
        lib.stc_add_to.argtypes = [_f32p, _f32p, _f32p, ctypes.c_int64]
        lib.stc_apply_frame.restype = None
        lib.stc_apply_frame.argtypes = [
            _f32p, _f32p, _i64p, _i64p, _i64p, ctypes.c_int64, _f32p, _u32p,
        ]
        _f64p = np.ctypeslib.ndpointer(np.float64, flags="C")
        lib.stc_scale_partials.restype = None
        lib.stc_scale_partials.argtypes = [
            _f32p, _i64p, _i64p, ctypes.c_int64, _f64p, _f64p, _f64p,
        ]
        lib.stc_accumulate_update.restype = None
        lib.stc_accumulate_update.argtypes = [_f32p, _f32p, ctypes.c_int64]
        lib.stc_accumulate_update_to.restype = None
        lib.stc_accumulate_update_to.argtypes = [
            _f32p, _f32p, _f32p, _i64p, _i64p, _i64p, ctypes.c_int64,
        ]
        # fused sender pass + next-frame scale partials (the native
        # engine's burst loop; parity-pinned in test_codec_np)
        lib.stc_quantize_ef_partials.restype = None
        lib.stc_quantize_ef_partials.argtypes = [
            _f32p, _f32p, _i64p, _i64p, _i64p, ctypes.c_int64, _f32p, _u32p,
            _f64p, _f64p, _f64p,
        ]
        # k-frame fused apply: one pass over the target regardless of k
        # (replaces the delta-buffer path; bit-identical to it — see
        # stcodec.c). Trailing partials pointers may be None.
        _f64p_opt = ctypes.POINTER(ctypes.c_double)
        lib.stc_apply_frames.restype = None
        lib.stc_apply_frames.argtypes = [
            _f32p, _f32p, _i64p, _i64p, _i64p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int32, _f32p, _u32p,
            _f64p_opt, _f64p_opt, _f64p_opt,
        ]
        lib.stc_accumulate_update_to_partials.restype = None
        lib.stc_accumulate_update_to_partials.argtypes = [
            _f32p, _f32p, _f32p, _i64p, _i64p, _i64p, ctypes.c_int64,
            _f64p, _f64p, _f64p,
        ]
        # r11 cascade quantize: K halving frames in ONE pass (scales ride
        # the wire, so the sender-chosen schedule is protocol-legal); the
        # sign2 (2-bit) twins carry sign + magnitude planes per frame.
        lib.stc_quantize_ef_cascade.restype = None
        lib.stc_quantize_ef_cascade.argtypes = [
            _f32p, _f32p, _i64p, _i64p, _i64p, ctypes.c_int64,
            ctypes.c_int32, _f32p, _u32p, ctypes.c_int64,
            _f64p, _f64p, _f64p,
        ]
        lib.stc_quantize2_ef_cascade.restype = None
        lib.stc_quantize2_ef_cascade.argtypes = [
            _f32p, _f32p, _i64p, _i64p, _i64p, ctypes.c_int64,
            ctypes.c_int32, _f32p, _u32p, ctypes.c_int64, ctypes.c_int64,
            _f64p, _f64p, _f64p,
        ]
        lib.stc_apply_frames2.restype = None
        lib.stc_apply_frames2.argtypes = [
            _f32p, _f32p, _i64p, _i64p, _i64p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int32, _f32p, _u32p,
            _f64p_opt, _f64p_opt, _f64p_opt,
        ]
        lib.stc_apply_frame2.restype = None
        lib.stc_apply_frame2.argtypes = [
            _f32p, _f32p, _i64p, _i64p, _i64p, ctypes.c_int64,
            ctypes.c_int64, _f32p, _u32p,
        ]
        _LIB = lib
    except Exception:  # no toolchain / build failure: numpy fallback
        _LIB = None
    return _LIB


_spec_layout_cache: dict = {}


def _layout(spec: TableSpec):
    """(offsets, ns, padded) as int64 arrays, cached per spec. Keyed by the
    spec VALUE (TableSpec is a hashable frozen dataclass — it is already a
    jit static arg): an id() key could alias a garbage-collected spec whose
    id was reused, handing the C kernels another layout's offsets."""
    hit = _spec_layout_cache.get(spec)
    if hit is not None:
        return hit
    out = (
        np.asarray([off for off, _, _ in _leaf_slices(spec)], np.int64),
        np.asarray(spec.ns, np.int64),
        np.asarray(spec.padded, np.int64),
    )
    if len(_spec_layout_cache) > 256:
        _spec_layout_cache.clear()
    _spec_layout_cache[spec] = out
    return out


def _pow2_floor_np(x: np.ndarray) -> np.ndarray:
    """2^floor(log2(x)) by clearing the f32 mantissa (exact, transcendental-
    free — same rationale as ops/codec.pow2_floor)."""
    bits = np.asarray(x, np.float32).view(np.uint32)
    return (bits & np.uint32(0x7F800000)).view(np.float32)


def _leaf_slices(spec: TableSpec):
    off = 0
    for n, p in zip(spec.ns, spec.padded):
        yield off, n, p
        off += p


def flatten_np(tree, spec: TableSpec, *, copy: bool = True) -> np.ndarray:
    """Numpy twin of ops.table.flatten (pytree -> padded flat f32 buffer,
    padding exactly 0). The host tier must never run jax array ops: merely
    creating a jnp array initializes the XLA CPU client, whose thread pool
    contends with the C codec loops (measured 2.7x slower frames on a
    1-vCPU host). jax.tree_util is pure Python and backend-free.

    ``copy=False`` (r11): a caller that only READS the result before
    returning control (the engine add hot path — st_engine_add consumes
    ``u`` synchronously) may receive the caller's own buffer when the
    tree is a single unpadded C-contiguous f32 leaf — at 1 Mi the
    zeros+copy here was two full table passes per add() on the
    production throughput path (the add cadence is what feeds the
    sender's frame rate). Never pass the result anywhere that retains
    it; the default copies as before."""
    import jax

    leaves, treedef = jax.tree.flatten(tree)
    if treedef != spec.treedef:
        raise ValueError(
            f"tree structure {treedef} does not match spec {spec.treedef}"
        )
    if not copy and len(leaves) == 1 and spec.num_leaves == 1:
        flat = np.ravel(np.asarray(leaves[0])).astype(np.float32, copy=False)
        if flat.shape[0] != spec.ns[0]:
            raise ValueError(
                f"leaf has {flat.shape[0]} elements, spec expects "
                f"{spec.ns[0]}"
            )
        if flat.shape[0] == spec.total and flat.flags.c_contiguous:
            return flat
    out = np.zeros(spec.total, np.float32)
    for (off, n, _), leaf in zip(_leaf_slices(spec), leaves):
        flat = np.ravel(np.asarray(leaf)).astype(np.float32, copy=False)
        if flat.shape[0] != n:
            raise ValueError(f"leaf has {flat.shape[0]} elements, spec expects {n}")
        out[off : off + n] = flat
    return out


def unflatten_np(flat: np.ndarray, spec: TableSpec):
    """Numpy twin of ops.table.unflatten. Leaves are COPIES, not views:
    a view would alias the live replica buffer, and an in-place edit on a
    read() snapshot would then mutate the replica behind the codec's back
    (never entering any residual — permanent tree divergence). The device
    tier gets this for free from jnp immutability."""
    import jax

    flat = np.asarray(flat)
    leaves = [
        flat[off : off + n].copy().reshape(shape)
        for (off, n, _), shape in zip(_leaf_slices(spec), spec.shapes)
    ]
    return jax.tree.unflatten(spec.treedef, leaves)


def compute_scales_np(
    residual: np.ndarray,
    spec: TableSpec,
    policy: ScalePolicy = ScalePolicy.POW2_RMS,
    per_leaf: bool = True,
) -> np.ndarray:
    """Per-leaf scales, overflow-safe (normalize by max|r| before squaring —
    quirk Q9 fix, matching ops/table.compute_scales). With the native tier
    the reductions run as ONE fused C pass with double accumulators
    (overflow-safe without the normalization); scales can differ from the
    f32 tiers by ~1 ulp of rounding, which any tier tolerates — the scale is
    carried on the wire, never recomputed by a receiver."""
    lib = _native()
    if lib is not None:
        r = np.ascontiguousarray(residual, np.float32)
        offs, ns_arr, _ = _layout(spec)
        L = spec.num_leaves
        amax = np.zeros(L, np.float64)
        ss = np.zeros(L, np.float64)
        sabs = np.zeros(L, np.float64)
        lib.stc_scale_partials(r, offs, ns_arr, L, amax, ss, sabs)
        ns = np.asarray(spec.ns, np.float64)
        if not per_leaf:
            amax = np.full(L, amax.max())
            ss = np.full(L, ss.sum())
            sabs = np.full(L, sabs.sum())
            ns = np.full(L, float(spec.total_n))
        if policy == ScalePolicy.ABS_MEAN:
            s = (sabs / ns).astype(np.float32)
        else:
            rms = np.sqrt(ss / ns).astype(np.float32)
            s = _pow2_floor_np(rms) if policy == ScalePolicy.POW2_RMS else rms
        return np.where((amax > 0) & np.isfinite(s), s, 0.0).astype(np.float32)
    if not per_leaf:
        segs = [(0, spec.total_n, None)]
    else:
        segs = list(_leaf_slices(spec))
    out = np.zeros(len(segs), np.float32)
    for i, seg in enumerate(segs):
        if per_leaf:
            off, n, _ = seg
            live = residual[off : off + n]
        else:
            live = residual  # padding is 0 by invariant; only divisor differs
            n = spec.total_n
        amax = np.float32(np.max(np.abs(live))) if live.size else np.float32(0)
        if not (amax > 0) or not np.isfinite(amax):
            continue
        norm = live.astype(np.float32) / amax
        if policy == ScalePolicy.ABS_MEAN:
            s = amax * np.float32(
                np.sum(np.abs(norm), dtype=np.float32) / np.float32(n)
            )
        else:
            rms = amax * np.float32(
                np.sqrt(np.sum(norm * norm, dtype=np.float32) / np.float32(n))
            )
            s = _pow2_floor_np(rms)[()] if policy == ScalePolicy.POW2_RMS else rms
        out[i] = s if np.isfinite(s) else 0.0
    if not per_leaf:
        out = np.full(spec.num_leaves, out[0], np.float32)
    return out


def _scale_per_element(scales: np.ndarray, spec: TableSpec) -> np.ndarray:
    s = np.empty(spec.total, np.float32)
    for i, (off, n, p) in enumerate(_leaf_slices(spec)):
        s[off : off + p] = scales[i]
    return s


_live_cache: dict = {}


def _live_mask_np(spec: TableSpec) -> np.ndarray:
    m = _live_cache.get(spec)  # value key — see _layout
    if m is None:
        m = np.zeros(spec.total, bool)
        for off, n, p in _leaf_slices(spec):
            m[off : off + n] = True
        if len(_live_cache) > 256:
            _live_cache.clear()
        _live_cache[spec] = m
    return m


def quantize_table_np(
    residual: np.ndarray,
    spec: TableSpec,
    policy: ScalePolicy = ScalePolicy.POW2_RMS,
    per_leaf: bool = True,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Sender step: returns (scales f32[L], words u32[total//32],
    new_residual f32[total]). Semantics identical to ops/table.quantize_table
    (bit set iff r <= 0; residual moves by -+leaf scale; scale-0 leaves
    idle; padding stays exactly 0)."""
    r = np.ascontiguousarray(residual, np.float32)
    scales = compute_scales_np(r, spec, policy, per_leaf)
    lib = _native()
    if lib is not None:
        offs, ns, padded = _layout(spec)
        new_r = np.empty(spec.total, np.float32)
        # C writes every word (padding words are emitted as 0), so empty is safe
        words = np.empty(spec.total // 32, np.uint32)
        lib.stc_quantize(
            r, new_r, offs, ns, padded, spec.num_leaves, scales, words
        )
        return scales, words, new_r
    live = _live_mask_np(spec)
    s_el = _scale_per_element(scales, spec)
    neg = r <= 0
    bits = neg & live
    words = np.packbits(bits, bitorder="little").view("<u4").astype(np.uint32)
    sent = np.where(neg, -s_el, s_el)
    new_r = np.where(live & (s_el > 0), r - sent, np.where(live, r, 0.0)).astype(
        np.float32
    )
    return scales, words, new_r


def apply_table_batch_np(
    arrays: tuple[np.ndarray, ...],
    scales: np.ndarray,  # f32[K, L]
    words: np.ndarray,  # u32[K, total//32]
    spec: TableSpec,
) -> tuple[np.ndarray, ...]:
    """Receiver step for K stacked frames applied to every array (replica +
    other links' residuals — the flood), accumulating the summed delta in one
    f32 buffer then adding it once per target."""
    k = scales.shape[0]
    lib = _native()
    if lib is not None:
        offs, ns, padded = _layout(spec)
        if k == 1:
            # Single frame (the common receive case): fully fused
            # out = clip(in + delta) — one memory pass per target, no delta
            # buffer, no copy. At sizes past LLC the host tier is
            # bandwidth-bound and this is ~2x the accumulate+copy+add path.
            row = np.ascontiguousarray(scales[0], np.float32)
            w0 = np.ascontiguousarray(words[0], np.uint32)
            out = []
            for a in arrays:
                src = np.ascontiguousarray(a, np.float32)
                dst = np.empty(spec.total, np.float32)
                lib.stc_apply_frame(
                    src, dst, offs, ns, padded, spec.num_leaves, row, w0
                )
                out.append(dst)
            return tuple(out)
        # k-frame fused apply (stc_apply_frames): one pass over each target
        # regardless of k — reads the k PACKED word rows (total/8 bytes
        # each) instead of building a total*4 delta buffer with k
        # read-modify-write passes. Bit-identical to the delta path by
        # construction (same per-element +/-s summation order, same final
        # clip(a + delta)).
        srows = np.ascontiguousarray(scales, np.float32)
        wrows = np.ascontiguousarray(words, np.uint32)
        out = []
        for a in arrays:
            src = np.ascontiguousarray(a, np.float32)
            dst = np.empty(spec.total, np.float32)
            lib.stc_apply_frames(
                src, dst, offs, ns, padded, spec.num_leaves,
                spec.total // 32, k, srows, wrows, None, None, None,
            )
            out.append(dst)
        return tuple(out)
    delta = np.zeros(spec.total, np.float32)
    live = _live_mask_np(spec)
    for i in range(k):
        row = np.asarray(scales[i], np.float32)
        if not row.any():
            continue  # zero-scale padding frame contributes nothing
        bits = np.unpackbits(
            np.ascontiguousarray(words[i]).view(np.uint8), bitorder="little"
        )[: spec.total]
        s_el = _scale_per_element(row, spec)
        # values[i] += scale - bit*2*scale (reference src/sharedtensor.c:109)
        delta += s_el * (1.0 - 2.0 * bits.astype(np.float32))
    delta[~live] = 0.0
    out = []
    for a in arrays:
        v = np.clip(np.asarray(a, np.float32) + delta, -_SAT, _SAT)
        v[~live] = 0.0
        out.append(v)
    return tuple(out)


def apply_table_many_np(
    arrays: tuple[np.ndarray, ...],
    scales: np.ndarray,  # f32[L]
    words: np.ndarray,  # u32[total//32]
    spec: TableSpec,
) -> tuple[np.ndarray, ...]:
    return apply_table_batch_np(
        arrays, scales.reshape(1, -1), words.reshape(1, -1), spec
    )


def accumulate_table_np(
    arrays: tuple[np.ndarray, ...], update: np.ndarray, spec: TableSpec
) -> tuple[np.ndarray, ...]:
    """values += u and each link residual += u, sanitized (quirk Q9 fix,
    matching ops/table.accumulate_table)."""
    lib = _native()
    if lib is not None:
        # one fused pass per target: dst = clip(a + sanitize(u)) on live
        # lanes, padding copied from a — no update copy, no target copy
        offs, ns, padded = _layout(spec)
        u_src = np.ascontiguousarray(update, np.float32)
        out = []
        for a in arrays:
            src = np.ascontiguousarray(a, np.float32)
            dst = np.empty(spec.total, np.float32)
            lib.stc_accumulate_update_to(
                dst, src, u_src, offs, ns, padded, spec.num_leaves
            )
            out.append(dst)
        return tuple(out)
    live = _live_mask_np(spec)
    u = np.asarray(update, np.float32).copy()
    u[~live] = 0.0
    np.nan_to_num(u, copy=False, nan=0.0, posinf=3.0e38, neginf=-3.0e38)
    return tuple(
        np.clip(np.asarray(a, np.float32) + u, -3.0e38, 3.0e38) for a in arrays
    )


# ---- r11 sign2 (2-bit sign/magnitude) reference twins -----------------------
#
# PURE-numpy semantic references for the engine tier's sign2 kernels
# (stc_quantize2_ef_cascade / stc_apply_frames2) — deliberately NO native
# fast path: these exist so the parity tests can pin the C loops (and the
# JAX lab step, parallel/ici_lab.build_sign2_sync_step) against an
# independent implementation of the codec-lab Sign2 rule:
#   neg = r <= 0 (zero-negative, quirk Q3), big = |r| > 2s,
#   sent = +/- (3s if big else s), r' = r - sent on live lanes with s > 0.
# Wire layout per frame: [scales L*4][sign words W*4][mag words W*4].


def quantize2_table_np(
    residual: np.ndarray,
    spec: TableSpec,
    policy: ScalePolicy = ScalePolicy.POW2_RMS,
    per_leaf: bool = True,
    scales: "np.ndarray | None" = None,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """One sign2 sender frame: returns (scales f32[L], sign_words
    u32[total//32], mag_words u32[total//32], new_residual). Pass
    ``scales`` to quantize at given scales (the cross-tier parity
    discipline: bit-identical GIVEN the same scales)."""
    r = np.ascontiguousarray(residual, np.float32)
    if scales is None:
        scales = compute_scales_np(r, spec, policy, per_leaf)
    live = _live_mask_np(spec)
    s_el = _scale_per_element(np.asarray(scales, np.float32), spec)
    neg = r <= 0
    big = np.abs(r) > np.float32(2.0) * s_el
    sign_words = (
        np.packbits(neg & live, bitorder="little").view("<u4").astype(np.uint32)
    )
    mag_words = (
        np.packbits(big & live, bitorder="little").view("<u4").astype(np.uint32)
    )
    mag = np.where(big, np.float32(3.0) * s_el, s_el)
    sent = np.where(neg, -mag, mag)
    new_r = np.where(
        live & (s_el > 0), r - sent, np.where(live, r, 0.0)
    ).astype(np.float32)
    return np.asarray(scales, np.float32), sign_words, mag_words, new_r


def apply2_table_np(
    arrays: tuple[np.ndarray, ...],
    scales: np.ndarray,  # f32[K, L]
    words: np.ndarray,  # u32[K, 2 * total//32]: sign plane then mag plane
    spec: TableSpec,
) -> tuple[np.ndarray, ...]:
    """Receiver reference for K sign2 frames: delta = s * (1-2*neg) *
    (1+2*big) summed across frames, clip once (the fused-apply summation
    order)."""
    k = np.asarray(scales).shape[0]
    w = spec.total // 32
    live = _live_mask_np(spec)
    delta = np.zeros(spec.total, np.float32)
    for i in range(k):
        row = np.asarray(scales[i], np.float32)
        if not row.any():
            continue
        wrow = np.ascontiguousarray(words[i]).view(np.uint32)
        neg = np.unpackbits(
            np.ascontiguousarray(wrow[:w]).view(np.uint8), bitorder="little"
        )[: spec.total].astype(np.float32)
        big = np.unpackbits(
            np.ascontiguousarray(wrow[w:]).view(np.uint8), bitorder="little"
        )[: spec.total].astype(np.float32)
        s_el = _scale_per_element(row, spec)
        delta += s_el * (1.0 - 2.0 * neg) * (1.0 + 2.0 * big)
    delta[~live] = 0.0
    out = []
    for a in arrays:
        v = np.clip(np.asarray(a, np.float32) + delta, -_SAT, _SAT)
        v[~live] = 0.0
        out.append(v)
    return tuple(out)
