"""Experimental compression methods — the reference's last open TODO.

Reference README.md:45 asks to "try different compression methods in the
real world". The production planes (ops/codec*.py, native/) are pinned to
the reference's 1-bit sign codec so every tier stays bit-compatible; this
module is the LAB: alternative delta codecs under the identical
error-feedback frame contract, so they can be compared on the same
residual trajectories the production codec runs —

    encode(residual)  -> (frame, new_residual)   # residual -= decode(frame)
    decode(frame)     -> dense delta             # receiver: values += delta

Every method keeps the two invariants the framework's semantics rest on
(SURVEY.md App. B):

- **conservation**: ``residual_in == decode(frame) + residual_out`` to
  within 1 ulp of the sent magnitude (the f32 subtraction rounds when
  exponents differ — the same ~1 ulp bound the production codec documents
  for receiver accumulation; TopK is exactly conservative since it ships
  f32 copies);
- **boundedness**: an all-zero residual encodes to an idle frame
  (``scale == 0`` / empty payload) and decodes to zero.

Methods:

``Sign1``
    The production codec (1 bit/elem + 4 B scale), wrapped into the lab
    interface as the baseline — reference src/sharedtensor.c:145-177.

``Sign2``
    Two-bit sign-magnitude extension of the same idea: per element send
    ``±scale`` or ``±3·scale`` (magnitude bit set when ``|r| > 2·scale``),
    the measured-best design of the 2-bit family (see the class docstring
    for the design sweep and the limit-cycle failure mode that rules out
    mid-rise levels). Faster per frame on gaussian residuals; identical to
    Sign1 (and exactly draining) on uniform ones.

``TopK``
    Sparse exact transfer: the k largest-|r| elements go over as
    ``(index, f32 value)`` pairs and are subtracted exactly; the rest stay
    in the residual. 8 bytes per sent element — wins when updates are
    heavy-tailed (a few big coordinates carry most of the RMS), loses on
    dense uniform noise. This is the signSGD-vs-sparsification trade the
    literature studies, measurable here on real link trajectories.

Implementations here are numpy (host tier): the lab's job is
apples-to-apples *policy* comparison on CPU-measurable trajectories
(benchmarks/codec_lab.py -> CODEC_LAB_r{N}.json), not another production
data plane. The two winners also have jitted device-tier implementations
(ops/codec_lab_jax.py), bit-parity-pinned against this module, proving
they drop into the TPU compute path. The production integration point for
a winning method is ops/table.py's dispatch plus a wire frame tag
(comm/wire.py) — deliberately not wired until a method earns it on the
Pareto.
"""

from __future__ import annotations

import dataclasses
from typing import Protocol

import numpy as np

from .codec_np import _pow2_floor_np


def _rms_scale(residual: np.ndarray) -> float:
    """The reference scale rule on a numpy residual (overflow-safe, like
    ops/codec.py compute_scale)."""
    amax = float(np.max(np.abs(residual))) if residual.size else 0.0
    if not (amax > 0.0) or not np.isfinite(amax):
        return 0.0
    norm = residual.astype(np.float64) / amax
    rms = amax * float(np.sqrt(np.mean(norm * norm)))
    s = float(_pow2_floor_np(np.float32(rms))[()])
    return s if np.isfinite(s) and s > 0 else 0.0


@dataclasses.dataclass(frozen=True)
class LabFrame:
    """One lab frame: an opaque payload plus its honest wire cost.

    ``payload_bytes`` counts exactly what a wire message would carry
    (scale/header + packed element data) so the Pareto's bytes axis is
    method-comparable."""

    kind: str
    scale: float
    data: np.ndarray  # kind-specific: packed codes or (idx, val) pairs
    payload_bytes: int


class LabCodec(Protocol):
    name: str

    def encode(self, residual: np.ndarray) -> tuple[LabFrame, np.ndarray]: ...

    def decode(self, frame: LabFrame, n: int) -> np.ndarray: ...


class Sign1:
    """Production 1-bit codec in lab clothing (baseline)."""

    name = "sign1"

    def encode(self, residual: np.ndarray) -> tuple[LabFrame, np.ndarray]:
        s = _rms_scale(residual)
        if s == 0.0:
            return LabFrame(self.name, 0.0, np.zeros(0, np.uint8), 4), residual
        neg = residual <= 0  # bit set => -scale (reference sign rule, Q3)
        sent = np.where(neg, -s, s).astype(np.float32)
        new_r = (residual - sent).astype(np.float32)
        bits = np.packbits(neg.astype(np.uint8), bitorder="little")
        return LabFrame(self.name, s, bits, 4 + bits.nbytes), new_r

    def decode(self, frame: LabFrame, n: int) -> np.ndarray:
        if frame.scale == 0.0:
            return np.zeros(n, np.float32)
        neg = np.unpackbits(frame.data, count=n, bitorder="little")
        return np.where(neg, -frame.scale, frame.scale).astype(np.float32)


class Sign2:
    """2-bit sign-magnitude: sign bit + magnitude bit selecting ``±s`` or
    ``±3s`` (magnitude set when ``|r| > 2s``), at the reference's pow2-RMS
    step so Pareto differences are attributable to the quantizer alone.

    Chosen by measurement over the 2-bit design space ({a,b}·s level pairs
    and deadzone variants, geometric-mean rms decay over 20 frames on a
    gaussian residual, n=64 Ki): {±s, ±3s} decays 0.79/frame vs Sign1's
    0.85 — the best of the family — while every mid-rise variant without
    an exact ±s level (e.g. {±s/2, ±3s/2}) falls into scale-pinned limit
    cycles and never drains. On a uniform residual |r| never exceeds 2s,
    the magnitude bit sits idle, and the trajectory is bit-identical to
    Sign1's — the exact-drain property is preserved by construction. Both
    magnitudes are exact f32 multiples of the pow2 scale (3s has a 1.5
    mantissa), keeping the 1-ulp conservation bound.

    The lab's headline finding (CODEC_LAB artifact): the regimes split by
    residual shape. On UNIFORM residuals Sign1 is byte-optimal (Sign2
    degenerates to it at 2x the bytes). On GAUSSIAN ones the early decay
    favors Sign1 per byte (0.85 per n/8 B compounds past 0.79), but the
    tail flips it: outliers move only ±s per frame under Sign1 (decay
    stalls toward 1.0 — the known slow gaussian tail), while ±3s moves
    them 3x faster, so to a 1% target Sign2 wins per frame AND per byte
    (measured 20 vs 72 frames, 1.31 vs 2.36 MB at n=256 Ki). On heavy
    tails neither competes with TopK (Sign1 never reaches 1% in 400
    frames; TopK does in one)."""

    name = "sign2"

    def encode(self, residual: np.ndarray) -> tuple[LabFrame, np.ndarray]:
        s = _rms_scale(residual)
        if s == 0.0:
            return LabFrame(self.name, 0.0, np.zeros(0, np.uint8), 4), residual
        neg = (residual <= 0).astype(np.uint8)
        big = (np.abs(residual) > np.float32(2.0 * s)).astype(np.uint8)
        mag = np.where(big, np.float32(3.0 * s), np.float32(s))
        sent = np.where(neg, -mag, mag).astype(np.float32)
        new_r = (residual - sent).astype(np.float32)
        codes = neg | (big << 1)  # 2 bits/elem
        packed = np.packbits(
            np.stack([codes & 1, codes >> 1], axis=1).reshape(-1),
            bitorder="little",
        )
        return LabFrame(self.name, s, packed, 4 + packed.nbytes), new_r

    def decode(self, frame: LabFrame, n: int) -> np.ndarray:
        if frame.scale == 0.0:
            return np.zeros(n, np.float32)
        flat = np.unpackbits(frame.data, count=2 * n, bitorder="little")
        codes = flat.reshape(n, 2)
        neg, big = codes[:, 0], codes[:, 1]
        mag = np.where(
            big, np.float32(3.0 * frame.scale), np.float32(frame.scale)
        )
        return np.where(neg, -mag, mag).astype(np.float32)


class TopK:
    """Sparse exact transfer of the k largest-|residual| coordinates."""

    name = "topk"

    def __init__(self, k: int):
        if k <= 0:
            raise ValueError(f"k must be positive, got {k}")
        self.k = k
        self.name = f"topk{k}"

    def encode(self, residual: np.ndarray) -> tuple[LabFrame, np.ndarray]:
        n = residual.shape[0]
        k = min(self.k, n)
        absr = np.abs(residual)
        if not absr.any():
            empty = np.zeros((0, 2), np.float32)
            return LabFrame(self.name, 0.0, empty, 4), residual
        idx = np.argpartition(absr, n - k)[n - k:]
        idx = idx[absr[idx] > 0]  # never ship zero coordinates
        vals = residual[idx].astype(np.float32)
        new_r = residual.copy()
        new_r[idx] = 0.0  # exact subtraction: r - r == 0
        # indices ride as u32 bit patterns viewed f32: exact at any n (a
        # float32 ASTYPE would corrupt indices past 2^24 — 16 Mi tables are
        # in range, PARETO_r03)
        pairs = np.stack(
            [idx.astype(np.uint32).view(np.float32), vals], axis=1
        )
        # honest wire cost: 4 B count header + (u32 index + f32 value) per elem
        return LabFrame(self.name, 1.0, pairs, 4 + 8 * len(idx)), new_r

    def decode(self, frame: LabFrame, n: int) -> np.ndarray:
        out = np.zeros(n, np.float32)
        if frame.data.size:
            idx = frame.data[:, 0].view(np.uint32).astype(np.int64)
            out[idx] = frame.data[:, 1]  # indices are distinct by construction
        return out


def standard_lab(n: int) -> list:
    """The comparison set the benchmark and tests share: baseline, the
    2-bit variant, and top-k at 1/32 density (8 B x n/32 = n/4 bytes — the
    same wire cost per frame as Sign2, making that pair directly
    comparable)."""
    return [Sign1(), Sign2(), TopK(max(1, n // 32))]
