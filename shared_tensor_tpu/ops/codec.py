"""The approximate-delta codec: 1-bit sign quantization with error feedback.

This is the semantic heart of the framework — a faithful, functional
re-statement of the reference codec (reference src/sharedtensor.c:106-111
receiver, :145-177 sender; SURVEY.md Appendix B):

  sender, per frame over a link with residual ``r``:
    1. ``s = 2^floor(log2(rms(r)))``      (power-of-2 floor; s=0 -> idle)
    2. ``b_i = [r_i <= 0]``; ``r_i -= (1 - 2*b_i) * s``   (error feedback)
    3. transmit ``(s, bits)``
  receiver:  ``x_i += (1 - 2*b_i) * s``  applied to its replica AND to the
  residuals of its other links (per-hop re-quantized flooding).

Where the reference is 5 racy threads doing unsynchronized ``float +=`` over
shared buffers (SURVEY.md §5.2, quirk Q7), these are pure functions over
immutable arrays — the race class is gone by construction while the
approximate/eventually-consistent semantics stay in the codec where they
belong.

Layout: all state is flat float32, zero-padded to a multiple of the (8,128)
float32 TPU tile. Invariant: padding lanes of residuals and values are always
exactly 0 (quantize/apply mask them), so full-array reductions need no mask.

This module is the pure-JAX *golden* implementation; the fused
single-HBM-pass Pallas kernels (ops/codec_pallas.py, built on top of this)
must match it bit-for-bit.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp

from .packing import pack_bits, padded_len, unpack_bits
from ..config import ScalePolicy


def pow2_floor(x: jnp.ndarray) -> jnp.ndarray:
    """2^floor(log2(x)) computed exactly by clearing the f32 mantissa.

    TPU log2/exp2 are approximate — a scale that is off by 1 ulp from a power
    of two breaks the codec's exact-convergence property (residual
    subtraction no longer cancels), so transcendentals are not an option
    here. Denormal input maps to 0 (idle frame), matching the reference's
    behavior of grinding to scale==0. Shared by the scalar and table codecs,
    which must match bit-for-bit.
    """
    bits = jax.lax.bitcast_convert_type(x, jnp.uint32)
    return jax.lax.bitcast_convert_type(bits & jnp.uint32(0x7F800000), jnp.float32)


class Frame(NamedTuple):
    """One codec frame: everything that crosses the wire for one link-step.

    ``words`` are the LSB-first packed sign bits (see ops/packing.py for the
    wire-layout contract); ``scale`` is the power-of-2 step size. A set bit
    means ``-scale``, clear means ``+scale`` (reference src/sharedtensor.c:109).
    """

    scale: jnp.ndarray  # f32 scalar
    words: jnp.ndarray  # uint32[n_padded // 32]


def compute_scale(
    residual: jnp.ndarray,
    n: int,
    policy: ScalePolicy = ScalePolicy.POW2_RMS,
) -> jnp.ndarray:
    """Per-frame step size from the residual.

    POW2_RMS is the reference rule ``2^floor(log2(sqrt(mean(r^2))))``
    (reference src/sharedtensor.c:153-159). ``n`` is the true (unpadded)
    element count — the padded tail is all-zero by invariant so it only
    affects the divisor. Returns 0.0 for an all-zero residual (idle link).
    """
    # Overflow-safe RMS: normalize by max|r| before squaring. The reference
    # accumulates raw squares in f32 (src/sharedtensor.c:156-157) and
    # overflows to inf for |r| ~ 1e20+, poisoning every replica via the flood
    # (quirk Q9) — fixed here, not inherited.
    amax = jnp.max(jnp.abs(residual))
    norm = residual / jnp.where(amax > 0, amax, 1.0)
    rms = amax * jnp.sqrt(jnp.sum(norm * norm, dtype=jnp.float32) / jnp.float32(n))
    if policy == ScalePolicy.RMS:
        scale = rms
    elif policy == ScalePolicy.ABS_MEAN:
        # Same amax normalization as rms: a raw f32 |r| sum can overflow.
        scale = amax * (jnp.sum(jnp.abs(norm), dtype=jnp.float32) / jnp.float32(n))
    else:  # POW2_RMS
        scale = pow2_floor(rms)
    # Non-finite rms (residual poisoned despite the accumulate() clamp) maps
    # to 0: the link idles instead of flooding NaN/inf to every replica.
    return jnp.where((rms > 0) & jnp.isfinite(rms), scale, jnp.float32(0.0))


@partial(jax.jit, static_argnames=("n", "policy"))
def quantize(
    residual: jnp.ndarray,
    n: int,
    policy: ScalePolicy = ScalePolicy.POW2_RMS,
) -> tuple[Frame, jnp.ndarray]:
    """One sender step: residual -> (frame, new_residual).

    Sign rule matches the reference exactly: ``r > 0`` sends ``+s`` (bit
    clear), ``r <= 0`` sends ``-s`` (bit set) — zero counts as negative
    (quirk Q3, kept deliberately: converged elements oscillate within
    +/-scale, which is the documented overshoot bound). Padding lanes are
    forced to bit=0 and residual=0.

    With scale == 0 the residual is untouched and the frame is a no-op on
    any receiver — callers may skip sending it (config
    ``suppress_zero_frames``, fixing reference quirk Q2).
    """
    n_pad = residual.shape[0]
    scale = compute_scale(residual, n, policy)
    live = jnp.arange(n_pad, dtype=jnp.int32) < n
    neg = residual <= 0  # bit set => -scale
    bits = jnp.where(live, neg, False)
    sent = jnp.where(neg, -scale, scale)
    new_residual = jnp.where(live, residual - sent, 0.0)
    # scale == 0: keep residual exactly as-is (all-zero stays all-zero).
    new_residual = jnp.where(scale > 0, new_residual, residual)
    return Frame(scale, pack_bits(bits)), new_residual


#: Saturation bound for every state-mutating path (accumulate AND apply, all
#: tiers). Add-side sanitization alone leaves one absorbing state: values
#: legally at +/-SAT plus one max-scale frame (2^127, legal for a residual at
#: the clamp) overflows to inf, and inf - inf = NaN floods tree-wide
#: (reference quirk Q9). Clamping the apply result closes the model: no
#: reachable state is non-finite, by construction. On sane magnitudes the
#: clip is the identity, so cross-tier bit-parity is unaffected.
SAT = 3.0e38


@partial(jax.jit, static_argnames=("n",))
def apply_frame(values: jnp.ndarray, frame: Frame, n: int) -> jnp.ndarray:
    """One receiver step: ``values[i] += scale - bit_i * 2 * scale``
    (reference src/sharedtensor.c:106-111), padding masked to stay 0."""
    n_pad = values.shape[0]
    bits = unpack_bits(frame.words)
    live = jnp.arange(n_pad, dtype=jnp.int32) < n
    delta = frame.scale * (1.0 - 2.0 * bits.astype(jnp.float32))
    return jnp.where(live, jnp.clip(values + delta, -SAT, SAT), 0.0)


@partial(jax.jit, static_argnames=("n",))
def apply_frame_many(
    arrays: tuple[jnp.ndarray, ...], frame: Frame, n: int
) -> tuple[jnp.ndarray, ...]:
    """Apply one frame to several arrays in one traced step — the receive-side
    flood: a frame from link A updates the replica plus the residuals of every
    *other* link (split horizon; reference src/sharedtensor.c:124-127)."""
    n_pad = arrays[0].shape[0]
    bits = unpack_bits(frame.words)
    live = jnp.arange(n_pad, dtype=jnp.int32) < n
    delta = jnp.where(live, frame.scale * (1.0 - 2.0 * bits.astype(jnp.float32)), 0.0)
    return tuple(jnp.clip(a + delta, -SAT, SAT) for a in arrays)


@partial(jax.jit, static_argnames=("n",))
def accumulate(
    arrays: tuple[jnp.ndarray, ...], update: jnp.ndarray, n: int
) -> tuple[jnp.ndarray, ...]:
    """The local additive update: ``values += u`` and every link residual
    ``+= u`` in one step (reference addFromInternal, src/sharedtensor.c:
    334-344). ``update`` is already padded; padding is re-masked for safety.

    Updates are sanitized at this boundary (NaN -> 0, +/-inf clamped): one bad
    delta in the reference NaN-poisons every replica through the flood (quirk
    Q9); here bad values never enter the shared state.
    """
    n_pad = arrays[0].shape[0]
    live = jnp.arange(n_pad, dtype=jnp.int32) < n
    u = jnp.where(live, update, 0.0)
    u = jnp.nan_to_num(u, nan=0.0, posinf=3.0e38, neginf=-3.0e38)
    # Clamp the sum too: a residual near f32 max plus a large update would
    # otherwise overflow to inf and permanently wedge the link.
    return tuple(jnp.clip(a + u, -3.0e38, 3.0e38) for a in arrays)


def pad_flat(x: jnp.ndarray, n_pad: int | None = None) -> jnp.ndarray:
    """Flatten to 1-D float32 and zero-pad to a tile multiple."""
    flat = jnp.ravel(x).astype(jnp.float32)
    n = flat.shape[0]
    n_pad = padded_len(n) if n_pad is None else n_pad
    return jnp.pad(flat, (0, n_pad - n))


def unpad(flat: jnp.ndarray, shape: Sequence[int]) -> jnp.ndarray:
    """Undo :func:`pad_flat` back to the caller's shape."""
    n = 1
    for d in shape:
        n *= d
    return flat[:n].reshape(shape)
