"""Pallas TPU kernels for the approximate-delta codec.

The reference's hot path is 4-6 sequential CPU passes of n float ops per frame
(quantize src/sharedtensor.c:153-174, apply :106-111 — measured codec-CPU-bound
at 202 M elem/s, BASELINE.md). These kernels move that work onto the TPU VPU
with the minimum number of HBM passes:

- ``quantize``: one reduction pass for the scale (XLA — it is a dependency of
  every element, so a second pass is inherent, exactly as in the reference),
  then ONE fused pass that sign-quantizes, packs the bits into LSB-first
  uint32 words, and applies the error feedback to the residual.
- ``apply_frame_many``: ONE fused pass that unpacks the bits once and adds the
  reconstructed +/-scale delta to K arrays (replica + other links' residuals —
  the split-horizon flood), instead of K separate unpack+apply passes.

Bit layout is identical to ops/codec.py (flat bit i -> word[i//32] bit i%32),
so frames from either implementation interoperate; parity tests in
tests/test_codec_pallas.py require bit-for-bit equality.

Kernels run compiled on TPU and fall back to the interpreter on CPU (tests).

Layout: flat padded length n_pad (multiple of 1024) viewed as (n_pad/128, 128)
float32 rows; packed words viewed as (n_pad/128, 4) uint32 rows. Row r, word k
covers flat bits 128*r + 32*k .. +31, so ``words2d.reshape(-1)`` is the flat
word vector used by the wire layer.
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # noqa: F401  (memory spaces)

from ..config import ScalePolicy
from .codec import SAT, Frame, compute_scale
from .packing import LANES, BITS_PER_WORD

WORDS_PER_ROW = LANES // BITS_PER_WORD  # 4
#: Rows per grid step: 512 rows x 128 lanes x 4 B = 256 KiB per buffer in
#: VMEM — small enough to leave room for the multi-array apply, large enough
#: to amortize grid overhead.
BLOCK_ROWS = 512


def _interpret() -> bool:
    """Compiled on any TPU-like backend, interpreter elsewhere (CPU tests).

    The tunneled TPU plugin registers platform name "axon", not "tpu" —
    matching on backend name alone would silently interpret on the real chip
    (round-1 bench postmortem), so also accept any device whose device_kind
    says TPU.
    """
    backend = jax.default_backend()
    if backend in ("tpu", "axon"):
        return False
    try:
        kind = getattr(jax.devices()[0], "device_kind", "")
    except Exception:
        kind = ""
    return "tpu" not in kind.lower()


def use_pallas() -> bool:
    """Should the production codec paths (ops/table.py, parallel/ici.py) run
    these kernels? Default: yes exactly when they would compile (real TPU);
    on CPU the pure-XLA codec is faster than the Pallas interpreter.
    ``ST_CODEC=pallas|xla`` overrides (tests use it to pin either tier)."""
    mode = os.environ.get("ST_CODEC", "auto").lower()
    if mode == "pallas":
        return True
    if mode == "xla":
        return False
    return not _interpret()


def _live_mask(block_rows: int, pid, n: int):
    """live[i,j] = (flat index of element (i,j) in this block) < n."""
    row = jax.lax.broadcasted_iota(jnp.int32, (block_rows, LANES), 0)
    lane = jax.lax.broadcasted_iota(jnp.int32, (block_rows, LANES), 1)
    flat = (pid * block_rows + row) * LANES + lane
    return flat < n


def _exact_pow2(e_i32):
    """2^e as exact float32 via exponent-field construction (e in [0, 15]).
    TPU exp2 is approximate and must never be used for codec bit math."""
    return jax.lax.bitcast_convert_type((e_i32 + 127) << 23, jnp.float32)


def _pack_rows(bits_i32):
    """(rows, 128) 0/1 int32 -> (rows, 4) uint32, LSB-first per 32 lanes.

    Mosaic supports neither unsigned reductions nor lane-splitting reshapes
    ((rows,128)->(rows,4,32) fails "unsupported shape cast"), so the
    lane-group reduction runs on the MXU instead: two (rows,128)x(128,4) dots
    with constant weight matrices W_half[l, k] = [l//32 == k] * 2^(l%16),
    one for the low 16 bits of each word and one for the high 16. Every value
    stays <= 65535, so the f32 dot is exact; the halves are recombined with
    integer shifts.
    """
    rows = bits_i32.shape[0]
    lane = jax.lax.broadcasted_iota(jnp.int32, (LANES, WORDS_PER_ROW), 0)
    word = jax.lax.broadcasted_iota(jnp.int32, (LANES, WORDS_PER_ROW), 1)
    in_word = lane // BITS_PER_WORD == word
    e = lane % BITS_PER_WORD  # bit position within the word, 0..31
    w_lo = jnp.where(in_word & (e < 16), _exact_pow2(e % 16), 0.0)
    w_hi = jnp.where(in_word & (e >= 16), _exact_pow2(e % 16), 0.0)
    bits_f = bits_i32.astype(jnp.float32)
    lo = jnp.dot(bits_f, w_lo, preferred_element_type=jnp.float32)
    hi = jnp.dot(bits_f, w_hi, preferred_element_type=jnp.float32)
    words_i32 = lo.astype(jnp.int32) | (hi.astype(jnp.int32) << 16)
    return jax.lax.bitcast_convert_type(words_i32, jnp.uint32)


def _unpack_rows(words_u32):
    """(rows, 4) uint32 -> (rows, 128) 0/1 int32 (inverse of _pack_rows).

    The lane replication (lane l <- word[l//32]) must stay in integer domain:
    an MXU dot would round its f32 inputs to bf16 and corrupt word values
    above 2^8. Each word column is lane-broadcast to its 32 lanes and the
    four spans concatenated; bit extraction is then shift+mask in int32
    (`& 1` discards arithmetic-shift sign extension).
    """
    rows = words_u32.shape[0]
    words = jax.lax.bitcast_convert_type(words_u32, jnp.int32)
    wrep = jnp.concatenate(
        [
            jnp.broadcast_to(words[:, k : k + 1], (rows, BITS_PER_WORD))
            for k in range(WORDS_PER_ROW)
        ],
        axis=1,
    )
    shift = jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 1) % BITS_PER_WORD
    return (wrep >> shift) & jnp.int32(1)


# --- quantize --------------------------------------------------------------


def _quantize_kernel(scale_ref, resid_ref, words_ref, new_resid_ref, *, n):
    s = scale_ref[0, 0]
    r = resid_ref[...]
    live = _live_mask(r.shape[0], pl.program_id(0), n)
    neg = r <= 0.0  # bit set => send -scale (zero counts as negative, Q3)
    bits = jnp.logical_and(live, neg)
    words_ref[...] = _pack_rows(bits.astype(jnp.int32))
    sent = jnp.where(neg, -s, s)
    # s == 0: idle frame, residual untouched; padding lanes are forced back
    # to 0 either way (re-establishes the invariant even if the caller handed
    # us a buffer with garbage past n — matches ops/codec.py exactly).
    new_r = jnp.where(jnp.logical_and(live, s > 0.0), r - sent, jnp.where(live, r, 0.0))
    new_resid_ref[...] = new_r


@partial(jax.jit, static_argnames=("n", "policy"), donate_argnums=(0,))
def quantize(
    residual: jnp.ndarray,
    n: int,
    policy: ScalePolicy = ScalePolicy.POW2_RMS,
) -> tuple[Frame, jnp.ndarray]:
    """Drop-in replacement for ops.codec.quantize (bit-for-bit identical),
    with the quantize/pack/error-feedback pass as a single fused kernel.

    The residual argument is donated: on TPU the new residual reuses the old
    one's HBM buffer (callers in the sync engine always replace it).
    """
    n_pad = residual.shape[0]
    rows = n_pad // LANES
    block = min(BLOCK_ROWS, rows)
    scale = compute_scale(residual, n, policy)
    words2d, new_resid = pl.pallas_call(
        partial(_quantize_kernel, n=n),
        grid=(pl.cdiv(rows, block),),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((block, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec(
                (block, WORDS_PER_ROW), lambda i: (i, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec((block, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, WORDS_PER_ROW), jnp.uint32),
            jax.ShapeDtypeStruct((rows, LANES), jnp.float32),
        ],
        input_output_aliases={1: 1},  # new residual reuses the old buffer
        interpret=_interpret(),
    )(scale.reshape(1, 1), residual.reshape(rows, LANES))
    return Frame(scale, words2d.reshape(-1)), new_resid.reshape(-1)


# --- apply -----------------------------------------------------------------


def _apply_kernel(scale_ref, words_ref, *refs, n, k):
    s = scale_ref[0, 0]
    bits = _unpack_rows(words_ref[...])
    live = _live_mask(bits.shape[0], pl.program_id(0), n)
    delta = s * (1.0 - 2.0 * bits.astype(jnp.float32))
    in_refs, out_refs = refs[:k], refs[k:]
    for i_ref, o_ref in zip(in_refs, out_refs):
        # Padding lanes forced to 0; result clamped like the golden
        # apply_frame (codec.SAT — no absorbing inf/NaN state, any tier).
        o_ref[...] = jnp.where(
            live, jnp.clip(i_ref[...] + delta, -SAT, SAT), 0.0
        )


@partial(jax.jit, static_argnames=("n",), donate_argnums=(0,))
def apply_frame_many(
    arrays: tuple[jnp.ndarray, ...], frame: Frame, n: int
) -> tuple[jnp.ndarray, ...]:
    """Fused receive-side flood: unpack the frame once, add the +/-scale delta
    to every array (replica + other links' residuals) in one HBM pass.
    Bit-for-bit identical to ops.codec.apply_frame_many. Arrays are donated
    (updated in place on TPU)."""
    k = len(arrays)
    n_pad = arrays[0].shape[0]
    rows = n_pad // LANES
    block = min(BLOCK_ROWS, rows)
    blk = lambda i: (i, 0)
    vspec = pl.BlockSpec((block, LANES), blk, memory_space=pltpu.VMEM)
    outs = pl.pallas_call(
        partial(_apply_kernel, n=n, k=k),
        grid=(pl.cdiv(rows, block),),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec(
                (block, WORDS_PER_ROW), blk, memory_space=pltpu.VMEM
            ),
        ]
        + [vspec] * k,
        out_specs=[vspec] * k,
        out_shape=[jax.ShapeDtypeStruct((rows, LANES), jnp.float32)] * k,
        input_output_aliases={2 + i: i for i in range(k)},
        interpret=_interpret(),
    )(
        frame.scale.reshape(1, 1),
        frame.words.reshape(rows, WORDS_PER_ROW),
        *[a.reshape(rows, LANES) for a in arrays],
    )
    return tuple(o.reshape(-1) for o in outs)


@partial(jax.jit, static_argnames=("n",), donate_argnums=(0,))
def apply_frame(values: jnp.ndarray, frame: Frame, n: int) -> jnp.ndarray:
    """Single-array apply (see apply_frame_many)."""
    return apply_frame_many((values,), frame, n)[0]


# --- row-granular primitives (the table tier) -------------------------------
#
# The table codec (ops/table.py) runs the same sign/error-feedback rule with a
# DIFFERENT scale per leaf — per-leaf padding is row-aligned, so at kernel
# granularity that is simply "a scale per (1,128) row" plus "live lanes per
# row". These two primitives are the fused production tier for it (round-2
# verdict: the scalar kernels above were proven on chip but only the pure-XLA
# path shipped; these are what ops/table.py and parallel/ici.py now call).
# They are deliberately UN-jitted: table.py wraps them in its own jit, and
# parallel/ici.py embeds them inside a shard_map'd step.


def _quantize_rows_kernel(s_ref, cnt_ref, resid_ref, words_ref, new_resid_ref):
    s = s_ref[...]  # (block, 1) per-row scale
    c = cnt_ref[...]  # (block, 1) live lanes per row (0..128)
    r = resid_ref[...]  # (block, LANES)
    lane = jax.lax.broadcasted_iota(jnp.int32, r.shape, 1)
    live = lane < c
    neg = r <= 0.0  # bit set => send -scale (zero counts as negative, Q3)
    bits = jnp.logical_and(live, neg)
    words_ref[...] = _pack_rows(bits.astype(jnp.int32))
    sent = jnp.where(neg, -s, s)
    # rows whose leaf idles at scale 0 keep their residual; padding lanes are
    # forced back to 0 (the ops/table.py invariant, bit-for-bit)
    new_resid_ref[...] = jnp.where(
        jnp.logical_and(live, s > 0.0), r - sent, jnp.where(live, r, 0.0)
    )


def quantize_rows(
    s_row: jnp.ndarray, rowcount: jnp.ndarray, residual: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Fused sender pass with per-row scales: sign-quantize + LSB-first pack +
    error feedback in ONE pass over HBM.

    ``s_row`` f32[rows] (leaf scale broadcast to its rows), ``rowcount``
    i32[rows] (live lanes per row), ``residual`` f32[rows*128] flat.
    Returns (words u32[rows*4] flat, new_residual flat). Traceable — callers
    jit. Bit-for-bit equal to the ops/table.py XLA path.
    """
    rows = residual.shape[0] // LANES
    block = min(BLOCK_ROWS, rows)
    row_spec = lambda w: pl.BlockSpec((block, w), lambda i: (i, 0), memory_space=pltpu.VMEM)
    words2d, new_resid = pl.pallas_call(
        _quantize_rows_kernel,
        grid=(pl.cdiv(rows, block),),
        in_specs=[row_spec(1), row_spec(1), row_spec(LANES)],
        out_specs=[row_spec(WORDS_PER_ROW), row_spec(LANES)],
        out_shape=[
            jax.ShapeDtypeStruct((rows, WORDS_PER_ROW), jnp.uint32),
            jax.ShapeDtypeStruct((rows, LANES), jnp.float32),
        ],
        input_output_aliases={2: 1},
        interpret=_interpret(),
    )(
        s_row.reshape(rows, 1),
        rowcount.reshape(rows, 1).astype(jnp.int32),
        residual.reshape(rows, LANES),
    )
    return words2d.reshape(-1), new_resid.reshape(-1)


def _apply_rows_kernel(s_ref, cnt_ref, words_ref, *refs, k_frames, n_arrays):
    c = cnt_ref[...]  # (block, 1)
    lane = jax.lax.broadcasted_iota(jnp.int32, (c.shape[0], LANES), 1)
    live = lane < c
    delta = jnp.zeros((c.shape[0], LANES), jnp.float32)
    for kf in range(k_frames):
        w = words_ref[:, kf * WORDS_PER_ROW : (kf + 1) * WORDS_PER_ROW]
        bits = _unpack_rows(w)
        s = s_ref[:, kf : kf + 1]  # (block, 1)
        delta = delta + s * (1.0 - 2.0 * bits.astype(jnp.float32))
    delta = jnp.where(live, delta, 0.0)
    in_refs, out_refs = refs[:n_arrays], refs[n_arrays:]
    for i_ref, o_ref in zip(in_refs, out_refs):
        o_ref[...] = jnp.where(
            live, jnp.clip(i_ref[...] + delta, -SAT, SAT), 0.0
        )


def apply_rows_batch(
    s_rows: jnp.ndarray,
    rowcount: jnp.ndarray,
    words2d: jnp.ndarray,
    arrays: tuple[jnp.ndarray, ...],
) -> tuple[jnp.ndarray, ...]:
    """Fused receive pass for K frames x N target arrays, per-row scales: the
    frames are unpacked ONCE, their +/-scale deltas summed (codec deltas are
    pure adds — they commute, ops/table.py apply_table_batch rationale), and
    the sum applied to every array in one HBM pass.

    ``s_rows`` f32[rows, K] — per-frame, per-row scales (a frame's column is 0
    where it contributes nothing: idle leaves, split-horizon self-masking in
    parallel/ici.py); ``words2d`` u32[rows, K*4] — frame k's packed bits for
    row r at [r, 4k:4k+4]; ``arrays`` flat f32[rows*128] each.
    """
    rows = arrays[0].shape[0] // LANES
    k = s_rows.shape[1]
    n_arr = len(arrays)
    # Cap the words block at ~2 MiB of VMEM so large K still fits alongside
    # the target arrays (block stays a whole number of 8-row tiles).
    block = min(BLOCK_ROWS, rows, max(8, (2 << 20) // (k * WORDS_PER_ROW * 4) // 8 * 8))
    row_spec = lambda w: pl.BlockSpec((block, w), lambda i: (i, 0), memory_space=pltpu.VMEM)
    vspec = row_spec(LANES)
    outs = pl.pallas_call(
        partial(_apply_rows_kernel, k_frames=k, n_arrays=n_arr),
        grid=(pl.cdiv(rows, block),),
        in_specs=[row_spec(k), row_spec(1), row_spec(k * WORDS_PER_ROW)]
        + [vspec] * n_arr,
        out_specs=[vspec] * n_arr,
        out_shape=[jax.ShapeDtypeStruct((rows, LANES), jnp.float32)] * n_arr,
        input_output_aliases={3 + i: i for i in range(n_arr)},
        interpret=_interpret(),
    )(
        s_rows,
        rowcount.reshape(rows, 1).astype(jnp.int32),
        words2d,
        *[a.reshape(rows, LANES) for a in arrays],
    )
    return tuple(o.reshape(-1) for o in outs)
