"""Device-tier (jitted JAX) implementations of the codec-lab methods.

ops/codec_lab.py measures the alternative compression methods on host
trajectories; this module shows the two winners are implementable in the
TPU compute path with the same layout discipline as the production codec
(ops/codec.py: flat f32 padded to the (8,128) tile, pad lanes pinned to
zero, LSB-first bit packing from ops/packing.py — so the packed codes
serialize to the identical bytes the numpy lab produces):

``sign2_quantize`` / ``sign2_apply``
    The 2-bit sign-magnitude quantizer ({±s, ±3s}, magnitude bit at
    |r| > 2s — the measured-best 2-bit design, see codec_lab.Sign2).
    Codes interleave as flat bits [sign_0, mag_0, sign_1, mag_1, ...]
    packed into uint32 words, exactly the numpy lab's
    ``packbits(..., bitorder="little")`` layout.

``topk_quantize`` / ``topk_apply``
    Sparse exact transfer via ``lax.top_k`` on |r|. Static k (XLA needs
    static shapes); coordinates whose residual is exactly zero still
    occupy slots but carry value 0 — a no-op on both ends, so
    conservation is unaffected (the host lab instead drops them from the
    payload; on device the fixed-size slot IS the honest wire cost).

Everything is jittable with static ``n``/``k``/``policy`` and runs under
the standard test mesh (CPU) today; on TPU these compile to the same
fused elementwise + reduce shapes the production codec uses. Parity with
the numpy lab is pinned bit-for-bit in tests/test_codec_lab_jax.py —
with the same caveat every cross-tier scale comparison in this codebase
carries (stengine.cpp header, ops/codec_np.py): the RMS accumulations
differ in summation order/precision across tiers, and the pow2 floor
absorbs those ulps EXCEPT when the true RMS sits exactly at an octave
boundary, where the tiers may legally pick adjacent octaves. Scales ride
the wire (receivers never recompute them), so this affects only
same-trajectory comparisons, never correctness.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ..config import ScalePolicy
from .codec import SAT, compute_scale
from .packing import pack_bits, unpack_bits


@partial(jax.jit, static_argnames=("n", "policy"))
def sign2_quantize(
    residual: jnp.ndarray,
    n: int,
    policy: ScalePolicy = ScalePolicy.POW2_RMS,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One 2-bit sender step: residual -> (scale, packed codes, new_residual).

    Sign rule matches the production codec (r <= 0 => sign bit set, quirk
    Q3's zero-negative convention); magnitude bit set where |r| > 2s sends
    ±3s instead of ±s. With scale == 0 the residual is untouched (idle
    frame). Pad lanes: both bits forced 0, residual stays 0."""
    n_pad = residual.shape[0]
    scale = compute_scale(residual, n, policy)
    live = jnp.arange(n_pad, dtype=jnp.int32) < n
    neg = residual <= 0
    big = jnp.abs(residual) > 2.0 * scale
    mag = jnp.where(big, 3.0 * scale, scale)
    sent = jnp.where(neg, -mag, mag)
    new_residual = jnp.where(live, residual - sent, 0.0)
    new_residual = jnp.where(scale > 0, new_residual, residual)
    codes = jnp.stack(
        [jnp.where(live, neg, False), jnp.where(live, big, False)], axis=-1
    ).reshape(2 * n_pad)
    return scale, pack_bits(codes), new_residual


@partial(jax.jit, static_argnames=("n",))
def sign2_apply(
    values: jnp.ndarray, scale: jnp.ndarray, words: jnp.ndarray, n: int
) -> jnp.ndarray:
    """One 2-bit receiver step, clamped to ±SAT like every apply tier."""
    n_pad = values.shape[0]
    codes = unpack_bits(words).reshape(n_pad, 2)
    neg = codes[:, 0].astype(jnp.float32)
    big = codes[:, 1].astype(jnp.float32)
    mag = scale * (1.0 + 2.0 * big)
    delta = (1.0 - 2.0 * neg) * mag
    live = jnp.arange(n_pad, dtype=jnp.int32) < n
    # scale == 0 (idle/corrupt-zeroed) decodes to a no-op even though the
    # sign bits would otherwise read as ±scale
    delta = jnp.where(live & (scale > 0), delta, 0.0)
    return jnp.where(live, jnp.clip(values + delta, -SAT, SAT), 0.0)


@partial(jax.jit, static_argnames=("k",))
def topk_quantize(
    residual: jnp.ndarray, k: int
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Sparse sender step: residual -> (indices u32[k], values f32[k],
    new_residual). The k largest-|r| coordinates ship exactly and zero out
    of the residual (exact conservation — f32 copies, no rounding)."""
    absr = jnp.abs(residual)
    _, idx = jax.lax.top_k(absr, k)
    vals = residual[idx]
    new_residual = residual.at[idx].set(0.0)
    return idx.astype(jnp.uint32), vals, new_residual


@partial(jax.jit, static_argnames=("n",))
def topk_apply(
    values: jnp.ndarray, idx: jnp.ndarray, vals: jnp.ndarray, n: int
) -> jnp.ndarray:
    """Sparse receiver step: values[idx] += vals, clamped to ±SAT. Indices
    are distinct by construction (top_k), so add == set semantics on the
    delta; zero-valued slots are no-ops."""
    n_pad = values.shape[0]
    out = values.at[idx.astype(jnp.int32)].add(vals)
    live = jnp.arange(n_pad, dtype=jnp.int32) < n
    return jnp.where(live, jnp.clip(out, -SAT, SAT), 0.0)
