"""Sign-bit packing: bool arrays <-> uint32 words <-> reference wire bytes.

Layout contract (load-bearing for wire compatibility): flat bit ``i`` lives in
word ``i // 32`` at bit position ``i % 32`` (LSB-first). Serializing the words
little-endian therefore reproduces the reference's bitmask byte layout exactly
— bit ``i`` at ``byte[i/8]``, position ``i % 8``, LSB-first (reference
src/sharedtensor.c:106-111 receiver, :166-174 sender) — so one packed
representation serves both the TPU-native path and wire-compat interop.

All functions here are pure JAX (jittable) except the ``*_wire_*`` pair, which
are host-side numpy (they touch Python ``bytes``).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

#: float32 TPU tile is (8, 128) sublanes x lanes; pad flat buffers to this so
#: the Pallas kernels see whole tiles.
LANES = 128
SUBLANES = 8
TILE = LANES * SUBLANES  # 1024
BITS_PER_WORD = 32


def padded_len(n: int, multiple: int = TILE) -> int:
    """Smallest multiple of ``multiple`` >= n (and >= 1 tile)."""
    if n <= 0:
        raise ValueError(f"need a positive element count, got {n}")
    return ((n + multiple - 1) // multiple) * multiple


def pack_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """Pack a flat bool/int array (length divisible by 32) into uint32 words,
    LSB-first: ``word[j] = sum_b bits[32*j+b] << b``."""
    n = bits.shape[-1]
    assert n % BITS_PER_WORD == 0, n
    b = bits.astype(jnp.uint32).reshape(*bits.shape[:-1], -1, BITS_PER_WORD)
    shifts = jnp.arange(BITS_PER_WORD, dtype=jnp.uint32)
    return jnp.sum(b << shifts, axis=-1, dtype=jnp.uint32)


def unpack_bits(words: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`pack_bits`: uint32 words -> flat int32 0/1 array."""
    shifts = jnp.arange(BITS_PER_WORD, dtype=jnp.uint32)
    bits = (words[..., :, None] >> shifts) & jnp.uint32(1)
    return bits.reshape(*words.shape[:-1], -1).astype(jnp.int32)


def words_to_wire(words: np.ndarray, n: int) -> bytes:
    """Serialize packed words to the reference's bitmask wire bytes:
    little-endian words truncated to ``ceil(n/8)`` bytes."""
    raw = np.asarray(words, dtype="<u4").tobytes()
    return raw[: (n + 7) // 8]


def wire_to_words(payload: bytes, n_padded: int) -> np.ndarray:
    """Parse reference bitmask wire bytes into ``n_padded/32`` uint32 words
    (zero-filled past the wire payload)."""
    nwords = n_padded // BITS_PER_WORD
    buf = np.zeros(nwords * 4, dtype=np.uint8)
    buf[: len(payload)] = np.frombuffer(payload, dtype=np.uint8)
    return buf.view("<u4").astype(np.uint32)
