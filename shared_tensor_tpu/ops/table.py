"""Table sync: the codec generalized to a pytree ("table") of tensors with an
independent scale per leaf.

The reference syncs exactly one flat float buffer with ONE global scale; its
README's top wishlist item is "Allow a table of tensors to be synced" because
mixed-magnitude parameter sets degrade badly under a single scale (reference
README.md:41; measured in BASELINE.md: 1000:1 mix leaves the small half at 24%
error after 48 frames). This module provides that capability natively:

- A pytree is flattened into ONE padded flat buffer, each leaf padded to a
  whole (8,128)-tile multiple so leaf boundaries are row-aligned.
- Quantization computes an independent power-of-2 RMS scale per leaf
  (segment reductions), then runs the same sign/error-feedback rule with a
  per-row scale — still a single pass over HBM, one frame on the wire.
- The wire frame carries k scales (one per leaf) + the packed bitmask.

With a single-leaf table this is byte-for-byte the reference codec.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..config import ScalePolicy
from .codec import SAT, pad_flat, pow2_floor
from .packing import LANES, TILE, pack_bits, padded_len, unpack_bits


class TableFrame(NamedTuple):
    """One codec frame for a table: per-leaf scales + packed sign bits."""

    scales: jnp.ndarray  # f32[num_leaves]
    words: jnp.ndarray  # uint32[total_padded // 32]


@dataclasses.dataclass(frozen=True)
class TableSpec:
    """Static layout of a pytree flattened into one padded flat buffer.

    Hashable (all-tuple fields) so it can be a jit static argument. Leaf i
    occupies flat rows [row_offsets[i], row_offsets[i] + padded[i]//128) with
    ns[i] live elements.
    """

    treedef: Any
    shapes: tuple[tuple[int, ...], ...]
    ns: tuple[int, ...]  # true element count per leaf
    padded: tuple[int, ...]  # padded length per leaf (tile multiple)

    @property
    def num_leaves(self) -> int:
        return len(self.ns)

    @property
    def total(self) -> int:
        return sum(self.padded)

    @property
    def total_n(self) -> int:
        return sum(self.ns)

    def layout_digest(self) -> bytes:
        """16-byte digest identifying the full table layout (tree structure,
        leaf shapes, padding). Two specs with equal digests decode each
        other's frames leaf-for-leaf; (num_leaves, total_n) alone cannot
        distinguish e.g. {w:(8,128), b:(128,)} from {w:(128,), b:(8,128)}."""
        import hashlib

        desc = repr((str(self.treedef), self.shapes, self.ns, self.padded))
        return hashlib.sha256(desc.encode()).digest()[:16]

    def row_leaf(self) -> np.ndarray:
        """int32[rows]: leaf index owning each 128-lane row."""
        return np.repeat(
            np.arange(self.num_leaves, dtype=np.int32),
            [p // LANES for p in self.padded],
        )

    def live_rowcount(self) -> np.ndarray:
        """int32[rows]: number of live lanes in each row (0..128)."""
        counts = []
        for n, p in zip(self.ns, self.padded):
            rows = p // LANES
            full, rem = divmod(n, LANES)
            c = np.zeros(rows, dtype=np.int32)
            c[:full] = LANES
            if rem:
                c[full] = rem
            counts.append(c)
        return np.concatenate(counts)


def make_spec(tree: Any) -> TableSpec:
    """Build the static layout for a pytree of arrays."""
    leaves, treedef = jax.tree.flatten(tree)
    shapes = tuple(tuple(np.shape(l)) for l in leaves)
    ns = tuple(int(np.prod(s)) if s else 1 for s in shapes)
    padded = tuple(padded_len(n, TILE) for n in ns)
    return TableSpec(treedef, shapes, ns, padded)


def flatten(tree: Any, spec: TableSpec) -> jnp.ndarray:
    """Pytree -> single padded flat float32 buffer (padding exactly 0)."""
    leaves, treedef = jax.tree.flatten(tree)
    if treedef != spec.treedef:
        # the reference raises THError("Not the right size!") on mismatch
        # (src/sharedtensor.c:335); a structural mismatch here would silently
        # merge deltas into the wrong leaves and flood the corruption to
        # every replica.
        raise ValueError(
            f"tree structure {treedef} does not match spec {spec.treedef}"
        )
    parts = []
    for i, (leaf, n, p) in enumerate(zip(leaves, spec.ns, spec.padded)):
        flat = jnp.ravel(jnp.asarray(leaf)).astype(jnp.float32)
        if flat.shape[0] != n:
            raise ValueError(
                f"leaf {i} has {flat.shape[0]} elements, spec expects {n}"
            )
        parts.append(pad_flat(flat, p))
    return jnp.concatenate(parts) if len(parts) > 1 else parts[0]


def unflatten(flat: jnp.ndarray, spec: TableSpec) -> Any:
    """Inverse of :func:`flatten`."""
    leaves = []
    off = 0
    for shape, n, p in zip(spec.shapes, spec.ns, spec.padded):
        leaves.append(flat[off : off + n].reshape(shape))
        off += p
    return jax.tree.unflatten(spec.treedef, leaves)


def _live_mask_flat(spec: TableSpec) -> np.ndarray:
    """bool[total]: True for live (non-padding) elements."""
    rows = spec.live_rowcount()
    lane = np.arange(LANES, dtype=np.int32)
    return (lane[None, :] < rows[:, None]).reshape(-1)


def compute_scales(
    residual: jnp.ndarray,
    spec: TableSpec,
    policy: ScalePolicy = ScalePolicy.POW2_RMS,
) -> jnp.ndarray:
    """Per-leaf step sizes (overflow-safe segment RMS; see codec.compute_scale
    for the scalar version this generalizes)."""
    k = spec.num_leaves
    rows = residual.reshape(-1, LANES)
    row_leaf = jnp.asarray(spec.row_leaf())
    amax_row = jnp.max(jnp.abs(rows), axis=1)
    amax = jax.ops.segment_max(amax_row, row_leaf, num_segments=k)
    amax = jnp.maximum(amax, 0.0)  # segment_max identity is -inf
    denom = jnp.where(amax > 0, amax, 1.0)
    norm = rows / denom[row_leaf][:, None]
    ns = jnp.asarray(np.asarray(spec.ns, dtype=np.float32))
    if policy == ScalePolicy.ABS_MEAN:
        s_row = jnp.sum(jnp.abs(norm), axis=1, dtype=jnp.float32)
        mean = jax.ops.segment_sum(s_row, row_leaf, num_segments=k) / ns
        scales = amax * mean
    else:
        ss_row = jnp.sum(norm * norm, axis=1, dtype=jnp.float32)
        rms = amax * jnp.sqrt(
            jax.ops.segment_sum(ss_row, row_leaf, num_segments=k) / ns
        )
        scales = pow2_floor(rms) if policy == ScalePolicy.POW2_RMS else rms
    rms_pos = amax > 0
    return jnp.where(rms_pos & jnp.isfinite(scales), scales, 0.0)


def _table_scales(
    residual: jnp.ndarray,
    spec: TableSpec,
    policy: ScalePolicy,
    per_leaf: bool,
) -> jnp.ndarray:
    """Per-leaf scales; ``per_leaf=False`` computes ONE scale over the whole
    table (the reference's behavior, src/sharedtensor.c:153-159 — wire-compat
    interop with C peers requires it) replicated to every leaf so the apply
    path is uniform."""
    if per_leaf:
        return compute_scales(residual, spec, policy)
    one_spec = dataclasses.replace(
        spec,
        shapes=((spec.total_n,),),
        ns=(spec.total_n,),
        padded=(spec.total,),
    )
    # NOTE: valid because padding lanes are 0 by invariant; the single-
    # leaf view only changes which elements each scale aggregates over.
    s = compute_scales(residual, one_spec, policy)[0]
    return jnp.full((spec.num_leaves,), s, jnp.float32)


def _resolve_impl(impl: str) -> str:
    """'auto' -> the Pallas kernels exactly when they would compile (TPU);
    pure XLA elsewhere (CPU tests/peers). See codec_pallas.use_pallas."""
    if impl != "auto":
        return impl
    from . import codec_pallas

    return "pallas" if codec_pallas.use_pallas() else "xla"


@partial(jax.jit, static_argnames=("spec", "policy", "per_leaf", "impl"))
def _quantize_table(
    residual: jnp.ndarray,
    spec: TableSpec,
    policy: ScalePolicy,
    per_leaf: bool,
    impl: str,
) -> tuple[TableFrame, jnp.ndarray]:
    scales = _table_scales(residual, spec, policy, per_leaf)
    row_leaf = jnp.asarray(spec.row_leaf())
    if impl == "pallas":
        from . import codec_pallas

        words, new_flat = codec_pallas.quantize_rows(
            scales[row_leaf], jnp.asarray(spec.live_rowcount()), residual
        )
        return TableFrame(scales, words), new_flat
    rows = residual.reshape(-1, LANES)
    s_row = scales[row_leaf][:, None]  # (rows, 1)
    live = jnp.asarray(_live_mask_flat(spec)).reshape(-1, LANES)
    neg = rows <= 0
    bits = jnp.where(live, neg, False)
    sent = jnp.where(neg, -s_row, s_row)
    new_rows = jnp.where(live & (s_row > 0), rows - sent, jnp.where(live, rows, 0.0))
    return (
        TableFrame(scales, pack_bits(bits.reshape(-1))),
        new_rows.reshape(-1),
    )


def quantize_table(
    residual: jnp.ndarray,
    spec: TableSpec,
    policy: ScalePolicy = ScalePolicy.POW2_RMS,
    per_leaf: bool = True,
    impl: str = "auto",
) -> tuple[TableFrame, jnp.ndarray]:
    """Sender step over a table: one pass, per-leaf scales.

    Per-leaf semantics are identical to codec.quantize: bit set iff r <= 0,
    residual moves by -+scale of its own leaf, leaves with scale 0 idle.

    On TPU the sign/pack/error-feedback pass runs as the fused Pallas kernel
    (codec_pallas.quantize_rows) — the production tier; the XLA path is the
    golden reference and the CPU fallback. ``impl`` pins either ("xla" /
    "pallas") for parity tests."""
    return _quantize_table(residual, spec, policy, per_leaf, _resolve_impl(impl))


@partial(jax.jit, static_argnames=("spec", "k", "policy", "per_leaf", "impl"))
def _quantize_table_burst(
    residual: jnp.ndarray,
    spec: TableSpec,
    k: int,
    policy: ScalePolicy,
    per_leaf: bool,
    impl: str,
) -> tuple[TableFrame, jnp.ndarray]:
    def body(r, _):
        frame, r2 = _quantize_table(r, spec, policy, per_leaf, impl)
        return r2, (frame.scales, frame.words)

    new_r, (scales, words) = jax.lax.scan(body, residual, None, length=k)
    return TableFrame(scales, words), new_r


def quantize_table_burst(
    residual: jnp.ndarray,
    spec: TableSpec,
    k: int,
    policy: ScalePolicy = ScalePolicy.POW2_RMS,
    per_leaf: bool = True,
    impl: str = "auto",
) -> tuple[TableFrame, jnp.ndarray]:
    """K successive residual halvings in ONE device dispatch (lax.scan of
    the sender step): returns stacked (scales f32[K,L], words u32[K,W]) and
    the final residual. The point is the peer tier's device BURST path —
    one dispatch + ONE device->host fetch carries K frames, amortizing the
    device-link round trip exactly as the host burst amortizes per-message
    engine cost (round-3 verdict item 3: the tunneled device link's
    ~8 ms/frame round trip capped E2E at 109 f/s regardless of pipeline
    depth). Once the residual quantizes to all-zero scales every later
    frame in the scan is an exact no-op (scale 0 idles), so the host side
    trims the zero tail after the fetch."""
    return _quantize_table_burst(
        residual, spec, int(k), policy, per_leaf, _resolve_impl(impl)
    )


def _batch_layout(frames: TableFrame, spec: TableSpec):
    """(scales [K,L], words [K,W]) -> the row-major layout the Pallas batch
    kernel consumes: s_rows f32[rows, K], words2d u32[rows, K*4] (frame k's
    words for row r at [r, 4k:4k+4])."""
    k = frames.scales.shape[0]
    rows = spec.total // LANES
    row_leaf = jnp.asarray(spec.row_leaf())
    s_rows = frames.scales[:, row_leaf].T  # (rows, K)
    words2d = (
        frames.words.reshape(k, rows, LANES // 32)
        .transpose(1, 0, 2)
        .reshape(rows, k * (LANES // 32))
    )
    return s_rows, words2d


@partial(jax.jit, static_argnames=("spec", "impl"))
def _apply_table_many(
    arrays: tuple[jnp.ndarray, ...], frame: TableFrame, spec: TableSpec, impl: str
) -> tuple[jnp.ndarray, ...]:
    row_leaf = jnp.asarray(spec.row_leaf())
    if impl == "pallas":
        from . import codec_pallas

        rows = spec.total // LANES
        return codec_pallas.apply_rows_batch(
            frame.scales[row_leaf].reshape(rows, 1),
            jnp.asarray(spec.live_rowcount()),
            frame.words.reshape(rows, LANES // 32),
            arrays,
        )
    bits = unpack_bits(frame.words).reshape(-1, LANES)
    s_row = frame.scales[row_leaf][:, None]
    live = jnp.asarray(_live_mask_flat(spec)).reshape(-1, LANES)
    delta = jnp.where(live, s_row * (1.0 - 2.0 * bits.astype(jnp.float32)), 0.0)
    flat_delta = delta.reshape(-1)
    return tuple(
        jnp.where(live.reshape(-1), jnp.clip(a + flat_delta, -SAT, SAT), 0.0)
        for a in arrays
    )


def apply_table_many(
    arrays: tuple[jnp.ndarray, ...],
    frame: TableFrame,
    spec: TableSpec,
    impl: str = "auto",
) -> tuple[jnp.ndarray, ...]:
    """Receiver step over a table applied to several arrays (replica + other
    links' residuals — the flood), one fused pass (Pallas on TPU)."""
    return _apply_table_many(arrays, frame, spec, _resolve_impl(impl))


def apply_table(values: jnp.ndarray, frame: TableFrame, spec: TableSpec) -> jnp.ndarray:
    return apply_table_many((values,), frame, spec)[0]


@partial(jax.jit, static_argnames=("spec", "impl"))
def _apply_table_batch(
    arrays: tuple[jnp.ndarray, ...], frames: TableFrame, spec: TableSpec, impl: str
) -> tuple[jnp.ndarray, ...]:
    if impl == "pallas":
        from . import codec_pallas

        s_rows, words2d = _batch_layout(frames, spec)
        return codec_pallas.apply_rows_batch(
            s_rows, jnp.asarray(spec.live_rowcount()), words2d, arrays
        )
    k = frames.scales.shape[0]
    bits = unpack_bits(frames.words.reshape(-1)).reshape(k, -1, LANES)
    row_leaf = jnp.asarray(spec.row_leaf())
    s_row = frames.scales[:, row_leaf][:, :, None]  # [K, rows, 1]
    live = jnp.asarray(_live_mask_flat(spec)).reshape(-1, LANES)
    delta = jnp.sum(s_row * (1.0 - 2.0 * bits.astype(jnp.float32)), axis=0)
    flat_delta = jnp.where(live, delta, 0.0).reshape(-1)
    live_flat = live.reshape(-1)
    return tuple(
        jnp.where(live_flat, jnp.clip(a + flat_delta, -SAT, SAT), 0.0)
        for a in arrays
    )


def apply_table_batch(
    arrays: tuple[jnp.ndarray, ...],
    frames: TableFrame,
    spec: TableSpec,
    impl: str = "auto",
) -> tuple[jnp.ndarray, ...]:
    """Apply a STACK of K frames (scales f32[K, L], words u32[K, W]) in one
    dispatch: the summed delta of all K frames lands in one pass.

    Equivalent to applying the frames sequentially — codec deltas are pure
    adds, so they commute — but one device round-trip instead of K. This is
    what keeps the receive path ahead of a fast sender: per-frame dispatch
    overhead on a busy device was measured to back the RX queue up by
    hundreds of frames (train/hierarchical.py's two-pod run). Zero-scale
    padding frames contribute exactly nothing, so callers can pad a partial
    batch up to a bucketed K to bound jit specializations.

    On TPU the unpack/sum/apply runs as ONE fused Pallas pass
    (codec_pallas.apply_rows_batch) instead of K XLA unpack passes."""
    return _apply_table_batch(arrays, frames, spec, _resolve_impl(impl))


@partial(jax.jit, static_argnames=("spec",))
def accumulate_table(
    arrays: tuple[jnp.ndarray, ...], update: jnp.ndarray, spec: TableSpec
) -> tuple[jnp.ndarray, ...]:
    """values += u and each link residual += u, sanitized (see
    codec.accumulate)."""
    live = jnp.asarray(_live_mask_flat(spec))
    u = jnp.where(live, update, 0.0)
    u = jnp.nan_to_num(u, nan=0.0, posinf=3.0e38, neginf=-3.0e38)
    return tuple(jnp.clip(a + u, -3.0e38, 3.0e38) for a in arrays)
