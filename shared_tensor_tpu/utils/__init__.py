"""Auxiliary subsystems the reference lacks (SURVEY.md §5): checkpoint/
resume lives here; observability counters live on the objects they observe
(SharedTensor counters, peer.metrics(), per-frame scales from sync steps)."""

from . import checkpoint

__all__ = ["checkpoint"]
