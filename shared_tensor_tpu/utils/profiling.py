"""Tracing / profiling / rate metrics (SURVEY.md §5.1, §5.5 — the reference
has no instrumentation beyond lifecycle fprintf lines).

Three small tools:

- :func:`trace`: context manager around ``jax.profiler`` producing a
  TensorBoard-loadable device trace of whatever ran inside (the fused sync
  step, the codec kernels, a training loop).
- :class:`RateMeter`: turns the framework's monotonically-increasing
  counters (SharedTensor.frames_in/out, the canonical
  ``st_link_bytes_*_total{link=}`` series from ``peer.metrics()``) into
  rates over a sliding window — frames/s, wire B/s, equivalent fp32-delta
  B/s, the §6 quantities.
- :func:`effective_bits`: measured bits/element/frame from a residual-RMS
  trajectory — the matched-approximation-error yardstick (BASELINE.md's
  convergence table; 1.0 for the reference on homogeneous data).
"""

from __future__ import annotations

import contextlib
import math
import time
from collections import deque
from typing import Iterable, Iterator

import jax


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Device-level profiler trace; view with TensorBoard's profile plugin.
    Usable around any jitted region (sync step, codec chain, train loop)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class RateMeter:
    """Sliding-window rates from cumulative counters.

    >>> meter = RateMeter()
    >>> meter.update(frames=st.frames_in, wire_bytes=stats.bytes_in)
    >>> meter.rates()  # {"frames": f/s, "wire_bytes": B/s}
    """

    def __init__(self, window_sec: float = 10.0):
        self.window = window_sec
        self._samples: deque[tuple[float, dict[str, float]]] = deque()

    def update(self, **counters: float) -> None:
        self.update_at(time.monotonic(), **counters)

    def update_at(self, now: float, **counters: float) -> None:
        """`update` with an explicit timestamp — the testable entry point
        (r18 satellite), and the one for callers replaying recorded
        counter trajectories."""
        # Wall-clock-jump tolerance (r18 satellite): a sample stamped
        # EARLIER than the previous one (suspend/resume replay, a caller
        # switching time sources, test replays) would give a negative dt
        # and an inverted window. Re-anchor exactly like a counter reset:
        # the old timeline is unusable, the new one starts here.
        if self._samples and now < self._samples[-1][0]:
            self._samples.clear()
        # Counter-reset tolerance (r08 satellite): cumulative counters can
        # legitimately restart from ~0 — a link re-graft hands the stream
        # to a FRESH link id (new LinkStats), an engine peer is re-created
        # after a crash-point kill, a compat peer reconnects, a process
        # restores from checkpoint with zeroed registries. A window
        # spanning the reset would then report a huge NEGATIVE rate (new
        # minus old counter). Detect any counter going backwards and drop
        # the pre-reset history: the meter re-anchors at the reset point
        # and reports rates for the new stream only.
        if self._samples:
            _, last = self._samples[-1]
            if any(
                counters[k] < last[k] for k in counters if k in last
            ):
                self._samples.clear()
        self._samples.append((now, dict(counters)))
        cutoff = now - self.window
        # Evict while the SECOND-oldest sample is already at/past the window
        # edge — keeping exactly one sample at or before it, so rates() spans
        # the full window rather than just the last update interval.
        while len(self._samples) > 2 and self._samples[1][0] <= cutoff:
            self._samples.popleft()

    def rates(self) -> dict[str, float]:
        """Per-second rates over (at most) the trailing window.

        The oldest retained sample can be far older than the window (it is
        kept as the at-or-before-the-edge anchor; after an idle gap it may
        predate the edge by the whole gap). Using its raw timestamp would
        dilute the rate over the gap, so the counter value AT the window
        edge is linearly interpolated between the two samples bracketing it
        and the rate taken from there.
        """
        if len(self._samples) < 2:
            return {}
        t1, c1 = self._samples[-1]
        cutoff = t1 - self.window
        t0, c0 = self._samples[0]
        if t0 < cutoff:
            i = 1
            while i < len(self._samples) - 1 and self._samples[i][0] < cutoff:
                i += 1
            (ta, ca), (tb, cb) = self._samples[i - 1], self._samples[i]
            w = min(1.0, (cutoff - ta) / max(tb - ta, 1e-9))
            c0 = {
                k: ca.get(k, 0.0) + (cb.get(k, 0.0) - ca.get(k, 0.0)) * w
                for k in cb
            }
            t0 = min(cutoff, tb)
        dt = max(t1 - t0, 1e-9)
        # Clamped at zero: resets/rewinds re-anchor the window above, so a
        # negative delta here can only be float noise at the interpolated
        # edge — and a rate is a non-negative quantity by definition.
        return {
            k: max(0.0, (c1.get(k, 0.0) - c0.get(k, 0.0)) / dt) for k in c1
        }


def effective_bits(rms_trajectory: Iterable[float]) -> float:
    """Average bits of precision gained per element per frame, from a
    residual-RMS trajectory (one entry per frame). The reference codec
    achieves 1.0 on homogeneous data (RMS halves per frame, BASELINE.md)
    and ~0.15 on 1000:1 mixed magnitudes — the failure per-leaf scales fix."""
    traj = [float(x) for x in rms_trajectory]
    if len(traj) < 2 or traj[0] <= 0:
        return 0.0
    first, last = traj[0], traj[-1]
    if last <= 0:  # exact convergence: count bits down to fp32 epsilon
        last = first * 2.0**-24
    return math.log2(first / last) / (len(traj) - 1)
