"""Device-side timing for codec work (shared by bench.py and
benchmarks/pareto.py).

Through the axon tunnel, dispatch + completion signaling costs a variable
~0.1 s regardless of work, and ``block_until_ready`` can return
optimistically — so each measurement chains L codec frames device-side in
ONE program, forces TRUE completion by fetching a scalar that depends on the
final frame of both the residual and values chains, and sizes L so the chain
runs for seconds: the overhead becomes a small bias that only UNDERSTATES
the reported rate. (A long-minus-short marginal estimate would cancel the
overhead exactly, but the tunnel's jitter is comparable to the overhead
itself and can even drive the difference negative.)"""

from __future__ import annotations

import time
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp


def codec_frame_time(
    codec,
    n: int,
    policy,
    make_residual: Callable[[int], jnp.ndarray] | None = None,
    target_seconds: float = 3.0,
    reps: int = 2,
) -> float:
    """Seconds per fused codec roundtrip frame (sender quantize + receiver
    apply) at table size ``n``. ``make_residual(seed)`` supplies the starting
    residual (default: standard normal — nonzero scale throughout, so every
    frame does the full non-idle work)."""
    if make_residual is None:
        make_residual = lambda seed: jax.random.normal(
            jax.random.key(seed), (n,), jnp.float32
        )

    @partial(jax.jit, static_argnames=("length",), donate_argnums=(0, 1))
    def group(resid, values, length):
        def body(carry, _):
            r, v = carry
            frame, r = codec.quantize(r, n, policy)
            v = codec.apply_frame(v, frame, n)
            return (r, v), ()

        (r, v), _ = jax.lax.scan(body, (resid, values), None, length=length)
        # The fetched scalar depends on both chains (each frame's error
        # feedback feeds r, each apply feeds v), so neither half can be
        # dead-code-eliminated and the fetch waits for the whole program.
        return r, v, r[0] + v[0]

    def timed(length: int) -> float:
        best = float("inf")
        for rep in range(reps):
            r = make_residual(rep)
            v = jnp.zeros((n,), jnp.float32)
            jax.block_until_ready((r, v))
            t0 = time.perf_counter()
            _, _, probe = group(r, v, length)
            float(probe)  # forces completion through the tunnel
            best = min(best, time.perf_counter() - t0)
        return best

    # Grow the chain until the measured run itself is target-length: a pilot
    # estimate alone UNDERSHOOTS (its per-frame time over-counts the fixed
    # overhead, so the projected length lands short and the long run would
    # still be overhead-dominated). Each distinct length is a fresh (slow,
    # remote) compile, so lengths move in x8 buckets — the loop converges in
    # 1-3 extra measurements.
    length = 512
    timed(length)  # warmup/compile
    t = timed(length)
    while t < target_seconds and length < 1_000_000:
        est = max(t / length, 1e-9)
        want = target_seconds / est
        while length < want and length < 1_000_000:
            length *= 8
        t = timed(length)
    return t / length
