"""Device-side timing for codec work (shared by bench.py and
benchmarks/pareto.py).

Through the axon tunnel, dispatch + completion signaling costs a variable
~0.1 s regardless of work, and ``block_until_ready`` can return
optimistically — so each measurement chains L codec frames device-side in
ONE program, forces TRUE completion by fetching a scalar that depends on the
final frame of both the residual and values chains, and sizes L so the chain
runs for seconds: the overhead becomes a small bias that only UNDERSTATES
the reported rate. (A long-minus-short marginal estimate would cancel the
overhead exactly, but the tunnel's jitter is comparable to the overhead
itself and can even drive the difference negative.)

The chain length is a *dynamic* ``lax.fori_loop`` trip count, so every
length reuses ONE compiled program — round 1's version used ``lax.scan``
with a static length and paid a fresh (slow, remote) compile per length
step, which is how the bench burned its whole budget compiling and timed
out with nothing emitted (VERDICT.md "What's weak" #1).
"""

from __future__ import annotations

import time
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp


def codec_frame_time(
    codec,
    n: int,
    policy,
    make_residual: Callable[[int], jnp.ndarray] | None = None,
    target_seconds: float = 3.0,
    reps: int = 2,
    budget_s: float | None = None,
) -> float:
    """Seconds per fused codec roundtrip frame (sender quantize + receiver
    apply) at table size ``n``. ``make_residual(seed)`` supplies the starting
    residual (default: standard normal — nonzero scale throughout, so every
    frame does the full non-idle work). ``budget_s`` is a hard wall-clock
    budget for the whole measurement including compile: the best estimate so
    far is returned when it trips (never raises for budget reasons)."""
    deadline = None if budget_s is None else time.monotonic() + budget_s
    if make_residual is None:
        make_residual = lambda seed: jax.random.normal(
            jax.random.key(seed), (n,), jnp.float32
        )

    @partial(jax.jit, donate_argnums=(0, 1))
    def group(resid, values, length):
        def body(_, carry):
            r, v = carry
            frame, r = codec.quantize(r, n, policy)
            v = codec.apply_frame(v, frame, n)
            return (r, v)

        r, v = jax.lax.fori_loop(0, length, body, (resid, values))
        # The fetched scalar depends on both chains (each frame's error
        # feedback feeds r, each apply feeds v), so neither half can be
        # dead-code-eliminated and the fetch waits for the whole program.
        return r, v, r[0] + v[0]

    def timed(length: int) -> float:
        best = float("inf")
        for rep in range(reps):
            r = make_residual(rep)
            v = jnp.zeros((n,), jnp.float32)
            jax.block_until_ready((r, v))
            t0 = time.perf_counter()
            _, _, probe = group(r, v, jnp.int32(length))
            float(probe)  # forces completion through the tunnel
            best = min(best, time.perf_counter() - t0)
            if deadline is not None and time.monotonic() > deadline:
                break
        return best

    # Grow the chain until the measured run itself is target-length: a pilot
    # estimate alone UNDERSHOOTS (its per-frame time over-counts the fixed
    # overhead, so the projected length lands short and the long run would
    # still be overhead-dominated). Dynamic trip count = no recompiles, so
    # growth can jump straight to the projected length.
    length = 256
    timed(length)  # warmup/compile (the one compile)
    t = timed(length)
    max_length = 4_000_000
    while t < target_seconds and length < max_length:
        if deadline is not None and time.monotonic() > deadline:
            break
        est = max(t / length, 1e-9)
        length = min(max_length, max(length * 2, int(target_seconds / est)))
        t = timed(length)
    return t / length
