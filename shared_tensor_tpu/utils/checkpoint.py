"""Checkpoint / resume for the shared tensor (SURVEY.md §5.4).

The reference has NO persistence — kill the tree and the tensor is gone
(reference src/sharedtensor.c has no file I/O at all); its only
state-recovery mechanism is streaming full state to a late joiner through
the codec (src/sharedtensor.c:379-381). This module adds the missing half:

- checkpoint = the replica values + every link/peer residual, written
  atomically (tmp + rename) as a single .npz;
- resume has two modes:
  1. restore-in-place (this module): reload values/residuals and continue;
  2. rejoin-as-peer (comm/peer.py): start fresh and receive state through
     the codec stream — the reference's own join mechanism, which this
     checkpoint complements rather than replaces.

Plain .npz keeps the format inspectable and dependency-free. Two pod-tier
formats: save_pod/load_pod round-trip through one host's memory (tables
that fit a host); save_pod_sharded/load_pod_sharded write one file per
device shard and restore via per-shard callbacks, so tables sharded
precisely because they exceed host RAM (quirk Q6's fix) checkpoint with
O(total / n_devices) peak host memory.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import time
from typing import TYPE_CHECKING, Optional

import jax
import numpy as np

from ..core import SharedTensor
from ..ops.table import TableSpec

if TYPE_CHECKING:  # avoid importing the mesh tier for peer-only users
    from jax.sharding import Mesh

    from ..config import MeshConfig
    from ..parallel.ici import PeerSyncState

_FORMAT = 1


def _atomic_savez(path: str, **arrays) -> None:
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def save_shared(st: SharedTensor, path: str) -> None:
    """Snapshot a peer-tier SharedTensor: replica + every link residual,
    taken atomically via ``snapshot_all`` (one lock acquisition) so a
    concurrent frame cannot tear the error-feedback invariant."""
    values, links = st.snapshot_all()
    arrays = {
        "values": np.asarray(values),
        "layout": np.frombuffer(st.spec.layout_digest(), dtype=np.uint8),
    }
    for lid, r in links.items():
        arrays[f"link_{lid}"] = np.asarray(r)
    arrays["meta"] = np.frombuffer(
        json.dumps({"format": _FORMAT, "links": list(links)}).encode(),
        dtype=np.uint8,
    )
    _atomic_savez(path, **arrays)


def load_shared(st: SharedTensor, path: str) -> None:
    """Restore into an existing (layout-compatible) SharedTensor. Residuals
    are restored for links that exist in the file; links opened after the
    checkpoint keep their current residuals."""
    with np.load(path) as z:
        digest = z["layout"].tobytes()
        if digest != st.spec.layout_digest():
            raise ValueError(
                "checkpoint layout does not match this SharedTensor's table "
                "layout (different tree structure/shapes)"
            )
        meta = json.loads(z["meta"].tobytes().decode())
        values = z["values"]
        links = {
            lid: z[f"link_{lid}"]
            for lid in meta.get("links", [])
            if f"link_{lid}" in z
        }
    restore = getattr(st, "restore_state", None)
    if restore is not None:  # native-engine tier: state lives in C
        restore(values, links)
        return
    with st._lock:
        # _asarray keeps the tensor's codec tier: numpy arrays on the host
        # tier (a jnp restore would silently bounce every later frame
        # through jax<->numpy conversions), jax arrays on device tiers.
        st.values = st._asarray(values)
        for lid, r in links.items():
            if lid in st._links:
                st._links[lid] = st._asarray(r)
            elif lid < 0:
                # the carry pseudo-slot (owed re-graft mass): recreate it
                # unconditionally, matching the engine tier's restore —
                # dropping it would present the restored mass as tree-known
                # at the next handshake and erase it tree-wide
                st._links[lid] = st._asarray(r)


def save_pod(state: "PeerSyncState", spec: TableSpec, path: str) -> None:
    """Snapshot the pod tier's sharded state (all peers' replicas +
    residuals) through host memory."""
    values, residual = jax.device_get((state.values, state.residual))
    _atomic_savez(
        path,
        values=values,
        residual=residual,
        layout=np.frombuffer(spec.layout_digest(), dtype=np.uint8),
        meta=np.frombuffer(
            json.dumps({"format": _FORMAT}).encode(), dtype=np.uint8
        ),
    )


def save_trainer(trainer, path: str) -> None:
    """Snapshot a PodTrainer COMPLETELY: sharded sync state, step counter, and
    — when an optax optimizer is attached — the per-peer optimizer state
    (momentum/Adam moments). Round-2 verdict Weak #5: dropping opt_state made
    an Adam run resume with reset moments, silently changing training."""
    values, residual = jax.device_get((trainer.state.values, trainer.state.residual))
    arrays = {
        "values": values,
        "residual": residual,
        "layout": np.frombuffer(trainer.spec.layout_digest(), dtype=np.uint8),
    }
    n_opt = 0
    if trainer.opt_state is not None:
        for i, leaf in enumerate(jax.tree.leaves(jax.device_get(trainer.opt_state))):
            arrays[f"opt_{i}"] = np.asarray(leaf)
            n_opt = i + 1
    arrays["meta"] = np.frombuffer(
        json.dumps(
            {"format": _FORMAT, "steps": trainer.steps, "opt_leaves": n_opt}
        ).encode(),
        dtype=np.uint8,
    )
    _atomic_savez(path, **arrays)


def load_trainer(trainer, path: str) -> None:
    """Restore a :func:`save_trainer` checkpoint into an existing PodTrainer
    (same template/mesh/optimizer — the treedef of the live opt_state is the
    deserialization schema, so no pickling of optax internals is needed).
    Training continues bit-identically from the saved step."""
    with np.load(path) as z:
        if z["layout"].tobytes() != trainer.spec.layout_digest():
            raise ValueError("checkpoint layout does not match the trainer's table")
        meta = json.loads(z["meta"].tobytes().decode())
        values, residual = z["values"], z["residual"]
        opt_leaves = [z[f"opt_{i}"] for i in range(meta.get("opt_leaves", 0))]
    from ..parallel.ici import PeerSyncState, state_sharding

    sh = state_sharding(trainer.mesh, trainer.mesh_config)
    if values.shape[0] != trainer.n_peer:
        raise ValueError(
            f"checkpoint has {values.shape[0]} peers, trainer has {trainer.n_peer}"
        )
    trainer.state = PeerSyncState(
        jax.device_put(values, sh), jax.device_put(residual, sh)
    )
    if trainer.opt_state is not None:
        live, treedef = jax.tree.flatten(trainer.opt_state)
        if len(live) != len(opt_leaves):
            raise ValueError(
                f"checkpoint has {len(opt_leaves)} optimizer leaves, the "
                f"trainer's optimizer has {len(live)} — different optimizer?"
            )
        from jax.sharding import NamedSharding, PartitionSpec as P

        peer_ax = trainer.mesh_config.peer_axis
        restored = []
        for cur, new in zip(live, opt_leaves):
            if tuple(np.shape(cur)) != tuple(new.shape):
                raise ValueError(
                    f"optimizer leaf shape {new.shape} != live {np.shape(cur)}"
                )
            # vmap(optimizer.init) gave every leaf a leading peer axis; pin it
            # back onto the mesh the same way (an explicit single-device put
            # would commit the leaf and conflict with the sharded sync state)
            lsh = NamedSharding(
                trainer.mesh, P(peer_ax, *([None] * (new.ndim - 1)))
            )
            restored.append(jax.device_put(new, lsh))
        trainer.opt_state = jax.tree.unflatten(treedef, restored)
    elif opt_leaves:
        raise ValueError("checkpoint carries optimizer state; trainer has none")
    trainer.steps = int(meta.get("steps", 0))


def load_pod(
    path: str,
    mesh: "Mesh",
    spec: TableSpec,
    config: "MeshConfig | None" = None,
) -> "PeerSyncState":
    """Rebuild a PeerSyncState on ``mesh`` from a checkpoint. The peer count
    must match the mesh's peer axis (re-sharding across a different peer
    count is a join/leave operation, not a restore)."""
    from ..parallel.ici import PeerSyncState, state_sharding

    with np.load(path) as z:
        if z["layout"].tobytes() != spec.layout_digest():
            raise ValueError("checkpoint layout does not match the table spec")
        values, residual = z["values"], z["residual"]
    sh = state_sharding(mesh, config)
    n_peer = mesh.shape[sh.spec[0]]
    if values.shape[0] != n_peer:
        raise ValueError(
            f"checkpoint has {values.shape[0]} peers, mesh has {n_peer}"
        )
    return PeerSyncState(
        jax.device_put(values, sh), jax.device_put(residual, sh)
    )


# ---- r12 cluster lifecycle: per-node shards + root manifest ---------------
#
# One consistent-cut snapshot of a whole tree = one shard file per node
# (shard_<name>.npz) + MANIFEST.json at the root. A shard captures what the
# quiesce barrier froze: the replica, every writer link's error-feedback
# residual (sign2/cascade state included — the engine snapshot is one
# mutex acquisition, comm/engine.py snapshot_ex), the re-graft carry, and
# per-link aux (role, tx/rx wire seqs at the cut, governor precision).
# Subscriber links persist META ONLY: a read-only leaf re-seeds from
# scratch on restore, so its transient residual would be dead weight.
#
# The manifest records a sha256 per shard so ``ctl restore`` / the restart
# path can audit a snapshot before trusting it. Per-link seqs are recorded
# for POST-MORTEM inspection (the barrier's drained-ledger discipline makes
# tx-on-uplink == parent's-rx-for-that-child at every capture; link ids are
# node-local, so pairing them offline needs the operator's knowledge of the
# topology — the audit does not attempt it). Plain .npz + JSON keeps both
# inspectable, like every other format in this module.

MANIFEST_NAME = "MANIFEST.json"


def shard_filename(node_name: str) -> str:
    """Shard file for a node name (sanitized: names land in filenames)."""
    safe = "".join(
        c if c.isalnum() or c in "-_." else "_" for c in str(node_name)
    )
    return f"shard_{safe}.npz"


def file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def save_cluster_shard(
    dirpath: str,
    node_name: str,
    snap_id: str,
    layout_digest: bytes,
    values: np.ndarray,
    links: list[dict],
    wire_version: int = 0,
) -> dict:
    """Write one node's shard. ``links`` entries: ``{"id", "role"
    ("up"|"child"|"sub"|"carry"), "tx_seq", "rx_count", "prec",
    "resid" (f32 array or None)}``. Returns the manifest entry
    ``{"node", "file", "sha256", "bytes", "links"}``."""
    os.makedirs(dirpath, exist_ok=True)
    fname = shard_filename(node_name)
    path = os.path.join(dirpath, fname)
    arrays = {
        "values": np.ascontiguousarray(values, np.float32),
        "layout": np.frombuffer(layout_digest, dtype=np.uint8),
    }
    meta_links = []
    for entry in links:
        lid = int(entry["id"])
        resid = entry.get("resid")
        if resid is not None:
            arrays[f"resid_{lid}"] = np.ascontiguousarray(resid, np.float32)
        meta_links.append(
            {
                "id": lid,
                "role": entry.get("role", "child"),
                "tx_seq": int(entry.get("tx_seq", 0)),
                "rx_count": int(entry.get("rx_count", 0)),
                "prec": int(entry.get("prec", 1)),
                "has_resid": resid is not None,
            }
        )
    arrays["meta"] = np.frombuffer(
        json.dumps(
            {
                "format": _FORMAT,
                "kind": "cluster_shard",
                "snap_id": str(snap_id),
                "node": str(node_name),
                "wire_version": int(wire_version),
                "time": time.time(),
                "links": meta_links,
            }
        ).encode(),
        dtype=np.uint8,
    )
    _atomic_savez(path, **arrays)
    return {
        "node": str(node_name),
        "file": fname,
        "sha256": file_sha256(path),
        "bytes": os.path.getsize(path),
        "links": meta_links,
    }


def load_cluster_shard(path: str) -> dict:
    """Read a shard back: ``{"values", "layout", "meta", "links":
    {id: {"role", "tx_seq", "rx_count", "prec", "resid"-or-None}}}``."""
    with np.load(path) as z:
        meta = json.loads(z["meta"].tobytes().decode())
        if meta.get("kind") != "cluster_shard":
            raise ValueError(f"{path} is not a cluster shard")
        values = np.asarray(z["values"], np.float32)
        layout = z["layout"].tobytes()
        links: dict[int, dict] = {}
        for entry in meta.get("links", []):
            lid = int(entry["id"])
            links[lid] = dict(entry)
            links[lid]["resid"] = (
                np.asarray(z[f"resid_{lid}"], np.float32)
                if entry.get("has_resid") and f"resid_{lid}" in z
                else None
            )
    return {"values": values, "layout": layout, "meta": meta, "links": links}


def restore_carry_from_shard(shard: dict) -> Optional[np.ndarray]:
    """The re-graft carry a RESTARTED node re-joins with: its checkpointed
    uplink residual plus any checkpointed carry. Only up-flow mass rides
    the carry — child-link residuals are deliberately dropped, because the
    children's own re-join diff handshakes re-derive exactly the down-flow
    they are missing (summing both directions into one carry would deliver
    the same add twice; see the README restore note)."""
    out = None
    for entry in shard["links"].values():
        if entry.get("role") in ("up", "carry") and entry.get("resid") is not None:
            r = np.asarray(entry["resid"], np.float32)
            out = r if out is None else out + r
    return out


def atomic_write_json(path: str, doc: dict) -> str:
    """tmp + rename JSON write — the one implementation every lifecycle
    surface shares (manifest here, the peer's ctl result, the CLI's
    command file), so cleanup-on-failure semantics can't drift between
    hand-rolled copies."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    return path


def write_manifest(
    dirpath: str, snap_id: str, entries: list[dict], extra: dict | None = None
) -> str:
    doc = {
        "format": _FORMAT,
        "kind": "cluster_manifest",
        "snap_id": str(snap_id),
        "time": time.time(),
        "nodes": sorted(entries, key=lambda e: e["node"]),
    }
    if extra:
        doc.update(extra)
    return atomic_write_json(os.path.join(dirpath, MANIFEST_NAME), doc)


def load_manifest(dirpath: str) -> dict:
    with open(os.path.join(dirpath, MANIFEST_NAME)) as f:
        doc = json.load(f)
    if doc.get("kind") != "cluster_manifest":
        raise ValueError(f"{dirpath} holds no cluster manifest")
    return doc


def verify_manifest(dirpath: str) -> list[str]:
    """Audit a snapshot directory against its manifest: manifest parses,
    every shard present, every sha256 matches. (Per-link seqs are
    recorded for post-mortem reading, not audited here — see the module
    note: link ids are node-local, so pairing them needs topology
    knowledge the snapshot doesn't carry.) Returns a list of problems
    ([] = clean)."""
    problems: list[str] = []
    try:
        doc = load_manifest(dirpath)
    except (OSError, ValueError) as e:
        return [f"manifest unreadable: {e}"]
    for entry in doc.get("nodes", []):
        path = os.path.join(dirpath, entry["file"])
        if not os.path.exists(path):
            problems.append(f"{entry['node']}: shard {entry['file']} missing")
            continue
        digest = file_sha256(path)
        if digest != entry.get("sha256"):
            problems.append(
                f"{entry['node']}: shard digest mismatch "
                f"({digest[:12]} != {entry.get('sha256', '')[:12]})"
            )
    return problems


# ---- r16 cluster-sharded tensor: per-owner shard state --------------------
#
# A sharded cluster's checkpoint is one file per NODE (same shard_<name>
# naming + sha256-manifest discipline as the r12 lifecycle shards), but
# the payload is the r16 memory model, not a full replica: the node's
# OWNED slices (word ranges of the global table), its per-target-shard
# OUTBOX residuals (out-of-shard mass quantized-but-undelivered — owed to
# other owners, so dropping it at restart would silently lose cluster
# mass), its per-origin END-TO-END dedup windows (without them a restart
# re-applies any frame that was delivered-but-unacked at the kill), and
# its fwd_seq high-water mark (forward-compat only: origin obs ids are
# pid-seeded, so a reborn node's identities are fresh either way — the
# windows are what protect against OTHER, still-alive origins' resends).
# MANIFEST.json gains per-shard entries via the normal ``nodes`` rows —
# each row's ``shards`` list records which word ranges that node owned at
# the capture, so ``ctl verify``/restore tooling can audit coverage
# (every shard owned exactly once) before trusting a snapshot.


def save_shard_state(
    dirpath: str,
    node_name: str,
    layout_digest: bytes,
    owned: dict,
    outboxes: dict,
    dedup: dict,
    fwd_seq: int,
) -> dict:
    """Write one sharded node's checkpoint. ``owned`` maps shard index ->
    (word_lo, word_cnt, values f32); ``outboxes`` maps shard index ->
    (word_lo, residual f32); ``dedup`` maps origin (str) -> sorted seq
    list. Returns the MANIFEST.json entry (``{"node", "file", "sha256",
    "bytes", "shards"}``)."""
    os.makedirs(dirpath, exist_ok=True)
    fname = shard_filename(node_name)
    path = os.path.join(dirpath, fname)
    arrays = {
        "layout": np.frombuffer(layout_digest, dtype=np.uint8),
    }
    shard_meta = []
    for k, (wlo, wcnt, vals) in sorted(owned.items()):
        arrays[f"owned_{int(k)}"] = np.ascontiguousarray(vals, np.float32)
        shard_meta.append(
            {"shard": int(k), "word_lo": int(wlo), "word_cnt": int(wcnt)}
        )
    outbox_meta = []
    for k, (wlo, resid) in sorted(outboxes.items()):
        arrays[f"outbox_{int(k)}"] = np.ascontiguousarray(resid, np.float32)
        outbox_meta.append({"shard": int(k), "word_lo": int(wlo)})
    arrays["meta"] = np.frombuffer(
        json.dumps(
            {
                "format": _FORMAT,
                "kind": "shard_state",
                "node": str(node_name),
                "time": time.time(),
                "shards": shard_meta,
                "outboxes": outbox_meta,
                "dedup": {str(o): list(map(int, s)) for o, s in dedup.items()},
                "fwd_seq": int(fwd_seq),
            }
        ).encode(),
        dtype=np.uint8,
    )
    _atomic_savez(path, **arrays)
    return {
        "node": str(node_name),
        "file": fname,
        "sha256": file_sha256(path),
        "bytes": os.path.getsize(path),
        "shards": shard_meta,
    }


def load_shard_state(path: str) -> dict:
    """Read a :func:`save_shard_state` file back: ``{"layout", "owned":
    {shard: (word_lo, word_cnt, values)}, "outboxes": {shard: (word_lo,
    residual)}, "dedup": {origin: [seqs]}, "fwd_seq"}``."""
    with np.load(path) as z:
        meta = json.loads(z["meta"].tobytes().decode())
        if meta.get("kind") != "shard_state":
            raise ValueError(f"{path} is not an r16 shard-state checkpoint")
        layout = z["layout"].tobytes()
        owned = {
            int(e["shard"]): (
                int(e["word_lo"]),
                int(e["word_cnt"]),
                np.asarray(z[f"owned_{int(e['shard'])}"], np.float32),
            )
            for e in meta.get("shards", [])
        }
        outboxes = {
            int(e["shard"]): (
                int(e["word_lo"]),
                np.asarray(z[f"outbox_{int(e['shard'])}"], np.float32),
            )
            for e in meta.get("outboxes", [])
        }
    return {
        "layout": layout,
        "owned": owned,
        "outboxes": outboxes,
        "dedup": meta.get("dedup", {}),
        "fwd_seq": int(meta.get("fwd_seq", 0)),
    }


def verify_shard_coverage(dirpath: str, n_shards: int) -> list[str]:
    """Sharded-manifest audit on top of :func:`verify_manifest`: every
    shard index in [0, n_shards) owned by EXACTLY one node at the
    capture. Returns problems ([] = clean)."""
    problems = verify_manifest(dirpath)
    try:
        doc = load_manifest(dirpath)
    except (OSError, ValueError):
        return problems  # verify_manifest already reported it
    owners: dict[int, list[str]] = {}
    for entry in doc.get("nodes", []):
        for s in entry.get("shards", []):
            owners.setdefault(int(s["shard"]), []).append(entry["node"])
    for k in range(n_shards):
        who = owners.get(k, [])
        if len(who) != 1:
            problems.append(
                f"shard {k}: owned by {who or 'nobody'} at the capture "
                f"(exactly-one-owner audit)"
            )
    return problems


# ---- sharded (per-device) pod checkpoint ----------------------------------
#
# save_pod/load_pod round-trip the whole table through ONE host's memory
# (jax.device_get of the full array) — fine until the table is sharded
# precisely because it exceeds a host's RAM (quirk Q6's fix, SURVEY.md §5.7).
# These variants move exactly one SHARD at a time: save iterates the
# array's addressable shards (each process writes only its own), load
# rebuilds via jax.make_array_from_callback, which pulls each device's
# slice individually — peak host memory is O(total / n_devices), not
# O(total). Layout on disk:
#
#   path/meta.npz            layout digest, global shape, n_processes
#                            (written by process 0)
#   path/manifest_p<i>.npz   process i's authoritative shard list
#   path/shard_p<i>_<k0-k1_...>.npz  one device shard: values + residual
#
# The loader unions exactly the manifests meta names and reads ONLY files
# they list — stray shard files from an earlier save with a different
# sharding (save never deletes other layouts' files) are ignored instead of
# silently served. Plain .npz keeps the format inspectable and
# dependency-free; orbax would add async/parallel-write polish but no
# semantic difference.


def _index_key(index, shape) -> str:
    """Stable filename key for a global shard index. A dim partitioned over
    a size-1 mesh axis arrives as slice(None) — normalize its bounds to the
    full dim, never embedding 'None' in the filename."""
    return "_".join(
        f"{s.start or 0}-{s.stop if s.stop is not None else shape[d]}"
        for d, s in enumerate(index)
    )


def save_pod_sharded(state: "PeerSyncState", spec: TableSpec, path: str) -> None:
    """Per-shard snapshot of the pod state into directory ``path``. Each
    addressable shard of (values, residual) lands in its own .npz; on a
    multi-process pod every process writes only its addressable shards (and
    its own manifest), so no host ever materializes the full table."""
    os.makedirs(path, exist_ok=True)
    pi = jax.process_index()
    shape = state.values.shape
    shard_keys = []
    for vs, rs in zip(
        state.values.addressable_shards, state.residual.addressable_shards
    ):
        # values and residual share one sharding (state_sharding), so the
        # shard lists align index-for-index; assert rather than assume
        if vs.index != rs.index:
            raise AssertionError("values/residual shard indices diverged")
        key = _index_key(vs.index, shape)
        shard_keys.append(key)
        _atomic_savez(
            os.path.join(path, f"shard_p{pi}_{key}.npz"),
            values=np.asarray(vs.data),
            residual=np.asarray(rs.data),
        )
    _atomic_savez(
        os.path.join(path, f"manifest_p{pi}.npz"),
        meta=np.frombuffer(
            json.dumps({"shards": shard_keys}).encode(), dtype=np.uint8
        ),
    )
    if pi == 0:
        _atomic_savez(
            os.path.join(path, "meta.npz"),
            layout=np.frombuffer(spec.layout_digest(), dtype=np.uint8),
            shape=np.asarray(shape, np.int64),
            meta=np.frombuffer(
                json.dumps(
                    {"format": _FORMAT, "n_processes": jax.process_count()}
                ).encode(),
                dtype=np.uint8,
            ),
        )


def load_pod_sharded(
    path: str,
    mesh: "Mesh",
    spec: TableSpec,
    config: "MeshConfig | None" = None,
) -> "PeerSyncState":
    """Rebuild a PeerSyncState from a :func:`save_pod_sharded` directory.
    ``jax.make_array_from_callback`` asks for one device's global index at a
    time; the callback opens only the covering shard's file and decodes only
    the needed member (npz members decode lazily), so peak host memory stays
    at one shard. The mesh may differ from the saving mesh as long as saved
    shards cover the new boundaries (a callback index is served by slicing
    the one saved shard that contains it)."""
    from ..parallel.ici import PeerSyncState, state_sharding

    with np.load(os.path.join(path, "meta.npz")) as z:
        if z["layout"].tobytes() != spec.layout_digest():
            raise ValueError("checkpoint layout does not match the table spec")
        shape = tuple(int(x) for x in z["shape"])
        meta = json.loads(z["meta"].tobytes().decode())
    sh = state_sharding(mesh, config)
    n_peer = mesh.shape[sh.spec[0]]
    if shape[0] != n_peer:
        raise ValueError(f"checkpoint has {shape[0]} peers, mesh has {n_peer}")
    # authoritative shard set = union of exactly the saving processes'
    # manifests (never a bare listdir: stale files from an earlier save
    # with a different sharding must not be served)
    saved = []
    for pi in range(int(meta.get("n_processes", 1))):
        with np.load(os.path.join(path, f"manifest_p{pi}.npz")) as z:
            keys = json.loads(z["meta"].tobytes().decode())["shards"]
        for key in keys:
            bounds = [
                tuple(int(v) for v in part.split("-"))
                for part in key.split("_")
            ]
            saved.append((bounds, os.path.join(path, f"shard_p{pi}_{key}.npz")))
    if not saved:
        raise FileNotFoundError(f"no shards manifested under {path}")

    # size-1 decode cache: on a finer restore mesh several callback indices
    # fall inside one saved shard; without it each sub-index would re-decode
    # the member array
    cache: dict = {}

    def _member(file: str, field: str) -> np.ndarray:
        k = (file, field)
        if k not in cache:
            cache.clear()
            with np.load(file) as z:
                cache[k] = z[field]
        return cache[k]

    def _fetch(field: str):
        def cb(index):
            want = [
                (s.start or 0, s.stop if s.stop is not None else shape[d])
                for d, s in enumerate(index)
            ]
            for bounds, file in saved:
                if all(
                    b[0] <= w[0] and w[1] <= b[1] for b, w in zip(bounds, want)
                ):
                    arr = _member(file, field)
                    local = tuple(
                        slice(w[0] - b[0], w[1] - b[0])
                        for b, w in zip(bounds, want)
                    )
                    return np.ascontiguousarray(arr[local])
            raise ValueError(
                f"no saved shard covers index {want} — checkpoint written "
                f"with an incompatible sharding"
            )

        return jax.make_array_from_callback(shape, sh, cb)

    return PeerSyncState(_fetch("values"), _fetch("residual"))
