"""ServingHandle: double-buffered hot-swap weight publication.

The inference loop's side of the serving tier: ``params()`` is a plain
reference read (lock-free, GIL-atomic — never touches the subscriber's
apply path, the transport, or any data-plane lock), and ``refresh()``
atomically swaps a NEW verified snapshot in underneath it. A model server
calls ``refresh`` on its own schedule (per request batch, per N steps, or
from a background ticker) while trainers stream updates through the tree;
requests in flight keep the pytree they started with — the swap can never
tear a forward pass.

This is where the JAX conversion happens: the subscriber itself is pure
numpy (host-tier rule — it never initializes a backend), while the serving
process builds jnp arrays because it is about to run a jitted model anyway.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional

from .subscriber import StalenessError, Subscriber


class ServingHandle:
    """Hot-swap view over a :class:`Subscriber` for an inference loop."""

    def __init__(
        self,
        sub: Subscriber,
        max_staleness: Optional[float] = None,
        as_jax: bool = True,
    ):
        self._sub = sub
        self._bound = max_staleness
        self._as_jax = as_jax and not sub._ranged
        self._params: Any = None
        self._version = -1
        self._staleness = float("inf")
        self._swaps = 0
        # refresh() may be called from several serving threads; the swap
        # itself is a reference assignment, but the (version check ->
        # rebuild -> swap) sequence should not run twice for one version
        self._mu = threading.Lock()

    def params(self) -> Any:
        """The current published params (None before the first successful
        refresh). Lock-free reference read — safe from any thread, never
        blocks, never touches the data plane."""
        return self._params

    @property
    def version(self) -> int:
        return self._version

    @property
    def staleness(self) -> float:
        """Verified staleness of the CURRENT params at their last refresh,
        plus the time elapsed since — the bound a request served now is
        actually getting."""
        return self._staleness + (time.monotonic() - self._at)

    @property
    def swaps(self) -> int:
        """How many times refresh() actually swapped new weights in."""
        return self._swaps

    def refresh(self, max_staleness: Optional[float] = None) -> bool:
        """Verify + swap: pull the subscriber's latest snapshot, verify its
        staleness bound (raise :class:`StalenessError` otherwise — a
        serving loop must fail loud, not serve stale), and atomically
        publish it. Returns True when new weights were swapped in, False
        when the state hadn't moved (params untouched, verification still
        performed — the freshness clock advances either way)."""
        bound = self._bound if max_staleness is None else max_staleness
        with self._mu:
            # ONE acquire: array, staleness and version arrive together
            # (a separately-read version could label older params with a
            # newer number and skip the real newest snapshot forever)
            flat, staleness, ver = self._sub.read_flat(bound)
            self._staleness = staleness
            self._at = time.monotonic()
            if ver == self._version and self._params is not None:
                return False
            if self._sub._ranged:
                new = flat  # raw page array; callers index it directly
            else:
                from ..ops.codec_np import unflatten_np

                tree = unflatten_np(flat, self._sub.spec)
                if self._as_jax:
                    import jax

                    tree = jax.tree.map(self._to_jax, tree)
                new = tree
            # the swap: one reference assignment — in-flight readers keep
            # the pytree they already hold
            self._params = new
            self._version = ver
            self._swaps += 1
            return True

    _at = 0.0

    @staticmethod
    def _to_jax(x):
        import jax.numpy as jnp

        return jnp.asarray(x)
